"""Device kernels: the fused filter→score→select→bind pipeline.

Reference mapping:
  findNodesThatFit (generic_scheduler.go:289-377)  -> staged fail masks + reason bits
  PrioritizeNodes  (generic_scheduler.go:542-680)  -> vectorized scores + masked normalize
  selectHost       (generic_scheduler.go:183-198)  -> masked argmax + round-robin tie pick
  assume/bind      (scheduler.go:431-497)          -> scatter-add into the carry

Execution mode (SURVEY.md §7 step 5): schedule_scan — EXACT: one lax.scan
step per pod; pod t's bind is seen by pod t+1, identical to the Go loop.
(A "wavefront" approximate mode — K pods vmapped against a frozen snapshot
per wave — existed through round 4 and was removed: measured on the
BASELINE.md phase shape it was slower than the exact scan at every K on
CPU AND overestimated schedulable capacity by 8-75% under saturation,
because pods in a wave don't see each other's binds; see BASELINE.md
"wavefront verdict".)
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.engine.predicates import (
    CHECK_NODE_DISK_PRESSURE_PRED,
    CHECK_NODE_LABEL_PRESENCE_PRED,
    CHECK_NODE_MEMORY_PRESSURE_PRED,
    CHECK_NODE_UNSCHEDULABLE_PRED,
    CHECK_SERVICE_AFFINITY_PRED,
    CHECK_VOLUME_BINDING_PRED,
    DEFAULT_MAXPD_LIMITS,
    GENERAL_PRED,
    HOSTNAME_PRED,
    MATCH_INTERPOD_AFFINITY_PRED,
    MATCH_NODE_SELECTOR_PRED,
    MAX_AZURE_DISK_VOLUME_COUNT_PRED,
    MAX_EBS_VOLUME_COUNT_PRED,
    MAX_GCE_PD_VOLUME_COUNT_PRED,
    NO_DISK_CONFLICT_PRED,
    NO_VOLUME_ZONE_CONFLICT_PRED,
    POD_FITS_HOST_PORTS_PRED,
    POD_FITS_RESOURCES_PRED,
    POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED,
    POD_TOLERATES_NODE_TAINTS_PRED,
)
from tpusim.jaxe.packing import (
    GANG_RACK_SHIFT,
    GANG_SCORE_MASK,
    GANG_ZONE_SHIFT,
    TIE_BITS as _ANALYTICS_TIE_BITS,
    encode_gang_rank,
    encode_topk_keys,
)
from tpusim.jaxe.state import (
    BIT_AFFINITY_NOT_MATCH,
    BIT_AFFINITY_RULES,
    BIT_ANTI_AFFINITY_RULES,
    BIT_DISK_CONFLICT,
    BIT_DISK_PRESSURE,
    BIT_MAX_VOLUME_COUNT,
    BIT_VOLUME_ZONE_CONFLICT,
    BIT_EXISTING_ANTI_AFFINITY,
    BIT_HOSTNAME_MISMATCH,
    BIT_HOST_PORTS,
    BIT_INSUFFICIENT_CPU,
    BIT_INSUFFICIENT_EPHEMERAL,
    BIT_INSUFFICIENT_GPU,
    BIT_INSUFFICIENT_MEMORY,
    BIT_INSUFFICIENT_PODS,
    BIT_MEMORY_PRESSURE,
    BIT_NODE_LABEL_PRESENCE,
    BIT_NODE_SELECTOR_MISMATCH,
    BIT_NODE_UNSCHEDULABLE,
    BIT_SERVICE_AFFINITY,
    BIT_TAINTS_NOT_TOLERATED,
    NUM_FIXED_BITS,
    CompiledCluster,
    PodColumns,
)

MAX_PRIORITY = 10
AVOID_PODS_WEIGHT = 10000


class Carry(NamedTuple):
    used_cpu: jnp.ndarray      # [N] int64
    used_mem: jnp.ndarray
    used_gpu: jnp.ndarray
    used_eph: jnp.ndarray
    used_scalar: jnp.ndarray   # [N, S]
    nonzero_cpu: jnp.ndarray
    nonzero_mem: jnp.ndarray
    pod_count: jnp.ndarray
    presence: jnp.ndarray      # [G, N] int32 — pods per (group, node)
    presence_dom: jnp.ndarray  # [G, K, D] int32 — presence summed per topo domain
    used_vols: jnp.ndarray     # [N, V] bool — MaxPD volume ids mounted per node
    # ServiceAffinity (policy): per first-service-sig lock = node index of the
    # designated first matching pod once it binds; -1 = not yet locked,
    # -2 = permanently unpinned (the first pod's node is unknowable)
    sa_lock: jnp.ndarray       # [Fd] int32
    rr: jnp.ndarray            # scalar int64 — selectHost's lastNodeIndex


class Statics(NamedTuple):
    alloc_cpu: jnp.ndarray
    alloc_mem: jnp.ndarray
    alloc_gpu: jnp.ndarray
    alloc_eph: jnp.ndarray
    allowed_pods: jnp.ndarray
    alloc_scalar: jnp.ndarray
    cond_fail_bits: jnp.ndarray
    mem_pressure: jnp.ndarray
    disk_pressure: jnp.ndarray
    selector_ok: jnp.ndarray
    taint_ok: jnp.ndarray
    taint_ok_noexec: jnp.ndarray
    intolerable: jnp.ndarray
    affinity_count: jnp.ndarray
    avoid_score: jnp.ndarray
    host_ok: jnp.ndarray
    # pod-group tables (state.GroupTables; zero-size-semantics dummies when off)
    port_conflict: jnp.ndarray
    port_sig: jnp.ndarray
    disk_conflict: jnp.ndarray
    disk_sig: jnp.ndarray
    vol_mask: jnp.ndarray
    vol_type: jnp.ndarray
    zone_ok: jnp.ndarray
    ss_rows: jnp.ndarray
    ss_sig: jnp.ndarray
    saa_rows: jnp.ndarray
    saa_sig: jnp.ndarray
    term_match: jnp.ndarray
    zone_dom: jnp.ndarray
    topo_dom: jnp.ndarray
    aff_valid: jnp.ndarray
    aff_err: jnp.ndarray
    aff_empty: jnp.ndarray
    aff_term: jnp.ndarray
    aff_key: jnp.ndarray
    aff_hostname: jnp.ndarray
    aff_self: jnp.ndarray
    aff_unplaced: jnp.ndarray
    anti_valid: jnp.ndarray
    anti_err: jnp.ndarray
    anti_empty: jnp.ndarray
    anti_term: jnp.ndarray
    anti_key: jnp.ndarray
    anti_hostname: jnp.ndarray
    pref_w: jnp.ndarray
    pref_term: jnp.ndarray
    pref_key: jnp.ndarray
    # policy-configured custom plugin rows (trivial when no policy):
    #   label_ok   — [L, N] pass masks for the policy's label-presence
    #                predicates; PolicySpec.label_rows names each row's
    #                ordering slot (a custom registered under a standard
    #                PREDICATES_ORDERING name evaluates at that position in
    #                the host's _predicate_key_order; other names run after
    #                the fixed ordering, folded into one tail row)
    #   label_prio — pre-weighted sum of NodeLabel/LabelPreference priority
    #                rows (node_label.go; no normalize pass)
    #   image_score — [Si, N] ImageLocalityPriority map scores per interned
    #                pod-image-set signature (image_locality.go; static)
    label_ok: jnp.ndarray
    label_prio: jnp.ndarray
    image_score: jnp.ndarray
    #   saa_dom — [E, N] per-ServiceAntiAffinity-entry node label-value domain
    #             ids (0 = label absent), from jaxe.policyc
    #   ServiceAffinity predicates (policy): sa_val [La, N] interned node
    #   values per policy affinity label (0 = absent; label rows concatenate
    #   the entries' label lists, segmented by PolicySpec.sa_segs); sa_pin
    #   [Cs, La] the pod's own nodeSelector pins in the same value space
    #   (0 = label unpinned; a pinned value no node carries interns to a
    #   fresh id that matches nothing)
    saa_dom: jnp.ndarray
    sa_val: jnp.ndarray
    sa_pin: jnp.ndarray


class PodX(NamedTuple):
    """One pod's columns (scan xs slice)."""

    req_cpu: jnp.ndarray
    req_mem: jnp.ndarray
    req_gpu: jnp.ndarray
    req_eph: jnp.ndarray
    req_scalar: jnp.ndarray    # [S]
    nz_cpu: jnp.ndarray
    nz_mem: jnp.ndarray
    zero_request: jnp.ndarray
    best_effort: jnp.ndarray
    sel_id: jnp.ndarray
    tol_id: jnp.ndarray
    aff_id: jnp.ndarray
    avoid_id: jnp.ndarray
    host_id: jnp.ndarray
    group_id: jnp.ndarray
    img_id: jnp.ndarray
    # ServiceAffinity (policy): own-nodeSelector-pin signature
    sa_self_id: jnp.ndarray


@dataclass(frozen=True)
class PolicySpec:
    """Compile-time image of a scheduler Policy (api/types.go:52-77) for the
    device engine: which standard predicates run and each score component's
    weight. Built by jaxe.policyc.compile_policy; None on the provider paths
    (= provider defaults). Hashable so EngineConfig stays a valid jit static.

    pred_keys: frozenset of predicate names from PREDICATES_ORDERING that the
    policy enables (customs are carried via the has_label_* flags + Statics
    rows, not names). CheckNodeCondition runs regardless — it is mandatory
    (build_predicates unions mandatory_fit_predicates)."""

    pred_keys: frozenset
    w_least: int = 0
    w_most: int = 0
    w_balanced: int = 0
    w_node_aff: int = 0
    w_taint: int = 0
    w_avoid: int = 0           # NodePreferAvoidPodsPriority policy weight
    w_spread: int = 0
    w_interpod: int = 0
    w_image: int = 0           # ImageLocalityPriority (table-driven)
    # ServiceAntiAffinity custom priorities: one weight per entry, parallel
    # to the Statics.saa_dom rows (selector_spreading.go:176-280)
    saa_weights: tuple = ()
    # ServiceAffinity predicates (policy): one slot per entry — a canonical
    # PREDICATES_ORDERING name evaluates at that position; any other policy
    # name runs after the fixed ordering at its alphabetical tail position
    # ("tail:<k>"). sa_segs holds each entry's label count, segmenting the
    # concatenated Statics.sa_val label rows. sa_enabled gates the
    # first-matching-pod lock updates in the bind scatter.
    sa_enabled: bool = False
    sa_slots: tuple = ()
    sa_segs: tuple = ()
    # 1.0 PodFitsPorts alias: tail slots ("tail:<k>") where the
    # port-conflict stage runs again (the host evaluates registry keys
    # outside predicates.Ordering() at the alphabetical tail)
    ports_slots: tuple = ()
    # first-failure reason selection becomes collect-all-failures
    # (generic_scheduler.go alwaysCheckAllPredicates)
    always_check_all: bool = False
    # one entry per Statics.label_ok row: the PREDICATES_ORDERING name whose
    # slot the row evaluates at, or "tail:<k>" for its alphabetical position
    # after the fixed ordering
    label_rows: tuple = ()
    has_label_prio: bool = False


@dataclass(frozen=True)
class EngineConfig:
    """Static (compile-time) provider configuration."""

    most_requested: bool = False  # LeastRequested -> MostRequested swap (TD/autoscaler)
    num_reason_bits: int = NUM_FIXED_BITS
    # pod-group features — compiled in only when the workload needs them
    has_ports: bool = False
    has_services: bool = False
    has_interpod: bool = False
    has_disk_conflict: bool = False
    has_maxpd: bool = False
    has_vol_zone: bool = False
    maxpd_limits: tuple = DEFAULT_MAXPD_LIMITS  # (EBS, GCE PD, AzureDisk)
    hard_weight: int = 10         # HardPodAffinitySymmetricWeight
    n_topo_doms: int = 1          # segment counts (incl. the invalid-0 bucket)
    n_zone_doms: int = 1
    # lax.scan unroll factor for the exact sequential mode: semantically
    # identical, amortizes per-step dispatch overhead at the cost of compile
    # time; tune via TPUSIM_SCAN_UNROLL (backend reads the env)
    scan_unroll: int = 1
    # policy-as-data overrides (None = the named provider's defaults)
    policy: PolicySpec = None
    # segment count for the ServiceAntiAffinity label domains (incl. the
    # invalid-0 bucket); set by the backend from the compiled node labels
    n_saa_doms: int = 1
    # decision provenance (ISSUE 13): when > 0 the scan additionally emits,
    # per pod, the top-k candidate nodes by final score with each node's
    # per-priority score contributions (explain_part_names order). Static,
    # so explain_k=0 traces are byte-identical to pre-provenance programs —
    # zero cost when disabled.
    explain_k: int = 0
    # node-axis sharding (ISSUE 16): when set, the fused step runs inside
    # shard_map over a mesh axis of this name — every global node reduction
    # becomes a collective and selection merges across shards bit-identically
    # (integer arithmetic only, so the collective sums/maxes are exact and
    # order-independent). None (the default) emits NO collectives: the trace
    # is byte-identical to the single-device engine.
    shard_axis: str = None


# ---------------------------------------------------------------------------
# Axis registries: for each pytree field, a tuple naming every array axis.
# sharding.py pads/shards the "node" axis; whatif.py unifies every *other*
# named axis to a common cross-scenario size. PodX omits its leading pod axis.
# Adding a field to a NamedTuple requires only a matching entry here.
# ---------------------------------------------------------------------------

STATICS_AXES = dict(
    alloc_cpu=("node",), alloc_mem=("node",), alloc_gpu=("node",),
    alloc_eph=("node",), allowed_pods=("node",), alloc_scalar=("node", "scalar"),
    cond_fail_bits=("node",), mem_pressure=("node",), disk_pressure=("node",),
    selector_ok=("sig_sel", "node"), taint_ok=("sig_tol", "node"),
    taint_ok_noexec=("sig_tol", "node"), intolerable=("sig_tol", "node"), affinity_count=("sig_aff", "node"),
    avoid_score=("sig_avoid", "node"), host_ok=("sig_host", "node"),
    port_conflict=("port_sig", "port_sig"), port_sig=("group",),
    disk_conflict=("disk_sig", "disk_sig"), disk_sig=("group",),
    vol_mask=("group", "vol_id"), vol_type=("vol_id", "vol_filter"),
    zone_ok=("group", "node"),
    ss_rows=("spread_sig", "group"), ss_sig=("group",),
    saa_rows=("saa_sig", "group"), saa_sig=("group",),
    term_match=("term_sig", "group"),
    zone_dom=("node",), topo_dom=("topo_key", "node"),
    aff_valid=("group", "aff_term"), aff_err=("group",),
    aff_empty=("group", "aff_term"), aff_term=("group", "aff_term"),
    aff_key=("group", "aff_term"), aff_hostname=("group", "aff_term"),
    aff_self=("group", "aff_term"), aff_unplaced=("group", "aff_term"),
    anti_valid=("group", "anti_term"), anti_err=("group",),
    anti_empty=("group", "anti_term"), anti_term=("group", "anti_term"),
    anti_key=("group", "anti_term"), anti_hostname=("group", "anti_term"),
    pref_w=("group", "pref_term"), pref_term=("group", "pref_term"),
    pref_key=("group", "pref_term"),
    label_ok=("label_pred", "node"), label_prio=("node",),
    image_score=("sig_img", "node"), saa_dom=("saa_entry", "node"),
    sa_val=("sa_label", "node"),
    sa_pin=("sig_sa_self", "sa_label"),
)
CARRY_AXES = dict(
    used_cpu=("node",), used_mem=("node",), used_gpu=("node",), used_eph=("node",),
    used_scalar=("node", "scalar"), nonzero_cpu=("node",), nonzero_mem=("node",),
    pod_count=("node",), presence=("group", "node"),
    presence_dom=("group", "topo_key", "topo_dom"),
    used_vols=("node", "vol_id"), sa_lock=("saa_sig",), rr=(),
)
PODX_AXES = dict(
    req_cpu=(), req_mem=(), req_gpu=(), req_eph=(), req_scalar=("scalar",),
    nz_cpu=(), nz_mem=(), zero_request=(), best_effort=(), sel_id=(),
    tol_id=(), aff_id=(), avoid_id=(), host_id=(), group_id=(), img_id=(),
    sa_self_id=(),
)
# Node-axis pad fill per field (default 0). Exception: cond_fail_bits is
# special-cased in sharding._pad_node_tree with a lazily-built infeasible
# sentinel (1<<62 needs x64 enabled), so padded nodes can never be selected.
PAD_FILLS: dict = {}


def scan_unroll_from_env() -> int:
    import os

    try:
        return max(1, int(os.environ.get("TPUSIM_SCAN_UNROLL", "1")))
    except ValueError:
        return 1


def config_for(compiled_list, most_requested: bool, num_reason_bits: int,
               hard_weight: int = 10) -> EngineConfig:
    """Union EngineConfig across one or more CompiledClusters (the what-if
    batch shares one jitted program; zero-filled tables are no-ops)."""
    limits = [c.maxpd_limits for c in compiled_list if c.has_maxpd]
    return EngineConfig(
        most_requested=most_requested,
        num_reason_bits=num_reason_bits,
        has_ports=any(c.has_ports for c in compiled_list),
        has_services=any(c.has_services for c in compiled_list),
        has_interpod=any(c.has_interpod for c in compiled_list),
        has_disk_conflict=any(c.has_disk_conflict for c in compiled_list),
        has_maxpd=any(c.has_maxpd for c in compiled_list),
        has_vol_zone=any(c.has_vol_zone for c in compiled_list),
        maxpd_limits=limits[0] if limits else DEFAULT_MAXPD_LIMITS,
        hard_weight=hard_weight,
        n_topo_doms=max(c.n_topo_doms for c in compiled_list),
        n_zone_doms=max(c.n_zone_doms for c in compiled_list),
        scan_unroll=scan_unroll_from_env())


def statics_to_host(compiled: CompiledCluster) -> Statics:
    """Statics pytree over host numpy arrays (no device transfer)."""
    s, t, gt = compiled.statics, compiled.tables, compiled.groups
    return Statics(
        alloc_cpu=s.alloc_cpu, alloc_mem=s.alloc_mem,
        alloc_gpu=s.alloc_gpu, alloc_eph=s.alloc_eph,
        allowed_pods=s.allowed_pods, alloc_scalar=s.alloc_scalar,
        cond_fail_bits=s.cond_fail_bits, mem_pressure=s.mem_pressure,
        disk_pressure=s.disk_pressure,
        selector_ok=t.selector_ok, taint_ok=t.taint_ok,
        taint_ok_noexec=t.taint_ok_noexec, intolerable=t.intolerable, affinity_count=t.affinity_count,
        avoid_score=t.avoid_score, host_ok=t.host_ok,
        port_conflict=gt.port_conflict, port_sig=gt.port_sig,
        disk_conflict=gt.disk_conflict, disk_sig=gt.disk_sig,
        vol_mask=gt.vol_mask, vol_type=gt.vol_type, zone_ok=gt.zone_ok,
        ss_rows=gt.ss_rows, ss_sig=gt.ss_sig,
        saa_rows=gt.saa_rows, saa_sig=gt.saa_sig, term_match=gt.term_match,
        zone_dom=gt.zone_dom, topo_dom=gt.topo_dom,
        aff_valid=gt.aff_valid, aff_err=gt.aff_err, aff_empty=gt.aff_empty,
        aff_term=gt.aff_term, aff_key=gt.aff_key,
        aff_hostname=gt.aff_hostname, aff_self=gt.aff_self,
        aff_unplaced=gt.aff_unplaced,
        anti_valid=gt.anti_valid, anti_err=gt.anti_err,
        anti_empty=gt.anti_empty, anti_term=gt.anti_term,
        anti_key=gt.anti_key, anti_hostname=gt.anti_hostname,
        pref_w=gt.pref_w, pref_term=gt.pref_term, pref_key=gt.pref_key,
        # trivial policy rows; jaxe.policyc overwrites them via _replace
        label_ok=np.ones((1, len(s.alloc_cpu)), dtype=bool),
        label_prio=np.zeros(len(s.alloc_cpu), dtype=np.int64),
        image_score=np.zeros((1, len(s.alloc_cpu)), dtype=np.int64),
        saa_dom=np.zeros((1, len(s.alloc_cpu)), dtype=np.int32),
        sa_val=np.zeros((1, len(s.alloc_cpu)), dtype=np.int32),
        sa_pin=np.zeros((1, 1), dtype=np.int32))


def _presence_dom_init(presence: np.ndarray, topo_dom: np.ndarray,
                       n_doms: int) -> np.ndarray:
    """presence_dom[g, k, d] = sum of presence[g, n] over nodes in domain d."""
    g, _ = presence.shape
    k = topo_dom.shape[0]
    pd = np.zeros((g, k, n_doms), dtype=np.int32)
    for ki in range(k):
        np.add.at(pd[:, ki, :], (slice(None), topo_dom[ki]), presence)
    return pd


def carry_init_host(compiled: CompiledCluster) -> Carry:
    """Initial carry over host numpy arrays (no device transfer)."""
    d, gt = compiled.dynamic, compiled.groups
    return Carry(
        used_cpu=d.used_cpu, used_mem=d.used_mem, used_gpu=d.used_gpu,
        used_eph=d.used_eph, used_scalar=d.used_scalar,
        nonzero_cpu=d.nonzero_cpu, nonzero_mem=d.nonzero_mem,
        pod_count=d.pod_count,
        presence=gt.presence,
        presence_dom=_presence_dom_init(gt.presence, gt.topo_dom,
                                        compiled.n_topo_doms),
        used_vols=gt.used_vols_init,
        sa_lock=np.full(gt.saa_rows.shape[0], -1, dtype=np.int32),
        rr=np.int64(0))


def pod_columns_to_host(cols: PodColumns) -> PodX:
    """PodX pytree over host numpy arrays (no device transfer)."""
    return PodX(
        req_cpu=cols.req_cpu, req_mem=cols.req_mem, req_gpu=cols.req_gpu,
        req_eph=cols.req_eph, req_scalar=cols.req_scalar,
        nz_cpu=cols.nz_cpu, nz_mem=cols.nz_mem,
        zero_request=cols.zero_request, best_effort=cols.best_effort,
        sel_id=cols.sel_id, tol_id=cols.tol_id, aff_id=cols.aff_id,
        avoid_id=cols.avoid_id, host_id=cols.host_id, group_id=cols.group_id,
        img_id=cols.img_id, sa_self_id=cols.sa_self_id)


def _tree_to_device(tree):
    return type(tree)(*(jnp.asarray(a) for a in tree))


def statics_to_device(compiled: CompiledCluster) -> Statics:
    return _tree_to_device(statics_to_host(compiled))


def carry_init(compiled: CompiledCluster) -> Carry:
    return _tree_to_device(carry_init_host(compiled))


def pod_columns_to_device(cols: PodColumns) -> PodX:
    return _tree_to_device(pod_columns_to_host(cols))


def _ratio_score(requested, capacity, most: bool):
    """least_requested.go:41-52 / most_requested.go:44-55, elementwise."""
    valid = (capacity > 0) & (requested <= capacity)
    if most:
        return jnp.where(valid, (requested * MAX_PRIORITY) // jnp.maximum(capacity, 1), 0)
    return jnp.where(
        valid, ((capacity - requested) * MAX_PRIORITY) // jnp.maximum(capacity, 1), 0)


# --- exact 128-bit integer helpers (4x32-bit limbs held in uint64) ---------
# Score arithmetic must be EXACT, not float64: TPUs have no native f64 (XLA
# emulates it), and emulated divisions round differently from the host's IEEE
# f64, flipping scores at integer boundaries — observed as placement-hash
# divergence between the CPU and TPU runs of the same workload. Products like
# req_cpu*alloc_mem overflow int64 for large-memory nodes, so the balanced-
# allocation score runs on 128-bit limbs (DEVIATIONS.md #16).

_M32 = np.uint64(0xFFFFFFFF)


def _mul_limbs(a, b):
    """Exact 128-bit product of two nonnegative int64 arrays as 4x32-bit
    limbs (least-significant first), each limb stored in a uint64."""
    a = a.astype(jnp.uint64)
    b = b.astype(jnp.uint64)
    ah, al = a >> 32, a & _M32
    bh, bl = b >> 32, b & _M32
    ll = al * bl
    lh = al * bh
    hl = ah * bl
    hh = ah * bh
    l0 = ll & _M32
    c1 = (ll >> 32) + (lh & _M32) + (hl & _M32)
    l1 = c1 & _M32
    c2 = (c1 >> 32) + (lh >> 32) + (hl >> 32) + (hh & _M32)
    l2 = c2 & _M32
    l3 = (c2 >> 32) + (hh >> 32)  # < 2^32: the full product is < 2^126
    return (l0, l1, l2, l3)


def _scale_limbs(limbs, k: int):
    """limbs * k for a small Python int k (k <= 10); returns len+1 limbs."""
    k64 = np.uint64(k)
    out = []
    carry = jnp.zeros_like(limbs[0])
    for li in limbs:
        v = li * k64 + carry  # < 2^32 * 10 + carry: fits uint64 easily
        out.append(v & _M32)
        carry = v >> 32
    out.append(carry)
    return tuple(out)


def _ge_limbs(x, y):
    """x >= y, lexicographic over equal-length limb tuples (LSB first)."""
    ge = jnp.ones_like(x[0], dtype=bool)
    for xi, yi in zip(x, y):  # LSB -> MSB; the last differing limb decides
        ge = (xi > yi) | ((xi == yi) & ge)
    return ge


def _sub_limbs(x, y):
    """x - y over 4-limb values, requiring x >= y elementwise."""
    base = np.uint64(1) << np.uint64(32)
    out = []
    borrow = jnp.zeros_like(x[0])
    for xi, yi in zip(x, y):
        need = yi + borrow  # <= 2^32: no overflow
        under = xi < need
        out.append(jnp.where(under, xi + base - need, xi - need))
        borrow = under.astype(jnp.uint64)
    return tuple(out)


def _balanced_score(req_cpu, req_mem, alloc_cpu, alloc_mem):
    """balanced_resource_allocation.go:39-63 in exact rational arithmetic.

    score = floor(10 * (den - |rc*am - rm*ac|) / den), den = ac*am — the same
    quantity Go computes as int64((1-|cpuFrac-memFrac|)*10) in float64, here
    evaluated exactly on 128-bit limbs: score = #{t in 0..9 : 10*num <= t*den}
    (t*den >= 10*num  <=>  t/10 >= num/den  counts each score unit)."""
    p1 = _mul_limbs(req_cpu, alloc_mem)
    p2 = _mul_limbs(req_mem, alloc_cpu)
    swap = _ge_limbs(p1, p2)
    hi = tuple(jnp.where(swap, a, b) for a, b in zip(p1, p2))
    lo = tuple(jnp.where(swap, b, a) for a, b in zip(p1, p2))
    num10 = _scale_limbs(_sub_limbs(hi, lo), 10)
    den = _mul_limbs(alloc_cpu, alloc_mem)
    score = jnp.zeros(req_cpu.shape, dtype=jnp.int64)
    for t in range(10):
        score = score + _ge_limbs(_scale_limbs(den, t), num10).astype(jnp.int64)
    zero = ((alloc_cpu == 0) | (req_cpu >= alloc_cpu)
            | (alloc_mem == 0) | (req_mem >= alloc_mem))
    return jnp.where(zero, 0, score)


def _seg_rows(values, doms, num_segments: int):
    """Row-wise segment sums: [T, N] values × [T, N] domain ids -> [T, D]."""
    return jax.vmap(
        lambda v, d: jax.ops.segment_sum(v, d, num_segments=num_segments)
    )(values, doms)


def policy_weights(ps, most_requested: bool) -> tuple:
    """The score-component weight table (generic_scheduler.go:631-639),
    shared by the XLA scan and the Pallas fast kernel so the ps-None
    provider defaults (the most_requested swap, AVOID_PODS_WEIGHT) can
    never drift between the two engines: (least, most, balanced, node_aff,
    taint, avoid, spread, interpod)."""
    if ps is None:
        w_least, w_most = (0, 1) if most_requested else (1, 0)
        return (w_least, w_most, 1, 1, 1, AVOID_PODS_WEIGHT, 1, 1)
    return (ps.w_least, ps.w_most, ps.w_balanced, ps.w_node_aff,
            ps.w_taint, ps.w_avoid, ps.w_spread, ps.w_interpod)


# Any real node score is a small weighted sum of 0..10*weight components;
# masking infeasible nodes to -(1<<62) before the explain top_k leaves a
# comfortable decode threshold at -(1<<61): a top-k row scoring at or below
# it is padding from fewer-than-k feasible nodes, not a candidate.
EXPLAIN_SENTINEL = -(1 << 61)


def explain_part_names(config: EngineConfig) -> list:
    """Provider-priority names for the explain lanes' part columns, in the
    exact order _evaluate's score section appends them. Must mirror that
    section's static gating — tests/test_provenance.py locks the two
    together by summing parts back to the emitted top-k scores."""
    ps = config.policy
    (w_least, w_most, w_balanced, w_node_aff, w_taint, w_avoid, w_spread,
     w_interpod) = policy_weights(ps, config.most_requested)
    names = []
    if w_least:
        names.append("LeastRequestedPriority")
    if w_most:
        names.append("MostRequestedPriority")
    if w_balanced:
        names.append("BalancedResourceAllocation")
    if w_node_aff:
        names.append("NodeAffinityPriority")
    if w_taint:
        names.append("TaintTolerationPriority")
    if w_avoid:
        names.append("NodePreferAvoidPodsPriority")
    if ps is not None and ps.has_label_prio:
        names.append("NodeLabelPriority")
    if ps is not None and ps.w_image:
        names.append("ImageLocalityPriority")
    if ps is not None and ps.saa_weights:
        names.append("ServiceAntiAffinityPriority")
    if config.has_services and w_spread:
        names.append("SelectorSpreadPriority")
    if config.has_interpod and w_interpod:
        names.append("InterPodAffinityPriority")
    return names


# --- node-axis collectives (ISSUE 16) --------------------------------------
# Every cross-node reduction in _evaluate/_select funnels through these four
# helpers. With axis=None they are identity wrappers (the single-device trace
# is untouched); with a mesh axis name they append the matching collective.
# All reduced quantities are integers (or integer-valued f64 counts below
# 2^53), so psum/pmax/pmin across shards are exact and order-independent —
# the basis for the bit-identical cross-shard claim.

def _ax_sum(v, axis):
    return v if axis is None else jax.lax.psum(v, axis)


def _ax_max(v, axis):
    return v if axis is None else jax.lax.pmax(v, axis)


def _ax_min(v, axis):
    return v if axis is None else jax.lax.pmin(v, axis)


def _ax_any(v, axis):
    if axis is None:
        return v
    return jax.lax.pmax(v.astype(jnp.int32), axis) != 0


def _evaluate(config: EngineConfig, carry: Carry, st: Statics, x: PodX):
    """Filter + score one pod against the carried aggregates.

    Returns (feasible[N], reason_bits[N], score[N], n_feasible).

    With config.policy set, stages/components are statically gated to the
    policy's predicate set and priority weights (factory.go CreateFromConfig);
    stage order always follows PREDICATES_ORDERING so first-failure reason
    selection matches the host engine's short-circuit."""
    ps = config.policy
    en = ps.pred_keys if ps is not None else None
    ax = config.shard_axis

    def on(name):
        # None = the provider's default predicate set (the full pipeline)
        return en is None or name in en

    # ---- filter: staged fail masks in predicatesOrdering ----
    # CheckNodeCondition is mandatory (build_predicates always unions it in);
    # CheckNodeUnschedulable adds nothing on the device: the condition bits
    # already carry spec.unschedulable and fail first with the same reason
    fail_cond = st.cond_fail_bits != 0
    stages = [(fail_cond, st.cond_fail_bits)]
    if (ps is not None and ps.always_check_all and en is not None
            and CHECK_NODE_UNSCHEDULABLE_PRED in en):
        # with always-check-all, a registered CheckNodeUnschedulable emits
        # the unschedulable reason a SECOND time beyond the mandatory
        # condition check (both run; same string) — the count-mode histogram
        # below sums stage firings, so a duplicate stage reproduces the
        # host's doubled occurrence exactly
        unsched = (st.cond_fail_bits
                   & (jnp.int64(1) << BIT_NODE_UNSCHEDULABLE)) != 0
        stages.append((unsched, jnp.int64(1) << BIT_NODE_UNSCHEDULABLE))

    # policy label-presence predicates evaluate at the ordering slot of the
    # name they were registered under (the host's _predicate_key_order slots
    # any custom key whose name appears in PREDICATES_ORDERING); "" = tail
    label_at: dict = {}
    if ps is not None:
        for i, slot in enumerate(ps.label_rows):
            label_at.setdefault(slot, []).append(i)

    if ps is not None and ps.sa_slots:
        # ServiceAffinity predicates (predicates.py check_service_affinity),
        # shared prelude: the candidate node must match (a) the labels the
        # pod pins via its own nodeSelector and (b), for the remaining
        # entry labels, the values on the locked first-service-pod's node —
        # when a lock exists and the locked node carries the label. The
        # lock (a node index) is entry-independent (same first matching
        # pod); only the label segments differ per entry.
        _sa_f = st.saa_sig[x.group_id]
        _sa_lock = carry.sa_lock[_sa_f]
        _sa_li = jnp.maximum(_sa_lock, 0)
        _sa_pin = st.sa_pin[x.sa_self_id]                    # [La]
        _sa_unres = _sa_pin == 0
        _sa_own_l = _sa_unres[:, None] | (st.sa_val == _sa_pin[:, None])
        _sa_locked = st.sa_val[:, _sa_li]                    # [La]
        _sa_pinned = _sa_unres & (_sa_locked > 0)
        _sa_lock_l = (~_sa_pinned[:, None]
                      | (st.sa_val == _sa_locked[:, None]))  # [La, N]
        _sa_off = [0]
        for seg in ps.sa_segs:
            _sa_off.append(_sa_off[-1] + seg)

    def sa_fail(e):
        l0, l1 = _sa_off[e], _sa_off[e + 1]
        own_ok = jnp.all(_sa_own_l[l0:l1], axis=0)
        lock_ok = jnp.all(_sa_lock_l[l0:l1], axis=0)
        ok = own_ok & (lock_ok | (_sa_lock < 0))
        return ~ok

    def emit_label(slot_name):
        for i in label_at.get(slot_name, ()):
            stages.append((~st.label_ok[i],
                           jnp.int64(1) << BIT_NODE_LABEL_PRESENCE))
        if ps is not None:
            for e, slot in enumerate(ps.sa_slots):
                if slot == slot_name:
                    stages.append((sa_fail(e),
                                   jnp.int64(1) << BIT_SERVICE_AFFINITY))
            if slot_name in ps.ports_slots and config.has_ports:
                # the PodFitsPorts tail alias re-emits the port stage here
                # (port_bad is defined by the time tail slots run; a
                # port-free workload has nothing to re-check)
                stages.append((port_bad, jnp.int64(1) << BIT_HOST_PORTS))

    emit_label(CHECK_NODE_UNSCHEDULABLE_PRED)

    general_on = on(GENERAL_PRED)
    part_on = {name: en is not None and name in en
               for name in (HOSTNAME_PRED, POD_FITS_HOST_PORTS_PRED,
                            MATCH_NODE_SELECTOR_PRED, POD_FITS_RESOURCES_PRED)}

    if general_on or part_on[POD_FITS_RESOURCES_PRED]:
        insuff_pods = (carry.pod_count + 1) > st.allowed_pods
        check_res = ~x.zero_request
        insuff_cpu = check_res & (st.alloc_cpu < x.req_cpu + carry.used_cpu)
        insuff_mem = check_res & (st.alloc_mem < x.req_mem + carry.used_mem)
        insuff_gpu = check_res & (st.alloc_gpu < x.req_gpu + carry.used_gpu)
        insuff_eph = check_res & (st.alloc_eph < x.req_eph + carry.used_eph)
        insuff_scalar = check_res[..., None] & (
            st.alloc_scalar < x.req_scalar[None, :] + carry.used_scalar)
        fail_res = (insuff_pods | insuff_cpu | insuff_mem | insuff_gpu
                    | insuff_eph | jnp.any(insuff_scalar, axis=-1))
        bits_res = (
            insuff_pods.astype(jnp.int64) << BIT_INSUFFICIENT_PODS
            | insuff_cpu.astype(jnp.int64) << BIT_INSUFFICIENT_CPU
            | insuff_mem.astype(jnp.int64) << BIT_INSUFFICIENT_MEMORY
            | insuff_gpu.astype(jnp.int64) << BIT_INSUFFICIENT_GPU
            | insuff_eph.astype(jnp.int64) << BIT_INSUFFICIENT_EPHEMERAL)
        if st.alloc_scalar.shape[-1] > 0:
            scalar_bits = (insuff_scalar.astype(jnp.int64)
                           << (NUM_FIXED_BITS + jnp.arange(
                               st.alloc_scalar.shape[-1], dtype=jnp.int64)))
            bits_res = bits_res | jnp.sum(scalar_bits, axis=-1)
    if general_on or part_on[HOSTNAME_PRED]:
        host_bad = ~st.host_ok[x.host_id]
    if general_on or part_on[MATCH_NODE_SELECTOR_PRED]:
        sel_bad = ~st.selector_ok[x.sel_id]
    ports_alias_on = ps is not None and bool(ps.ports_slots)
    if config.has_ports and (general_on or part_on[POD_FITS_HOST_PORTS_PRED]
                             or ports_alias_on):
        # PodFitsHostPorts (predicates.go:1019-1039), part of GeneralPredicates:
        # a wanted port of my group conflicts with occupancy of any group
        # present; conflict is factored through interned port-set ids
        conflict_row = st.port_conflict[st.port_sig[x.group_id]][st.port_sig]
        port_bad = jnp.any(conflict_row[:, None] & (carry.presence > 0), axis=0)

    if general_on:
        fail_general = fail_res | host_bad | sel_bad
        bits_general = (
            bits_res
            | host_bad.astype(jnp.int64) << BIT_HOSTNAME_MISMATCH
            | sel_bad.astype(jnp.int64) << BIT_NODE_SELECTOR_MISMATCH)
        if config.has_ports:
            fail_general = fail_general | port_bad
            bits_general = bits_general | (
                port_bad.astype(jnp.int64) << BIT_HOST_PORTS)
        stages.append((fail_general, bits_general))
    emit_label(GENERAL_PRED)
    # individually-named parts run as separate short-circuit stages in the
    # ordering slots HostName → PodFitsHostPorts → MatchNodeSelector →
    # PodFitsResources (predicates.go:130-136)
    if part_on[HOSTNAME_PRED]:
        stages.append((host_bad, jnp.int64(1) << BIT_HOSTNAME_MISMATCH))
    emit_label(HOSTNAME_PRED)
    if part_on[POD_FITS_HOST_PORTS_PRED] and config.has_ports:
        stages.append((port_bad, jnp.int64(1) << BIT_HOST_PORTS))
    emit_label(POD_FITS_HOST_PORTS_PRED)
    if part_on[MATCH_NODE_SELECTOR_PRED]:
        stages.append((sel_bad, jnp.int64(1) << BIT_NODE_SELECTOR_MISMATCH))
    emit_label(MATCH_NODE_SELECTOR_PRED)
    if part_on[POD_FITS_RESOURCES_PRED]:
        stages.append((fail_res, bits_res))
    emit_label(POD_FITS_RESOURCES_PRED)

    if config.has_disk_conflict and on(NO_DISK_CONFLICT_PRED):
        # NoDiskConflict (predicates.go:266-276): my volume set conflicts with
        # the volume set of any group present on the node
        disk_row = st.disk_conflict[st.disk_sig[x.group_id]][st.disk_sig]
        fail_disk = jnp.any(disk_row[:, None] & (carry.presence > 0), axis=0)
        stages.append((fail_disk, jnp.int64(1) << BIT_DISK_CONFLICT))
    emit_label(NO_DISK_CONFLICT_PRED)

    if on(POD_TOLERATES_NODE_TAINTS_PRED):
        stages.append((~st.taint_ok[x.tol_id],
                       jnp.int64(1) << BIT_TAINTS_NOT_TOLERATED))
    emit_label(POD_TOLERATES_NODE_TAINTS_PRED)
    if en is not None and POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED in en:
        # policy-registered NoExecute-only variant (not in any provider set)
        stages.append((~st.taint_ok_noexec[x.tol_id],
                       jnp.int64(1) << BIT_TAINTS_NOT_TOLERATED))
    emit_label(POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED)
    emit_label(CHECK_NODE_LABEL_PRESENCE_PRED)
    emit_label(CHECK_SERVICE_AFFINITY_PRED)

    maxpd_on = (on(MAX_EBS_VOLUME_COUNT_PRED), on(MAX_GCE_PD_VOLUME_COUNT_PRED),
                on(MAX_AZURE_DISK_VOLUME_COUNT_PRED))
    if config.has_maxpd and any(maxpd_on):
        # Max{EBS,GCEPD,AzureDisk}VolumeCount (predicates.go:422-460): unique
        # relevant volume ids on the node incl. mine vs the per-type limit;
        # a pod adding no relevant volumes passes regardless. Disabled types
        # get an unreachable limit.
        mask_g = st.vol_mask[x.group_id]                       # [V]
        type_i = st.vol_type.astype(jnp.int32)                 # [V, 3]
        union_counts = (carry.used_vols | mask_g[None, :]).astype(jnp.int32) @ type_i
        my_counts = mask_g.astype(jnp.int32) @ type_i          # [3]
        limits = jnp.array(
            [lim if enabled else (1 << 30)
             for lim, enabled in zip(config.maxpd_limits, maxpd_on)],
            dtype=jnp.int32)
        fail_maxpd = jnp.any((my_counts[None, :] > 0)
                             & (union_counts > limits[None, :]), axis=1)
        stages.append((fail_maxpd, jnp.int64(1) << BIT_MAX_VOLUME_COUNT))
    emit_label(MAX_EBS_VOLUME_COUNT_PRED)
    emit_label(MAX_GCE_PD_VOLUME_COUNT_PRED)
    emit_label(MAX_AZURE_DISK_VOLUME_COUNT_PRED)
    emit_label(CHECK_VOLUME_BINDING_PRED)

    if config.has_vol_zone and on(NO_VOLUME_ZONE_CONFLICT_PRED):
        # NoVolumeZoneConflict (predicates.go:510-533): static per
        # (volume-set, node) — bound PV zone labels vs node zone labels
        stages.append((~st.zone_ok[x.group_id],
                       jnp.int64(1) << BIT_VOLUME_ZONE_CONFLICT))
    emit_label(NO_VOLUME_ZONE_CONFLICT_PRED)

    if on(CHECK_NODE_MEMORY_PRESSURE_PRED):
        stages.append((st.mem_pressure & x.best_effort,
                       jnp.int64(1) << BIT_MEMORY_PRESSURE))
    emit_label(CHECK_NODE_MEMORY_PRESSURE_PRED)
    if on(CHECK_NODE_DISK_PRESSURE_PRED):
        stages.append((st.disk_pressure, jnp.int64(1) << BIT_DISK_PRESSURE))
    emit_label(CHECK_NODE_DISK_PRESSURE_PRED)

    if config.has_interpod:
        # shared prelude for the MatchInterPodAffinity predicate and the
        # InterPodAffinityPriority score block
        g = x.group_id
        presence_f = carry.presence.astype(jnp.float64)
        pd_f = carry.presence_dom.astype(jnp.float64)
        k_count = st.topo_dom.shape[0]

    if config.has_interpod and on(MATCH_INTERPOD_AFFINITY_PRED):
        # MatchInterPodAffinity (predicates.go:1125-1450) — last in
        # predicatesOrdering. Group-space matching is precompiled; here only
        # presence/topology aggregation runs.

        # own required affinity terms (_satisfies_pods_affinity_anti_affinity)
        mcount = st.term_match[st.aff_term[g]].astype(jnp.float64) @ presence_f  # [Ta, N]
        dom_rows = st.topo_dom[st.aff_key[g]]                       # [Ta, N]
        valid_dom = dom_rows > 0
        dc_at = jnp.take_along_axis(
            _ax_sum(_seg_rows(mcount, dom_rows, config.n_topo_doms), ax),
            dom_rows, axis=1)
        is_host = st.aff_hostname[g][:, None]
        on_node = mcount > 0.5
        term_matches = jnp.where(is_host, valid_dom & on_node,
                                 valid_dom & (dc_at > 0.5))
        # hostname terms scan only this node's pods (predicates.go: topologyKey
        # == hostname restricts the search), so "matching pod exists" is
        # per-node there and global (incl. unplaced snapshot pods) otherwise
        exists = jnp.where(
            is_host, on_node,
            ((_ax_sum(jnp.sum(mcount, axis=1), ax) > 0.5)
             | st.aff_unplaced[g])[:, None])
        term_ok = term_matches | ((~exists) & st.aff_self[g][:, None])
        aff_fail = jnp.any(st.aff_valid[g][:, None] & ~term_ok,
                           axis=0) | st.aff_err[g]

        # own required anti-affinity terms
        bmcount = st.term_match[st.anti_term[g]].astype(jnp.float64) @ presence_f
        bdom_rows = st.topo_dom[st.anti_key[g]]
        bvalid = bdom_rows > 0
        bdc_at = jnp.take_along_axis(
            _ax_sum(_seg_rows(bmcount, bdom_rows, config.n_topo_doms), ax),
            bdom_rows, axis=1)
        b_is_host = st.anti_hostname[g][:, None]
        b_matches = jnp.where(b_is_host, bvalid & (bmcount > 0.5),
                              bvalid & (bdc_at > 0.5))
        anti_fail = jnp.any(st.anti_valid[g][:, None] & b_matches,
                            axis=0) | st.anti_err[g]

        # existing pods' anti-affinity vs me (symmetric check; runs first)
        w = st.anti_valid & st.term_match[st.anti_term, g]          # [G, Tb]
        grp_present = _ax_sum(jnp.sum(carry.presence, axis=1), ax) > 0  # [G]
        fail_all = jnp.any(w & st.anti_empty & grp_present[:, None])
        key_oh = jax.nn.one_hot(st.anti_key, k_count, dtype=jnp.float64)
        bad_dom = jnp.einsum("gtk,gt,gkd->kd", key_oh,
                             (w & ~st.anti_empty).astype(jnp.float64), pd_f)
        bad_at = jnp.take_along_axis(bad_dom, st.topo_dom, axis=1)  # [K, N]
        exist_fail = jnp.any((st.topo_dom > 0) & (bad_at > 0.5),
                             axis=0) | fail_all

        fail_interpod = exist_fail | aff_fail | anti_fail
        # two reasons per failure: the umbrella + the specific rule, in the
        # engine's check order (existing-anti, affinity, anti-affinity)
        interpod_bits = (jnp.int64(1) << BIT_AFFINITY_NOT_MATCH) | jnp.where(
            exist_fail, jnp.int64(1) << BIT_EXISTING_ANTI_AFFINITY,
            jnp.where(aff_fail, jnp.int64(1) << BIT_AFFINITY_RULES,
                      jnp.int64(1) << BIT_ANTI_AFFINITY_RULES))
        stages.append((fail_interpod, interpod_bits))
    emit_label(MATCH_INTERPOD_AFFINITY_PRED)
    # customs under non-ordering names run after the fixed ordering in the
    # host's ALPHABETICAL name order; policyc assigns each tail custom
    # (label-presence row or ServiceAffinity entry) its sorted position as
    # slot "tail:<k>"
    if ps is not None:
        tail_ks = sorted(
            int(s.split(":", 1)[1])
            for s in set(ps.label_rows) | set(ps.sa_slots)
            | set(ps.ports_slots)
            if s.startswith("tail:"))
        for k in tail_ks:
            emit_label(f"tail:{k}")

    fail_any = stages[0][0]
    for fail, _ in stages[1:]:
        fail_any = fail_any | fail
    feasible = ~fail_any
    reason_bits = jnp.int64(0)
    aca_counts = None
    if ps is not None and ps.always_check_all:
        # alwaysCheckAllPredicates: every failing stage contributes its
        # reasons (podFitsOnNode keeps evaluating past the first failure).
        # Sentinel-padded nodes (sharding/what-if node-axis padding: condition
        # bit 62, never decoded) must contribute NOTHING else, or phantom
        # nodes would inflate the reason histogram.
        is_pad = (st.cond_fail_bits & (jnp.int64(1) << 62)) != 0
        # count mode: the host can emit one reason STRING several times per
        # node (a duplicated stage pair, or several label predicates sharing
        # ERR_NODE_LABEL_PRESENCE_VIOLATED) — summing decoded stage firings
        # reproduces those multiplicities, which a bitmask OR cannot. Only
        # the cheap [S, N] stacks materialize here; the [S, N, bits] decode
        # is deferred to the caller's not-found cond branch (hoisting it
        # would run it on every step, bound or not — see the histogram
        # comment in make_step).
        fail_stack = jnp.stack([fail & ~is_pad for fail, _ in stages])
        bits_stack = jnp.stack([
            jnp.broadcast_to(bits, fail.shape) for fail, bits in stages])
        aca_counts = (fail_stack, bits_stack)
        # reason_bits stays zero in count mode: the scan step's consumer
        # reads aca_counts instead
    else:
        # short-circuit reason selection: first failing stage wins (padded
        # nodes fail at the cond stage, whose sentinel bit is never decoded)
        for fail, bits in reversed(stages):
            reason_bits = jnp.where(fail, bits, reason_bits)
    n_feasible = _ax_sum(jnp.sum(feasible), ax)

    # ---- score (weighted sum, generic_scheduler.go:631-639) ----
    (w_least, w_most, w_balanced, w_node_aff, w_taint, w_avoid, w_spread,
     w_interpod) = policy_weights(ps, config.most_requested)
    label_prio_on = ps is not None and ps.has_label_prio

    score = jnp.zeros_like(st.alloc_cpu)
    # explain lanes (ISSUE 13): each weighted component lands in `parts`
    # alongside its addition into score, in explain_part_names order. The
    # list stays empty when explain_k == 0 (static), so the disabled trace
    # is unchanged.
    explain = config.explain_k > 0
    parts: list = []

    def add(term):
        nonlocal score
        score = score + term
        if explain:
            parts.append(jnp.broadcast_to(term, score.shape))

    if w_least or w_most or w_balanced:
        total_cpu = x.nz_cpu + carry.nonzero_cpu
        total_mem = x.nz_mem + carry.nonzero_mem
    if w_least:
        # least_requested.go:41-52
        add(w_least * (
            (_ratio_score(total_cpu, st.alloc_cpu, False)
             + _ratio_score(total_mem, st.alloc_mem, False)) // 2))
    if w_most:
        # most_requested.go:44-55
        add(w_most * (
            (_ratio_score(total_cpu, st.alloc_cpu, True)
             + _ratio_score(total_mem, st.alloc_mem, True)) // 2))
    if w_balanced:
        add(w_balanced * _balanced_score(
            total_cpu, total_mem, st.alloc_cpu, st.alloc_mem))

    if w_node_aff:
        # NodeAffinityPriority: NormalizeReduce(10, False) over feasible nodes
        aff = st.affinity_count[x.aff_id]
        aff_max = _ax_max(jnp.max(jnp.where(feasible, aff, 0)), ax)
        aff_norm = jnp.where(
            aff_max > 0, MAX_PRIORITY * aff // jnp.maximum(aff_max, 1), 0)
        add(w_node_aff * aff_norm)

    if w_taint:
        # TaintTolerationPriority: NormalizeReduce(10, True) over feasible nodes
        intol = st.intolerable[x.tol_id]
        intol_max = _ax_max(jnp.max(jnp.where(feasible, intol, 0)), ax)
        taint_norm = jnp.where(
            intol_max > 0,
            MAX_PRIORITY - MAX_PRIORITY * intol // jnp.maximum(intol_max, 1),
            MAX_PRIORITY)
        add(w_taint * taint_norm)

    if w_avoid:
        add(st.avoid_score[x.avoid_id] * w_avoid)

    if label_prio_on:
        # NodeLabel/LabelPreference priorities: static pre-weighted rows
        add(st.label_prio)

    if ps is not None and ps.w_image:
        # ImageLocalityPriority (image_locality.go): static per
        # (pod-image-set, node) score row
        add(st.image_score[x.img_id] * ps.w_image)

    if ps is not None and ps.saa_weights:
        # ServiceAntiAffinity (selector_spreading.go:176-280): spread the
        # pods matching MY first service's selector across node groups
        # identified by the policy label. cnt counts such pods per node;
        # the reduce is over feasible nodes (the host maps over filtered
        # nodes only); unlabeled nodes score 0.
        # the f64 matmul is exact (counts are small integers, far below
        # 2^24); the normalize below is exact integer (DEVIATIONS.md #16)
        saa_cnt = (st.saa_rows[st.saa_sig[x.group_id]].astype(jnp.float64) @
                   carry.presence.astype(jnp.float64)).astype(jnp.int64)  # [N]
        saa_fcnt = jnp.where(feasible, saa_cnt, 0)
        saa_total = _ax_sum(jnp.sum(saa_fcnt), ax)
        # entries accumulate into ONE explain part (integer adds: regrouping
        # the per-entry additions into a single term is exact)
        saa_term = jnp.zeros_like(score)
        for e, w_saa in enumerate(ps.saa_weights):
            dom = st.saa_dom[e]
            labeled = dom > 0
            grp = _ax_sum(jax.ops.segment_sum(
                jnp.where(labeled, saa_fcnt, 0), dom,
                num_segments=config.n_saa_doms), ax).at[0].set(0)
            f_score = jnp.where(
                saa_total > 0,
                (MAX_PRIORITY * (saa_total - grp[dom]))
                // jnp.maximum(saa_total, 1),
                MAX_PRIORITY)
            saa_term = saa_term + jnp.where(labeled, f_score, 0) * w_saa
        add(saa_term)

    if config.has_services and w_spread:
        # SelectorSpreadPriority (selector_spreading.go:66-175): per-node count
        # of same-namespace pods matched by my services' selectors, then the
        # node/zone-blended normalize over feasible nodes
        # f64 matmul exact for small integer counts; normalize + zone blend
        # in exact integer arithmetic, one floor at the end — matching the
        # host's rational form of Go's nodeScore/3 + 2*zoneScore/3
        # (selector_spreading.go hardcodes zoneWeighting=2.0/3.0;
        # DEVIATIONS.md #16)
        cnt = (st.ss_rows[st.ss_sig[x.group_id]].astype(jnp.float64) @
               carry.presence.astype(jnp.float64)).astype(jnp.int64)  # [N]
        fcnt = jnp.where(feasible, cnt, 0)
        max_node = _ax_max(jnp.max(fcnt), ax)
        zdom = st.zone_dom
        zvalid = zdom > 0
        zcnt = _ax_sum(jax.ops.segment_sum(
            fcnt, zdom, num_segments=config.n_zone_doms), ax).at[0].set(0)
        have_zones = _ax_any(jnp.any(feasible & zvalid), ax)
        max_zone = jnp.max(zcnt)
        node_num = jnp.where(max_node > 0, max_node - cnt, 1)
        node_den = jnp.maximum(max_node, 1)
        zone_num = jnp.where(max_zone > 0, max_zone - zcnt[zdom], 1)
        zone_den = jnp.maximum(max_zone, 1)
        plain = (MAX_PRIORITY * node_num) // node_den
        blend = (MAX_PRIORITY
                 * (node_num * zone_den + 2 * zone_num * node_den)
                 ) // (3 * node_den * zone_den)
        add(jnp.where(have_zones & zvalid, blend, plain) * w_spread)

    if config.has_interpod and w_interpod:
        # InterPodAffinityPriority (interpod_affinity.go:118+): float64 counts
        # from (a) my preferred terms over existing pods, (b) existing pods'
        # preferred terms over me, (c) their required affinity × hard weight;
        # all contributions are integer-valued so summation order is exact
        p_w = st.pref_w[g]                                          # [Tp]
        pcount = st.term_match[st.pref_term[g]].astype(jnp.float64) @ presence_f  # [Tp, N]
        pdom = st.topo_dom[st.pref_key[g]]                          # [Tp, N]
        pdc_at = jnp.take_along_axis(
            _ax_sum(_seg_rows(pcount, pdom, config.n_topo_doms), ax),
            pdom, axis=1)
        counts = jnp.sum(p_w[:, None] * jnp.where(pdom > 0, pdc_at, 0.0), axis=0)

        wb = st.pref_w * st.term_match[st.pref_term, g]             # [G, Tp]
        wc = float(config.hard_weight) * (
            st.aff_valid & ~st.aff_empty
            & st.term_match[st.aff_term, g]).astype(jnp.float64)    # [G, Ta]
        key_oh_p = jax.nn.one_hot(st.pref_key, k_count, dtype=jnp.float64)
        key_oh_a = jax.nn.one_hot(st.aff_key, k_count, dtype=jnp.float64)
        wsum = (jnp.einsum("gtk,gt,gkd->kd", key_oh_p, wb, pd_f)
                + jnp.einsum("gtk,gt,gkd->kd", key_oh_a, wc, pd_f))  # [K, D]
        wsum_at = jnp.take_along_axis(wsum, st.topo_dom, axis=1)     # [K, N]
        counts = counts + jnp.sum(
            jnp.where(st.topo_dom > 0, wsum_at, 0.0), axis=0)

        # counts are integer-valued f64 sums (weights and hard_weight are
        # ints, well below 2^24: exact); the normalize is exact integer —
        # the numerator is nonnegative, so floor division equals Go's
        # toward-zero int() conversion (DEVIATIONS.md #16)
        counts_i = counts.astype(jnp.int64)
        big = jnp.int64(1) << 62
        maxc = jnp.maximum(
            _ax_max(jnp.max(jnp.where(feasible, counts_i, -big)), ax), 0)
        minc = jnp.minimum(
            _ax_min(jnp.min(jnp.where(feasible, counts_i, big)), ax), 0)
        rng = maxc - minc
        ip = jnp.where(rng > 0,
                       (MAX_PRIORITY * (counts_i - minc)) // jnp.maximum(rng, 1),
                       0)
        add(ip * w_interpod)

    return feasible, reason_bits, score, n_feasible, aca_counts, parts


def _select(feasible, score, n_feasible, rr, axis=None):
    """selectHost (generic_scheduler.go:183-198): stable-desc + round-robin
    among max-score ties; rr is consumed only when >1 node passed the filter
    (with one feasible node scheduleOne returns it directly, :176-180).

    With `axis` set (the shard_map route) each shard holds a contiguous
    block of the node axis and the same selection runs globally: the tie
    threshold is a pmax, the tie COUNT a psum, and each shard ranks its
    ties at a global offset (the all-gathered tie counts of earlier
    shards) — so `rank == k` fires on exactly one node cluster-wide, at
    the same position the single-device cumsum would pick. The winning
    shard publishes its global index through a pmin (losers contribute
    int32-max), making `choice` replicated and bit-identical to the
    unsharded route, round-robin tie-break included."""
    masked = jnp.where(feasible, score, jnp.int64(-1))
    max_score = _ax_max(jnp.max(masked), axis)
    tie = feasible & (masked == max_score)
    local_ties = jnp.sum(tie)
    ties = jnp.maximum(_ax_sum(local_ties, axis), 1)
    k = jnp.where(n_feasible > 1, rr % ties, 0)
    rank = jnp.cumsum(tie.astype(jnp.int64)) - 1
    if axis is not None:
        per_shard = jax.lax.all_gather(local_ties, axis)        # [S]
        me = jax.lax.axis_index(axis)
        rank = rank + jnp.sum(jnp.where(
            jnp.arange(per_shard.shape[0]) < me, per_shard, 0))
    pick = tie & (rank == k)
    choice = jnp.argmax(pick).astype(jnp.int32)
    if axis is not None:
        base = (jax.lax.axis_index(axis) * feasible.shape[0]).astype(jnp.int32)
        choice = _ax_min(jnp.where(jnp.any(pick), base + choice,
                                   jnp.iinfo(jnp.int32).max), axis)
    found = n_feasible > 0
    return jnp.where(found, choice, -1), found


def _reason_histogram(reason_bits, num_bits: int):
    bit_ids = jnp.arange(num_bits, dtype=jnp.int64)
    present = (reason_bits[:, None] >> bit_ids[None, :]) & 1
    return jnp.sum(present, axis=0).astype(jnp.int32)


def _aca_histogram(aca_counts, num_bits: int):
    """Count-mode histogram from _evaluate's (fail_stack, bits_stack):
    per-reason-string occurrence sums over ALL failing stages (pad-masked
    already), reproducing the host's duplicate-string multiplicities under
    alwaysCheckAllPredicates."""
    fail_stack, bits_stack = aca_counts
    bit_ids = jnp.arange(num_bits, dtype=jnp.int64)
    decoded = ((bits_stack[..., None] >> bit_ids) & 1) != 0   # [S, N, B]
    return jnp.sum(fail_stack[..., None] & decoded,
                   axis=(0, 1)).astype(jnp.int32)


def make_step(config: EngineConfig):
    """The exact sequential scan step: (carry, PodX) -> (carry', (choice, counts))."""

    def step(state: tuple, x: PodX):
        carry, st = state
        feasible, reason_bits, score, n_feasible, aca_counts, parts = \
            _evaluate(config, carry, st, x)
        choice, found = _select(feasible, score, n_feasible, carry.rr,
                                config.shard_axis)
        rr_next = carry.rr + jnp.where(n_feasible > 1, 1, 0)

        if config.shard_axis is None:
            bind = found
            idx = jnp.maximum(choice, 0)
        else:
            # sharded route: `choice` is a GLOBAL node index (replicated by
            # _select's pmin); only the owner shard scatters into its
            # node-sharded columns. Replicated fields (presence_dom, rr)
            # update identically on every shard further down.
            n_local = feasible.shape[0]
            base = (jax.lax.axis_index(config.shard_axis)
                    * n_local).astype(jnp.int32)
            local = choice - base
            bind = found & (local >= 0) & (local < n_local)
            idx = jnp.clip(local, 0, n_local - 1)
        gate = bind.astype(jnp.int64)
        gate32 = bind.astype(jnp.int32)
        if (config.has_ports or config.has_services or config.has_interpod
                or config.has_disk_conflict):
            presence = carry.presence.at[x.group_id, idx].add(gate32)
        else:
            presence = carry.presence
        if config.has_maxpd:
            row = jnp.where(bind,
                            carry.used_vols[idx] | st.vol_mask[x.group_id],
                            carry.used_vols[idx])
            used_vols = carry.used_vols.at[idx].set(row)
        else:
            used_vols = carry.used_vols
        if config.has_interpod:
            k_count = st.topo_dom.shape[0]
            dom_at = st.topo_dom[:, idx]                    # [K]
            if config.shard_axis is not None:
                # presence_dom is replicated: every shard applies the same
                # update, so the owner broadcasts its topo_dom column (the
                # psum has one nonzero contributor)
                dom_at = jax.lax.psum(jnp.where(bind, dom_at, 0),
                                      config.shard_axis)
            presence_dom = carry.presence_dom.at[
                x.group_id, jnp.arange(k_count), dom_at].add(
                    found.astype(jnp.int32))
        else:
            presence_dom = carry.presence_dom
        if config.policy is not None and config.policy.sa_enabled:
            # the first ASSIGNED pod matching a selector defines its pin (the
            # plugin pod lister is the scheduler cache, factory.go:166), and
            # assigned order == bind order here — so the first matching BIND
            # locks each still-unlocked sig to the chosen node
            match_f = st.saa_rows[:, x.group_id] & found      # [F]
            sa_lock = jnp.where((carry.sa_lock == -1) & match_f,
                                idx.astype(jnp.int32), carry.sa_lock)
        else:
            sa_lock = carry.sa_lock
        new_carry = Carry(
            used_cpu=carry.used_cpu.at[idx].add(gate * x.req_cpu),
            used_mem=carry.used_mem.at[idx].add(gate * x.req_mem),
            used_gpu=carry.used_gpu.at[idx].add(gate * x.req_gpu),
            used_eph=carry.used_eph.at[idx].add(gate * x.req_eph),
            used_scalar=carry.used_scalar.at[idx].add(gate * x.req_scalar),
            nonzero_cpu=carry.nonzero_cpu.at[idx].add(gate * x.nz_cpu),
            nonzero_mem=carry.nonzero_mem.at[idx].add(gate * x.nz_mem),
            pod_count=carry.pod_count.at[idx].add(gate),
            presence=presence, presence_dom=presence_dom,
            used_vols=used_vols, sa_lock=sa_lock,
            rr=rr_next)

        # the histogram lambdas must stay INSIDE the cond branch: hoisting
        # them out captures the decode as a cond operand and XLA then
        # computes the [N x bits] (or [S x N x bits]) sum every step, bound
        # or not (measured ~25% on the 20k x 2000 CPU scan)
        counts = jax.lax.cond(
            found,
            lambda: jnp.zeros(config.num_reason_bits, dtype=jnp.int32),
            (lambda: _aca_histogram(aca_counts, config.num_reason_bits))
            if aca_counts is not None else
            (lambda: _reason_histogram(reason_bits, config.num_reason_bits)))
        if config.shard_axis is not None:
            # per-shard histograms merge OUTSIDE the cond (found is
            # replicated, so every shard takes the same branch and the
            # psum stays uniform; a bound pod psums zeros)
            counts = jax.lax.psum(counts, config.shard_axis)
        # advanced: selectHost consumed the rr counter for this pod — lets the
        # preemption hybrid (jaxe/preempt.py) resume rr mid-batch on re-dispatch
        if config.explain_k > 0:
            # explain lanes: top-k candidates by final score with per-part
            # contributions; infeasible nodes masked far below any real
            # score so padding rows decode as EXPLAIN_SENTINEL
            k = min(config.explain_k, score.shape[0])
            masked_sc = jnp.where(feasible, score,
                                  jnp.asarray(2 * EXPLAIN_SENTINEL,
                                              dtype=score.dtype))
            top_scores, top_idx = jax.lax.top_k(masked_sc, k)
            if parts:
                parts_mat = jnp.stack(parts)              # [C, N]
                top_parts = parts_mat[:, top_idx].T       # [k, C]
            else:
                top_parts = jnp.zeros((k, 0), dtype=score.dtype)
            return (new_carry, st), (choice, counts, n_feasible > 1,
                                     top_idx, top_scores, top_parts)
        return (new_carry, st), (choice, counts, n_feasible > 1)

    return step


def _schedule_scan_impl(config: EngineConfig, carry: Carry, statics: Statics,
                        xs: PodX):
    step = make_step(config)
    if config.explain_k > 0:
        (final_carry, _), (choices, counts, advanced, top_idx, top_scores,
                           top_parts) = jax.lax.scan(
            step, (carry, statics), xs, unroll=config.scan_unroll)
        return (final_carry, choices, counts, advanced,
                (top_idx, top_scores, top_parts))
    (final_carry, _), (choices, counts, advanced) = jax.lax.scan(
        step, (carry, statics), xs, unroll=config.scan_unroll)
    return final_carry, choices, counts, advanced


# Exact sequential mode: scan the fused step over the pod axis.
schedule_scan = partial(jax.jit, static_argnames=("config",))(_schedule_scan_impl)


# Chunked-driver variant: the carry buffers are donated, so a host loop
# feeding pod chunks (carry, ch = scan(carry, chunk)) updates the [N]-sized
# state in place instead of churning fresh HBM allocations per chunk
# (SURVEY.md §7 hard part 6 — 1M-pod batches).
schedule_scan_donated = jax.jit(_schedule_scan_impl,
                                static_argnames=("config",),
                                donate_argnums=(1,))


# --------------------------------------------------------------------------
# Node-axis sharded route (ISSUE 16): the SAME fused step, wrapped in
# shard_map over a "node" mesh axis. Each shard owns a contiguous block of
# the (shard-even padded) node axis; per-step reductions and host selection
# merge through the collectives threaded above, so placements are
# bit-identical to the single-device scan — the backend's verify-then-trust
# seam (_SHARD_AUTO) replays the first batch per signature to prove it.

def node_partition_specs(axis: str = "node"):
    """(Statics, Carry, PodX) PartitionSpec trees for the node-sharded route,
    derived from the axis registries: "node" axes map to the mesh axis,
    everything else (group tables, presence_dom, pod columns) replicates."""
    from jax.sharding import PartitionSpec as P

    def tree(cls, registry):
        return cls(*(P(*(axis if a == "node" else None
                         for a in registry[f])) for f in cls._fields))

    # PodX leaves carry a leading pod axis ahead of their registry axes;
    # every pod column is replicated, so P() covers them regardless of rank
    return (tree(Statics, STATICS_AXES), tree(Carry, CARRY_AXES),
            PodX(*(P() for _ in PodX._fields)))


def shard_route_eligible(config: EngineConfig):
    """(ok, reason) — static feature gates the sharded route cannot serve.
    ServiceAffinity reads node columns by a GLOBAL locked index (sa_val
    gathers cross shards) and explain lanes emit a per-node top-k that has
    no associative merge wired yet; both fall back, classified."""
    ps = config.policy
    if ps is not None and (ps.sa_enabled or ps.sa_slots):
        return False, "service_affinity"
    if config.explain_k > 0:
        return False, "explain_lanes"
    return True, ""


_SHARDED_SCAN_PROGRAMS: dict = {}


def sharded_scan_fn(config: EngineConfig, mesh, donate: bool = False):
    """The jitted shard_map program for the node-sharded fused scan,
    cached per (config, mesh, donate). `config.shard_axis` must name a
    mesh axis; inputs must be shard-even padded (sharding.pad_node_axis)
    and placed/placeable per `node_partition_specs`. Signature matches
    schedule_scan minus the leading config: fn(carry, statics, xs) ->
    (final_carry, choices, counts, advanced)."""
    if config.shard_axis is None:
        raise ValueError("sharded_scan_fn requires config.shard_axis")
    ok, why = shard_route_eligible(config)
    if not ok:
        raise ValueError(f"sharded route cannot serve this config: {why}")
    key = (config, mesh, donate)
    fn = _SHARDED_SCAN_PROGRAMS.get(key)
    if fn is None:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        st_specs, ca_specs, xs_specs = node_partition_specs(config.shard_axis)
        sm = shard_map(
            partial(_schedule_scan_impl, config), mesh=mesh,
            in_specs=(ca_specs, st_specs, xs_specs),
            # final carry keeps its node-sharded layout; choices/counts/
            # advanced are replicated by construction (pmin/psum merges)
            out_specs=(ca_specs, P(), P(), P()),
            check_rep=False)
        fn = jax.jit(sm, donate_argnums=(0,) if donate else ())
        _SHARDED_SCAN_PROGRAMS[key] = fn
    return fn


def schedule_scan_chunked(config: EngineConfig, carry: Carry, statics: Statics,
                          xs_host: PodX, chunk: int, progress=None,
                          scan_donated=None, put=None):
    """Exact sequential scan over a pod batch too large for one dispatch,
    with double-buffered transfers (SURVEY.md §7 hard part 6).

    `scan_donated` swaps the per-chunk program — the sharded route passes
    its shard_map fn (signature (carry, statics, xs), config already
    bound) — and `put` overrides the chunk upload (e.g. a device_put onto
    the mesh's replicated sharding). Defaults reproduce the single-device
    donated scan exactly.

    `xs_host` holds host-numpy pod columns; the full [P]-row PodX never lands
    in HBM at once. Per iteration the host loop (a) dispatches chunk t on the
    donated carry, (b) immediately enqueues the async upload of chunk t+1's
    columns, and (c) only then fetches chunk t-1's choices — so the one host
    sync per iteration overlaps with chunk t's device compute, and the upload
    rides the same overlap window instead of serializing with dispatch.
    Placements are bit-identical to the unchunked scan (the carry crosses
    chunk boundaries untouched; padding rows are infeasible no-ops).

    Returns (final_carry, choices[P], counts[P, bits], advanced[P]) with the
    result arrays as host numpy."""
    p = int(xs_host.req_cpu.shape[0])
    pad = (-p) % chunk
    if pad:
        xs_host = pad_infeasible_rows(xs_host, pad)
    num_chunks = (p + pad) // chunk

    def upload(ci):
        sl = slice(ci * chunk, (ci + 1) * chunk)
        rows = PodX(*(a[sl] for a in xs_host))
        return jax.device_put(rows) if put is None else put(rows)

    choice_parts, count_parts, adv_parts = [], [], []
    pending = None
    nxt = upload(0)
    for ci in range(num_chunks):
        xs_c = nxt
        if scan_donated is None:
            carry, ch, cnt, adv = schedule_scan_donated(config, carry,
                                                        statics, xs_c)
        else:
            carry, ch, cnt, adv = scan_donated(carry, statics, xs_c)
        if ci + 1 < num_chunks:
            nxt = upload(ci + 1)
        count_parts.append(cnt)
        adv_parts.append(adv)
        if pending is not None:
            choice_parts.append(np.asarray(pending))  # forces chunk ci-1
            if progress is not None:
                progress(ci, num_chunks, ci * chunk)
        pending = ch
    choice_parts.append(np.asarray(pending))
    if progress is not None:
        progress(num_chunks, num_chunks, p)
    choices = np.concatenate(choice_parts)[:p]
    counts = np.concatenate([np.asarray(c) for c in count_parts])[:p]
    advanced = np.concatenate([np.asarray(a) for a in adv_parts])[:p]
    return carry, choices, counts, advanced


def pad_infeasible_rows(xs, pad: int):
    """Append `pad` PodX rows that fail PodFitsResources on every node
    (req_cpu = 2^61 exceeds any allocatable): no carry mutation, no rr
    advance (n_feasible == 0 skips both), so shape padding is semantics-free.
    Host-numpy in, host-numpy out."""
    if pad <= 0:
        return xs

    def pad_field(name, arr):
        fill = (np.int64(1) << 61) if name == "req_cpu" else 0
        widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
        return np.pad(arr, widths, constant_values=fill)

    return PodX(*(pad_field(name, arr)
                  for name, arr in zip(PodX._fields, xs)))


# --------------------------------------------------------------------------
# Streaming delta commit (ISSUE 7): O(delta) scatter updates into the
# device-resident carry instead of a full carry_init + device_put restage.
#
# The host (jaxe.delta.IncrementalCluster) stays the source of truth: after
# folding a cycle's watch events it gathers the AUTHORITATIVE post-event
# values of every touched node row / presence cell, and the donated kernel
# scatter-`set`s them into the resident carry. Set-from-authoritative (not
# add-a-delta) makes the commit idempotent and self-healing — the device can
# never drift from the host columns on the fields it syncs.
#
# Per-batch lanes are re-armed here too: sa_lock resets to -1 and rr to 0,
# exactly what carry_init_host hands a fresh restage, so a stream cycle and
# a restage cycle run the scan from byte-identical carries. presence_dom and
# used_vols have no scatter path (their host mirrors live in the group
# tables, which rebuild on any structural event) — the stream layer
# (tpusim.stream) restages whenever a config with has_interpod/has_maxpd
# sees presence/volume churn, so their stale values are never read.
#
# Shapes are the caller's retrace contract: tpusim.stream pads node_idx /
# presence cells to pow2 buckets, so a warm steady-state churn rate reuses
# one compiled commit program (the zero-retrace warm cycle).
# --------------------------------------------------------------------------


class DeltaRows(NamedTuple):
    """Authoritative post-event dynamic values for `node_idx` rows, gathered
    from the host columns (DynamicInit dtypes: int64 throughout)."""

    used_cpu: jnp.ndarray      # [U]
    used_mem: jnp.ndarray      # [U]
    used_gpu: jnp.ndarray      # [U]
    used_eph: jnp.ndarray      # [U]
    used_scalar: jnp.ndarray   # [U, S]
    nonzero_cpu: jnp.ndarray   # [U]
    nonzero_mem: jnp.ndarray   # [U]
    pod_count: jnp.ndarray     # [U]


def _apply_delta_impl(carry: Carry, node_idx, rows: DeltaRows,
                      pres_gid, pres_nid, pres_val, sa_lock_init) -> Carry:
    # duplicate indices (bucket padding repeats a real row) are safe under
    # `set` scatter semantics only because every duplicate carries the same
    # authoritative value — any winner writes the same bytes
    return carry._replace(
        used_cpu=carry.used_cpu.at[node_idx].set(rows.used_cpu),
        used_mem=carry.used_mem.at[node_idx].set(rows.used_mem),
        used_gpu=carry.used_gpu.at[node_idx].set(rows.used_gpu),
        used_eph=carry.used_eph.at[node_idx].set(rows.used_eph),
        used_scalar=carry.used_scalar.at[node_idx].set(rows.used_scalar),
        nonzero_cpu=carry.nonzero_cpu.at[node_idx].set(rows.nonzero_cpu),
        nonzero_mem=carry.nonzero_mem.at[node_idx].set(rows.nonzero_mem),
        pod_count=carry.pod_count.at[node_idx].set(rows.pod_count),
        presence=carry.presence.at[pres_gid, pres_nid].set(pres_val),
        sa_lock=jnp.asarray(sa_lock_init, carry.sa_lock.dtype),
        rr=jnp.zeros_like(carry.rr))


# Donating the carry makes the commit a true in-place HBM update: the
# resident buffers are patched, not reallocated, mirroring
# schedule_scan_donated's chunk-loop contract above.
#
# sa_lock_init re-arms the ServiceAffinity segment-lock lanes exactly the way
# carry_init does on a restage: providers pass the all-unlocked fill(-1),
# compiled policies with ServiceAffinity pass policyc.sa_lock_init_rows
# recomputed from the live snapshot, so the resident plan sees the same
# first-assigned-pod pins a fresh restage would (ISSUE 9).
apply_delta_donated = jax.jit(_apply_delta_impl, donate_argnums=(0,))


def _overlay_restore_impl(carry: Carry, node_idx, rows: DeltaRows,
                          pres_gid, pres_nid, pres_val, sa_lock_save,
                          rr_save) -> Carry:
    # The rollback half of a what-if overlay (tpusim.stream overlay_query):
    # the same authoritative scatter as _apply_delta_impl over the nodes the
    # overlay scan BOUND, but the per-batch lanes restore the SAVED pre-mark
    # arrays instead of re-arming — sa_lock returns to the segment locks the
    # last real cycle left and rr to its pre-overlay cursor, so the
    # post-rollback carry is byte-identical to the pre-mark carry (modulo
    # churn the overlay early-committed, which the restored journal makes
    # the next real commit's idempotent no-op).
    return carry._replace(
        used_cpu=carry.used_cpu.at[node_idx].set(rows.used_cpu),
        used_mem=carry.used_mem.at[node_idx].set(rows.used_mem),
        used_gpu=carry.used_gpu.at[node_idx].set(rows.used_gpu),
        used_eph=carry.used_eph.at[node_idx].set(rows.used_eph),
        used_scalar=carry.used_scalar.at[node_idx].set(rows.used_scalar),
        nonzero_cpu=carry.nonzero_cpu.at[node_idx].set(rows.nonzero_cpu),
        nonzero_mem=carry.nonzero_mem.at[node_idx].set(rows.nonzero_mem),
        pod_count=carry.pod_count.at[node_idx].set(rows.pod_count),
        presence=carry.presence.at[pres_gid, pres_nid].set(pres_val),
        sa_lock=jnp.asarray(sa_lock_save, carry.sa_lock.dtype),
        rr=jnp.asarray(rr_save, carry.rr.dtype))


# Donation contract matches apply_delta_donated: the overlay scan's final
# carry is patched in place back to host truth. Shapes ride the same pow2
# bucketing, so warm overlay traffic reuses one compiled restore program.
overlay_restore_donated = jax.jit(_overlay_restore_impl, donate_argnums=(0,))


class StaticsDelta(NamedTuple):
    """Authoritative post-churn statics columns for `node_idx`, one column
    slice per table whose cells depend on node labels/taints. The leading
    (signature/policy-row) dims match the resident tables; the trailing dim
    is the padded churn-node bucket U."""

    selector_ok: jnp.ndarray       # [Ksel, U] bool
    taint_ok: jnp.ndarray          # [Ktol, U] bool
    taint_ok_noexec: jnp.ndarray   # [Ktol, U] bool
    intolerable: jnp.ndarray       # [Ktol, U] int32
    affinity_count: jnp.ndarray    # [Kaff, U] int64
    avoid_score: jnp.ndarray       # [Kav, U] int64
    host_ok: jnp.ndarray           # [Khost, U] bool
    label_ok: jnp.ndarray          # [L, U] bool
    label_prio: jnp.ndarray        # [U] int64
    image_score: jnp.ndarray       # [Si, U] int64
    saa_dom: jnp.ndarray           # [E, U] int32
    sa_val: jnp.ndarray            # [La, U] int32


def _apply_statics_delta_impl(statics: Statics, node_idx,
                              d: StaticsDelta) -> Statics:
    # Label/taint churn only moves per-(signature, node) and per-(policy-row,
    # node) cells; every other statics table is either node-structural
    # (alloc_*, cond_fail_bits — those churn classes restage via node_set /
    # scalar_set) or group-derived (rebuilt behind groups_dirty).
    return statics._replace(
        selector_ok=statics.selector_ok.at[:, node_idx].set(d.selector_ok),
        taint_ok=statics.taint_ok.at[:, node_idx].set(d.taint_ok),
        taint_ok_noexec=statics.taint_ok_noexec.at[:, node_idx].set(
            d.taint_ok_noexec),
        intolerable=statics.intolerable.at[:, node_idx].set(d.intolerable),
        affinity_count=statics.affinity_count.at[:, node_idx].set(
            d.affinity_count),
        avoid_score=statics.avoid_score.at[:, node_idx].set(d.avoid_score),
        host_ok=statics.host_ok.at[:, node_idx].set(d.host_ok),
        label_ok=statics.label_ok.at[:, node_idx].set(d.label_ok),
        label_prio=statics.label_prio.at[node_idx].set(d.label_prio),
        image_score=statics.image_score.at[:, node_idx].set(d.image_score),
        saa_dom=statics.saa_dom.at[:, node_idx].set(d.saa_dom),
        sa_val=statics.sa_val.at[:, node_idx].set(d.sa_val))


# Same donation contract as apply_delta_donated: the resident statics
# buffers are patched in HBM, not reallocated. XLA refcounts device buffers,
# so donating while a previously dispatched scan still reads the old statics
# is safe — the old buffers live until that computation retires.
apply_statics_delta_donated = jax.jit(_apply_statics_delta_impl,
                                      donate_argnums=(0,))


# --------------------------------------------------------------------------
# Device-side preemption victim selection — the arithmetic-reprieve class.
#
# Reference mapping (all in core/generic_scheduler.go):
#   selectVictimsOnNode (:583-665)    -> masked scan over priority-sorted
#                                        victim slots, one candidate node per
#                                        lane; the reprieve re-check reduces
#                                        to PodFitsResources' integer
#                                        arithmetic in this class (the host
#                                        mirror is GenericScheduler.
#                                        _make_arithmetic_reprieve)
#   pickOneNodeForPreemption (:739-831) -> five tie-break criteria as masked
#                                        reductions over the lane axis
#
# The host side (jaxe/preempt.py) computes the candidate lanes (static
# predicate mask + stripped-node resource fit) and the priority-sorted victim
# slots from its columnar pod table; the kernel runs the cumulative reprieve
# and the pick. Lane and slot axes are pow2-bucketed by the caller, bounding
# recompiles to O(log C · log V) variants.

PRIO_SUM_OFFSET = 1 << 31  # util.MAX_INT32 + 1 (pickOneNode criterion 4)


def _preempt_select_impl(zero_req: bool, lane_valid, node_idx,
                         alloc_cpu, alloc_mem, alloc_gpu, alloc_eph, allowed,
                         n_base, base_cpu, base_mem, base_gpu, base_eph,
                         v_prio, v_cpu, v_mem, v_gpu, v_eph, v_valid):
    """One failed pod against C candidate lanes × V victim slots.

    Per-lane inputs ([C], int64 unless noted): node_idx = global node index
    (insertion-order tie-breaks), alloc_* / allowed = node allocatables,
    n_base = resident pods AFTER stripping every lower-priority pod,
    base_* = stripped usage PLUS the incoming pod's request (the
    _make_arithmetic_reprieve state seed). Slot inputs ([C, V]): the lane's
    lower-priority pods sorted priority-desc (stable by NodeInfo.pods
    position); v_valid masks real slots. zero_req (static): the incoming
    pod requests nothing, so only the pod-count check applies
    (predicates.go:706-776 early-out).

    Returns (winner, empty_winner, victim[C, V] bool, num[C]):
    winner = node_idx picked by criteria 2-5 over lanes with victims
    (num_violating is uniformly 0 in this class — no PDBs), empty_winner =
    first-in-order lane with zero victims (criterion 1; its existence means
    the node fit without preempting anyone, i.e. a device/host scan
    disagreement the caller must resolve on the host). Both are the big
    sentinel when no lane qualifies."""

    def step(state, slot):
        n, cpu, mem, gpu, eph = state
        vp, vc, vm, vg, ve, valid = slot
        # state holds the incoming pod's request already; +2 = +victim +pod
        fits = n + 2 <= allowed
        if not zero_req:
            fits = fits & ((alloc_cpu >= cpu + vc)
                           & (alloc_mem >= mem + vm)
                           & (alloc_gpu >= gpu + vg)
                           & (alloc_eph >= eph + ve))
        reprieved = fits & valid
        state = (n + reprieved.astype(jnp.int64),
                 cpu + jnp.where(reprieved, vc, 0),
                 mem + jnp.where(reprieved, vm, 0),
                 gpu + jnp.where(reprieved, vg, 0),
                 eph + jnp.where(reprieved, ve, 0))
        return state, valid & ~fits

    state0 = (n_base, base_cpu, base_mem, base_gpu, base_eph)
    xs = (v_prio.T, v_cpu.T, v_mem.T, v_gpu.T, v_eph.T, v_valid.T)
    _, victim_cols = jax.lax.scan(step, state0, xs)
    victim = victim_cols.T  # [C, V]

    big = jnp.int64(1) << 62
    num = jnp.sum(victim, axis=1)
    empty = lane_valid & (num == 0)
    empty_winner = jnp.min(jnp.where(empty, node_idx, big))

    # criterion 3: lowest highest-victim priority — slots are priority-desc,
    # so the first masked slot per lane carries the lane's highest
    first = jnp.argmax(victim, axis=1)
    highest = jnp.take_along_axis(v_prio, first[:, None], axis=1)[:, 0]
    # criterion 4: smallest sum(priority + MAX_INT32 + 1) over victims
    psum = jnp.sum(jnp.where(victim, v_prio + PRIO_SUM_OFFSET, 0), axis=1)

    # staged min-filters (criteria 2 is a no-op: num_violating uniformly 0);
    # a single surviving lane passes every later filter unchanged, matching
    # the host's len(names) > 1 guards
    sel = lane_valid & (num > 0)
    sel = sel & (highest == jnp.min(jnp.where(sel, highest, big)))
    sel = sel & (psum == jnp.min(jnp.where(sel, psum, big)))
    sel = sel & (num == jnp.min(jnp.where(sel, num, big)))
    winner = jnp.min(jnp.where(sel, node_idx, big))  # criterion 5: first
    return winner, empty_winner, victim, num


preempt_select = partial(jax.jit, static_argnums=(0,))(_preempt_select_impl)


# --------------------------------------------------------------------------
# Cluster analytics reduction (ISSUE 14).
#
# A post-scan fold over the resident twin's per-node columns: the ten
# allocatable/requested arrays below are plain field references into a
# (Statics, Carry) pair, so building AnalyticsIn costs a tuple pack and the
# reduction is one extra O(N) dispatch per cycle that never touches the
# scheduling scan itself (placement hashes stay pinned by construction).
#
# The kernel is integer-only: sums, maxes, counts, and encoded top-k keys.
# Ratios (utilization, fragmentation) are derived at host decode time in
# tpusim/obs/analytics.py, whose numpy mirror recomputes these same integer
# ops so device-vs-host comparison is bit-exact, not within-epsilon.

ANALYTICS_RESOURCES = ("cpu", "memory", "gpu", "ephemeral", "pods")
ANALYTICS_UTIL_SCALE = 1_000_000  # utilization in ppm (integer floor-div)
# _ANALYTICS_TIE_BITS (the key layout) now lives in jaxe/packing.py and is
# re-exported above for the host mirror in obs/analytics.py


class AnalyticsIn(NamedTuple):
    """Per-node columns the analytics reduction folds ([N] each).

    Allocatables come from Statics, requested totals from the scan's final
    Carry; `analytics_in` builds one by reference (no copies, no tracing of
    the full trees — serve slices exactly these ten fields per entry)."""
    alloc_cpu: jnp.ndarray
    alloc_mem: jnp.ndarray
    alloc_gpu: jnp.ndarray
    alloc_eph: jnp.ndarray
    allowed_pods: jnp.ndarray
    used_cpu: jnp.ndarray
    used_mem: jnp.ndarray
    used_gpu: jnp.ndarray
    used_eph: jnp.ndarray
    pod_count: jnp.ndarray


class AnalyticsStats(NamedTuple):
    """Integer aggregates, resource axis ordered as ANALYTICS_RESOURCES.

    hot_keys / cold_keys encode `score * 2^32 + (2^32 - 1 - node_index)`
    (score = dominant cpu/mem utilization in ppm, clipped to [0, 1e6]);
    the index term makes every key unique, so lax.top_k and a host-side
    descending sort agree exactly. Nodes outside n_valid carry key -1 and
    are dropped at decode."""
    alloc: jnp.ndarray           # [R] int64 — allocatable totals
    used: jnp.ndarray            # [R] int64 — requested totals
    free_sum: jnp.ndarray        # [R] int64 — sum of per-node free (>= 0)
    free_max: jnp.ndarray        # [R] int64 — largest single free slot
    headroom_nodes: jnp.ndarray  # [R] int64 — nodes with free > 0
    feasible_nodes: jnp.ndarray  # int64 — free cpu AND mem AND pod slots
    valid_nodes: jnp.ndarray     # int64 — nodes inside n_valid
    hot_keys: jnp.ndarray        # [k] int64 — hottest-first encoded keys
    cold_keys: jnp.ndarray       # [k] int64 — coldest-first encoded keys


def analytics_in(statics, carry) -> AnalyticsIn:
    """The ten-column analytics view of a (Statics, Carry) pair."""
    return AnalyticsIn(
        alloc_cpu=statics.alloc_cpu, alloc_mem=statics.alloc_mem,
        alloc_gpu=statics.alloc_gpu, alloc_eph=statics.alloc_eph,
        allowed_pods=statics.allowed_pods,
        used_cpu=carry.used_cpu, used_mem=carry.used_mem,
        used_gpu=carry.used_gpu, used_eph=carry.used_eph,
        pod_count=carry.pod_count)


def _merged_top_k(keys, k: int, axis):
    """Descending top-k over (possibly node-sharded) packed keys. Sharded,
    each shard takes its local top-k and an all_gather + re-top-k merges —
    associative and exact because keys are unique (the index tiebreak), so
    any global top-k key is necessarily within its own shard's top-k."""
    if axis is None:
        vals, _ = jax.lax.top_k(keys, k)
        return vals
    local, _ = jax.lax.top_k(keys, min(k, keys.shape[0]))
    gathered = jax.lax.all_gather(local, axis).reshape(-1)
    vals, _ = jax.lax.top_k(gathered, k)
    return vals


def _analytics_reduce_impl(inp: AnalyticsIn, n_valid, *, k: int, axis=None):
    n = inp.alloc_cpu.shape[0]
    if axis is None:
        gidx = jnp.arange(n, dtype=jnp.int64)
    else:
        # inside shard_map `n` is the local block; keys carry GLOBAL node
        # indices so the merged top-k decodes identically to single-device
        gidx = (jax.lax.axis_index(axis).astype(jnp.int64) * n
                + jnp.arange(n, dtype=jnp.int64))
    mask = gidx < n_valid
    alloc = jnp.stack([inp.alloc_cpu.astype(jnp.int64),
                       inp.alloc_mem.astype(jnp.int64),
                       inp.alloc_gpu.astype(jnp.int64),
                       inp.alloc_eph.astype(jnp.int64),
                       inp.allowed_pods.astype(jnp.int64)])
    used = jnp.stack([inp.used_cpu.astype(jnp.int64),
                      inp.used_mem.astype(jnp.int64),
                      inp.used_gpu.astype(jnp.int64),
                      inp.used_eph.astype(jnp.int64),
                      inp.pod_count.astype(jnp.int64)])
    alloc = jnp.where(mask[None, :], alloc, 0)  # [R, N]
    used = jnp.where(mask[None, :], used, 0)
    free = jnp.maximum(alloc - used, 0)

    # dominant-share hotness in ppm; padded/invalid nodes encode key -1
    util = jnp.where(alloc[:2] > 0,
                     (used[:2] * ANALYTICS_UTIL_SCALE)
                     // jnp.maximum(alloc[:2], 1), 0)
    score = jnp.clip(jnp.maximum(util[0], util[1]),
                     0, ANALYTICS_UTIL_SCALE)
    hot = encode_topk_keys(score, gidx, mask)
    cold = encode_topk_keys(ANALYTICS_UTIL_SCALE - score, gidx, mask)
    hot_keys = _merged_top_k(hot, k, axis)
    cold_keys = _merged_top_k(cold, k, axis)

    return AnalyticsStats(
        alloc=_ax_sum(alloc.sum(axis=1), axis),
        used=_ax_sum(used.sum(axis=1), axis),
        free_sum=_ax_sum(free.sum(axis=1), axis),
        free_max=_ax_max(free.max(axis=1), axis),
        headroom_nodes=_ax_sum(
            (free > 0).sum(axis=1).astype(jnp.int64), axis),
        feasible_nodes=_ax_sum(((free[0] > 0) & (free[1] > 0)
                                & (free[4] > 0)).sum().astype(jnp.int64),
                               axis),
        valid_nodes=_ax_sum(mask.sum().astype(jnp.int64), axis),
        hot_keys=hot_keys,
        cold_keys=cold_keys)


analytics_reduce = partial(jax.jit, static_argnames=("k",))(
    _analytics_reduce_impl)


_ANALYTICS_SHARDED_PROGRAMS: dict = {}


def analytics_reduce_sharded(mesh, inp: AnalyticsIn, n_valid, *, k: int,
                             axis: str = "node"):
    """Two-level analytics reduction over a node-sharded AnalyticsIn: each
    shard folds its block (sums/maxes/counts + a local top-k of packed keys
    carrying GLOBAL node indices), then psum/pmax/all_gather-merge — the
    result is bit-identical to `analytics_reduce` on the unsharded columns,
    so obs/analytics.py's host mirror verifies it unchanged."""
    key = (mesh, k, axis)
    fn = _ANALYTICS_SHARDED_PROGRAMS.get(key)
    if fn is None:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        sm = shard_map(
            partial(_analytics_reduce_impl, k=k, axis=axis), mesh=mesh,
            in_specs=(AnalyticsIn(*(P(axis) for _ in AnalyticsIn._fields)),
                      P()),
            out_specs=AnalyticsStats(*(P() for _ in AnalyticsStats._fields)),
            check_rep=False)
        fn = jax.jit(sm)
        _ANALYTICS_SHARDED_PROGRAMS[key] = fn
    return fn(inp, n_valid)


# --------------------------------------------------------------------------
# Gang admission (ISSUE 15): member lanes + joint rank-aware packing.
#
# A gang decision is two programs. First, `gang_lanes` vmaps the fused
# scan's filter/score stage over the member rows against ONE frozen carry —
# every member sees the identical snapshot, so the lanes are a consistent
# (member, node) feasibility/score matrix, not a sequence of stale reads.
# Second, `gang_select` solves the joint placement: a fori_loop over members
# in feed order that packs each one onto the highest-ranked node, where the
# rank key prefers zone domains and then rack domains already holding
# placed mates and breaks ties by the scan's own score. Capacity is
# re-checked arithmetically as members stack (the same resource-arithmetic
# reprieve `_preempt_select_impl` applies to victims: cpu/mem/gpu/eph +
# pod count; presence-dependent predicates are frozen at lane time — a
# documented gang deviation). The host oracle in tpusim/gang/oracle.py
# mirrors this loop in numpy with identical int64 arithmetic, so
# device-vs-host choices are bit-exact, not within-epsilon.

# Rank-key layout (int64): zone-mate count, then rack-mate count, then the
# clipped scan score; -1 marks an infeasible/over-capacity node. First-
# occurrence argmax resolves ties identically in numpy and XLA. The
# encode (and the GANG_* constants re-exported above for gang/oracle.py)
# lives in jaxe/packing.py, shared with the numpy mirror.


class GangIn(NamedTuple):
    """Per-node columns the packing solve reads ([N] each)."""

    alloc_cpu: jnp.ndarray
    alloc_mem: jnp.ndarray
    alloc_gpu: jnp.ndarray
    alloc_eph: jnp.ndarray
    allowed_pods: jnp.ndarray
    used_cpu: jnp.ndarray
    used_mem: jnp.ndarray
    used_gpu: jnp.ndarray
    used_eph: jnp.ndarray
    pod_count: jnp.ndarray
    zone_dom: jnp.ndarray   # int32, 0 = no zone domain
    rack_dom: jnp.ndarray   # int32, 0 = no rack domain


def gang_columns(statics: Statics, carry: Carry, zone_dom, rack_dom) -> GangIn:
    """Pack a GangIn from an engine (Statics, Carry) pair plus the packing
    domain ids computed by the gang driver (plain field references)."""
    return GangIn(
        alloc_cpu=statics.alloc_cpu, alloc_mem=statics.alloc_mem,
        alloc_gpu=statics.alloc_gpu, alloc_eph=statics.alloc_eph,
        allowed_pods=statics.allowed_pods,
        used_cpu=carry.used_cpu, used_mem=carry.used_mem,
        used_gpu=carry.used_gpu, used_eph=carry.used_eph,
        pod_count=carry.pod_count,
        zone_dom=zone_dom, rack_dom=rack_dom)


def _gang_lanes_impl(config: EngineConfig, carry: Carry, statics: Statics,
                     xs: PodX):
    """(feasible[M, N], score[M, N]): the fused scan's filter/score stage for
    each member against the SAME carry. Only the two lanes the packing solve
    consumes are returned — reason decoding for a rejected gang is the
    driver's single shared FitError, not a per-member histogram."""

    def lanes(x: PodX):
        feasible, _bits, score, _n, _aca, _parts = _evaluate(
            config, carry, statics, x)
        return feasible, score

    return jax.vmap(lanes)(xs)


gang_lanes = partial(jax.jit, static_argnames=("config",))(_gang_lanes_impl)


_GANG_LANES_SHARDED_PROGRAMS: dict = {}


def gang_lanes_sharded(config: EngineConfig, mesh, carry: Carry,
                       statics: Statics, xs: PodX):
    """Cross-shard gang lanes (ISSUE 16 sub-problem b): the member vmap
    runs per shard over its node block (with config.shard_axis collectives
    globalizing the filter/score reductions), and the stitched out_specs
    all_gather the node axis — every host then holds the full (member,
    node) feasible/score matrix and ONE `gang_select` packer pass decides
    jointly, bit-identical to single-device lanes."""
    if config.shard_axis is None:
        raise ValueError("gang_lanes_sharded requires config.shard_axis")
    key = (config, mesh)
    fn = _GANG_LANES_SHARDED_PROGRAMS.get(key)
    if fn is None:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        st_specs, ca_specs, xs_specs = node_partition_specs(config.shard_axis)
        sm = shard_map(
            partial(_gang_lanes_impl, config), mesh=mesh,
            in_specs=(ca_specs, st_specs, xs_specs),
            out_specs=(P(None, config.shard_axis),
                       P(None, config.shard_axis)),
            check_rep=False)
        fn = jax.jit(sm)
        _GANG_LANES_SHARDED_PROGRAMS[key] = fn
    return fn(carry, statics, xs)


def _gang_select_impl(feasible, score, req_cpu, req_mem, req_gpu, req_eph,
                      zero_request, gi: GangIn, n_zone: int, n_rack: int):
    """Joint greedy packing over the (member, node) lanes. Returns
    choices[M] (node index or -1). Members are visited in feed order; each
    placement feeds the next member's domain bonuses and capacity stack."""
    m, n = feasible.shape
    del n  # shapes are static under jit; n documents the lane width

    def body(i, state):
        (gang_cpu, gang_mem, gang_gpu, gang_eph, gang_pods,
         zone_cnt, rack_cnt, choices) = state
        fits = (gi.pod_count + gang_pods + 1) <= gi.allowed_pods
        check = ~zero_request[i]
        fits &= ~check | (gi.alloc_cpu >= gi.used_cpu + gang_cpu + req_cpu[i])
        fits &= ~check | (gi.alloc_mem >= gi.used_mem + gang_mem + req_mem[i])
        fits &= ~check | (gi.alloc_gpu >= gi.used_gpu + gang_gpu + req_gpu[i])
        fits &= ~check | (gi.alloc_eph >= gi.used_eph + gang_eph + req_eph[i])
        ok = feasible[i] & fits
        zone_bonus = jnp.where(gi.zone_dom > 0, zone_cnt[gi.zone_dom], 0)
        rack_bonus = jnp.where(gi.rack_dom > 0, rack_cnt[gi.rack_dom], 0)
        rank = encode_gang_rank(zone_bonus, rack_bonus, score[i], ok)
        choice = jnp.argmax(rank).astype(jnp.int32)
        found = rank[choice] >= 0
        idx = jnp.maximum(choice, 0)
        gate = found.astype(jnp.int64)
        gate32 = found.astype(jnp.int32)
        # domain slot 0 is the "no domain" bucket: incrementing it is
        # harmless because the bonus reads above gate on dom > 0
        return (gang_cpu.at[idx].add(gate * req_cpu[i]),
                gang_mem.at[idx].add(gate * req_mem[i]),
                gang_gpu.at[idx].add(gate * req_gpu[i]),
                gang_eph.at[idx].add(gate * req_eph[i]),
                gang_pods.at[idx].add(gate),
                zone_cnt.at[gi.zone_dom[idx]].add(gate32),
                rack_cnt.at[gi.rack_dom[idx]].add(gate32),
                choices.at[i].set(jnp.where(found, choice, -1)))

    n_nodes = gi.alloc_cpu.shape[0]
    init = (jnp.zeros(n_nodes, dtype=jnp.int64),
            jnp.zeros(n_nodes, dtype=jnp.int64),
            jnp.zeros(n_nodes, dtype=jnp.int64),
            jnp.zeros(n_nodes, dtype=jnp.int64),
            jnp.zeros(n_nodes, dtype=jnp.int64),
            jnp.zeros(n_zone, dtype=jnp.int32),
            jnp.zeros(n_rack, dtype=jnp.int32),
            jnp.full(m, -1, dtype=jnp.int32))
    state = jax.lax.fori_loop(0, m, body, init)
    return state[-1]


gang_select = partial(jax.jit, static_argnames=("n_zone", "n_rack"))(
    _gang_select_impl)

"""Device kernels: the fused filter→score→select→bind pipeline.

Reference mapping:
  findNodesThatFit (generic_scheduler.go:289-377)  -> staged fail masks + reason bits
  PrioritizeNodes  (generic_scheduler.go:542-680)  -> vectorized scores + masked normalize
  selectHost       (generic_scheduler.go:183-198)  -> masked argmax + round-robin tie pick
  assume/bind      (scheduler.go:431-497)          -> scatter-add into the carry

Two execution modes (SURVEY.md §7 step 5):
  schedule_scan      — EXACT: one lax.scan step per pod; pod t's bind is seen
                       by pod t+1, identical to the Go loop.
  schedule_wavefront — FAST/approximate: K pods evaluated against a frozen
                       snapshot per wave (vmap), binds applied between waves.
                       Within a wave pods don't see each other's binds, so a
                       nearly-full node can be overcommitted; exact when pods
                       in a wave commute (uniform workloads). The rr counter
                       bookkeeping matches the sequential rule given the
                       frozen state (exclusive cumsum of "selectHost called").
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.jaxe.state import (
    BIT_DISK_PRESSURE,
    BIT_HOSTNAME_MISMATCH,
    BIT_INSUFFICIENT_CPU,
    BIT_INSUFFICIENT_EPHEMERAL,
    BIT_INSUFFICIENT_GPU,
    BIT_INSUFFICIENT_MEMORY,
    BIT_INSUFFICIENT_PODS,
    BIT_MEMORY_PRESSURE,
    BIT_NODE_SELECTOR_MISMATCH,
    BIT_TAINTS_NOT_TOLERATED,
    NUM_FIXED_BITS,
    CompiledCluster,
    PodColumns,
)

MAX_PRIORITY = 10
AVOID_PODS_WEIGHT = 10000


class Carry(NamedTuple):
    used_cpu: jnp.ndarray      # [N] int64
    used_mem: jnp.ndarray
    used_gpu: jnp.ndarray
    used_eph: jnp.ndarray
    used_scalar: jnp.ndarray   # [N, S]
    nonzero_cpu: jnp.ndarray
    nonzero_mem: jnp.ndarray
    pod_count: jnp.ndarray
    rr: jnp.ndarray            # scalar int64 — selectHost's lastNodeIndex


class Statics(NamedTuple):
    alloc_cpu: jnp.ndarray
    alloc_mem: jnp.ndarray
    alloc_gpu: jnp.ndarray
    alloc_eph: jnp.ndarray
    allowed_pods: jnp.ndarray
    alloc_scalar: jnp.ndarray
    cond_fail_bits: jnp.ndarray
    mem_pressure: jnp.ndarray
    disk_pressure: jnp.ndarray
    selector_ok: jnp.ndarray
    taint_ok: jnp.ndarray
    intolerable: jnp.ndarray
    affinity_count: jnp.ndarray
    avoid_score: jnp.ndarray
    host_ok: jnp.ndarray


class PodX(NamedTuple):
    """One pod's columns (scan xs slice / wavefront row)."""

    req_cpu: jnp.ndarray
    req_mem: jnp.ndarray
    req_gpu: jnp.ndarray
    req_eph: jnp.ndarray
    req_scalar: jnp.ndarray    # [S]
    nz_cpu: jnp.ndarray
    nz_mem: jnp.ndarray
    zero_request: jnp.ndarray
    best_effort: jnp.ndarray
    sel_id: jnp.ndarray
    tol_id: jnp.ndarray
    aff_id: jnp.ndarray
    avoid_id: jnp.ndarray
    host_id: jnp.ndarray


@dataclass(frozen=True)
class EngineConfig:
    """Static (compile-time) provider configuration."""

    most_requested: bool = False  # LeastRequested -> MostRequested swap (TD/autoscaler)
    num_reason_bits: int = NUM_FIXED_BITS


# ---------------------------------------------------------------------------
# Axis registries: for each pytree field, a tuple naming every array axis.
# sharding.py pads/shards the "node" axis; whatif.py unifies every *other*
# named axis to a common cross-scenario size. PodX omits its leading pod axis.
# Adding a field to a NamedTuple requires only a matching entry here.
# ---------------------------------------------------------------------------

STATICS_AXES = dict(
    alloc_cpu=("node",), alloc_mem=("node",), alloc_gpu=("node",),
    alloc_eph=("node",), allowed_pods=("node",), alloc_scalar=("node", "scalar"),
    cond_fail_bits=("node",), mem_pressure=("node",), disk_pressure=("node",),
    selector_ok=("sig_sel", "node"), taint_ok=("sig_tol", "node"),
    intolerable=("sig_tol", "node"), affinity_count=("sig_aff", "node"),
    avoid_score=("sig_avoid", "node"), host_ok=("sig_host", "node"),
)
CARRY_AXES = dict(
    used_cpu=("node",), used_mem=("node",), used_gpu=("node",), used_eph=("node",),
    used_scalar=("node", "scalar"), nonzero_cpu=("node",), nonzero_mem=("node",),
    pod_count=("node",), rr=(),
)
PODX_AXES = dict(
    req_cpu=(), req_mem=(), req_gpu=(), req_eph=(), req_scalar=("scalar",),
    nz_cpu=(), nz_mem=(), zero_request=(), best_effort=(), sel_id=(),
    tol_id=(), aff_id=(), avoid_id=(), host_id=(),
)
# Node-axis pad fill per field (default 0). Exception: cond_fail_bits is
# special-cased in sharding._pad_node_tree with a lazily-built infeasible
# sentinel (1<<62 needs x64 enabled), so padded nodes can never be selected.
PAD_FILLS: dict = {}


def statics_to_host(compiled: CompiledCluster) -> Statics:
    """Statics pytree over host numpy arrays (no device transfer)."""
    s, t = compiled.statics, compiled.tables
    return Statics(
        alloc_cpu=s.alloc_cpu, alloc_mem=s.alloc_mem,
        alloc_gpu=s.alloc_gpu, alloc_eph=s.alloc_eph,
        allowed_pods=s.allowed_pods, alloc_scalar=s.alloc_scalar,
        cond_fail_bits=s.cond_fail_bits, mem_pressure=s.mem_pressure,
        disk_pressure=s.disk_pressure,
        selector_ok=t.selector_ok, taint_ok=t.taint_ok,
        intolerable=t.intolerable, affinity_count=t.affinity_count,
        avoid_score=t.avoid_score, host_ok=t.host_ok)


def carry_init_host(compiled: CompiledCluster) -> Carry:
    """Initial carry over host numpy arrays (no device transfer)."""
    d = compiled.dynamic
    return Carry(
        used_cpu=d.used_cpu, used_mem=d.used_mem, used_gpu=d.used_gpu,
        used_eph=d.used_eph, used_scalar=d.used_scalar,
        nonzero_cpu=d.nonzero_cpu, nonzero_mem=d.nonzero_mem,
        pod_count=d.pod_count, rr=np.int64(0))


def pod_columns_to_host(cols: PodColumns) -> PodX:
    """PodX pytree over host numpy arrays (no device transfer)."""
    return PodX(
        req_cpu=cols.req_cpu, req_mem=cols.req_mem, req_gpu=cols.req_gpu,
        req_eph=cols.req_eph, req_scalar=cols.req_scalar,
        nz_cpu=cols.nz_cpu, nz_mem=cols.nz_mem,
        zero_request=cols.zero_request, best_effort=cols.best_effort,
        sel_id=cols.sel_id, tol_id=cols.tol_id, aff_id=cols.aff_id,
        avoid_id=cols.avoid_id, host_id=cols.host_id)


def _tree_to_device(tree):
    return type(tree)(*(jnp.asarray(a) for a in tree))


def statics_to_device(compiled: CompiledCluster) -> Statics:
    return _tree_to_device(statics_to_host(compiled))


def carry_init(compiled: CompiledCluster) -> Carry:
    return _tree_to_device(carry_init_host(compiled))


def pod_columns_to_device(cols: PodColumns) -> PodX:
    return _tree_to_device(pod_columns_to_host(cols))


def _ratio_score(requested, capacity, most: bool):
    """least_requested.go:41-52 / most_requested.go:44-55, elementwise."""
    valid = (capacity > 0) & (requested <= capacity)
    if most:
        return jnp.where(valid, (requested * MAX_PRIORITY) // jnp.maximum(capacity, 1), 0)
    return jnp.where(
        valid, ((capacity - requested) * MAX_PRIORITY) // jnp.maximum(capacity, 1), 0)


def _balanced_score(req_cpu, req_mem, alloc_cpu, alloc_mem):
    """balanced_resource_allocation.go:39-63 — float64 like Go."""
    cpu_frac = jnp.where(alloc_cpu == 0, 1.0,
                         req_cpu.astype(jnp.float64) / jnp.maximum(alloc_cpu, 1))
    mem_frac = jnp.where(alloc_mem == 0, 1.0,
                         req_mem.astype(jnp.float64) / jnp.maximum(alloc_mem, 1))
    diff = jnp.abs(cpu_frac - mem_frac)
    score = ((1.0 - diff) * MAX_PRIORITY).astype(jnp.int64)
    return jnp.where((cpu_frac >= 1) | (mem_frac >= 1), 0, score)


def _evaluate(config: EngineConfig, carry: Carry, st: Statics, x: PodX):
    """Filter + score one pod against the carried aggregates.

    Returns (feasible[N], reason_bits[N], score[N], n_feasible)."""
    # ---- filter: staged fail masks in predicatesOrdering ----
    fail_cond = st.cond_fail_bits != 0

    insuff_pods = (carry.pod_count + 1) > st.allowed_pods
    check_res = ~x.zero_request
    insuff_cpu = check_res & (st.alloc_cpu < x.req_cpu + carry.used_cpu)
    insuff_mem = check_res & (st.alloc_mem < x.req_mem + carry.used_mem)
    insuff_gpu = check_res & (st.alloc_gpu < x.req_gpu + carry.used_gpu)
    insuff_eph = check_res & (st.alloc_eph < x.req_eph + carry.used_eph)
    insuff_scalar = check_res[..., None] & (
        st.alloc_scalar < x.req_scalar[None, :] + carry.used_scalar)
    host_bad = ~st.host_ok[x.host_id]
    sel_bad = ~st.selector_ok[x.sel_id]
    fail_general = (insuff_pods | insuff_cpu | insuff_mem | insuff_gpu
                    | insuff_eph | jnp.any(insuff_scalar, axis=-1)
                    | host_bad | sel_bad)
    bits_general = (
        insuff_pods.astype(jnp.int64) << BIT_INSUFFICIENT_PODS
        | insuff_cpu.astype(jnp.int64) << BIT_INSUFFICIENT_CPU
        | insuff_mem.astype(jnp.int64) << BIT_INSUFFICIENT_MEMORY
        | insuff_gpu.astype(jnp.int64) << BIT_INSUFFICIENT_GPU
        | insuff_eph.astype(jnp.int64) << BIT_INSUFFICIENT_EPHEMERAL
        | host_bad.astype(jnp.int64) << BIT_HOSTNAME_MISMATCH
        | sel_bad.astype(jnp.int64) << BIT_NODE_SELECTOR_MISMATCH)
    if st.alloc_scalar.shape[-1] > 0:
        scalar_bits = (insuff_scalar.astype(jnp.int64)
                       << (NUM_FIXED_BITS + jnp.arange(st.alloc_scalar.shape[-1],
                                                       dtype=jnp.int64)))
        bits_general = bits_general | jnp.sum(scalar_bits, axis=-1)

    fail_taint = ~st.taint_ok[x.tol_id]
    fail_mem_pressure = st.mem_pressure & x.best_effort
    fail_disk_pressure = st.disk_pressure

    feasible = ~(fail_cond | fail_general | fail_taint
                 | fail_mem_pressure | fail_disk_pressure)
    # short-circuit reason selection: first failing stage wins
    reason_bits = jnp.where(
        fail_cond, st.cond_fail_bits,
        jnp.where(fail_general, bits_general,
                  jnp.where(fail_taint, jnp.int64(1) << BIT_TAINTS_NOT_TOLERATED,
                            jnp.where(fail_mem_pressure,
                                      jnp.int64(1) << BIT_MEMORY_PRESSURE,
                                      jnp.where(fail_disk_pressure,
                                                jnp.int64(1) << BIT_DISK_PRESSURE,
                                                jnp.int64(0))))))
    n_feasible = jnp.sum(feasible)

    # ---- score ----
    total_cpu = x.nz_cpu + carry.nonzero_cpu
    total_mem = x.nz_mem + carry.nonzero_mem
    ratio = (_ratio_score(total_cpu, st.alloc_cpu, config.most_requested)
             + _ratio_score(total_mem, st.alloc_mem, config.most_requested)) // 2
    balanced = _balanced_score(total_cpu, total_mem, st.alloc_cpu, st.alloc_mem)

    # NodeAffinityPriority: NormalizeReduce(10, False) over feasible nodes
    aff = st.affinity_count[x.aff_id]
    aff_max = jnp.max(jnp.where(feasible, aff, 0))
    aff_norm = jnp.where(aff_max > 0, MAX_PRIORITY * aff // jnp.maximum(aff_max, 1), 0)

    # TaintTolerationPriority: NormalizeReduce(10, True) over feasible nodes
    intol = st.intolerable[x.tol_id]
    intol_max = jnp.max(jnp.where(feasible, intol, 0))
    taint_norm = jnp.where(
        intol_max > 0,
        MAX_PRIORITY - MAX_PRIORITY * intol // jnp.maximum(intol_max, 1),
        MAX_PRIORITY)

    avoid = st.avoid_score[x.avoid_id] * AVOID_PODS_WEIGHT
    score = ratio + balanced + aff_norm + taint_norm + avoid
    return feasible, reason_bits, score, n_feasible


def _select(feasible, score, n_feasible, rr):
    """selectHost (generic_scheduler.go:183-198): stable-desc + round-robin
    among max-score ties; rr is consumed only when >1 node passed the filter
    (with one feasible node scheduleOne returns it directly, :176-180)."""
    masked = jnp.where(feasible, score, jnp.int64(-1))
    max_score = jnp.max(masked)
    tie = feasible & (masked == max_score)
    ties = jnp.maximum(jnp.sum(tie), 1)
    k = jnp.where(n_feasible > 1, rr % ties, 0)
    rank = jnp.cumsum(tie.astype(jnp.int64)) - 1
    pick = tie & (rank == k)
    choice = jnp.argmax(pick).astype(jnp.int32)
    found = n_feasible > 0
    return jnp.where(found, choice, -1), found


def _reason_histogram(reason_bits, num_bits: int):
    bit_ids = jnp.arange(num_bits, dtype=jnp.int64)
    present = (reason_bits[:, None] >> bit_ids[None, :]) & 1
    return jnp.sum(present, axis=0).astype(jnp.int32)


def make_step(config: EngineConfig):
    """The exact sequential scan step: (carry, PodX) -> (carry', (choice, counts))."""

    def step(state: tuple, x: PodX):
        carry, st = state
        feasible, reason_bits, score, n_feasible = _evaluate(config, carry, st, x)
        choice, found = _select(feasible, score, n_feasible, carry.rr)
        rr_next = carry.rr + jnp.where(n_feasible > 1, 1, 0)

        idx = jnp.maximum(choice, 0)
        gate = found.astype(jnp.int64)
        new_carry = Carry(
            used_cpu=carry.used_cpu.at[idx].add(gate * x.req_cpu),
            used_mem=carry.used_mem.at[idx].add(gate * x.req_mem),
            used_gpu=carry.used_gpu.at[idx].add(gate * x.req_gpu),
            used_eph=carry.used_eph.at[idx].add(gate * x.req_eph),
            used_scalar=carry.used_scalar.at[idx].add(gate * x.req_scalar),
            nonzero_cpu=carry.nonzero_cpu.at[idx].add(gate * x.nz_cpu),
            nonzero_mem=carry.nonzero_mem.at[idx].add(gate * x.nz_mem),
            pod_count=carry.pod_count.at[idx].add(gate),
            rr=rr_next)

        counts = jax.lax.cond(
            found,
            lambda: jnp.zeros(config.num_reason_bits, dtype=jnp.int32),
            lambda: _reason_histogram(reason_bits, config.num_reason_bits))
        return (new_carry, st), (choice, counts)

    return step


@partial(jax.jit, static_argnames=("config",))
def schedule_scan(config: EngineConfig, carry: Carry, statics: Statics, xs: PodX):
    """Exact sequential mode: scan the fused step over the pod axis."""
    step = make_step(config)
    (final_carry, _), (choices, counts) = jax.lax.scan(step, (carry, statics), xs)
    return final_carry, choices, counts


def make_wavefront_step(config: EngineConfig):
    """One wave: evaluate K pods against the frozen carry, then apply binds."""

    def step(state: tuple, wave):
        carry, st = state
        xs, valid = wave  # PodX with leading K axis, valid[K] (padding mask)

        feasible, reason_bits, score, n_feasible = jax.vmap(
            lambda x: _evaluate(config, carry, st, x))(xs)

        # rr bookkeeping: pod k sees rr advanced by every prior in-wave pod
        # that would have invoked selectHost (n_feasible > 1), matching the
        # sequential rule against the frozen snapshot.
        advances = ((n_feasible > 1) & valid).astype(jnp.int64)
        rr_offsets = carry.rr + jnp.cumsum(advances) - advances
        choices, founds = jax.vmap(_select)(feasible, score, n_feasible, rr_offsets)

        gate = (founds & valid).astype(jnp.int64)
        n = carry.used_cpu.shape[0]
        seg = jnp.where(gate == 1, choices, n)  # padding/unschedulable -> dump row

        def scatter(amounts, target):
            return target + jax.ops.segment_sum(amounts * gate, seg,
                                                num_segments=n + 1)[:n]

        new_carry = Carry(
            used_cpu=scatter(xs.req_cpu, carry.used_cpu),
            used_mem=scatter(xs.req_mem, carry.used_mem),
            used_gpu=scatter(xs.req_gpu, carry.used_gpu),
            used_eph=scatter(xs.req_eph, carry.used_eph),
            used_scalar=carry.used_scalar + jax.ops.segment_sum(
                xs.req_scalar * gate[:, None], seg, num_segments=n + 1)[:n],
            nonzero_cpu=scatter(xs.nz_cpu, carry.nonzero_cpu),
            nonzero_mem=scatter(xs.nz_mem, carry.nonzero_mem),
            pod_count=scatter(jnp.ones_like(gate), carry.pod_count),
            rr=carry.rr + jnp.sum(advances))

        counts = jnp.where(
            (founds | ~valid)[:, None],
            jnp.zeros((1, config.num_reason_bits), dtype=jnp.int32),
            jax.vmap(lambda b: _reason_histogram(b, config.num_reason_bits))(reason_bits))
        choices = jnp.where(valid, choices, -1)  # _select already yields -1 on not-found
        return (new_carry, st), (choices, counts)

    return step


@partial(jax.jit, static_argnames=("config", "batch_size"))
def schedule_wavefront(config: EngineConfig, carry: Carry, statics: Statics,
                       xs: PodX, batch_size: int):
    """Fast mode: waves of `batch_size` pods against frozen snapshots."""
    p = xs.req_cpu.shape[0]
    num_waves = -(-p // batch_size)
    padded = num_waves * batch_size
    pad = padded - p

    def pad_field(a):
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths).reshape((num_waves, batch_size) + a.shape[1:])

    xs_w = PodX(*(pad_field(f) for f in xs))
    valid = pad_field(jnp.ones(p, dtype=bool))

    step = make_wavefront_step(config)
    (final_carry, _), (choices, counts) = jax.lax.scan(
        step, (carry, statics), (xs_w, valid))
    return (final_carry,
            choices.reshape(padded)[:p],
            counts.reshape(padded, config.num_reason_bits)[:p])

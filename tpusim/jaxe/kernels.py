"""Device kernels: the fused filter→score→select→bind scan step.

Reference mapping:
  findNodesThatFit (generic_scheduler.go:289-377)  -> staged fail masks + reason bits
  PrioritizeNodes  (generic_scheduler.go:542-680)  -> vectorized scores + masked normalize
  selectHost       (generic_scheduler.go:183-198)  -> masked argmax + round-robin tie pick
  assume/bind      (scheduler.go:431-497)          -> scatter-add into the carry

One `lax.scan` step fuses the whole per-pod pipeline; the carry holds only the
dynamic aggregates (requested/nonzero resources, pod counts, rr counter).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tpusim.jaxe.state import (
    BIT_DISK_PRESSURE,
    BIT_HOSTNAME_MISMATCH,
    BIT_INSUFFICIENT_CPU,
    BIT_INSUFFICIENT_EPHEMERAL,
    BIT_INSUFFICIENT_GPU,
    BIT_INSUFFICIENT_MEMORY,
    BIT_INSUFFICIENT_PODS,
    BIT_MEMORY_PRESSURE,
    BIT_NODE_SELECTOR_MISMATCH,
    BIT_TAINTS_NOT_TOLERATED,
    NUM_FIXED_BITS,
    CompiledCluster,
    PodColumns,
)

MAX_PRIORITY = 10
AVOID_PODS_WEIGHT = 10000


class Carry(NamedTuple):
    used_cpu: jnp.ndarray      # [N] int64
    used_mem: jnp.ndarray
    used_gpu: jnp.ndarray
    used_eph: jnp.ndarray
    used_scalar: jnp.ndarray   # [N, S]
    nonzero_cpu: jnp.ndarray
    nonzero_mem: jnp.ndarray
    pod_count: jnp.ndarray
    rr: jnp.ndarray            # scalar int64 — selectHost's lastNodeIndex


class Statics(NamedTuple):
    alloc_cpu: jnp.ndarray
    alloc_mem: jnp.ndarray
    alloc_gpu: jnp.ndarray
    alloc_eph: jnp.ndarray
    allowed_pods: jnp.ndarray
    alloc_scalar: jnp.ndarray
    cond_fail_bits: jnp.ndarray
    mem_pressure: jnp.ndarray
    disk_pressure: jnp.ndarray
    selector_ok: jnp.ndarray
    taint_ok: jnp.ndarray
    intolerable: jnp.ndarray
    affinity_count: jnp.ndarray
    avoid_score: jnp.ndarray
    host_ok: jnp.ndarray


class PodX(NamedTuple):
    """One scan step's xs slice."""

    req_cpu: jnp.ndarray
    req_mem: jnp.ndarray
    req_gpu: jnp.ndarray
    req_eph: jnp.ndarray
    req_scalar: jnp.ndarray    # [S]
    nz_cpu: jnp.ndarray
    nz_mem: jnp.ndarray
    zero_request: jnp.ndarray
    best_effort: jnp.ndarray
    sel_id: jnp.ndarray
    tol_id: jnp.ndarray
    aff_id: jnp.ndarray
    avoid_id: jnp.ndarray
    host_id: jnp.ndarray


@dataclass(frozen=True)
class EngineConfig:
    """Static (compile-time) provider configuration."""

    most_requested: bool = False  # LeastRequested -> MostRequested swap (TD/autoscaler)
    num_reason_bits: int = NUM_FIXED_BITS


def statics_to_device(compiled: CompiledCluster) -> Statics:
    s, t = compiled.statics, compiled.tables
    return Statics(
        alloc_cpu=jnp.asarray(s.alloc_cpu), alloc_mem=jnp.asarray(s.alloc_mem),
        alloc_gpu=jnp.asarray(s.alloc_gpu), alloc_eph=jnp.asarray(s.alloc_eph),
        allowed_pods=jnp.asarray(s.allowed_pods),
        alloc_scalar=jnp.asarray(s.alloc_scalar),
        cond_fail_bits=jnp.asarray(s.cond_fail_bits),
        mem_pressure=jnp.asarray(s.mem_pressure),
        disk_pressure=jnp.asarray(s.disk_pressure),
        selector_ok=jnp.asarray(t.selector_ok), taint_ok=jnp.asarray(t.taint_ok),
        intolerable=jnp.asarray(t.intolerable),
        affinity_count=jnp.asarray(t.affinity_count),
        avoid_score=jnp.asarray(t.avoid_score), host_ok=jnp.asarray(t.host_ok))


def carry_init(compiled: CompiledCluster) -> Carry:
    d = compiled.dynamic
    return Carry(
        used_cpu=jnp.asarray(d.used_cpu), used_mem=jnp.asarray(d.used_mem),
        used_gpu=jnp.asarray(d.used_gpu), used_eph=jnp.asarray(d.used_eph),
        used_scalar=jnp.asarray(d.used_scalar),
        nonzero_cpu=jnp.asarray(d.nonzero_cpu), nonzero_mem=jnp.asarray(d.nonzero_mem),
        pod_count=jnp.asarray(d.pod_count), rr=jnp.asarray(0, dtype=jnp.int64))


def pod_columns_to_device(cols: PodColumns) -> PodX:
    return PodX(
        req_cpu=jnp.asarray(cols.req_cpu), req_mem=jnp.asarray(cols.req_mem),
        req_gpu=jnp.asarray(cols.req_gpu), req_eph=jnp.asarray(cols.req_eph),
        req_scalar=jnp.asarray(cols.req_scalar),
        nz_cpu=jnp.asarray(cols.nz_cpu), nz_mem=jnp.asarray(cols.nz_mem),
        zero_request=jnp.asarray(cols.zero_request),
        best_effort=jnp.asarray(cols.best_effort),
        sel_id=jnp.asarray(cols.sel_id), tol_id=jnp.asarray(cols.tol_id),
        aff_id=jnp.asarray(cols.aff_id), avoid_id=jnp.asarray(cols.avoid_id),
        host_id=jnp.asarray(cols.host_id))


def _ratio_score(requested, capacity, most: bool):
    """least_requested.go:41-52 / most_requested.go:44-55, elementwise."""
    valid = (capacity > 0) & (requested <= capacity)
    if most:
        raw = jnp.where(valid, (requested * MAX_PRIORITY) // jnp.maximum(capacity, 1), 0)
    else:
        raw = jnp.where(
            valid, ((capacity - requested) * MAX_PRIORITY) // jnp.maximum(capacity, 1), 0)
    return raw


def _balanced_score(req_cpu, req_mem, alloc_cpu, alloc_mem):
    """balanced_resource_allocation.go:39-63 — float64 like Go."""
    cpu_frac = jnp.where(alloc_cpu == 0, 1.0,
                         req_cpu.astype(jnp.float64) / jnp.maximum(alloc_cpu, 1))
    mem_frac = jnp.where(alloc_mem == 0, 1.0,
                         req_mem.astype(jnp.float64) / jnp.maximum(alloc_mem, 1))
    diff = jnp.abs(cpu_frac - mem_frac)
    score = ((1.0 - diff) * MAX_PRIORITY).astype(jnp.int64)
    return jnp.where((cpu_frac >= 1) | (mem_frac >= 1), 0, score)


def make_step(config: EngineConfig):
    """Build the scan step: (carry, PodX) -> (carry', (choice, reason_counts))."""

    num_bits = config.num_reason_bits

    def step(state: tuple, x: PodX):
        carry, st = state  # st: Statics closed into carry tuple for sharding ease

        # ---- filter: staged fail masks in predicatesOrdering ----
        # stage 0: CheckNodeCondition (static)
        fail_cond = st.cond_fail_bits != 0

        # stage 1: GeneralPredicates (PodFitsResources + Host + Ports + Selector)
        insuff_pods = (carry.pod_count + 1) > st.allowed_pods
        check_res = ~x.zero_request
        insuff_cpu = check_res & (st.alloc_cpu < x.req_cpu + carry.used_cpu)
        insuff_mem = check_res & (st.alloc_mem < x.req_mem + carry.used_mem)
        insuff_gpu = check_res & (st.alloc_gpu < x.req_gpu + carry.used_gpu)
        insuff_eph = check_res & (st.alloc_eph < x.req_eph + carry.used_eph)
        # scalars: [N, S] comparison
        insuff_scalar = check_res[..., None] & (
            st.alloc_scalar < x.req_scalar[None, :] + carry.used_scalar)
        host_bad = ~st.host_ok[x.host_id]
        sel_bad = ~st.selector_ok[x.sel_id]
        fail_general = (insuff_pods | insuff_cpu | insuff_mem | insuff_gpu
                        | insuff_eph | jnp.any(insuff_scalar, axis=-1)
                        | host_bad | sel_bad)
        bits_general = (
            insuff_pods.astype(jnp.int64) << BIT_INSUFFICIENT_PODS
            | insuff_cpu.astype(jnp.int64) << BIT_INSUFFICIENT_CPU
            | insuff_mem.astype(jnp.int64) << BIT_INSUFFICIENT_MEMORY
            | insuff_gpu.astype(jnp.int64) << BIT_INSUFFICIENT_GPU
            | insuff_eph.astype(jnp.int64) << BIT_INSUFFICIENT_EPHEMERAL
            | host_bad.astype(jnp.int64) << BIT_HOSTNAME_MISMATCH
            | sel_bad.astype(jnp.int64) << BIT_NODE_SELECTOR_MISMATCH)
        if st.alloc_scalar.shape[-1] > 0:
            scalar_bits = (insuff_scalar.astype(jnp.int64)
                           << (NUM_FIXED_BITS + jnp.arange(st.alloc_scalar.shape[-1],
                                                           dtype=jnp.int64)))
            bits_general = bits_general | jnp.sum(scalar_bits, axis=-1)

        # stage 2: PodToleratesNodeTaints (static per toleration signature)
        fail_taint = ~st.taint_ok[x.tol_id]
        # stage 3/4: memory / disk pressure
        fail_mem_pressure = st.mem_pressure & x.best_effort
        fail_disk_pressure = st.disk_pressure

        feasible = ~(fail_cond | fail_general | fail_taint
                     | fail_mem_pressure | fail_disk_pressure)
        # short-circuit reason selection: first failing stage wins
        reason_bits = jnp.where(
            fail_cond, st.cond_fail_bits,
            jnp.where(fail_general, bits_general,
                      jnp.where(fail_taint, jnp.int64(1) << BIT_TAINTS_NOT_TOLERATED,
                                jnp.where(fail_mem_pressure,
                                          jnp.int64(1) << BIT_MEMORY_PRESSURE,
                                          jnp.where(fail_disk_pressure,
                                                    jnp.int64(1) << BIT_DISK_PRESSURE,
                                                    jnp.int64(0))))))

        n_feasible = jnp.sum(feasible)

        # ---- score (only feasible nodes matter) ----
        total_cpu = x.nz_cpu + carry.nonzero_cpu
        total_mem = x.nz_mem + carry.nonzero_mem
        ratio = (_ratio_score(total_cpu, st.alloc_cpu, config.most_requested)
                 + _ratio_score(total_mem, st.alloc_mem, config.most_requested)) // 2
        balanced = _balanced_score(total_cpu, total_mem, st.alloc_cpu, st.alloc_mem)

        # NodeAffinityPriority: NormalizeReduce(10, False) over feasible nodes
        aff = st.affinity_count[x.aff_id]
        aff_max = jnp.max(jnp.where(feasible, aff, 0))
        aff_norm = jnp.where(aff_max > 0,
                             MAX_PRIORITY * aff // jnp.maximum(aff_max, 1), 0)

        # TaintTolerationPriority: NormalizeReduce(10, True) over feasible nodes
        intol = st.intolerable[x.tol_id]
        intol_max = jnp.max(jnp.where(feasible, intol, 0))
        taint_norm = jnp.where(
            intol_max > 0,
            MAX_PRIORITY - MAX_PRIORITY * intol // jnp.maximum(intol_max, 1),
            MAX_PRIORITY)

        avoid = st.avoid_score[x.avoid_id] * AVOID_PODS_WEIGHT

        score = ratio + balanced + aff_norm + taint_norm + avoid

        # ---- select: stable-desc + round-robin among max ties ----
        masked_score = jnp.where(feasible, score, jnp.int64(-1))
        max_score = jnp.max(masked_score)
        tie = feasible & (masked_score == max_score)
        ties = jnp.maximum(jnp.sum(tie), 1)
        # selectHost is only invoked when >1 node passed the filter; with exactly
        # one feasible node scheduleOne returns it directly and the rr counter is
        # NOT advanced (generic_scheduler.go:176-180).
        k = jnp.where(n_feasible > 1, carry.rr % ties, 0)
        rank = jnp.cumsum(tie.astype(jnp.int64)) - 1
        pick = tie & (rank == k)
        choice = jnp.argmax(pick).astype(jnp.int32)
        found = n_feasible > 0
        choice = jnp.where(found, choice, -1)
        rr_next = carry.rr + jnp.where(n_feasible > 1, 1, 0)

        # ---- bind: scatter-add into carry ----
        idx = jnp.maximum(choice, 0)
        gate = found.astype(jnp.int64)
        new_carry = Carry(
            used_cpu=carry.used_cpu.at[idx].add(gate * x.req_cpu),
            used_mem=carry.used_mem.at[idx].add(gate * x.req_mem),
            used_gpu=carry.used_gpu.at[idx].add(gate * x.req_gpu),
            used_eph=carry.used_eph.at[idx].add(gate * x.req_eph),
            used_scalar=carry.used_scalar.at[idx].add(gate * x.req_scalar),
            nonzero_cpu=carry.nonzero_cpu.at[idx].add(gate * x.nz_cpu),
            nonzero_mem=carry.nonzero_mem.at[idx].add(gate * x.nz_mem),
            pod_count=carry.pod_count.at[idx].add(gate),
            rr=rr_next)

        # ---- failure histogram (only when unschedulable) ----
        def reason_counts():
            bit_ids = jnp.arange(num_bits, dtype=jnp.int64)
            present = (reason_bits[:, None] >> bit_ids[None, :]) & 1
            return jnp.sum(present, axis=0).astype(jnp.int32)

        counts = jax.lax.cond(found,
                              lambda: jnp.zeros(num_bits, dtype=jnp.int32),
                              reason_counts)

        return (new_carry, st), (choice, counts)

    return step


@partial(jax.jit, static_argnames=("config",))
def schedule_scan(config: EngineConfig, carry: Carry, statics: Statics, xs: PodX):
    """Exact sequential mode: scan the fused step over the pod axis."""
    step = make_step(config)
    (final_carry, _), (choices, counts) = jax.lax.scan(step, (carry, statics), xs)
    return final_carry, choices, counts

"""Compile a scheduler Policy (api/types.go:52-77) for the device engine.

Mirrors factory.go CreateFromConfig:933-1000 + plugins.go
RegisterCustomFitPredicate:197-240 / RegisterCustomPriorityFunction:302-348,
but instead of assembling host predicate/priority closures it produces:

  * a kernels.PolicySpec — static predicate gating + score-component weights
    baked into the jitted program (EngineConfig.policy), and
  * per-node static rows for the policy's custom plugins
    (CheckNodeLabelPresence masks, NodeLabel priority scores) that overwrite
    the trivial rows in Statics.

Host-bound policy features have no device encoding and fall back to the
reference engine (the same containment as volume workloads): extenders (HTTP
round-trips mid-filter), the ServiceAffinity PREDICATE (its constraint is the
node of the first matching POD in lister order — a property of live
placements that presence counts cannot represent), and the few
alwaysCheckAllPredicates shapes where the host can emit one reason string
twice per node (the device histogram is bit-per-string). Everything else in
the 1.10 registry compiles: ImageLocality and the NoExecute taint variant
ride static signature tables; ServiceAntiAffinity compiles because services
are static during a run, so its first-matching-SERVICE selector interns at
group-compile time (state._compile_groups saa tables); and
alwaysCheckAllPredicates otherwise runs on device (reason bits OR over all
failing stages). Unknown names raise the host registry's KeyError
byte-for-byte."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import numpy as np

from tpusim.engine import predicates as preds
from tpusim.engine.policy import Policy, validate_policy
from tpusim.engine.priorities import MAX_PRIORITY
from tpusim.jaxe.kernels import AVOID_PODS_WEIGHT, PolicySpec

# standard predicates the device evaluates natively, by registry name
COMPILABLE_PREDS = frozenset({
    preds.CHECK_NODE_CONDITION_PRED, preds.CHECK_NODE_UNSCHEDULABLE_PRED,
    preds.GENERAL_PRED, preds.HOSTNAME_PRED, preds.POD_FITS_HOST_PORTS_PRED,
    preds.MATCH_NODE_SELECTOR_PRED, preds.POD_FITS_RESOURCES_PRED,
    preds.NO_DISK_CONFLICT_PRED, preds.POD_TOLERATES_NODE_TAINTS_PRED,
    preds.MAX_EBS_VOLUME_COUNT_PRED, preds.MAX_GCE_PD_VOLUME_COUNT_PRED,
    preds.MAX_AZURE_DISK_VOLUME_COUNT_PRED,
    # CheckVolumeBinding is a pass with the VolumeScheduling gate off
    # (predicates.go:1586), which is the jax backend's only mode
    preds.CHECK_VOLUME_BINDING_PRED,
    preds.NO_VOLUME_ZONE_CONFLICT_PRED,
    preds.CHECK_NODE_MEMORY_PRESSURE_PRED, preds.CHECK_NODE_DISK_PRESSURE_PRED,
    preds.MATCH_INTERPOD_AFFINITY_PRED,
    # NoExecute-only taint variant (policy-registered): its own static table
    preds.POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED,
})

# priority name -> PolicySpec weight field (EqualPriority adds the same
# constant to every node, so it cannot change the argmax or the tie set)
_WEIGHT_FIELDS: Dict[str, str] = {
    "LeastRequestedPriority": "w_least",
    "MostRequestedPriority": "w_most",
    "BalancedResourceAllocation": "w_balanced",
    "NodeAffinityPriority": "w_node_aff",
    "TaintTolerationPriority": "w_taint",
    "NodePreferAvoidPodsPriority": "w_avoid",
    "SelectorSpreadPriority": "w_spread",
    "InterPodAffinityPriority": "w_interpod",
}
# every priority the 1.10 registry knows now compiles (ImageLocality rides a
# static signature table); custom args route by kind below
COMPILABLE_PRIOS = frozenset(_WEIGHT_FIELDS) | {"EqualPriority",
                                                "ImageLocalityPriority"}

# the DefaultProvider weight set (defaults.go:219-259); policies that omit
# `priorities` inherit it (CreateFromConfig → DefaultProvider keys)
_DEFAULT_WEIGHTS = dict(w_least=1, w_most=0, w_balanced=1, w_node_aff=1,
                        w_taint=1, w_avoid=AVOID_PODS_WEIGHT, w_spread=1,
                        w_interpod=1)


@dataclass
class CompiledPolicy:
    spec: PolicySpec
    # policy HardPodAffinitySymmetricWeight override; None = keep config value
    # (CreateFromConfig treats 0 as unset, providers.py:415-417)
    hard_weight: int = None
    # custom label-presence predicate rows, parallel to spec.label_rows: each
    # (ordering slot name or "" for tail, [(labels, presence), ...] folded
    # into that row)
    label_rows: List[Tuple[str, list]] = field(default_factory=list)
    # custom label priorities: (label, presence, weight)
    label_prios: List[Tuple[str, bool, int]] = field(default_factory=list)
    # ServiceAntiAffinity entries: (node label, weight), parallel to
    # spec.saa_weights
    saa_entries: List[Tuple[str, int]] = field(default_factory=list)
    # host-bound features forcing the reference fallback (empty = compilable)
    unsupported: List[str] = field(default_factory=list)


def compile_policy(policy: Policy) -> CompiledPolicy:
    """Raises PolicyError/KeyError exactly like the host assembly; returns a
    CompiledPolicy whose `unsupported` lists any host-bound feature."""
    validate_policy(policy)
    unsupported: List[str] = []
    if policy.extender_configs:
        unsupported.append("policy extenders (HTTP round-trips mid-filter)")

    # Both registries key plugins by NAME and a later registration under the
    # same name overwrites the earlier one, while the key set dedups
    # (plugins.go RegisterCustomFitPredicate/RegisterCustomPriorityFunction +
    # the {register_...} set comprehension in providers.create_from_config) —
    # so duplicates resolve last-wins here too.
    label_rows: List[Tuple[str, list]] = []
    if policy.predicates is None:
        pred_keys = None
    else:
        pred_by_name: Dict[str, tuple] = {}
        for pp in policy.predicates:
            arg = pp.argument
            if arg is not None and arg.service_affinity is not None:
                pred_by_name[pp.name] = ("unsupported",
                                         f"ServiceAffinity predicate {pp.name!r} "
                                         "(label-consistency state over live "
                                         "placements)")
            elif arg is not None and arg.labels_presence is not None:
                pred_by_name[pp.name] = (
                    "label", (tuple(arg.labels_presence.labels),
                              bool(arg.labels_presence.presence)))
            elif pp.name in COMPILABLE_PREDS:
                pred_by_name[pp.name] = ("standard",)
            else:
                # plugins.go RegisterCustomFitPredicate's failure, byte-matched
                raise KeyError("Invalid configuration: Predicate type not "
                               f"found for {pp.name}")
        pred_keys = set()
        slotted: Dict[str, list] = {}
        tail_entries: list = []
        for name, entry in pred_by_name.items():
            if entry[0] == "standard":
                pred_keys.add(name)
            elif entry[0] == "label":
                # the host registers the custom under the policy's name: a
                # name appearing in PREDICATES_ORDERING evaluates at that
                # slot (generic_scheduler.py _predicate_key_order), any other
                # name runs after the fixed ordering
                if name == preds.CHECK_NODE_CONDITION_PRED:
                    # would REPLACE the mandatory condition predicate the
                    # device always evaluates — host-bound edge
                    unsupported.append(
                        "label predicate replacing the mandatory "
                        "CheckNodeCondition")
                elif name in preds.PREDICATES_ORDERING:
                    slotted[name] = [entry[1]]
                else:
                    tail_entries.append(entry[1])
            else:
                unsupported.append(entry[1])
        for name in preds.PREDICATES_ORDERING:
            if name in slotted:
                label_rows.append((name, slotted[name]))
        if tail_entries:
            label_rows.append(("", tail_entries))

    weights = dict(_DEFAULT_WEIGHTS)
    label_prios: List[Tuple[str, bool, int]] = []
    saa_entries: List[Tuple[str, int]] = []
    image_weight = 0
    if policy.priorities is not None:
        weights = dict.fromkeys(weights, 0)
        prio_by_name: Dict[str, tuple] = {}
        for pr in policy.priorities:
            arg = pr.argument
            if arg is not None and arg.service_anti_affinity is not None:
                prio_by_name[pr.name] = (
                    "saa", (arg.service_anti_affinity.label, pr.weight))
            elif arg is not None and arg.label_preference is not None:
                prio_by_name[pr.name] = (
                    "label", (arg.label_preference.label,
                              bool(arg.label_preference.presence), pr.weight))
            elif pr.name in _WEIGHT_FIELDS:
                # referencing a pre-registered priority takes the POLICY's
                # weight (plugins.go:302-348 → PriorityConfigFactory.weight)
                prio_by_name[pr.name] = ("weight", _WEIGHT_FIELDS[pr.name],
                                         pr.weight)
            elif pr.name == "ImageLocalityPriority":
                prio_by_name[pr.name] = ("image", pr.weight)
            elif pr.name == "EqualPriority":
                prio_by_name[pr.name] = ("equal",)
            else:
                raise KeyError("Invalid configuration: Priority type not "
                               f"found for {pr.name}")
        for entry in prio_by_name.values():
            if entry[0] == "weight":
                weights[entry[1]] = entry[2]
            elif entry[0] == "label":
                label_prios.append(entry[1])
            elif entry[0] == "image":
                image_weight = entry[1]
            elif entry[0] == "saa":
                saa_entries.append(entry[1])
            elif entry[0] == "unsupported":
                unsupported.append(entry[1])
            # "equal": constant shift; no effect on selection or ties

    aca = bool(policy.always_check_all_predicates)
    if aca:
        # the device reason histogram counts each reason STRING at most once
        # per node; with always-check-all the host can emit the same string
        # twice for one node in exactly these shapes — fall back there
        n_label_entries = sum(len(entries) for _, entries in label_rows)
        if n_label_entries > 1:
            unsupported.append("alwaysCheckAllPredicates with multiple "
                               "label-presence predicates (duplicate reason "
                               "strings per node)")
        if pred_keys:
            parts = {preds.HOSTNAME_PRED, preds.POD_FITS_HOST_PORTS_PRED,
                     preds.MATCH_NODE_SELECTOR_PRED,
                     preds.POD_FITS_RESOURCES_PRED}
            if preds.GENERAL_PRED in pred_keys and pred_keys & parts:
                unsupported.append(
                    "alwaysCheckAllPredicates with GeneralPredicates plus an "
                    "individually-named part (duplicate reason strings)")
            if preds.CHECK_NODE_UNSCHEDULABLE_PRED in pred_keys:
                unsupported.append(
                    "alwaysCheckAllPredicates with CheckNodeUnschedulable "
                    "(duplicates the mandatory condition check's reason)")
            if {preds.POD_TOLERATES_NODE_TAINTS_PRED,
                    preds.POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED} \
                    <= pred_keys:
                unsupported.append(
                    "alwaysCheckAllPredicates with both taint predicates "
                    "(duplicate reason strings per node)")
    spec = PolicySpec(
        pred_keys=frozenset(pred_keys) if pred_keys is not None else None,
        label_rows=tuple(slot for slot, _ in label_rows),
        has_label_prio=bool(label_prios),
        w_image=image_weight,
        saa_weights=tuple(w for _, w in saa_entries),
        always_check_all=aca,
        **weights)
    hard = (policy.hard_pod_affinity_symmetric_weight
            if policy.hard_pod_affinity_symmetric_weight != 0 else None)
    return CompiledPolicy(spec=spec, hard_weight=hard,
                          label_rows=label_rows,
                          label_prios=label_prios, saa_entries=saa_entries,
                          unsupported=unsupported)


def _label_pred_row(nodes_by_idx: list, entries) -> np.ndarray:
    """Folded per-node pass mask for a list of label-presence predicates
    (predicates.go NewNodeLabelPredicate: every label's existence must equal
    `presence`)."""
    n = len(nodes_by_idx)
    row = np.ones(n, dtype=bool)
    for labels, presence in entries:
        for i, node in enumerate(nodes_by_idx):
            node_labels = node.metadata.labels
            for label in labels:
                if (label in node_labels) != presence:
                    row[i] = False
                    break
    return row


def image_locality_columns(pods, nodes, node_index: Dict[str, int]):
    """(img_id[P] int32, image_score[Si, N] int64): pod container-image
    multisets interned to signature ids, with the ImageLocalityPriority map
    score (image_locality.go thresholds) precomputed per (signature, node).
    Reuses the host map function for exactness."""
    from types import SimpleNamespace

    from tpusim.engine.priorities import image_locality_priority_map

    n = len(node_index)
    by_idx: list = [None] * n
    for node in nodes:
        i = node_index.get(node.name)
        if i is not None:
            by_idx[i] = node

    sig_ids: Dict[tuple, int] = {}
    reps: List = []
    img_id = np.zeros(len(pods), dtype=np.int32)
    for j, pod in enumerate(pods):
        # a multiset: two containers sharing an image each add its size
        sig = tuple(sorted(c.image for c in pod.spec.containers))
        if sig not in sig_ids:
            sig_ids[sig] = len(reps)
            reps.append(pod)
        img_id[j] = sig_ids[sig]

    table = np.zeros((max(len(reps), 1), n), dtype=np.int64)
    for s, rep in enumerate(reps):
        for i, node in enumerate(by_idx):
            info = SimpleNamespace(node=node)
            table[s, i] = image_locality_priority_map(rep, None, info).score
    return img_id, table


def saa_dom_rows(cp: CompiledPolicy, nodes, node_index: Dict[str, int]):
    """(saa_dom [E, N] int32, n_doms int): per-ServiceAntiAffinity-entry
    node label-value domains (0 = label absent; values interned per entry,
    one shared segment count)."""
    n = len(node_index)
    e_count = max(len(cp.saa_entries), 1)
    dom = np.zeros((e_count, n), dtype=np.int32)
    n_doms = 1
    for e, (label, _w) in enumerate(cp.saa_entries):
        values: Dict[str, int] = {}
        for node in nodes:
            i = node_index.get(node.name)
            if i is None:
                continue
            value = node.metadata.labels.get(label)
            if value is None:
                continue
            vid = values.get(value)
            if vid is None:
                vid = len(values) + 1
                values[value] = vid
            dom[e, i] = vid
        n_doms = max(n_doms, len(values) + 1)
    return dom, n_doms


def policy_static_rows(cp: CompiledPolicy, nodes,
                       node_index: Dict[str, int]):
    """(label_ok[L, N], label_prio[N]) in compiled node order, rows parallel
    to spec.label_rows. `nodes` is the snapshot node list; node_index the
    compiled order."""
    n = len(node_index)
    by_idx: list = [None] * n
    for node in nodes:
        i = node_index.get(node.name)
        if i is not None:
            by_idx[i] = node
    if cp.label_rows:
        label_ok = np.stack([_label_pred_row(by_idx, entries)
                             for _, entries in cp.label_rows])
    else:
        label_ok = np.ones((1, n), dtype=bool)
    prio = np.zeros(n, dtype=np.int64)
    for label, presence, weight in cp.label_prios:
        for i, node in enumerate(by_idx):
            exists = label in node.metadata.labels
            if exists == presence:
                prio[i] += weight * MAX_PRIORITY
    return label_ok, prio

"""Compile a scheduler Policy (api/types.go:52-77) for the device engine.

Mirrors factory.go CreateFromConfig:933-1000 + plugins.go
RegisterCustomFitPredicate:197-240 / RegisterCustomPriorityFunction:302-348,
but instead of assembling host predicate/priority closures it produces:

  * a kernels.PolicySpec — static predicate gating + score-component weights
    baked into the jitted program (EngineConfig.policy), and
  * per-node static rows for the policy's custom plugins
    (CheckNodeLabelPresence masks, NodeLabel priority scores) that overwrite
    the trivial rows in Statics.

Host-bound policy features have no device encoding and fall back to the
reference engine (the same containment as volume workloads): extenders (HTTP
round-trips mid-filter), multiple ServiceAffinity predicates in one policy
(the device carries one first-pod lock per first-service signature), and the
few alwaysCheckAllPredicates shapes where the host can emit one reason
string twice per node (the device histogram is bit-per-string). Everything
else in the 1.10 registry compiles: ImageLocality and the NoExecute taint
variant ride static signature tables; Service(Anti)Affinity compile because
services are static during a run (the first-matching-SERVICE selector
interns at group-compile time) and the ServiceAffinity first matching POD is
a static property of snapshot+feed order (service_affinity_columns — a
seeded pod is a static lock, a fed pod locks the carry when it binds); and
alwaysCheckAllPredicates otherwise runs on device (reason bits OR over all
failing stages). Unknown names raise the host registry's KeyError
byte-for-byte."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import numpy as np

from tpusim.engine import predicates as preds
from tpusim.engine.policy import Policy, validate_policy
from tpusim.engine.priorities import MAX_PRIORITY
from tpusim.jaxe.kernels import AVOID_PODS_WEIGHT, PolicySpec

# standard predicates the device evaluates natively, by registry name
COMPILABLE_PREDS = frozenset({
    preds.CHECK_NODE_CONDITION_PRED, preds.CHECK_NODE_UNSCHEDULABLE_PRED,
    preds.GENERAL_PRED, preds.HOSTNAME_PRED, preds.POD_FITS_HOST_PORTS_PRED,
    preds.MATCH_NODE_SELECTOR_PRED, preds.POD_FITS_RESOURCES_PRED,
    preds.NO_DISK_CONFLICT_PRED, preds.POD_TOLERATES_NODE_TAINTS_PRED,
    preds.MAX_EBS_VOLUME_COUNT_PRED, preds.MAX_GCE_PD_VOLUME_COUNT_PRED,
    preds.MAX_AZURE_DISK_VOLUME_COUNT_PRED,
    # CheckVolumeBinding is a pass with the VolumeScheduling gate off
    # (predicates.go:1586), which is the jax backend's only mode
    preds.CHECK_VOLUME_BINDING_PRED,
    preds.NO_VOLUME_ZONE_CONFLICT_PRED,
    preds.CHECK_NODE_MEMORY_PRESSURE_PRED, preds.CHECK_NODE_DISK_PRESSURE_PRED,
    preds.MATCH_INTERPOD_AFFINITY_PRED,
    # NoExecute-only taint variant (policy-registered): its own static table
    preds.POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED,
})

# 1.0 backward-compat alias (defaults.go:63-65). HOST-BOUND, not aliased to
# the hostports slot: the host engine evaluates registry keys outside
# predicates.Ordering() at the alphabetical TAIL slot (the documented
# deliberate deviation in generic_scheduler.py), so "PodFitsPorts" short-
# circuits in a different position than "PodFitsHostPorts" — first-failure
# reason strings can differ. The device's fixed-slot pipeline cannot express
# a standard predicate at a tail slot; policies naming the alias fall back.
_HOST_BOUND_PRED_ALIASES = frozenset({"PodFitsPorts"})

# priority name -> PolicySpec weight field (EqualPriority adds the same
# constant to every node, so it cannot change the argmax or the tie set).
# ServiceSpreadingPriority (the 1.0 alias) shares w_spread: the device's
# spread signatures are service-derived only (state.py — RC/RS/StatefulSet
# informers are empty fakes in the simulator, simulator.go:352-366), so the
# alias scores identically to SelectorSpreadPriority and a policy naming
# BOTH sums their weights, matching two host instances' summed scores.
_WEIGHT_FIELDS: Dict[str, str] = {
    "LeastRequestedPriority": "w_least",
    "MostRequestedPriority": "w_most",
    "BalancedResourceAllocation": "w_balanced",
    "NodeAffinityPriority": "w_node_aff",
    "TaintTolerationPriority": "w_taint",
    "NodePreferAvoidPodsPriority": "w_avoid",
    "SelectorSpreadPriority": "w_spread",
    "ServiceSpreadingPriority": "w_spread",
    "InterPodAffinityPriority": "w_interpod",
}
# every priority the 1.10 registry knows now compiles (ImageLocality rides a
# static signature table); custom args route by kind below
COMPILABLE_PRIOS = frozenset(_WEIGHT_FIELDS) | {"EqualPriority",
                                                "ImageLocalityPriority"}

# the DefaultProvider weight set (defaults.go:219-259); policies that omit
# `priorities` inherit it (CreateFromConfig → DefaultProvider keys)
_DEFAULT_WEIGHTS = dict(w_least=1, w_most=0, w_balanced=1, w_node_aff=1,
                        w_taint=1, w_avoid=AVOID_PODS_WEIGHT, w_spread=1,
                        w_interpod=1)


@dataclass
class CompiledPolicy:
    spec: PolicySpec
    # policy HardPodAffinitySymmetricWeight override; None = keep config value
    # (CreateFromConfig treats 0 as unset, providers.py:415-417)
    hard_weight: int = None
    # custom label-presence predicate rows, parallel to spec.label_rows: each
    # (ordering slot name or "" for tail, [(labels, presence), ...] folded
    # into that row)
    label_rows: List[Tuple[str, list]] = field(default_factory=list)
    # custom label priorities: (label, presence, weight)
    label_prios: List[Tuple[str, bool, int]] = field(default_factory=list)
    # ServiceAntiAffinity entries: (node label, weight), parallel to
    # spec.saa_weights
    saa_entries: List[Tuple[str, int]] = field(default_factory=list)
    # ServiceAffinity predicate: the policy's affinity label list
    sa_labels: tuple = ()
    # host-bound features forcing the reference fallback (empty = compilable)
    unsupported: List[str] = field(default_factory=list)


def compile_policy(policy: Policy) -> CompiledPolicy:
    """Raises PolicyError/KeyError exactly like the host assembly; returns a
    CompiledPolicy whose `unsupported` lists any host-bound feature."""
    validate_policy(policy)
    unsupported: List[str] = []
    if policy.extender_configs:
        unsupported.append("policy extenders (HTTP round-trips mid-filter)")

    # Both registries key plugins by NAME and a later registration under the
    # same name overwrites the earlier one, while the key set dedups
    # (plugins.go RegisterCustomFitPredicate/RegisterCustomPriorityFunction +
    # the {register_...} set comprehension in providers.create_from_config) —
    # so duplicates resolve last-wins here too.
    label_rows: List[Tuple[str, list]] = []
    sa_enabled = False
    sa_slot = ""
    sa_labels: tuple = ()
    if policy.predicates is None:
        pred_keys = None
    else:
        pred_by_name: Dict[str, tuple] = {}
        for pp in policy.predicates:
            arg = pp.argument
            if arg is not None and arg.service_affinity is not None:
                pred_by_name[pp.name] = (
                    "sa", tuple(arg.service_affinity.labels))
            elif arg is not None and arg.labels_presence is not None:
                pred_by_name[pp.name] = (
                    "label", (tuple(arg.labels_presence.labels),
                              bool(arg.labels_presence.presence)))
            elif pp.name in COMPILABLE_PREDS:
                pred_by_name[pp.name] = ("standard",)
            elif pp.name in _HOST_BOUND_PRED_ALIASES:
                unsupported.append(
                    f"predicate {pp.name} (1.0 alias; evaluates at the "
                    "host's custom tail slot, not the device's fixed "
                    "ordering)")
                continue
            else:
                # plugins.go RegisterCustomFitPredicate's failure, byte-matched
                raise KeyError("Invalid configuration: Predicate type not "
                               f"found for {pp.name}")
        pred_keys = set()
        slotted: Dict[str, list] = {}
        tail_entries: list = []
        sa_found: List[Tuple[str, tuple]] = []
        for name, entry in pred_by_name.items():
            if entry[0] == "standard":
                pred_keys.add(name)
            elif entry[0] == "sa":
                if name == preds.CHECK_NODE_CONDITION_PRED:
                    unsupported.append("ServiceAffinity predicate replacing "
                                       "the mandatory CheckNodeCondition")
                else:
                    sa_found.append((name, entry[1]))
            elif entry[0] == "label":
                # the host registers the custom under the policy's name: a
                # name appearing in PREDICATES_ORDERING evaluates at that
                # slot (generic_scheduler.py _predicate_key_order), any other
                # name runs after the fixed ordering
                if name == preds.CHECK_NODE_CONDITION_PRED:
                    # would REPLACE the mandatory condition predicate the
                    # device always evaluates — host-bound edge
                    unsupported.append(
                        "label predicate replacing the mandatory "
                        "CheckNodeCondition")
                elif name in preds.PREDICATES_ORDERING:
                    slotted[name] = [entry[1]]
                else:
                    tail_entries.append((name, entry[1]))
            else:
                unsupported.append(entry[1])
        if len(sa_found) > 1:
            unsupported.append(
                "multiple ServiceAffinity predicates (the device carries one "
                "first-pod lock per first-service signature)")
            sa_found = []
        sa_name = None
        if sa_found:
            sa_name, sa_labels = sa_found[0]
            sa_slot = sa_name if sa_name in preds.PREDICATES_ORDERING else ""
            sa_enabled = True
        for name in preds.PREDICATES_ORDERING:
            if name in slotted:
                label_rows.append((name, slotted[name]))
        if tail_entries:
            # the host runs tail customs in ALPHABETICAL name order
            # (generic_scheduler.py _predicate_key_order); label-vs-label
            # order is invisible (one shared reason string), but a tail
            # ServiceAffinity splits them into before/after rows
            tail_entries.sort(key=lambda pair: pair[0])
            if sa_enabled and sa_slot == "" and sa_name is not None:
                pre = [e for n, e in tail_entries if n < sa_name]
                post = [e for n, e in tail_entries if n > sa_name]
                if pre:
                    label_rows.append(("", pre))
                if post:
                    label_rows.append(("post", post))
            else:
                label_rows.append(("", [e for _, e in tail_entries]))

    weights = dict(_DEFAULT_WEIGHTS)
    label_prios: List[Tuple[str, bool, int]] = []
    saa_entries: List[Tuple[str, int]] = []
    image_weight = 0
    if policy.priorities is not None:
        weights = dict.fromkeys(weights, 0)
        prio_by_name: Dict[str, tuple] = {}
        for pr in policy.priorities:
            arg = pr.argument
            if arg is not None and arg.service_anti_affinity is not None:
                prio_by_name[pr.name] = (
                    "saa", (arg.service_anti_affinity.label, pr.weight))
            elif arg is not None and arg.label_preference is not None:
                prio_by_name[pr.name] = (
                    "label", (arg.label_preference.label,
                              bool(arg.label_preference.presence), pr.weight))
            elif pr.name in _WEIGHT_FIELDS:
                # referencing a pre-registered priority takes the POLICY's
                # weight (plugins.go:302-348 → PriorityConfigFactory.weight)
                prio_by_name[pr.name] = ("weight", _WEIGHT_FIELDS[pr.name],
                                         pr.weight)
            elif pr.name == "ImageLocalityPriority":
                prio_by_name[pr.name] = ("image", pr.weight)
            elif pr.name == "EqualPriority":
                prio_by_name[pr.name] = ("equal",)
            else:
                raise KeyError("Invalid configuration: Priority type not "
                               f"found for {pr.name}")
        for entry in prio_by_name.values():
            if entry[0] == "weight":
                # += not =: two NAMES sharing a field (SelectorSpread +
                # ServiceSpreading aliases) sum like two host instances;
                # same-name duplicates already collapsed last-wins above
                weights[entry[1]] += entry[2]
            elif entry[0] == "label":
                label_prios.append(entry[1])
            elif entry[0] == "image":
                image_weight = entry[1]
            elif entry[0] == "saa":
                saa_entries.append(entry[1])
            elif entry[0] == "unsupported":
                unsupported.append(entry[1])
            # "equal": constant shift; no effect on selection or ties

    aca = bool(policy.always_check_all_predicates)
    if aca:
        # the device reason histogram counts each reason STRING at most once
        # per node; with always-check-all the host can emit the same string
        # twice for one node in exactly these shapes — fall back there
        n_label_entries = sum(len(entries) for _, entries in label_rows)
        if n_label_entries > 1:
            unsupported.append("alwaysCheckAllPredicates with multiple "
                               "label-presence predicates (duplicate reason "
                               "strings per node)")
        if pred_keys:
            parts = {preds.HOSTNAME_PRED, preds.POD_FITS_HOST_PORTS_PRED,
                     preds.MATCH_NODE_SELECTOR_PRED,
                     preds.POD_FITS_RESOURCES_PRED}
            if preds.GENERAL_PRED in pred_keys and pred_keys & parts:
                unsupported.append(
                    "alwaysCheckAllPredicates with GeneralPredicates plus an "
                    "individually-named part (duplicate reason strings)")
            if preds.CHECK_NODE_UNSCHEDULABLE_PRED in pred_keys:
                unsupported.append(
                    "alwaysCheckAllPredicates with CheckNodeUnschedulable "
                    "(duplicates the mandatory condition check's reason)")
            if {preds.POD_TOLERATES_NODE_TAINTS_PRED,
                    preds.POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED} \
                    <= pred_keys:
                unsupported.append(
                    "alwaysCheckAllPredicates with both taint predicates "
                    "(duplicate reason strings per node)")
    spec = PolicySpec(
        pred_keys=frozenset(pred_keys) if pred_keys is not None else None,
        label_rows=tuple(slot for slot, _ in label_rows),
        has_label_prio=bool(label_prios),
        w_image=image_weight,
        saa_weights=tuple(w for _, w in saa_entries),
        sa_enabled=sa_enabled, sa_slot=sa_slot,
        always_check_all=aca,
        **weights)
    hard = (policy.hard_pod_affinity_symmetric_weight
            if policy.hard_pod_affinity_symmetric_weight != 0 else None)
    if hard is not None and (hard < 1 or hard > 100):
        # the same [1, 100] range _create_from_keys enforces host-side
        # (factory.go:1024-1026) — both backends must reject identically
        raise ValueError(f"invalid hardPodAffinitySymmetricWeight: {hard}, "
                         "must be in the range 1-100")
    return CompiledPolicy(spec=spec, hard_weight=hard,
                          label_rows=label_rows,
                          label_prios=label_prios, saa_entries=saa_entries,
                          sa_labels=sa_labels,
                          unsupported=unsupported)


def _label_pred_row(nodes_by_idx: list, entries) -> np.ndarray:
    """Folded per-node pass mask for a list of label-presence predicates
    (predicates.go NewNodeLabelPredicate: every label's existence must equal
    `presence`)."""
    n = len(nodes_by_idx)
    row = np.ones(n, dtype=bool)
    for labels, presence in entries:
        for i, node in enumerate(nodes_by_idx):
            node_labels = node.metadata.labels
            for label in labels:
                if (label in node_labels) != presence:
                    row[i] = False
                    break
    return row


def image_locality_columns(pods, nodes, node_index: Dict[str, int]):
    """(img_id[P] int32, image_score[Si, N] int64): pod container-image
    multisets interned to signature ids, with the ImageLocalityPriority map
    score (image_locality.go thresholds) precomputed per (signature, node).
    Reuses the host map function for exactness."""
    from types import SimpleNamespace

    from tpusim.engine.priorities import image_locality_priority_map

    n = len(node_index)
    by_idx: list = [None] * n
    for node in nodes:
        i = node_index.get(node.name)
        if i is not None:
            by_idx[i] = node

    sig_ids: Dict[tuple, int] = {}
    reps: List = []
    img_id = np.zeros(len(pods), dtype=np.int32)
    for j, pod in enumerate(pods):
        # a multiset: two containers sharing an image each add its size
        sig = tuple(sorted(c.image for c in pod.spec.containers))
        if sig not in sig_ids:
            sig_ids[sig] = len(reps)
            reps.append(pod)
        img_id[j] = sig_ids[sig]

    table = np.zeros((max(len(reps), 1), n), dtype=np.int64)
    for s, rep in enumerate(reps):
        for i, node in enumerate(by_idx):
            info = SimpleNamespace(node=node)
            table[s, i] = image_locality_priority_map(rep, None, info).score
    return img_id, table


def _nodes_by_index(nodes, node_index: Dict[str, int]) -> list:
    by_idx: list = [None] * len(node_index)
    for node in nodes:
        i = node_index.get(node.name)
        if i is not None:
            by_idx[i] = node
    return by_idx


def _label_value_row(by_idx: list, label: str):
    """Intern one node label's values into an int32 row (0 = absent);
    returns (row[N], number of distinct values + 1)."""
    row = np.zeros(len(by_idx), dtype=np.int32)
    values: Dict[str, int] = {}
    for i, node in enumerate(by_idx):
        value = node.metadata.labels.get(label)
        if value is None:
            continue
        vid = values.get(value)
        if vid is None:
            vid = len(values) + 1
            values[value] = vid
        row[i] = vid
    return row, len(values) + 1


def saa_dom_rows(cp: CompiledPolicy, nodes, node_index: Dict[str, int]):
    """(saa_dom [E, N] int32, n_doms int): per-ServiceAntiAffinity-entry
    node label-value domains (0 = label absent; values interned per entry,
    one shared segment count)."""
    by_idx = _nodes_by_index(nodes, node_index)
    e_count = max(len(cp.saa_entries), 1)
    dom = np.zeros((e_count, len(by_idx)), dtype=np.int32)
    n_doms = 1
    for e, (label, _w) in enumerate(cp.saa_entries):
        dom[e], n_values = _label_value_row(by_idx, label)
        n_doms = max(n_doms, n_values)
    return dom, n_doms


def service_affinity_columns(cp: CompiledPolicy, pods, snapshot,
                             node_index: Dict[str, int], saa_defs: list):
    """Static ServiceAffinity state (predicates.py check_service_affinity):

    Returns (sa_self_id[P], sa_self_ok[Cs, N], sa_unres[Cs, La],
    sa_val[La, N], sa_lock_init[Fd]).

    The plugin pod lister is the scheduler cache (factory.go:166) — ASSIGNED
    pods, seeded in snapshot order then bound pods in bind order — so the
    first matching pod is either a seeded assigned pod (static: its node
    index locks sig f, or -2 when the node is unknowable so nothing ever
    pins) or the first matching pod to BIND, which the kernel locks into the
    carry when that bind happens (-1 until then)."""
    labels = list(cp.sa_labels)
    n = len(node_index)
    la = max(len(labels), 1)
    by_idx = _nodes_by_index(snapshot.nodes, node_index)

    sa_val = np.zeros((la, n), dtype=np.int32)
    for li, label in enumerate(labels):
        sa_val[li], _ = _label_value_row(by_idx, label)

    sig_ids: Dict[tuple, int] = {}
    reps: List[tuple] = []
    sa_self_id = np.zeros(len(pods), dtype=np.int32)
    for j, pod in enumerate(pods):
        selector = pod.spec.node_selector or {}
        pins = tuple(sorted((label, selector[label]) for label in labels
                            if label in selector))
        cid = sig_ids.get(pins)
        if cid is None:
            cid = len(reps)
            sig_ids[pins] = cid
            reps.append(pins)
        sa_self_id[j] = cid

    cs = max(len(reps), 1)
    sa_self_ok = np.ones((cs, n), dtype=bool)
    sa_unres = np.zeros((cs, la), dtype=bool)
    for c, pins in enumerate(reps):
        pinned = dict(pins)
        for li, label in enumerate(labels):
            sa_unres[c, li] = label not in pinned
        for i, node in enumerate(by_idx):
            sa_self_ok[c, i] = all(node.metadata.labels.get(k) == v
                                   for k, v in pinned.items())

    fd = max(len(saa_defs), 1)
    lock_init = np.full(fd, -1, dtype=np.int32)
    for f in range(1, len(saa_defs)):
        ns, sel = saa_defs[f]
        first = next(
            (p for p in snapshot.pods
             if p.spec.node_name and p.namespace == ns
             and all(p.metadata.labels.get(k) == v for k, v in sel.items())),
            None)
        if first is not None:
            if first.spec.node_name in node_index:
                lock_init[f] = node_index[first.spec.node_name]
            else:
                # assigned to an unknowable node: it stays service_pods[0]
                # forever (assigned order), so nothing ever pins
                lock_init[f] = -2
    return sa_self_id, sa_self_ok, sa_unres, sa_val, lock_init


def policy_static_rows(cp: CompiledPolicy, nodes,
                       node_index: Dict[str, int]):
    """(label_ok[L, N], label_prio[N]) in compiled node order, rows parallel
    to spec.label_rows. `nodes` is the snapshot node list; node_index the
    compiled order."""
    n = len(node_index)
    by_idx = _nodes_by_index(nodes, node_index)
    if cp.label_rows:
        label_ok = np.stack([_label_pred_row(by_idx, entries)
                             for _, entries in cp.label_rows])
    else:
        label_ok = np.ones((1, n), dtype=bool)
    prio = np.zeros(n, dtype=np.int64)
    for label, presence, weight in cp.label_prios:
        for i, node in enumerate(by_idx):
            exists = label in node.metadata.labels
            if exists == presence:
                prio[i] += weight * MAX_PRIORITY
    return label_ok, prio

"""Compile a scheduler Policy (api/types.go:52-77) for the device engine.

Mirrors factory.go CreateFromConfig:933-1000 + plugins.go
RegisterCustomFitPredicate:197-240 / RegisterCustomPriorityFunction:302-348,
but instead of assembling host predicate/priority closures it produces:

  * a kernels.PolicySpec — static predicate gating + score-component weights
    baked into the jitted program (EngineConfig.policy), and
  * per-node static rows for the policy's custom plugins
    (CheckNodeLabelPresence masks, NodeLabel priority scores) that overwrite
    the trivial rows in Statics.

The ONLY host-bound policy feature left is extenders (HTTP round-trips
mid-filter); they fall back to the reference engine (the same containment
as volume-binder workloads). Everything else in the 1.10 registry compiles — including MULTIPLE
ServiceAffinity predicates in one policy: each entry evaluates its own label
segment (PolicySpec.sa_segs over the concatenated sa_val rows) as a separate
stage at its own ordering/tail slot against the shared first-matching-pod
lock (the lock is a node index identifying the same first pod for every
entry); the 1.0 PodFitsPorts alias re-emits the port-conflict stage at
its alphabetical tail slot (ports_slots). ImageLocality and the
NoExecute taint variant ride static signature tables; Service(Anti)Affinity
compile because services are static during a run (the first-matching-SERVICE
selector interns at group-compile time) and the ServiceAffinity first
matching POD is a static property of snapshot+feed order
(service_affinity_columns — a seeded pod is a static lock, a fed pod locks
the carry when it binds); and alwaysCheckAllPredicates runs on device in
count mode — the histogram sums per-string occurrences over ALL failing
stages, so shapes where the host emits one reason string several times per
node (GeneralPredicates plus an individually-named part, both taint
predicates, CheckNodeUnschedulable beside the mandatory condition check,
several label-presence predicates) reproduce the host's multiplicities
exactly. Unknown names raise the host registry's KeyError byte-for-byte."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from tpusim.engine import predicates as preds
from tpusim.engine.policy import Policy, validate_policy
from tpusim.engine.priorities import MAX_PRIORITY
from tpusim.jaxe.kernels import AVOID_PODS_WEIGHT, PolicySpec

# standard predicates the device evaluates natively, by registry name
COMPILABLE_PREDS = frozenset({
    preds.CHECK_NODE_CONDITION_PRED, preds.CHECK_NODE_UNSCHEDULABLE_PRED,
    preds.GENERAL_PRED, preds.HOSTNAME_PRED, preds.POD_FITS_HOST_PORTS_PRED,
    preds.MATCH_NODE_SELECTOR_PRED, preds.POD_FITS_RESOURCES_PRED,
    preds.NO_DISK_CONFLICT_PRED, preds.POD_TOLERATES_NODE_TAINTS_PRED,
    preds.MAX_EBS_VOLUME_COUNT_PRED, preds.MAX_GCE_PD_VOLUME_COUNT_PRED,
    preds.MAX_AZURE_DISK_VOLUME_COUNT_PRED,
    # CheckVolumeBinding is a pass with the VolumeScheduling gate off
    # (predicates.go:1586), which is the jax backend's only mode
    preds.CHECK_VOLUME_BINDING_PRED,
    preds.NO_VOLUME_ZONE_CONFLICT_PRED,
    preds.CHECK_NODE_MEMORY_PRESSURE_PRED, preds.CHECK_NODE_DISK_PRESSURE_PRED,
    preds.MATCH_INTERPOD_AFFINITY_PRED,
    # NoExecute-only taint variant (policy-registered): its own static table
    preds.POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED,
})

# --- preemption victim-selection class (decided at policy-compile time) ----
#
# A predicate key set is "arithmetic-reprieve" eligible when every registered
# predicate is either the resource check (PodFitsResources, or the resource
# half of GeneralPredicates) or provably victim-invariant — its outcome never
# depends on which pods remain on the node (generic_scheduler.
# _POD_SET_INDEPENDENT_PREDS). Victim search then reduces to pure integer
# arithmetic over resource aggregates, which jaxe/preempt.py routes to the
# device kernel (kernels.preempt_select); everything else keeps the host
# clone/add reprieve pipeline. Pod-set-DEPENDENT predicates whose feature is
# absent from the whole workload (no host ports anywhere, no conflictable or
# MaxPD volumes, no inter-pod terms) are constant-true for every victim set
# of the run, so the run-time feature flags can elide them — the same rule
# GenericScheduler.preemption_reprieve_class applies to the reprieve chain.

# pod-set-dependent predicate key -> workload feature flag that elides it
_FEATURE_GATED_PREDS: Dict[str, str] = {
    preds.POD_FITS_HOST_PORTS_PRED: "has_ports",
    preds.NO_DISK_CONFLICT_PRED: "has_disk_conflict",
    preds.MAX_EBS_VOLUME_COUNT_PRED: "has_maxpd",
    preds.MAX_GCE_PD_VOLUME_COUNT_PRED: "has_maxpd",
    preds.MAX_AZURE_DISK_VOLUME_COUNT_PRED: "has_maxpd",
    preds.MATCH_INTERPOD_AFFINITY_PRED: "has_interpod",
}


def classify_preemption_class(pred_keys, feature_flags=None,
                              has_extenders: bool = False):
    """Classify a predicate key set for preemption victim selection.

    Returns ("arithmetic" | "general", reason). pred_keys None means the
    provider-default set (a policy that omits `predicates`). feature_flags
    maps has_ports/has_disk_conflict/has_maxpd/has_interpod to whether the
    feature occurs anywhere in the workload (new AND placed pods); None —
    the policy-compile-time call, before any workload is known — treats
    every feature as present, so "arithmetic" at compile time means
    arithmetic for EVERY workload."""
    if has_extenders:
        return "general", "extenders re-filter preemption candidates"
    if pred_keys is None:
        from tpusim.engine.providers import DEFAULT_PREDICATE_KEYS
        pred_keys = DEFAULT_PREDICATE_KEYS
    from tpusim.engine.generic_scheduler import _POD_SET_INDEPENDENT_PREDS

    keys = set(pred_keys)
    flags = feature_flags or {}
    if (preds.GENERAL_PRED not in keys
            and preds.POD_FITS_RESOURCES_PRED not in keys):
        return "general", "no resource predicate registered"
    for key in sorted(keys):
        if key == preds.POD_FITS_RESOURCES_PRED:
            continue
        if key == preds.GENERAL_PRED:
            # GeneralPredicates bundles PodFitsHostPorts (pod-set-dependent)
            if flags.get("has_ports", True):
                return "general", "GeneralPredicates with host ports in the workload"
            continue
        if key in _POD_SET_INDEPENDENT_PREDS:
            continue
        flag = _FEATURE_GATED_PREDS.get(
            "PodFitsHostPorts" if key == _TAIL_PORTS_ALIAS else key)
        if flag is not None and not flags.get(flag, True):
            continue
        return "general", f"pod-set-dependent predicate {key}"
    return "arithmetic", ""


# 1.0 backward-compat alias (defaults.go:63-65). NOT aliased to the
# hostports slot: the host engine evaluates registry keys outside
# predicates.Ordering() at the alphabetical TAIL slot (the documented
# deliberate deviation in generic_scheduler.py), so "PodFitsPorts"
# short-circuits in a different position than "PodFitsHostPorts" —
# first-failure reason strings can differ. The device expresses that via
# the generic tail-slot mechanism ("tail:<k>", shared with label-presence
# rows and ServiceAffinity entries): the port-conflict stage is emitted
# again at the alias's sorted tail position (PolicySpec.ports_slots).
_TAIL_PORTS_ALIAS = "PodFitsPorts"

# priority name -> PolicySpec weight field (EqualPriority adds the same
# constant to every node, so it cannot change the argmax or the tie set).
# ServiceSpreadingPriority (the 1.0 alias) shares w_spread: the device's
# spread signatures are service-derived only (state.py — RC/RS/StatefulSet
# informers are empty fakes in the simulator, simulator.go:352-366), so the
# alias scores identically to SelectorSpreadPriority and a policy naming
# BOTH sums their weights, matching two host instances' summed scores.
_WEIGHT_FIELDS: Dict[str, str] = {
    "LeastRequestedPriority": "w_least",
    "MostRequestedPriority": "w_most",
    "BalancedResourceAllocation": "w_balanced",
    "NodeAffinityPriority": "w_node_aff",
    "TaintTolerationPriority": "w_taint",
    "NodePreferAvoidPodsPriority": "w_avoid",
    "SelectorSpreadPriority": "w_spread",
    "ServiceSpreadingPriority": "w_spread",
    "InterPodAffinityPriority": "w_interpod",
}
# every priority the 1.10 registry knows now compiles (ImageLocality rides a
# static signature table); custom args route by kind below
COMPILABLE_PRIOS = frozenset(_WEIGHT_FIELDS) | {"EqualPriority",
                                                "ImageLocalityPriority"}

# the DefaultProvider weight set (defaults.go:219-259); policies that omit
# `priorities` inherit it (CreateFromConfig → DefaultProvider keys)
_DEFAULT_WEIGHTS = dict(w_least=1, w_most=0, w_balanced=1, w_node_aff=1,
                        w_taint=1, w_avoid=AVOID_PODS_WEIGHT, w_spread=1,
                        w_interpod=1)


@dataclass
class CompiledPolicy:
    spec: PolicySpec
    # policy HardPodAffinitySymmetricWeight override; None = keep config value
    # (CreateFromConfig treats 0 as unset, providers.py:415-417)
    hard_weight: int = None
    # custom label-presence predicate rows, parallel to spec.label_rows: each
    # (ordering slot name or "" for tail, [(labels, presence), ...] folded
    # into that row)
    label_rows: List[Tuple[str, list]] = field(default_factory=list)
    # custom label priorities: (label, presence, weight)
    label_prios: List[Tuple[str, bool, int]] = field(default_factory=list)
    # ServiceAntiAffinity entries: (node label, weight), parallel to
    # spec.saa_weights
    saa_entries: List[Tuple[str, int]] = field(default_factory=list)
    # ServiceAffinity predicates: one label tuple per entry, in the entry
    # order of PolicySpec.sa_slots / sa_segs
    sa_entries: tuple = ()
    # host-bound features forcing the reference fallback (empty = compilable)
    unsupported: List[str] = field(default_factory=list)
    # preemption victim-selection class, decided at policy-compile time with
    # every workload feature assumed present ("arithmetic" here = device
    # -kernel eligible for EVERY workload; run-time feature flags can still
    # upgrade a "general" set — see classify_preemption_class)
    preemption_class: str = "general"
    preemption_class_reason: str = ""


def compile_policy(policy: Policy) -> CompiledPolicy:
    """Raises PolicyError/KeyError exactly like the host assembly; returns a
    CompiledPolicy whose `unsupported` lists any host-bound feature."""
    validate_policy(policy)
    unsupported: List[str] = []
    if policy.extender_configs:
        unsupported.append("policy extenders (HTTP round-trips mid-filter)")

    # Both registries key plugins by NAME and a later registration under the
    # same name overwrites the earlier one, while the key set dedups
    # (plugins.go RegisterCustomFitPredicate/RegisterCustomPriorityFunction +
    # the {register_...} set comprehension in providers.create_from_config) —
    # so duplicates resolve last-wins here too.
    label_rows: List[Tuple[str, list]] = []
    sa_entries: List[tuple] = []
    sa_slots: List[str] = []
    ports_slots: List[str] = []
    if policy.predicates is None:
        pred_keys = None
    else:
        pred_by_name: Dict[str, tuple] = {}
        for pp in policy.predicates:
            arg = pp.argument
            if arg is not None and arg.service_affinity is not None:
                pred_by_name[pp.name] = (
                    "sa", tuple(arg.service_affinity.labels))
            elif arg is not None and arg.labels_presence is not None:
                pred_by_name[pp.name] = (
                    "label", (tuple(arg.labels_presence.labels),
                              bool(arg.labels_presence.presence)))
            elif pp.name in COMPILABLE_PREDS:
                pred_by_name[pp.name] = ("standard",)
            elif pp.name == _TAIL_PORTS_ALIAS:
                pred_by_name[pp.name] = ("ports",)
            else:
                # plugins.go RegisterCustomFitPredicate's failure, byte-matched
                raise KeyError("Invalid configuration: Predicate type not "
                               f"found for {pp.name}")
        pred_keys = set()
        slotted: Dict[str, list] = {}
        tail_entries: list = []
        sa_found: List[Tuple[str, tuple]] = []
        tail_ports: List[str] = []
        for name, entry in pred_by_name.items():
            if entry[0] == "standard":
                pred_keys.add(name)
            elif entry[0] == "ports":
                tail_ports.append(name)
            elif entry[0] == "sa":
                if name == preds.CHECK_NODE_CONDITION_PRED:
                    unsupported.append("ServiceAffinity predicate replacing "
                                       "the mandatory CheckNodeCondition")
                else:
                    sa_found.append((name, entry[1]))
            elif entry[0] == "label":
                # the host registers the custom under the policy's name: a
                # name appearing in PREDICATES_ORDERING evaluates at that
                # slot (generic_scheduler.py _predicate_key_order), any other
                # name runs after the fixed ordering
                if name == preds.CHECK_NODE_CONDITION_PRED:
                    # would REPLACE the mandatory condition predicate the
                    # device always evaluates — host-bound edge
                    unsupported.append(
                        "label predicate replacing the mandatory "
                        "CheckNodeCondition")
                elif name in preds.PREDICATES_ORDERING:
                    slotted[name] = [entry[1]]
                else:
                    tail_entries.append((name, entry[1]))
            else:
                unsupported.append(entry[1])
        for name in preds.PREDICATES_ORDERING:
            if name in slotted:
                label_rows.append((name, slotted[name]))
        # ServiceAffinity entries under a PREDICATES_ORDERING name evaluate
        # at that slot; every other custom (label-presence row or SA entry)
        # runs after the fixed ordering in the host's ALPHABETICAL name
        # order — each gets its sorted position as slot "tail:<k>". One ROW
        # PER LABEL PREDICATE (not folded): with alwaysCheckAllPredicates
        # each failing predicate contributes its own occurrence of the
        # shared reason string, and the kernel's count-mode histogram sums
        # per-stage firings — folding would collapse them to one.
        sa_found.sort(key=lambda pair: pair[0])
        for name, labels in sa_found:
            if name in preds.PREDICATES_ORDERING:
                sa_entries.append(tuple(labels))
                sa_slots.append(name)
        tail_customs = sorted(
            [(n, "label", e) for n, e in tail_entries]
            + [(n, "sa", tuple(labels)) for n, labels in sa_found
               if n not in preds.PREDICATES_ORDERING]
            + [(n, "ports", None) for n in tail_ports])
        for k, (_n, kind, payload) in enumerate(tail_customs):
            if kind == "label":
                label_rows.append((f"tail:{k}", [payload]))
            elif kind == "ports":
                # the 1.0 PodFitsPorts alias: the port-conflict stage runs
                # AGAIN at its alphabetical tail position (the host evaluates
                # registry keys outside predicates.Ordering() there)
                ports_slots.append(f"tail:{k}")
            else:
                sa_entries.append(payload)
                sa_slots.append(f"tail:{k}")

    weights = dict(_DEFAULT_WEIGHTS)
    label_prios: List[Tuple[str, bool, int]] = []
    saa_entries: List[Tuple[str, int]] = []
    image_weight = 0
    if policy.priorities is not None:
        weights = dict.fromkeys(weights, 0)
        prio_by_name: Dict[str, tuple] = {}
        for pr in policy.priorities:
            arg = pr.argument
            if arg is not None and arg.service_anti_affinity is not None:
                prio_by_name[pr.name] = (
                    "saa", (arg.service_anti_affinity.label, pr.weight))
            elif arg is not None and arg.label_preference is not None:
                prio_by_name[pr.name] = (
                    "label", (arg.label_preference.label,
                              bool(arg.label_preference.presence), pr.weight))
            elif pr.name in _WEIGHT_FIELDS:
                # referencing a pre-registered priority takes the POLICY's
                # weight (plugins.go:302-348 → PriorityConfigFactory.weight)
                prio_by_name[pr.name] = ("weight", _WEIGHT_FIELDS[pr.name],
                                         pr.weight)
            elif pr.name == "ImageLocalityPriority":
                prio_by_name[pr.name] = ("image", pr.weight)
            elif pr.name == "EqualPriority":
                prio_by_name[pr.name] = ("equal",)
            else:
                raise KeyError("Invalid configuration: Priority type not "
                               f"found for {pr.name}")
        for entry in prio_by_name.values():
            if entry[0] == "weight":
                # += not =: two NAMES sharing a field (SelectorSpread +
                # ServiceSpreading aliases) sum like two host instances;
                # same-name duplicates already collapsed last-wins above
                weights[entry[1]] += entry[2]
            elif entry[0] == "label":
                label_prios.append(entry[1])
            elif entry[0] == "image":
                image_weight = entry[1]
            elif entry[0] == "saa":
                saa_entries.append(entry[1])
            elif entry[0] == "unsupported":
                unsupported.append(entry[1])
            # "equal": constant shift; no effect on selection or ties

    # alwaysCheckAllPredicates shapes where one node emits the same reason
    # string more than once (duplicated stage pairs, several label
    # predicates) compile natively: the kernel switches its histogram to
    # count mode — per-string occurrence sums over all failing stages —
    # instead of the bit-per-string OR (VERDICT r3 item 8)
    aca = bool(policy.always_check_all_predicates)
    spec = PolicySpec(
        pred_keys=frozenset(pred_keys) if pred_keys is not None else None,
        label_rows=tuple(slot for slot, _ in label_rows),
        has_label_prio=bool(label_prios),
        w_image=image_weight,
        saa_weights=tuple(w for _, w in saa_entries),
        sa_enabled=bool(sa_entries), sa_slots=tuple(sa_slots),
        sa_segs=tuple(len(e) for e in sa_entries),
        ports_slots=tuple(ports_slots),
        always_check_all=aca,
        **weights)
    hard = (policy.hard_pod_affinity_symmetric_weight
            if policy.hard_pod_affinity_symmetric_weight != 0 else None)
    if hard is not None and (hard < 1 or hard > 100):
        # the same [1, 100] range _create_from_keys enforces host-side
        # (factory.go:1024-1026) — both backends must reject identically
        raise ValueError(f"invalid hardPodAffinitySymmetricWeight: {hard}, "
                         "must be in the range 1-100")
    pclass, pclass_why = classify_preemption_class(
        frozenset(pred_keys) if pred_keys is not None else None,
        has_extenders=bool(policy.extender_configs))
    if pclass == "arithmetic" and sa_entries:
        pclass, pclass_why = ("general", "ServiceAffinity first-matching-pod "
                              "lock is pod-set-dependent")
    return CompiledPolicy(spec=spec, hard_weight=hard,
                          label_rows=label_rows,
                          label_prios=label_prios, saa_entries=saa_entries,
                          sa_entries=tuple(sa_entries),
                          unsupported=unsupported,
                          preemption_class=pclass,
                          preemption_class_reason=pclass_why)


def _label_pred_row(nodes_by_idx: list, entries) -> np.ndarray:
    """Folded per-node pass mask for a list of label-presence predicates
    (predicates.go NewNodeLabelPredicate: every label's existence must equal
    `presence`)."""
    n = len(nodes_by_idx)
    row = np.ones(n, dtype=bool)
    for labels, presence in entries:
        for i, node in enumerate(nodes_by_idx):
            node_labels = node.metadata.labels
            for label in labels:
                if (label in node_labels) != presence:
                    row[i] = False
                    break
    return row


def image_locality_columns(pods, nodes, node_index: Dict[str, int]):
    """(img_id[P] int32, image_score[Si, N] int64): pod container-image
    multisets interned to signature ids, with the ImageLocalityPriority map
    score (image_locality.go thresholds) precomputed per (signature, node).
    Reuses the host map function for exactness."""
    from types import SimpleNamespace

    from tpusim.engine.priorities import image_locality_priority_map

    n = len(node_index)
    by_idx: list = [None] * n
    for node in nodes:
        i = node_index.get(node.name)
        if i is not None:
            by_idx[i] = node

    sig_ids: Dict[tuple, int] = {}
    reps: List = []
    img_id = np.zeros(len(pods), dtype=np.int32)
    for j, pod in enumerate(pods):
        # a multiset: two containers sharing an image each add its size
        sig = tuple(sorted(c.image for c in pod.spec.containers))
        if sig not in sig_ids:
            sig_ids[sig] = len(reps)
            reps.append(pod)
        img_id[j] = sig_ids[sig]

    table = np.zeros((max(len(reps), 1), n), dtype=np.int64)
    for s, rep in enumerate(reps):
        for i, node in enumerate(by_idx):
            info = SimpleNamespace(node=node)
            table[s, i] = image_locality_priority_map(rep, None, info).score
    return img_id, table


def _nodes_by_index(nodes, node_index: Dict[str, int]) -> list:
    by_idx: list = [None] * len(node_index)
    for node in nodes:
        i = node_index.get(node.name)
        if i is not None:
            by_idx[i] = node
    return by_idx


def _label_value_row(by_idx: list, label: str, extra_values=()):
    """Intern one node label's values into an int32 row (0 = absent);
    returns (row[N], number of distinct values + 1, value->id map).
    extra_values are interned too (after the node values) so callers can
    express pod-side pins in the same id space — a pinned value no node
    carries gets a fresh id that matches nothing."""
    row = np.zeros(len(by_idx), dtype=np.int32)
    values: Dict[str, int] = {}
    for i, node in enumerate(by_idx):
        value = node.metadata.labels.get(label)
        if value is None:
            continue
        vid = values.get(value)
        if vid is None:
            vid = len(values) + 1
            values[value] = vid
        row[i] = vid
    for value in extra_values:
        if value not in values:
            values[value] = len(values) + 1
    return row, len(values) + 1, values


def saa_dom_rows(cp: CompiledPolicy, nodes, node_index: Dict[str, int]):
    """(saa_dom [E, N] int32, n_doms int): per-ServiceAntiAffinity-entry
    node label-value domains (0 = label absent; values interned per entry,
    one shared segment count)."""
    by_idx = _nodes_by_index(nodes, node_index)
    e_count = max(len(cp.saa_entries), 1)
    dom = np.zeros((e_count, len(by_idx)), dtype=np.int32)
    n_doms = 1
    for e, (label, _w) in enumerate(cp.saa_entries):
        dom[e], n_values, _ = _label_value_row(by_idx, label)
        n_doms = max(n_doms, n_values)
    return dom, n_doms


def service_affinity_columns(cp: CompiledPolicy, pods, snapshot,
                             node_index: Dict[str, int], saa_defs: list):
    """Static ServiceAffinity state (predicates.py check_service_affinity):

    Returns (sa_self_id[P], sa_pin[Cs, La], sa_val[La, N], sa_lock_init[Fd]).

    The label axis concatenates every entry's label list in PolicySpec
    sa_segs order (one policy may carry several ServiceAffinity predicates;
    each evaluates its own segment as a separate stage). Pod-side pins are
    interned into sa_val's per-label value space (0 = unpinned).

    The plugin pod lister is the scheduler cache (factory.go:166) — ASSIGNED
    pods, seeded in snapshot order then bound pods in bind order — so the
    first matching pod is either a seeded assigned pod (static: its node
    index locks sig f, or -2 when the node is unknowable so nothing ever
    pins) or the first matching pod to BIND, which the kernel locks into the
    carry when that bind happens (-1 until then). The lock — a node index —
    is shared by every entry: it identifies the same first matching pod."""
    labels = [label for entry in cp.sa_entries for label in entry]
    n = len(node_index)
    la = max(len(labels), 1)
    by_idx = _nodes_by_index(snapshot.nodes, node_index)

    # intern pods' pinned values alongside node values, per label
    pinned_values: List[set] = [set() for _ in labels]
    for pod in pods:
        selector = pod.spec.node_selector or {}
        for li, label in enumerate(labels):
            if label in selector:
                pinned_values[li].add(selector[label])
    sa_val = np.zeros((la, n), dtype=np.int32)
    value_maps: List[Dict[str, int]] = [{} for _ in range(la)]
    for li, label in enumerate(labels):
        sa_val[li], _, value_maps[li] = _label_value_row(
            by_idx, label, extra_values=sorted(pinned_values[li]))

    sig_ids: Dict[tuple, int] = {}
    reps: List[tuple] = []
    sa_self_id = np.zeros(len(pods), dtype=np.int32)
    for j, pod in enumerate(pods):
        selector = pod.spec.node_selector or {}
        pins = tuple(sorted((label, selector[label]) for label in set(labels)
                            if label in selector))
        cid = sig_ids.get(pins)
        if cid is None:
            cid = len(reps)
            sig_ids[pins] = cid
            reps.append(pins)
        sa_self_id[j] = cid

    cs = max(len(reps), 1)
    sa_pin = np.zeros((cs, la), dtype=np.int32)
    for c, pins in enumerate(reps):
        pinned = dict(pins)
        for li, label in enumerate(labels):
            if label in pinned:
                sa_pin[c, li] = value_maps[li][pinned[label]]

    lock_init = sa_lock_init_rows(saa_defs, snapshot.pods, node_index)
    return sa_self_id, sa_pin, sa_val, lock_init


def sa_lock_init_rows(saa_defs: list, pods, node_index: Dict[str, int]):
    """sa_lock_init[Fd] int32: per ServiceAffinity-signature first-matching-
    assigned-pod locks (see service_affinity_columns' lister contract).
    `pods` is the snapshot pod iterable in cache order. Split out so the
    stream runtime can re-arm the segment-lock lanes per commit without
    rebuilding the rest of the SA tables (ISSUE 9)."""
    fd = max(len(saa_defs), 1)
    lock_init = np.full(fd, -1, dtype=np.int32)
    for f in range(1, len(saa_defs)):
        ns, sel = saa_defs[f]
        first = next(
            (p for p in pods
             if p.spec.node_name and p.namespace == ns
             and all(p.metadata.labels.get(k) == v for k, v in sel.items())),
            None)
        if first is not None:
            if first.spec.node_name in node_index:
                lock_init[f] = node_index[first.spec.node_name]
            else:
                # assigned to an unknowable node: it stays service_pods[0]
                # forever (assigned order), so nothing ever pins
                lock_init[f] = -2
    return lock_init


def policy_static_rows(cp: CompiledPolicy, nodes,
                       node_index: Dict[str, int]):
    """(label_ok[L, N], label_prio[N]) in compiled node order, rows parallel
    to spec.label_rows. `nodes` is the snapshot node list; node_index the
    compiled order."""
    n = len(node_index)
    by_idx = _nodes_by_index(nodes, node_index)
    if cp.label_rows:
        label_ok = np.stack([_label_pred_row(by_idx, entries)
                             for _, entries in cp.label_rows])
    else:
        label_ok = np.ones((1, n), dtype=bool)
    prio = np.zeros(n, dtype=np.int64)
    for label, presence, weight in cp.label_prios:
        for i, node in enumerate(by_idx):
            exists = label in node.metadata.labels
            if exists == presence:
                prio[i] += weight * MAX_PRIORITY
    return label_ok, prio


@dataclass
class PolicyTables:
    """Host-side policy static tables, bundled for the Pallas fast path.

    Built once per compile by build_policy_tables; plan_fast bakes these
    into the kernel plan and the XLA branch overwrites the trivial Statics
    rows from the same arrays, so both engines see identical inputs."""

    label_ok: np.ndarray         # [L, N] bool  — label-presence pass masks
    label_prio: np.ndarray       # [N] int64    — NodeLabel priority scores
    image_score: np.ndarray      # [Si, N] int64 — ImageLocality table
    has_image: bool              # policy weights ImageLocality
    saa_dom: np.ndarray          # [E, N] int32 — SAA per-entry label domains
    n_saa_doms: int              # shared segment count (incl. absent 0)
    sa_pin: np.ndarray           # [Cs, La] int32 — per-pod-sig SA pins
    sa_val: np.ndarray           # [La, N] int32 — SA node label values
    sa_lock_init: np.ndarray     # [Fd] int32 — first-matching-pod locks


def build_policy_tables(cp: CompiledPolicy, snapshot, pods,
                        compiled, cols) -> PolicyTables:
    """Assemble every policy static table the device engines consume.

    Fills cols.img_id / cols.sa_self_id IN PLACE (per-pod signature columns)
    and returns the node-axis tables. Centralizes what backend.schedule,
    whatif's host-batch prep, and the fast-path planner all need so the two
    device routes can't drift on their inputs."""
    ps = cp.spec
    nodes = snapshot.nodes
    node_index = compiled.node_index
    n = max(len(node_index), 1)
    label_ok, label_prio = policy_static_rows(cp, nodes, node_index)
    has_image = bool(ps.w_image)
    if has_image:
        img_id, image_score = image_locality_columns(pods, nodes, node_index)
        cols.img_id[:] = img_id
    else:
        image_score = np.zeros((1, n), dtype=np.int64)
    saa_dom, n_saa_doms = saa_dom_rows(cp, nodes, node_index)
    if ps.sa_enabled or ps.sa_slots:
        sa_self_id, sa_pin, sa_val, sa_lock_init = service_affinity_columns(
            cp, pods, snapshot, node_index, compiled.groups.saa_defs)
        cols.sa_self_id[:] = sa_self_id
    else:
        sa_pin = np.zeros((1, 1), dtype=np.int32)
        sa_val = np.zeros((1, n), dtype=np.int32)
        sa_lock_init = np.full(
            compiled.groups.saa_rows.shape[0], -1, dtype=np.int32)
    return PolicyTables(label_ok=label_ok, label_prio=label_prio,
                        image_score=image_score, has_image=has_image,
                        saa_dom=saa_dom, n_saa_doms=n_saa_doms,
                        sa_pin=sa_pin, sa_val=sa_val,
                        sa_lock_init=sa_lock_init)


# --------------------------------------------------------------------------
# Policy residency (ISSUE 9): the interning state a resident policy-table
# set was built with, so the stream runtime can (a) remap a new batch's
# per-pod signature columns against the RESIDENT id spaces and (b) recompute
# only the churned nodes' policy columns — both without restaging. Any
# signature or label value outside the resident spaces means the id space
# must grow, which is a table-shape change: the caller restages.
# --------------------------------------------------------------------------


def policy_plan_key(cp: Optional[CompiledPolicy]):
    """Hashable identity of the compiled plan a policy'd session stages.

    PolicySpec alone under-determines the tables (label_rows holds slot
    names, not the label entries; two policies can share a spec yet mask
    different labels), so the key freezes every table-defining input. Two
    equal keys stage byte-identical policy statics for the same cluster;
    a key change is the `policy_plan_change` restage class."""
    if cp is None:
        return None
    return (cp.spec, cp.hard_weight,
            tuple((slot, tuple((tuple(labels), presence)
                               for labels, presence in entries))
                  for slot, entries in cp.label_rows),
            tuple((label, presence, weight)
                  for label, presence, weight in cp.label_prios),
            tuple((label, weight) for label, weight in cp.saa_entries),
            tuple(tuple(entry) for entry in cp.sa_entries))


@dataclass
class PolicyResidency:
    """Interning state captured at restage time (build_policy_residency).

    img_rows/img_reps: container-image multiset signature -> image_score row,
    with the representative pod per row (image_locality_columns first-seen
    order). sa_rows: pod pin signature -> sa_pin row. sa_value_maps /
    saa_value_maps: per-label value -> id interning for sa_val / saa_dom
    (re-derived deterministically from the snapshot, identical to what the
    table builders interned)."""

    img_rows: Dict[tuple, int] = field(default_factory=dict)
    img_reps: List = field(default_factory=list)
    sa_labels: tuple = ()
    sa_rows: Dict[tuple, int] = field(default_factory=dict)
    sa_value_maps: List[Dict[str, int]] = field(default_factory=list)
    saa_value_maps: List[Dict[str, int]] = field(default_factory=list)


def build_policy_residency(cp: CompiledPolicy, snapshot, pods,
                           compiled, ptabs: PolicyTables) -> PolicyResidency:
    """Rebuild the interning maps the ptabs tables were built with.

    Must walk pods/nodes in exactly the order the table builders did so the
    ids line up; the value maps come from calling _label_value_row again
    (deterministic: same snapshot, same extra_values)."""
    node_index = compiled.node_index
    by_idx = _nodes_by_index(snapshot.nodes, node_index)
    res = PolicyResidency()

    if ptabs.has_image:
        for pod in pods:
            sig = tuple(sorted(c.image for c in pod.spec.containers))
            if sig not in res.img_rows:
                res.img_rows[sig] = len(res.img_reps)
                res.img_reps.append(pod)

    ps = cp.spec
    if ps.sa_enabled or ps.sa_slots:
        labels = [label for entry in cp.sa_entries for label in entry]
        res.sa_labels = tuple(labels)
        pinned_values: List[set] = [set() for _ in labels]
        for pod in pods:
            selector = pod.spec.node_selector or {}
            for li, label in enumerate(labels):
                if label in selector:
                    pinned_values[li].add(selector[label])
        res.sa_value_maps = [{} for _ in range(max(len(labels), 1))]
        for li, label in enumerate(labels):
            _, _, res.sa_value_maps[li] = _label_value_row(
                by_idx, label, extra_values=sorted(pinned_values[li]))
        label_set = set(labels)
        for pod in pods:
            selector = pod.spec.node_selector or {}
            pins = tuple(sorted((label, selector[label])
                                for label in label_set if label in selector))
            if pins not in res.sa_rows:
                res.sa_rows[pins] = len(res.sa_rows)

    for label, _w in cp.saa_entries:
        _, _, vmap = _label_value_row(by_idx, label)
        res.saa_value_maps.append(vmap)
    return res


def remap_policy_columns(cp: CompiledPolicy, res: PolicyResidency,
                         pods, cols) -> Optional[str]:
    """Fill cols.img_id / cols.sa_self_id for a NEW batch against the
    RESIDENT id spaces. Returns None on success or a restage-reason string
    when a pod carries a signature the resident tables never interned
    (the table shapes would have to grow)."""
    ps = cp.spec
    if ps.w_image:
        for j, pod in enumerate(pods):
            sig = tuple(sorted(c.image for c in pod.spec.containers))
            row = res.img_rows.get(sig)
            if row is None:
                return "new_signature"
            cols.img_id[j] = row
    if ps.sa_enabled or ps.sa_slots:
        label_set = set(res.sa_labels)
        for j, pod in enumerate(pods):
            selector = pod.spec.node_selector or {}
            pins = tuple(sorted((label, selector[label])
                                for label in label_set if label in selector))
            row = res.sa_rows.get(pins)
            if row is None:
                return "new_signature"
            cols.sa_self_id[j] = row
    return None


def policy_delta_columns(cp: Optional[CompiledPolicy],
                         res: Optional[PolicyResidency],
                         ptabs: Optional[PolicyTables],
                         by_idx: list, idxs, shapes):
    """Recompute the policy statics columns for the churned node indices.

    `by_idx` is the compiled-order node list (post-churn host truth), `idxs`
    the churned indices, `shapes` the resident (L, Si, E, La) leading dims.
    Returns (label_ok[L,U], label_prio[U], image_score[Si,U], saa_dom[E,U],
    sa_val[La,U]) or a restage-reason string when a churned node carries a
    label value outside the resident interning (the domain id space must
    grow, which is a staged-shape property)."""
    from types import SimpleNamespace

    from tpusim.engine.priorities import image_locality_priority_map

    n_l, n_si, n_e, n_la = shapes
    u = len(idxs)
    label_ok = np.ones((n_l, u), dtype=bool)
    label_prio = np.zeros(u, dtype=np.int64)
    image_score = np.zeros((n_si, u), dtype=np.int64)
    saa_dom = np.zeros((n_e, u), dtype=np.int32)
    sa_val = np.zeros((n_la, u), dtype=np.int32)
    if cp is None:
        return label_ok, label_prio, image_score, saa_dom, sa_val

    for r, (_slot, entries) in enumerate(cp.label_rows):
        for k, i in enumerate(idxs):
            node_labels = by_idx[i].metadata.labels
            ok = True
            for labels, presence in entries:
                for label in labels:
                    if (label in node_labels) != presence:
                        ok = False
                        break
                if not ok:
                    break
            label_ok[r, k] = ok
    for label, presence, weight in cp.label_prios:
        for k, i in enumerate(idxs):
            if (label in by_idx[i].metadata.labels) == presence:
                label_prio[k] += weight * MAX_PRIORITY
    if ptabs is not None and ptabs.has_image:
        for s, rep in enumerate(res.img_reps):
            for k, i in enumerate(idxs):
                info = SimpleNamespace(node=by_idx[i])
                image_score[s, k] = image_locality_priority_map(
                    rep, None, info).score
    for e, (label, _w) in enumerate(cp.saa_entries):
        vmap = res.saa_value_maps[e]
        for k, i in enumerate(idxs):
            value = by_idx[i].metadata.labels.get(label)
            if value is None:
                continue
            vid = vmap.get(value)
            if vid is None:
                return "new_signature"
            saa_dom[e, k] = vid
    for li, label in enumerate(res.sa_labels):
        vmap = res.sa_value_maps[li]
        for k, i in enumerate(idxs):
            value = by_idx[i].metadata.labels.get(label)
            if value is None:
                continue
            vid = vmap.get(value)
            if vid is None:
                return "new_signature"
            sa_val[li, k] = vid
    return label_ok, label_prio, image_score, saa_dom, sa_val

"""Command-line entry.

Reference: cmd/app/server.go + cmd/app/options/options.go. Flag surface kept
(--kubeconfig --podspec --algorithmprovider), extended per BASELINE.json with
--backend, plus snapshot sources replacing the live-cluster
List (this environment has no kube apiserver): --snapshot / --nodes / --pods /
--synthetic-nodes.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from tpusim.api.podspec import expand_simulation_pods, load_simulation_pods
from tpusim.api.snapshot import (
    ClusterSnapshot,
    load_nodes_checkpoint,
    load_pods_checkpoint,
    synthetic_cluster,
)
from tpusim.framework.report import (
    cluster_capacity_review_print,
    get_report,
    spec_print,
)
from tpusim.simulator import run_simulation


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpusim",
        description="Cluster-capacity schedule simulation on a TPU-native engine")
    # reference flags (options.go:67-71)
    parser.add_argument("--kubeconfig", default="",
                        help="Path to kubeconfig for a live-cluster snapshot "
                             "(Running pods across all namespaces + all nodes, "
                             "server.go:104-118); CC_INCLUSTER=1 uses the "
                             "in-cluster service-account config instead")
    parser.add_argument("--podspec", default="",
                        help="YAML/JSON file with [{name, pod, num}] entries")
    parser.add_argument("--algorithmprovider", default="DefaultProvider",
                        help="DefaultProvider | ClusterAutoscalerProvider | "
                             "TalkintDataProvider")
    # AlgorithmSource.Policy analog (simulator.go:383-424): policy from a
    # serialized file, or from a ConfigMap object saved as JSON/YAML
    parser.add_argument("--scheduler-policy-file", default="",
                        help="schedulerapi/v1 Policy file (kind: Policy) "
                             "overriding the algorithm provider")
    parser.add_argument("--scheduler-policy-configmap-file", default="",
                        help="ConfigMap object (JSON/YAML) carrying the policy "
                             "under data['policy.cfg']")
    parser.add_argument("--scheduler-policy-configmap", default="",
                        help="Name of a ConfigMap to fetch the policy from the "
                             "live cluster API (simulator.go:402-415); needs "
                             "--kubeconfig or CC_INCLUSTER")
    parser.add_argument("--scheduler-policy-configmap-namespace",
                        default="kube-system",
                        help="Namespace of --scheduler-policy-configmap")
    parser.add_argument("--namespace", default="default",
                        help="Namespace stamped onto simulated pods")
    # new flags (BASELINE.json)
    parser.add_argument("--backend", default="auto",
                        choices=["auto", "reference", "jax"],
                        help="Scheduling engine: jax (TPU batched), reference "
                             "(pure-Python parity loop), or auto (default — "
                             "workloads under TPUSIM_AUTO_THRESHOLD pods x "
                             "nodes [100k] run on the host engine, avoiding "
                             "device-dispatch latency on tiny runs; larger "
                             "ones use the jax engine)")
    # snapshot sources
    parser.add_argument("--snapshot", default="",
                        help="Combined ClusterSnapshot JSON ({nodes, pods, services})")
    parser.add_argument("--nodes", default="", help="nodes.json checkpoint")
    parser.add_argument("--pods", default="", help="pods.json checkpoint (Running pods)")
    parser.add_argument("--synthetic-nodes", type=int, default=0,
                        help="Generate N homogeneous synthetic nodes")
    parser.add_argument("--synthetic-milli-cpu", type=int, default=4000)
    parser.add_argument("--synthetic-memory", type=int, default=16 * 1024**3)
    parser.add_argument("--event-log", default="",
                        help="Watch-event log (JSON lines, the WatchBuffer "
                             "wire frames: {type: Added|Modified|Deleted, "
                             "object: {kind: Pod|Node|Service, ...}}) "
                             "replayed on top of the snapshot before "
                             "scheduling; on the jax backend the replay "
                             "drives incremental column-cache updates")
    parser.add_argument("--what-if", default="",
                        help="Manifest JSON [{snapshot, podspec}, ...]: run "
                             "all scenarios as ONE batched device program "
                             "(jax backend; snapshot axis shardable over a "
                             "mesh). Ignores --podspec/--snapshot.")
    parser.add_argument("--mesh", default="",
                        help="What-if device mesh 'SNAPxNODE' (e.g. 2x4): "
                             "scenarios data-parallel over SNAP devices, "
                             "node columns sharded over NODE devices with "
                             "GSPMD collectives (jaxe/sharding.py). Needs "
                             "SNAP*NODE visible jax devices; default "
                             "single-device.")
    parser.add_argument("--chaos-plan", default="",
                        help="Fault-plan JSON (tpusim.chaos schema: churn/"
                             "fabric/device sections) injected into the run; "
                             "the summary line reports invariant violations "
                             "and a non-empty audit exits 1")
    parser.add_argument("--chaos-seed", type=int, default=None,
                        help="Generate a seeded adversarial fault plan "
                             "against the loaded workload instead of (or "
                             "overriding the seed of) --chaos-plan; "
                             "deterministic per seed")
    parser.add_argument("--enable-pod-priority", action="store_true",
                        help="Enable the PodPriority feature gate (preemption). "
                             "On the jax backend this runs the host-device "
                             "hybrid: device scan + exact host Preempt pipeline")
    parser.add_argument("--enable-volume-scheduling", action="store_true",
                        help="Enable the VolumeScheduling feature gate "
                             "(CheckVolumeBinding + delayed PV binding); "
                             "reference backend only")
    parser.add_argument("--feature-gates", default="",
                        help="Comma-separated key=bool feature gates "
                             "(kube --feature-gates format): "
                             "TaintNodesByCondition, "
                             "ResourceLimitsPriorityFunction (registry "
                             "surgery, defaults.go:181-205), plus "
                             "PodPriority / VolumeScheduling as aliases "
                             "for the dedicated flags")
    parser.add_argument("--platform", default=os.environ.get("TPUSIM_PLATFORM", ""),
                        help="Pin the jax platform (e.g. cpu) — needed because "
                             "the TPU plugin can override JAX_PLATFORMS; default "
                             "auto (TPUSIM_PLATFORM env)")
    parser.add_argument("--print-requirements", action="store_true",
                        help="Also print per-pod requirement spec")
    parser.add_argument("--quiet", action="store_true",
                        help="Only print the summary counts and timing")
    parser.add_argument("--v", type=int, default=0, dest="verbosity",
                        help="Log verbosity (glog analog). >=2 surfaces the "
                             "tpusim.* loggers on stderr (slow-schedule "
                             "traces, backend routing); >=5 enables DEBUG "
                             "plus the per-node score dump: every priority's "
                             "score per node and the post-extender aggregate "
                             "(generic_scheduler.go:618-622,670-674)")
    parser.add_argument("--trace-out", default="",
                        help="Write the flight-recorder timeline after the "
                             "run: Chrome trace_event JSON (Perfetto-"
                             "loadable) by default, or a raw span stream "
                             "with a .jsonl extension")
    parser.add_argument("--metrics-out", default="",
                        help="Write the scheduler metrics registry in "
                             "Prometheus text exposition format after the "
                             "run")
    add_explain_flags(parser)
    parser.add_argument("--analytics-out", default="",
                        help="Append cluster-analytics samples (reduced "
                             "on-device from the final scan carry) to this "
                             "JSONL file")
    return parser


def add_explain_flags(parser: argparse.ArgumentParser) -> None:
    """The decision-provenance flag pair, shared by the one-shot, serve,
    and stream entrypoints."""
    parser.add_argument("--explain-out", default="",
                        help="Append decision-provenance records (one JSON "
                             "object per pod decision: why placed / why "
                             "not, with failure text byte-identical to the "
                             "host FitError) to this JSONL file; query it "
                             "with `tpusim explain FILE`")
    parser.add_argument("--explain-top-k", type=int, default=0,
                        help="Also record the top-K candidate nodes per "
                             "placed pod with each one's per-priority score "
                             "breakdown (jax backend one-shot runs; routes "
                             "through the XLA scan). 0 = failures-only "
                             "provenance")


def add_obs_flags(parser: argparse.ArgumentParser) -> None:
    """The live-telemetry flag pair, shared by serve and stream."""
    parser.add_argument("--listen", default="",
                        help="Serve the live telemetry plane on HOST:PORT "
                             "(also ':PORT' or 'PORT'): GET /metrics "
                             "(Prometheus/OpenMetrics text), /healthz "
                             "(JSON liveness; 503 while the dispatch "
                             "breaker is open), /debug/provenance (recent "
                             "decision records)")
    parser.add_argument("--slo-target-ms", type=float, default=0.0,
                        help="Arm the per-cycle latency SLO at this target: "
                             "publishes tpusim_slo_cycles_total{verdict} "
                             "and tpusim_slo_burn_rate, and drops "
                             "slo:burn_start/_end instants on the flight "
                             "recorder at burn-rate crossings (0: off)")
    parser.add_argument("--analytics-out", default="",
                        help="Append cluster-analytics samples (one JSON "
                             "object per cycle/dispatch: per-resource "
                             "utilization/fragmentation, feasible-node "
                             "count, top-k hot/cold nodes, reduced "
                             "on-device) to this JSONL file")


def _arm_observability(args):
    """Install the provenance log, SLO tracker, and telemetry endpoint the
    flags ask for; returns a teardown callable (flushes --explain-out)."""
    from tpusim.obs import analytics, provenance, slo

    server = None
    listen = getattr(args, "listen", "")
    explain_out = getattr(args, "explain_out", "")
    explain_top_k = max(0, getattr(args, "explain_top_k", 0))
    slo_target_ms = getattr(args, "slo_target_ms", 0.0)
    analytics_out = getattr(args, "analytics_out", "")
    # --listen without --explain-out still arms an in-memory ring so
    # /debug/provenance serves the recent decisions
    if explain_out or explain_top_k or listen:
        provenance.install(provenance.ProvenanceLog(
            top_k=explain_top_k, path=explain_out or None))
    # likewise --listen alone arms the analytics ring so /analytics (and
    # `tpusim top` against this endpoint) serves live samples
    if analytics_out or listen:
        analytics.install(analytics.ClusterAnalytics(
            path=analytics_out or None))
    if slo_target_ms and slo_target_ms > 0:
        slo.install(slo.SloTracker(slo_target_ms * 1000.0))
    if listen:
        from tpusim.obs.server import start_server

        server = start_server(listen)
        host, port = server.address
        print(f"telemetry: listening on http://{host}:{port} "
              "(/metrics /healthz /debug/provenance /analytics)",
              file=sys.stderr)

    def teardown() -> None:
        if provenance.get_log() is not None:
            provenance.uninstall()   # close() flushes --explain-out
        if analytics.get() is not None:
            # pin the final sample into the tpusim_cluster_* gauges so a
            # post-teardown --metrics-out dump carries it
            analytics.refresh_gauges()
            analytics.uninstall()    # close() flushes --analytics-out
        if slo.get_tracker() is not None:
            slo.uninstall()
        if server is not None:
            server.stop()

    return teardown


def load_snapshot(args) -> ClusterSnapshot:
    if args.kubeconfig or os.environ.get("CC_INCLUSTER"):
        if args.snapshot or args.nodes or args.pods or args.synthetic_nodes:
            raise ValueError(
                "--kubeconfig/CC_INCLUSTER conflicts with "
                "--snapshot/--nodes/--pods/--synthetic-nodes; pick one "
                "snapshot source")
        # the reference's only real network I/O: the initial checkpoint
        # (server.go:75-118); its Namespace field is never flag-bound, so the
        # pod list always spans all namespaces — --namespace here only stamps
        # the simulated pods
        from tpusim.api.kubeclient import snapshot_from_cluster

        return snapshot_from_cluster(kubeconfig=args.kubeconfig)
    if args.snapshot:
        return ClusterSnapshot.load(args.snapshot)
    snapshot = ClusterSnapshot()
    if args.nodes:
        snapshot.nodes = load_nodes_checkpoint(args.nodes)
    elif args.synthetic_nodes:
        snapshot.nodes = synthetic_cluster(
            args.synthetic_nodes, milli_cpu=args.synthetic_milli_cpu,
            memory=args.synthetic_memory).nodes
    if args.pods:
        snapshot.pods = load_pods_checkpoint(args.pods)
    return snapshot


def load_policy_from_args(args):
    """(policy | None, error string | None) from the three policy sources:
    serialized Policy file, ConfigMap-object file, or a live ConfigMap fetched
    from the cluster API (simulator.go:383-424)."""
    live_name = getattr(args, "scheduler_policy_configmap", "")
    if not (args.scheduler_policy_file or args.scheduler_policy_configmap_file
            or live_name):
        return None, None
    from tpusim.engine.policy import (
        PolicyError,
        load_policy_configmap_file,
        load_policy_file,
        policy_from_configmap,
    )
    try:
        if args.scheduler_policy_file:
            policy = load_policy_file(args.scheduler_policy_file)
        elif args.scheduler_policy_configmap_file:
            policy = load_policy_configmap_file(
                args.scheduler_policy_configmap_file)
        else:
            # live source: ConfigMaps(ns).Get(name) through the kube client
            # (simulator.go:402-406)
            if not (args.kubeconfig or os.environ.get("CC_INCLUSTER")):
                return None, ("--scheduler-policy-configmap needs a cluster "
                              "connection (--kubeconfig or CC_INCLUSTER)")
            from tpusim.api.kubeclient import (
                KubeClient,
                in_cluster_config,
                load_kubeconfig,
            )
            config = (load_kubeconfig(args.kubeconfig) if args.kubeconfig
                      else in_cluster_config())
            try:
                client = KubeClient(config)
            finally:
                config.cleanup()
            ns = args.scheduler_policy_configmap_namespace
            try:
                obj = client.get_configmap(ns, live_name)
            except OSError as exc:
                return None, (f"couldn't get policy config map "
                              f"{ns}/{live_name}: {exc}")
            policy = policy_from_configmap(obj)
    except (OSError, PolicyError) as exc:
        return None, f"invalid scheduler policy: {exc}"
    return policy, None


def run_what_if_cli(args) -> int:
    """Batched multi-snapshot mode (BASELINE.json config 5)."""
    import json

    from tpusim.jaxe import ensure_responsive_platform
    from tpusim.jaxe.whatif import run_what_if

    # a wedged accelerator tunnel must degrade to CPU, not hang the dispatch
    ensure_responsive_platform()
    if args.verbosity >= 5:
        print("note: the per-node score dump (--v 5) is produced by the "
              "host engine; --what-if always runs the batched device "
              "program and emits no dump.", file=sys.stderr)

    try:
        with open(args.what_if) as f:
            manifest = json.load(f)
        if not isinstance(manifest, list) or not manifest:
            raise ValueError("manifest must be a non-empty JSON list")
        scenarios = []
        for entry in manifest:
            snapshot = ClusterSnapshot.load(entry["snapshot"])
            sim_pods = load_simulation_pods(entry["podspec"])
            pods = expand_simulation_pods(sim_pods, namespace=args.namespace)
            # match run_simulation's LIFO feed order
            scenarios.append((snapshot, list(reversed(pods))))
    except (OSError, KeyError, TypeError, ValueError) as exc:
        print(f"error: invalid what-if manifest: {exc}", file=sys.stderr)
        return 2

    policy, policy_err = load_policy_from_args(args)
    if policy_err:
        print(f"error: {policy_err}", file=sys.stderr)
        return 2

    mesh = None
    if args.mesh:
        import jax

        from tpusim.jaxe.sharding import make_mesh

        try:
            snap_s, _, node_s = args.mesh.lower().partition("x")
            snap, node = int(snap_s), int(node_s)
            if snap < 1 or node < 1:
                raise ValueError
        except ValueError:
            print(f"error: --mesh {args.mesh!r}: want 'SNAPxNODE', e.g. 2x4",
                  file=sys.stderr)
            return 2
        have = len(jax.devices())
        if snap * node > have:
            print(f"error: --mesh {args.mesh} needs {snap * node} devices, "
                  f"{have} visible", file=sys.stderr)
            return 2
        mesh = make_mesh(snap * node, snap=snap)

    start = time.perf_counter()
    try:
        results = run_what_if(scenarios, provider=args.algorithmprovider,
                              policy=policy, mesh=mesh)
    except (KeyError, ValueError, NotImplementedError) as exc:
        # KeyError: unknown provider/plugin name; ValueError incl. PolicyError
        # from compile_policy's validation — same contract as the single-run
        # path's build-time error arm
        print(f"error: {exc}", file=sys.stderr)
        return 2
    elapsed = time.perf_counter() - start
    total = sum(r.total for r in results)
    for i, result in enumerate(results):
        print(f"scenario {i}: {result.scheduled} scheduled, "
              f"{result.unschedulable} unschedulable")
    rate = total / elapsed if elapsed > 0 else 0.0
    print(f"\n{len(results)} scenarios, {total} pods in one batched dispatch "
          f"[{elapsed:.3f}s, {rate:.0f} pods/s]")
    return 0


def build_serve_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpusim serve",
        description="Scenario fleet: run the what-if capacity service over "
                    "a snapshot and drive it with a synthetic request load "
                    "(tpusim/serve; in-process — no network listener)")
    parser.add_argument("--snapshot", default="",
                        help="Combined ClusterSnapshot JSON ({nodes, pods})")
    parser.add_argument("--nodes", default="", help="nodes.json checkpoint")
    parser.add_argument("--synthetic-nodes", type=int, default=0,
                        help="Generate N homogeneous synthetic nodes")
    parser.add_argument("--synthetic-milli-cpu", type=int, default=4000)
    parser.add_argument("--synthetic-memory", type=int, default=16 * 1024**3)
    parser.add_argument("--podspec", required=True,
                        help="YAML/JSON [{name, pod, num}] entries: the pod "
                             "pool the load generator draws request "
                             "workloads from")
    parser.add_argument("--algorithmprovider", default="DefaultProvider")
    parser.add_argument("--scheduler-policy-file", default="",
                        help="schedulerapi/v1 Policy file applied to every "
                             "request")
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--requests", type=int, default=32,
                        help="Synthetic what-if requests to generate")
    parser.add_argument("--seed", type=int, default=0,
                        help="Load-generator seed (request sizes)")
    parser.add_argument("--bucket-size", type=int, default=4,
                        help="Scenarios per dispatched device program")
    parser.add_argument("--flush-after-ms", type=float, default=50.0,
                        help="Deadline before a partial bucket dispatches "
                             "ghost-padded")
    parser.add_argument("--deadline-ms", type=float, default=0.0,
                        help="Fleet-wide request deadline: a request older "
                             "than this at staging or bucket time is "
                             "rejected REJECT_DEADLINE instead of run "
                             "(0: no deadline)")
    parser.add_argument("--chaos-plan", default="",
                        help="Fault-plan JSON, device section only: scripted "
                             "dispatch faults behind the serve retry + "
                             "circuit-breaker + host-fallback path")
    parser.add_argument("--max-queue", type=int, default=256,
                        help="Admission queue bound (backpressure)")
    parser.add_argument("--warm-repeats", type=int, default=1,
                        help="Extra passes over the same request set: repeat "
                             "traffic must ride the warm-executable and "
                             "device-batch caches")
    parser.add_argument("--mesh", default="",
                        help="Scenario mesh 'SCENARIOxNODE' (e.g. 8x1) or "
                             "just 'SCENARIO': shard each bucket over the "
                             "mesh's scenario axis with shard_map "
                             "(make_scenario_mesh); bucket size must divide "
                             "over it")
    parser.add_argument("--attach-stream", action="store_true",
                        help="Live-twin serving (ISSUE 19): hold the cluster "
                             "device-resident in a StreamSession, warm it "
                             "with a few churn cycles, and answer requests "
                             "through copy-on-write overlay queries on the "
                             "resident carry — zero per-request staging; "
                             "the staged pipeline stays armed as fallback")
    parser.add_argument("--stream-cycles", type=int, default=4,
                        help="Churn warm-up cycles for --attach-stream")
    parser.add_argument("--stream-arrivals", type=int, default=16,
                        help="Arrivals per --attach-stream warm-up cycle")
    parser.add_argument("--platform",
                        default=os.environ.get("TPUSIM_PLATFORM", ""))
    parser.add_argument("--quiet", action="store_true",
                        help="Only print the summary lines")
    parser.add_argument("--metrics-out", default="",
                        help="Write the tpusim_serve_* metric families "
                             "(Prometheus text format) after the run")
    parser.add_argument("--trace-out", default="",
                        help="Write the serve: span timeline (Chrome trace "
                             "JSON, or .jsonl for raw spans)")
    add_obs_flags(parser)
    add_explain_flags(parser)
    return parser


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def serve_cli(argv) -> int:
    """`tpusim serve`: stand up a ScenarioFleet and load-generate against it."""
    import random

    args = build_serve_parser().parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
        os.environ["TPUSIM_PROBE"] = "0"

    from tpusim.jaxe import ensure_responsive_platform

    ensure_responsive_platform()

    # snapshot source (load_snapshot's flag subset; no live cluster, no
    # running-pods checkpoint — the fleet schedules synthetic pods only)
    try:
        if args.snapshot:
            snapshot = ClusterSnapshot.load(args.snapshot)
        elif args.nodes:
            snapshot = ClusterSnapshot(nodes=load_nodes_checkpoint(args.nodes))
        elif args.synthetic_nodes:
            snapshot = synthetic_cluster(
                args.synthetic_nodes, milli_cpu=args.synthetic_milli_cpu,
                memory=args.synthetic_memory)
        else:
            print("error: no cluster nodes; pass --snapshot, --nodes, or "
                  "--synthetic-nodes", file=sys.stderr)
            return 2
        sim_pods = load_simulation_pods(args.podspec)
    except (OSError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    pool = expand_simulation_pods(sim_pods, namespace=args.namespace)
    if not pool:
        print("error: podspec expands to zero pods", file=sys.stderr)
        return 2

    policy = None
    if args.scheduler_policy_file:
        from tpusim.engine.policy import PolicyError, load_policy_file

        try:
            policy = load_policy_file(args.scheduler_policy_file)
        except (OSError, PolicyError) as exc:
            print(f"error: invalid scheduler policy: {exc}", file=sys.stderr)
            return 2

    mesh = None
    if args.mesh:
        import jax

        from tpusim.jaxe.sharding import make_scenario_mesh

        try:
            scen_s, _, node_s = args.mesh.lower().partition("x")
            scen, node = int(scen_s), int(node_s or 1)
            if scen < 1 or node < 1:
                raise ValueError
        except ValueError:
            print(f"error: --mesh {args.mesh!r}: want 'SCENARIOxNODE' "
                  "(e.g. 8x1) or 'SCENARIO'", file=sys.stderr)
            return 2
        have = len(jax.devices())
        if scen * node > have:
            print(f"error: --mesh {args.mesh} needs {scen * node} devices, "
                  f"{have} visible", file=sys.stderr)
            return 2
        mesh = make_scenario_mesh(scen * node, scenario=scen)

    breaker = None
    if args.chaos_plan:
        from tpusim.chaos import load_plan
        from tpusim.chaos.plan import PlanError
        from tpusim.jaxe.backend import install_chaos

        try:
            chaos_plan = load_plan(args.chaos_plan)
        except (OSError, PlanError, ValueError) as exc:
            print(f"error: --chaos-plan: {exc}", file=sys.stderr)
            return 2
        breaker = install_chaos(chaos_plan.device)

    recorder = None
    if args.trace_out:
        from tpusim.obs import recorder as flight

        recorder = flight.install(
            flight.FlightRecorder(process_name="tpusim-serve"))

    from tpusim.serve import ScenarioFleet, WhatIfRequest

    try:
        fleet = ScenarioFleet(provider=args.algorithmprovider,
                              bucket_size=args.bucket_size,
                              flush_after_s=args.flush_after_ms / 1000.0,
                              max_queue=args.max_queue, mesh=mesh,
                              deadline_s=(args.deadline_ms / 1000.0
                                          if args.deadline_ms > 0 else None))
    except (KeyError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    ref = "base"
    if args.attach_stream:
        # live-twin serving: the fleet answers against a device-resident
        # StreamSession's carry via overlay queries instead of staging a
        # fresh device picture per request (ISSUE 19). Fresh object
        # graphs per consumer — the twin and the churn generator must
        # never share mutable nodes with each other or the pod pool.
        from tpusim.stream import ChurnLoadGen, StreamSession

        twin_snap = ClusterSnapshot.from_obj(snapshot.to_obj())
        session = StreamSession(twin_snap, provider=args.algorithmprovider,
                                policy=policy)
        sgen = ChurnLoadGen(ClusterSnapshot.from_obj(snapshot.to_obj()),
                            seed=args.seed, arrivals=args.stream_arrivals,
                            evict_fraction=0.25)
        for c in range(max(1, args.stream_cycles)):
            session.apply_events(sgen.events(c))
            sgen.note_bound(session.schedule(sgen.batch()))
        fleet.attach_stream(session, ref="live")
        ref = "live"
        if not args.quiet:
            print(f"live twin: {max(1, args.stream_cycles)} warm-up churn "
                  f"cycles over {len(twin_snap.nodes)} nodes; overlay path "
                  "armed (staged fallback behind it)", file=sys.stderr)
    else:
        fleet.register_snapshot("base", snapshot)

    # the load: random-size what-if queries drawn from the pod pool, each
    # cache-keyed so warm repeats exercise the staged + device-batch caches
    rng = random.Random(args.seed)
    sizes = [rng.randint(1, len(pool)) for _ in range(args.requests)]
    make_load = lambda: [  # noqa: E731
        WhatIfRequest(pods=pool[:n], snapshot_ref=ref, policy=policy,
                      cache_key=f"load-{i}-{n}")
        for i, n in enumerate(sizes)]

    obs_teardown = _arm_observability(args)
    fleet.start()
    try:
        passes = []  # (label, elapsed, responses)
        for rep in range(1 + max(0, args.warm_repeats)):
            label = "cold" if rep == 0 else f"warm {rep}"
            start = time.perf_counter()
            futures = [fleet.submit(r) for r in make_load()]
            responses = [f.result(timeout=600) for f in futures]
            passes.append((label, time.perf_counter() - start, responses))
    finally:
        fleet.stop()
        obs_teardown()
        if breaker is not None:
            from tpusim.jaxe.backend import uninstall_chaos

            uninstall_chaos()

    stats = fleet.executor.stats
    exit_code = 0
    for label, elapsed, responses in passes:
        ok = [r for r in responses if r.ok]
        rejected = [r for r in responses if r.rejected is not None]
        errors = [r for r in responses if r.error and r.rejected is None]
        lat = sorted(r.latency_s for r in ok)
        rate = len(responses) / elapsed if elapsed > 0 else 0.0
        hits = sum(1 for r in ok if r.compile_cache_hit)
        degraded = sum(1 for r in ok if r.degraded)
        print(f"{label}: {len(ok)}/{len(responses)} ok "
              f"({len(rejected)} rejected, {len(errors)} failed"
              + (f", {degraded} degraded" if degraded else "") + "), "
              f"{rate:.1f} scenarios/s, latency p50/p90/max "
              f"{_percentile(lat, 0.5) * 1e3:.1f}/"
              f"{_percentile(lat, 0.9) * 1e3:.1f}/"
              f"{(lat[-1] if lat else 0.0) * 1e3:.1f} ms, "
              f"compile_cache_hit {hits}/{len(ok)}")
        if not args.quiet:
            for r in rejected[:5]:
                print(f"  rejected {r.request_id}: [{r.rejected}] {r.error}",
                      file=sys.stderr)
            for r in errors[:5]:
                print(f"  failed {r.request_id}: {r.error}", file=sys.stderr)
        if errors:
            exit_code = 1
    print(f"fleet: {stats['dispatches']} dispatches "
          f"({stats['warm_hits']} warm, {stats['device_batch_hits']} "
          f"device-resident), {stats['traces']} program traces, "
          f"{stats['staged_hits']} staged-cache hits"
          + (f", mesh {mesh.shape['scenario']}x{mesh.shape['node']}"
             if mesh is not None else ""))
    if args.attach_stream:
        print(f"overlay: {stats['overlay_hits']} served from the resident "
              f"twin, {stats['overlay_fallbacks']} staged fallbacks")

    if recorder is not None:
        from tpusim.obs import recorder as flight

        flight.uninstall()
        try:
            recorder.write(args.trace_out)
        except OSError as exc:
            print(f"error: failed to write trace: {exc}", file=sys.stderr)
            return 2
        if not args.quiet:
            print(f"trace: {args.trace_out} ({len(recorder.events)} events)",
                  file=sys.stderr)
    if args.metrics_out:
        try:
            _write_metrics(args.metrics_out)
        except OSError as exc:
            print(f"error: failed to write metrics: {exc}", file=sys.stderr)
            return 2
    return exit_code


def _write_metrics(path: str) -> None:
    """Dump the registry in Prometheus text exposition format (the scrape
    body the reference never served; framework/metrics.py docstring)."""
    from tpusim.framework.metrics import register
    from tpusim.obs import analytics

    # fold the latest analytics sample + HBM sources into the gauges,
    # exactly like a live /metrics scrape does
    analytics.refresh_gauges()
    with open(path, "w") as f:
        f.write(register().expose())


def build_stream_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpusim stream",
        description="Streaming runtime: hold the compiled cluster resident "
                    "on device and drive it with seeded churn — arrivals, "
                    "evictions, node flaps (tpusim/stream). Warm cycles "
                    "scatter-commit the watch delta instead of re-staging "
                    "the cluster")
    parser.add_argument("--snapshot", default="",
                        help="Combined ClusterSnapshot JSON ({nodes, pods})")
    parser.add_argument("--synthetic-nodes", type=int, default=64,
                        help="Generate N homogeneous synthetic nodes "
                             "(ignored with --snapshot)")
    parser.add_argument("--synthetic-milli-cpu", type=int, default=4000)
    parser.add_argument("--synthetic-memory", type=int, default=16 * 1024**3)
    parser.add_argument("--cycles", type=int, default=50,
                        help="Scheduling cycles to run")
    parser.add_argument("--arrivals", type=int, default=32,
                        help="Fresh pod arrivals per cycle")
    parser.add_argument("--evict-fraction", type=float, default=0.25,
                        help="Fraction of the arrival batch size evicted "
                             "from the bound population per cycle (the "
                             "O(delta) scatter load)")
    parser.add_argument("--flap-every", type=int, default=0,
                        help="Cordon+restore a random node every k-th cycle "
                             "(structural events: forces classified "
                             "restages; 0 = never)")
    parser.add_argument("--label-churn", type=int, default=0,
                        help="Rewrite N random nodes' labels per cycle "
                             "(label-only churn: absorbed by the statics "
                             "scatter, zero restages under a fixed policy "
                             "plan)")
    parser.add_argument("--taint-churn", type=int, default=0,
                        help="Toggle a NoSchedule taint on N random nodes "
                             "per cycle (taint-only churn: scatter path, "
                             "no restage)")
    parser.add_argument("--gang-size", type=int, default=0,
                        help="Members per generated pod group (tpusim/gang: "
                             "all-or-nothing admission with rank-aware "
                             "packing; 0 = no gangs)")
    parser.add_argument("--gang-count", type=int, default=0,
                        help="Pod groups appended to each cycle's arrivals "
                             "(requires --gang-size)")
    parser.add_argument("--seed", type=int, default=0,
                        help="Load-generator seed")
    parser.add_argument("--algorithmprovider", default="DefaultProvider")
    parser.add_argument("--policy-file", default="",
                        help="Scheduler policy JSON (kube-scheduler "
                             "--policy-config-file shape); the compiled "
                             "plan stays device-resident across cycles "
                             "(stream v2)")
    parser.add_argument("--pipeline", action="store_true",
                        help="Pipelined cycles: dispatch cycle N on device, "
                             "decode cycle N-1's placements while it runs "
                             "(identical placements, one cycle of latency)")
    parser.add_argument("--always-restage", action="store_true",
                        help="Disable the O(delta) fast path: full compile + "
                             "re-stage every cycle (the comparison arm; "
                             "placements are identical)")
    parser.add_argument("--verify", action="store_true",
                        help="Cross-check every cycle against a fresh-"
                             "compile JaxBackend dispatch (placement_hash "
                             "byte-parity)")
    parser.add_argument("--chaos-plan", default="",
                        help="Fault-plan JSON: device section plus "
                             "process_crash churn events (other churn/"
                             "fabric faults are the load generator's job)")
    parser.add_argument("--checkpoint-dir", default="",
                        help="Durability directory: every committed watch "
                             "delta and placement appends to a WAL here, "
                             "with periodic device-state checkpoints "
                             "(stream.persist)")
    parser.add_argument("--checkpoint-every", type=int, default=10,
                        help="Cycles between checkpoints (0: genesis "
                             "checkpoint only, WAL replay covers the rest)")
    parser.add_argument("--recover", action="store_true",
                        help="Recover from --checkpoint-dir (checkpoint + "
                             "WAL tail replay) and resume the interrupted "
                             "run; the fold chain proves placement parity "
                             "with the uninterrupted run")
    parser.add_argument("--fsync-every", type=int, default=0,
                        help="fsync the WAL every N appends (0: flush-only "
                             "durability); the mode is stamped into every "
                             "checkpoint manifest")
    parser.add_argument("--replicate-to", default="",
                        help="HOST:PORT of a listening `tpusim follow` "
                             "standby: ship every WAL record + checkpoint "
                             "manifest over the replication protocol "
                             "(stream.replicate) and drain the acks before "
                             "exiting; requires --checkpoint-dir")
    parser.add_argument("--whatif-every", type=int, default=0,
                        help="Serve a live what-if query against the "
                             "device-resident twin every N cycles via a "
                             "copy-on-write overlay (mark -> scan -> roll "
                             "back; the churn chain is byte-unchanged); "
                             "0 = no queries (ISSUE 19)")
    parser.add_argument("--whatif-pods", type=int, default=4,
                        help="Scenario pods per live what-if query")
    parser.add_argument("--platform",
                        default=os.environ.get("TPUSIM_PLATFORM", ""))
    parser.add_argument("--json", action="store_true",
                        help="Print the full summary dict as JSON")
    parser.add_argument("--metrics-out", default="",
                        help="Write the metric families (Prometheus text "
                             "format) after the run — includes "
                             "tpusim_stream_restage_total{reason} and "
                             "tpusim_stream_cycles_total{path}")
    parser.add_argument("--trace-out", default="",
                        help="Write the stream span timeline (Chrome trace "
                             "JSON, or .jsonl for raw spans)")
    add_obs_flags(parser)
    add_explain_flags(parser)
    return parser


def stream_cli(argv) -> int:
    """`tpusim stream`: churn load against the device-resident runtime."""
    args = build_stream_parser().parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
        os.environ["TPUSIM_PROBE"] = "0"

    snapshot = None
    chaos_plan = None
    policy = None
    try:
        if args.snapshot:
            snapshot = ClusterSnapshot.load(args.snapshot)
        if args.policy_file:
            from tpusim.engine.policy import load_policy_file

            policy = load_policy_file(args.policy_file)
        if args.chaos_plan:
            from tpusim.chaos import load_plan
            from tpusim.chaos.plan import PlanError

            try:
                chaos_plan = load_plan(args.chaos_plan)
            except PlanError as exc:
                print(f"error: --chaos-plan: {exc}", file=sys.stderr)
                return 2
    except (OSError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    recorder = None
    if args.trace_out:
        from tpusim.obs import recorder as flight

        recorder = flight.install(
            flight.FlightRecorder(process_name="tpusim-stream"))

    replicate_to = None
    if args.replicate_to:
        from tpusim.obs.server import parse_listen

        if not args.checkpoint_dir:
            print("error: --replicate-to ships the WAL; pass "
                  "--checkpoint-dir", file=sys.stderr)
            return 2
        try:
            replicate_to = parse_listen(args.replicate_to)
        except ValueError:
            print(f"error: --replicate-to {args.replicate_to!r}: want "
                  "HOST:PORT", file=sys.stderr)
            return 2

    from tpusim.chaos.engine import ProcessCrash
    from tpusim.simulator import run_stream_simulation

    obs_teardown = _arm_observability(args)
    try:
        out = run_stream_simulation(
            snapshot, num_nodes=args.synthetic_nodes, cycles=args.cycles,
            arrivals=args.arrivals, evict_fraction=args.evict_fraction,
            node_flap_every=args.flap_every, seed=args.seed,
            label_churn=args.label_churn, taint_churn=args.taint_churn,
            gang_size=args.gang_size, gang_count=args.gang_count,
            provider=args.algorithmprovider,
            policy=policy, pipeline=args.pipeline,
            always_restage=args.always_restage, verify=args.verify,
            chaos_plan=chaos_plan,
            checkpoint_dir=args.checkpoint_dir or None,
            checkpoint_every=args.checkpoint_every,
            fsync_every=args.fsync_every,
            replicate_to=replicate_to,
            recover=args.recover,
            whatif_every=args.whatif_every,
            whatif_pods=args.whatif_pods)
    except ProcessCrash as exc:
        # the scripted kill: state up to the crash is durable in the WAL;
        # rerun with --recover to resume from it
        print(f"crashed: {exc}", file=sys.stderr)
        print(f"recover with: tpusim stream --checkpoint-dir "
              f"{args.checkpoint_dir} --recover ...", file=sys.stderr)
        return 3
    except (KeyError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    finally:
        obs_teardown()

    exit_code = 0
    if args.json:
        import json

        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        paths = ", ".join(f"{k} x{v}" for k, v in sorted(out["paths"].items()))
        restages = ", ".join(f"{k} x{v}"
                             for k, v in sorted(out["restages"].items()))
        print(f"{out['cycles']} cycles over {out['nodes']} nodes: "
              f"{out['scheduled']}/{out['decisions']} scheduled, "
              f"{out['decisions_per_s']:.0f} decisions/s, cycle p50/p99 "
              f"{out['p50_cycle_ms']:.1f}/{out['p99_cycle_ms']:.1f} ms")
        print(f"paths: {paths or 'none'}; restages: {restages or 'none'}; "
              f"{out['commits']} scatter commits")
        print(f"load: {out['load']['arrivals']} arrivals, "
              f"{out['load']['evictions']} evictions, "
              f"{out['load']['flaps']} flaps; "
              f"placement chain {out['placement_chain'][:16]}")
        if "overlay" in out:
            ov = out["overlay"]
            print(f"live what-if: {ov['answered']}/{ov['queries']} overlay "
                  f"queries answered ({ov['fallbacks']} fell back), query "
                  f"p50/p99 {ov['p50_query_ms']:.1f}/"
                  f"{ov['p99_query_ms']:.1f} ms")
        if out.get("recovered"):
            print(f"recovered: resumed at cycle {out['resume_cycle']} "
                  f"({len(out['recomputed_cycles'])} cycles recomputed, replay "
                  f"{out['replay_ms']:.1f} ms); fold chain "
                  f"{out['fold_chain'][:16]}")
        elif "wal_records" in out:
            print(f"durability: {out['wal_records']} WAL records, "
                  f"{out['checkpoints']} checkpoints; fold chain "
                  f"{out['fold_chain'][:16]}")
        if "replication_acked_seq" in out:
            parity = (out["replication_acked_chain"] == out["fold_chain"])
            print(f"replication: acked through seq "
                  f"{out['replication_acked_seq']}, lag "
                  f"{out['replication_lag_at_close']} record(s) at close; "
                  f"follower chain "
                  f"{'matches' if parity else 'DIVERGED from'} the leader")
    if args.verify:
        if out["verified"]:
            print("verify: every cycle placement_hash-identical to the "
                  "full-restage backend")
        else:
            print(f"verify: FAILED — {out['mismatched_cycles']} cycles "
                  "diverged from the full-restage backend", file=sys.stderr)
            exit_code = 1

    if recorder is not None:
        from tpusim.obs import recorder as flight

        flight.uninstall()
        try:
            recorder.write(args.trace_out)
        except OSError as exc:
            print(f"error: failed to write trace: {exc}", file=sys.stderr)
            return 2
        print(f"trace: {args.trace_out} ({len(recorder.events)} events)",
              file=sys.stderr)
    if args.metrics_out:
        try:
            _write_metrics(args.metrics_out)
        except OSError as exc:
            print(f"error: failed to write metrics: {exc}", file=sys.stderr)
            return 2
    return exit_code


def _add_follow_snapshot_flags(parser: argparse.ArgumentParser) -> None:
    """The twin's snapshot source: MUST reproduce the leader's cycle-0
    picture (same --snapshot file or same synthetic parameters) — the
    shipper replays the journal from its first record."""
    parser.add_argument("--snapshot", default="",
                        help="Combined ClusterSnapshot JSON — the leader's "
                             "cycle-0 snapshot source")
    parser.add_argument("--synthetic-nodes", type=int, default=64,
                        help="Generate N homogeneous synthetic nodes "
                             "(must match the leader's)")
    parser.add_argument("--synthetic-milli-cpu", type=int, default=4000)
    parser.add_argument("--synthetic-memory", type=int, default=16 * 1024**3)
    parser.add_argument("--seed-label-universe", action="store_true",
                        help="Seed the churn label universe across the "
                             "synthetic nodes (required when the leader "
                             "runs --policy-file or label/taint churn)")
    parser.add_argument("--algorithmprovider", default="DefaultProvider")
    parser.add_argument("--policy-file", default="",
                        help="Scheduler policy JSON — must match the "
                             "leader's (the twin re-decides every cycle)")
    parser.add_argument("--always-restage", action="store_true")
    parser.add_argument("--platform",
                        default=os.environ.get("TPUSIM_PLATFORM", ""))
    parser.add_argument("--json", action="store_true",
                        help="Print the summary dict as JSON")


def _load_follow_snapshot(args):
    snapshot = None
    policy = None
    if args.snapshot:
        snapshot = ClusterSnapshot.load(args.snapshot)
    else:
        snapshot = synthetic_cluster(
            args.synthetic_nodes, milli_cpu=args.synthetic_milli_cpu,
            memory=args.synthetic_memory)
    if args.policy_file:
        from tpusim.engine.policy import load_policy_file

        policy = load_policy_file(args.policy_file)
    if not args.snapshot and (policy is not None
                              or args.seed_label_universe):
        from tpusim.stream.loadgen import DEFAULT_LABEL_UNIVERSE

        # the leader's run_stream_simulation seeds synthetic nodes the
        # same way — the twins' cold-start compiles must intern the same
        # label domains
        for i, node in enumerate(snapshot.nodes):
            node.metadata.labels.update(
                {k: vals[i % len(vals)]
                 for k, vals in DEFAULT_LABEL_UNIVERSE.items()})
    return snapshot, policy


def build_follow_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpusim follow",
        description="Hot standby: listen for a leader's WAL-shipping "
                    "stream (tpusim stream --replicate-to) and replay "
                    "every shipped cycle through a live scheduler twin, "
                    "cross-checking the placement-hash chain per cycle "
                    "(stream.replicate). With --watch-leader, promote "
                    "automatically when the leader's /healthz dies")
    parser.add_argument("--bind", default="127.0.0.1:0",
                        help="HOST:PORT the replication listener binds "
                             "(':0' picks a free port, printed on start)")
    parser.add_argument("--checkpoint-dir", default="",
                        help="The LEADER's durability directory (shared "
                             "storage): promotion replays its WAL tail "
                             "and journals onward into it; required with "
                             "--watch-leader")
    parser.add_argument("--watch-leader", default="",
                        help="Leader /healthz URL (http://HOST:PORT): "
                             "probe it and promote this twin when it "
                             "misses --misses probes in a row")
    parser.add_argument("--watch-interval", type=float, default=0.25,
                        help="Seconds between leader probes")
    parser.add_argument("--misses", type=int, default=2,
                        help="Consecutive probe misses declaring death")
    parser.add_argument("--watch-timeout", type=float, default=0.0,
                        help="Give up watching after this many seconds "
                             "(0: watch forever)")
    parser.add_argument("--checkpoint-every", type=int, default=10,
                        help="Post-promotion checkpoint cadence")
    parser.add_argument("--fsync-every", type=int, default=0,
                        help="Post-promotion WAL fsync cadence")
    parser.add_argument("--bootstrap", action="store_true",
                        help="Late join (ISSUE 19): request the leader's "
                             "latest checkpoint manifest + WAL offset in "
                             "the hello exchange and rebuild the twin from "
                             "it, instead of replaying from a cycle-0 "
                             "snapshot (--snapshot/--synthetic-nodes are "
                             "then ignored)")
    parser.add_argument("--trace-out", default="",
                        help="Write the follower's flight-recorder trace "
                             "(Chrome trace_event JSON) on exit: replay "
                             "spans carry the leader's trace ids, so "
                             "tools/trace_merge.py joins this file with "
                             "the leader's into one flow graph (ISSUE 20)")
    _add_follow_snapshot_flags(parser)
    add_obs_flags(parser)
    add_explain_flags(parser)
    return parser


def follow_cli(argv) -> int:
    """`tpusim follow`: a live standby twin (ISSUE 18)."""
    import json

    args = build_follow_parser().parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
        os.environ["TPUSIM_PROBE"] = "0"
    if args.watch_leader and not args.checkpoint_dir:
        print("error: --watch-leader promotes from the leader's WAL; pass "
              "--checkpoint-dir (the shared durability directory)",
              file=sys.stderr)
        return 2

    from tpusim.obs.server import parse_listen

    try:
        bind = parse_listen(args.bind)
        snapshot, policy = _load_follow_snapshot(args)
        if args.bootstrap:
            snapshot = None   # the shipped manifest is the twin's source
    except (OSError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    from tpusim.stream.replicate import (
        FailoverController,
        FollowerTwin,
        PromotionRefused,
        http_probe,
    )

    obs_teardown = _arm_observability(args)
    recorder = None
    if args.trace_out:
        from tpusim.obs import recorder as flight

        recorder = flight.install(
            flight.FlightRecorder(process_name="tpusim-follow"))
    try:
        try:
            follower = FollowerTwin(snapshot,
                                    provider=args.algorithmprovider,
                                    policy=policy,
                                    always_restage=args.always_restage,
                                    listen=bind,
                                    bootstrap=args.bootstrap)
        except (KeyError, ValueError, OSError) as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2
        host, port = follower.address
        print(f"follower: replication listener on {host}:{port} "
              f"(leader side: tpusim stream --replicate-to {host}:{port})",
              file=sys.stderr)

        def summary(extra=None) -> dict:
            body = {"applied_records": follower.wal_records_applied,
                    "cycles_emitted": follower.cycles_emitted,
                    "chain": follower.chain,
                    "scheduled": follower.scheduled,
                    "decisions": follower.decisions,
                    "divergence": follower.diverged}
            body.update(extra or {})
            return body

        if args.watch_leader:
            url = args.watch_leader.rstrip("/")
            if "://" not in url:   # bare HOST:PORT (the --listen spelling)
                url = "http://" + url
            if not url.endswith("/healthz"):
                url += "/healthz"
            controller = FailoverController(
                http_probe(url), [follower], args.checkpoint_dir,
                interval_s=max(0.01, args.watch_interval),
                misses=max(1, args.misses),
                checkpoint_every=args.checkpoint_every,
                fsync_every=args.fsync_every)
            timeout = args.watch_timeout if args.watch_timeout > 0 else 1e9
            try:
                _, report = controller.run(timeout=timeout)
            except TimeoutError as exc:
                print(f"{exc}; exiting without promotion", file=sys.stderr)
                follower.stop()
                out = summary({"promoted": False})
                print(json.dumps(out, sort_keys=True) if args.json
                      else f"follower: applied {out['applied_records']} "
                           f"records, chain {out['chain'][:16]}")
                return 0
            except PromotionRefused as exc:
                print(f"error: promotion refused: {exc}", file=sys.stderr)
                return 1
            out = summary({
                "promoted": True, "rto_s": report.rto_s,
                "resume_cycle": report.resume_cycle,
                "replayed_records": report.tail_records,
                "recomputed_cycles": list(report.recomputed),
                "settled_live_cycles": list(report.settled_live),
                "promotion_violations": list(report.violations)})
            follower.persist.close()
            if args.json:
                print(json.dumps(out, sort_keys=True))
            else:
                print(f"promoted: resumed at cycle {out['resume_cycle']} "
                      f"(replayed {out['replayed_records']} tail records, "
                      f"RTO {out['rto_s'] * 1e3:.1f} ms); chain "
                      f"{out['chain'][:16]}")
                print(f"resume the churn load with: tpusim stream "
                      f"--checkpoint-dir {args.checkpoint_dir} --recover ...")
            return 1 if out["promotion_violations"] else 0

        try:
            while True:
                time.sleep(1.0)
        except KeyboardInterrupt:
            pass
        follower.stop()
        out = summary()
        print(json.dumps(out, sort_keys=True) if args.json
              else f"follower: applied {out['applied_records']} records "
                   f"over {out['cycles_emitted']} cycles, chain "
                   f"{out['chain'][:16]}"
                   + (f"; DIVERGED: {out['divergence']}"
                      if out["divergence"] else ""))
        return 1 if out["divergence"] else 0
    finally:
        if recorder is not None:
            from tpusim.obs import recorder as flight

            flight.uninstall()
            try:
                recorder.write(args.trace_out)
                print(f"trace: {args.trace_out} "
                      f"({len(recorder.events)} events)", file=sys.stderr)
            except OSError as exc:
                print(f"error: failed to write trace: {exc}",
                      file=sys.stderr)
        obs_teardown()


def build_promote_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpusim promote",
        description="Durable-state promotion: replay a dead leader's "
                    "entire WAL (checkpoint dir) through a fresh twin "
                    "via the promotion path — chain cross-checked "
                    "against the durable checkpoint manifest, crash-tail "
                    "cycles re-decided, a fresh checkpoint written. "
                    "Resume the run afterwards with `tpusim stream "
                    "--recover`")
    parser.add_argument("--checkpoint-dir", required=True,
                        help="The dead leader's durability directory")
    parser.add_argument("--checkpoint-every", type=int, default=10)
    parser.add_argument("--fsync-every", type=int, default=0)
    parser.add_argument("--metrics-out", default="",
                        help="Write the metric families (including "
                             "tpusim_replication_*) after promotion")
    _add_follow_snapshot_flags(parser)
    return parser


def promote_cli(argv) -> int:
    """`tpusim promote`: one-shot durable promotion (ISSUE 18)."""
    import json

    args = build_promote_parser().parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
        os.environ["TPUSIM_PROBE"] = "0"
    try:
        snapshot, policy = _load_follow_snapshot(args)
    except (OSError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    from tpusim.stream.replicate import FollowerTwin, PromotionRefused

    try:
        follower = FollowerTwin(snapshot, provider=args.algorithmprovider,
                                policy=policy,
                                always_restage=args.always_restage)
    except (KeyError, ValueError, OSError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    try:
        report = follower.promote(args.checkpoint_dir,
                                  checkpoint_every=args.checkpoint_every,
                                  fsync_every=args.fsync_every)
    except PromotionRefused as exc:
        follower.stop()
        print(f"error: promotion refused: {exc}", file=sys.stderr)
        return 1
    except (OSError, ValueError, KeyError) as exc:
        follower.stop()
        print(f"error: {exc}", file=sys.stderr)
        return 2
    follower.persist.close()
    out = {"promoted": True, "chain": report.chain,
           "resume_cycle": report.resume_cycle,
           "replayed_records": report.tail_records,
           "recomputed_cycles": list(report.recomputed),
           "wal_records": report.wal_records,
           "replay_s": report.replay_s,
           "violations": list(report.violations)}
    if args.json:
        print(json.dumps(out, sort_keys=True))
    else:
        print(f"promoted: {out['replayed_records']} WAL records replayed "
              f"({len(out['recomputed_cycles'])} cycles re-decided) in "
              f"{out['replay_s'] * 1e3:.1f} ms; chain {out['chain'][:16]}")
        print(f"resume with: tpusim stream --checkpoint-dir "
              f"{args.checkpoint_dir} --recover ...")
        for violation in out["violations"]:
            print(f"promotion violation: {violation}", file=sys.stderr)
    if args.metrics_out:
        try:
            _write_metrics(args.metrics_out)
        except OSError as exc:
            print(f"error: failed to write metrics: {exc}", file=sys.stderr)
            return 2
    return 1 if out["violations"] else 0


def build_audit_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpusim audit",
        description="Chain-divergence forensics (ISSUE 20): bisect two "
                    "WAL directories (checkpoint.json + wal.jsonl pairs, "
                    "e.g. a leader's and a follower's, or two same-seed "
                    "runs) to the FIRST divergent cycle via the sha256 "
                    "digest chain, then re-run that cycle through the "
                    "scheduler with explain lanes on and emit a "
                    "per-decision forensic diff: score parts, top-k "
                    "candidate order, restage classification, shard "
                    "ownership of the flipped node")
    parser.add_argument("wal_a", help="First WAL directory")
    parser.add_argument("wal_b", help="Second WAL directory")
    parser.add_argument("--algorithmprovider", default="DefaultProvider",
                        help="Provider the audited runs used (the replay "
                             "re-decides under the same policy surface)")
    parser.add_argument("--explain-k", type=int, default=3,
                        help="Top-k score-breakdown depth for the "
                             "forensic re-run (default 3; 0 disables "
                             "the score-parts lanes)")
    parser.add_argument("--no-replay", action="store_true",
                        help="Record-level diff only: skip rebuilding "
                             "the shared prefix and re-deciding the "
                             "divergent cycle")
    parser.add_argument("--json", action="store_true",
                        help="Print the full report as one JSON object")
    parser.add_argument("--out", default="",
                        help="Additionally write the JSON report here "
                             "(the repro harness's forensic artifact)")
    parser.add_argument("--platform", default="",
                        help="JAX platform for the replay (e.g. cpu)")
    return parser


def audit_cli(argv) -> int:
    """`tpusim audit`: first-divergence forensics over two WALs."""
    import json

    args = build_audit_parser().parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
        os.environ["TPUSIM_PROBE"] = "0"
    from tpusim.obs.audit import audit_wal_pair, render_report
    from tpusim.stream.persist import StreamPersistence

    for d in (args.wal_a, args.wal_b):
        if not os.path.exists(os.path.join(d, StreamPersistence.WAL)):
            print(f"error: no {StreamPersistence.WAL} in {d}",
                  file=sys.stderr)
            return 2
    try:
        report = audit_wal_pair(args.wal_a, args.wal_b,
                                provider=args.algorithmprovider,
                                explain_k=args.explain_k,
                                replay=not args.no_replay)
    except (OSError, ValueError, KeyError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if args.out:
        try:
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(report, f, sort_keys=True, indent=2)
                f.write("\n")
        except OSError as exc:
            print(f"error: failed to write report: {exc}", file=sys.stderr)
            return 2
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(render_report(report), end="")
    return 1 if report.get("verdict") == "diverged" else 0


def build_explain_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpusim explain",
        description="Query a decision-provenance file (--explain-out "
                    "JSONL): why each pod placed where it did, or the "
                    "exact per-predicate failure text when it didn't")
    parser.add_argument("file", help="JSONL file written by --explain-out")
    parser.add_argument("--pod", default="",
                        help="Only records whose pod name contains this "
                             "substring ('ns/name' matches exactly)")
    parser.add_argument("--source", default="",
                        help="Only records from this capture source "
                             "(backend, stream, serve, ...)")
    parser.add_argument("--failed", action="store_true",
                        help="Only unschedulable decisions")
    parser.add_argument("--placed", action="store_true",
                        help="Only placed decisions")
    parser.add_argument("--limit", type=int, default=0,
                        help="Print at most the LAST N matching records "
                             "(0: all)")
    parser.add_argument("--summary", action="store_true",
                        help="Aggregate counts instead of per-record lines: "
                             "placed/failed by source, failure messages by "
                             "frequency")
    parser.add_argument("--json", action="store_true",
                        help="Emit matching records as JSON lines instead "
                             "of the human-readable rendering")
    return parser


def _format_explain_record(rec: dict) -> str:
    where = rec.get("source", "?")
    if rec.get("cycle") is not None:
        where += f" c{rec['cycle']}"
    head = f"#{rec.get('seq', '?')} [{where}] {rec.get('pod', '?')}"
    if rec.get("placed"):
        line = f"{head} -> {rec.get('node')}"
        top = rec.get("top_k") or []
        if top:
            best = top[0]
            parts = best.get("parts") or {}
            breakdown = ", ".join(f"{k}={v}" for k, v in parts.items() if v)
            line += (f"  (score {best.get('score')}"
                     + (f": {breakdown}" if breakdown else "") + ")")
            for alt in top[1:]:
                line += f"\n    runner-up {alt['node']} score {alt['score']}"
        return line
    return (f"{head} UNSCHEDULABLE [{rec.get('reason', '?')}]\n"
            f"    {rec.get('message', '')}")


def explain_cli(argv) -> int:
    """`tpusim explain`: offline queries over an --explain-out file."""
    import json
    from collections import Counter

    from tpusim.obs.provenance import read_jsonl

    args = build_explain_parser().parse_args(argv)
    if args.failed and args.placed:
        print("error: --failed and --placed are mutually exclusive",
              file=sys.stderr)
        return 2

    def matches(rec: dict) -> bool:
        if args.pod:
            pod = rec.get("pod", "")
            if args.pod != pod and args.pod not in pod:
                return False
        if args.source and rec.get("source") != args.source:
            return False
        if args.failed and rec.get("placed"):
            return False
        if args.placed and not rec.get("placed"):
            return False
        return True

    try:
        records = [r for r in read_jsonl(args.file) if matches(r)]
    except (OSError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    if args.summary:
        by_source: Counter = Counter()
        placed = failed = 0
        messages: Counter = Counter()
        nodes: Counter = Counter()
        for rec in records:
            by_source[rec.get("source", "?")] += 1
            if rec.get("placed"):
                placed += 1
                nodes[rec.get("node", "?")] += 1
            else:
                failed += 1
                messages[rec.get("message", "")] += 1
        print(f"{len(records)} decision(s): {placed} placed, "
              f"{failed} unschedulable")
        for source, n in by_source.most_common():
            print(f"  source {source}: {n}")
        if nodes:
            print("top nodes:")
            for node, n in nodes.most_common(10):
                print(f"  {n:6d}  {node}")
        if messages:
            print("failure messages:")
            for message, n in messages.most_common(10):
                print(f"  {n:6d}  {message}")
        return 0

    if args.limit > 0:
        records = records[-args.limit:]
    for rec in records:
        print(json.dumps(rec, sort_keys=True) if args.json
              else _format_explain_record(rec))
    return 0


def build_top_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tpusim top",
        description="Live cluster view against a running --listen "
                    "endpoint: per-resource utilization/fragmentation, "
                    "feasible nodes, hottest/coldest nodes, HBM residency "
                    "and compile cost (rendered from GET /analytics)")
    parser.add_argument("endpoint",
                        help="A --listen endpoint: http://HOST:PORT, "
                             "HOST:PORT, ':PORT', or 'PORT'")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="Seconds between refreshes (default 2)")
    parser.add_argument("--iterations", type=int, default=0,
                        help="Render this many frames then exit "
                             "(0: until interrupted)")
    parser.add_argument("--once", action="store_true",
                        help="Render a single frame without clearing the "
                             "screen and exit")
    parser.add_argument("--json", action="store_true",
                        help="Print one raw /analytics JSON body and exit")
    return parser


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"


def _render_top(body: dict, url: str) -> str:
    """One `tpusim top` frame from a /analytics body."""
    lines = [f"tpusim top — {url}   samples={body.get('samples', 0)}"]
    latest = body.get("latest")
    if not body.get("enabled"):
        lines.append("analytics plane not armed on this endpoint "
                     "(start the session with --listen or --analytics-out)")
    elif latest is None:
        lines.append("no samples yet (waiting for the first cycle)")
    else:
        where = latest.get("source", "?")
        if latest.get("cycle") is not None:
            where += f" c{latest['cycle']}"
        nodes = latest.get("nodes", {})
        lines.append(f"nodes: {nodes.get('valid', '?')} valid, "
                     f"{nodes.get('feasible', '?')} feasible "
                     f"(cpu+mem+pod headroom)   [latest: {where}]")
        lines.append(f"{'RESOURCE':<10} {'UTIL':>7} {'FRAG':>7} "
                     f"{'REQUESTED':>14} {'ALLOCATABLE':>14} "
                     f"{'LARGEST-FREE':>13}")
        for name, row in latest.get("resources", {}).items():
            util = row.get("utilization")
            util_s = f"{util * 100:.1f}%" if util is not None else "-"
            frag_s = f"{row.get('fragmentation', 0.0) * 100:.1f}%"
            lines.append(f"{name:<10} {util_s:>7} {frag_s:>7} "
                         f"{row.get('requested', 0):>14} "
                         f"{row.get('allocatable', 0):>14} "
                         f"{row.get('largest_free', 0):>13}")
        for label, key in (("hottest", "hot_nodes"),
                           ("coldest", "cold_nodes")):
            entries = latest.get(key) or []
            if entries:
                lines.append(f"{label}: " + "  ".join(
                    f"{e['node']} {e['utilization_ppm'] / 10_000:.1f}%"
                    for e in entries[:5]))
    hbm = body.get("hbm") or {}
    if hbm:
        lines.append("hbm: " + "  ".join(
            f"{comp} {_fmt_bytes(slot.get('bytes', 0))}"
            f"/{slot.get('entries', 0)} entries"
            for comp, slot in sorted(hbm.items())))
    comp = body.get("compile") or {}
    if comp:
        lines.append("compile: " + "  ".join(
            f"{site} {slot.get('traces', 0)} traces "
            f"{slot.get('total_us', 0.0) / 1e6:.2f}s"
            for site, slot in sorted(comp.items())))
    return "\n".join(lines)


def top_cli(argv) -> int:
    """`tpusim top`: live analytics view against a --listen endpoint."""
    import json
    import time as _time
    from urllib.error import URLError
    from urllib.request import urlopen

    args = build_top_parser().parse_args(argv)
    endpoint = args.endpoint.strip()
    if endpoint.startswith("http://") or endpoint.startswith("https://"):
        url = endpoint.rstrip("/")
    else:
        from tpusim.obs.server import parse_listen

        try:
            host, port = parse_listen(endpoint)
        except ValueError:
            print(f"error: bad endpoint {endpoint!r}", file=sys.stderr)
            return 2
        url = f"http://{host}:{port}"

    def fetch() -> dict:
        with urlopen(f"{url}/analytics?limit=1", timeout=5) as resp:
            return json.loads(resp.read().decode())

    frames = 0
    try:
        while True:
            try:
                body = fetch()
            except (URLError, OSError, ValueError) as exc:
                if frames:
                    print(f"endpoint gone ({exc}); exiting", file=sys.stderr)
                    return 0
                print(f"error: cannot reach {url}/analytics: {exc}",
                      file=sys.stderr)
                return 2
            if args.json:
                print(json.dumps(body, sort_keys=True))
                return 0
            frame = _render_top(body, url)
            if not args.once and sys.stdout.isatty():
                print("\x1b[2J\x1b[H" + frame, flush=True)
            else:
                print(frame, flush=True)
            frames += 1
            if args.once or (args.iterations and frames >= args.iterations):
                return 0
            _time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        return serve_cli(argv[1:])
    if argv and argv[0] == "stream":
        return stream_cli(argv[1:])
    if argv and argv[0] == "follow":
        return follow_cli(argv[1:])
    if argv and argv[0] == "promote":
        return promote_cli(argv[1:])
    if argv and argv[0] == "audit":
        return audit_cli(argv[1:])
    if argv and argv[0] == "explain":
        return explain_cli(argv[1:])
    if argv and argv[0] == "top":
        return top_cli(argv[1:])
    args = build_parser().parse_args(argv)
    feature_gates = None
    if args.feature_gates:
        from tpusim.engine.providers import parse_feature_gates

        try:
            feature_gates = parse_feature_gates(args.feature_gates)
        except ValueError as exc:
            print(f"error: --feature-gates: {exc}", file=sys.stderr)
            return 2
        # PodPriority / VolumeScheduling gate the same behavior as the
        # dedicated flags (scheduler.go:175,210-213)
        if feature_gates.pop("PodPriority", False):
            args.enable_pod_priority = True
        if feature_gates.pop("VolumeScheduling", False):
            args.enable_volume_scheduling = True

    if args.verbosity >= 2:
        # glog -v analog. The tpusim.* loggers (engine/trace.py slow-
        # schedule traces, backend routing decisions) emit into the root
        # logger, which python leaves handler-less: configure it so V(2)+
        # actually prints. V(5)+ additionally turns on DEBUG, including
        # the engine's per-node score dump.
        import logging

        logging.basicConfig(stream=sys.stderr, format="%(message)s")
        # "tpusim.engine" and "tpusim.trace" inherit the package level
        logging.getLogger("tpusim").setLevel(
            logging.DEBUG if args.verbosity >= 5 else logging.INFO)

    # (An env-level JAX_PLATFORMS=cpu pin is honored by the import-time guard
    # in tpusim/jaxe/__init__.py — every jax-using path imports that module
    # before backend init, so no duplicate check is needed here.)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
        # an explicit pin is a deliberate choice: the wedged-tunnel probe
        # guard must neither delay it nor silently override it with CPU
        os.environ["TPUSIM_PROBE"] = "0"

    if args.what_if:
        if args.event_log:
            print("error: --event-log cannot be combined with --what-if "
                  "(what-if scenarios carry their own snapshots)",
                  file=sys.stderr)
            return 2
        if args.trace_out:
            print("error: --trace-out cannot be combined with --what-if "
                  "(scenario runs share one process; their spans would "
                  "interleave on a single timeline)", file=sys.stderr)
            return 2
        rc = run_what_if_cli(args)
        if rc == 0 and args.metrics_out:
            _write_metrics(args.metrics_out)
        return rc
    if args.mesh:
        print("error: --mesh applies only to --what-if (the single-run scan "
              "is sequential; scale it via more nodes per snapshot)",
              file=sys.stderr)
        return 2
    if not args.podspec:
        print("error: --podspec is required (or use --what-if)", file=sys.stderr)
        return 2

    try:
        snapshot = load_snapshot(args)
    except (OSError, ValueError) as exc:
        print(f"error: failed to load cluster snapshot: {exc}", file=sys.stderr)
        return 2
    if not snapshot.nodes:
        print("error: no cluster nodes; pass --snapshot, --nodes, or "
              "--synthetic-nodes", file=sys.stderr)
        return 2

    try:
        sim_pods = load_simulation_pods(args.podspec)
    except (OSError, ValueError) as exc:
        print(f"error: failed to parse podspec: {exc}", file=sys.stderr)
        return 2
    pods = expand_simulation_pods(sim_pods, namespace=args.namespace)

    policy, policy_err = load_policy_from_args(args)
    if policy_err:
        print(f"error: {policy_err}", file=sys.stderr)
        return 2

    events = None
    if args.event_log:
        from tpusim.framework.events import load_event_log

        try:
            events = load_event_log(args.event_log)
        except (OSError, ValueError) as exc:
            print(f"error: invalid event log: {exc}", file=sys.stderr)
            return 2

    if args.verbosity >= 5:
        # the per-node score dump is a host-engine trace; the device
        # pipeline is one fused program with no per-node observability
        # point — warn whenever THIS invocation will run on the device
        # (explicit jax, or auto routing away from the host engine; auto
        # sizes AFTER the event-log fold, so count node adds/deletes)
        from tpusim.api.types import Node
        from tpusim.framework.store import ADDED, DELETED
        from tpusim.simulator import auto_routes_to_host

        n_nodes = len(snapshot.nodes)
        for etype, obj in events or []:
            if isinstance(obj, Node):
                n_nodes += 1 if etype == ADDED else \
                    -1 if etype == DELETED else 0
        device_bound = (args.backend == "jax"
                        or (args.backend == "auto" and not auto_routes_to_host(
                            len(pods), n_nodes,
                            args.enable_volume_scheduling)))
        if device_bound:
            print("note: the per-node score dump (--v 5) is produced by "
                  "the host engine; this run uses the fused device "
                  "program. Use --backend reference to see the dump.",
                  file=sys.stderr)

    chaos_plan = None
    if args.chaos_plan or args.chaos_seed is not None:
        from tpusim.chaos import load_plan, random_plan
        from tpusim.chaos.plan import PlanError

        try:
            if args.chaos_plan:
                chaos_plan = load_plan(args.chaos_plan)
                if args.chaos_seed is not None:
                    chaos_plan.seed = args.chaos_seed
            else:
                # seed-only: generate an adversarial plan against the
                # loaded workload (deterministic per seed)
                chaos_plan = random_plan(
                    args.chaos_seed,
                    node_names=[n.name for n in snapshot.nodes],
                    pod_keys=[p.key() for p in pods],
                    attempts=max(len(pods), 1))
        except (OSError, PlanError) as exc:
            print(f"error: invalid chaos plan: {exc}", file=sys.stderr)
            return 2

    recorder = None
    if args.trace_out:
        from tpusim.obs import recorder as flight

        recorder = flight.install(flight.FlightRecorder())

    obs_teardown = _arm_observability(args)
    start = time.perf_counter()
    try:
        status = run_simulation(pods, snapshot, provider=args.algorithmprovider,
                                backend=args.backend,
                                enable_pod_priority=args.enable_pod_priority,
                                enable_volume_scheduling=args.enable_volume_scheduling,
                                policy=policy, events=events,
                                feature_gates=feature_gates,
                                chaos_plan=chaos_plan)
    except (ValueError, KeyError) as exc:
        # invalid policy/provider/plugin names surfaced at build time
        # (PolicyError is a ValueError; the registry raises KeyError)
        print(f"error: {exc}", file=sys.stderr)
        return 2
    finally:
        obs_teardown()
    elapsed = time.perf_counter() - start

    if recorder is not None:
        from tpusim.obs import recorder as flight

        flight.uninstall()
        try:
            recorder.write(args.trace_out)
        except OSError as exc:
            print(f"error: failed to write trace: {exc}", file=sys.stderr)
            return 2
        if not args.quiet:
            print(f"trace: {args.trace_out} "
                  f"({len(recorder.events)} events)", file=sys.stderr)
    if args.metrics_out:
        try:
            _write_metrics(args.metrics_out)
        except OSError as exc:
            print(f"error: failed to write metrics: {exc}", file=sys.stderr)
            return 2

    report = get_report(status)
    if args.print_requirements and not args.quiet:
        spec_print(report.review["success"].spec)
        spec_print(report.review["failed"].spec)
    if not args.quiet:
        cluster_capacity_review_print(report)
    n_ok = len(status.successful_pods)
    n_fail = len(status.failed_pods)
    rate = (n_ok + n_fail) / elapsed if elapsed > 0 else 0.0
    print(f"\n{n_ok} pod(s) scheduled, {n_fail} unschedulable, "
          f"{len(status.scheduled_pods)} pre-scheduled "
          f"[{args.backend} backend, {elapsed:.3f}s, {rate:.0f} pods/s]")
    print(f"StopReason: {status.stop_reason.strip()}")
    if chaos_plan is not None:
        summary = getattr(status, "chaos_summary", None) or {}
        violations = getattr(status, "chaos_violations", None) or []
        fired = summary.get("churn_fired", 0)
        fabric = len(summary.get("fabric_injected", []))
        device = (len(summary.get("device_injected", []))
                  or len(summary.get("breaker_transitions", [])))
        print(f"Chaos: {fired} churn event(s), {fabric} fabric fault(s), "
              f"{device} device fault/transition(s), "
              f"{len(violations)} invariant violation(s) [seed "
              f"{chaos_plan.seed}]")
        if violations:
            for violation in violations:
                print(f"chaos violation: {violation}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Scheduling engine with Go-parity semantics.

Re-expresses the vendored kube-scheduler pipeline
(reference: vendor/k8s.io/kubernetes/pkg/scheduler/) in Python as the parity
oracle for the JAX backend:

  errors        predicate failure reasons   (algorithm/predicates/error.go)
  resources     Resource / NodeInfo / ports (schedulercache/node_info.go, util/utils.go)
  predicates    ordered fit predicates      (algorithm/predicates/predicates.go)
  priorities    score map/reduce functions  (algorithm/priorities/*.go)
  generic_scheduler  filter→score→select    (core/generic_scheduler.go)
  providers     registry + algorithm providers (factory/plugins.go, algorithmprovider/defaults)
  cache         scheduler cache             (schedulercache/cache.go)
  queue         scheduling queues           (core/scheduling_queue.go)
"""

"""Scheduling queues: FIFO and the priority queue.

Reference: core/scheduling_queue.go — `NewSchedulingQueue` returns a plain FIFO
unless pod priority is enabled, else the PriorityQueue with an active heap,
an unschedulable map, a nominated-pods index, and the receivedMoveRequest flag
(:49-340). The simulator runs one pod in flight so the queues are small, but
the semantics (ordering, unschedulable parking, nominated-index maintenance,
affinity-triggered moves) are preserved — pinned by the golden tables ported
from core/scheduling_queue_test.go (tests/test_queue_goldens.py).

Deviation from upstream: Pop() returns None on an empty queue instead of
blocking on a condition variable — the single-threaded simulator drives the
feed itself (simulator.py nextPod), so there is never a consumer to park.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Dict, List, Optional

from tpusim.api.types import Pod
from tpusim.engine.util import get_pod_priority


def nominated_node_name(pod: Pod) -> str:
    """scheduling_queue.go:143-145."""
    return pod.status.nominated_node_name


def is_pod_unschedulable(pod: Pod) -> bool:
    """scheduling_queue.go:268-271: carries PodScheduled=False with reason
    Unschedulable."""
    for cond in pod.status.conditions:
        if cond.type == "PodScheduled":
            return cond.status == "False" and cond.reason == "Unschedulable"
    return False


def _pod_uid(pod: Pod) -> str:
    """Nominated-index identity: upstream compares pod UIDs
    (scheduling_queue.go:190-216); fall back to the ns/name key for fixtures
    without UIDs."""
    return pod.metadata.uid or pod.key()


def is_pod_updated(old_pod: Optional[Pod], new_pod: Pod) -> bool:
    """scheduling_queue.go:321-331 isPodUpdated: strip status (and the
    versioning fields our model does not carry) and compare — an update that
    only touches status cannot have made the pod schedulable."""
    if old_pod is None:
        return True

    def strip(pod: Pod) -> dict:
        o = pod.to_obj()
        o.pop("status", None)
        meta = o.get("metadata") or {}
        meta.pop("resourceVersion", None)
        meta.pop("generation", None)
        return o

    return strip(old_pod) != strip(new_pod)


class SchedulingQueue:
    """Reference: scheduling_queue.go:49-61 (interface)."""

    def add(self, pod: Pod) -> None:
        raise NotImplementedError

    def has_nominated_pods(self) -> bool:
        """True when any parked pod carries a nominated node (those feed the
        feasibility double-pass of later pods, generic_scheduler.go:420-534)."""
        return False

    def add_if_not_present(self, pod: Pod) -> None:
        raise NotImplementedError

    def add_unschedulable_if_not_present(self, pod: Pod) -> None:
        raise NotImplementedError

    def pop(self) -> Optional[Pod]:
        raise NotImplementedError

    def update(self, old_pod: Optional[Pod], new_pod: Pod) -> None:
        raise NotImplementedError

    def delete(self, pod: Pod) -> None:
        raise NotImplementedError

    def assigned_pod_added(self, pod: Pod) -> None:
        raise NotImplementedError

    def assigned_pod_updated(self, pod: Pod) -> None:
        raise NotImplementedError

    def move_all_to_active_queue(self) -> None:
        raise NotImplementedError

    def waiting_pods_for_node(self, node_name: str) -> List[Pod]:
        raise NotImplementedError

    def clear_nominations_for_node(self, node_name: str) -> List[Pod]:
        """Drop every nomination pointing at `node_name` (the node left the
        cluster; a nomination on it is a promise that can't be kept) and
        return the affected pods so the caller can clear their status."""
        return []

    def take_matching(self, pred) -> List[Pod]:
        """Remove and return every queued pod satisfying `pred` — the gang
        gather on retry: a popped group member pulls its queued mates
        forward so the group re-decides as one unit. Implementations
        without queued state hold nothing to gather."""
        return []

    def clear_nominations_for_gangs(self, names) -> List[Pod]:
        """Drop every nomination held by a member of the named pod groups
        (the gang released — e.g. one member was preempted, so its mates'
        nominations are promises for a group that no longer stands) and
        return the affected pods."""
        return []


class FIFO(SchedulingQueue):
    """Reference: scheduling_queue.go:73-139 — wrapper over cache.FIFO."""

    def __init__(self):
        self._order: List[str] = []
        self._items: Dict[str, Pod] = {}

    def add(self, pod: Pod) -> None:
        key = pod.key()
        if key not in self._items:
            self._order.append(key)
        self._items[key] = pod

    def add_if_not_present(self, pod: Pod) -> None:
        if pod.key() not in self._items:
            self.add(pod)

    # FIFO treats unschedulable pods like any other (scheduling_queue.go:87-92)
    def add_unschedulable_if_not_present(self, pod: Pod) -> None:
        self.add_if_not_present(pod)

    def pop(self) -> Optional[Pod]:
        while self._order:
            key = self._order.pop(0)
            pod = self._items.pop(key, None)
            if pod is not None:
                return pod
        return None

    def update(self, old_pod: Optional[Pod], new_pod: Pod) -> None:
        self.add(new_pod)

    def delete(self, pod: Pod) -> None:
        self._items.pop(pod.key(), None)

    # FIFO ignores assigned-pod and move events (scheduling_queue.go:104-116)
    def assigned_pod_added(self, pod: Pod) -> None:
        pass

    def assigned_pod_updated(self, pod: Pod) -> None:
        pass

    def move_all_to_active_queue(self) -> None:
        pass

    def waiting_pods_for_node(self, node_name: str) -> List[Pod]:
        return []

    def take_matching(self, pred) -> List[Pod]:
        taken = [p for p in self._items.values() if pred(p)]
        for pod in taken:
            self.delete(pod)
        return taken

    def __len__(self) -> int:
        return len(self._items)


class PriorityQueue(SchedulingQueue):
    """Reference: scheduling_queue.go:147-460 — activeQ heap ordered by pod
    priority (ties FIFO by insertion), unschedulableQ parking lot, nominated
    pods index maintained across add/update/delete/pop, receivedMoveRequest,
    and affinity-triggered unschedulable->active moves."""

    def __init__(self):
        self._counter = itertools.count()
        self._active: List[tuple] = []  # (-priority, seq, key)
        self._active_items: Dict[str, Pod] = {}
        self._active_seq: Dict[str, int] = {}  # key -> live heap entry seq
        self._unschedulable: Dict[str, Pod] = {}
        self._nominated: Dict[str, List[Pod]] = {}  # node name -> pods
        self.received_move_request = False

    # --- nominated-pods index (scheduling_queue.go:188-226) ---

    def _add_nominated(self, pod: Pod) -> None:
        node = nominated_node_name(pod)
        if node:
            if any(_pod_uid(np) == _pod_uid(pod)
                   for np in self._nominated.get(node, ())):
                return  # adding an existing pod does not update it
            self._nominated.setdefault(node, []).append(pod)

    def _delete_nominated(self, pod: Pod) -> None:
        node = nominated_node_name(pod)
        if node and node in self._nominated:
            self._nominated[node] = [p for p in self._nominated[node]
                                     if _pod_uid(p) != _pod_uid(pod)]
            if not self._nominated[node]:
                del self._nominated[node]

    def _update_nominated(self, old_pod: Optional[Pod], new_pod: Pod) -> None:
        if old_pod is not None:
            self._delete_nominated(old_pod)
        self._add_nominated(new_pod)

    def has_nominated_pods(self) -> bool:
        return bool(self._nominated)

    # --- activeQ heap with lazy invalidation (cache.Heap Add/Update) ---

    def _heap_add(self, pod: Pod) -> None:
        key = pod.key()
        seq = next(self._counter)
        heapq.heappush(self._active, (-get_pod_priority(pod), seq, key))
        self._active_items[key] = pod
        self._active_seq[key] = seq

    # --- queue ops ---

    def add(self, pod: Pod) -> None:
        """scheduling_queue.go:228-246."""
        key = pod.key()
        self._heap_add(pod)
        if key in self._unschedulable:
            self._delete_nominated(pod)
            del self._unschedulable[key]
        self._add_nominated(pod)

    def add_if_not_present(self, pod: Pod) -> None:
        """scheduling_queue.go:248-266."""
        key = pod.key()
        if key in self._unschedulable or key in self._active_items:
            return
        self._heap_add(pod)
        self._add_nominated(pod)

    def add_unschedulable_if_not_present(self, pod: Pod) -> None:
        """scheduling_queue.go:273-293: park only when no move request
        arrived mid-flight AND the pod actually carries the Unschedulable
        condition; anything else goes (back) to the active queue."""
        key = pod.key()
        if key in self._unschedulable or key in self._active_items:
            return
        if not self.received_move_request and is_pod_unschedulable(pod):
            self._unschedulable[key] = pod
            self._add_nominated(pod)
            return
        self._heap_add(pod)
        self._add_nominated(pod)

    def pop(self) -> Optional[Pod]:
        """scheduling_queue.go:295-312 (non-blocking; see module docstring):
        removes the popped pod from the nominated index and clears
        receivedMoveRequest to mark a new scheduling cycle."""
        while self._active:
            _, seq, key = heapq.heappop(self._active)
            if self._active_seq.get(key) != seq:
                continue  # superseded by an update; skip the stale entry
            del self._active_seq[key]
            pod = self._active_items.pop(key)
            self._delete_nominated(pod)
            self.received_move_request = False
            return pod
        return None

    def update(self, old_pod: Optional[Pod], new_pod: Pod) -> None:
        """scheduling_queue.go:333-363."""
        key = new_pod.key()
        if key in self._active_items:
            self._update_nominated(old_pod, new_pod)
            self._heap_add(new_pod)  # re-push; stale entry skipped at pop
            return
        if key in self._unschedulable:
            self._update_nominated(old_pod, new_pod)
            if is_pod_updated(old_pod, new_pod):
                del self._unschedulable[key]
                self._heap_add(new_pod)
            else:
                self._unschedulable[key] = new_pod
            return
        self._heap_add(new_pod)
        self._add_nominated(new_pod)

    def delete(self, pod: Pod) -> None:
        """scheduling_queue.go:365-376."""
        key = pod.key()
        self._delete_nominated(pod)
        if key in self._active_items:
            del self._active_items[key]
            self._active_seq.pop(key, None)
        else:
            self._unschedulable.pop(key, None)

    # --- assigned-pod events (scheduling_queue.go:378-446) ---

    def assigned_pod_added(self, pod: Pod) -> None:
        self._move_pods_to_active_queue(
            self._unschedulable_pods_with_matching_affinity_term(pod))

    def assigned_pod_updated(self, pod: Pod) -> None:
        self._move_pods_to_active_queue(
            self._unschedulable_pods_with_matching_affinity_term(pod))

    def _move_pods_to_active_queue(self, pods: List[Pod]) -> None:
        for pod in pods:
            self._heap_add(pod)
            self._unschedulable.pop(pod.key(), None)
        self.received_move_request = True

    def _unschedulable_pods_with_matching_affinity_term(
            self, pod: Pod) -> List[Pod]:
        """getUnschedulablePodsWithMatchingAffinityTerm: parked pods with any
        REQUIRED pod-affinity term matching the newly assigned pod."""
        from tpusim.engine.predicates import (
            get_namespaces_from_pod_affinity_term,
            get_pod_affinity_terms,
            pod_matches_term_namespace_and_selector,
        )

        to_move = []
        for up in self._unschedulable.values():
            affinity = up.spec.affinity
            if affinity is None or affinity.pod_affinity is None:
                continue
            for term in get_pod_affinity_terms(affinity.pod_affinity):
                namespaces = get_namespaces_from_pod_affinity_term(up, term)
                if pod_matches_term_namespace_and_selector(
                        pod, namespaces, term.label_selector):
                    to_move.append(up)
                    break
        return to_move

    def move_all_to_active_queue(self) -> None:
        """scheduling_queue.go:391-410 (pods keep their nominated entries)."""
        for pod in self._unschedulable.values():
            self._heap_add(pod)
        self._unschedulable.clear()
        self.received_move_request = True

    def waiting_pods_for_node(self, node_name: str) -> List[Pod]:
        return list(self._nominated.get(node_name, []))

    def clear_nominations_for_node(self, node_name: str) -> List[Pod]:
        cleared = self._nominated.pop(node_name, [])
        if cleared:
            # the parked pods lost their claim on the dead node; re-activate
            # them so they re-attempt against the surviving cluster
            self._move_pods_to_active_queue(
                [p for p in cleared if p.key() in self._unschedulable])
        return list(cleared)

    def take_matching(self, pred) -> List[Pod]:
        taken = [p for p in self._active_items.values() if pred(p)]
        taken += [p for p in self._unschedulable.values() if pred(p)]
        for pod in taken:
            self.delete(pod)
        return taken

    def clear_nominations_for_gangs(self, names) -> List[Pod]:
        from tpusim.gang.group import gang_name

        names = set(names)
        cleared: List[Pod] = []
        for node in list(self._nominated):
            stale = [p for p in self._nominated[node]
                     if gang_name(p) in names]
            if not stale:
                continue
            remaining = [p for p in self._nominated[node]
                         if gang_name(p) not in names]
            if remaining:
                self._nominated[node] = remaining
            else:
                del self._nominated[node]
            cleared.extend(stale)
        if cleared:
            # released members re-attempt with the rest of their gang
            self._move_pods_to_active_queue(
                [p for p in cleared if p.key() in self._unschedulable])
        return cleared

    def __len__(self) -> int:
        return len(self._active_items) + len(self._unschedulable)


def new_scheduling_queue(pod_priority_enabled: bool) -> SchedulingQueue:
    """Reference: scheduling_queue.go:64-70."""
    return PriorityQueue() if pod_priority_enabled else FIFO()

"""Scheduling queues: FIFO and the priority queue.

Reference: core/scheduling_queue.go — `NewSchedulingQueue` returns a plain FIFO
unless pod priority is enabled, else the PriorityQueue with an active heap,
an unschedulable map, a nominated-pods index, and the receivedMoveRequest flag
(:49-340). The simulator runs one pod in flight so the queues are small, but
the semantics (ordering, unschedulable parking, move-to-active) are preserved.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Callable, Dict, List, Optional

from tpusim.api.types import Pod
from tpusim.engine.util import get_pod_priority


class SchedulingQueue:
    """Reference: scheduling_queue.go:49-61 (interface)."""

    def add(self, pod: Pod) -> None:
        raise NotImplementedError

    def has_nominated_pods(self) -> bool:
        """True when any parked pod carries a nominated node (those feed the
        feasibility double-pass of later pods, generic_scheduler.go:420-534)."""
        return False

    def add_if_not_present(self, pod: Pod) -> None:
        raise NotImplementedError

    def add_unschedulable_if_not_present(self, pod: Pod) -> None:
        raise NotImplementedError

    def pop(self) -> Optional[Pod]:
        raise NotImplementedError

    def update(self, pod: Pod) -> None:
        raise NotImplementedError

    def delete(self, pod: Pod) -> None:
        raise NotImplementedError

    def move_all_to_active_queue(self) -> None:
        raise NotImplementedError

    def waiting_pods_for_node(self, node_name: str) -> List[Pod]:
        raise NotImplementedError


class FIFO(SchedulingQueue):
    """Reference: scheduling_queue.go:73-139 — wrapper over cache.FIFO."""

    def __init__(self):
        self._order: List[str] = []
        self._items: Dict[str, Pod] = {}

    def add(self, pod: Pod) -> None:
        key = pod.key()
        if key not in self._items:
            self._order.append(key)
        self._items[key] = pod

    def add_if_not_present(self, pod: Pod) -> None:
        if pod.key() not in self._items:
            self.add(pod)

    # FIFO treats unschedulable pods like any other (scheduling_queue.go:87-92)
    def add_unschedulable_if_not_present(self, pod: Pod) -> None:
        self.add_if_not_present(pod)

    def pop(self) -> Optional[Pod]:
        while self._order:
            key = self._order.pop(0)
            pod = self._items.pop(key, None)
            if pod is not None:
                return pod
        return None

    def update(self, pod: Pod) -> None:
        self.add(pod)

    def delete(self, pod: Pod) -> None:
        self._items.pop(pod.key(), None)

    def move_all_to_active_queue(self) -> None:
        pass

    def waiting_pods_for_node(self, node_name: str) -> List[Pod]:
        return []

    def __len__(self) -> int:
        return len(self._items)


class PriorityQueue(SchedulingQueue):
    """Reference: scheduling_queue.go:147-340 — activeQ heap ordered by pod
    priority (ties FIFO by insertion), unschedulableQ parking lot, nominated
    pods index, receivedMoveRequest."""

    def __init__(self):
        self._counter = itertools.count()
        self._active: List[tuple] = []  # (-priority, seq, key)
        self._active_items: Dict[str, Pod] = {}
        self._unschedulable: Dict[str, Pod] = {}
        self._nominated: Dict[str, List[Pod]] = {}  # node name -> pods
        self.received_move_request = False

    # --- nominated-pods index ---

    def _nominated_node(self, pod: Pod) -> str:
        return pod.status.nominated_node_name

    def _add_nominated(self, pod: Pod) -> None:
        node = self._nominated_node(pod)
        if node:
            self._nominated.setdefault(node, []).append(pod)

    def has_nominated_pods(self) -> bool:
        return bool(self._nominated)

    def _delete_nominated(self, pod: Pod) -> None:
        node = self._nominated_node(pod)
        if node and node in self._nominated:
            self._nominated[node] = [p for p in self._nominated[node]
                                     if p.key() != pod.key()]
            if not self._nominated[node]:
                del self._nominated[node]

    # --- queue ops ---

    def add(self, pod: Pod) -> None:
        key = pod.key()
        if key in self._unschedulable:
            del self._unschedulable[key]
            self._delete_nominated(pod)
        if key not in self._active_items:
            heapq.heappush(self._active,
                           (-get_pod_priority(pod), next(self._counter), key))
        self._active_items[key] = pod
        self._add_nominated(pod)

    def add_if_not_present(self, pod: Pod) -> None:
        key = pod.key()
        if key in self._unschedulable or key in self._active_items:
            return
        self.add(pod)

    def add_unschedulable_if_not_present(self, pod: Pod) -> None:
        """scheduling_queue.go:214-235: park unless a move request arrived
        while this pod was being scheduled."""
        key = pod.key()
        if key in self._unschedulable or key in self._active_items:
            return
        if self.received_move_request:
            self.add(pod)
        else:
            self._unschedulable[key] = pod
            self._add_nominated(pod)

    def pop(self) -> Optional[Pod]:
        while self._active:
            _, _, key = heapq.heappop(self._active)
            pod = self._active_items.pop(key, None)
            if pod is not None:
                self.received_move_request = False
                return pod
        return None

    def update(self, pod: Pod) -> None:
        key = pod.key()
        if key in self._active_items:
            self._active_items[key] = pod
            return
        if key in self._unschedulable:
            # updates that may make the pod schedulable move it to active
            del self._unschedulable[key]
        self.add(pod)

    def delete(self, pod: Pod) -> None:
        key = pod.key()
        self._delete_nominated(pod)
        self._active_items.pop(key, None)
        self._unschedulable.pop(key, None)

    def move_all_to_active_queue(self) -> None:
        for pod in list(self._unschedulable.values()):
            key = pod.key()
            if key not in self._active_items:
                heapq.heappush(self._active,
                               (-get_pod_priority(pod), next(self._counter), key))
                self._active_items[key] = pod
        self._unschedulable.clear()
        self.received_move_request = True

    def waiting_pods_for_node(self, node_name: str) -> List[Pod]:
        return list(self._nominated.get(node_name, []))

    def __len__(self) -> int:
        return len(self._active_items) + len(self._unschedulable)


def new_scheduling_queue(pod_priority_enabled: bool) -> SchedulingQueue:
    """Reference: scheduling_queue.go:64-70."""
    return PriorityQueue() if pod_priority_enabled else FIFO()

"""SchedulerCache: the assumed-pod lifecycle + generation-based snapshots.

Reference: schedulercache/cache.go — schedulerCache struct (:46-80),
AssumePod/FinishBinding/ForgetPod (:125-197), AddPod confirmation and the
expire path (:199-262), the 30s assumed-pod TTL with the cleanup loop
(:32-44, :434-470), and the generation-checked snapshot
UpdateNodeNameToInfoMap (:83-97).

The lifecycle: scheduleOne optimistically Assumes the pod into the cache so
later pods see it immediately while the bind runs asynchronously
(scheduler.go:431-497); FinishBinding arms the TTL; the informer's Add event
Confirms it (clearing the deadline); an assumed pod whose confirmation never
arrives expires after the TTL and its resources are returned. In this offline
simulator the Bind intercept is synchronous, so confirmation normally lands
before FinishBinding — the machinery is engine behavior kept for parity (and
for callers that drive the seams asynchronously), exercised directly by
tests/test_cache.py.

Clock injection: `now` is a monotonic-seconds callable so tests (and any
replay driver) can control expiry deterministically, instead of the
reference's wall-clock ticker goroutine.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from tpusim.api.types import Node, Pod
from tpusim.engine.resources import NodeInfo

DEFAULT_ASSUMED_POD_TTL = 30.0  # factory.go:156 (30 * time.Second)


@dataclass
class _PodState:
    """cache.go podState: the cached pod + its assumed-expiry bookkeeping."""

    pod: Pod
    deadline: Optional[float] = None     # set by FinishBinding (cache.go:189)
    binding_finished: bool = False


class CacheError(RuntimeError):
    """Invalid lifecycle transition (the Go methods return errors)."""


class SchedulerCache:
    def __init__(self, ttl: float = DEFAULT_ASSUMED_POD_TTL,
                 now: Callable[[], float] = time.monotonic):
        self.ttl = ttl
        self._now = now
        self.nodes: Dict[str, NodeInfo] = {}       # the live view
        self.pod_states: Dict[str, _PodState] = {}
        self.assumed_pods: set = set()

    # --- internal helpers ---

    def _info(self, node_name: str) -> NodeInfo:
        info = self.nodes.get(node_name)
        if info is None:
            info = NodeInfo()
            self.nodes[node_name] = info
        return info

    def _add_to_node(self, pod: Pod) -> None:
        self._info(pod.spec.node_name).add_pod(pod)

    def _remove_from_node(self, pod: Pod) -> None:
        info = self.nodes.get(pod.spec.node_name)
        if info is not None:
            info.remove_pod(pod)
            # cache.go removePod deletes a node entry that has become empty
            # and carries no Node object (:301-306)
            if info.node is None and not info.pods:
                del self.nodes[pod.spec.node_name]

    # --- assumed-pod lifecycle (cache.go:125-197) ---

    def assume_pod(self, pod: Pod) -> None:
        key = pod.key()
        if key in self.pod_states:
            raise CacheError(f"pod {key} is in the cache, so can't be assumed")
        self._add_to_node(pod)
        self.pod_states[key] = _PodState(pod=pod)
        self.assumed_pods.add(key)

    def finish_binding(self, pod: Pod) -> None:
        """Arms the expiry deadline (cache.go:180-197). A no-op when the pod
        was already confirmed — in the synchronous simulator the store's
        Modified event lands before FinishBinding."""
        key = pod.key()
        if key in self.assumed_pods:
            state = self.pod_states[key]
            state.binding_finished = True
            state.deadline = self._now() + self.ttl

    def forget_pod(self, pod: Pod) -> None:
        """cache.go:199-216 — only assumed pods may be forgotten."""
        key = pod.key()
        state = self.pod_states.get(key)
        if state is not None and key in self.assumed_pods:
            self._remove_from_node(state.pod)
            del self.pod_states[key]
            self.assumed_pods.discard(key)
        elif state is not None:
            raise CacheError(f"pod {key} was assumed on {pod.spec.node_name} "
                             "but assigned to a different node")

    # --- confirmed-pod events (cache.go:218-299, informer handlers) ---

    def add_pod(self, pod: Pod) -> None:
        key = pod.key()
        state = self.pod_states.get(key)
        if state is not None and key in self.assumed_pods:
            # the informer confirms the assumed pod; if the apiserver placed
            # it elsewhere, move the accounting (cache.go:226-236)
            if state.pod.spec.node_name != pod.spec.node_name:
                self._remove_from_node(state.pod)
                self._add_to_node(pod)
            else:
                # refresh the cached object without re-counting
                info = self.nodes.get(pod.spec.node_name)
                if info is not None:
                    info.pods = [pod if p.key() == key else p
                                 for p in info.pods]
            self.assumed_pods.discard(key)
            self.pod_states[key] = _PodState(pod=pod)
        elif state is None:
            # plain add (or an expired assumed pod re-added, cache.go:243-246)
            self._add_to_node(pod)
            self.pod_states[key] = _PodState(pod=pod)
        # already-confirmed duplicate Add: ignore (the simulator's Modified
        # events re-deliver the same bound pod)

    def update_pod(self, old: Pod, new: Pod) -> None:
        key = old.key()
        if key in self.assumed_pods:
            raise CacheError(f"assumed pod {key} should not be updated")
        if key in self.pod_states:
            self._remove_from_node(self.pod_states[key].pod)
        self._add_to_node(new)
        self.pod_states[key] = _PodState(pod=new)

    def remove_pod(self, pod: Pod) -> None:
        key = pod.key()
        state = self.pod_states.get(key)
        if state is not None:
            self._remove_from_node(state.pod)
            del self.pod_states[key]
            self.assumed_pods.discard(key)

    def is_assumed_pod(self, pod: Pod) -> bool:
        return pod.key() in self.assumed_pods

    # --- expiry (cache.go:434-470; the 1s ticker becomes an explicit call) ---

    def cleanup_assumed_pods(self, now: Optional[float] = None) -> int:
        """Expire assumed pods whose binding finished and whose deadline
        passed; returns how many expired."""
        if now is None:
            now = self._now()
        expired = 0
        for key in list(self.assumed_pods):
            state = self.pod_states[key]
            if state.binding_finished and state.deadline is not None \
                    and now >= state.deadline:
                self._remove_from_node(state.pod)
                del self.pod_states[key]
                self.assumed_pods.discard(key)
                expired += 1
        return expired

    # --- node events (cache.go:308-345) ---

    def add_node(self, node: Node) -> None:
        self._info(node.name).set_node(node)

    def update_node(self, node: Node) -> None:
        self._info(node.name).set_node(node)

    def remove_node(self, node: Node) -> None:
        info = self.nodes.get(node.name)
        if info is None:
            return
        info.remove_node()
        if not info.pods:
            del self.nodes[node.name]

    # --- snapshot (cache.go:83-97) ---

    def update_node_name_to_info_map(self, info_map: Dict[str, NodeInfo]
                                     ) -> Dict[str, NodeInfo]:
        """Refresh `info_map` in place: clone only nodes whose generation
        moved, drop deleted nodes. Mutating the returned snapshot never
        touches the live cache."""
        for name, info in self.nodes.items():
            existing = info_map.get(name)
            if existing is None or existing.generation != info.generation:
                info_map[name] = info.clone()
        for name in list(info_map):
            if name not in self.nodes:
                del info_map[name]
        return info_map

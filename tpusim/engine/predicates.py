"""Fit predicates with the reference's ordering, semantics, and failure reasons.

Reference: algorithm/predicates/predicates.go. Each predicate has signature
``(pod, meta, node_info) -> (fits, [PredicateFailureReason])``; podFitsOnNode
runs them in PREDICATES_ORDERING and short-circuits on first failure unless
always_check_all_predicates (generic_scheduler.go:420-534).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from tpusim.api.types import (
    LABEL_HOSTNAME,
    RESOURCE_CPU,
    RESOURCE_EPHEMERAL_STORAGE,
    RESOURCE_MEMORY,
    RESOURCE_NVIDIA_GPU,
    RESOURCE_PODS,
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    Node,
    Pod,
    find_matching_untolerated_taint,
)
from tpusim.engine import errors as err
from tpusim.engine.resources import (
    NodeInfo,
    get_container_ports,
    get_resource_request,
    is_pod_best_effort,
)

# predicates.go:130-136 — evaluation (and reason-reporting) order
CHECK_NODE_CONDITION_PRED = "CheckNodeCondition"
CHECK_NODE_UNSCHEDULABLE_PRED = "CheckNodeUnschedulable"
GENERAL_PRED = "GeneralPredicates"
HOSTNAME_PRED = "HostName"
POD_FITS_HOST_PORTS_PRED = "PodFitsHostPorts"
MATCH_NODE_SELECTOR_PRED = "MatchNodeSelector"
POD_FITS_RESOURCES_PRED = "PodFitsResources"
NO_DISK_CONFLICT_PRED = "NoDiskConflict"
POD_TOLERATES_NODE_TAINTS_PRED = "PodToleratesNodeTaints"
POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED = "PodToleratesNodeNoExecuteTaints"
CHECK_NODE_LABEL_PRESENCE_PRED = "CheckNodeLabelPresence"
CHECK_SERVICE_AFFINITY_PRED = "CheckServiceAffinity"
MAX_EBS_VOLUME_COUNT_PRED = "MaxEBSVolumeCount"
MAX_GCE_PD_VOLUME_COUNT_PRED = "MaxGCEPDVolumeCount"
MAX_AZURE_DISK_VOLUME_COUNT_PRED = "MaxAzureDiskVolumeCount"
CHECK_VOLUME_BINDING_PRED = "CheckVolumeBinding"
NO_VOLUME_ZONE_CONFLICT_PRED = "NoVolumeZoneConflict"
CHECK_NODE_MEMORY_PRESSURE_PRED = "CheckNodeMemoryPressure"
CHECK_NODE_DISK_PRESSURE_PRED = "CheckNodeDiskPressure"
MATCH_INTERPOD_AFFINITY_PRED = "MatchInterPodAffinity"

PREDICATES_ORDERING = [
    CHECK_NODE_CONDITION_PRED, CHECK_NODE_UNSCHEDULABLE_PRED,
    GENERAL_PRED, HOSTNAME_PRED, POD_FITS_HOST_PORTS_PRED,
    MATCH_NODE_SELECTOR_PRED, POD_FITS_RESOURCES_PRED, NO_DISK_CONFLICT_PRED,
    POD_TOLERATES_NODE_TAINTS_PRED, POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED,
    CHECK_NODE_LABEL_PRESENCE_PRED,
    CHECK_SERVICE_AFFINITY_PRED, MAX_EBS_VOLUME_COUNT_PRED, MAX_GCE_PD_VOLUME_COUNT_PRED,
    MAX_AZURE_DISK_VOLUME_COUNT_PRED, CHECK_VOLUME_BINDING_PRED, NO_VOLUME_ZONE_CONFLICT_PRED,
    CHECK_NODE_MEMORY_PRESSURE_PRED, CHECK_NODE_DISK_PRESSURE_PRED,
    MATCH_INTERPOD_AFFINITY_PRED,
]

PredicateResult = tuple  # (bool, List[PredicateFailureReason])
FitPredicate = Callable[[Pod, Optional["PredicateMetadata"], NodeInfo], PredicateResult]


# ---------------------------------------------------------------------------
# predicate metadata (reference: algorithm/predicates/metadata.go:47-190)
# ---------------------------------------------------------------------------


@dataclass
class MatchingAntiAffinityTerm:
    term: object  # PodAffinityTerm
    node: Node


@dataclass
class PredicateMetadata:
    pod: Pod
    pod_best_effort: bool
    pod_request: object  # Resource
    pod_ports: list
    # existing-pod full name -> [MatchingAntiAffinityTerm] whose selector matched self.pod
    matching_anti_affinity_terms: Dict[str, List[MatchingAntiAffinityTerm]] = field(
        default_factory=dict)
    # extended resources managed (and ignored) by an extender
    # (RegisterPredicateMetadataProducerWithExtendedResourceOptions,
    # predicates.go:718-725)
    ignored_extended_resources: Optional[set] = None

    def add_pod(self, added_pod: Pod, node: Node) -> None:
        """metadata.go AddPod — incremental update for preemption simulations."""
        if added_pod.key() == self.pod.key():
            raise ValueError("added pod cannot be the same as the original pod")
        terms = get_matching_anti_affinity_terms_of_existing_pod(self.pod, added_pod, node)
        if terms:
            self.matching_anti_affinity_terms.setdefault(
                added_pod.key(), []).extend(terms)

    def remove_pod(self, deleted_pod: Pod) -> None:
        if deleted_pod.key() == self.pod.key():
            raise ValueError("deleted pod cannot be the same as the original pod")
        self.matching_anti_affinity_terms.pop(deleted_pod.key(), None)

    def shallow_copy(self) -> "PredicateMetadata":
        return PredicateMetadata(
            pod=self.pod,
            pod_best_effort=self.pod_best_effort,
            pod_request=self.pod_request,
            pod_ports=list(self.pod_ports),
            matching_anti_affinity_terms={
                k: list(v) for k, v in self.matching_anti_affinity_terms.items()},
        )


def get_namespaces_from_pod_affinity_term(pod: Pod, term) -> set:
    """priorityutil.GetNamespacesFromPodAffinityTerm: empty namespaces default
    to the term-owning pod's namespace."""
    if term.namespaces:
        return set(term.namespaces)
    return {pod.namespace}


def pod_matches_term_namespace_and_selector(target_pod: Pod, namespaces: set, selector) -> bool:
    """priorityutil.PodMatchesTermsNamespaceAndSelector; a nil selector matches
    nothing (LabelSelectorAsSelector(nil) == labels.Nothing())."""
    if target_pod.namespace not in namespaces:
        return False
    if selector is None:
        return False
    return selector.matches(target_pod.metadata.labels)


def nodes_have_same_topology_key(node_a: Optional[Node], node_b: Optional[Node],
                                 topology_key: str) -> bool:
    """priorityutil.NodesHaveSameTopologyKey."""
    if not topology_key or node_a is None or node_b is None:
        return False
    a = node_a.metadata.labels.get(topology_key)
    b = node_b.metadata.labels.get(topology_key)
    return a is not None and b is not None and a == b


def get_pod_affinity_terms(pod_affinity) -> list:
    """GetPodAffinityTerms: required terms only."""
    return list(pod_affinity.required) if pod_affinity is not None else []


def get_pod_anti_affinity_terms(pod_anti_affinity) -> list:
    return list(pod_anti_affinity.required) if pod_anti_affinity is not None else []


def get_matching_anti_affinity_terms_of_existing_pod(
        new_pod: Pod, existing_pod: Pod, node: Node) -> List[MatchingAntiAffinityTerm]:
    """predicates.go getMatchingAntiAffinityTermsOfExistingPod."""
    result: List[MatchingAntiAffinityTerm] = []
    affinity = existing_pod.spec.affinity
    if affinity is not None and affinity.pod_anti_affinity is not None:
        for term in get_pod_anti_affinity_terms(affinity.pod_anti_affinity):
            namespaces = get_namespaces_from_pod_affinity_term(existing_pod, term)
            if pod_matches_term_namespace_and_selector(new_pod, namespaces, term.label_selector):
                result.append(MatchingAntiAffinityTerm(term=term, node=node))
    return result


def get_matching_anti_affinity_terms(
        pod: Pod, node_info_map: Dict[str, NodeInfo]) -> Dict[str, List[MatchingAntiAffinityTerm]]:
    """predicates.go getMatchingAntiAffinityTerms, serial form."""
    result: Dict[str, List[MatchingAntiAffinityTerm]] = {}
    for node_info in node_info_map.values():
        node = node_info.node
        if node is None:
            continue
        for existing_pod in node_info.pods:
            terms = get_matching_anti_affinity_terms_of_existing_pod(pod, existing_pod, node)
            if terms:
                result.setdefault(existing_pod.key(), []).extend(terms)
    return result


def get_predicate_metadata(pod: Pod,
                           node_info_map: Dict[str, NodeInfo],
                           ignored_extended_resources: Optional[set] = None
                           ) -> PredicateMetadata:
    """The PredicateMetadataProducer (metadata.go:47-75)."""
    return PredicateMetadata(
        pod=pod,
        pod_best_effort=is_pod_best_effort(pod),
        pod_request=get_resource_request(pod),
        pod_ports=get_container_ports(pod),
        matching_anti_affinity_terms=get_matching_anti_affinity_terms(pod, node_info_map),
        ignored_extended_resources=ignored_extended_resources,
    )


# ---------------------------------------------------------------------------
# simple predicates
# ---------------------------------------------------------------------------


def pod_fits_resources(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
    """Reference: predicates.go:706-776."""
    if node_info.node is None:
        raise ValueError("node not found")
    fails: list = []
    allowed = node_info.allowed_pod_number()
    if len(node_info.pods) + 1 > allowed:
        fails.append(err.InsufficientResourceError(
            RESOURCE_PODS, 1, len(node_info.pods), allowed))

    pod_request = meta.pod_request if meta is not None else get_resource_request(pod)
    if (pod_request.milli_cpu == 0 and pod_request.memory == 0
            and pod_request.nvidia_gpu == 0 and pod_request.ephemeral_storage == 0
            and not pod_request.scalar):
        return (not fails), fails

    alloc = node_info.allocatable_resource
    used = node_info.requested_resource
    if alloc.milli_cpu < pod_request.milli_cpu + used.milli_cpu:
        fails.append(err.InsufficientResourceError(
            RESOURCE_CPU, pod_request.milli_cpu, used.milli_cpu, alloc.milli_cpu))
    if alloc.memory < pod_request.memory + used.memory:
        fails.append(err.InsufficientResourceError(
            RESOURCE_MEMORY, pod_request.memory, used.memory, alloc.memory))
    if alloc.nvidia_gpu < pod_request.nvidia_gpu + used.nvidia_gpu:
        fails.append(err.InsufficientResourceError(
            RESOURCE_NVIDIA_GPU, pod_request.nvidia_gpu, used.nvidia_gpu, alloc.nvidia_gpu))
    if alloc.ephemeral_storage < pod_request.ephemeral_storage + used.ephemeral_storage:
        fails.append(err.InsufficientResourceError(
            RESOURCE_EPHEMERAL_STORAGE, pod_request.ephemeral_storage,
            used.ephemeral_storage, alloc.ephemeral_storage))
    ignored = getattr(meta, "ignored_extended_resources", None) or set()
    for name, quant in pod_request.scalar.items():
        # extended resources managed by an IgnoredByScheduler extender are
        # skipped (predicates.go:754-761)
        if "/" in name and name in ignored:
            continue
        if alloc.scalar.get(name, 0) < quant + used.scalar.get(name, 0):
            fails.append(err.InsufficientResourceError(
                name, quant, used.scalar.get(name, 0), alloc.scalar.get(name, 0)))
    return (not fails), fails


def pod_matches_node_labels(pod: Pod, node: Node) -> bool:
    """Reference: predicates.go:778-846 (podMatchesNodeLabels +
    nodeMatchesNodeSelectorTerms): nodeSelector map AND required
    node-affinity. Terms are ORed in order; an empty term list matches
    nothing; a term whose selector fails validation (match_result None —
    NodeSelectorRequirementsAsSelector error) makes the whole affinity a
    non-match immediately."""
    if pod.spec.node_selector:
        for k, v in pod.spec.node_selector.items():
            if node.metadata.labels.get(k) != v:
                return False
    affinity = pod.spec.affinity
    if affinity is not None and affinity.node_affinity is not None:
        na = affinity.node_affinity
        if na.required_terms is not None:
            for t in na.required_terms:
                r = t.match_result(node.metadata.labels)
                if r is None:
                    return False  # parse error: "regarding as not match"
                if r:
                    break
            else:
                return False
    return True


def pod_match_node_selector(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
    if node_info.node is None:
        raise ValueError("node not found")
    if pod_matches_node_labels(pod, node_info.node):
        return True, []
    return False, [err.ERR_NODE_SELECTOR_NOT_MATCH]


def pod_fits_host(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
    """Reference: predicates.go:853-865."""
    if not pod.spec.node_name:
        return True, []
    if node_info.node is None:
        raise ValueError("node not found")
    if pod.spec.node_name == node_info.node.name:
        return True, []
    return False, [err.ERR_POD_NOT_MATCH_HOST_NAME]


def pod_fits_host_ports(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
    """Reference: predicates.go:1019-1039."""
    want_ports = meta.pod_ports if meta is not None else get_container_ports(pod)
    if not want_ports:
        return True, []
    existing = node_info.used_ports
    for port in want_ports:
        if existing.check_conflict(port.host_ip, port.protocol, port.host_port):
            return False, [err.ERR_POD_NOT_FITS_HOST_PORTS]
    return True, []


def general_predicates(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
    """Reference: predicates.go:1059-1123 — PodFitsResources + PodFitsHost +
    PodFitsHostPorts + PodMatchNodeSelector, all evaluated (no short-circuit)."""
    fails: list = []
    for pred in (pod_fits_resources, pod_fits_host, pod_fits_host_ports,
                 pod_match_node_selector):
        fit, reasons = pred(pod, meta, node_info)
        if not fit:
            fails.extend(reasons)
    return (not fails), fails


def _taint_filter_no_schedule_no_execute(taint) -> bool:
    return taint.effect in (TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE)


def _taint_filter_no_execute(taint) -> bool:
    return taint.effect == TAINT_NO_EXECUTE


def pod_tolerates_node_taints(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
    """Reference: predicates.go:1465-1478."""
    taint = find_matching_untolerated_taint(
        node_info.taints, pod.spec.tolerations, _taint_filter_no_schedule_no_execute)
    if taint is None:
        return True, []
    return False, [err.ERR_TAINTS_TOLERATIONS_NOT_MATCH]


def pod_tolerates_node_no_execute_taints(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
    taint = find_matching_untolerated_taint(
        node_info.taints, pod.spec.tolerations, _taint_filter_no_execute)
    if taint is None:
        return True, []
    return False, [err.ERR_TAINTS_TOLERATIONS_NOT_MATCH]


def check_node_memory_pressure(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
    """Reference: predicates.go:1502-1521 — only BestEffort pods are rejected."""
    best_effort = meta.pod_best_effort if meta is not None else is_pod_best_effort(pod)
    if not best_effort:
        return True, []
    if node_info.memory_pressure_condition():
        return False, [err.ERR_NODE_UNDER_MEMORY_PRESSURE]
    return True, []


def check_node_disk_pressure(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
    if node_info.disk_pressure_condition():
        return False, [err.ERR_NODE_UNDER_DISK_PRESSURE]
    return True, []


def check_node_condition(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
    """Reference: predicates.go:1533-1561 — Ready/OutOfDisk/NetworkUnavailable
    conditions plus spec.unschedulable."""
    if node_info is None or node_info.node is None:
        return False, [err.ERR_NODE_UNKNOWN_CONDITION]
    node = node_info.node
    reasons: list = []
    for cond in node.status.conditions:
        if cond.type == "Ready" and cond.status != "True":
            reasons.append(err.ERR_NODE_NOT_READY)
        elif cond.type == "OutOfDisk" and cond.status != "False":
            reasons.append(err.ERR_NODE_OUT_OF_DISK)
        elif cond.type == "NetworkUnavailable" and cond.status != "False":
            reasons.append(err.ERR_NODE_NETWORK_UNAVAILABLE)
    if node.spec.unschedulable:
        reasons.append(err.ERR_NODE_UNSCHEDULABLE)
    return (not reasons), reasons


def check_node_unschedulable(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
    """CheckNodeUnschedulablePred (registered under TaintNodesByCondition)."""
    if node_info.node is None:
        return False, [err.ERR_NODE_UNKNOWN_CONDITION]
    if node_info.node.spec.unschedulable:
        return False, [err.ERR_NODE_UNSCHEDULABLE]
    return True, []


# ---------------------------------------------------------------------------
# volume predicates (predicates.go:220-276, 288-533, 1563-1619)
# ---------------------------------------------------------------------------


def _have_overlap(a: list, b: list) -> bool:
    """predicates.go haveOverlap — any shared element."""
    if len(a) > len(b):
        a, b = b, a
    s = set(a)
    return any(x in s for x in b)


def is_volume_conflict(volume, pod: Pod) -> bool:
    """predicates.go isVolumeConflict:220-264 — GCE PD (read-only OK),
    AWS EBS (any sharing conflicts), ISCSI (same IQN, not both read-only),
    RBD (overlapping monitors + same pool/image, not both read-only)."""
    gce, ebs = volume.gce_persistent_disk, volume.aws_elastic_block_store
    rbd, iscsi = volume.rbd, volume.iscsi
    if gce is None and ebs is None and rbd is None and iscsi is None:
        return False
    for existing in pod.spec.volumes:
        egce = existing.gce_persistent_disk
        if gce is not None and egce is not None:
            if gce.get("pdName") == egce.get("pdName") and not (
                    gce.get("readOnly") and egce.get("readOnly")):
                return True
        eebs = existing.aws_elastic_block_store
        if ebs is not None and eebs is not None:
            if ebs.get("volumeID") == eebs.get("volumeID"):
                return True
        eiscsi = existing.iscsi
        if iscsi is not None and eiscsi is not None:
            if iscsi.get("iqn") == eiscsi.get("iqn") and not (
                    iscsi.get("readOnly") and eiscsi.get("readOnly")):
                return True
        erbd = existing.rbd
        if rbd is not None and erbd is not None:
            if (_have_overlap(rbd.get("monitors") or [], erbd.get("monitors") or [])
                    and rbd.get("pool") == erbd.get("pool")
                    and rbd.get("image") == erbd.get("image")
                    and not (rbd.get("readOnly") and erbd.get("readOnly"))):
                return True
    return False


def no_disk_conflict(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
    """Reference: predicates.go NoDiskConflict:266-276."""
    for volume in pod.spec.volumes:
        for existing in node_info.pods:
            if is_volume_conflict(volume, existing):
                return False, [err.ERR_DISK_CONFLICT]
    return True, []


# MaxPDVolumeCount (predicates.go:288-460)

EBS_VOLUME_FILTER_TYPE = "EBS"
GCE_PD_VOLUME_FILTER_TYPE = "GCE"
AZURE_DISK_VOLUME_FILTER_TYPE = "AzureDisk"

DEFAULT_MAX_EBS_VOLUMES = 39
DEFAULT_MAX_GCE_PD_VOLUMES = 16
DEFAULT_MAX_AZURE_DISK_VOLUMES = 16
# (EBS, GCE PD, AzureDisk) — the tuple order the jax backend's MaxPD kernel
# uses; single source for both engines
DEFAULT_MAXPD_LIMITS = (DEFAULT_MAX_EBS_VOLUMES, DEFAULT_MAX_GCE_PD_VOLUMES,
                        DEFAULT_MAX_AZURE_DISK_VOLUMES)
KUBE_MAX_PD_VOLS_ENV = "KUBE_MAX_PD_VOLS"

_VOLUME_FILTERS = {
    # (volume source accessor, PV source accessor, id field)
    EBS_VOLUME_FILTER_TYPE: (
        lambda v: v.aws_elastic_block_store, lambda pv: pv.aws_elastic_block_store,
        "volumeID", DEFAULT_MAX_EBS_VOLUMES),
    GCE_PD_VOLUME_FILTER_TYPE: (
        lambda v: v.gce_persistent_disk, lambda pv: pv.gce_persistent_disk,
        "pdName", DEFAULT_MAX_GCE_PD_VOLUMES),
    AZURE_DISK_VOLUME_FILTER_TYPE: (
        lambda v: v.azure_disk, lambda pv: pv.azure_disk,
        "diskName", DEFAULT_MAX_AZURE_DISK_VOLUMES),
}


def get_max_vols(default: int) -> int:
    """predicates.go getMaxVols: KUBE_MAX_PD_VOLS env override when valid."""
    import os

    raw = os.environ.get(KUBE_MAX_PD_VOLS_ENV, "")
    if raw:
        try:
            parsed = int(raw)
        except ValueError:
            return default
        if parsed > 0:
            return parsed
    return default


def effective_maxpd_limits() -> tuple:
    """The three per-type limits with the env override applied."""
    return tuple(get_max_vols(d) for d in DEFAULT_MAXPD_LIMITS)


def make_max_pd_volume_count_predicate(
        filter_type: str, pvc_getter=None, pv_getter=None,
        max_volumes: Optional[int] = None) -> FitPredicate:
    """Reference: predicates.go NewMaxPDVolumeCountPredicate:306-345 +
    filterVolumes:361-420 + predicate:422-460. Counts unique relevant volume
    ids (direct + resolved through PVC->PV); unresolvable PVCs count
    conservatively under a synthetic id."""
    if filter_type not in _VOLUME_FILTERS:
        raise KeyError(
            f"Wrong filterName, Only Support {EBS_VOLUME_FILTER_TYPE} "
            f"{GCE_PD_VOLUME_FILTER_TYPE} {AZURE_DISK_VOLUME_FILTER_TYPE}")
    vol_src, pv_src, id_field, default_max = _VOLUME_FILTERS[filter_type]
    limit = max_volumes if max_volumes is not None else get_max_vols(default_max)
    pvc_getter = pvc_getter or (lambda namespace, name: None)
    pv_getter = pv_getter or (lambda name: None)

    def filter_volumes(volumes, namespace: str, filtered: set) -> None:
        for vol in volumes:
            src = vol_src(vol)
            if src is not None:
                filtered.add((filter_type, src.get(id_field, "")))
                continue
            pvc_name = vol.pvc_name
            if pvc_name is None:
                continue
            if pvc_name == "":
                raise err.PredicateError("PersistentVolumeClaim had no name")
            # stand-in id: unresolvable claims count toward the limit
            # (predicates.go:379-410 logs and assumes relevant)
            pvc_id = ("pvc", f"{namespace}/{pvc_name}")
            pvc = pvc_getter(namespace, pvc_name)
            if pvc is None:
                filtered.add(pvc_id)
                continue
            pv_name = pvc.volume_name
            if not pv_name:
                filtered.add(pvc_id)
                continue
            pv = pv_getter(pv_name)
            if pv is None:
                filtered.add(pvc_id)
                continue
            pv_source = pv_src(pv)
            if pv_source is not None:
                filtered.add((filter_type, pv_source.get(id_field, "")))

    def max_pd_volume_count(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
        if not pod.spec.volumes:
            return True, []
        new_volumes: set = set()
        filter_volumes(pod.spec.volumes, pod.namespace, new_volumes)
        if not new_volumes:
            return True, []
        existing: set = set()
        for existing_pod in node_info.pods:
            filter_volumes(existing_pod.spec.volumes, existing_pod.namespace,
                           existing)
        if len(existing | new_volumes) > limit:
            return False, [err.ERR_MAX_VOLUME_COUNT_EXCEEDED]
        return True, []

    max_pd_volume_count.__name__ = f"max_{filter_type.lower()}_volume_count"
    return max_pd_volume_count


# NoVolumeZoneConflict (predicates.go:510-533 VolumeZoneChecker.predicate)

LABEL_ZONE_FAILURE_DOMAIN = "failure-domain.beta.kubernetes.io/zone"
LABEL_ZONE_REGION = "failure-domain.beta.kubernetes.io/region"
_ZONE_LABELS = (LABEL_ZONE_FAILURE_DOMAIN, LABEL_ZONE_REGION)


def label_zones_to_set(value: str) -> set:
    """volumeutil.LabelZonesToSet: '__'-separated zone list; raises on an
    empty element (ZonesToSet errors)."""
    zones = set()
    for zone in value.split("__"):
        if zone == "":
            raise ValueError(
                f"{value} content is not valid, content should not be empty")
        zones.add(zone)
    return zones


def make_no_volume_zone_conflict_predicate(
        pvc_getter=None, pv_getter=None, class_getter=None,
        volume_scheduling_enabled: bool = False) -> FitPredicate:
    """Reference: predicates.go VolumeZoneChecker.predicate:510-533 — bound
    PVs' zone/region labels must include the node's value for the same label."""
    pvc_getter = pvc_getter or (lambda namespace, name: None)
    pv_getter = pv_getter or (lambda name: None)
    class_getter = class_getter or (lambda name: None)

    def no_volume_zone_conflict(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
        if not pod.spec.volumes:
            return True, []
        node = node_info.node
        if node is None:
            raise err.PredicateError("node not found")
        constraints = {k: v for k, v in node.metadata.labels.items()
                       if k in _ZONE_LABELS}
        if not constraints:
            return True, []
        for volume in pod.spec.volumes:
            pvc_name = volume.pvc_name
            if pvc_name is None:
                continue
            if pvc_name == "":
                raise err.PredicateError("PersistentVolumeClaim had no name")
            pvc = pvc_getter(pod.namespace, pvc_name)
            if pvc is None:
                raise err.PredicateError(
                    f'PersistentVolumeClaim was not found: "{pvc_name}"')
            pv_name = pvc.volume_name
            if not pv_name:
                if volume_scheduling_enabled:
                    sc_name = pvc.storage_class_name
                    if sc_name:
                        sc = class_getter(sc_name)
                        if sc is not None:
                            from tpusim.api.types import VOLUME_BINDING_WAIT

                            if sc.volume_binding_mode is None:
                                raise err.PredicateError(
                                    "VolumeBindingMode not set for "
                                    f'StorageClass "{sc_name}"')
                            if sc.volume_binding_mode == VOLUME_BINDING_WAIT:
                                continue  # skip unbound delayed-binding volumes
                raise err.PredicateError(
                    f'PersistentVolumeClaim is not bound: "{pvc_name}"')
            pv = pv_getter(pv_name)
            if pv is None:
                raise err.PredicateError(
                    f'PersistentVolume not found: "{pv_name}"')
            for k, v in pv.metadata.labels.items():
                if k not in _ZONE_LABELS:
                    continue
                node_value = constraints.get(k)
                try:
                    volume_zones = label_zones_to_set(v)
                except ValueError:
                    continue  # unparsable label ignored (predicates.go:555-558)
                if node_value not in volume_zones:
                    return False, [err.ERR_VOLUME_ZONE_CONFLICT]
        return True, []

    return no_volume_zone_conflict


def make_check_volume_binding_predicate(binder) -> FitPredicate:
    """Reference: predicates.go VolumeBindingChecker.predicate:1586-1619 over a
    volume.VolumeBinder; trivially true while the VolumeScheduling feature gate
    is off (the reference's default)."""
    from tpusim.engine.volume import VolumeBinderError

    def check_volume_binding(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
        if binder is None or not binder.enabled:
            return True, []
        node = node_info.node
        if node is None:
            raise err.PredicateError("node not found")
        try:
            unbound_ok, bound_ok = binder.find_pod_volumes(pod, node)
        except VolumeBinderError as exc:
            raise err.PredicateError(str(exc))
        reasons = []
        if not bound_ok:
            reasons.append(err.ERR_VOLUME_NODE_CONFLICT)
        if not unbound_ok:
            reasons.append(err.ERR_VOLUME_BIND_CONFLICT)
        if reasons:
            return False, reasons
        return True, []

    return check_volume_binding


# ---------------------------------------------------------------------------
# label-presence / service-affinity (policy-configured)
# ---------------------------------------------------------------------------


def make_node_label_presence_predicate(labels: List[str], presence: bool) -> FitPredicate:
    """Reference: predicates.go NewNodeLabelPredicate (policy-configured)."""

    def check_node_label_presence(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
        if node_info.node is None:
            raise ValueError("node not found")
        node_labels = node_info.node.metadata.labels
        for label in labels:
            exists = label in node_labels
            if exists != presence:
                return False, [err.ERR_NODE_LABEL_PRESENCE_VIOLATED]
        return True, []

    return check_node_label_presence


def make_service_affinity_predicate(affinity_labels: List[str],
                                    pod_lister: Callable[[], List[Pod]],
                                    service_lister: Callable[[], list],
                                    node_getter: Callable[[str], Optional[Node]] = lambda name: None,
                                    ) -> FitPredicate:
    """Reference: predicates.go NewServiceAffinityPredicate (policy-configured).

    The pod must land on a node whose values for ``affinity_labels`` equal the
    values on the node of an arbitrary existing pod of the same service (or the
    pod's own nodeSelector values when no service peer exists). ``node_getter``
    resolves a peer pod's nodeName to its Node.
    """

    def check_service_affinity(pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
        if node_info.node is None:
            raise ValueError("node not found")
        # labels the pod itself pins via its nodeSelector
        affinity_selector = {k: v for k, v in (pod.spec.node_selector or {}).items()
                             if k in affinity_labels}
        unresolved = [l for l in affinity_labels if l not in affinity_selector]
        if unresolved:
            services = [s for s in service_lister()
                        if s.namespace == pod.namespace and s.selector
                        and all(pod.metadata.labels.get(k) == v
                                for k, v in s.selector.items())]
            if services:
                selector = services[0].selector
                service_pods = [p for p in pod_lister()
                                if p.namespace == pod.namespace
                                and all(p.metadata.labels.get(k) == v
                                        for k, v in selector.items())]
                if service_pods:
                    first = service_pods[0]
                    if first.spec.node_name:
                        other = node_getter(first.spec.node_name)
                        # the factory wires the scheduler cache's NodeInfo
                        # getter (providers.py register_custom_fit_predicate);
                        # accept a bare Node too
                        other_node = getattr(other, "node", other)
                        if other_node is not None:
                            labels = other_node.metadata.labels
                            for l in unresolved:
                                if l in labels:
                                    affinity_selector[l] = labels[l]
        node_labels = node_info.node.metadata.labels
        for k, v in affinity_selector.items():
            if node_labels.get(k) != v:
                return False, [err.ERR_SERVICE_AFFINITY_VIOLATED]
        return True, []

    return check_service_affinity


# ---------------------------------------------------------------------------
# inter-pod affinity (reference: predicates.go:1125-1450, PodAffinityChecker)
# ---------------------------------------------------------------------------


class PodAffinityChecker:
    def __init__(self, node_info_getter: Callable[[str], Optional[NodeInfo]],
                 pod_lister: Callable[[], List[Pod]]):
        self._node_info = node_info_getter
        self._pod_lister = pod_lister

    def _filtered_pods(self, node_info: NodeInfo) -> List[Pod]:
        """podLister.FilteredList(nodeInfo.Filter): drop pods that claim
        node_info's node but aren't tracked in it; pods elsewhere pass."""
        node = node_info.node
        tracked = {p.key() for p in node_info.pods}
        out = []
        for p in self._pod_lister():
            if node is not None and p.spec.node_name == node.name and p.key() not in tracked:
                continue
            out.append(p)
        return out

    def interpod_affinity_matches(self, pod: Pod, meta, node_info: NodeInfo) -> PredicateResult:
        if node_info.node is None:
            raise ValueError("node not found")
        failed = self._satisfies_existing_pods_anti_affinity(pod, meta, node_info)
        if failed is not None:
            return False, [err.ERR_POD_AFFINITY_NOT_MATCH, failed]
        affinity = pod.spec.affinity
        if affinity is None or (affinity.pod_affinity is None
                                and affinity.pod_anti_affinity is None):
            return True, []
        failed = self._satisfies_pods_affinity_anti_affinity(pod, node_info, affinity)
        if failed is not None:
            return False, [err.ERR_POD_AFFINITY_NOT_MATCH, failed]
        return True, []

    def _satisfies_existing_pods_anti_affinity(self, pod: Pod, meta,
                                               node_info: NodeInfo):
        node = node_info.node
        if meta is not None:
            matching_terms = meta.matching_anti_affinity_terms
        else:
            filtered = self._filtered_pods(node_info)
            matching_terms = {}
            for existing in filtered:
                existing_node_info = self._node_info(existing.spec.node_name)
                if existing_node_info is None or existing_node_info.node is None:
                    continue
                terms = get_matching_anti_affinity_terms_of_existing_pod(
                    pod, existing, existing_node_info.node)
                if terms:
                    matching_terms.setdefault(existing.key(), []).extend(terms)
        for terms in matching_terms.values():
            for mt in terms:
                if not mt.term.topology_key:
                    return err.ERR_EXISTING_PODS_ANTI_AFFINITY_RULES_NOT_MATCH
                if nodes_have_same_topology_key(node, mt.node, mt.term.topology_key):
                    return err.ERR_EXISTING_PODS_ANTI_AFFINITY_RULES_NOT_MATCH
        return None

    def _any_pod_matches_term(self, pod: Pod, pods: List[Pod], node_info: NodeInfo,
                              term) -> tuple[bool, bool]:
        if not term.topology_key:
            raise ValueError("empty topologyKey is not allowed except for "
                             "PreferredDuringScheduling pod anti-affinity")
        matching_pod_exists = False
        namespaces = get_namespaces_from_pod_affinity_term(pod, term)
        selector = term.label_selector
        # predicates.go: topologyKey == hostname restricts the search to this node
        pods_to_check = node_info.pods if term.topology_key == LABEL_HOSTNAME else pods
        for existing in pods_to_check:
            if pod_matches_term_namespace_and_selector(existing, namespaces, selector):
                matching_pod_exists = True
                existing_node_info = self._node_info(existing.spec.node_name)
                existing_node = existing_node_info.node if existing_node_info else None
                if nodes_have_same_topology_key(node_info.node, existing_node,
                                                term.topology_key):
                    return True, True
        return False, matching_pod_exists

    def _satisfies_pods_affinity_anti_affinity(self, pod: Pod, node_info: NodeInfo,
                                               affinity):
        filtered = self._filtered_pods(node_info)
        for term in get_pod_affinity_terms(affinity.pod_affinity):
            try:
                term_matches, matching_pod_exists = self._any_pod_matches_term(
                    pod, filtered, node_info, term)
            except ValueError:
                return err.ERR_POD_AFFINITY_RULES_NOT_MATCH
            if not term_matches:
                # first-pod-of-its-group special case (predicates.go:1303-1320)
                if matching_pod_exists:
                    return err.ERR_POD_AFFINITY_RULES_NOT_MATCH
                namespaces = get_namespaces_from_pod_affinity_term(pod, term)
                if not pod_matches_term_namespace_and_selector(
                        pod, namespaces, term.label_selector):
                    return err.ERR_POD_AFFINITY_RULES_NOT_MATCH
        for term in get_pod_anti_affinity_terms(affinity.pod_anti_affinity):
            try:
                term_matches, _ = self._any_pod_matches_term(pod, filtered, node_info, term)
            except ValueError:
                return err.ERR_POD_ANTI_AFFINITY_RULES_NOT_MATCH
            if term_matches:
                return err.ERR_POD_ANTI_AFFINITY_RULES_NOT_MATCH
        return None


def make_pod_affinity_predicate(node_info_getter, pod_lister) -> FitPredicate:
    return PodAffinityChecker(node_info_getter, pod_lister).interpod_affinity_matches

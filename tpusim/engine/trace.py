"""utiltrace analog: named traces with steps, logged when slow.

Reference: vendor/k8s.io/apiserver/pkg/util/trace/trace.go (Trace/Step/
LogIfLong) as used by core/generic_scheduler.go:113-165 — a per-pod
"Scheduling ns/name" trace with steps "Computing predicates", "Prioritizing",
"Selecting host", logged when the total exceeds 100ms with per-step
thresholding (threshold / (len(steps)+1)).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional, Tuple

logger = logging.getLogger("tpusim.trace")

SLOW_SCHEDULE_THRESHOLD = 0.100  # 100ms (generic_scheduler.go:114)


class Trace:
    def __init__(self, name: str, _now: Callable[[], float] = time.perf_counter):
        self.name = name
        self._now = _now
        self.start_time = _now()
        self.steps: List[Tuple[float, str]] = []

    def step(self, msg: str) -> None:
        self.steps.append((self._now(), msg))

    def total_time(self) -> float:
        return self._now() - self.start_time

    def _format(self, step_threshold: float) -> str:
        end = self._now()
        lines = [f'Trace: "{self.name}" '
                 f"(total time: {(end - self.start_time) * 1000:.1f}ms):"]
        last = self.start_time
        for step_time, msg in self.steps:
            duration = step_time - last
            if step_threshold == 0 or duration > step_threshold:
                lines.append(
                    f"Trace: [{(step_time - self.start_time) * 1000:.1f}ms] "
                    f"[{duration * 1000:.1f}ms] {msg}")
            last = step_time
        duration = end - last
        if step_threshold == 0 or duration > step_threshold:
            lines.append(f"Trace: [{(end - self.start_time) * 1000:.1f}ms] "
                         f"[{duration * 1000:.1f}ms] END")
        return "\n".join(lines)

    def log(self) -> None:
        logger.info(self._format(0))

    def log_if_long(self, threshold: float = SLOW_SCHEDULE_THRESHOLD) -> Optional[str]:
        """Log (and return) the trace when total time exceeds threshold; steps
        below their share (threshold / (steps+1)) are elided (trace.go:79-85)."""
        if self._now() - self.start_time >= threshold:
            step_threshold = threshold / (len(self.steps) + 1)
            text = self._format(step_threshold)
            logger.info(text)
            return text
        return None

"""Volume model: PV node-affinity checks, PV↔PVC matching, and the
scheduler-side volume binder.

Reference mapping:
  volumeutil.CheckNodeAffinity        (pkg/volume/util/util.go:269-310)
  findMatchingVolume                  (pkg/controller/volume/persistentvolume/index.go:125-255)
  volumeBinder.FindPodVolumes         (pkg/controller/volume/persistentvolume/scheduler_binder.go:126-166)
  volumeBinder.AssumePodVolumes       (scheduler_binder.go:169-218)
  shouldDelayBinding                  (pkg/controller/volume/persistentvolume/pv_controller.go:275-296)

The binder is constructed per simulation run over the snapshot's PV/PVC/
StorageClass lists; Assume mutates the in-memory PV copies (claimRef) so later
pods in the same run see earlier pods' volume consumption — the offline analog
of the pvCache.Assume overlay.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from tpusim.api.types import (
    VOLUME_BINDING_WAIT,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    StorageClass,
)


class VolumeBinderError(Exception):
    """A hard error from volume processing (Go's non-nil err return): aborts
    scheduling of the pod with the message, it is not a predicate failure."""


def check_node_affinity(pv: PersistentVolume, node_labels: dict) -> bool:
    """volumeutil.CheckNodeAffinity (volume/util/util.go:269-294): the PV's
    required node-affinity terms are ORed; no affinity = unconstrained. A
    term whose selector fails validation returns an ERROR upstream
    ("Failed to parse MatchExpressions") — raised here as VolumeBinderError,
    aborting the pod's scheduling rather than counting as a non-match."""
    terms = pv.node_affinity_terms()
    if terms is None:
        return True
    for term in terms:
        r = term.match_result(node_labels)
        if r is None:
            raise VolumeBinderError(
                "Failed to parse MatchExpressions on PersistentVolume "
                f"{pv.metadata.name}")
        if r:
            return True
    return False


def is_volume_bound_to_claim(pv: PersistentVolume,
                             claim: PersistentVolumeClaim) -> bool:
    """pv_controller.go isVolumeBoundToClaim: claimRef name/namespace match,
    and UID match when the claimRef carries one."""
    ref = pv.claim_ref
    if ref is None:
        return False
    if claim.name != (ref.get("name") or ""):
        return False
    if claim.namespace != (ref.get("namespace") or ""):
        return False
    if ref.get("uid") and claim.metadata.uid and ref["uid"] != claim.metadata.uid:
        return False
    return True


def _check_access_modes(claim: PersistentVolumeClaim,
                        pv: PersistentVolume) -> bool:
    """index.go checkAccessModes: every requested mode must be in the PV's."""
    pv_modes = set(pv.access_modes)
    return all(m in pv_modes for m in claim.access_modes)


def find_matching_volume(claim: PersistentVolumeClaim,
                         volumes: List[PersistentVolume],
                         node, excluded: Dict[str, PersistentVolume],
                         delay_binding: bool) -> Optional[PersistentVolume]:
    """index.go findMatchingVolume:125-255 — prefer a pre-bound PV; otherwise
    the smallest available PV that satisfies size/class/selector/access-modes
    and (scheduler path) the node's labels."""
    smallest: Optional[PersistentVolume] = None
    requested = claim.request_storage
    requested_class = claim.storage_class_name
    selector = claim.selector()

    smallest_capacity = 0
    for pv in volumes:
        if pv.name in excluded:
            continue
        capacity = pv.capacity_storage
        if pv.volume_mode != claim.volume_mode:
            continue
        node_affinity_valid = True
        if node is not None:
            node_affinity_valid = check_node_affinity(
                pv, node.metadata.labels)
        if is_volume_bound_to_claim(pv, claim):
            if capacity < requested:
                continue
            if not node_affinity_valid:
                # prebound PV unusable on this node -> no match at all
                return None
            return pv
        if node is None and delay_binding:
            # PV-controller path: the scheduler will bind delayed claims
            # (index.go:206-211)
            continue
        if pv.claim_ref is not None:
            continue
        if selector is not None and not selector.matches(pv.metadata.labels):
            continue
        if pv.storage_class_name != requested_class:
            continue
        if not node_affinity_valid:
            continue
        if node is not None and not _check_access_modes(claim, pv):
            continue
        if capacity >= requested and (
                smallest is None or capacity < smallest_capacity):
            smallest = pv
            smallest_capacity = capacity
    return smallest


class VolumeBinder:
    """The scheduler_binder.go volumeBinder analog over snapshot lists.

    enabled == the VolumeScheduling feature gate (off by default in the
    reference vintage: CheckVolumeBinding passes trivially and binding-mode
    delays never apply, predicates.go:1587-1589)."""

    def __init__(self, pvs: Optional[List[PersistentVolume]] = None,
                 pvcs: Optional[List[PersistentVolumeClaim]] = None,
                 classes: Optional[List[StorageClass]] = None,
                 enabled: bool = False):
        # PV copies: Assume mutates claimRef without touching snapshot objects
        self._pvs: Dict[str, PersistentVolume] = {
            pv.name: pv.copy() for pv in pvs or []}
        self._pvcs: Dict[str, PersistentVolumeClaim] = {
            pvc.key(): pvc for pvc in pvcs or []}
        self._classes: Dict[str, StorageClass] = {
            sc.name: sc for sc in classes or []}
        self.enabled = enabled
        # FindPodVolumes decisions per (pod key, node name), consumed by Assume
        # (podBindingCache analog)
        self._binding_cache: Dict[Tuple[str, str],
                                  List[Tuple[PersistentVolumeClaim,
                                             PersistentVolume]]] = {}

    # --- lister surface (PluginFactoryArgs hands these to predicates) ---

    def get_pv(self, name: str) -> Optional[PersistentVolume]:
        return self._pvs.get(name)

    def get_pvc(self, namespace: str, name: str) -> Optional[PersistentVolumeClaim]:
        return self._pvcs.get(f"{namespace}/{name}")

    def get_class(self, name: str) -> Optional[StorageClass]:
        return self._classes.get(name)

    def list_pvs(self, storage_class: str = "") -> List[PersistentVolume]:
        """pvCache.ListPVs(storageClassName) — PVs of one class."""
        return [pv for pv in self._pvs.values()
                if pv.storage_class_name == storage_class]

    # --- shouldDelayBinding (pv_controller.go:275-296) ---

    def should_delay_binding(self, pvc: PersistentVolumeClaim) -> bool:
        if not self.enabled:
            return False
        class_name = pvc.storage_class_name
        if not class_name:
            return False
        sc = self._classes.get(class_name)
        if sc is None:
            return False
        mode = sc.volume_binding_mode
        if mode is None:
            raise VolumeBinderError(
                f'VolumeBindingMode not set for StorageClass "{class_name}"')
        return mode == VOLUME_BINDING_WAIT

    # --- FindPodVolumes (scheduler_binder.go:126-166) ---

    def _pod_claims(self, pod: Pod):
        """getPodVolumes: (bound, unbound-delayed, unbound-immediate) PVC lists."""
        bound, unbound, immediate = [], [], []
        for vol in pod.spec.volumes:
            pvc_name = vol.pvc_name
            if pvc_name is None:
                continue
            pvc = self.get_pvc(pod.namespace, pvc_name)
            if pvc is None:
                raise VolumeBinderError(
                    f'error getting PVC "{pvc_name}": not found')
            if pvc.volume_name:
                bound.append(pvc)
            elif self.should_delay_binding(pvc):
                unbound.append(pvc)
            else:
                immediate.append(pvc)
        return bound, unbound, immediate

    def find_pod_volumes(self, pod: Pod, node) -> Tuple[bool, bool]:
        """Returns (unbound_satisfied, bound_satisfied)."""
        unbound_ok = True
        bound_ok = True
        bound, unbound, immediate = self._pod_claims(pod)
        if immediate:
            raise VolumeBinderError("pod has unbound PersistentVolumeClaims")
        for pvc in bound:
            pv = self.get_pv(pvc.volume_name)
            if pv is None:
                raise VolumeBinderError(
                    f'PersistentVolume "{pvc.volume_name}" not found')
            if not check_node_affinity(pv, node.metadata.labels):
                bound_ok = False
                break
        if unbound:
            unbound_ok = self._find_matching_volumes(pod, unbound, node)
        return unbound_ok, bound_ok

    def _find_matching_volumes(self, pod: Pod,
                               claims: List[PersistentVolumeClaim],
                               node) -> bool:
        """scheduler_binder.go findMatchingVolumes:342-377 — smallest-first
        claim order, chosen PVs excluded from later claims."""
        claims = sorted(claims, key=lambda c: c.request_storage)
        chosen: Dict[str, PersistentVolume] = {}
        bindings = []
        for pvc in claims:
            all_pvs = self.list_pvs(pvc.storage_class_name)
            pv = find_matching_volume(pvc, all_pvs, node, chosen,
                                      delay_binding=True)
            if pv is None:
                return False
            chosen[pv.name] = pv
            bindings.append((pvc, pv))
        self._binding_cache[(pod.key(), node.name)] = bindings
        return True

    # --- AssumePodVolumes (scheduler_binder.go:169-218) ---

    def assume_pod_volumes(self, pod: Pod, node_name: str) -> None:
        """Bind the cached per-node decisions into the in-memory PV state so
        subsequent pods see the consumed PVs (pvCache.Assume analog)."""
        for pvc, pv in self._binding_cache.pop((pod.key(), node_name), []):
            live = self._pvs.get(pv.name)
            if live is not None and live.claim_ref is None:
                spec = live.raw.setdefault("spec", {})
                spec["claimRef"] = {"name": pvc.name,
                                    "namespace": pvc.namespace,
                                    "uid": pvc.metadata.uid}
        # decisions for other nodes are stale once the pod is placed
        self._binding_cache = {k: v for k, v in self._binding_cache.items()
                               if k[0] != pod.key()}

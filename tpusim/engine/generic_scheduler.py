"""The core scheduling algorithm: findNodesThatFit → PrioritizeNodes → selectHost.

Reference: core/generic_scheduler.go. The 16-way goroutine fan-out over nodes
(:348, :607) is replaced here by plain loops (this backend is the semantics
oracle; the JAX backend owns performance).

Tie-break parity note (SURVEY.md §7 hard part 2): the Go selectHost does
``sort.Sort(sort.Reverse(priorityList))`` — an UNSTABLE sort keyed on score
only — then round-robins over the maximal-score prefix with a persistent
``lastNodeIndex`` counter (:183-198). Go's unstable tie order is an artifact of
its introsort; we define the parity semantics as a STABLE descending sort (ties
keep node-list order), which both backends implement identically.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from time import perf_counter as _now
from typing import Callable, Dict, List, Optional

from tpusim.api.types import Node, Pod
from tpusim.engine import errors as err
from tpusim.engine.errors import (
    FailureReason,
    PredicateError,
    PredicateFailureReason,
)
from tpusim.engine.predicates import (
    CHECK_NODE_CONDITION_PRED,
    CHECK_NODE_DISK_PRESSURE_PRED,
    CHECK_NODE_LABEL_PRESENCE_PRED,
    CHECK_NODE_MEMORY_PRESSURE_PRED,
    CHECK_NODE_UNSCHEDULABLE_PRED,
    CHECK_VOLUME_BINDING_PRED,
    HOSTNAME_PRED,
    MATCH_NODE_SELECTOR_PRED,
    NO_VOLUME_ZONE_CONFLICT_PRED,
    POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED,
    POD_TOLERATES_NODE_TAINTS_PRED,
    PREDICATES_ORDERING,
    PredicateMetadata,
    get_predicate_metadata,
)
from tpusim.engine.priorities import HostPriority, PriorityConfig
from tpusim.engine.resources import NodeInfo, get_resource_request
from tpusim.engine.trace import Trace
from tpusim.framework.metrics import register as register_metrics, since_in_microseconds
from tpusim.obs import recorder as flight
from tpusim.engine.util import (
    MAX_INT32,
    get_pod_priority as util_get_pod_priority,
    sort_by_priority_desc,
)

NO_NODE_AVAILABLE_MSG = "0/{} nodes are available"

log = logging.getLogger(__name__)

# Predicates whose outcome is a function of (pod, node statics) only — they
# never read node_info.pods / used_ports / meta's matching terms, so once they
# pass on the fully-stripped node (selectVictimsOnNode's first fit) they pass
# for every victim subset and the reprieve loop may skip them. Unknown or
# policy-registered predicate names are conservatively treated as dependent.
_POD_SET_INDEPENDENT_PREDS = frozenset({
    CHECK_NODE_CONDITION_PRED, CHECK_NODE_UNSCHEDULABLE_PRED, HOSTNAME_PRED,
    MATCH_NODE_SELECTOR_PRED, POD_TOLERATES_NODE_TAINTS_PRED,
    POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED, CHECK_NODE_LABEL_PRESENCE_PRED,
    CHECK_VOLUME_BINDING_PRED, NO_VOLUME_ZONE_CONFLICT_PRED,
    CHECK_NODE_MEMORY_PRESSURE_PRED, CHECK_NODE_DISK_PRESSURE_PRED,
})
_REPRIEVE_ORDERING = [k for k in PREDICATES_ORDERING
                      if k not in _POD_SET_INDEPENDENT_PREDS]


class SchedulingError(Exception):
    pass


class FitError(SchedulingError):
    """Reference: generic_scheduler.go:51-90 — aggregates per-node predicate
    failures into the sorted reason-histogram message."""

    def __init__(self, pod: Pod, num_all_nodes: int,
                 failed_predicates: Dict[str, List[PredicateFailureReason]]):
        self.pod = pod
        self.num_all_nodes = num_all_nodes
        self.failed_predicates = failed_predicates
        super().__init__(self.error())

    def reason_histogram(self) -> Dict[str, int]:
        """Per-pod attribution: failure reason -> number of nodes rejected
        for it (the aggregation behind error(), exposed for telemetry)."""
        reasons: Dict[str, int] = {}
        for preds in self.failed_predicates.values():
            for reason in preds:
                key = reason.get_reason()
                reasons[key] = reasons.get(key, 0) + 1
        return reasons

    def error(self) -> str:
        reasons = self.reason_histogram()
        reason_strings = sorted(f"{v} {k}" for k, v in reasons.items())
        return (NO_NODE_AVAILABLE_MSG.format(self.num_all_nodes)
                + ": " + ", ".join(reason_strings) + ".")


ERR_NO_NODES_AVAILABLE = SchedulingError("no nodes available to schedule pods")


@dataclass
class ScheduleResult:
    suggested_host: str
    evaluated_nodes: int = 0
    feasible_nodes: int = 0


class GenericScheduler:
    """Reference: generic_scheduler.go:93-200 (genericScheduler struct + Schedule)."""

    def __init__(
        self,
        predicates: Dict[str, Callable],
        prioritizers: List[PriorityConfig],
        predicate_meta_producer: Callable = get_predicate_metadata,
        priority_meta_producer: Optional[Callable] = None,
        extenders: Optional[list] = None,
        always_check_all_predicates: bool = False,
        equivalence_cache=None,
        scheduling_queue=None,
        pdb_lister: Optional[Callable[[], list]] = None,
    ):
        self.predicates = predicates
        self.prioritizers = prioritizers
        self.predicate_meta_producer = predicate_meta_producer
        self.priority_meta_producer = priority_meta_producer
        self.extenders = extenders or []
        self.always_check_all_predicates = always_check_all_predicates
        self.equivalence_cache = equivalence_cache
        self.scheduling_queue = scheduling_queue
        self.pdb_lister = pdb_lister or (lambda: [])
        self.last_node_index = 0  # persistent round-robin counter (:97)
        # Ordered keys first; then custom (policy-registered) keys that are not
        # in the fixed ordering, alphabetically. DELIBERATE DEVIATION: the
        # reference vintage iterates only predicates.Ordering()
        # (generic_scheduler.go:467), silently skipping custom policy
        # predicates — a known kube bug fixed in 1.11 by evaluating the extra
        # keys; reproducing it would make PredicateArgument configs dead weight.
        self._predicate_key_order = list(PREDICATES_ORDERING) + sorted(
            k for k in self.predicates if k not in PREDICATES_ORDERING)
        self._metrics = register_metrics()

    # --- filter phase ---

    def _add_nominated_pods(self, pod_priority: int,
                            meta: Optional[PredicateMetadata],
                            node_info: NodeInfo):
        """generic_scheduler.go addNominatedPods: clone state with the node's
        nominated pods of >= priority added; returns (added, meta', info')."""
        if self.scheduling_queue is None or node_info.node is None:
            return False, meta, node_info
        nominated = self.scheduling_queue.waiting_pods_for_node(node_info.node.name)
        nominated = [p for p in nominated
                     if util_get_pod_priority(p) >= pod_priority]
        if not nominated:
            return False, meta, node_info
        meta_copy = meta.shallow_copy() if meta is not None else None
        info_copy = node_info.clone()
        for p in nominated:
            info_copy.add_pod(p)
            if meta_copy is not None:
                meta_copy.add_pod(p, info_copy.node)
        return True, meta_copy, info_copy

    def pod_fits_on_node(self, pod: Pod, meta: Optional[PredicateMetadata],
                         node_info: NodeInfo) -> tuple[bool, List[PredicateFailureReason]]:
        """Reference: generic_scheduler.go:420-534 — predicates run in
        PREDICATES_ORDERING with short-circuit; when nominated pods exist the
        loop runs twice (once with them added, once without) and the
        equivalence cache is consulted only on the clean pass."""
        fails: List[PredicateFailureReason] = []
        pods_added = False
        ecache = self.equivalence_cache
        equiv_hash = (ecache.get_equivalence_class_hash(pod)
                      if ecache is not None else None)
        for i in range(2):
            meta_to_use, info_to_use = meta, node_info
            if i == 0:
                pods_added, meta_to_use, info_to_use = self._add_nominated_pods(
                    util_get_pod_priority(pod), meta, node_info)
            elif not pods_added or fails:
                break
            ecache_available = ecache is not None and not pods_added
            for pred_key in self._predicate_key_order:
                predicate = self.predicates.get(pred_key)
                if predicate is None:
                    continue
                if ecache_available:
                    fit, reasons = ecache.run_predicate(
                        predicate, pred_key, pod, meta_to_use, info_to_use,
                        equiv_hash)
                else:
                    fit, reasons = predicate(pod, meta_to_use, info_to_use)
                if not fit:
                    fails.extend(reasons)
                    if not self.always_check_all_predicates:
                        break
        return (not fails), fails

    def find_nodes_that_fit(self, pod: Pod, nodes: List[Node],
                            node_info_map: Dict[str, NodeInfo]
                            ) -> tuple[List[Node], Dict[str, List[PredicateFailureReason]]]:
        """Reference: generic_scheduler.go:289-377."""
        if not self.predicates:
            filtered = list(nodes)
            failed: Dict[str, List[PredicateFailureReason]] = {}
        else:
            meta = self.predicate_meta_producer(pod, node_info_map)
            filtered = []
            failed = {}
            errs: Dict[str, int] = {}
            for node in nodes:
                try:
                    fits, fails = self.pod_fits_on_node(
                        pod, meta, node_info_map[node.name])
                except PredicateError as exc:
                    # checkNode error arm: the message is counted, the node is
                    # neither fit nor failed (generic_scheduler.go:330-340)
                    errs[str(exc)] = errs.get(str(exc), 0) + 1
                    continue
                if fits:
                    filtered.append(node)
                else:
                    failed[node.name] = fails
            if errs:
                # CreateAggregateFromMessageCountMap: scheduling of the pod
                # aborts with the aggregated message (generic_scheduler.go:341-343)
                messages = [m if c == 1 else f"{m} (repeated {c} times)"
                            for m, c in errs.items()]
                raise SchedulingError(
                    messages[0] if len(messages) == 1
                    else "[" + ", ".join(messages) + "]")
        if filtered and self.extenders:
            # extender filters run after the built-in predicates; failures are
            # appended as plain-message reasons (generic_scheduler.go:355-376)
            for extender in self.extenders:
                if not extender.is_interested(pod):
                    continue
                try:
                    filtered, failed_map = extender.filter(pod, filtered,
                                                           node_info_map)
                except SchedulingError:
                    raise
                except Exception as exc:
                    # a filter transport/result error fails this pod's
                    # scheduling attempt, never the whole simulation
                    # (generic_scheduler.go:360-363 → scheduleOne error arm)
                    raise SchedulingError(f"extender filter failed: {exc}")
                for name, msg in failed_map.items():
                    failed.setdefault(name, []).append(FailureReason(msg))
                if not filtered:
                    break
        return filtered, failed

    # --- score phase ---

    def prioritize_nodes(self, pod: Pod, node_info_map: Dict[str, NodeInfo],
                         nodes: List[Node]) -> List[HostPriority]:
        """Reference: generic_scheduler.go:542-680."""
        # If no priority configs and no extenders: all nodes score 1 (:556-571).
        if not self.prioritizers and not self.extenders:
            return [HostPriority(n.name, 1) for n in nodes]

        meta = self.priority_meta_producer(pod) if self.priority_meta_producer else None

        # map phase per config (nodes × maps), then per-config reduce
        results: List[List[HostPriority]] = []
        for config in self.prioritizers:
            if config.function is not None:
                results.append(config.function(pod, node_info_map, nodes))
            else:
                per_node = [config.map_fn(pod, meta, node_info_map[n.name]) for n in nodes]
                results.append(per_node)
        for i, config in enumerate(self.prioritizers):
            if config.reduce_fn is not None:
                config.reduce_fn(pod, meta, node_info_map, results[i])

        # per-priority score dump at high verbosity (the reference's V(10)
        # "%v -> %v: %v, Score: (%d)" lines, generic_scheduler.go:618-622);
        # answers "why did node X win" when a placement surprises
        dump = log.isEnabledFor(logging.DEBUG)
        if dump:
            for j, config in enumerate(self.prioritizers):
                for hp in results[j]:
                    log.debug("%s/%s -> %s: %s, Score: (%d)", pod.namespace,
                              pod.name, hp.host, config.name, hp.score)

        # weighted sum (:631-639)
        result = []
        for i, node in enumerate(nodes):
            total = 0
            for j, config in enumerate(self.prioritizers):
                total += results[j][i].score * config.weight
            result.append(HostPriority(node.name, total))

        if self.extenders:
            # extender prioritize errors are ignored — k8s/other extenders
            # determine the priorities (generic_scheduler.go:649-653)
            combined = {hp.host: hp.score for hp in result}
            for extender in self.extenders:
                if not extender.is_interested(pod):
                    continue
                try:
                    prioritized_list, weight = extender.prioritize(pod, nodes)
                except Exception:
                    continue
                for hp in prioritized_list:
                    # hosts outside the candidate list are harmless, matching
                    # the Go map semantics (combinedScores auto-zeroes and is
                    # only read back for candidate hosts)
                    if hp.host in combined:
                        combined[hp.host] += hp.score * weight
            result = [HostPriority(n.name, combined[n.name]) for n in nodes]
        if dump:
            # aggregate dump, post-extender like the reference
            # (generic_scheduler.go:670-674)
            for hp in result:
                log.debug("Host %s => Score %d", hp.host, hp.score)
        return result

    # --- select phase ---

    def select_host(self, priority_list: List[HostPriority]) -> str:
        """Reference: generic_scheduler.go:183-198 — stable sort desc by score,
        round-robin among the top-score ties via the persistent counter."""
        if not priority_list:
            raise SchedulingError("empty priorityList")
        ordered = sorted(priority_list, key=lambda hp: -hp.score)
        max_score = ordered[0].score
        first_after_max = 1
        while first_after_max < len(ordered) and ordered[first_after_max].score == max_score:
            first_after_max += 1
        ix = self.last_node_index % first_after_max
        self.last_node_index += 1
        return ordered[ix].host

    # --- the pipeline ---

    def schedule(self, pod: Pod, nodes: List[Node],
                 node_info_map: Dict[str, NodeInfo]) -> str:
        """Reference: generic_scheduler.go:112-180 — incl. the per-pod
        utiltrace ("Scheduling ns/name", logged >100ms, :113-114) and the
        predicate/priority evaluation histograms (:148,154,163)."""
        trace = Trace(f"Scheduling {pod.namespace}/{pod.name}")
        metrics = self._metrics
        try:
            if not nodes:
                raise ERR_NO_NODES_AVAILABLE
            start = _now()
            with flight.span("predicates") as fsp:
                filtered, failed_predicate_map = self.find_nodes_that_fit(
                    pod, nodes, node_info_map)
                if fsp:
                    fsp.set("nodes", len(nodes))
                    fsp.set("feasible", len(filtered))
            metrics.predicate_evaluation.observe(since_in_microseconds(start))
            trace.step("Computing predicates")
            if not filtered:
                fit_err = FitError(pod, len(nodes), failed_predicate_map)
                if flight.get_recorder() is not None:
                    flight.instant("fit_error", "host", {
                        "pod": f"{pod.namespace}/{pod.name}",
                        "nodes": len(nodes),
                        "reasons": fit_err.reason_histogram(),
                    })
                raise fit_err
            start = _now()
            if len(filtered) == 1:
                metrics.priority_evaluation.observe(since_in_microseconds(start))
                return filtered[0].name
            with flight.span("priorities"):
                priority_list = self.prioritize_nodes(pod, node_info_map, filtered)
            metrics.priority_evaluation.observe(since_in_microseconds(start))
            trace.step("Prioritizing")
            with flight.span("select_host"):
                host = self.select_host(priority_list)
            trace.step("Selecting host")
            return host
        finally:
            trace.log_if_long()

    # --- preemption (generic_scheduler.go:205-1000) ---
    # Dormant by default: pod priority is feature-gated off at the reference's
    # defaults (scheduler.go:210-213 via util.PodPriorityEnabled); the
    # simulator enables it through SchedulerServerConfig.enable_pod_priority.

    # predicate failures that removing pods can never fix
    # (nodesWherePreemptionMightHelp)
    _UNRESOLVABLE = {
        err.ERR_NODE_SELECTOR_NOT_MATCH, err.ERR_POD_NOT_MATCH_HOST_NAME,
        err.ERR_TAINTS_TOLERATIONS_NOT_MATCH, err.ERR_NODE_LABEL_PRESENCE_VIOLATED,
        err.ERR_NODE_NOT_READY, err.ERR_NODE_NETWORK_UNAVAILABLE,
        err.ERR_NODE_UNSCHEDULABLE, err.ERR_NODE_UNKNOWN_CONDITION,
        err.ERR_VOLUME_ZONE_CONFLICT, err.ERR_VOLUME_NODE_CONFLICT,
        err.ERR_VOLUME_BIND_CONFLICT,
    }

    def preempt(self, pod: Pod, nodes: List[Node],
                node_info_map: Dict[str, NodeInfo], schedule_err: Exception,
                candidate_filter=None):
        """Returns (node, victims, nominated_pods_to_clear).

        candidate_filter: optional `name -> bool` prefilter over potential
        nodes; callers may pass one ONLY when it provably excludes just nodes
        where _select_victims_on_node would return fits=False (e.g. the
        vectorized lower-priority resource bound in jaxe/preempt.py), so the
        outcome is identical to the unfiltered pipeline."""
        if not isinstance(schedule_err, FitError):
            return None, [], []
        if not self._pod_eligible_to_preempt_others(pod, node_info_map):
            return None, [], []
        if not nodes:
            raise ERR_NO_NODES_AVAILABLE
        potential = self._nodes_where_preemption_might_help(
            nodes, schedule_err.failed_predicates)
        if not potential:
            # clean up any existing nominated node name of the pod (:231-234)
            return None, [], [pod]
        if candidate_filter is not None:
            # an emptied list matches the all-candidates-unfit path below
            # (empty node_to_victims -> None without clearing nominations),
            # NOT the no-potential-nodes arm above
            potential = [n for n in potential if candidate_filter(n.name)]
            if not potential:
                return None, [], []
        pdbs = self.pdb_lister()
        node_to_victims = self._select_nodes_for_preemption(
            pod, node_info_map, potential, pdbs)
        by_name = {n.name: n for n in nodes}
        while node_to_victims:
            name = self._pick_one_node_for_preemption(node_to_victims)
            if name is None:
                return None, [], []
            victims, _ = node_to_victims[name]
            if self._node_passes_extenders_for_preemption(pod, name, victims,
                                                          node_info_map):
                nominated = self._get_lower_priority_nominated_pods(pod, name)
                return by_name[name], victims, nominated
            del node_to_victims[name]
        return None, [], []

    def _pod_eligible_to_preempt_others(self, pod: Pod,
                                        node_info_map: Dict[str, NodeInfo]) -> bool:
        """podEligibleToPreemptOthers: don't preempt again while a prior
        preemption's victims are still terminating on the nominated node.
        The offline simulator deletes victims synchronously, so the terminating
        state never materializes and this returns True (matching the reference
        when no DeletionTimestamp is set)."""
        nom = pod.status.nominated_node_name
        if nom and nom in node_info_map:
            for p in node_info_map[nom].pods:
                if (getattr(p.metadata, "deletion_timestamp", None) is not None
                        and util_get_pod_priority(p) < util_get_pod_priority(pod)):
                    return False
        return True

    def _nodes_where_preemption_might_help(self, nodes: List[Node],
                                           failed_predicates) -> List[Node]:
        potential = []
        for node in nodes:
            fails = failed_predicates.get(node.name, [])
            if any(f in self._UNRESOLVABLE for f in fails):
                continue
            potential.append(node)
        return potential

    def _select_nodes_for_preemption(self, pod: Pod, node_info_map, potential,
                                     pdbs) -> Dict[str, tuple]:
        """selectNodesForPreemption: node name -> (victims, num_pdb_violations).
        Keyed by name with insertion in node-list order for deterministic
        pick-one tie-breaking (Go iterates a map in random order)."""
        meta = self.predicate_meta_producer(pod, node_info_map)
        result: Dict[str, tuple] = {}
        with flight.span("preempt_candidates") as csp:
            for node in potential:
                meta_copy = meta.shallow_copy() if meta is not None else None
                victims, violations, fits = self._select_victims_on_node(
                    pod, meta_copy, node_info_map[node.name], pdbs)
                if fits:
                    result[node.name] = (victims, violations)
            if csp:
                csp.set("candidates", len(potential))
                csp.set("fitting", len(result))
        return result

    def _select_victims_on_node(self, pod: Pod, meta, node_info: NodeInfo,
                                pdbs) -> tuple:
        """selectVictimsOnNode: remove all lower-priority pods, check fit, then
        reprieve as many as possible (PDB-violating victims first, each group
        highest-priority first)."""
        pod_priority = util_get_pod_priority(pod)
        potential_victims = [p for p in node_info.pods
                             if util_get_pod_priority(p) < pod_priority]
        # one rebuilt-from-survivors clone instead of clone + per-pod strip
        info_copy = node_info.clone_without(potential_victims)

        def remove_pod(p):
            info_copy.remove_pod(p)
            if meta is not None:
                meta.remove_pod(p)

        def add_pod(p):
            info_copy.add_pod(p)
            if meta is not None:
                meta.add_pod(p, info_copy.node)

        if meta is not None:
            for p in potential_victims:
                meta.remove_pod(p)
        potential_victims = sort_by_priority_desc(potential_victims)

        fits, _ = self._fits_sans_nominated(pod, meta, info_copy)
        if not fits:
            return None, 0, False

        victims: List[Pod] = []
        num_violating = 0
        violating, non_violating = self._filter_pods_with_pdb_violation(
            potential_victims, pdbs)

        reprieve = self._make_arithmetic_reprieve(pod, meta, info_copy,
                                                 victims)
        if reprieve is None:
            chain = self._reprieve_chain()

            def reprieve(p) -> bool:
                add_pod(p)
                # the full-ordering fit above already passed on the
                # stripped node; fit is an order-independent AND over the
                # predicate set, so the boolean-only chain (pod-set
                # -dependent predicates, cheapest first) gives the
                # identical outcome
                fits = True
                for predicate in chain:
                    ok, _ = predicate(pod, meta, info_copy)
                    if not ok:
                        fits = False
                        break
                if not fits:
                    remove_pod(p)
                    victims.append(p)
                return fits

        for p in violating:
            if not reprieve(p):
                num_violating += 1
        for p in non_violating:
            reprieve(p)
        return victims, num_violating, True

    # workload feature hints, settable by the device-engine hybrid
    # (jaxe/preempt.py) which statically knows whether ANY pod in the run —
    # new or placed — carries host ports / conflictable volumes / MaxPD
    # volumes / inter-pod terms. A reprieve-chain predicate for an absent
    # feature is constant-true over every (pod, victim set) of the run, so
    # eliding it cannot change any outcome; when the elided chain is
    # exactly PodFitsResources, reprieve decisions reduce to pure integer
    # arithmetic with no NodeInfo/metadata mutation at all.
    reprieve_feature_hints = None

    def preemption_reprieve_class(self) -> str:
        """The class-dispatch seam for device-side victim selection
        (jaxe/preempt.py): "arithmetic" when the workload feature hints
        elide every pod-set-dependent predicate except PodFitsResources
        from the reprieve chain — victim search is then pure integer
        arithmetic over resource aggregates, the shape the device kernel
        (jaxe/kernels.py preempt_select) reproduces bit-for-bit.
        "general" keeps the host clone/add reprieve pipeline (inter-pod
        -affinity-sensitive victims, port/volume interactions)."""
        hints = self.reprieve_feature_hints
        if hints is None:
            return "general"
        from tpusim.engine.predicates import (
            no_disk_conflict,
            pod_fits_host_ports,
            pod_fits_resources,
        )
        from tpusim.engine.predicates import (
            MAX_AZURE_DISK_VOLUME_COUNT_PRED,
            MAX_EBS_VOLUME_COUNT_PRED,
            MAX_GCE_PD_VOLUME_COUNT_PRED,
            MATCH_INTERPOD_AFFINITY_PRED,
        )

        maxpd = {self.predicates.get(k)
                 for k in (MAX_EBS_VOLUME_COUNT_PRED,
                           MAX_GCE_PD_VOLUME_COUNT_PRED,
                           MAX_AZURE_DISK_VOLUME_COUNT_PRED)}
        interpod = self.predicates.get(MATCH_INTERPOD_AFFINITY_PRED)
        chain = self._reprieve_chain()
        if pod_fits_resources not in chain:
            # a set with neither GeneralPredicates nor PodFitsResources
            # must not have resource checks imposed on it (the chain-based
            # reprieve would never apply them)
            return "general"
        for fn in chain:
            if fn is pod_fits_resources:
                continue
            if fn is pod_fits_host_ports and not hints.get("has_ports"):
                continue
            if fn is no_disk_conflict and not hints.get("has_disk_conflict"):
                continue
            if fn in maxpd and not hints.get("has_maxpd"):
                continue
            if fn is interpod and not hints.get("has_interpod"):
                continue
            return "general"  # a live pod-set-dependent predicate remains
        return "arithmetic"

    def _make_arithmetic_reprieve(self, pod, meta, info_copy, victims):
        """Returns the integer-arithmetic reprieve closure, or None when
        preemption_reprieve_class() is "general" (the generic clone/add
        path then runs)."""
        if self.preemption_reprieve_class() != "arithmetic":
            return None

        # mirror pod_fits_resources (predicates.go:706-776) exactly: pod
        # count always; resource axes only for a nonzero-request pod;
        # extender-ignored extended resources skipped
        preq = meta.pod_request if meta is not None \
            else get_resource_request(pod)
        zero_req = (preq.milli_cpu == 0 and preq.memory == 0
                    and preq.nvidia_gpu == 0
                    and preq.ephemeral_storage == 0 and not preq.scalar)
        alloc = info_copy.allocatable_resource
        allowed = info_copy.allowed_pod_number()
        used = info_copy.requested_resource
        ignored = getattr(meta, "ignored_extended_resources", None) or set()
        scal_names = [name for name in preq.scalar
                      if not ("/" in name and name in ignored)]
        state = {
            "n": len(info_copy.pods),
            "cpu": used.milli_cpu + preq.milli_cpu,
            "mem": used.memory + preq.memory,
            "gpu": used.nvidia_gpu + preq.nvidia_gpu,
            "eph": used.ephemeral_storage + preq.ephemeral_storage,
            "scal": {name: used.scalar.get(name, 0) + preq.scalar[name]
                     for name in scal_names},
        }

        def reprieve_math(v) -> bool:
            vr = get_resource_request(v)
            fits = state["n"] + 2 <= allowed  # +v +the incoming pod
            if fits and not zero_req:
                fits = (alloc.milli_cpu >= state["cpu"] + vr.milli_cpu
                        and alloc.memory >= state["mem"] + vr.memory
                        and alloc.nvidia_gpu >= state["gpu"] + vr.nvidia_gpu
                        and alloc.ephemeral_storage
                        >= state["eph"] + vr.ephemeral_storage)
                if fits and scal_names:
                    for name in scal_names:
                        if alloc.scalar.get(name, 0) < state["scal"][name] \
                                + vr.scalar.get(name, 0):
                            fits = False
                            break
            if fits:
                state["n"] += 1
                state["cpu"] += vr.milli_cpu
                state["mem"] += vr.memory
                state["gpu"] += vr.nvidia_gpu
                state["eph"] += vr.ephemeral_storage
                for name in scal_names:
                    state["scal"][name] += vr.scalar.get(name, 0)
            else:
                victims.append(v)
            return fits

        return reprieve_math

    def _fits_sans_nominated(self, pod, meta, node_info):
        """podFitsOnNode with queue=nil and no ecache (the preemption calls)."""
        fails: List[PredicateFailureReason] = []
        for pred_key in PREDICATES_ORDERING:
            predicate = self.predicates.get(pred_key)
            if predicate is None:
                continue
            fit, reasons = predicate(pod, meta, node_info)
            if not fit:
                fails.extend(reasons)
                break
        return (not fails), fails

    def _reprieve_chain(self) -> list:
        """The boolean-only predicate chain for reprieve re-checks in
        _select_victims_on_node: pod-set-dependent predicates only (node-
        static ones passed on the stripped node and cannot change when only
        the pod set changes), with GeneralPredicates decomposed into its
        dependent halves — PodFitsResources + PodFitsHostPorts; PodFitsHost
        and PodMatchNodeSelector are node-static (predicates.go:1059-1123) —
        and resources hoisted first as the dominant reprieve failure."""
        chain = getattr(self, "_reprieve_chain_cache", None)
        if chain is None:
            from tpusim.engine.predicates import (
                GENERAL_PRED,
                POD_FITS_HOST_PORTS_PRED,
                POD_FITS_RESOURCES_PRED,
                pod_fits_host_ports,
                pod_fits_resources,
            )
            decomposed = (GENERAL_PRED, POD_FITS_RESOURCES_PRED,
                          POD_FITS_HOST_PORTS_PRED)
            chain = []
            if (GENERAL_PRED in self.predicates
                    or POD_FITS_RESOURCES_PRED in self.predicates):
                chain.append(pod_fits_resources)
            if (GENERAL_PRED in self.predicates
                    or POD_FITS_HOST_PORTS_PRED in self.predicates):
                chain.append(pod_fits_host_ports)
            for key in _REPRIEVE_ORDERING:
                if key in decomposed:
                    continue
                fn = self.predicates.get(key)
                if fn is not None:
                    chain.append(fn)
            self._reprieve_chain_cache = chain
        return chain

    @staticmethod
    def _filter_pods_with_pdb_violation(pods, pdbs):
        """filterPodsWithPDBViolation — order within each bucket preserved."""
        violating, non_violating = [], []
        for pod in pods:
            violated = False
            if pod.metadata.labels:
                for pdb in pdbs:
                    if pdb.namespace != pod.namespace or pdb.selector is None:
                        continue
                    if (not pdb.selector.match_labels
                            and not pdb.selector.match_expressions):
                        continue  # empty selector matches nothing here
                    if not pdb.selector.matches(pod.metadata.labels):
                        continue
                    if pdb.disruptions_allowed <= 0:
                        violated = True
                        break
            (violating if violated else non_violating).append(pod)
        return violating, non_violating

    def _pick_one_node_for_preemption(self, node_to_victims: Dict[str, tuple]
                                      ) -> Optional[str]:
        """pickOneNodeForPreemption's 5 criteria: fewest PDB violations, lowest
        highest-priority victim, smallest priority sum, fewest victims, first.
        Returns the chosen node name (Go returns the map key's node; map order
        is random there — we use node-list insertion order deterministically)."""
        if not node_to_victims:
            return None
        names = list(node_to_victims.keys())
        for name in names:
            victims, _ = node_to_victims[name]
            if not victims:
                return name
        min_violations = min(v[1] for v in node_to_victims.values())
        names = [n for n in names if node_to_victims[n][1] == min_violations]
        if len(names) > 1:
            highest = {n: util_get_pod_priority(node_to_victims[n][0][0])
                       for n in names}
            min_highest = min(highest.values())
            names = [n for n in names if highest[n] == min_highest]
        if len(names) > 1:
            sums = {n: sum(util_get_pod_priority(p) + MAX_INT32 + 1
                           for p in node_to_victims[n][0]) for n in names}
            min_sum = min(sums.values())
            names = [n for n in names if sums[n] == min_sum]
        if len(names) > 1:
            counts = {n: len(node_to_victims[n][0]) for n in names}
            min_count = min(counts.values())
            names = [n for n in names if counts[n] == min_count]
        return names[0]

    def _node_passes_extenders_for_preemption(self, pod, node_name, victims,
                                              node_info_map) -> bool:
        """nodePassesExtendersForPreemption (generic_scheduler.go:842-874):
        re-run each extender's Filter on the node with the victims removed."""
        if not self.extenders:
            return True
        original = node_info_map[node_name]
        info_copy = original.clone()
        for victim in victims:
            info_copy.remove_pod(victim)
        node_info_map[node_name] = info_copy
        try:
            filtered = [info_copy.node]
            for extender in self.extenders:
                if not extender.is_interested(pod):
                    continue
                try:
                    filtered, failed_map = extender.filter(pod, filtered,
                                                           node_info_map)
                except Exception as exc:
                    # same per-pod containment as the filter phase: an
                    # extender error fails this preemption attempt, not the
                    # whole simulation
                    raise SchedulingError(
                        f"extender filter failed during preemption: {exc}")
                if node_name in failed_map or not filtered:
                    return False
            return True
        finally:
            node_info_map[node_name] = original

    def _get_lower_priority_nominated_pods(self, pod: Pod,
                                           node_name: str) -> List[Pod]:
        if self.scheduling_queue is None:
            return []
        pods = self.scheduling_queue.waiting_pods_for_node(node_name)
        priority = util_get_pod_priority(pod)
        return [p for p in pods if util_get_pod_priority(p) < priority]

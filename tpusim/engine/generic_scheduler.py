"""The core scheduling algorithm: findNodesThatFit → PrioritizeNodes → selectHost.

Reference: core/generic_scheduler.go. The 16-way goroutine fan-out over nodes
(:348, :607) is replaced here by plain loops (this backend is the semantics
oracle; the JAX backend owns performance).

Tie-break parity note (SURVEY.md §7 hard part 2): the Go selectHost does
``sort.Sort(sort.Reverse(priorityList))`` — an UNSTABLE sort keyed on score
only — then round-robins over the maximal-score prefix with a persistent
``lastNodeIndex`` counter (:183-198). Go's unstable tie order is an artifact of
its introsort; we define the parity semantics as a STABLE descending sort (ties
keep node-list order), which both backends implement identically.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from tpusim.api.types import Node, Pod
from tpusim.engine.errors import PredicateFailureReason
from tpusim.engine.predicates import (
    PREDICATES_ORDERING,
    PredicateMetadata,
    get_predicate_metadata,
)
from tpusim.engine.priorities import HostPriority, PriorityConfig
from tpusim.engine.resources import NodeInfo

NO_NODE_AVAILABLE_MSG = "0/{} nodes are available"


class SchedulingError(Exception):
    pass


class FitError(SchedulingError):
    """Reference: generic_scheduler.go:51-90 — aggregates per-node predicate
    failures into the sorted reason-histogram message."""

    def __init__(self, pod: Pod, num_all_nodes: int,
                 failed_predicates: Dict[str, List[PredicateFailureReason]]):
        self.pod = pod
        self.num_all_nodes = num_all_nodes
        self.failed_predicates = failed_predicates
        super().__init__(self.error())

    def error(self) -> str:
        reasons: Dict[str, int] = {}
        for preds in self.failed_predicates.values():
            for reason in preds:
                key = reason.get_reason()
                reasons[key] = reasons.get(key, 0) + 1
        reason_strings = sorted(f"{v} {k}" for k, v in reasons.items())
        return (NO_NODE_AVAILABLE_MSG.format(self.num_all_nodes)
                + ": " + ", ".join(reason_strings) + ".")


ERR_NO_NODES_AVAILABLE = SchedulingError("no nodes available to schedule pods")


@dataclass
class ScheduleResult:
    suggested_host: str
    evaluated_nodes: int = 0
    feasible_nodes: int = 0


class GenericScheduler:
    """Reference: generic_scheduler.go:93-200 (genericScheduler struct + Schedule)."""

    def __init__(
        self,
        predicates: Dict[str, Callable],
        prioritizers: List[PriorityConfig],
        predicate_meta_producer: Callable = get_predicate_metadata,
        priority_meta_producer: Optional[Callable] = None,
        extenders: Optional[list] = None,
        always_check_all_predicates: bool = False,
    ):
        self.predicates = predicates
        self.prioritizers = prioritizers
        self.predicate_meta_producer = predicate_meta_producer
        self.priority_meta_producer = priority_meta_producer
        self.extenders = extenders or []
        self.always_check_all_predicates = always_check_all_predicates
        self.last_node_index = 0  # persistent round-robin counter (:97)

    # --- filter phase ---

    def pod_fits_on_node(self, pod: Pod, meta: Optional[PredicateMetadata],
                         node_info: NodeInfo) -> tuple[bool, List[PredicateFailureReason]]:
        """Reference: generic_scheduler.go:420-534, with the nominated-pods
        double-pass elided (pod priority is feature-gated off in the simulator,
        so no nominated pods exist; SURVEY.md §3.3)."""
        fails: List[PredicateFailureReason] = []
        fits = True
        for pred_key in PREDICATES_ORDERING:
            predicate = self.predicates.get(pred_key)
            if predicate is None:
                continue
            fit, reasons = predicate(pod, meta, node_info)
            if not fit:
                fits = False
                fails.extend(reasons)
                if not self.always_check_all_predicates:
                    break
        return fits, fails

    def find_nodes_that_fit(self, pod: Pod, nodes: List[Node],
                            node_info_map: Dict[str, NodeInfo]
                            ) -> tuple[List[Node], Dict[str, List[PredicateFailureReason]]]:
        """Reference: generic_scheduler.go:289-377."""
        if not self.predicates:
            filtered = list(nodes)
            failed: Dict[str, List[PredicateFailureReason]] = {}
        else:
            meta = self.predicate_meta_producer(pod, node_info_map)
            filtered = []
            failed = {}
            for node in nodes:
                fits, fails = self.pod_fits_on_node(pod, meta, node_info_map[node.name])
                if fits:
                    filtered.append(node)
                else:
                    failed[node.name] = fails
        if filtered and self.extenders:
            for extender in self.extenders:
                filtered, failed_map = extender.filter(pod, filtered, node_info_map)
                for name, reason in failed_map.items():
                    failed[name] = [reason]
                if not filtered:
                    break
        return filtered, failed

    # --- score phase ---

    def prioritize_nodes(self, pod: Pod, node_info_map: Dict[str, NodeInfo],
                         nodes: List[Node]) -> List[HostPriority]:
        """Reference: generic_scheduler.go:542-680."""
        # If no priority configs and no extenders: all nodes score 1 (:556-571).
        if not self.prioritizers and not self.extenders:
            return [HostPriority(n.name, 1) for n in nodes]

        meta = self.priority_meta_producer(pod) if self.priority_meta_producer else None

        # map phase per config (nodes × maps), then per-config reduce
        results: List[List[HostPriority]] = []
        for config in self.prioritizers:
            if config.function is not None:
                results.append(config.function(pod, node_info_map, nodes))
            else:
                per_node = [config.map_fn(pod, meta, node_info_map[n.name]) for n in nodes]
                results.append(per_node)
        for i, config in enumerate(self.prioritizers):
            if config.reduce_fn is not None:
                config.reduce_fn(pod, meta, node_info_map, results[i])

        # weighted sum (:631-639)
        result = []
        for i, node in enumerate(nodes):
            total = 0
            for j, config in enumerate(self.prioritizers):
                total += results[j][i].score * config.weight
            result.append(HostPriority(node.name, total))

        if self.extenders:
            combined = {hp.host: hp.score for hp in result}
            for extender in self.extenders:
                prioritized_list, weight = extender.prioritize(pod, nodes)
                for hp in prioritized_list:
                    combined[hp.host] += hp.score * weight
            result = [HostPriority(n.name, combined[n.name]) for n in nodes]
        return result

    # --- select phase ---

    def select_host(self, priority_list: List[HostPriority]) -> str:
        """Reference: generic_scheduler.go:183-198 — stable sort desc by score,
        round-robin among the top-score ties via the persistent counter."""
        if not priority_list:
            raise SchedulingError("empty priorityList")
        ordered = sorted(priority_list, key=lambda hp: -hp.score)
        max_score = ordered[0].score
        first_after_max = 1
        while first_after_max < len(ordered) and ordered[first_after_max].score == max_score:
            first_after_max += 1
        ix = self.last_node_index % first_after_max
        self.last_node_index += 1
        return ordered[ix].host

    # --- the pipeline ---

    def schedule(self, pod: Pod, nodes: List[Node],
                 node_info_map: Dict[str, NodeInfo]) -> str:
        """Reference: generic_scheduler.go:112-180."""
        if not nodes:
            raise ERR_NO_NODES_AVAILABLE
        filtered, failed_predicate_map = self.find_nodes_that_fit(pod, nodes, node_info_map)
        if not filtered:
            raise FitError(pod, len(nodes), failed_predicate_map)
        if len(filtered) == 1:
            return filtered[0].name
        priority_list = self.prioritize_nodes(pod, node_info_map, filtered)
        return self.select_host(priority_list)

    def preempt(self, pod: Pod, nodes: List[Node],
                node_info_map: Dict[str, NodeInfo], schedule_err: Exception):
        """Reference: generic_scheduler.go:205-262. Pod priority is feature-gated
        off at the reference's defaults (scheduler.go:210-213 short-circuits via
        util.PodPriorityEnabled), so preemption never fires in simulation runs;
        the full victim-selection pipeline is tracked for a later milestone."""
        return None, [], []

"""Equivalence cache: memoized predicate results per equivalence class.

Reference: core/equivalence_cache.go — per-node LRU (100 entries) of
predicate-name -> {equivalence hash -> (fit, reasons)}, where the equivalence
class of a pod is derived from its controller OwnerReferences (pods stamped
from the same template are interchangeable for predicate evaluation), with
invalidation hooks driven by cluster events (factory.go event handlers).

Note the JAX backend intentionally does NOT port this: its compile step
materializes every signature×node result up front (tpusim/jaxe/__init__.py),
which subsumes the cache. This implementation serves the reference backend and
capability parity.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from tpusim.api.types import Pod

ALGORITHM_CACHE_SIZE = 100  # equivalence_cache.go: maxCacheEntries


def get_equivalence_hash(pod: Pod, pvc_getter: Callable = None) -> Optional[int]:
    """predicates.EquivalencePodGenerator.getEquivalencePod (utils.go:87-124)
    hashed like getHashEquivalencePod: the equivalence class is the pod's
    CONTROLLER owner reference plus its (unordered) set of resolved PVC UIDs
    — pods stamped from the same template claiming the same PVCs are
    interchangeable for predicate evaluation. No controller reference, or a
    PVC that does not resolve, means no valid class (not cacheable)."""
    for ref in pod.metadata.owner_references:
        if not ref.controller:
            continue
        pvc_set = set()
        for volume in pod.spec.volumes:
            claim = volume.pvc_name
            if claim is None:
                continue
            pvc = pvc_getter(pod.namespace, claim) if pvc_getter else None
            if pvc is None:
                return None  # unresolvable claim: no equivalence class
            pvc_set.add(pvc.metadata.uid or pvc.key())
        # a pod can only belong to one controller
        return hash((ref.api_version, ref.kind, ref.name, ref.uid,
                     frozenset(pvc_set)))
    return None


class _LRU(OrderedDict):
    def __init__(self, maxsize: int):
        super().__init__()
        self.maxsize = maxsize

    def get_entry(self, key):
        if key in self:
            self.move_to_end(key)
            return self[key]
        return None

    def put(self, key, value):
        if key in self:
            self.move_to_end(key)
        self[key] = value
        if len(self) > self.maxsize:
            self.popitem(last=False)


class EquivalenceCache:
    def __init__(self, pvc_getter: Callable = None):
        """pvc_getter: the PVC lister handed to the equivalence-class
        generator (factory.go passes the PVC informer into
        NewEquivalencePodGenerator)."""
        # node name -> LRU(predicate key -> {equiv hash -> (fit, reasons)})
        self._by_node: Dict[str, _LRU] = {}
        self._pvc_getter = pvc_getter
        self.hits = 0
        self.misses = 0

    def get_equivalence_class_hash(self, pod: Pod) -> Optional[int]:
        """getEquivalenceClassInfo via the configured generator."""
        return get_equivalence_hash(pod, self._pvc_getter)

    def lookup(self, node_name: str, predicate_key: str,
               equiv_hash: int) -> Optional[Tuple[bool, list]]:
        node_cache = self._by_node.get(node_name)
        if node_cache is None:
            self.misses += 1
            return None
        pred_map = node_cache.get_entry(predicate_key)
        if pred_map is None or equiv_hash not in pred_map:
            self.misses += 1
            return None
        self.hits += 1
        return pred_map[equiv_hash]

    def update(self, node_name: str, predicate_key: str, equiv_hash: int,
               fit: bool, reasons: list) -> None:
        node_cache = self._by_node.setdefault(node_name, _LRU(ALGORITHM_CACHE_SIZE))
        pred_map = node_cache.get_entry(predicate_key)
        if pred_map is None:
            pred_map = {}
            node_cache.put(predicate_key, pred_map)
        pred_map[equiv_hash] = (fit, list(reasons))

    def run_predicate(self, predicate, predicate_key: str, pod: Pod, meta,
                      node_info, equiv_hash: Optional[int]):
        """RunPredicate: consult the cache, else evaluate and fill."""
        node_name = node_info.node.name if node_info.node is not None else ""
        if equiv_hash is not None and node_name:
            cached = self.lookup(node_name, predicate_key, equiv_hash)
            if cached is not None:
                return cached[0], list(cached[1])
        fit, reasons = predicate(pod, meta, node_info)
        if equiv_hash is not None and node_name:
            self.update(node_name, predicate_key, equiv_hash, fit, reasons)
        return fit, reasons

    # --- invalidation hooks (equivalence_cache.go:126-233) ---

    def invalidate_predicates(self, predicate_keys: List[str]) -> None:
        for node_cache in self._by_node.values():
            for key in predicate_keys:
                node_cache.pop(key, None)

    def invalidate_predicates_on_node(self, node_name: str,
                                      predicate_keys: List[str]) -> None:
        node_cache = self._by_node.get(node_name)
        if node_cache is not None:
            for key in predicate_keys:
                node_cache.pop(key, None)

    def invalidate_all_on_node(self, node_name: str) -> None:
        self._by_node.pop(node_name, None)

    def invalidate_cached_predicate_item_of_all_nodes(
            self, predicate_keys: List[str]) -> None:
        self.invalidate_predicates(predicate_keys)

"""Plugin registry + algorithm providers.

Reference: factory/plugins.go:111-376 (RegisterFitPredicate /
RegisterPriorityFunction2 / RegisterAlgorithmProvider / policy factories) and
algorithmprovider/defaults/defaults.go (DefaultProvider,
ClusterAutoscalerProvider, and the locally-added TalkintDataProvider =
defaults with LeastRequested→MostRequested; defaults.go:33-37,207-217).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

from tpusim.engine import predicates as preds
from tpusim.engine import priorities as prios
from tpusim.engine.generic_scheduler import GenericScheduler
from tpusim.engine.priorities import PriorityConfig

DEFAULT_PROVIDER = "DefaultProvider"
CLUSTER_AUTOSCALER_PROVIDER = "ClusterAutoscalerProvider"
TD_PROVIDER = "TalkintDataProvider"

DEFAULT_HARD_POD_AFFINITY_SYMMETRIC_WEIGHT = 1  # schedulerapi default; simulator passes 10

# the DefaultProvider predicate key set (defaults.go:169-205), shared by all
# three shipped providers; module-level so policy compilation
# (jaxe/policyc.classify_preemption_class) can classify a provider-default
# policy without assembling a registry
DEFAULT_PREDICATE_KEYS = frozenset({
    preds.NO_VOLUME_ZONE_CONFLICT_PRED,
    preds.MAX_EBS_VOLUME_COUNT_PRED,
    preds.MAX_GCE_PD_VOLUME_COUNT_PRED,
    preds.MAX_AZURE_DISK_VOLUME_COUNT_PRED,
    preds.MATCH_INTERPOD_AFFINITY_PRED,
    preds.NO_DISK_CONFLICT_PRED,
    preds.GENERAL_PRED,
    preds.CHECK_NODE_MEMORY_PRESSURE_PRED,
    preds.CHECK_NODE_DISK_PRESSURE_PRED,
    preds.CHECK_NODE_CONDITION_PRED,
    preds.POD_TOLERATES_NODE_TAINTS_PRED,
    preds.CHECK_VOLUME_BINDING_PRED,
})


@dataclass
class PluginFactoryArgs:
    """Reference: factory/plugins.go PluginFactoryArgs — the listers handed to
    predicate/priority factories."""

    pod_lister: Callable[[], list] = field(default=lambda: [])
    service_lister: Callable[[], list] = field(default=lambda: [])
    controller_lister: Callable[[], list] = field(default=lambda: [])
    replica_set_lister: Callable[[], list] = field(default=lambda: [])
    stateful_set_lister: Callable[[], list] = field(default=lambda: [])
    node_info_getter: Callable[[str], object] = field(default=lambda name: None)
    # volume listers (factory.go pVLister/pVCLister/storageClassLister) + the
    # scheduler-side binder (factory.go:252-259); None binder = gate off
    pvc_getter: Callable[[str, str], object] = field(default=lambda ns, name: None)
    pv_getter: Callable[[str], object] = field(default=lambda name: None)
    storage_class_getter: Callable[[str], object] = field(default=lambda name: None)
    volume_binder: Optional[object] = None
    volume_scheduling_enabled: bool = False
    hard_pod_affinity_symmetric_weight: int = DEFAULT_HARD_POD_AFFINITY_SYMMETRIC_WEIGHT
    # extended resources ignored in PodFitsResources because an extender
    # manages them (factory.go:984-988)
    ignored_extended_resources: Optional[Set[str]] = None

    def selector_spread(self) -> "prios.SelectorSpread":
        """One shared SelectorSpread per factory args, so the map/reduce fns and
        the priority-metadata pod_selectors can never disagree."""
        if not hasattr(self, "_selector_spread"):
            self._selector_spread = prios.SelectorSpread(
                self.service_lister, self.controller_lister,
                self.replica_set_lister, self.stateful_set_lister)
        return self._selector_spread


@dataclass
class PriorityConfigFactory:
    map_reduce_function: Optional[Callable] = None  # args -> (map_fn, reduce_fn)
    function: Optional[Callable] = None             # args -> legacy function
    weight: int = 1


# plugins.go:476 validName — note the upstream regex requires >= 2 chars
VALID_NAME_RE = re.compile(r"^[a-zA-Z0-9]([-a-zA-Z0-9]*[a-zA-Z0-9])$")
# api/types.go:31-38 — MaxInt is Go's 64-bit int; MaxWeight = MaxInt/MaxPriority
MAX_TOTAL_PRIORITY = 2**63 - 1


def validate_algorithm_name(name: str) -> None:
    """plugins.go:478-482 validateAlgorithmNameOrDie (raises, never dies).
    fullmatch, not match: Python's $ would accept a trailing newline that
    Go's end-of-text anchor rejects."""
    if not VALID_NAME_RE.fullmatch(name):
        raise ValueError(f"algorithm name {name!r} does not match the name "
                         f"validation regex \"{VALID_NAME_RE.pattern}\"")


def validate_selected_configs(configs: List["PriorityConfig"]) -> None:
    """plugins.go:463-474: the summed weight*MaxPriority must not overflow."""
    from tpusim.engine.priorities import MAX_PRIORITY

    total = 0
    for config in configs:
        if config.weight * MAX_PRIORITY > MAX_TOTAL_PRIORITY - total:
            raise ValueError(
                "Total priority of priority functions has overflown")
        total += config.weight * MAX_PRIORITY


class AlgorithmRegistry:
    """One registry instance == the Go package-level registries."""

    def __init__(self):
        self.fit_predicates: Dict[str, Callable] = {}           # name -> fn
        self.fit_predicate_factories: Dict[str, Callable] = {}  # name -> (args -> fn)
        self.mandatory_fit_predicates: Set[str] = set()
        self.priority_factories: Dict[str, PriorityConfigFactory] = {}
        self.providers: Dict[str, tuple[Set[str], Set[str]]] = {}

    # --- registration (plugins.go:111-376) ---

    def register_fit_predicate(self, name: str, fn: Callable) -> str:
        validate_algorithm_name(name)
        self.fit_predicates[name] = fn
        return name

    def register_fit_predicate_factory(self, name: str, factory: Callable) -> str:
        validate_algorithm_name(name)
        self.fit_predicate_factories[name] = factory
        return name

    def register_mandatory_fit_predicate(self, name: str, fn: Callable) -> str:
        validate_algorithm_name(name)
        self.fit_predicates[name] = fn
        self.mandatory_fit_predicates.add(name)
        return name

    def remove_fit_predicate(self, name: str) -> None:
        self.fit_predicates.pop(name, None)
        self.fit_predicate_factories.pop(name, None)
        self.mandatory_fit_predicates.discard(name)

    def register_priority_function2(self, name: str, map_fn, reduce_fn, weight: int) -> str:
        validate_algorithm_name(name)
        self.priority_factories[name] = PriorityConfigFactory(
            map_reduce_function=lambda args: (map_fn, reduce_fn), weight=weight)
        return name

    def register_priority_config_factory(self, name: str,
                                         factory: PriorityConfigFactory) -> str:
        validate_algorithm_name(name)
        self.priority_factories[name] = factory
        return name

    def register_algorithm_provider(self, name: str, predicate_keys: Set[str],
                                    priority_keys: Set[str]) -> str:
        validate_algorithm_name(name)
        self.providers[name] = (set(predicate_keys), set(priority_keys))
        return name

    def get_algorithm_provider(self, name: str) -> tuple[Set[str], Set[str]]:
        if name not in self.providers:
            raise KeyError(f"plugin {name!r} has not been registered")
        return self.providers[name]

    # --- assembly (factory.go CreateFromKeys:1021-1082) ---

    def build_predicates(self, keys: Set[str], args: PluginFactoryArgs) -> Dict[str, Callable]:
        result: Dict[str, Callable] = {}
        for key in set(keys) | self.mandatory_fit_predicates:
            if key in self.fit_predicate_factories:
                result[key] = self.fit_predicate_factories[key](args)
            elif key in self.fit_predicates:
                result[key] = self.fit_predicates[key]
            else:
                raise KeyError(f"invalid predicate key {key!r}")
        return result

    def build_prioritizers(self, keys: Set[str], args: PluginFactoryArgs
                           ) -> List[PriorityConfig]:
        configs = []
        for key in sorted(keys):  # deterministic (Go iterates a map)
            if key not in self.priority_factories:
                raise KeyError(f"invalid priority key {key!r}")
            factory = self.priority_factories[key]
            if factory.function is not None:
                configs.append(PriorityConfig(name=key, weight=factory.weight,
                                              function=factory.function(args)))
            else:
                map_fn, reduce_fn = factory.map_reduce_function(args)
                configs.append(PriorityConfig(name=key, weight=factory.weight,
                                              map_fn=map_fn, reduce_fn=reduce_fn))
        validate_selected_configs(configs)
        return configs


def default_registry() -> AlgorithmRegistry:
    """Reproduces algorithmprovider/defaults/defaults.go init()."""
    r = AlgorithmRegistry()

    # --- predicates (defaults.go:113-178 + init extras) ---
    r.register_fit_predicate_factory(
        preds.NO_VOLUME_ZONE_CONFLICT_PRED,
        lambda args: preds.make_no_volume_zone_conflict_predicate(
            args.pvc_getter, args.pv_getter, args.storage_class_getter,
            volume_scheduling_enabled=args.volume_scheduling_enabled))
    r.register_fit_predicate_factory(
        preds.MAX_EBS_VOLUME_COUNT_PRED,
        lambda args: preds.make_max_pd_volume_count_predicate(
            "EBS", args.pvc_getter, args.pv_getter))
    r.register_fit_predicate_factory(
        preds.MAX_GCE_PD_VOLUME_COUNT_PRED,
        lambda args: preds.make_max_pd_volume_count_predicate(
            "GCE", args.pvc_getter, args.pv_getter))
    r.register_fit_predicate_factory(
        preds.MAX_AZURE_DISK_VOLUME_COUNT_PRED,
        lambda args: preds.make_max_pd_volume_count_predicate(
            "AzureDisk", args.pvc_getter, args.pv_getter))
    r.register_fit_predicate_factory(
        preds.MATCH_INTERPOD_AFFINITY_PRED,
        lambda args: preds.make_pod_affinity_predicate(args.node_info_getter,
                                                       args.pod_lister))
    r.register_fit_predicate(preds.NO_DISK_CONFLICT_PRED, preds.no_disk_conflict)
    r.register_fit_predicate(preds.GENERAL_PRED, preds.general_predicates)
    r.register_fit_predicate(preds.CHECK_NODE_MEMORY_PRESSURE_PRED,
                             preds.check_node_memory_pressure)
    r.register_fit_predicate(preds.CHECK_NODE_DISK_PRESSURE_PRED,
                             preds.check_node_disk_pressure)
    r.register_mandatory_fit_predicate(preds.CHECK_NODE_CONDITION_PRED,
                                       preds.check_node_condition)
    r.register_fit_predicate(preds.POD_TOLERATES_NODE_TAINTS_PRED,
                             preds.pod_tolerates_node_taints)
    r.register_fit_predicate_factory(
        preds.CHECK_VOLUME_BINDING_PRED,
        lambda args: preds.make_check_volume_binding_predicate(args.volume_binder))
    # registered-but-not-default predicates (defaults.go init():60-111)
    r.register_fit_predicate(preds.POD_FITS_RESOURCES_PRED, preds.pod_fits_resources)
    r.register_fit_predicate(preds.HOSTNAME_PRED, preds.pod_fits_host)
    r.register_fit_predicate(preds.POD_FITS_HOST_PORTS_PRED, preds.pod_fits_host_ports)
    # 1.0 backward-compat alias for PodFitsHostPorts (defaults.go:63-65)
    r.register_fit_predicate("PodFitsPorts", preds.pod_fits_host_ports)
    r.register_fit_predicate(preds.MATCH_NODE_SELECTOR_PRED, preds.pod_match_node_selector)
    r.register_fit_predicate(preds.CHECK_NODE_UNSCHEDULABLE_PRED,
                             preds.check_node_unschedulable)
    r.register_fit_predicate(preds.POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED,
                             preds.pod_tolerates_node_no_execute_taints)

    default_predicate_keys = set(DEFAULT_PREDICATE_KEYS)

    # --- priorities (defaults.go:219-259 + init extras) ---
    r.register_priority_config_factory(
        "SelectorSpreadPriority",
        PriorityConfigFactory(
            map_reduce_function=lambda args: _selector_spread_map_reduce(args),
            weight=1))
    r.register_priority_config_factory(
        "InterPodAffinityPriority",
        PriorityConfigFactory(
            function=lambda args: prios.InterPodAffinityPriority(
                args.node_info_getter,
                args.hard_pod_affinity_symmetric_weight).calculate,
            weight=1))
    r.register_priority_function2("LeastRequestedPriority",
                                  prios.least_requested_priority_map, None, 1)
    r.register_priority_function2("BalancedResourceAllocation",
                                  prios.balanced_resource_allocation_map, None, 1)
    r.register_priority_function2("NodePreferAvoidPodsPriority",
                                  prios.calculate_node_prefer_avoid_pods_priority_map,
                                  None, 10000)
    r.register_priority_function2("NodeAffinityPriority",
                                  prios.calculate_node_affinity_priority_map,
                                  prios.calculate_node_affinity_priority_reduce, 1)
    r.register_priority_function2("TaintTolerationPriority",
                                  prios.compute_taint_toleration_priority_map,
                                  prios.compute_taint_toleration_priority_reduce, 1)
    # registered-but-not-default (defaults.go:100-111)
    # 1.0 backward-compat alias: service-only spreading (defaults.go:89-101 —
    # SelectorSpread over the service lister with EMPTY controller/RS/SS
    # listers, unlike SelectorSpreadPriority's fully-wired instance)
    r.register_priority_config_factory(
        "ServiceSpreadingPriority",
        PriorityConfigFactory(
            map_reduce_function=lambda args: _service_spreading_map_reduce(args),
            weight=1))
    r.register_priority_function2("EqualPriority", prios.equal_priority_map, None, 1)
    r.register_priority_function2("ImageLocalityPriority",
                                  prios.image_locality_priority_map, None, 1)
    r.register_priority_function2("MostRequestedPriority",
                                  prios.most_requested_priority_map, None, 1)

    default_priority_keys = {
        "SelectorSpreadPriority",
        "InterPodAffinityPriority",
        "LeastRequestedPriority",
        "BalancedResourceAllocation",
        "NodePreferAvoidPodsPriority",
        "NodeAffinityPriority",
        "TaintTolerationPriority",
    }

    def copy_and_replace(keys: Set[str], what: str, with_: str) -> Set[str]:
        result = set(keys)
        if what in result:
            result.discard(what)
            result.add(with_)
        return result

    # registerAlgorithmProvider (defaults.go:207-217)
    r.register_algorithm_provider(DEFAULT_PROVIDER, default_predicate_keys,
                                  default_priority_keys)
    autoscaler_priorities = copy_and_replace(
        default_priority_keys, "LeastRequestedPriority", "MostRequestedPriority")
    r.register_algorithm_provider(CLUSTER_AUTOSCALER_PROVIDER, default_predicate_keys,
                                  autoscaler_priorities)
    r.register_algorithm_provider(TD_PROVIDER, default_predicate_keys,
                                  autoscaler_priorities)
    return r


KNOWN_FEATURE_GATES = {"TaintNodesByCondition", "ResourceLimitsPriorityFunction",
                       "PodPriority", "VolumeScheduling"}


def parse_feature_gates(spec: str) -> Dict[str, bool]:
    """Parse the kube --feature-gates map flag ("Key=true,Other=false");
    unknown keys and non-boolean values are rejected like
    utilfeature.DefaultFeatureGate.Set does."""
    gates: Dict[str, bool] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, val = part.partition("=")
        key = key.strip()
        if key not in KNOWN_FEATURE_GATES:
            raise ValueError(f"unrecognized feature gate: {key}")
        if not sep:
            raise ValueError(f"missing bool value for {key}")
        val = val.strip().lower()
        if val not in ("true", "false"):
            raise ValueError(
                f"invalid value of {key}={val}, err: strconv.ParseBool: "
                f"parsing {val!r}: invalid syntax")
        gates[key] = val == "true"
    return gates


def apply_feature_gates(registry: AlgorithmRegistry,
                        gates: Dict[str, bool]) -> None:
    """ApplyFeatureGates (defaults.go:181-205): feature-gate-driven registry
    surgery, run before provider/policy assembly like the scheduler app does.

    TaintNodesByCondition: CheckNodeCondition is removed (from the registry
    AND every provider's key set) and PodToleratesNodeTaints becomes a
    MANDATORY predicate inserted into every provider — fit is then
    determined by whether the pod tolerates all of the node's taints.
    ResourceLimitsPriorityFunction: registers ResourceLimitsPriority at
    weight 1 (registration only — selection still follows the provider or
    policy keys, matching the Go behavior). Both gates default off in this
    k8s vintage."""
    if gates.get("TaintNodesByCondition"):
        registry.remove_fit_predicate(preds.CHECK_NODE_CONDITION_PRED)
        for pred_keys, _pri_keys in registry.providers.values():
            pred_keys.discard(preds.CHECK_NODE_CONDITION_PRED)
        registry.register_mandatory_fit_predicate(
            preds.POD_TOLERATES_NODE_TAINTS_PRED,
            preds.pod_tolerates_node_taints)
        for pred_keys, _pri_keys in registry.providers.values():
            pred_keys.add(preds.POD_TOLERATES_NODE_TAINTS_PRED)
    if gates.get("ResourceLimitsPriorityFunction"):
        registry.register_priority_function2(
            "ResourceLimitsPriority", prios.resource_limits_priority_map,
            None, 1)


def _selector_spread_map_reduce(args: PluginFactoryArgs):
    spread = args.selector_spread()
    return spread.calculate_spread_priority_map, spread.calculate_spread_priority_reduce


def _service_spreading_map_reduce(args: PluginFactoryArgs):
    """ServiceSpreadingPriority (1.0 alias): services only, empty controller/
    ReplicaSet/StatefulSet listers (defaults.go:92-100)."""
    spread = prios.SelectorSpread(args.service_lister)
    return (spread.calculate_spread_priority_map,
            spread.calculate_spread_priority_reduce)


def create_from_provider(provider: str, args: PluginFactoryArgs,
                         registry: Optional[AlgorithmRegistry] = None,
                         always_check_all_predicates: bool = False) -> GenericScheduler:
    """factory.go CreateFromProvider → CreateFromKeys."""
    registry = registry or default_registry()
    pred_keys, pri_keys = registry.get_algorithm_provider(provider)
    return _create_from_keys(registry, pred_keys, pri_keys, args,
                             always_check_all_predicates=always_check_all_predicates)


def _create_from_keys(registry: AlgorithmRegistry, pred_keys: Set[str],
                      pri_keys: Set[str], args: PluginFactoryArgs,
                      extenders: Optional[list] = None,
                      always_check_all_predicates: bool = False) -> GenericScheduler:
    """factory.go CreateFromKeys:1021-1082."""
    weight = args.hard_pod_affinity_symmetric_weight
    if weight < 1 or weight > 100:
        # factory.go:1024-1026: the range is [1, 100]
        raise ValueError(f"invalid hardPodAffinitySymmetricWeight: {weight}, "
                         "must be in the range 1-100")
    predicates = registry.build_predicates(pred_keys, args)
    prioritizers = registry.build_prioritizers(pri_keys, args)

    def priority_meta_producer(pod):
        return prios.get_priority_metadata(pod, args.selector_spread())

    def predicate_meta_producer(pod, node_info_map):
        return preds.get_predicate_metadata(
            pod, node_info_map,
            ignored_extended_resources=args.ignored_extended_resources)

    return GenericScheduler(
        predicates=predicates,
        prioritizers=prioritizers,
        predicate_meta_producer=predicate_meta_producer,
        priority_meta_producer=priority_meta_producer,
        extenders=extenders,
        always_check_all_predicates=always_check_all_predicates,
    )


# ---------------------------------------------------------------------------
# policy-as-data assembly (factory.go CreateFromConfig:933-1000,
# plugins.go RegisterCustomFitPredicate:197-240 /
# RegisterCustomPriorityFunction:302-348)
# ---------------------------------------------------------------------------


def register_custom_fit_predicate(registry: AlgorithmRegistry,
                                  pred_policy) -> str:
    """plugins.go RegisterCustomFitPredicate:197-240: a policy entry either
    instantiates a parameterized predicate (ServiceAffinity / LabelsPresence)
    under the policy's name, or references a pre-registered predicate."""
    arg = pred_policy.argument
    if arg is not None:
        if arg.service_affinity is not None:
            labels = list(arg.service_affinity.labels)
            factory = lambda args: preds.make_service_affinity_predicate(  # noqa: E731
                labels, args.pod_lister, args.service_lister,
                args.node_info_getter)
            return registry.register_fit_predicate_factory(pred_policy.name, factory)
        if arg.labels_presence is not None:
            labels = list(arg.labels_presence.labels)
            presence = arg.labels_presence.presence
            factory = lambda args: preds.make_node_label_presence_predicate(  # noqa: E731
                labels, presence)
            return registry.register_fit_predicate_factory(pred_policy.name, factory)
    if pred_policy.name in registry.fit_predicates \
            or pred_policy.name in registry.fit_predicate_factories:
        return pred_policy.name  # pre-defined predicate requested: reuse
    raise KeyError("Invalid configuration: Predicate type not found for "
                   f"{pred_policy.name}")


def register_custom_priority_function(registry: AlgorithmRegistry,
                                      pri_policy) -> str:
    """plugins.go RegisterCustomPriorityFunction:302-348."""
    arg = pri_policy.argument
    factory: Optional[PriorityConfigFactory] = None
    if arg is not None:
        if arg.service_anti_affinity is not None:
            label = arg.service_anti_affinity.label
            factory = PriorityConfigFactory(
                map_reduce_function=lambda args, label=label:
                    prios.make_service_anti_affinity_priority(
                        args.pod_lister, args.service_lister, label),
                weight=pri_policy.weight)
        elif arg.label_preference is not None:
            label = arg.label_preference.label
            presence = arg.label_preference.presence
            factory = PriorityConfigFactory(
                map_reduce_function=lambda args, label=label, presence=presence:
                    (prios.make_node_label_priority_map(label, presence), None),
                weight=pri_policy.weight)
    elif pri_policy.name in registry.priority_factories:
        existing = registry.priority_factories[pri_policy.name]
        # reuse the registered function, but take the policy's weight
        factory = PriorityConfigFactory(
            map_reduce_function=existing.map_reduce_function,
            function=existing.function, weight=pri_policy.weight)
    if factory is None:
        raise KeyError("Invalid configuration: Priority type not found for "
                       f"{pri_policy.name}")
    return registry.register_priority_config_factory(pri_policy.name, factory)


def create_from_config(policy, args: PluginFactoryArgs,
                       registry: Optional[AlgorithmRegistry] = None,
                       extender_transport=None) -> GenericScheduler:
    """factory.go CreateFromConfig:933-1000.

    policy.predicates None → DefaultProvider predicate keys; [] → mandatory
    only. policy.priorities None → DefaultProvider priority keys; [] → none.
    Extenders are built from ExtenderConfigs; a policy-provided
    HardPodAffinitySymmetricWeight overrides the CLI/config value, and
    AlwaysCheckAllPredicates can only be switched on, never off.
    """
    from tpusim.engine.extender import new_http_extender
    from tpusim.engine.policy import validate_policy

    validate_policy(policy)
    registry = registry or default_registry()

    if policy.predicates is None:
        pred_keys, _ = registry.get_algorithm_provider(DEFAULT_PROVIDER)
    else:
        pred_keys = {register_custom_fit_predicate(registry, p)
                     for p in policy.predicates}
    if policy.priorities is None:
        _, pri_keys = registry.get_algorithm_provider(DEFAULT_PROVIDER)
    else:
        pri_keys = {register_custom_priority_function(registry, p)
                    for p in policy.priorities}

    extenders = [new_http_extender(cfg, transport=extender_transport)
                 for cfg in policy.extender_configs]
    # predicates skip resources ignored by an extender (factory.go:984-988)
    ignored = {r.name for cfg in policy.extender_configs
               for r in cfg.managed_resources if r.ignored_by_scheduler}
    if ignored:
        args.ignored_extended_resources = ignored

    if policy.hard_pod_affinity_symmetric_weight != 0:
        args.hard_pod_affinity_symmetric_weight = \
            policy.hard_pod_affinity_symmetric_weight
    return _create_from_keys(
        registry, pred_keys, pri_keys, args, extenders=extenders,
        always_check_all_predicates=policy.always_check_all_predicates)

"""Priority (scoring) functions: map/reduce model with weighted summation.

Reference: algorithm/priorities/*.go. A priority is either a per-node map
function plus optional reduce (normalize) function, or a legacy whole-list
function (InterPodAffinity). MaxPriority = 10 (api/types.go:36).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from tpusim.api.types import (
    LABEL_ZONE_FAILURE_DOMAIN,
    LABEL_ZONE_REGION,
    TAINT_PREFER_NO_SCHEDULE,
    Node,
    Pod,
    tolerations_tolerate_taint,
)
from tpusim.engine.predicates import (
    get_namespaces_from_pod_affinity_term,
    nodes_have_same_topology_key,
    pod_matches_term_namespace_and_selector,
)
from tpusim.engine.resources import (
    NodeInfo,
    Resource,
    get_nonzero_pod_request,
)

MAX_PRIORITY = 10


@dataclass
class HostPriority:
    """Reference: api/types.go HostPriority{Host,Score}."""

    host: str
    score: int


@dataclass
class PriorityConfig:
    name: str
    weight: int = 1
    map_fn: Optional[Callable] = None      # (pod, meta, node_info) -> HostPriority
    reduce_fn: Optional[Callable] = None   # (pod, meta, node_info_map, result) -> None
    function: Optional[Callable] = None    # legacy: (pod, node_info_map, nodes) -> [HostPriority]


# ---------------------------------------------------------------------------
# resource-allocation family (resource_allocation.go scaffold)
# ---------------------------------------------------------------------------


def _resource_allocation_map(pod: Pod, meta, node_info: NodeInfo, scorer) -> HostPriority:
    if node_info.node is None:
        raise ValueError("node not found")
    if meta is not None and meta.nonzero_request is not None:
        requested = meta.nonzero_request.clone()
    else:
        # clone: the memoized request (engine/resources.request_memo) is a
        # shared object and the += below must not corrupt it
        requested = get_nonzero_pod_request(pod).clone()
    requested.milli_cpu += node_info.nonzero_request.milli_cpu
    requested.memory += node_info.nonzero_request.memory
    return HostPriority(node_info.node.name,
                        int(scorer(requested, node_info.allocatable_resource)))


def _least_requested_score(requested: int, capacity: int) -> int:
    """least_requested.go:41-52 — ((capacity-requested)*10)/capacity, int division."""
    if capacity == 0 or requested > capacity:
        return 0
    return ((capacity - requested) * MAX_PRIORITY) // capacity


def least_requested_priority_map(pod: Pod, meta, node_info: NodeInfo) -> HostPriority:
    return _resource_allocation_map(
        pod, meta, node_info,
        lambda req, alloc: (_least_requested_score(req.milli_cpu, alloc.milli_cpu)
                            + _least_requested_score(req.memory, alloc.memory)) // 2)


def _most_requested_score(requested: int, capacity: int) -> int:
    """most_requested.go:44-55."""
    if capacity == 0 or requested > capacity:
        return 0
    return (requested * MAX_PRIORITY) // capacity


def most_requested_priority_map(pod: Pod, meta, node_info: NodeInfo) -> HostPriority:
    return _resource_allocation_map(
        pod, meta, node_info,
        lambda req, alloc: (_most_requested_score(req.milli_cpu, alloc.milli_cpu)
                            + _most_requested_score(req.memory, alloc.memory)) // 2)


def _balanced_scorer(requested: Resource, allocatable: Resource) -> int:
    """balanced_resource_allocation.go:39-63, in exact rational arithmetic.

    Go computes int64((1 - |cpuFrac - memFrac|) * 10) in float64; this is the
    same quantity as floor(10 * (den - |rc*am - rm*ac|) / den) with
    den = ac*am, evaluated exactly (DEVIATIONS.md #16: scores deviate from
    Go only where float64 rounding crosses an integer boundary, and are
    identical across host/CPU/TPU)."""
    rc, ac = requested.milli_cpu, allocatable.milli_cpu
    rm, am = requested.memory, allocatable.memory
    # fractionOfCapacity: capacity 0 -> fraction 1; fraction >= 1 -> score 0
    if ac == 0 or rc >= ac or am == 0 or rm >= am:
        return 0
    num = abs(rc * am - rm * ac)
    den = ac * am
    return (MAX_PRIORITY * (den - num)) // den


def balanced_resource_allocation_map(pod: Pod, meta, node_info: NodeInfo) -> HostPriority:
    return _resource_allocation_map(pod, meta, node_info, _balanced_scorer)


# ---------------------------------------------------------------------------
# normalize reduce (reduce.go:29-62)
# ---------------------------------------------------------------------------


def normalize_reduce(max_priority: int, reverse: bool) -> Callable:
    def reduce_fn(pod: Pod, meta, node_info_map: Dict[str, NodeInfo],
                  result: List[HostPriority]) -> None:
        max_count = 0
        for hp in result:
            if hp.score > max_count:
                max_count = hp.score
        if max_count == 0:
            if reverse:
                for hp in result:
                    hp.score = max_priority
            return
        for hp in result:
            score = max_priority * hp.score // max_count
            if reverse:
                score = max_priority - score
            hp.score = score

    return reduce_fn


# ---------------------------------------------------------------------------
# node affinity (node_affinity.go:34-79)
# ---------------------------------------------------------------------------


def calculate_node_affinity_priority_map(pod: Pod, meta, node_info: NodeInfo) -> HostPriority:
    node = node_info.node
    if node is None:
        raise ValueError("node not found")
    affinity = meta.affinity if meta is not None else pod.spec.affinity
    count = 0
    if affinity is not None and affinity.node_affinity is not None:
        for term in affinity.node_affinity.preferred:
            if term.weight == 0:
                continue
            if term.preference.matches(node.metadata.labels):
                count += term.weight
    return HostPriority(node.name, count)


calculate_node_affinity_priority_reduce = normalize_reduce(MAX_PRIORITY, False)


# ---------------------------------------------------------------------------
# taint toleration (taint_toleration.go:30-75)
# ---------------------------------------------------------------------------


def _tolerations_prefer_no_schedule(tolerations: list) -> list:
    return [t for t in tolerations if not t.effect or t.effect == TAINT_PREFER_NO_SCHEDULE]


def compute_taint_toleration_priority_map(pod: Pod, meta, node_info: NodeInfo) -> HostPriority:
    node = node_info.node
    if node is None:
        raise ValueError("node not found")
    if meta is not None and meta.pod_tolerations is not None:
        tolerations = meta.pod_tolerations
    else:
        tolerations = _tolerations_prefer_no_schedule(pod.spec.tolerations)
    intolerable = 0
    for taint in node.spec.taints:
        if taint.effect != TAINT_PREFER_NO_SCHEDULE:
            continue
        if not tolerations_tolerate_taint(tolerations, taint):
            intolerable += 1
    return HostPriority(node.name, intolerable)


compute_taint_toleration_priority_reduce = normalize_reduce(MAX_PRIORITY, True)


# ---------------------------------------------------------------------------
# node prefer avoid pods (node_prefer_avoid_pods.go, weight 10000)
# ---------------------------------------------------------------------------


def calculate_node_prefer_avoid_pods_priority_map(pod: Pod, meta,
                                                  node_info: NodeInfo) -> HostPriority:
    node = node_info.node
    if node is None:
        raise ValueError("node not found")
    controller_ref = pod.metadata.controller_ref()
    if controller_ref is not None and controller_ref.kind not in (
            "ReplicationController", "ReplicaSet"):
        controller_ref = None
    if controller_ref is None:
        return HostPriority(node.name, MAX_PRIORITY)
    import json

    ann = node.metadata.annotations.get("scheduler.alpha.kubernetes.io/preferAvoidPods")
    if not ann:
        return HostPriority(node.name, MAX_PRIORITY)
    try:
        avoids = json.loads(ann)
    except ValueError:
        return HostPriority(node.name, MAX_PRIORITY)
    for avoid in avoids.get("preferAvoidPods", []):
        ctrl = (avoid.get("podSignature") or {}).get("podController") or {}
        if ctrl.get("kind") == controller_ref.kind and ctrl.get("uid") == controller_ref.uid:
            return HostPriority(node.name, 0)
    return HostPriority(node.name, MAX_PRIORITY)


# ---------------------------------------------------------------------------
# image locality (image_locality.go)
# ---------------------------------------------------------------------------

_MB = 1024 * 1024
_MIN_IMG_SIZE = 23 * _MB
_MAX_IMG_SIZE = 1000 * _MB


def image_locality_priority_map(pod: Pod, meta, node_info: NodeInfo) -> HostPriority:
    node = node_info.node
    if node is None:
        raise ValueError("node not found")
    sum_size = 0
    for container in pod.spec.containers:
        for image in node.status.images:
            if container.image in image.names:
                sum_size += image.size_bytes
                break
    if sum_size == 0 or sum_size < _MIN_IMG_SIZE:
        score = 0
    elif sum_size >= _MAX_IMG_SIZE:
        score = MAX_PRIORITY
    else:
        score = int(MAX_PRIORITY * (sum_size - _MIN_IMG_SIZE)
                    // (_MAX_IMG_SIZE - _MIN_IMG_SIZE) + 1)
    return HostPriority(node.name, score)


# ---------------------------------------------------------------------------
# resource limits (resource_limits.go; feature-gated registration)
# ---------------------------------------------------------------------------


def resource_limits_priority_map(pod: Pod, meta, node_info: NodeInfo) -> HostPriority:
    node = node_info.node
    if node is None:
        raise ValueError("node not found")
    allocatable = node_info.allocatable_resource
    cpu_limit = 0
    mem_limit = 0
    for c in pod.spec.containers:
        if "cpu" in c.limits:
            cpu_limit += c.limits["cpu"].milli_value()
        if "memory" in c.limits:
            mem_limit += c.limits["memory"].value()
    score = 0
    cpu_score = 1 if (cpu_limit > 0 and allocatable.milli_cpu >= cpu_limit) else 0
    mem_score = 1 if (mem_limit > 0 and allocatable.memory >= mem_limit) else 0
    if cpu_score == 1 or mem_score == 1:
        score = 1
    return HostPriority(node.name, score)


# ---------------------------------------------------------------------------
# node label (policy-configured)
# ---------------------------------------------------------------------------


def make_node_label_priority_map(label: str, presence: bool) -> Callable:
    def node_label_priority_map(pod: Pod, meta, node_info: NodeInfo) -> HostPriority:
        node = node_info.node
        if node is None:
            raise ValueError("node not found")
        exists = label in node.metadata.labels
        score = MAX_PRIORITY if exists == presence else 0
        return HostPriority(node.name, score)

    return node_label_priority_map


def equal_priority_map(pod: Pod, meta, node_info: NodeInfo) -> HostPriority:
    """core.EqualPriorityMap — weight-1 constant."""
    if node_info.node is None:
        raise ValueError("node not found")
    return HostPriority(node_info.node.name, 1)


# ---------------------------------------------------------------------------
# selector spreading (selector_spreading.go:66-175)
# ---------------------------------------------------------------------------

# Go's zoneWeighting = 2.0/3.0 (selector_spreading.go:41) appears below (and
# in jaxe/kernels.py) as its exact rational form node/3 + 2*zone/3, evaluated
# in integer arithmetic with one floor at the end — see DEVIATIONS.md #16.


def get_zone_key(node: Optional[Node]) -> str:
    """utilnode.GetZoneKey: region + ":\\x00:" + zone; "" when both absent."""
    if node is None:
        return ""
    labels = node.metadata.labels
    region = labels.get(LABEL_ZONE_REGION, "")
    zone = labels.get(LABEL_ZONE_FAILURE_DOMAIN, "")
    if not region and not zone:
        return ""
    return f"{region}:\x00:{zone}"


class SelectorSpread:
    def __init__(self, service_lister, controller_lister=None,
                 replica_set_lister=None, stateful_set_lister=None):
        self.service_lister = service_lister        # () -> [Service]
        self.controller_lister = controller_lister or (lambda: [])
        self.replica_set_lister = replica_set_lister or (lambda: [])
        self.stateful_set_lister = stateful_set_lister or (lambda: [])

    def _get_selectors(self, pod: Pod) -> list:
        """getSelectors — selector callables from matching services / RCs / RSs /
        StatefulSets. The simulator wires empty fakes for everything but services
        (simulator.go:352-366)."""
        selectors = []
        for svc in self.service_lister():
            if (svc.namespace == pod.namespace and svc.selector
                    and all(pod.metadata.labels.get(k) == v
                            for k, v in svc.selector.items())):
                sel = dict(svc.selector)
                selectors.append(lambda labels, sel=sel: all(
                    labels.get(k) == v for k, v in sel.items()))
        for obj in (list(self.controller_lister()) + list(self.replica_set_lister())
                    + list(self.stateful_set_lister())):
            sel_obj = getattr(obj, "selector", None)
            matches = getattr(obj, "matches", None)
            if callable(matches) and obj.namespace == pod.namespace \
                    and matches(pod.metadata.labels):
                selectors.append(matches)
            elif sel_obj and obj.namespace == pod.namespace and all(
                    pod.metadata.labels.get(k) == v for k, v in sel_obj.items()):
                selectors.append(lambda labels, sel=dict(sel_obj): all(
                    labels.get(k) == v for k, v in sel.items()))
        return selectors

    def calculate_spread_priority_map(self, pod: Pod, meta,
                                      node_info: NodeInfo) -> HostPriority:
        node = node_info.node
        if node is None:
            raise ValueError("node not found")
        if meta is not None and meta.pod_selectors is not None:
            selectors = meta.pod_selectors
        else:
            selectors = self._get_selectors(pod)
        if not selectors:
            return HostPriority(node.name, 0)
        count = 0
        for node_pod in node_info.pods:
            if pod.namespace != node_pod.namespace:
                continue
            if any(sel(node_pod.metadata.labels) for sel in selectors):
                count += 1
        return HostPriority(node.name, count)

    def calculate_spread_priority_reduce(self, pod: Pod, meta,
                                         node_info_map: Dict[str, NodeInfo],
                                         result: List[HostPriority]) -> None:
        counts_by_zone: Dict[str, int] = {}
        max_count_by_node = 0
        for hp in result:
            if hp.score > max_count_by_node:
                max_count_by_node = hp.score
            info = node_info_map.get(hp.host)
            zone_id = get_zone_key(info.node if info else None)
            if not zone_id:
                continue
            counts_by_zone[zone_id] = counts_by_zone.get(zone_id, 0) + hp.score
        max_count_by_zone = max(counts_by_zone.values(), default=0)
        have_zones = bool(counts_by_zone)
        # Exact rational form of Go's float64 math (DEVIATIONS.md #16):
        # nodeScore = 10*(mn-c)/mn (10 when mn==0), zoneScore likewise, and
        # the zone blend is nodeScore/3 + 2*zoneScore/3 (selector_spreading.go
        # hardcodes zoneWeighting = 2.0/3.0) — one floor at the end.
        for hp in result:
            mn = max_count_by_node
            node_num, node_den = (mn - hp.score, mn) if mn > 0 else (1, 1)
            zone_id = None
            if have_zones:
                info = node_info_map.get(hp.host)
                zone_id = get_zone_key(info.node if info else None)
            if zone_id:
                mz = max_count_by_zone
                zone_num, zone_den = ((mz - counts_by_zone[zone_id], mz)
                                      if mz > 0 else (1, 1))
                hp.score = (MAX_PRIORITY
                            * (node_num * zone_den + 2 * zone_num * node_den)
                            ) // (3 * node_den * zone_den)
            else:
                hp.score = (MAX_PRIORITY * node_num) // node_den


# ---------------------------------------------------------------------------
# service anti-affinity (selector_spreading.go:176-280; policy-configured via
# PriorityArgument.ServiceAntiAffinity)
# ---------------------------------------------------------------------------


class ServiceAntiAffinity:
    """Spread pods of the first matching service across node groups identified
    by a node label (selector_spreading.go:176-280)."""

    def __init__(self, pod_lister, service_lister, label: str):
        self.pod_lister = pod_lister        # () -> [Pod] (unused; node_info has pods)
        self.service_lister = service_lister  # () -> [Service]
        self.label = label

    def _first_service_selector(self, pod: Pod) -> Optional[dict]:
        """getFirstServiceSelector — selector of the first service whose
        selector matches the pod's labels, in lister order."""
        for svc in self.service_lister():
            if (svc.namespace == pod.namespace and svc.selector
                    and all(pod.metadata.labels.get(k) == v
                            for k, v in svc.selector.items())):
                return dict(svc.selector)
        return None

    def calculate_anti_affinity_priority_map(self, pod: Pod, meta,
                                             node_info: NodeInfo) -> HostPriority:
        """Score = count of same-namespace pods on this node matching the
        pod's first-service selector (selector_spreading.go:223-244)."""
        node = node_info.node
        if node is None:
            raise ValueError("node not found")
        selector = self._first_service_selector(pod)
        if selector is None:
            return HostPriority(node.name, 0)
        count = sum(
            1 for node_pod in node_info.pods
            if node_pod.namespace == pod.namespace
            and all(node_pod.metadata.labels.get(k) == v
                    for k, v in selector.items()))
        return HostPriority(node.name, count)

    def calculate_anti_affinity_priority_reduce(self, pod: Pod, meta,
                                                node_info_map: Dict[str, NodeInfo],
                                                result: List[HostPriority]) -> None:
        """Nodes without the label score 0; labeled nodes score
        MaxPriority * (total - podsInGroup) / total (selector_spreading.go:
        246-280)."""
        num_service_pods = 0
        pod_counts: Dict[str, int] = {}
        label_of_host: Dict[str, str] = {}
        for hp in result:
            num_service_pods += hp.score
            info = node_info_map.get(hp.host)
            node = info.node if info else None
            if node is None or self.label not in node.metadata.labels:
                continue
            label = node.metadata.labels[self.label]
            label_of_host[hp.host] = label
            pod_counts[label] = pod_counts.get(label, 0) + hp.score
        for hp in result:
            label = label_of_host.get(hp.host)
            if label is None:
                hp.score = 0
                continue
            # exact rational form of Go's float64 math (DEVIATIONS.md #16)
            if num_service_pods > 0:
                hp.score = (MAX_PRIORITY
                            * (num_service_pods - pod_counts[label])
                            ) // num_service_pods
            else:
                hp.score = MAX_PRIORITY


def make_service_anti_affinity_priority(pod_lister, service_lister, label: str):
    """NewServiceAntiAffinityPriority (selector_spreading.go:183-192)."""
    anti = ServiceAntiAffinity(pod_lister, service_lister, label)
    return (anti.calculate_anti_affinity_priority_map,
            anti.calculate_anti_affinity_priority_reduce)


# ---------------------------------------------------------------------------
# inter-pod affinity priority (interpod_affinity.go:118+, legacy Function form)
# ---------------------------------------------------------------------------


class InterPodAffinityPriority:
    def __init__(self, node_info_getter, hard_pod_affinity_weight: int = 10):
        self._node_info = node_info_getter  # (name) -> NodeInfo | None
        self.hard_pod_affinity_weight = hard_pod_affinity_weight

    def calculate(self, pod: Pod, node_info_map: Dict[str, NodeInfo],
                  nodes: List[Node]) -> List[HostPriority]:
        affinity = pod.spec.affinity
        has_affinity = affinity is not None and affinity.pod_affinity is not None
        has_anti_affinity = affinity is not None and affinity.pod_anti_affinity is not None

        # integer weights summed in exact integer arithmetic (Go uses float64
        # for the same integer-valued quantities; DEVIATIONS.md #16)
        counts: Dict[str, int] = {n.name: 0 for n in nodes}

        def process_term(term, pod_defining, pod_to_check, fixed_node: Node,
                         weight: int) -> None:
            namespaces = get_namespaces_from_pod_affinity_term(pod_defining, term)
            if not pod_matches_term_namespace_and_selector(
                    pod_to_check, namespaces, term.label_selector):
                return
            for node in nodes:
                if nodes_have_same_topology_key(node, fixed_node, term.topology_key):
                    counts[node.name] += weight

        def process_weighted_terms(terms, pod_defining, pod_to_check, fixed_node,
                                   multiplier: int) -> None:
            for wt in terms:
                process_term(wt.pod_affinity_term, pod_defining, pod_to_check,
                             fixed_node, wt.weight * multiplier)

        def process_pod(existing_pod: Pod) -> None:
            existing_info = self._node_info(existing_pod.spec.node_name)
            if existing_info is None or existing_info.node is None:
                return
            existing_node = existing_info.node
            ex_affinity = existing_pod.spec.affinity
            ex_has_affinity = ex_affinity is not None and ex_affinity.pod_affinity is not None
            ex_has_anti = ex_affinity is not None and ex_affinity.pod_anti_affinity is not None
            if has_affinity:
                process_weighted_terms(affinity.pod_affinity.preferred, pod,
                                       existing_pod, existing_node, 1)
            if has_anti_affinity:
                process_weighted_terms(affinity.pod_anti_affinity.preferred, pod,
                                       existing_pod, existing_node, -1)
            if ex_has_affinity:
                if self.hard_pod_affinity_weight > 0:
                    for term in ex_affinity.pod_affinity.required:
                        process_term(term, existing_pod, pod, existing_node,
                                     self.hard_pod_affinity_weight)
                process_weighted_terms(ex_affinity.pod_affinity.preferred,
                                       existing_pod, pod, existing_node, 1)
            if ex_has_anti:
                process_weighted_terms(ex_affinity.pod_anti_affinity.preferred,
                                       existing_pod, pod, existing_node, -1)

        for node_info in node_info_map.values():
            if node_info.node is None:
                continue
            if has_affinity or has_anti_affinity:
                pods = node_info.pods
            else:
                pods = [p for p in node_info.pods if p.spec.affinity is not None]
            for existing_pod in pods:
                process_pod(existing_pod)

        max_count = max(max((counts[n.name] for n in nodes), default=0), 0)
        min_count = min(min((counts[n.name] for n in nodes), default=0), 0)

        result = []
        for node in nodes:
            score = 0
            if (max_count - min_count) > 0:
                # exact rational form of Go's float64 normalize
                # (DEVIATIONS.md #16); numerator is nonnegative, so floor
                # division equals Go's toward-zero int() conversion
                score = (MAX_PRIORITY * (counts[node.name] - min_count)
                         ) // (max_count - min_count)
            result.append(HostPriority(node.name, score))
        return result


# ---------------------------------------------------------------------------
# priority metadata (algorithm/priorities/metadata.go)
# ---------------------------------------------------------------------------


@dataclass
class PriorityMetadata:
    nonzero_request: Optional[Resource] = None
    pod_tolerations: Optional[list] = None
    affinity: Optional[object] = None
    pod_selectors: Optional[list] = None
    controller_ref: Optional[object] = None


def get_priority_metadata(pod: Pod, selector_spread: Optional[SelectorSpread] = None
                          ) -> PriorityMetadata:
    return PriorityMetadata(
        nonzero_request=get_nonzero_pod_request(pod),
        pod_tolerations=_tolerations_prefer_no_schedule(pod.spec.tolerations),
        affinity=pod.spec.affinity,
        pod_selectors=(selector_spread._get_selectors(pod)
                       if selector_spread is not None else None),
        controller_ref=pod.metadata.controller_ref(),
    )

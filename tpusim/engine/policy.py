"""Scheduler policy-as-data: the Policy schema, validation, and loaders.

Reference: vendor/k8s.io/kubernetes/pkg/scheduler/api/types.go:52-160 (Policy,
PredicatePolicy, PriorityPolicy, PredicateArgument, PriorityArgument,
ExtenderConfig, ExtenderManagedResource), api/validation/validation.go:34-67
(ValidatePolicy), and the two sourcing paths in pkg/scheduler/simulator.go:
372-424 — policy from a serialized file, or from a ConfigMap object under the
key "policy.cfg" (componentconfig.SchedulerPolicyConfigMapKey,
apis/componentconfig/types.go:41).

The JSON/YAML wire shape matches schedulerapi/v1 (kind: Policy,
apiVersion: v1) so existing kube-scheduler policy files load unchanged.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional

MAX_PRIORITY = 10  # api/types.go:36
MAX_INT = 2**63 - 1
MAX_WEIGHT = MAX_INT // MAX_PRIORITY  # api/types.go:38


class PolicyError(ValueError):
    """Invalid policy configuration (the Go side aggregates field errors)."""


# ---------------------------------------------------------------------------
# schema (api/types.go:52-160)
# ---------------------------------------------------------------------------


@dataclass
class ServiceAffinityArg:
    """api/types.go ServiceAffinity: node labels that must all match for a node
    to host pods of the same service group."""
    labels: List[str] = field(default_factory=list)


@dataclass
class LabelsPresenceArg:
    """api/types.go LabelsPresence: labels required present (or absent)."""
    labels: List[str] = field(default_factory=list)
    presence: bool = False


@dataclass
class ServiceAntiAffinityArg:
    """api/types.go ServiceAntiAffinity: the node label identifying groups."""
    label: str = ""


@dataclass
class LabelPreferenceArg:
    """api/types.go LabelPreference."""
    label: str = ""
    presence: bool = False


@dataclass
class PredicateArgument:
    """Only one member may be set (api/types.go:101-110)."""
    service_affinity: Optional[ServiceAffinityArg] = None
    labels_presence: Optional[LabelsPresenceArg] = None


@dataclass
class PriorityArgument:
    """Only one member may be set (api/types.go:112-121)."""
    service_anti_affinity: Optional[ServiceAntiAffinityArg] = None
    label_preference: Optional[LabelPreferenceArg] = None


@dataclass
class PredicatePolicy:
    name: str = ""
    argument: Optional[PredicateArgument] = None


@dataclass
class PriorityPolicy:
    name: str = ""
    weight: int = 0
    argument: Optional[PriorityArgument] = None


@dataclass
class ExtenderManagedResource:
    name: str = ""
    ignored_by_scheduler: bool = False


@dataclass
class ExtenderConfig:
    """api/types.go:164-205. TLS options are accepted but unused (the offline
    transport is in-process; a real HTTP transport honors url_prefix only)."""
    url_prefix: str = ""
    filter_verb: str = ""
    prioritize_verb: str = ""
    weight: int = 0
    bind_verb: str = ""
    enable_https: bool = False
    tls_config: Optional[dict] = None
    http_timeout: float = 0.0  # seconds; 0 → DefaultExtenderTimeout (5s)
    node_cache_capable: bool = False
    managed_resources: List[ExtenderManagedResource] = field(default_factory=list)


@dataclass
class Policy:
    """api/types.go:52-77. Semantics preserved exactly:
    predicates=None → provider defaults; predicates=[] → only mandatory
    predicates; priorities=None → provider defaults; priorities=[] → none."""
    predicates: Optional[List[PredicatePolicy]] = None
    priorities: Optional[List[PriorityPolicy]] = None
    extender_configs: List[ExtenderConfig] = field(default_factory=list)
    hard_pod_affinity_symmetric_weight: int = 0
    always_check_all_predicates: bool = False


# ---------------------------------------------------------------------------
# validation (api/validation/validation.go:34-67)
# ---------------------------------------------------------------------------


def validate_policy(policy: Policy) -> None:
    errors: List[str] = []
    for priority in policy.priorities or []:
        if priority.weight <= 0 or priority.weight >= MAX_WEIGHT:
            errors.append(
                f"Priority {priority.name} should have a positive weight "
                "applied to it or it has overflown")
    binders = 0
    seen_resources = set()
    for ext in policy.extender_configs:
        if ext.prioritize_verb and ext.weight <= 0:
            errors.append(f"Priority for extender {ext.url_prefix} should have "
                          "a positive weight applied to it")
        if ext.bind_verb:
            binders += 1
        for resource in ext.managed_resources:
            if "/" not in resource.name:
                errors.append(f"{resource.name} is an invalid extended resource name")
            if resource.name in seen_resources:
                errors.append("Duplicate extender managed resource name "
                              f"{resource.name}")
            seen_resources.add(resource.name)
    if binders > 1:
        errors.append(f"Only one extender can implement bind, found {binders}")
    if errors:
        raise PolicyError("; ".join(errors))


# ---------------------------------------------------------------------------
# decoding (schedulerapi/v1 JSON/YAML wire shape)
# ---------------------------------------------------------------------------


def _decode_predicate(o: dict) -> PredicatePolicy:
    arg = None
    a = o.get("argument")
    if a:
        sa, lp = a.get("serviceAffinity"), a.get("labelsPresence")
        arg = PredicateArgument(
            service_affinity=ServiceAffinityArg(labels=list(sa.get("labels") or []))
            if sa is not None else None,
            labels_presence=LabelsPresenceArg(
                labels=list(lp.get("labels") or []),
                presence=bool(lp.get("presence", False)))
            if lp is not None else None)
    return PredicatePolicy(name=o.get("name", ""), argument=arg)


def _decode_priority(o: dict) -> PriorityPolicy:
    arg = None
    a = o.get("argument")
    if a:
        saa, lp = a.get("serviceAntiAffinity"), a.get("labelPreference")
        arg = PriorityArgument(
            service_anti_affinity=ServiceAntiAffinityArg(label=saa.get("label", ""))
            if saa is not None else None,
            label_preference=LabelPreferenceArg(
                label=lp.get("label", ""),
                presence=bool(lp.get("presence", False)))
            if lp is not None else None)
    return PriorityPolicy(name=o.get("name", ""), weight=int(o.get("weight", 0)),
                          argument=arg)


def _decode_extender(o: dict) -> ExtenderConfig:
    managed = [ExtenderManagedResource(name=m.get("name", ""),
                                       ignored_by_scheduler=bool(
                                           m.get("ignoredByScheduler", False)))
               for m in o.get("managedResources") or []]
    # the Go type uses time.Duration (nanoseconds) in the internal type but
    # the v1 JSON carries it as nanoseconds too; accept seconds if small floats
    timeout = o.get("httpTimeout", 0) or 0
    if isinstance(timeout, (int, float)) and timeout > 1e6:
        timeout = timeout / 1e9  # nanoseconds → seconds
    return ExtenderConfig(
        url_prefix=o.get("urlPrefix", ""),
        filter_verb=o.get("filterVerb", ""),
        prioritize_verb=o.get("prioritizeVerb", ""),
        weight=int(o.get("weight", 0)),
        bind_verb=o.get("bindVerb", ""),
        enable_https=bool(o.get("enableHttps", False)),
        tls_config=o.get("tlsConfig"),
        http_timeout=float(timeout),
        node_cache_capable=bool(o.get("nodeCacheCapable", False)),
        managed_resources=managed)


def decode_policy(obj: dict) -> Policy:
    """Decode a schedulerapi/v1 Policy object (already parsed from JSON/YAML).

    Mirrors runtime.DecodeInto(latestschedulerapi.Codec, data, policy)
    (simulator.go:397-399): unknown kinds are rejected, absent lists keep
    their nil-vs-empty distinction.
    """
    kind = obj.get("kind", "Policy")
    if kind != "Policy":
        raise PolicyError(f"unexpected kind {kind!r}, expected \"Policy\"")
    preds = obj.get("predicates")
    pris = obj.get("priorities")
    # validation is owned by providers.create_from_config (the Go owner is
    # factory.CreateFromConfig); decode stays a pure structural transform
    return Policy(
        predicates=[_decode_predicate(p) for p in preds] if preds is not None else None,
        priorities=[_decode_priority(p) for p in pris] if pris is not None else None,
        extender_configs=[_decode_extender(e) for e in obj.get("extenders") or []],
        hard_pod_affinity_symmetric_weight=int(
            obj.get("hardPodAffinitySymmetricWeight", 0)),
        always_check_all_predicates=bool(obj.get("alwaysCheckAllPredicates", False)))


def _parse_document(data: str, what: str) -> dict:
    """JSON-then-YAML parse; any syntax failure or non-mapping document
    surfaces as PolicyError (the analog of runtime.DecodeInto's error)."""
    try:
        obj = json.loads(data)
    except json.JSONDecodeError:
        import yaml
        try:
            obj = yaml.safe_load(data)
        except yaml.YAMLError as exc:
            raise PolicyError(f"invalid policy: {what}: {exc}")
    if not isinstance(obj, dict):
        raise PolicyError(f"invalid policy document in {what}")
    return obj


def load_policy_file(path: str) -> Policy:
    """Policy from a serialized file (simulator.go:386-399). JSON or YAML."""
    with open(path) as f:
        data = f.read()
    return decode_policy(_parse_document(data, path))


SCHEDULER_POLICY_CONFIGMAP_KEY = "policy.cfg"  # componentconfig/types.go:41


def policy_from_configmap(configmap_obj) -> Policy:
    """Policy from a ConfigMap object's data["policy.cfg"] value
    (simulator.go:401-415). Takes the ConfigMap as a parsed dict — the
    offline build has no apiserver to Get() it from."""
    if not isinstance(configmap_obj, dict):
        raise PolicyError("config map document is not an object")
    data = (configmap_obj.get("data") or {})
    raw = data.get(SCHEDULER_POLICY_CONFIGMAP_KEY)
    if raw is None:
        raise PolicyError("missing policy config map value at key "
                          f'"{SCHEDULER_POLICY_CONFIGMAP_KEY}"')
    return decode_policy(_parse_document(raw, "config map"))


def load_policy_configmap_file(path: str) -> Policy:
    """Policy from a ConfigMap object saved to a file as JSON/YAML — the
    offline stand-in for reading the ConfigMap off the apiserver."""
    with open(path) as f:
        data = f.read()
    return policy_from_configmap(_parse_document(data, path))

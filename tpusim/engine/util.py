"""Scheduler utilities: pod priority helpers + per-pod exponential backoff.

Reference: util/utils.go (GetPodPriority, SortableList/HigherPriorityPod) and
util/backoff_utils.go (PodBackoff: 1s initial, 60s max, doubling).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from tpusim.api.types import Pod

DEFAULT_POD_PRIORITY = 0
MAX_INT32 = 2**31 - 1


def get_pod_priority(pod: Pod) -> int:
    """util.GetPodPriority: spec.priority or 0."""
    if pod.spec.priority is not None:
        return pod.spec.priority
    return DEFAULT_POD_PRIORITY


def sort_by_priority_desc(pods: list) -> list:
    """SortableList with HigherPriorityPod: highest priority first; stable."""
    return sorted(pods, key=lambda p: -get_pod_priority(p))


class BackoffEntry:
    def __init__(self):
        self.backoff = 1.0  # seconds (initial)
        self.last_update = 0.0


class PodBackoff:
    """Reference: backoff_utils.go:88-135 — exponential per-pod backoff with
    doubling up to max; entries garbage-collected by age."""

    def __init__(self, default_duration: float = 1.0, max_duration: float = 60.0,
                 clock=time.monotonic):
        self.default_duration = default_duration
        self.max_duration = max_duration
        self._clock = clock
        self._entries: Dict[str, BackoffEntry] = {}

    def get_entry(self, pod_id: str) -> BackoffEntry:
        """GetEntry also refreshes lastUpdate (backoff_utils.go:122-132)."""
        entry = self._entries.get(pod_id)
        if entry is None:
            entry = BackoffEntry()
            entry.backoff = self.default_duration
            self._entries[pod_id] = entry
        entry.last_update = self._clock()
        return entry

    def get_backoff_time(self, pod_id: str) -> float:
        """Current duration, then double it (getBackoff semantics)."""
        entry = self.get_entry(pod_id)
        duration = entry.backoff
        entry.backoff = min(duration * 2, self.max_duration)
        entry.last_update = self._clock()
        return duration

    def try_backoff_and_wait(self, pod_id: str) -> bool:
        """Non-sleeping variant used by the simulator: reports whether the pod
        is allowed to retry now (no real wall-clock waits in an offline sim).
        Reads the entry WITHOUT the GetEntry lastUpdate refresh — the elapsed
        time since the last recorded backoff is the whole question."""
        entry = self._entries.get(pod_id)
        now = self._clock()
        if entry is None:
            self.get_entry(pod_id)  # creates the entry (stamps lastUpdate)
            return True
        if now - entry.last_update >= entry.backoff:
            entry.last_update = now
            return True
        return False

    def gc(self, max_age: float = None) -> None:
        """backoff_utils.go Gc: entries idle longer than maxDuration drop."""
        if max_age is None:
            max_age = self.max_duration
        now = self._clock()
        stale = [k for k, e in self._entries.items()
                 if now - e.last_update > max_age]
        for k in stale:
            del self._entries[k]

    def clear_pod_backoff(self, pod_id: str) -> None:
        self._entries.pop(pod_id, None)

"""Scheduler extender: out-of-process Filter/Prioritize/Bind hooks.

Reference: core/extender.go:40-293 (HTTPExtender) + api/types.go:164-260
(ExtenderConfig, ExtenderArgs, ExtenderFilterResult, ExtenderBindingArgs).

The wire protocol is kept byte-compatible with the reference — POST
`{url_prefix}/{verb}` with an ExtenderArgs JSON body ({"pod": ..., "nodes":
{"items": [...]}} or {"nodeNames": [...]} when node_cache_capable) — so real
kube-scheduler extender webhooks work unchanged. Two transports:

  * http (default): urllib POST with the configured timeout
    (DefaultExtenderTimeout 5s, extender.go:37-38).
  * in-process: any callable `(verb, args_dict) -> result_dict` — the natural
    seam for tests and for co-located Python extenders (no socket needed; the
    reference's simulator configures no extenders at all, simulator.go:375).
"""

from __future__ import annotations

import json
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from tpusim.api.types import Node, Pod
from tpusim.engine.policy import ExtenderConfig
from tpusim.engine.priorities import HostPriority

DEFAULT_EXTENDER_TIMEOUT = 5.0  # seconds (extender.go:37-38)


class ExtenderError(Exception):
    pass


def http_transport(url_prefix: str, timeout: float) -> Callable[[str, dict], dict]:
    """POST JSON to {url_prefix}/{verb} (extender.go send():233-263)."""

    def send(verb: str, args: dict) -> dict:
        url = url_prefix.rstrip("/") + "/" + verb
        req = urllib.request.Request(
            url, data=json.dumps(args).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            if resp.status != 200:
                raise ExtenderError(
                    f"Failed {verb} with extender at URL {url_prefix}, "
                    f"code {resp.status}")
            return json.load(resp)

    return send


class HTTPExtender:
    """algorithm.SchedulerExtender implementation (core/extender.go:41-293)."""

    def __init__(self, config: ExtenderConfig,
                 transport: Optional[Callable[[str, dict], dict]] = None):
        self.extender_url = config.url_prefix
        self.filter_verb = config.filter_verb
        self.prioritize_verb = config.prioritize_verb
        self.bind_verb = config.bind_verb
        self.weight = config.weight
        self.node_cache_capable = config.node_cache_capable
        self.managed_resources = {r.name for r in config.managed_resources}
        timeout = config.http_timeout or DEFAULT_EXTENDER_TIMEOUT
        self._send = transport or http_transport(config.url_prefix, timeout)

    # --- args encoding (api/types.go ExtenderArgs:207-218) ---

    def _encode_args(self, pod: Pod, nodes: List[Node]) -> dict:
        if self.node_cache_capable:
            return {"pod": pod.to_obj(), "nodes": None,
                    "nodeNames": [n.name for n in nodes]}
        return {"pod": pod.to_obj(),
                "nodes": {"items": [n.to_obj() for n in nodes]},
                "nodeNames": None}

    # --- Filter (extender.go:105-163) ---

    def filter(self, pod: Pod, nodes: List[Node], node_info_map: dict
               ) -> Tuple[List[Node], Dict[str, str]]:
        """Returns (filtered subset, failed node → message). Raises on
        transport error or a result carrying Error — filter failures fail the
        pod's scheduling (generic_scheduler.go:360-363)."""
        if not self.filter_verb:
            return nodes, {}
        result = self._send(self.filter_verb, self._encode_args(pod, nodes))
        if result.get("error"):
            raise ExtenderError(result["error"])
        if self.node_cache_capable and result.get("nodeNames") is not None:
            node_result = [node_info_map[name].node
                           for name in result["nodeNames"]]
        elif result.get("nodes") is not None:
            by_name = {n.name: n for n in nodes}
            node_result = [by_name[item["metadata"]["name"]]
                           for item in result["nodes"].get("items", [])]
        else:
            node_result = []
        return node_result, dict(result.get("failedNodes") or {})

    # --- Prioritize (extender.go:165-209) ---

    def prioritize(self, pod: Pod, nodes: List[Node]
                   ) -> Tuple[List[HostPriority], int]:
        if not self.prioritize_verb:
            return [HostPriority(n.name, 0) for n in nodes], 0
        result = self._send(self.prioritize_verb, self._encode_args(pod, nodes))
        return [HostPriority(hp["host"], int(hp["score"])) for hp in result], \
            self.weight

    # --- Bind (extender.go:211-231) ---

    def bind(self, pod: Pod, node_name: str) -> None:
        if not self.is_binder():
            raise ExtenderError("Unexpected empty bindVerb in extender")
        args = {"podName": pod.name, "podNamespace": pod.namespace,
                "podUID": pod.metadata.uid, "node": node_name}
        result = self._send(self.bind_verb, args)
        if result and result.get("error"):
            raise ExtenderError(result["error"])

    def is_binder(self) -> bool:
        return bool(self.bind_verb)

    # --- IsInterested (extender.go:265-293) ---

    def is_interested(self, pod: Pod) -> bool:
        if not self.managed_resources:
            return True
        for container in list(pod.spec.containers) + list(pod.spec.init_containers):
            for name in list(container.requests) + list(container.limits):
                if name in self.managed_resources:
                    return True
        return False


def new_http_extender(config: ExtenderConfig,
                      transport: Optional[Callable] = None) -> HTTPExtender:
    """core/extender.go NewHTTPExtender:76-104."""
    return HTTPExtender(config, transport=transport)

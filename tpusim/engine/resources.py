"""Resource accounting: Resource, HostPortInfo, NodeInfo.

Reference: schedulercache/node_info.go (NodeInfo + Resource + incremental
AddPod/RemovePod accounting), util/utils.go (HostPortInfo),
algorithm/priorities/util/non_zero.go (non-zero request defaults).
"""

from __future__ import annotations

import itertools

from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpusim.api.quantity import parse_quantity
from tpusim.api.types import (
    RESOURCE_CPU,
    RESOURCE_EPHEMERAL_STORAGE,
    RESOURCE_MEMORY,
    RESOURCE_NVIDIA_GPU,
    RESOURCE_PODS,
    Node,
    Pod,
    is_scalar_resource_name,
)

# non_zero.go:31-34 — defaults applied for priority computation only
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024


@dataclass
class Resource:
    """Reference: node_info.go:66-76."""

    milli_cpu: int = 0
    memory: int = 0
    nvidia_gpu: int = 0
    ephemeral_storage: int = 0
    allowed_pod_number: int = 0
    scalar: Dict[str, int] = field(default_factory=dict)

    def add_resource_list(self, rl: dict) -> None:
        """Reference: node_info.go Resource.Add — accumulate a v1.ResourceList."""
        for name, q in rl.items():
            if name == RESOURCE_CPU:
                self.milli_cpu += q.milli_value()
            elif name == RESOURCE_MEMORY:
                self.memory += q.value()
            elif name == RESOURCE_NVIDIA_GPU:
                self.nvidia_gpu += q.value()
            elif name == RESOURCE_EPHEMERAL_STORAGE:
                self.ephemeral_storage += q.value()
            elif name == RESOURCE_PODS:
                self.allowed_pod_number += q.value()
            elif is_scalar_resource_name(name):
                self.scalar[name] = self.scalar.get(name, 0) + q.value()

    def add(self, other: "Resource") -> None:
        self.milli_cpu += other.milli_cpu
        self.memory += other.memory
        self.nvidia_gpu += other.nvidia_gpu
        self.ephemeral_storage += other.ephemeral_storage
        for k, v in other.scalar.items():
            self.scalar[k] = self.scalar.get(k, 0) + v

    def subtract(self, other: "Resource") -> None:
        self.milli_cpu -= other.milli_cpu
        self.memory -= other.memory
        self.nvidia_gpu -= other.nvidia_gpu
        self.ephemeral_storage -= other.ephemeral_storage
        for k, v in other.scalar.items():
            self.scalar[k] = self.scalar.get(k, 0) - v

    def clone(self) -> "Resource":
        return Resource(self.milli_cpu, self.memory, self.nvidia_gpu,
                        self.ephemeral_storage, self.allowed_pod_number,
                        dict(self.scalar))


# Scoped request memo: preemption's victim selection recomputes the same
# pods' requests hundreds of times (clone/strip/reprieve per candidate node).
# When a scope is active, results are cached by object identity — the pod
# reference is held alongside so a recycled id() can never alias — and MUST be
# treated as immutable by callers (the one historical mutator,
# priorities._resource_allocation_map, clones its copy).
_REQ_MEMO: Optional[dict] = None
_NZ_MEMO: Optional[dict] = None
_PORTS_MEMO: Optional[dict] = None


@contextmanager
def request_memo():
    global _REQ_MEMO, _NZ_MEMO, _PORTS_MEMO
    prev = (_REQ_MEMO, _NZ_MEMO, _PORTS_MEMO)
    _REQ_MEMO, _NZ_MEMO, _PORTS_MEMO = {}, {}, {}
    try:
        yield
    finally:
        _REQ_MEMO, _NZ_MEMO, _PORTS_MEMO = prev


def get_resource_request(pod: Pod) -> Resource:
    """Reference: predicates.go:659-697 — sum containers, then per-resource max
    with each init container."""
    memo = _REQ_MEMO
    if memo is not None:
        hit = memo.get(id(pod))
        if hit is not None:
            return hit[1]
    result = Resource()
    for c in pod.spec.containers:
        result.add_resource_list(c.requests)
    for c in pod.spec.init_containers:
        for name, q in c.requests.items():
            if name == RESOURCE_MEMORY:
                result.memory = max(result.memory, q.value())
            elif name == RESOURCE_EPHEMERAL_STORAGE:
                result.ephemeral_storage = max(result.ephemeral_storage, q.value())
            elif name == RESOURCE_CPU:
                result.milli_cpu = max(result.milli_cpu, q.milli_value())
            elif name == RESOURCE_NVIDIA_GPU:
                result.nvidia_gpu = max(result.nvidia_gpu, q.value())
            elif is_scalar_resource_name(name):
                result.scalar[name] = max(result.scalar.get(name, 0), q.value())
    if memo is not None:
        memo[id(pod)] = (pod, result)
    return result


def get_nonzero_requests(requests: dict) -> tuple[int, int]:
    """Reference: non_zero.go:36-54 — default unset (not explicit-zero) cpu/mem."""
    if RESOURCE_CPU in requests:
        cpu = requests[RESOURCE_CPU].milli_value()
    else:
        cpu = DEFAULT_MILLI_CPU_REQUEST
    if RESOURCE_MEMORY in requests:
        mem = requests[RESOURCE_MEMORY].value()
    else:
        mem = DEFAULT_MEMORY_REQUEST
    return cpu, mem


def get_nonzero_pod_request(pod: Pod) -> Resource:
    """Reference: resource_allocation.go:75-84 (getNonZeroRequests): containers
    only, no init-container max."""
    memo = _NZ_MEMO
    if memo is not None:
        hit = memo.get(id(pod))
        if hit is not None:
            return hit[1]
    result = Resource()
    for c in pod.spec.containers:
        cpu, mem = get_nonzero_requests(c.requests)
        result.milli_cpu += cpu
        result.memory += mem
    if memo is not None:
        memo[id(pod)] = (pod, result)
    return result


def is_pod_best_effort(pod: Pod) -> bool:
    """v1qos.GetPodQOS(pod) == BestEffort: no container has cpu/memory in
    requests or limits (the supported QoS compute resources)."""
    for c in pod.spec.containers:
        for rl in (c.requests, c.limits):
            for name in rl:
                if name in (RESOURCE_CPU, RESOURCE_MEMORY):
                    return False
    return True


def get_container_ports(pod: Pod) -> list:
    """Reference: util/utils.go GetContainerPorts — every containerPort entry of
    the pod's (non-init) containers."""
    memo = _PORTS_MEMO
    if memo is not None:
        hit = memo.get(id(pod))
        if hit is not None:
            return hit[1]
    ports = []
    for c in pod.spec.containers:
        ports.extend(c.ports)
    if memo is not None:
        memo[id(pod)] = (pod, ports)
    return ports


DEFAULT_BIND_ALL_HOST_IP = "0.0.0.0"


class HostPortInfo:
    """Reference: util/utils.go:51-137 — (ip, protocol, port) occupancy with
    0.0.0.0 wildcard semantics."""

    def __init__(self):
        self._by_ip: Dict[str, set] = {}

    @staticmethod
    def _sanitize(ip: str, protocol: str) -> tuple[str, str]:
        return (ip or DEFAULT_BIND_ALL_HOST_IP, protocol or "TCP")

    def add(self, ip: str, protocol: str, port: int) -> None:
        if port <= 0:
            return
        ip, protocol = self._sanitize(ip, protocol)
        self._by_ip.setdefault(ip, set()).add((protocol, port))

    def remove(self, ip: str, protocol: str, port: int) -> None:
        if port <= 0:
            return
        ip, protocol = self._sanitize(ip, protocol)
        s = self._by_ip.get(ip)
        if s is not None:
            s.discard((protocol, port))
            if not s:
                del self._by_ip[ip]

    def check_conflict(self, ip: str, protocol: str, port: int) -> bool:
        if port <= 0:
            return False
        ip, protocol = self._sanitize(ip, protocol)
        pp = (protocol, port)
        if ip == DEFAULT_BIND_ALL_HOST_IP:
            return any(pp in s for s in self._by_ip.values())
        for key in (DEFAULT_BIND_ALL_HOST_IP, ip):
            if pp in self._by_ip.get(key, ()):
                return True
        return False

    def __len__(self) -> int:
        return sum(len(s) for s in self._by_ip.values())

    def clone(self) -> "HostPortInfo":
        h = HostPortInfo()
        h._by_ip = {k: set(v) for k, v in self._by_ip.items()}
        return h


_generation_counter = itertools.count(1)


def _next_generation() -> int:
    """Globally monotonic NodeInfo generation. A shared counter (instead of
    per-instance increments) makes generations unique across instances, so a
    mutated snapshot clone can never collide with the live cache entry in
    SchedulerCache.update_node_name_to_info_map's equality check."""
    return next(_generation_counter)


class NodeInfo:
    """Aggregated per-node scheduling state.

    Reference: node_info.go:35-63 (struct) / :318-398 (AddPod/RemovePod) /
    :400-448 (calculateResource, SetNode condition caching).
    """

    def __init__(self, *pods: Pod):
        self.node: Optional[Node] = None
        self.pods: List[Pod] = []
        self.requested_resource = Resource()
        self.nonzero_request = Resource()
        self.allocatable_resource = Resource()
        self.used_ports = HostPortInfo()
        self.taints: list = []
        self.memory_pressure = False
        self.disk_pressure = False
        self.generation = 0
        for p in pods:
            self.add_pod(p)

    # --- lifecycle ---

    def set_node(self, node: Node) -> None:
        self.node = node
        self.allocatable_resource = Resource()
        self.allocatable_resource.add_resource_list(node.status.allocatable)
        self.taints = list(node.spec.taints)
        self.memory_pressure = any(
            c.type == "MemoryPressure" and c.status == "True" for c in node.status.conditions)
        self.disk_pressure = any(
            c.type == "DiskPressure" and c.status == "True" for c in node.status.conditions)
        self.generation = _next_generation()

    def remove_node(self) -> None:
        self.node = None
        self.allocatable_resource = Resource()
        self.taints = []
        self.memory_pressure = False
        self.disk_pressure = False
        self.generation = _next_generation()

    def add_pod(self, pod: Pod) -> None:
        res = get_resource_request(pod)
        self.requested_resource.add(res)
        non0 = get_nonzero_pod_request(pod)
        self.nonzero_request.milli_cpu += non0.milli_cpu
        self.nonzero_request.memory += non0.memory
        self.pods.append(pod)
        for port in get_container_ports(pod):
            self.used_ports.add(port.host_ip, port.protocol, port.host_port)
        self.generation = _next_generation()

    def remove_pod(self, pod: Pod) -> None:
        # identity-first scan: callers (victim selection, cache accounting)
        # overwhelmingly pass the exact object held in self.pods, and the
        # key() fallback builds two strings per compared entry — measurably
        # hot at preemption's ~15 removals per candidate node
        for i, p in enumerate(self.pods):
            if p is pod:
                del self.pods[i]
                break
        else:
            key = pod.key()
            for i, p in enumerate(self.pods):
                if p.key() == key:
                    del self.pods[i]
                    break
            else:
                raise KeyError(f"no corresponding pod {key} in pods of node")
        res = get_resource_request(pod)
        self.requested_resource.subtract(res)
        non0 = get_nonzero_pod_request(pod)
        self.nonzero_request.milli_cpu -= non0.milli_cpu
        self.nonzero_request.memory -= non0.memory
        for port in get_container_ports(pod):
            self.used_ports.remove(port.host_ip, port.protocol, port.host_port)
        self.generation = _next_generation()

    # --- views ---

    def allowed_pod_number(self) -> int:
        return self.allocatable_resource.allowed_pod_number

    def memory_pressure_condition(self) -> bool:
        return self.memory_pressure

    def disk_pressure_condition(self) -> bool:
        return self.disk_pressure

    def clone(self) -> "NodeInfo":
        c = NodeInfo()
        c.node = self.node
        c.pods = list(self.pods)
        c.requested_resource = self.requested_resource.clone()
        c.nonzero_request = self.nonzero_request.clone()
        c.allocatable_resource = self.allocatable_resource.clone()
        c.used_ports = self.used_ports.clone()
        c.taints = list(self.taints)
        c.memory_pressure = self.memory_pressure
        c.disk_pressure = self.disk_pressure
        c.generation = self.generation
        return c

    def clone_without(self, excluded: List[Pod]) -> "NodeInfo":
        """Equivalent to clone() followed by remove_pod() for each of
        `excluded` (identity-matched members of self.pods), but built by
        re-accumulating the SURVIVORS: victim selection strips most of a
        node's pods, so rebuilding from the few kept ones is cheaper than
        paying per-removal accounting. Integer adds make the rebuilt
        aggregates bit-identical to subtract-per-removal."""
        c = NodeInfo()
        c.node = self.node
        excluded_ids = {id(p) for p in excluded}
        c.pods = [p for p in self.pods if id(p) not in excluded_ids]
        c.allocatable_resource = self.allocatable_resource.clone()
        c.taints = list(self.taints)
        c.memory_pressure = self.memory_pressure
        c.disk_pressure = self.disk_pressure
        for p in c.pods:
            c.requested_resource.add(get_resource_request(p))
            non0 = get_nonzero_pod_request(p)
            c.nonzero_request.milli_cpu += non0.milli_cpu
            c.nonzero_request.memory += non0.memory
            for port in get_container_ports(p):
                c.used_ports.add(port.host_ip, port.protocol, port.host_port)
        c.generation = _next_generation()
        return c


def new_node_info_map(nodes: List[Node], pods: List[Pod]) -> Dict[str, NodeInfo]:
    """Build name->NodeInfo from a snapshot (CreateNodeNameToInfoMap parity):
    pods with spec.nodeName are accounted to their node."""
    infos: Dict[str, NodeInfo] = {}
    for pod in pods:
        name = pod.spec.node_name
        if not name:
            continue
        infos.setdefault(name, NodeInfo()).add_pod(pod)
    for node in nodes:
        info = infos.setdefault(node.name, NodeInfo())
        info.set_node(node)
    return infos

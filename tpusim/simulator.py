"""ClusterCapacity: the simulation orchestrator.

Reference: pkg/scheduler/simulator.go. The control-flow inversion documented in
SURVEY.md §1 is preserved in-process and synchronously: pods are pushed into
the store, store events drive the scheduler, and the engine calls back up
through the two injected seams — Bind (GetBinder) and Update
(PodConditionUpdater) (simulator.go:247-255) — so placements mutate only the
in-memory store. The LIFO pod feed (store.go:223-233) and stop-reason strings
are reproduced exactly.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from time import perf_counter
from typing import Callable, Dict, List, Optional

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import Node, Pod, PodCondition, ResourceType
from tpusim.engine.cache import CacheError, SchedulerCache
from tpusim.engine.equivalence import EquivalenceCache
from tpusim.engine.generic_scheduler import FitError, GenericScheduler, SchedulingError
from tpusim.engine.queue import new_scheduling_queue
from tpusim.engine.util import PodBackoff
from tpusim.engine.policy import Policy
from tpusim.engine.providers import (
    DEFAULT_PROVIDER,
    PluginFactoryArgs,
    apply_feature_gates,
    create_from_config,
    create_from_provider,
    default_registry,
)
from tpusim.engine.resources import NodeInfo
from tpusim.framework.events import Recorder
from tpusim.framework.metrics import register as register_metrics, since_in_microseconds
from tpusim.framework.report import GeneralReview, Status, get_report
from tpusim.framework.store import ADDED, DELETED, MODIFIED, PodQueue, ResourceStore
from tpusim.framework.strategy import PredictiveStrategy
from tpusim.obs import recorder as flight
from tpusim.obs import tracectx

DEFAULT_SCHEDULER_NAME = "TD-Scheduler"  # options.go:49


@dataclass
class SchedulerServerConfig:
    """The slice of componentconfig.KubeSchedulerConfiguration the simulator
    reads (options.go:47-61), plus the two feature gates the engine consults:
    PodPriority (preemption; off by default like the reference's 1.10 gates,
    scheduler.go:210-213) and EnableEquivalenceClassCache (simulator.go:369)."""

    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    algorithm_provider: str = DEFAULT_PROVIDER
    # AlgorithmSource.Policy analog (simulator.go:383-424): when set, the
    # scheduler is built from the policy instead of the named provider
    policy: Optional[Policy] = None
    extender_transport: Optional[object] = None  # in-process extender seam
    hard_pod_affinity_symmetric_weight: int = 10
    enable_pod_priority: bool = False
    enable_equivalence_cache: bool = False
    # VolumeScheduling feature gate (scheduler.go:175; off in the reference's
    # 1.10 defaults): enables CheckVolumeBinding + delayed-binding semantics
    enable_volume_scheduling: bool = False
    # registry-surgery gates (ApplyFeatureGates, defaults.go:181-205):
    # TaintNodesByCondition / ResourceLimitsPriorityFunction — both default
    # off in this k8s vintage; applied before provider/policy assembly
    feature_gates: Optional[Dict[str, bool]] = None


class ClusterCapacity:
    """Reference: simulator.go:63-342."""

    def __init__(self, config: SchedulerServerConfig, new_pods: List[Pod],
                 scheduled_pods: List[Pod], nodes: List[Node],
                 services: Optional[list] = None,
                 pvs: Optional[list] = None, pvcs: Optional[list] = None,
                 storage_classes: Optional[list] = None,
                 chaos: Optional[object] = None,
                 backoff_clock: Optional[Callable[[], float]] = None):
        self.config = config
        self.status = Status()
        self.report: Optional[GeneralReview] = None
        self.closed = False
        # chaos engine (tpusim.chaos.ChaosEngine): fires scripted churn at
        # pod-attempt boundaries and audits end-state invariants; its
        # deterministic clock drives the backoff below so retry order is a
        # pure function of the fault plan
        self.chaos = chaos.attach(self) if chaos is not None else None

        # --- store + queue + strategy + recorder (simulator.go:286-342) ---
        self.resource_store = ResourceStore()
        self.strategy = PredictiveStrategy(self.resource_store)
        self.pod_queue = PodQueue(new_pods)
        self.recorder = Recorder(10)

        # --- the scheduler cache, maintained by store event handlers exactly
        # like factory.go's informer handlers (factory.go:139-299); carries
        # the assumed-pod lifecycle + generation-based snapshots
        # (schedulercache/cache.go, engine/cache.py) ---
        self.cache = SchedulerCache()
        self._cached_node_infos: Dict[str, NodeInfo] = {}
        self.resource_store.register_event_handler(ResourceType.PODS, self._on_pod_event)
        self.resource_store.register_event_handler(ResourceType.NODES, self._on_node_event)

        # --- seed cluster state (simulator.go:315-322) ---
        for node in nodes:
            self.resource_store.add(ResourceType.NODES, node)
        for pod in scheduled_pods:
            self.resource_store.add(ResourceType.PODS, pod)
            self.status.scheduled_pods.append(pod)
        for svc in services or []:
            self.resource_store.add(ResourceType.SERVICES, svc)
        for pv in pvs or []:
            self.resource_store.add(ResourceType.PERSISTENT_VOLUMES, pv)
        for pvc in pvcs or []:
            self.resource_store.add(ResourceType.PERSISTENT_VOLUME_CLAIMS, pvc)
        self.nodes = nodes

        # --- volume binder over the seeded PV/PVC/StorageClass state
        # (simulator SchedulerConfigLocal wires PV/PVC informers,
        # simulator.go:355-366; the binder itself is factory.go:252-259) ---
        from tpusim.engine.volume import VolumeBinder

        self.volume_binder = VolumeBinder(
            self.resource_store.list(ResourceType.PERSISTENT_VOLUMES),
            self.resource_store.list(ResourceType.PERSISTENT_VOLUME_CLAIMS),
            storage_classes or [],
            enabled=config.enable_volume_scheduling)

        # --- build the engine with store-backed listers (SchedulerConfigLocal,
        # simulator.go:345-428: fake empty RC/RS/StatefulSet listers, simulated
        # pod/node/service listers) ---
        args = PluginFactoryArgs(
            # the plugin pod lister is the SCHEDULER CACHE, not the store
            # (factory.go:166 podLister: schedulerCache): assigned pods only,
            # in cache insertion order (seed order then bind order) — the
            # deterministic stand-in for Go's random map iteration
            pod_lister=lambda: [state.pod for state
                                in self.cache.pod_states.values()],
            service_lister=lambda: self.resource_store.list(ResourceType.SERVICES),
            node_info_getter=lambda name: self.node_info_map.get(name),
            pvc_getter=self.volume_binder.get_pvc,
            pv_getter=self.volume_binder.get_pv,
            storage_class_getter=self.volume_binder.get_class,
            volume_binder=self.volume_binder,
            volume_scheduling_enabled=config.enable_volume_scheduling,
            hard_pod_affinity_symmetric_weight=config.hard_pod_affinity_symmetric_weight,
        )
        # ServiceAffinity predicates (policy-registered, arbitrary names)
        # judge OTHER nodes by where service pods sit, so any pod add/delete
        # invalidates them on ALL nodes (factory.go's onPodAdd/Delete
        # invalidation set includes CheckServiceAffinity)
        self._service_affinity_pred_names = [
            pp.name for pp in (config.policy.predicates or [])
            if pp.argument is not None
            and pp.argument.service_affinity is not None
        ] if config.policy is not None else []
        self.scheduling_queue = new_scheduling_queue(config.enable_pod_priority)
        # MakeDefaultErrorFunc's backoff state; the clock is injectable
        # (chaos > explicit > wall monotonic) so backoff expiry is testable
        # and chaos replays are byte-stable
        if self.chaos is not None:
            backoff_clock = self.chaos.clock
        self.pod_backoff = (PodBackoff(clock=backoff_clock)
                            if backoff_clock is not None else PodBackoff())
        registry = None
        if config.feature_gates:
            # ApplyFeatureGates runs before provider/policy assembly, like
            # the scheduler app (defaults.go:181-205)
            registry = default_registry()
            apply_feature_gates(registry, config.feature_gates)
        if config.policy is not None:
            # AlgorithmSource.Policy path (simulator.go:383-424 →
            # factory.go CreateFromConfig)
            self.scheduler: GenericScheduler = create_from_config(
                config.policy, args, registry=registry,
                extender_transport=config.extender_transport)
        else:
            self.scheduler = create_from_provider(
                config.algorithm_provider, args, registry=registry)
        self.scheduler.scheduling_queue = self.scheduling_queue
        if config.enable_equivalence_cache:
            self.scheduler.equivalence_cache = EquivalenceCache(
                pvc_getter=self.volume_binder.get_pvc)
        # PDBs come from the fake informer in the reference (empty,
        # simulator.go:352-366) but can be injected for preemption studies
        self.pdbs: list = []
        self.scheduler.pdb_lister = lambda: list(self.pdbs)
        self.metrics = register_metrics()

    # --- cache event handlers ---

    @property
    def node_info_map(self) -> Dict[str, NodeInfo]:
        """The cache's live per-node view (schedulerCache.nodes)."""
        return self.cache.nodes

    def refresh_node_info_snapshot(self) -> Dict[str, NodeInfo]:
        """Expire overdue assumed pods, then refresh the generation-checked
        snapshot the algorithm runs against (generic_scheduler.go:129 →
        cache.go UpdateNodeNameToInfoMap:83-97)."""
        self.cache.cleanup_assumed_pods()
        return self.cache.update_node_name_to_info_map(self._cached_node_infos)

    def _on_pod_event(self, event: str, pod: Pod) -> None:
        if event in (ADDED, MODIFIED) and pod.spec.node_name:
            # a bound pod confirms its assumed entry; re-delivered Modified
            # events for an already-confirmed pod are ignored by the cache
            if self.cache.is_assumed_pod(pod) \
                    or pod.key() not in self.cache.pod_states:
                self.cache.add_pod(pod)
                self._invalidate_ecache_for_node(pod.spec.node_name)
            # factory.go:607-615 wires assigned-pod informer events to the
            # queue's affinity-triggered moves: a bound pod may make parked
            # pods with matching required pod-affinity terms schedulable
            queue = getattr(self, "scheduling_queue", None)
            if queue is not None:
                if event == ADDED:
                    queue.assigned_pod_added(pod)
                else:
                    queue.assigned_pod_updated(pod)
        elif event == DELETED and pod.key() in self.cache.pod_states:
            self.cache.remove_pod(pod)
            self._invalidate_ecache_for_node(pod.spec.node_name)
            # factory.go:624-631: a deleted pod may free anti-affinity or
            # resources anywhere — move everything back to active
            queue = getattr(self, "scheduling_queue", None)
            if queue is not None:
                queue.move_all_to_active_queue()

    def _invalidate_ecache_for_node(self, node_name: str) -> None:
        """The factory event handlers invalidate cached predicate results when
        a node's pod set changes (factory.go:596-631 + ecache hooks); the
        conservative whole-node invalidation keeps the cache correct. A
        ServiceAffinity verdict on EVERY node can change when a service pod
        binds or leaves anywhere, so those predicate keys invalidate
        cluster-wide (factory.go's CheckServiceAffinity invalidation)."""
        # handlers also fire during __init__ seeding, before the engine exists
        scheduler = getattr(self, "scheduler", None)
        if scheduler is not None and scheduler.equivalence_cache is not None:
            scheduler.equivalence_cache.invalidate_all_on_node(node_name)
            if self._service_affinity_pred_names:
                scheduler.equivalence_cache \
                    .invalidate_cached_predicate_item_of_all_nodes(
                        self._service_affinity_pred_names)

    def _on_node_event(self, event: str, node: Node) -> None:
        if event == DELETED:
            self.cache.remove_node(node)
        else:
            self.cache.add_node(node)
        self._invalidate_ecache_for_node(node.name)

    # --- the two seams (simulator.go:108-185) ---

    def bind(self, pod: Pod, node_name: str) -> None:
        """SEAM 1 — Bind intercept (simulator.go:108-145)."""
        stored, exists = self.resource_store.get(ResourceType.PODS, pod.key())
        if not exists:
            raise SchedulingError(f"Unable to bind, pod {pod.key()} not found")
        if self.chaos is not None and node_name in self.chaos.deleted_nodes:
            # churn fires only at attempt boundaries, so the algorithm can
            # never legitimately pick a node deleted before its snapshot:
            # reaching here means stale state leaked through a seam
            self.chaos.record_violation(
                f"bind of {pod.key()} to deleted node {node_name}")
        updated = stored.copy()
        updated.spec.node_name = node_name
        updated.status.phase = "Running"
        if self.chaos is not None:
            # a chaos retry can bind a pod that already parked as
            # unschedulable in an earlier attempt; the terminal buckets
            # must stay disjoint (the reference never retries, so only
            # the chaos arm can hit this)
            self.status.failed_pods = [
                p for p in self.status.failed_pods if p.key() != pod.key()]
        self.strategy.add(updated)  # -> store.update -> Modified -> cache AddPod
        self.scheduling_queue.delete(updated)
        self.pod_backoff.clear_pod_backoff(updated.key())
        self.status.successful_pods.append(updated)
        self.recorder.eventf(updated, "Normal", "Scheduled",
                             "Successfully assigned %s to %s", pod.name, node_name)
        self.recorder.drain_one()  # simulator.go:130-132

    def update(self, pod: Pod, condition: PodCondition) -> None:
        """SEAM 2 — unschedulable intercept (simulator.go:163-185)."""
        stop = (condition.type == "PodScheduled" and condition.status == "False"
                and condition.reason == "Unschedulable")
        if stop:
            pod.status.phase = "Pending"
            pod.status.conditions.append(condition)
            pod.status.reason = condition.reason
            # MakeDefaultErrorFunc (factory.go:1259-1341): record backoff and
            # park the pod in the unschedulable queue — its nominated-node
            # state stays visible to later pods' feasibility double-pass
            self.pod_backoff.get_backoff_time(pod.key())
            self.scheduling_queue.add_unschedulable_if_not_present(pod)
            self.status.failed_pods.append(pod)
            self.recorder.eventf(pod, "Warning", "FailedScheduling", condition.message)
            self.recorder.drain_one()

    # --- the loop (simulator.go:187-223 + scheduler.go:431-497) ---

    def _next_pod(self) -> Optional[Pod]:
        pod = self.pod_queue.pop()
        if pod is None:
            return None
        # scheduling_queue.Pop's receivedMoveRequest reset marks the start of
        # a scheduling cycle (scheduling_queue.go:295-312); the simulator
        # feeds from the LIFO pod queue instead of popping the scheduling
        # queue, so the reset is mirrored here — a move request then flips
        # parking to re-activation only when it arrived while THIS pod was
        # in flight (e.g. a preemption's victim deletions), like upstream
        if hasattr(self.scheduling_queue, "received_move_request"):
            self.scheduling_queue.received_move_request = False
        self.resource_store.add(ResourceType.PODS, pod)
        return pod

    def _schedule_one(self, pod: Pod, preempt_budget: int = 1) -> str:
        """Returns 'bound' or 'failed' — the seam whose deferred nextPod sets
        the stop-reason string when the queue drains (simulator.go:136, :171).

        With the PodPriority gate on, a FitError triggers the preemption
        pipeline (scheduler.go:449-455): victims are deleted from the store
        (mutating the cache through the DELETED event) and the pod retries —
        synchronously here, since the one-pod-in-flight feed would pop it right
        back anyway. Deviation from the reference, documented: the transient
        Unschedulable condition the Go scheduler sets before a successful
        preemption is not recorded in FailedPods."""
        sp = flight.span("pod_attempt")
        if not sp:
            return self._schedule_one_inner(pod, preempt_budget)
        sp.set("pod", pod.key())
        sp.set("preempt_budget", preempt_budget)
        try:
            outcome = self._schedule_one_inner(pod, preempt_budget)
            sp.set("outcome", outcome)
            return outcome
        finally:
            sp.end()

    def _schedule_one_inner(self, pod: Pod, preempt_budget: int) -> str:
        metrics = self.metrics
        e2e_start = algo_start = perf_counter()
        # the algorithm runs against the cache's generation-checked snapshot,
        # not the live view (generic_scheduler.go:129)
        node_infos = self.refresh_node_info_snapshot()
        try:
            with flight.span("schedule"):
                host = self.scheduler.schedule(pod, self.nodes, node_infos)
            metrics.scheduling_algorithm_latency.observe(
                since_in_microseconds(algo_start))
        except FitError as fit_err:
            if self.config.enable_pod_priority and preempt_budget > 0:
                node, _victims = self.attempt_preemption(pod, fit_err)
                if node is not None:
                    return self._schedule_one(pod, preempt_budget - 1)
            # scheduler.go:190-201 error arm -> PodConditionUpdater.Update
            self.update(pod, PodCondition(type="PodScheduled", status="False",
                                          reason="Unschedulable",
                                          message=fit_err.error()))
            return "failed"
        except SchedulingError as sched_err:
            self.update(pod, PodCondition(type="PodScheduled", status="False",
                                          reason="Unschedulable",
                                          message=str(sched_err)))
            return "failed"
        # assumeAndBindVolumes (scheduler.go:367-398): with the gate on, the
        # matched PVs are consumed before the pod binds
        if self.config.enable_volume_scheduling:
            self.volume_binder.assume_pod_volumes(pod, host)
            if self.scheduler.equivalence_cache is not None:
                # PV claimRef changes invalidate volume predicates everywhere,
                # like the factory's PV/PVC event hooks (factory.go
                # invalidatePredicatesForPv/Pvc)
                from tpusim.engine import predicates as preds

                self.scheduler.equivalence_cache \
                    .invalidate_cached_predicate_item_of_all_nodes([
                        preds.MAX_EBS_VOLUME_COUNT_PRED,
                        preds.MAX_GCE_PD_VOLUME_COUNT_PRED,
                        preds.MAX_AZURE_DISK_VOLUME_COUNT_PRED,
                        preds.NO_VOLUME_ZONE_CONFLICT_PRED,
                        preds.CHECK_VOLUME_BINDING_PRED,
                    ])
        # assume (scheduler.go:366-398 → cache.AssumePod): later pods see the
        # placement immediately; the synchronous Bind's store event confirms it
        assumed = pod.copy()
        assumed.spec.node_name = host
        try:
            with flight.span("assume"):
                self.cache.assume_pod(assumed)
        except CacheError as cache_err:
            # assume error arm (scheduler.go:377-380 → config.Error): the pod
            # is reported failed, the run continues — e.g. a fed pod whose
            # namespace/name collides with an already-cached pod
            self.update(pod, PodCondition(type="PodScheduled", status="False",
                                          reason="Unschedulable",
                                          message=str(cache_err)))
            return "failed"
        # binding latency + e2e (scheduler.go:425,492)
        binding_start = perf_counter()
        try:
            with flight.span("bind") as bsp:
                if bsp:
                    bsp.set("host", host)
                self.bind(pod, host)
        except SchedulingError:
            # bind error arm (scheduler.go:484-496): forget the assumed pod
            # so its resources are returned, then surface the error
            self.cache.forget_pod(assumed)
            raise
        self.cache.finish_binding(assumed)  # no-op once confirmed
        metrics.binding_latency.observe(since_in_microseconds(binding_start))
        metrics.e2e_scheduling_latency.observe(since_in_microseconds(e2e_start))
        return "bound"

    # --- gang admission (tpusim/gang): all-or-nothing group scheduling ---

    def _schedule_or_admit(self, pod: Pod) -> str:
        """Per-pod dispatch: a pod carrying a group annotation routes its
        whole gang through all-or-nothing admission; everything else takes
        the unchanged scheduleOne path."""
        from tpusim.gang.group import gang_name

        if gang_name(pod):
            return self._admit_gang(pod)
        return self._schedule_one(pod)

    def _gather_gang(self, pod: Pod):
        """Pull `pod`'s mates forward — from the LIFO feed and, on retries,
        from the scheduling queue — so the group decides as one unit at the
        first member's feed position."""
        from tpusim.gang.group import PodGroup, gang_name

        name = gang_name(pod)
        members = [pod]
        seen = {pod.key()}
        for mate in (self.pod_queue.take_matching(
                lambda p: gang_name(p) == name)
                + self.scheduling_queue.take_matching(
                    lambda p: gang_name(p) == name)):
            if mate.key() not in seen:
                seen.add(mate.key())
                members.append(mate)
        return PodGroup(name=name, pods=members)

    def _trial_member(self, pod: Pod) -> Optional[str]:
        """One member's trial: schedule + assume + bind (so the next member
        sees the placement), WITHOUT the unschedulable intercept — failure
        attribution belongs to the group decision, not the member. Returns
        the host or None."""
        node_infos = self.refresh_node_info_snapshot()
        try:
            host = self.scheduler.schedule(pod, self.nodes, node_infos)
        except SchedulingError:
            return None
        assumed = pod.copy()
        assumed.spec.node_name = host
        try:
            self.cache.assume_pod(assumed)
        except CacheError:
            return None
        try:
            self.bind(pod, host)
        except SchedulingError:
            self.cache.forget_pod(assumed)
            return None
        self.cache.finish_binding(assumed)
        return host

    def _admit_gang(self, pod: Pod) -> str:
        """All-or-nothing admission of `pod`'s group: gather the mates,
        trial-bind members sequentially (intra-gang binds visible), then
        either keep the binds (>= min-available placed) or roll every one
        back through the store — the cache sees the deletes — and park the
        whole gang with ONE shared FitError. Gang admission does not
        attempt preemption (documented in DEVIATIONS.md)."""
        from tpusim.gang.driver import gang_fit_message

        group = self._gather_gang(pod)
        m = self.metrics
        m.gang_size.observe(len(group.pods))
        bound: List[Pod] = []
        overflow: List[Pod] = []
        with flight.span("gang:admit") as sp:
            if sp:
                sp.set("group", group.name)
                sp.set("members", len(group.pods))
            for member in group.pods:
                _stored, exists = self.resource_store.get(
                    ResourceType.PODS, member.key())
                if not exists:
                    self.resource_store.add(ResourceType.PODS, member)
                if self.chaos is not None:
                    # mates pulled forward never went through _next_pod:
                    # they are fed HERE, so the no-pod-lost audit and the
                    # eviction re-feed mechanics cover them too
                    self.chaos.note_fed(member)
                if self._trial_member(member) is not None:
                    bound.append(member)
                else:
                    overflow.append(member)

        if len(bound) >= group.min_available:
            # admitted: the gang stands; overflow members failed
            # individually, not the gang
            keys = {p.key() for p in bound}
            self.status.failed_pods = [
                p for p in self.status.failed_pods if p.key() not in keys]
            for member in overflow:
                msg = (f"pod group \"{group.name}\" admitted at "
                       f"{len(bound)}/{len(group.pods)}; this member did "
                       f"not fit.")
                self.update(member, PodCondition(
                    type="PodScheduled", status="False",
                    reason="Unschedulable", message=msg))
            m.gang_admitted.inc()
            flight.note_gang("admit", {"group": group.name,
                                       "placed": len(bound),
                                       "members": len(group.pods)})
            return "bound"

        # rejected: roll back every trial bind so no partial gang survives
        msg = gang_fit_message(group, len(self.nodes), len(bound))
        for member in bound:
            current, exists = self.resource_store.get(
                ResourceType.PODS, member.key())
            if exists and current.spec.node_name:
                self.resource_store.delete(ResourceType.PODS, current)
            key = member.key()
            self.status.successful_pods = [
                p for p in self.status.successful_pods if p.key() != key]
            # the pristine pending member goes back to the store, exactly
            # like a pod that never trial-bound
            self.resource_store.add(ResourceType.PODS, member)
        if bound:
            m.gang_partial_rollback.inc()
            flight.note_gang("rollback", {"group": group.name,
                                          "unbound": len(bound)})
        m.gang_rejected.inc("min_available" if self.nodes else "no_nodes")
        flight.note_gang("reject", {"group": group.name,
                                    "placed": len(bound)})
        for member in group.pods:
            self.update(member, PodCondition(
                type="PodScheduled", status="False",
                reason="Unschedulable", message=msg))
        return "failed"

    def _release_gangs(self, names, preemptor: Pod, node) -> None:
        """A preempted member releases its whole gang: every still-bound
        mate is deleted from the store (the cache sees the deletes), moved
        to the preempted bucket, and the group's queued nominations are
        cleared so parked members re-attempt as a unit."""
        from tpusim.gang.group import gang_name

        m = self.metrics
        for mate in list(self.resource_store.list(ResourceType.PODS)):
            if gang_name(mate) not in names or not mate.spec.node_name:
                continue
            self.resource_store.delete(ResourceType.PODS, mate)
            key = mate.key()
            self.status.successful_pods = [
                p for p in self.status.successful_pods if p.key() != key]
            self.status.scheduled_pods = [
                p for p in self.status.scheduled_pods if p.key() != key]
            self.status.preempted_pods.append(mate)
            self.recorder.eventf(mate, "Normal", "Preempted",
                                 "gang released by %s on node %s",
                                 preemptor.name, node.name)
            m.gang_partial_rollback.inc()
        cleared = self.scheduling_queue.clear_nominations_for_gangs(names)
        for p in cleared:
            p.status.nominated_node_name = ""
        flight.note_gang("release", {"groups": sorted(names)})

    def attempt_preemption(self, pod: Pod, fit_err: FitError,
                           candidate_filter=None):
        """The preemption arm of scheduleOne (scheduler.go:449-455 → the full
        Preempt pipeline, core/generic_scheduler.go:205-262): pick a node +
        victims, delete the victims from the store (mutating the cache through
        the DELETED events), and nominate the pod. Returns (node, victims) —
        node is None when preemption found nothing. Shared by the host loop
        (_schedule_one retry) and the jax backend's host-device hybrid
        (tpusim/jaxe/preempt.py)."""
        metrics = self.metrics
        preemption_start = perf_counter()
        metrics.preemption_attempts.inc()
        psp = flight.span("preempt")
        try:
            # Preempt runs against the same cached snapshot the failed
            # Schedule used (g.cachedNodeInfoMap, generic_scheduler.go:205)
            node, victims, to_clear = self.scheduler.preempt(
                pod, self.nodes, self._cached_node_infos, fit_err,
                candidate_filter=candidate_filter)
        except SchedulingError:
            # a failed preemption attempt (e.g. extender error) is
            # logged-and-dropped in the reference (scheduler.go:
            # 449-451); the pod still gets its Unschedulable condition
            node, victims, to_clear = None, [], []
        if psp:
            psp.set("pod", pod.key())
            psp.set("node", node.name if node is not None else "")
            psp.set("victims", len(victims))
            psp.end()
        metrics.preemption_evaluation.observe(
            since_in_microseconds(preemption_start))
        return self.commit_preemption(pod, node, victims, to_clear)

    def commit_preemption(self, pod: Pod, node, victims, to_clear):
        """The side-effect half of attempt_preemption (preempt.go:45-75):
        clear losing nominations, nominate the pod, delete victims from the
        store (mutating the cache through the DELETED events), and emit the
        Preempted events. Split out so the jax backend's device-side victim
        selection (tpusim/jaxe/preempt.py) can commit a kernel-picked
        (node, victims) through the exact same store/status/event sequence
        the host pipeline uses."""
        metrics = self.metrics
        metrics.preemption_victims.set(len(victims))
        for p in to_clear:
            p.status.nominated_node_name = ""
        if node is None:
            return None, []
        pod.status.nominated_node_name = node.name
        for victim in victims:
            self.resource_store.delete(ResourceType.PODS, victim)
            self.status.preempted_pods.append(victim)
            # an evicted pod is no longer placed: drop it from the
            # success/pre-scheduled buckets so the report balances
            key = victim.key()
            self.status.successful_pods = [
                p for p in self.status.successful_pods if p.key() != key]
            self.status.scheduled_pods = [
                p for p in self.status.scheduled_pods if p.key() != key]
            self.recorder.eventf(victim, "Normal", "Preempted",
                                 "by %s on node %s", pod.name, node.name)
        from tpusim.gang.group import gang_name

        gang_names = {gang_name(v) for v in victims if gang_name(v)}
        if gang_names:
            # preempting one member releases the whole gang — an
            # all-or-nothing admission cannot survive partially
            self._release_gangs(gang_names, pod, node)
        return node, victims

    STOP_REASONS = {
        # Bind's deferred nextPod uses lowercase "fail", Update's uses "Fail"
        "run": "fail to get next pod: No pods left\n",      # simulator.go:204
        "bound": "fail to get next pod: No pods left\n",    # simulator.go:136
        "failed": "Fail to get next pod: No pods left\n",   # simulator.go:171
    }

    def run(self) -> None:
        """Reference: simulator.go:187-213 — feed one pod at a time until the
        queue drains; the stop-reason strings match the Go format verbatim."""
        if self.chaos is not None:
            return self._run_chaos()
        rec = flight.get_recorder()
        idle_since = rec.clock() if rec is not None else 0.0
        pod = self._next_pod()
        if pod is None:
            self.status.stop_reason = self.STOP_REASONS["run"]
            self.close()
            return
        while pod is not None:
            if rec is not None:
                # time the pod sat in the LIFO feed since the scheduler
                # last went idle (the reference's scheduling-queue wait)
                rec.add_span("queue_wait", "host", idle_since, rec.clock(),
                             {"pod": pod.key()})
            outcome = self._schedule_or_admit(pod)
            if rec is not None:
                idle_since = rec.clock()
            next_pod = self._next_pod()
            if next_pod is None:
                self.status.stop_reason = self.STOP_REASONS[outcome]
                self.close()
                return
            pod = next_pod

    def _run_chaos(self) -> None:
        """The chaos arm of run(): identical seams and scheduling path, but
        every attempt boundary fires due churn first, and after the LIFO
        feed drains, churn-reactivated pods (evicted-and-requeued, or
        parked pods a returning node released) get bounded re-attempts out
        of the scheduling queue — gated per pod by the plan's max_retries
        and by PodBackoff under the chaos clock. A global attempt budget
        guarantees termination for any plan."""
        chaos = self.chaos
        outcome = "run"
        max_at = max([ev.at for ev in chaos.plan.churn] +
                     [ev.at + ev.restore_after for ev in chaos.plan.churn],
                     default=0)
        budget = ((len(self.pod_queue) + len(self.status.scheduled_pods)
                   + len(chaos.plan.churn) + 8)
                  * (chaos.plan.max_retries + 2) + max_at + 64)
        spent = 0
        while spent < budget:
            spent += 1
            chaos.fire_boundary()
            pod = self._next_pod()
            if pod is not None:
                chaos.note_fed(pod)
                outcome = self._schedule_or_admit(pod)
                continue
            if chaos.has_pending_churn():
                # churn scheduled past the attempt horizon may still evict
                # and requeue; keep ticking boundaries until it lands
                continue
            retry = self.scheduling_queue.pop()
            if retry is None:
                break
            if chaos.allow_retry(retry):
                outcome = self._schedule_or_admit(retry)
        if spent >= budget:
            chaos.record_violation(
                f"attempt budget exhausted ({budget}): the run did not "
                "quiesce")
        chaos.flush()
        self.status.stop_reason = self.STOP_REASONS.get(
            outcome, self.STOP_REASONS["run"])
        self.close()

    def close(self) -> None:
        self.closed = True

    def get_report(self) -> GeneralReview:
        if self.report is None:
            self.report = get_report(self.status)
        return self.report


def new_cluster_capacity(config: SchedulerServerConfig, new_pods: List[Pod],
                         scheduled_pods: List[Pod], nodes: List[Node],
                         services: Optional[list] = None) -> ClusterCapacity:
    """Reference: scheduler.New (simulator.go:286-342)."""
    return ClusterCapacity(config, new_pods, scheduled_pods, nodes, services)


def auto_routes_to_host(num_pods: int, num_nodes: int,
                        enable_volume_scheduling: bool = False) -> bool:
    """The --backend auto routing rule (shared with the CLI's --v 5 note;
    callers size num_nodes AFTER any event-log fold, since node-adding
    logs count toward the threshold).

    Tiny workloads lose to device-dispatch latency (BASELINE.md: the
    20-pod quickstart runs ~400x slower through an accelerator tunnel than
    the host engine; the crossover sits around config 2's 1k x 100 shape).
    Intentionally avoids initializing jax — merely listing devices can
    block on a wedged tunnel. Volume scheduling is host-bound and wins
    over everything."""
    if enable_volume_scheduling:
        return True
    threshold = int(os.environ.get("TPUSIM_AUTO_THRESHOLD", 100_000))
    return num_pods * max(num_nodes, 1) < threshold


def run_simulation(pods: List[Pod], snapshot: ClusterSnapshot,
                   provider: str = DEFAULT_PROVIDER, backend: str = "reference",
                   scheduler_name: str = DEFAULT_SCHEDULER_NAME,
                   enable_pod_priority: bool = False,
                   enable_volume_scheduling: bool = False,
                   policy: Optional[Policy] = None,
                   events: Optional[list] = None,
                   feature_gates: Optional[Dict[str, bool]] = None,
                   chaos_plan: Optional[object] = None) -> Status:
    """High-level entry: run `pods` (in podspec order; the LIFO feed reversal
    happens inside, matching the reference) against `snapshot` and return the
    final Status. backend='jax' routes the batch through the TPU engine and
    reconstructs the same Status/report shape.

    events: an optional [(ADDED|MODIFIED|DELETED, Pod|Node|Service), ...]
    watch-event log (framework.events.load_event_log) replayed on top of
    `snapshot` before scheduling — the reference's watch fabric
    (restclient.go:218-236 → informer cache mutations) as data. On the jax
    backend the replay drives the IncrementalCluster column caches
    (jaxe/delta.py), so compiled state is patched, not rebuilt.

    chaos_plan: an optional tpusim.chaos.FaultPlan. Churn and fabric
    sections drive the reference orchestrator (a jax-backend request with
    those sections reroutes host-side with a warning, like the other
    host-bound features); the device section arms the dispatch circuit
    breaker + fault injector around the jax backend. The returned Status
    gains `chaos_summary` (fired faults, retries) and `chaos_violations`
    (end-state invariant audit; empty = degraded gracefully)."""
    incremental = None
    if events:
        from tpusim.jaxe.delta import IncrementalCluster

        incremental = IncrementalCluster(snapshot)
        incremental.apply_events(events)
        folded = incremental.to_snapshot()
        # folded PV/PVC state includes applied PersistentVolume(Claim) events
        # (jaxe/delta.py); StorageClass objects are not watch-fabric events
        # and pass through from the seed snapshot
        snapshot = ClusterSnapshot(
            nodes=folded.nodes, pods=folded.pods, services=folded.services,
            pvs=folded.pvs, pvcs=folded.pvcs,
            storage_classes=snapshot.storage_classes)
    if backend == "auto":
        # sized AFTER the event-log fold above, so node-adding logs count
        backend = ("reference"
                   if auto_routes_to_host(len(pods), len(snapshot.nodes),
                                          enable_volume_scheduling)
                   else "jax")
    if feature_gates:
        # PodPriority / VolumeScheduling gate the same behavior as the
        # dedicated parameters (scheduler.go:175,210-213) for library
        # callers; the registry-surgery gates pass through to
        # apply_feature_gates
        feature_gates = dict(feature_gates)
        if feature_gates.pop("PodPriority", False):
            enable_pod_priority = True
        if feature_gates.pop("VolumeScheduling", False):
            enable_volume_scheduling = True
    if chaos_plan is not None:
        chaos_plan.validate()
        if backend == "jax" and not chaos_plan.host_sections_empty():
            # churn fires at per-pod attempt boundaries and fabric faults
            # hit watch streams — both exist only in the host orchestrator
            # (the jax batch path has neither); device faults alone stay
            # on the device path, absorbed by the circuit breaker
            import logging

            logging.getLogger(__name__).warning(
                "chaos churn/fabric sections are host-bound: running the "
                "reference orchestrator instead of the jax backend")
            backend = "reference"
    if feature_gates and any(feature_gates.get(g) for g in
                             ("TaintNodesByCondition",
                              "ResourceLimitsPriorityFunction")) \
            and backend == "jax":
        # registry surgery is host-registry-bound; the gated predicate/
        # priority sets have no compiled device shape (both gates default
        # off upstream, so the ungated device engine matches executed
        # reference behavior)
        import logging

        logging.getLogger(__name__).warning(
            "feature gates %s are host-bound: running the reference "
            "orchestrator instead of the jax backend",
            sorted(k for k, v in feature_gates.items() if v))
        backend = "reference"
    compiled_policy = None
    if policy is not None and backend == "jax":
        # compile (and validate) the policy for the device engine; the one
        # host-bound feature (extenders) routes to the reference
        # orchestrator, which has the full plugin registry and the
        # in-process extender seam
        import logging

        from tpusim.jaxe.policyc import compile_policy

        compiled_policy = compile_policy(policy)
        if compiled_policy.unsupported or enable_pod_priority:
            reason = ("preemption with a policy scheduler"
                      if not compiled_policy.unsupported else
                      "; ".join(sorted(set(compiled_policy.unsupported))[:5]))
            logging.getLogger(__name__).warning(
                "policy is host-bound (%s): running the reference "
                "orchestrator instead of the jax backend", reason)
            backend = "reference"
    if backend == "reference":
        chaos_engine = None
        if chaos_plan is not None:
            from tpusim.chaos import ChaosEngine

            chaos_engine = ChaosEngine(chaos_plan)
        cc = ClusterCapacity(
            SchedulerServerConfig(scheduler_name=scheduler_name,
                                  algorithm_provider=provider,
                                  policy=policy,
                                  enable_pod_priority=enable_pod_priority,
                                  enable_volume_scheduling=enable_volume_scheduling,
                                  feature_gates=feature_gates),
            new_pods=pods, scheduled_pods=snapshot.pods, nodes=snapshot.nodes,
            services=snapshot.services, pvs=snapshot.pvs, pvcs=snapshot.pvcs,
            storage_classes=snapshot.storage_classes, chaos=chaos_engine)
        cc.run()
        if chaos_engine is not None:
            from tpusim.chaos import check_invariants

            cc.status.chaos_violations = check_invariants(cc, chaos_engine)
            cc.status.chaos_summary = chaos_engine.summary()
        return cc.status
    if backend == "jax":
        # interactive robustness: a wedged accelerator tunnel must degrade
        # to the CPU backend instead of hanging the first device op forever
        from tpusim.jaxe import ensure_responsive_platform

        ensure_responsive_platform()
        from tpusim.backends import get_backend

        if enable_volume_scheduling:
            raise ValueError("--enable-volume-scheduling requires --backend "
                             "reference (delayed PV binding is stateful "
                             "host-side matching)")
        from tpusim.gang.group import has_gangs

        if enable_pod_priority:
            if has_gangs(pods):
                # preemption interplay (gang release, nomination cleanup)
                # lives in the host orchestrator's queue/store machinery;
                # the device hybrid has no group-aware retry loop
                import logging

                logging.getLogger(__name__).warning(
                    "pod groups with PodPriority are host-bound: running "
                    "the reference orchestrator instead of the jax backend")
                return run_simulation(
                    pods, snapshot, provider=provider, backend="reference",
                    scheduler_name=scheduler_name, enable_pod_priority=True,
                    policy=policy, events=events,
                    feature_gates=feature_gates, chaos_plan=chaos_plan)
            # host-device hybrid: device scan schedules, the exact host
            # Preempt pipeline fires on failures (jaxe/preempt.py)
            from tpusim.jaxe.preempt import run_with_preemption

            return run_with_preemption(pods, snapshot, provider=provider,
                                       incremental=incremental)
        jax_backend = get_backend("jax", provider=provider, policy=policy,
                                  compiled_policy=compiled_policy)
        feed = list(reversed(pods))  # the LIFO queue pops the last element first
        gangs = has_gangs(feed)
        precompiled = (incremental.compile(feed) if incremental is not None
                       and feed and snapshot.nodes and not gangs else None)
        breaker = None
        if chaos_plan is not None and not chaos_plan.device.empty():
            from tpusim.jaxe.backend import install_chaos

            breaker = install_chaos(chaos_plan.device)
        try:
            with flight.span("backend_schedule") as bsp:
                if bsp:
                    bsp.set("backend", "jax")
                    bsp.set("pods", len(feed))
                if gangs:
                    # gang feeds route through the group driver: ungrouped
                    # runs use the unchanged per-pod path against the live
                    # incremental cluster, gangs are admitted all-or-nothing
                    from tpusim.gang.driver import schedule_with_gangs
                    from tpusim.jaxe.delta import IncrementalCluster

                    inc = incremental or IncrementalCluster(snapshot)
                    placements = schedule_with_gangs(jax_backend, inc, feed)
                else:
                    placements = jax_backend.schedule(
                        feed, snapshot, precompiled=precompiled)
        finally:
            if breaker is not None:
                from tpusim.jaxe.backend import uninstall_chaos

                uninstall_chaos()
        status = Status(scheduled_pods=list(snapshot.pods))
        for placement in placements:
            if placement.scheduled:
                status.successful_pods.append(placement.pod)
            else:
                status.failed_pods.append(placement.pod)
        last_failed = placements and not placements[-1].scheduled
        status.stop_reason = ("Fail to get next pod: No pods left\n" if last_failed
                              else "fail to get next pod: No pods left\n")
        if breaker is not None:
            status.chaos_summary = {
                "breaker_transitions": list(breaker.transitions)}
            status.chaos_violations = []
        return status
    raise ValueError(f"unknown backend {backend!r}")


def run_stream_simulation(snapshot: Optional[ClusterSnapshot] = None, *,
                          num_nodes: int = 64, cycles: int = 50,
                          arrivals: int = 32, evict_fraction: float = 0.25,
                          node_flap_every: int = 0,
                          label_churn: int = 0, taint_churn: int = 0,
                          gang_size: int = 0, gang_count: int = 0,
                          seed: int = 0,
                          provider: str = DEFAULT_PROVIDER,
                          policy=None, pipeline: bool = False,
                          always_restage: bool = False, verify: bool = False,
                          chaos_plan: Optional[object] = None,
                          checkpoint_dir: Optional[str] = None,
                          checkpoint_every: int = 0,
                          fsync_every: int = 0,
                          replicate_to: Optional[tuple] = None,
                          recover: bool = False,
                          whatif_every: int = 0,
                          whatif_pods: int = 4) -> dict:
    """Drive a StreamSession through seeded churn (tpusim.stream.ChurnLoadGen)
    and return a summary dict — the `tpusim stream` CLI, the bench's configs
    9/10, and the smoke variants all sit on this loop.

    Unlike run_simulation (one batch, one decision), this is the steady-state
    shape the streaming runtime exists for: per cycle, watch events fold into
    the host picture, the delta scatter-commits onto the device-resident
    carry, and a fresh arrival batch schedules against it — O(delta) per warm
    cycle instead of O(cluster).

    always_restage: disable the fast path (the restage-comparison arm).
    policy: an engine.policy.Policy compiled for device residency (ISSUE 9);
        synthetic clusters get their node labels seeded from the churn
        universe so every label value interns at cold start — pure
        label/taint churn then rides the statics scatter with zero restages.
    pipeline: overlap host decode of cycle N-1 with cycle N's device
        execution (StreamSession.schedule_pipelined); placements and the
        placement chain are byte-identical to the synchronous path.
    label_churn / taint_churn: per-cycle label rewrites / taint toggles fed
        through the load generator (the scatter-absorbable churn class).
    gang_size / gang_count: per-cycle pod-group arrivals (tpusim/gang):
        each cycle carrying gangs runs as a multi-pod gang cycle —
        all-or-nothing admission with rank-aware packing; fold-back stays
        O(delta) through the journal's next-cycle scatter-commit.
    verify: additionally run every cycle through a fresh-compile
        JaxBackend.schedule and assert byte-identical placement hashes
        (pipelined cycles compare when their placements emerge, one cycle
        later).
    chaos_plan: device-fault and process_crash sections only — churn/fabric
        faults are what the load generator already produces, event-shaped.
        A process_crash event arms the WAL writer (requires
        checkpoint_dir) and the run dies with chaos.engine.ProcessCrash at
        the targeted record; a follow-up call with recover=True and the
        SAME workload arguments resumes it.
    checkpoint_dir / checkpoint_every: journal every cycle to a WAL and
        checkpoint the host+device picture every that-many emitted cycles
        (stream.persist); 0 = genesis checkpoint only.
    recover: load checkpoint_dir, replay the WAL tail, fast-forward the
        load generator over the committed prefix, and run the REMAINING
        cycles. The summary's fold_chain is then byte-identical to an
        uninterrupted run's.
    fsync_every: fsync the WAL file every N appends (stream.persist's
        durability dial; the mode is stamped into checkpoint manifests).
    replicate_to: (host, port) of a listening FollowerTwin — attach a
        WalShipper to the journal (requires checkpoint_dir) and drain it
        before returning; the summary grows replication_{drained,
        acked_seq, lag_at_close} (ISSUE 18).
    whatif_every: every N cycles, answer a live what-if query against the
        device-resident twin via StreamSession.overlay_query — a
        copy-on-write overlay (mark -> scatter scenario pods -> scan ->
        roll back) that leaves the carry byte-identical, so the run's
        fold_chain is unchanged by the queries (ISSUE 19). The summary
        grows an ``overlay`` block: queries/answered/fallbacks and query
        latency percentiles. 0 disables.
    whatif_pods: scenario pods per live query (drawn from a dedicated
        rng stream, deterministic per seed, never entering the churn
        picture).
    """
    from tpusim.api.snapshot import synthetic_cluster
    from tpusim.backends import Placement, bind_pod, get_backend, \
        placement_hash
    from tpusim.jaxe.delta import IncrementalCluster
    from tpusim.stream import ChurnLoadGen, StreamSession
    from tpusim.stream.loadgen import DEFAULT_LABEL_UNIVERSE
    from tpusim.stream.persist import (
        StreamPersistence,
        chain_fold,
        recover_stream_session,
    )

    if snapshot is None:
        snapshot = synthetic_cluster(num_nodes)
        if policy is not None or label_churn or taint_churn:
            # seed every churn-universe value across the synthetic nodes so
            # the cold-start compile interns the full label domain — churn
            # then never needs a new domain id (a staged-shape property)
            for i, node in enumerate(snapshot.nodes):
                node.metadata.labels.update(
                    {k: vals[i % len(vals)]
                     for k, vals in DEFAULT_LABEL_UNIVERSE.items()})
    breaker = None
    crash_events = []
    if chaos_plan is not None:
        chaos_plan.validate()
        if not chaos_plan.host_sections_empty():
            raise ValueError(
                "run_stream_simulation takes device fault and process_crash "
                "sections only: churn/fabric faults arrive through the load "
                "generator as watch events")
        crash_events = chaos_plan.crash_events()
        if crash_events and checkpoint_dir is None:
            raise ValueError(
                "process_crash faults fire from the WAL writer: pass "
                "checkpoint_dir (--checkpoint-dir)")
        if not chaos_plan.device.empty():
            from tpusim.jaxe.backend import install_chaos

            breaker = install_chaos(chaos_plan.device)
    if recover and checkpoint_dir is None:
        raise ValueError("recover=True needs checkpoint_dir")
    if replicate_to is not None and checkpoint_dir is None:
        raise ValueError("replicate_to ships the WAL: pass checkpoint_dir "
                         "(--checkpoint-dir)")
    if replicate_to is not None and recover:
        raise ValueError("replicate_to cannot resume a recovery replay; "
                         "recover first, then re-attach the shipper")
    if recover and verify:
        raise ValueError(
            "verify and recover are mutually exclusive: the verify arm "
            "replays the reference picture from cycle 0")
    persist = report = shipper = None
    start_cycle = 0
    if recover:
        session, report, persist = recover_stream_session(
            checkpoint_dir, provider=provider, policy=policy,
            always_restage=always_restage,
            checkpoint_every=checkpoint_every)
        start_cycle = report.resume_cycle
    else:
        session = StreamSession(snapshot, provider=provider, policy=policy,
                                always_restage=always_restage)
        if checkpoint_dir is not None:
            persist = StreamPersistence(checkpoint_dir,
                                        checkpoint_every=checkpoint_every,
                                        fsync_every=fsync_every)
            if replicate_to is not None:
                # hook the journal BEFORE attach so the genesis
                # checkpoint manifest is the first shipped frame
                from tpusim.stream.replicate import WalShipper

                shipper = WalShipper(persist, tuple(replicate_to))
            session.attach_persistence(persist)
    if crash_events and persist is not None:
        ev = crash_events[0]
        persist.arm_crash(ev.at, ev.target)
    gen = ChurnLoadGen(snapshot, seed=seed, arrivals=arrivals,
                       evict_fraction=evict_fraction,
                       node_flap_every=node_flap_every,
                       label_churn=label_churn, taint_churn=taint_churn,
                       gang_size=gang_size, gang_count=gang_count)
    skip_events = 0
    if recover:
        # deterministic fast-forward: the generator draws NO rng in batch()
        # or note_bound(), so replaying events()/batch() for the committed
        # prefix — with binds fed back from the WAL — leaves the rng and
        # the bound population exactly where the crashed run had them
        for c in range(start_cycle):
            gen.events(c)
            by_key = {p.key(): p for p in gen.batch()}
            gen.note_bound([
                Placement(pod=bind_pod(by_key[k], node), node_name=node)
                for k, node in report.bound_by_cycle.get(c, [])
                if k in by_key])
        # a crash mid-events left a partially-applied cycle: the replayed
        # picture already holds its first events_applied deltas
        skip_events = report.events_applied.get(start_cycle, 0)
    ref_inc = ref_backend = ref_gen = None
    if verify:
        ref_inc = IncrementalCluster(snapshot)
        ref_backend = get_backend("jax", provider=provider, policy=policy)
        ref_gen = ChurnLoadGen(snapshot, seed=seed, arrivals=arrivals,
                               evict_fraction=evict_fraction,
                               node_flap_every=node_flap_every,
                               label_churn=label_churn,
                               taint_churn=taint_churn,
                               gang_size=gang_size, gang_count=gang_count)
    import hashlib

    chain = hashlib.sha256()
    # the resumable fold over per-cycle placement hashes (persist.chain's
    # twin): seeded from the recovered prefix, so a recovered run's final
    # fold is comparable byte-for-byte with an uninterrupted run's
    fold_chain = report.chain if recover else ""
    latencies: List[float] = []
    expected_hashes: List[str] = []   # verify arm FIFO (pipeline lags 1)
    scheduled = decisions = mismatches = 0

    def account(placements) -> None:
        nonlocal decisions, scheduled, mismatches, fold_chain
        decisions += len(placements)
        scheduled += sum(1 for p in placements if p.node_name)
        h = placement_hash(placements)
        chain.update(h.encode())
        fold_chain = chain_fold(fold_chain, h)
        if persist is None:
            # with persistence attached log_emit publishes the WAL chain
            # head instead; don't fight it with the in-memory fold
            register_metrics().stream_chain_head.set_info(
                head=fold_chain, cycle=str(session.cycles))
        if verify and expected_hashes.pop(0) != h:
            mismatches += 1

    # live what-if arm (ISSUE 19): a dedicated rng stream so the query
    # pods never perturb the churn draw, and per-query latency tracking
    from numpy.random import RandomState as _RandomState
    whatif_rng = _RandomState(seed + 9173) if whatif_every else None
    whatif_lat: List[float] = []
    whatif_stats = {"queries": 0, "answered": 0, "fallbacks": 0}

    def live_query(cycle: int) -> None:
        from tpusim.api.snapshot import make_pod

        qpods = [make_pod(f"whatif-c{cycle}-p{i}",
                          milli_cpu=int(whatif_rng.randint(100, 1500)),
                          memory=int(whatif_rng.randint(2 ** 20, 2 ** 30)))
                 for i in range(whatif_pods)]
        whatif_stats["queries"] += 1
        tq = perf_counter()
        answered = session.overlay_query(qpods)
        if answered is None:
            whatif_stats["fallbacks"] += 1
        else:
            whatif_stats["answered"] += 1
            whatif_lat.append(perf_counter() - tq)

    t_start = perf_counter()
    clean_exit = False
    try:
        for cycle in range(start_cycle, cycles):
            if pipeline:
                # fold cycle N-1's binds BEFORE drawing cycle N's events:
                # the host picture evolves in exactly the synchronous order
                gen.note_bound(session.poll_placed())
            evs = gen.events(cycle)
            if skip_events:
                evs = evs[skip_events:]
                skip_events = 0
            # one trace context per driver cycle (ISSUE 20): the ingest
            # span AND the scheduler's own cycle context (a child — same
            # trace id) share one causal story, so the exported graph
            # connects ingest → scatter-commit → scan → fold → emit.
            # start() is None (and everything below a no-op) unless a
            # flight recorder is installed.
            with tracectx.activate(tracectx.start()):
                with flight.span("stream_ingest") as isp:
                    if isp:
                        isp.set("events", len(evs))
                    session.apply_events(evs)
                batch = gen.batch()
                t0 = perf_counter()
                if pipeline:
                    prev = session.schedule_pipelined(batch)
                else:
                    prev = session.schedule(batch)
            latencies.append(perf_counter() - t0)
            if verify:
                # the reference pictures advance at dispatch time (their
                # state matches the session's host picture NOW); the
                # comparison happens whenever the placements emerge
                ref_inc.apply_events(ref_gen.events(cycle))
                ref_batch = ref_gen.batch()
                from tpusim.gang.group import has_gangs as _has_gangs

                if _has_gangs(ref_batch):
                    # the group driver applies its binds to ref_inc
                    # internally — folding them again would double-apply
                    from tpusim.gang.driver import schedule_with_gangs

                    expected = schedule_with_gangs(ref_backend, ref_inc,
                                                   ref_batch)
                else:
                    expected = ref_backend.schedule(ref_batch,
                                                    ref_inc.to_snapshot())
                    for pl in expected:
                        if pl.node_name:
                            ref_inc.apply(MODIFIED, pl.pod)
                ref_gen.note_bound(expected)
                expected_hashes.append(placement_hash(expected))
            if pipeline:
                if prev is not None:
                    account(prev)
            else:
                gen.note_bound(prev)
                account(prev)
            if whatif_every and (cycle + 1) % whatif_every == 0:
                # interleave a live read with the churn: the overlay
                # rolls back to a byte-identical carry, so fold_chain is
                # provably unchanged vs the query-free run
                live_query(cycle)
        if pipeline:
            tail = session.flush()
            if tail:
                account(tail)
        clean_exit = True
    finally:
        if shipper is not None:
            # a graceful end waits for the follower's cumulative ack; a
            # ProcessCrash propagating through here deliberately does NOT
            # (drain=False is the death model — the unshipped tail lives
            # only in the durable WAL)
            shipper.close(drain=clean_exit, timeout=30.0)
        if persist is not None:
            persist.close()
        if breaker is not None:
            from tpusim.jaxe.backend import uninstall_chaos

            uninstall_chaos()
    elapsed = perf_counter() - t_start
    latencies.sort()

    def pct(q: float) -> float:
        i = min(len(latencies) - 1, int(round(q * (len(latencies) - 1))))
        return latencies[i] if latencies else 0.0

    out = {
        "cycles": cycles, "nodes": len(session.inc.nodes),
        "decisions": decisions, "scheduled": scheduled,
        "unschedulable": decisions - scheduled,
        "elapsed_s": elapsed,
        "decisions_per_s": decisions / elapsed if elapsed > 0 else 0.0,
        "p50_cycle_ms": pct(0.5) * 1e3, "p99_cycle_ms": pct(0.99) * 1e3,
        "paths": dict(session.path_counts),
        "restages": dict(session.restage_counts),
        "commits": session.device.commits,
        "placement_chain": chain.hexdigest(),
        "fold_chain": fold_chain,
        "load": dict(gen.stats),
    }
    if whatif_every:
        whatif_lat.sort()

        def qpct(q: float) -> float:
            if not whatif_lat:
                return 0.0
            i = min(len(whatif_lat) - 1,
                    int(round(q * (len(whatif_lat) - 1))))
            return whatif_lat[i]

        out["overlay"] = {
            **whatif_stats,
            "p50_query_ms": qpct(0.5) * 1e3,
            "p99_query_ms": qpct(0.99) * 1e3,
        }
    if verify:
        out["verified"] = mismatches == 0
        out["mismatched_cycles"] = mismatches
    if breaker is not None:
        out["breaker_transitions"] = list(breaker.transitions)
    if persist is not None:
        out["wal_records"] = persist.wal_records
        out["checkpoints"] = persist.checkpoints
        out["wal_chain"] = persist.chain
    if shipper is not None:
        out["replication_acked_seq"] = shipper.acked_seq
        out["replication_acked_chain"] = shipper.acked_chain
        out["replication_lag_at_close"] = shipper.lag_records()
    if recover:
        out["recovered"] = True
        out["resume_cycle"] = start_cycle
        out["replay_ms"] = report.replay_s * 1e3
        out["recomputed_cycles"] = list(report.recomputed)
        out["recovery_violations"] = list(report.violations)
    # cluster analytics (ISSUE 14): fold the run's fleet-state snapshot
    # into the report when the plane is armed (one None-check otherwise)
    from tpusim.obs import analytics as _analytics

    alog = _analytics.get()
    if alog is not None:
        alog.flush()
        out["analytics"] = alog.snapshot()
    return out


def run_replicated_stream(snapshot: Optional[ClusterSnapshot] = None, *,
                          num_nodes: int = 64, cycles: int = 50,
                          arrivals: int = 32, evict_fraction: float = 0.25,
                          node_flap_every: int = 0,
                          label_churn: int = 0, taint_churn: int = 0,
                          gang_size: int = 0, gang_count: int = 0,
                          seed: int = 0,
                          provider: str = DEFAULT_PROVIDER,
                          policy=None, pipeline: bool = False,
                          always_restage: bool = False,
                          chaos_plan: Optional[object] = None,
                          checkpoint_dir: Optional[str] = None,
                          checkpoint_every: int = 1,
                          fsync_every: int = 0,
                          drain_timeout: float = 30.0) -> dict:
    """Drive a LEADER StreamSession with a live FollowerTwin attached over
    the WAL-shipping socket protocol (stream.replicate, ISSUE 18).

    Without a chaos plan this is a replicated steady-state run: the
    summary reports the follower's chain head next to the leader's (they
    must be byte-identical after a drain) plus the shipping lag the run
    sustained.

    With a process_crash plan (chaos.plan.kill_leader_campaign) the
    leader dies at the targeted WAL record; a FailoverController detects
    the death, promotes the follower (byte-identical chain head is the
    promotion invariant — replaying ONLY the unshipped WAL tail), and
    the churn load generator resumes from the WAL position on the
    promoted twin for the remaining cycles. The summary's fold_chain is
    then byte-identical to an uninterrupted run's, and rto_s measures
    death-detection to promoted end-to-end.
    """
    from tpusim.api.snapshot import synthetic_cluster
    from tpusim.backends import Placement, bind_pod
    from tpusim.chaos.engine import ProcessCrash
    from tpusim.stream import ChurnLoadGen, StreamPersistence, StreamSession
    from tpusim.stream.loadgen import DEFAULT_LABEL_UNIVERSE
    from tpusim.stream.replicate import (
        FailoverController,
        FollowerTwin,
        WalShipper,
    )

    if checkpoint_dir is None:
        raise ValueError("run_replicated_stream needs checkpoint_dir: the "
                         "WAL is the replication substrate")

    def make_snap():
        # fresh object graphs per consumer: the leader, the follower, and
        # each load generator must never share mutable node/pod objects
        if snapshot is not None:
            return snapshot
        snap = synthetic_cluster(num_nodes)
        if policy is not None or label_churn or taint_churn:
            for i, node in enumerate(snap.nodes):
                node.metadata.labels.update(
                    {k: vals[i % len(vals)]
                     for k, vals in DEFAULT_LABEL_UNIVERSE.items()})
        return snap

    crash_events = []
    if chaos_plan is not None:
        chaos_plan.validate()
        if not chaos_plan.host_sections_empty() \
                or not chaos_plan.device.empty():
            raise ValueError(
                "run_replicated_stream takes process_crash sections only "
                "(kill-the-leader campaigns); churn arrives through the "
                "load generator and device faults through the breaker arm")
        crash_events = chaos_plan.crash_events()

    follower = FollowerTwin(make_snap(), provider=provider, policy=policy,
                            always_restage=always_restage)
    leader = StreamSession(make_snap(), provider=provider, policy=policy,
                           always_restage=always_restage)
    persist = StreamPersistence(checkpoint_dir,
                                checkpoint_every=checkpoint_every,
                                fsync_every=fsync_every)
    shipper = WalShipper(persist, follower.address)
    leader.attach_persistence(persist)
    if crash_events:
        ev = crash_events[0]
        persist.arm_crash(ev.at, ev.target)

    gen = ChurnLoadGen(make_snap(), seed=seed, arrivals=arrivals,
                       evict_fraction=evict_fraction,
                       node_flap_every=node_flap_every,
                       label_churn=label_churn, taint_churn=taint_churn,
                       gang_size=gang_size, gang_count=gang_count)

    latencies: List[float] = []
    crashed: Optional[str] = None
    lag_at_crash = 0
    leader_alive = [True]

    def run_cycles(session, g, start: int, skip_events: int) -> None:
        skip = skip_events
        for cycle in range(start, cycles):
            if pipeline:
                g.note_bound(session.poll_placed())
            evs = g.events(cycle)
            if skip:
                evs = evs[skip:]
                skip = 0
            session.apply_events(evs)
            batch = g.batch()
            t0 = perf_counter()
            prev = (session.schedule_pipelined(batch) if pipeline
                    else session.schedule(batch))
            latencies.append(perf_counter() - t0)
            if not pipeline:
                g.note_bound(prev)
        if pipeline:
            session.flush()

    t_start = perf_counter()
    try:
        run_cycles(leader, gen, 0, 0)
    except ProcessCrash as exc:
        crashed = str(exc)
        leader_alive[0] = False
        lag_at_crash = shipper.lag_records()
        # leader death: nothing drains — the wire keeps only what it
        # already carried, the durable WAL keeps everything
        shipper.close(drain=False)
        persist.close()

    out: dict = {
        "cycles": cycles, "pipeline": pipeline,
        "crashed": crashed is not None, "crash_detail": crashed,
        "promoted": False, "divergence": None,
    }
    if crashed is None:
        # steady-state shipping backlog: records appended but not yet
        # acked the instant the producer stops (drain clears it, so
        # sample before)
        lag_at_loop_end = shipper.lag_records()
        drained = shipper.drain(drain_timeout)
        shipper.close(drain=False)
        out.update({
            "drained": drained,
            "lag_at_loop_end": lag_at_loop_end,
            "fold_chain": persist.chain,
            "follower_chain": follower.chain,
            "follower_chain_matches": follower.chain == persist.chain,
            "wal_records": persist.wal_records,
            "checkpoints": persist.checkpoints,
            "decisions": persist.decisions,
            "scheduled": persist.scheduled,
            "applied_records": follower.wal_records_applied,
            "divergence": follower.diverged,
            "restages": dict(leader.restage_counts),
            "follower_restages": dict(follower.session.restage_counts),
        })
        follower.stop()
        final_persist = persist
    else:
        controller = FailoverController(
            lambda: leader_alive[0], [follower], checkpoint_dir,
            interval_s=0.005, misses=2,
            checkpoint_every=checkpoint_every, fsync_every=fsync_every,
            leader_was_alive=True)
        promoted, preport = controller.run(timeout=30.0)
        resume_cycle = preport.resume_cycle
        # resume the churn load generator from the WAL position: batch()
        # and note_bound() draw no rng, so replaying the committed prefix
        # with binds fed back from the replicated/replayed bind maps
        # leaves the rng and the bound population exactly where the dead
        # leader had them (the recover_stream_session fast-forward)
        gen2 = ChurnLoadGen(make_snap(), seed=seed, arrivals=arrivals,
                            evict_fraction=evict_fraction,
                            node_flap_every=node_flap_every,
                            label_churn=label_churn,
                            taint_churn=taint_churn,
                            gang_size=gang_size, gang_count=gang_count)
        for c in range(resume_cycle):
            gen2.events(c)
            by_key = {p.key(): p for p in gen2.batch()}
            gen2.note_bound([
                Placement(pod=bind_pod(by_key[k], node), node_name=node)
                for k, node in promoted.bound_by_cycle.get(c, [])
                if k in by_key])
        skip_events = promoted.events_applied.get(resume_cycle, 0)
        run_cycles(promoted.session, gen2, resume_cycle, skip_events)
        final_persist = promoted.persist
        final_persist.close()
        out.update({
            "promoted": True,
            "rto_s": preport.rto_s,
            "resume_cycle": resume_cycle,
            "replayed_records": preport.tail_records,
            "applied_records": preport.applied_records,
            "recomputed_cycles": list(preport.recomputed),
            "settled_live_cycles": list(preport.settled_live),
            "promotion_violations": list(preport.violations),
            "lag_at_crash": lag_at_crash,
            "fold_chain": final_persist.chain,
            "wal_records": final_persist.wal_records,
            "checkpoints": final_persist.checkpoints,
            "decisions": final_persist.decisions,
            "scheduled": final_persist.scheduled,
            "divergence": promoted.diverged,
            "restages": dict(leader.restage_counts),
            "follower_restages": dict(promoted.session.restage_counts),
        })
    elapsed = perf_counter() - t_start
    latencies.sort()
    out["elapsed_s"] = elapsed
    out["nodes"] = num_nodes
    out["p50_cycle_ms"] = (latencies[len(latencies) // 2] * 1e3
                           if latencies else 0.0)
    return out

"""SimulationPod spec parsing and expansion.

Reference: cmd/app/options/options.go:73-99 — decode a YAML/JSON list of
SimulationPod{name,pod,num}, expand each entry ``num`` times with a fresh UUID
used as both name and UID, labels replaced by {"SimulationName": entry name},
and the namespace forced to the CLI namespace.
"""

from __future__ import annotations

import json
import uuid
from typing import List

import yaml

from tpusim.api.types import DEFAULT_NAMESPACE, Pod, SimulationPod


def load_simulation_pods(path: str) -> List[SimulationPod]:
    with open(path) as f:
        text = f.read()
    return parse_simulation_pods(text)


def parse_simulation_pods(text: str) -> List[SimulationPod]:
    """Accepts YAML or JSON (YAMLOrJSONDecoder parity)."""
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        data = yaml.safe_load(text)
    if data is None:
        return []
    if not isinstance(data, list):
        raise ValueError("podspec must be a list of {name, pod, num} entries")
    return [SimulationPod.from_obj(o) for o in data]


def expand_simulation_pods(
    sim_pods: List[SimulationPod],
    namespace: str = DEFAULT_NAMESPACE,
    deterministic_ids: bool = False,
) -> List[Pod]:
    """Expand each SimulationPod ``num`` times (options.go:88-97).

    ``deterministic_ids`` swaps the UUIDs for stable "<name>-<i>" identifiers so
    tests and parity harnesses get reproducible pod names.
    """
    pods: List[Pod] = []
    for sp in sim_pods:
        for i in range(sp.num):
            pod = sp.pod.copy()
            uid = f"{sp.name}-{i}" if deterministic_ids else str(uuid.uuid4())
            pod.metadata.uid = uid
            pod.metadata.name = uid
            pod.metadata.labels = {"SimulationName": sp.name}
            pod.metadata.namespace = namespace
            pods.append(pod)
    return pods

"""Live-cluster snapshotter: a minimal kube-apiserver REST client.

Reference: cmd/app/server.go:71-118 — the ONLY real network I/O in the whole
reference program is the initial checkpoint: List Running pods (FieldSelector
"status.phase=Running", namespace-scoped when --namespace is set) plus all
nodes, via a client built from kubeconfig (clientcmd.BuildConfigFromFlags) or,
when the CC_INCLUSTER env var is present, the in-cluster service-account
config (server.go:62-69). Everything after the snapshot is in-process.

Implemented on the stdlib (urllib + ssl) so the offline build carries no
client-go analog dependency; kubeconfig parsing covers the fields the
reference path exercises: current-context resolution, cluster server +
certificate-authority(-data) + insecure-skip-tls-verify, and user token /
tokenFile / client-certificate(-data) / client-key(-data) / basic auth.
"""

from __future__ import annotations

import base64
import json
import os
import ssl
import tempfile
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import yaml

from tpusim.api.snapshot import ClusterSnapshot
from tpusim.api.types import Node, Pod

SERVICE_ACCOUNT_ROOT = "/var/run/secrets/kubernetes.io/serviceaccount"
RUNNING_FIELD_SELECTOR = "status.phase=Running"


class KubeConfigError(ValueError):
    pass


@dataclass
class KubeClientConfig:
    server: str
    ca_file: str = ""
    insecure_skip_tls_verify: bool = False
    token: str = ""
    username: str = ""
    password: str = ""
    client_cert_file: str = ""
    client_key_file: str = ""
    _temp_files: list = field(default_factory=list, repr=False)

    def cleanup(self) -> None:
        """Unlink materialized *-data temp files (may hold client TLS keys);
        safe to call repeatedly. Call after the client's TLS context is built
        — ssl reads the files eagerly."""
        for path in self._temp_files:
            try:
                os.unlink(path)
            except OSError:
                pass
        self._temp_files.clear()


def _materialize(data_b64: str, suffix: str, cfg: KubeClientConfig) -> str:
    """Write a base64 *-data kubeconfig field to a temp file (ssl wants paths)."""
    f = tempfile.NamedTemporaryFile(suffix=suffix, delete=False)
    f.write(base64.b64decode(data_b64))
    f.close()
    cfg._temp_files.append(f.name)
    return f.name


def _by_name(items, name: str, kind: str) -> dict:
    for item in items or []:
        if item.get("name") == name:
            return item.get(kind) or {}
    raise KubeConfigError(f"kubeconfig: no {kind} named {name!r}")


def load_kubeconfig(path: str, context: str = "") -> KubeClientConfig:
    """clientcmd.BuildConfigFromFlags("", path) essentials: resolve
    current-context (or `context`) to a (cluster, user) pair."""
    try:
        with open(path) as f:
            doc = yaml.safe_load(f)
    except yaml.YAMLError as exc:
        raise KubeConfigError(f"kubeconfig: invalid YAML: {exc}") from exc
    if not isinstance(doc, dict):
        raise KubeConfigError("kubeconfig: not a mapping")
    ctx_name = context or doc.get("current-context") or ""
    if not ctx_name:
        raise KubeConfigError("kubeconfig: no current-context")
    ctx = _by_name(doc.get("contexts"), ctx_name, "context")
    cluster = _by_name(doc.get("clusters"), ctx.get("cluster", ""), "cluster")
    user = _by_name(doc.get("users"), ctx.get("user", ""), "user") \
        if ctx.get("user") else {}

    server = cluster.get("server") or ""
    if not server:
        raise KubeConfigError("kubeconfig: cluster has no server")
    cfg = KubeClientConfig(
        server=server.rstrip("/"),
        insecure_skip_tls_verify=bool(cluster.get("insecure-skip-tls-verify")))
    try:
        if cluster.get("certificate-authority"):
            cfg.ca_file = cluster["certificate-authority"]
        elif cluster.get("certificate-authority-data"):
            cfg.ca_file = _materialize(cluster["certificate-authority-data"],
                                       ".crt", cfg)
        token = user.get("token") or ""
        if not token and user.get("tokenFile"):
            with open(user["tokenFile"]) as f:
                token = f.read().strip()
        cfg.token = token
        cfg.username = user.get("username") or ""
        cfg.password = user.get("password") or ""
        if user.get("client-certificate"):
            cfg.client_cert_file = user["client-certificate"]
        elif user.get("client-certificate-data"):
            cfg.client_cert_file = _materialize(user["client-certificate-data"],
                                                ".crt", cfg)
        if user.get("client-key"):
            cfg.client_key_file = user["client-key"]
        elif user.get("client-key-data"):
            cfg.client_key_file = _materialize(user["client-key-data"], ".key",
                                               cfg)
    except Exception:
        # materialized *-data temp files can hold client TLS keys; don't
        # leave them behind when the rest of the config fails to parse
        cfg.cleanup()
        raise
    return cfg


def in_cluster_config(root: str = SERVICE_ACCOUNT_ROOT,
                      environ=os.environ) -> KubeClientConfig:
    """rest.InClusterConfig: server from KUBERNETES_SERVICE_HOST/PORT, bearer
    token + CA from the mounted service account."""
    host = environ.get("KUBERNETES_SERVICE_HOST", "")
    port = environ.get("KUBERNETES_SERVICE_PORT", "")
    if not host or not port:
        raise KubeConfigError(
            "in-cluster config: KUBERNETES_SERVICE_HOST/PORT not set")
    token_path = os.path.join(root, "token")
    ca_path = os.path.join(root, "ca.crt")
    with open(token_path) as f:
        token = f.read().strip()
    # net.JoinHostPort semantics: bracket IPv6 hosts
    if ":" in host and not host.startswith("["):
        host = f"[{host}]"
    return KubeClientConfig(server=f"https://{host}:{port}", token=token,
                            ca_file=ca_path if os.path.exists(ca_path) else "")


class KubeClient:
    def __init__(self, config: KubeClientConfig, timeout: float = 30.0):
        self.config = config
        self.timeout = timeout
        self._ssl_context: Optional[ssl.SSLContext] = None
        if config.server.startswith("https"):
            if config.insecure_skip_tls_verify:
                ctx = ssl.create_default_context()
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            else:
                ctx = ssl.create_default_context(
                    cafile=config.ca_file or None)
            if config.client_cert_file:
                ctx.load_cert_chain(config.client_cert_file,
                                    config.client_key_file or None)
            self._ssl_context = ctx

    def _get(self, path: str, query: Optional[dict] = None) -> dict:
        url = self.config.server + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        req = urllib.request.Request(url)
        req.add_header("Accept", "application/json")
        if self.config.token:
            req.add_header("Authorization", f"Bearer {self.config.token}")
        elif self.config.username:
            basic = base64.b64encode(
                f"{self.config.username}:{self.config.password}".encode()
            ).decode()
            req.add_header("Authorization", f"Basic {basic}")
        with urllib.request.urlopen(req, timeout=self.timeout,
                                    context=self._ssl_context) as resp:
            return json.load(resp)

    def list_running_pods(self, namespace: str = "") -> List[Pod]:
        """Pods(namespace).List(FieldSelector: status.phase=Running)
        (server.go:105); empty namespace = all namespaces."""
        path = (f"/api/v1/namespaces/{urllib.parse.quote(namespace)}/pods"
                if namespace else "/api/v1/pods")
        body = self._get(path, {"fieldSelector": RUNNING_FIELD_SELECTOR})
        return [Pod.from_obj(item) for item in body.get("items") or []]

    def list_nodes(self) -> List[Node]:
        """Nodes().List() (server.go:111)."""
        body = self._get("/api/v1/nodes")
        return [Node.from_obj(item) for item in body.get("items") or []]

    def get_configmap(self, namespace: str, name: str) -> dict:
        """ConfigMaps(namespace).Get(name) — the live scheduler-policy source
        (simulator.go:402-406). Returns the raw ConfigMap object."""
        path = (f"/api/v1/namespaces/{urllib.parse.quote(namespace)}"
                f"/configmaps/{urllib.parse.quote(name)}")
        return self._get(path)


def get_checkpoints(client: KubeClient,
                    namespace: str = "") -> Tuple[List[Pod], List[Node]]:
    """The reference's getCheckpoints (server.go:104-118)."""
    return client.list_running_pods(namespace), client.list_nodes()


def snapshot_from_cluster(kubeconfig: str = "", namespace: str = "",
                          context: str = "") -> ClusterSnapshot:
    """Build a simulation snapshot from a live cluster: kubeconfig when given,
    else the in-cluster service-account config (the CC_INCLUSTER path,
    server.go:62-69)."""
    config = (load_kubeconfig(kubeconfig, context) if kubeconfig
              else in_cluster_config())
    try:
        client = KubeClient(config)
    finally:
        config.cleanup()
    pods, nodes = get_checkpoints(client, namespace)
    return ClusterSnapshot(nodes=nodes, pods=pods)

"""Kubernetes resource.Quantity semantics.

The reference engine does all resource arithmetic on int64s extracted from
`resource.Quantity` (vendor/k8s.io/apimachinery/pkg/api/resource): CPU via
``MilliValue()`` (rounded up to the nearest milli-core) and everything else via
``Value()`` (rounded up to the nearest whole unit). This module reproduces the
parsing grammar (sign, decimal digits, optional fraction, and a binary-SI /
decimal-SI / decimal-exponent suffix) and the two integer views, using exact
Fraction arithmetic so "100m", "0.1", and "1e-1" all agree.
"""

from __future__ import annotations

from fractions import Fraction
from functools import lru_cache
import re

_BINARY_SUFFIXES = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}

_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)"
    r"(?:(?P<exp>[eE][+-]?\d+)|(?P<suffix>[A-Za-z]{0,2}))$"
)


class Quantity:
    """Immutable exact quantity with k8s Value()/MilliValue() views."""

    __slots__ = ("_frac", "_text", "_value", "_milli")

    def __init__(self, value, text: str | None = None):
        if isinstance(value, Quantity):
            self._frac = value._frac
            self._text = text if text is not None else value._text
        elif isinstance(value, Fraction):
            self._frac = value
            self._text = text
        elif isinstance(value, (int, float, str)):
            q = parse_quantity(value)
            self._frac = q._frac
            self._text = text if text is not None else q._text
        else:
            raise TypeError(f"cannot build Quantity from {type(value)}")
        # integer views are lazily computed once: the engine reads them per
        # pod per scheduling pass, and Fraction math is the host-compile
        # hot path at 100k+ pods
        self._value = None
        self._milli = None

    # --- integer views (reference: resource.Quantity.Value/MilliValue) ---

    def value(self) -> int:
        """Round up to the nearest integer (k8s Value())."""
        if self._value is None:
            self._value = _ceil(self._frac)
        return self._value

    def milli_value(self) -> int:
        """Round up to the nearest 1/1000 (k8s MilliValue())."""
        if self._milli is None:
            self._milli = _ceil(self._frac * 1000)
        return self._milli

    def is_zero(self) -> bool:
        return self._frac == 0

    @property
    def fraction(self) -> Fraction:
        return self._frac

    # --- arithmetic ---

    def __add__(self, other: "Quantity") -> "Quantity":
        return Quantity(self._frac + _as_frac(other))

    def __sub__(self, other: "Quantity") -> "Quantity":
        return Quantity(self._frac - _as_frac(other))

    def __neg__(self) -> "Quantity":
        return Quantity(-self._frac)

    def __eq__(self, other) -> bool:
        return isinstance(other, (Quantity, int, Fraction)) and self._frac == _as_frac(other)

    def __lt__(self, other) -> bool:
        return self._frac < _as_frac(other)

    def __le__(self, other) -> bool:
        return self._frac <= _as_frac(other)

    def __hash__(self):
        return hash(self._frac)

    # --- printing (canonical-ish; keeps original text when available) ---

    def __str__(self) -> str:
        if self._text is not None:
            return self._text
        return format_quantity(self._frac)

    def __repr__(self) -> str:
        return f"Quantity({str(self)!r})"


def _as_frac(x) -> Fraction:
    if isinstance(x, Quantity):
        return x._frac
    if isinstance(x, (int, Fraction)):
        return Fraction(x)
    raise TypeError(f"cannot compare Quantity with {type(x)}")


def _ceil(f: Fraction) -> int:
    """k8s Value()/MilliValue() round away from zero (resource/math.go), so
    fractional negatives get more negative: -0.5 -> -1."""
    if f.numerator >= 0:
        return -((-f.numerator) // f.denominator)
    return f.numerator // f.denominator


def parse_quantity(s) -> Quantity:
    """Parse a k8s quantity literal (str) or bare number (int/float).
    String parses are memoized — workloads repeat a handful of literals
    across 100k+ pods, and Quantity is immutable so sharing is safe."""
    if isinstance(s, Quantity):
        return s
    if isinstance(s, int):
        return Quantity(Fraction(s), text=str(s))
    if isinstance(s, float):
        return Quantity(Fraction(str(s)), text=None)
    return _parse_str(str(s))


@lru_cache(maxsize=65536)
def _parse_str(text: str) -> Quantity:
    text = text.strip()
    m = _QUANTITY_RE.match(text)
    if not m:
        raise ValueError(f"invalid quantity: {text!r}")
    num = Fraction(m.group("num"))
    if m.group("sign") == "-":
        num = -num
    exp = m.group("exp")
    if exp:
        e = int(exp[1:])
        num *= Fraction(10) ** e
    else:
        suffix = m.group("suffix") or ""
        if suffix in _BINARY_SUFFIXES:
            num *= _BINARY_SUFFIXES[suffix]
        elif suffix in _DECIMAL_SUFFIXES:
            num *= _DECIMAL_SUFFIXES[suffix]
        else:
            raise ValueError(f"invalid quantity suffix: {text!r}")
    return Quantity(num, text=text)


def format_quantity(f: Fraction) -> str:
    """Canonical decimal-SI-ish formatting, good enough for reports."""
    if f.denominator == 1:
        n = f.numerator
        for suffix in ("E", "P", "T", "G", "M", "k"):
            factor = _DECIMAL_SUFFIXES[suffix]
            if n != 0 and Fraction(n) % factor == 0 and abs(n) >= factor:
                return f"{n // int(factor)}{suffix}"
        return str(n)
    milli = f * 1000
    if milli.denominator == 1:
        return f"{milli.numerator}m"
    return str(float(f))


def milli_value(v) -> int:
    """MilliValue of a quantity literal (None -> 0)."""
    if v is None:
        return 0
    return parse_quantity(v).milli_value()


def int_value(v) -> int:
    """Value of a quantity literal (None -> 0)."""
    if v is None:
        return 0
    return parse_quantity(v).value()

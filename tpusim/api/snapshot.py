"""Cluster snapshots: file checkpoints and synthetic generators.

Reference: pkg/main.go:147-179 (pods.json / nodes.json checkpoint readers) and
pkg/main.go:189-231 (createSamplePods / newSampleNode synthetic generators).
The file format is a JSON list of v1 objects, as produced by a live-cluster
List call — Running pods + all nodes (cmd/app/server.go:104-118).
"""

from __future__ import annotations

from dataclasses import dataclass, field
import json
from typing import List, Optional

from tpusim.api.types import (
    LABEL_HOSTNAME,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    Service,
    StorageClass,
)


@dataclass
class ClusterSnapshot:
    """A frozen cluster state: the simulator's 'checkpoint' (SURVEY.md §5)."""

    nodes: List[Node] = field(default_factory=list)
    pods: List[Pod] = field(default_factory=list)  # already-scheduled (Running) pods
    services: List[Service] = field(default_factory=list)
    pvs: List[PersistentVolume] = field(default_factory=list)
    pvcs: List[PersistentVolumeClaim] = field(default_factory=list)
    storage_classes: List[StorageClass] = field(default_factory=list)

    def to_obj(self) -> dict:
        o = {
            "nodes": [n.to_obj() for n in self.nodes],
            "pods": [p.to_obj() for p in self.pods],
            "services": [s.to_obj() for s in self.services],
        }
        if self.pvs:
            o["persistentVolumes"] = [pv.to_obj() for pv in self.pvs]
        if self.pvcs:
            o["persistentVolumeClaims"] = [pvc.to_obj() for pvc in self.pvcs]
        if self.storage_classes:
            o["storageClasses"] = [sc.to_obj() for sc in self.storage_classes]
        return o

    @classmethod
    def from_obj(cls, o: dict) -> "ClusterSnapshot":
        return cls(
            nodes=[Node.from_obj(n) for n in o.get("nodes") or []],
            pods=[Pod.from_obj(p) for p in o.get("pods") or []],
            services=[Service.from_obj(s) for s in o.get("services") or []],
            pvs=[PersistentVolume.from_obj(v)
                 for v in o.get("persistentVolumes") or []],
            pvcs=[PersistentVolumeClaim.from_obj(v)
                  for v in o.get("persistentVolumeClaims") or []],
            storage_classes=[StorageClass.from_obj(v)
                             for v in o.get("storageClasses") or []],
        )

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_obj(), f)

    @classmethod
    def load(cls, path: str) -> "ClusterSnapshot":
        with open(path) as f:
            return cls.from_obj(json.load(f))


def load_pods_checkpoint(path: str) -> List[Pod]:
    """Reference: pkg/main.go:147-162 (getPodsCheckPoint from pods.json).

    Accepts either a bare JSON list of pods or a v1 List envelope {"items": [...]}.
    """
    with open(path) as f:
        data = json.load(f)
    items = data["items"] if isinstance(data, dict) else data
    return [Pod.from_obj(p) for p in items]


def load_nodes_checkpoint(path: str) -> List[Node]:
    """Reference: pkg/main.go:164-179 (getNodeCheckPoint from nodes.json)."""
    with open(path) as f:
        data = json.load(f)
    items = data["items"] if isinstance(data, dict) else data
    return [Node.from_obj(n) for n in items]


# ---------------------------------------------------------------------------
# synthetic generators
# ---------------------------------------------------------------------------


def make_node(
    name: str,
    milli_cpu: int = 4000,
    memory: int = 16 * 1024**3,
    pods: int = 110,
    gpus: int = 0,
    labels: Optional[dict] = None,
    taints: Optional[list] = None,
    unschedulable: bool = False,
    ready: bool = True,
    scalars: Optional[dict] = None,
) -> Node:
    """Build a schedulable node fixture (reference: pkg/main.go:200-231 newSampleNode)."""
    cpu = f"{milli_cpu}m"
    obj = {
        "metadata": {"name": name, "labels": {LABEL_HOSTNAME: name, **(labels or {})}},
        "spec": {},
        "status": {
            "capacity": {"cpu": cpu, "memory": str(memory), "pods": str(pods)},
            "allocatable": {"cpu": cpu, "memory": str(memory), "pods": str(pods)},
            "conditions": [{"type": "Ready", "status": "True" if ready else "False"}],
        },
    }
    if gpus:
        obj["status"]["capacity"]["alpha.kubernetes.io/nvidia-gpu"] = str(gpus)
        obj["status"]["allocatable"]["alpha.kubernetes.io/nvidia-gpu"] = str(gpus)
    for res, qty in (scalars or {}).items():
        obj["status"]["capacity"][res] = str(qty)
        obj["status"]["allocatable"][res] = str(qty)
    if unschedulable:
        obj["spec"]["unschedulable"] = True
    if taints:
        obj["spec"]["taints"] = taints
    return Node.from_obj(obj)


def make_pod(
    name: str,
    milli_cpu: int = 0,
    memory: int = 0,
    gpus: int = 0,
    namespace: str = "default",
    node_name: str = "",
    phase: str = "",
    labels: Optional[dict] = None,
    node_selector: Optional[dict] = None,
    tolerations: Optional[list] = None,
    affinity: Optional[dict] = None,
    volumes: Optional[list] = None,
    scalars: Optional[dict] = None,
) -> Pod:
    """Build a pod fixture (reference: pkg/main.go:189-198 newSamplePod)."""
    requests = {}
    if milli_cpu:
        requests["cpu"] = f"{milli_cpu}m"
    if memory:
        requests["memory"] = str(memory)
    if gpus:
        requests["alpha.kubernetes.io/nvidia-gpu"] = str(gpus)
    for res, qty in (scalars or {}).items():
        requests[res] = str(qty)
    obj = {
        "metadata": {"name": name, "namespace": namespace, "uid": name,
                     "labels": labels or {}},
        "spec": {"containers": [{"name": "c", "resources": {"requests": requests}}]},
        "status": {},
    }
    if node_name:
        obj["spec"]["nodeName"] = node_name
    if phase:
        obj["status"]["phase"] = phase
    if node_selector:
        obj["spec"]["nodeSelector"] = node_selector
    if tolerations:
        obj["spec"]["tolerations"] = tolerations
    if affinity:
        obj["spec"]["affinity"] = affinity
    if volumes:
        obj["spec"]["volumes"] = volumes
    return Pod.from_obj(obj)


def make_pod_volume(name: str, source: Optional[dict] = None,
                    pvc: str = "") -> dict:
    """A pod .spec.volumes entry: either a direct source dict (e.g.
    {"gcePersistentDisk": {...}}) or a PVC reference."""
    obj: dict = {"name": name}
    if pvc:
        obj["persistentVolumeClaim"] = {"claimName": pvc}
    if source:
        obj.update(source)
    return obj


def make_pv(
    name: str,
    storage: str = "1Gi",
    labels: Optional[dict] = None,
    storage_class: str = "",
    access_modes: Optional[list] = None,
    claim_ref: Optional[dict] = None,
    node_affinity_terms: Optional[list] = None,
    source: Optional[dict] = None,
) -> PersistentVolume:
    """Build a PersistentVolume fixture."""
    spec: dict = {"capacity": {"storage": storage}}
    if storage_class:
        spec["storageClassName"] = storage_class
    if access_modes:
        spec["accessModes"] = list(access_modes)
    if claim_ref:
        spec["claimRef"] = dict(claim_ref)
    if node_affinity_terms is not None:
        spec["nodeAffinity"] = {
            "required": {"nodeSelectorTerms": node_affinity_terms}}
    if source:
        spec.update(source)
    return PersistentVolume.from_obj(
        {"metadata": {"name": name, "labels": labels or {}}, "spec": spec})


def make_pvc(
    name: str,
    namespace: str = "default",
    volume_name: str = "",
    storage: str = "1Gi",
    storage_class: Optional[str] = None,
    access_modes: Optional[list] = None,
    selector: Optional[dict] = None,
) -> PersistentVolumeClaim:
    """Build a PersistentVolumeClaim fixture; volume_name='' = unbound."""
    spec: dict = {"resources": {"requests": {"storage": storage}}}
    if volume_name:
        spec["volumeName"] = volume_name
    if storage_class is not None:
        spec["storageClassName"] = storage_class
    if access_modes:
        spec["accessModes"] = list(access_modes)
    if selector:
        spec["selector"] = dict(selector)
    return PersistentVolumeClaim.from_obj(
        {"metadata": {"name": name, "namespace": namespace}, "spec": spec})


def make_storage_class(name: str, binding_mode: str = "") -> StorageClass:
    obj: dict = {"metadata": {"name": name}}
    if binding_mode:
        obj["volumeBindingMode"] = binding_mode
    return StorageClass.from_obj(obj)


def synthetic_cluster(
    num_nodes: int,
    milli_cpu: int = 4000,
    memory: int = 16 * 1024**3,
    pods_per_node: int = 110,
    name_prefix: str = "node",
) -> ClusterSnapshot:
    """Homogeneous synthetic cluster (BASELINE.md config 2 shape)."""
    nodes = [make_node(f"{name_prefix}-{i}", milli_cpu=milli_cpu, memory=memory,
                       pods=pods_per_node) for i in range(num_nodes)]
    return ClusterSnapshot(nodes=nodes)

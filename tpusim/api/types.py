"""Domain model: the subset of the Kubernetes object model the scheduling engine reads.

Mirrors the reference's typed API layer (reference: pkg/api/api.go:27-83) plus the
v1 fields consumed by the vendored engine (requests/limits, init containers,
nodeSelector/affinity, tolerations, host ports, node conditions, taints,
allocatable, labels — see SURVEY.md §7 step 1). Objects round-trip to/from
k8s-style camelCase dicts so `pods.json` / `nodes.json` checkpoints
(reference: pkg/main.go:147-179) load unchanged.
"""

from __future__ import annotations

import copy as _copy_mod
import enum
import re
from dataclasses import dataclass, field, is_dataclass
from typing import Any, Optional

from tpusim.api.quantity import Quantity, parse_quantity

# v1 resource names as of the reference's vintage (k8s ~1.10):
# v1.ResourceNvidiaGPU = "alpha.kubernetes.io/nvidia-gpu".
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_NVIDIA_GPU = "alpha.kubernetes.io/nvidia-gpu"
RESOURCE_EPHEMERAL_STORAGE = "ephemeral-storage"
RESOURCE_PODS = "pods"

DEFAULT_NAMESPACE = "default"

# effects
TAINT_NO_SCHEDULE = "NoSchedule"
TAINT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_NO_EXECUTE = "NoExecute"

# well-known topology labels (kubeletapis.LabelHostname / LabelZoneFailureDomain /
# LabelZoneRegion at the reference's vintage)
LABEL_HOSTNAME = "kubernetes.io/hostname"
LABEL_ZONE_FAILURE_DOMAIN = "failure-domain.beta.kubernetes.io/zone"
LABEL_ZONE_REGION = "failure-domain.beta.kubernetes.io/region"


def is_scalar_resource_name(name: str) -> bool:
    """Reference: v1helper.IsScalarResourceName = extended or hugepages.

    Extended means namespaced outside the default namespace: the name contains a
    "/", does not contain "kubernetes.io/", and is not "requests."-prefixed
    (quota notation; v1helper.IsExtendedResourceName). Used at
    predicates.go:687-696, 755-767. "alpha.kubernetes.io/nvidia-gpu" is
    therefore NOT scalar — GPUs are tracked as a first-class field.
    """
    extended = ("/" in name and "kubernetes.io/" not in name
                and not name.startswith("requests."))
    return extended or name.startswith("hugepages-")


class ResourceType(enum.Enum):
    """Reference: pkg/api/api.go:27-58 (ResourceType enum + ObjectType mapping)."""

    PODS = "pods"
    PERSISTENT_VOLUMES = "persistentvolumes"
    NODES = "nodes"
    SERVICES = "services"
    PERSISTENT_VOLUME_CLAIMS = "persistentvolumeclaims"
    STORAGE_CLASSES = "storageclasses"

    @staticmethod
    def from_string(s: str) -> "ResourceType":
        """Reference: pkg/api/api.go:60-77 (StringToResourceType)."""
        try:
            return ResourceType(s.lower())
        except ValueError:
            raise ValueError(f"unknown resource type: {s}")

    def object_type(self):
        return _RESOURCE_OBJECT_TYPES[self]


def _get(d: dict, *keys, default=None):
    for k in keys:
        if d is None:
            return default
        d = d.get(k)
    return d if d is not None else default


# ---------------------------------------------------------------------------
# metadata
# ---------------------------------------------------------------------------


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False

    @classmethod
    def from_obj(cls, o: dict) -> "OwnerReference":
        return cls(
            api_version=o.get("apiVersion", ""),
            kind=o.get("kind", ""),
            name=o.get("name", ""),
            uid=o.get("uid", ""),
            controller=bool(o.get("controller", False)),
        )

    def to_obj(self) -> dict:
        o = {"apiVersion": self.api_version, "kind": self.kind, "name": self.name, "uid": self.uid}
        if self.controller:
            o["controller"] = True
        return o


@dataclass
class ObjectMeta:
    """namespace stays "" when absent (cluster-scoped objects like Node never
    get one); namespaced accessors default it to DEFAULT_NAMESPACE at read time
    so checkpoints round-trip byte-identical."""

    name: str = ""
    namespace: str = ""
    uid: str = ""
    labels: dict = field(default_factory=dict)
    annotations: dict = field(default_factory=dict)
    owner_references: list = field(default_factory=list)

    @classmethod
    def from_obj(cls, o: Optional[dict]) -> "ObjectMeta":
        o = o or {}
        return cls(
            name=o.get("name", ""),
            namespace=o.get("namespace") or "",
            uid=o.get("uid", ""),
            labels=dict(o.get("labels") or {}),
            annotations=dict(o.get("annotations") or {}),
            owner_references=[OwnerReference.from_obj(r) for r in o.get("ownerReferences") or []],
        )

    def to_obj(self) -> dict:
        o: dict[str, Any] = {"name": self.name}
        if self.namespace:
            o["namespace"] = self.namespace
        if self.uid:
            o["uid"] = self.uid
        if self.labels:
            o["labels"] = dict(self.labels)
        if self.annotations:
            o["annotations"] = dict(self.annotations)
        if self.owner_references:
            o["ownerReferences"] = [r.to_obj() for r in self.owner_references]
        return o

    def controller_ref(self) -> Optional[OwnerReference]:
        for r in self.owner_references:
            if r.controller:
                return r
        return None


# ---------------------------------------------------------------------------
# selectors / affinity
# ---------------------------------------------------------------------------

# apimachinery validation (labels.NewRequirement -> util/validation):
# label values are <= 63 chars, empty or alphanumeric with -_. inside;
# label keys are [prefix/]name with a DNS-1123-subdomain prefix and a
# 63-char qualified name part
_LABEL_VALUE_RE = re.compile(r"^(([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9])?$")
_LABEL_NAME_RE = re.compile(r"^([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9]$")
_DNS1123_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?"
                         r"(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$")


def _valid_label_value(v: str) -> bool:
    return len(v) <= 63 and bool(_LABEL_VALUE_RE.match(v))


def _valid_label_key(k: str) -> bool:
    prefix, sep, name = k.rpartition("/")
    if sep and not prefix:
        return False  # IsQualifiedName: "prefix part must be non-empty"
    if prefix and (len(prefix) > 253 or not _DNS1123_RE.match(prefix)):
        return False
    return 0 < len(name) <= 63 and bool(_LABEL_NAME_RE.match(name))


_INT64_RE = re.compile(r"^[+-]?[0-9]+$")
_INT64_MIN, _INT64_MAX = -(1 << 63), (1 << 63) - 1


def _parse_int64(s: str) -> Optional[int]:
    """Go strconv.ParseInt(s, 10, 64): plain decimal digits only (no
    underscores, no whitespace) within int64 range."""
    if not _INT64_RE.match(s):
        return None
    v = int(s)
    return v if _INT64_MIN <= v <= _INT64_MAX else None


@dataclass
class NodeSelectorRequirement:
    key: str = ""
    operator: str = "In"  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: list = field(default_factory=list)

    @classmethod
    def from_obj(cls, o: dict) -> "NodeSelectorRequirement":
        return cls(key=o.get("key", ""), operator=o.get("operator", "In"),
                   values=list(o.get("values") or []))

    def to_obj(self) -> dict:
        o = {"key": self.key, "operator": self.operator}
        if self.values:
            o["values"] = list(self.values)
        return o

    def invalid(self) -> bool:
        """labels.NewRequirement validation (apimachinery selector.go:134-169)
        as invoked by NodeSelectorRequirementsAsSelector: a requirement that
        would fail construction (bad operator, wrong value count, non-integer
        Gt/Lt value, invalid label key/value) errors the WHOLE selector."""
        if not _valid_label_key(self.key):
            return True
        if self.operator in ("In", "NotIn"):
            if not self.values:
                return True
        elif self.operator in ("Exists", "DoesNotExist"):
            if self.values:
                return True
        elif self.operator in ("Gt", "Lt"):
            if len(self.values) != 1:
                return True
            if _parse_int64(self.values[0]) is None:
                return True
        else:
            return True
        return any(not _valid_label_value(v) for v in self.values)

    def matches(self, labels: dict) -> bool:
        """apimachinery labels.Requirement.Matches semantics (for a
        requirement that passed `invalid()` validation)."""
        has = self.key in labels
        if self.operator == "In":
            return has and labels[self.key] in self.values
        if self.operator == "NotIn":
            return (not has) or labels[self.key] not in self.values
        if self.operator == "Exists":
            return has
        if self.operator == "DoesNotExist":
            return not has
        if self.operator in ("Gt", "Lt"):
            if not has or len(self.values) != 1:
                return False
            lhs = _parse_int64(labels[self.key])
            rhs = _parse_int64(self.values[0])
            if lhs is None or rhs is None:
                return False
            return lhs > rhs if self.operator == "Gt" else lhs < rhs
        return False


@dataclass
class NodeSelectorTerm:
    match_expressions: list = field(default_factory=list)

    @classmethod
    def from_obj(cls, o: dict) -> "NodeSelectorTerm":
        return cls(match_expressions=[NodeSelectorRequirement.from_obj(e)
                                      for e in o.get("matchExpressions") or []])

    def to_obj(self) -> dict:
        return {"matchExpressions": [e.to_obj() for e in self.match_expressions]}

    def match_result(self, labels: dict) -> Optional[bool]:
        """NodeSelectorRequirementsAsSelector semantics (v1 helpers.go:215):
        None when any requirement fails validation (the selector errors),
        False for an empty term ([] builds labels.Nothing()), else the ANDed
        requirement match."""
        if not self.match_expressions:
            return False
        if any(e.invalid() for e in self.match_expressions):
            return None
        return all(e.matches(labels) for e in self.match_expressions)

    def matches(self, labels: dict) -> bool:
        """match_result collapsed: errors and the empty-term Nothing()
        selector both count as no-match (the preferred-affinity scorer path;
        the required path needs the tri-state — predicates.go:778-792)."""
        return self.match_result(labels) is True


@dataclass
class PreferredSchedulingTerm:
    weight: int = 0
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)

    @classmethod
    def from_obj(cls, o: dict) -> "PreferredSchedulingTerm":
        return cls(weight=int(o.get("weight", 0)),
                   preference=NodeSelectorTerm.from_obj(o.get("preference") or {}))

    def to_obj(self) -> dict:
        return {"weight": self.weight, "preference": self.preference.to_obj()}


@dataclass
class NodeAffinity:
    # requiredDuringSchedulingIgnoredDuringExecution: list of terms (ORed)
    required_terms: Optional[list] = None
    preferred: list = field(default_factory=list)

    @classmethod
    def from_obj(cls, o: dict) -> "NodeAffinity":
        req = o.get("requiredDuringSchedulingIgnoredDuringExecution")
        return cls(
            required_terms=None if req is None else [
                NodeSelectorTerm.from_obj(t) for t in req.get("nodeSelectorTerms") or []],
            preferred=[PreferredSchedulingTerm.from_obj(t)
                       for t in o.get("preferredDuringSchedulingIgnoredDuringExecution") or []],
        )

    def to_obj(self) -> dict:
        o: dict[str, Any] = {}
        if self.required_terms is not None:
            o["requiredDuringSchedulingIgnoredDuringExecution"] = {
                "nodeSelectorTerms": [t.to_obj() for t in self.required_terms]}
        if self.preferred:
            o["preferredDuringSchedulingIgnoredDuringExecution"] = [
                t.to_obj() for t in self.preferred]
        return o


@dataclass
class LabelSelector:
    """A nil selector in Go is represented as None here (matches nothing at call
    sites); an empty LabelSelector() matches everything."""

    match_labels: dict = field(default_factory=dict)
    match_expressions: list = field(default_factory=list)

    @classmethod
    def from_obj(cls, o: Optional[dict]) -> Optional["LabelSelector"]:
        if o is None:
            return None
        return cls(match_labels=dict(o.get("matchLabels") or {}),
                   match_expressions=[NodeSelectorRequirement.from_obj(e)
                                      for e in o.get("matchExpressions") or []])

    def to_obj(self) -> dict:
        o: dict[str, Any] = {}
        if self.match_labels:
            o["matchLabels"] = dict(self.match_labels)
        if self.match_expressions:
            o["matchExpressions"] = [e.to_obj() for e in self.match_expressions]
        return o

    def matches(self, labels: dict) -> bool:
        """metav1.LabelSelectorAsSelector: matchLabels AND matchExpressions.
        An empty selector matches all objects."""
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        return all(e.matches(labels) for e in self.match_expressions)


@dataclass
class PodAffinityTerm:
    label_selector: Optional[LabelSelector] = None
    namespaces: list = field(default_factory=list)
    topology_key: str = ""

    @classmethod
    def from_obj(cls, o: dict) -> "PodAffinityTerm":
        return cls(label_selector=LabelSelector.from_obj(o.get("labelSelector")),
                   namespaces=list(o.get("namespaces") or []),
                   topology_key=o.get("topologyKey", ""))

    def to_obj(self) -> dict:
        o: dict[str, Any] = {}
        if self.label_selector is not None:
            o["labelSelector"] = self.label_selector.to_obj()
        if self.namespaces:
            o["namespaces"] = list(self.namespaces)
        if self.topology_key:
            o["topologyKey"] = self.topology_key
        return o


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 0
    pod_affinity_term: PodAffinityTerm = field(default_factory=PodAffinityTerm)

    @classmethod
    def from_obj(cls, o: dict) -> "WeightedPodAffinityTerm":
        return cls(weight=int(o.get("weight", 0)),
                   pod_affinity_term=PodAffinityTerm.from_obj(o.get("podAffinityTerm") or {}))

    def to_obj(self) -> dict:
        return {"weight": self.weight, "podAffinityTerm": self.pod_affinity_term.to_obj()}


@dataclass
class PodAffinity:
    required: list = field(default_factory=list)  # list[PodAffinityTerm]
    preferred: list = field(default_factory=list)  # list[WeightedPodAffinityTerm]

    @classmethod
    def from_obj(cls, o: dict) -> "PodAffinity":
        return cls(
            required=[PodAffinityTerm.from_obj(t)
                      for t in o.get("requiredDuringSchedulingIgnoredDuringExecution") or []],
            preferred=[WeightedPodAffinityTerm.from_obj(t)
                       for t in o.get("preferredDuringSchedulingIgnoredDuringExecution") or []],
        )

    def to_obj(self) -> dict:
        o: dict[str, Any] = {}
        if self.required:
            o["requiredDuringSchedulingIgnoredDuringExecution"] = [t.to_obj() for t in self.required]
        if self.preferred:
            o["preferredDuringSchedulingIgnoredDuringExecution"] = [t.to_obj() for t in self.preferred]
        return o


class PodAntiAffinity(PodAffinity):
    pass


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None

    @classmethod
    def from_obj(cls, o: Optional[dict]) -> Optional["Affinity"]:
        if not o:
            return None
        return cls(
            node_affinity=NodeAffinity.from_obj(o["nodeAffinity"]) if o.get("nodeAffinity") else None,
            pod_affinity=PodAffinity.from_obj(o["podAffinity"]) if o.get("podAffinity") else None,
            pod_anti_affinity=PodAntiAffinity.from_obj(o["podAntiAffinity"]) if o.get("podAntiAffinity") else None,
        )

    def to_obj(self) -> dict:
        o: dict[str, Any] = {}
        if self.node_affinity is not None:
            o["nodeAffinity"] = self.node_affinity.to_obj()
        if self.pod_affinity is not None:
            o["podAffinity"] = self.pod_affinity.to_obj()
        if self.pod_anti_affinity is not None:
            o["podAntiAffinity"] = self.pod_anti_affinity.to_obj()
        return o


# ---------------------------------------------------------------------------
# taints / tolerations
# ---------------------------------------------------------------------------


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = ""  # NoSchedule | PreferNoSchedule | NoExecute

    @classmethod
    def from_obj(cls, o: dict) -> "Taint":
        return cls(key=o.get("key", ""), value=o.get("value", ""), effect=o.get("effect", ""))

    def to_obj(self) -> dict:
        return {"key": self.key, "value": self.value, "effect": self.effect}


@dataclass
class Toleration:
    key: str = ""
    operator: str = ""  # "" (== Equal) | Equal | Exists
    value: str = ""
    effect: str = ""
    toleration_seconds: Optional[int] = None

    @classmethod
    def from_obj(cls, o: dict) -> "Toleration":
        return cls(key=o.get("key", ""), operator=o.get("operator", ""),
                   value=o.get("value", ""), effect=o.get("effect", ""),
                   toleration_seconds=o.get("tolerationSeconds"))

    def to_obj(self) -> dict:
        o: dict[str, Any] = {}
        if self.key:
            o["key"] = self.key
        if self.operator:
            o["operator"] = self.operator
        if self.value:
            o["value"] = self.value
        if self.effect:
            o["effect"] = self.effect
        if self.toleration_seconds is not None:
            o["tolerationSeconds"] = self.toleration_seconds
        return o

    def tolerates(self, taint: Taint) -> bool:
        """v1.Toleration.ToleratesTaint semantics: empty effect matches all effects,
        empty key with Exists matches all taints."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator in ("", "Equal"):
            return self.value == taint.value
        if self.operator == "Exists":
            return True
        return False


def tolerations_tolerate_taint(tolerations: list, taint: Taint) -> bool:
    """v1helper.TolerationsTolerateTaint."""
    return any(t.tolerates(taint) for t in tolerations)


def find_matching_untolerated_taint(taints: list, tolerations: list, taint_filter) -> Optional[Taint]:
    """v1helper.FindMatchingUntoleratedTaint: first filtered taint not tolerated."""
    for taint in taints:
        if not taint_filter(taint):
            continue
        if not tolerations_tolerate_taint(tolerations, taint):
            return taint
    return None


# ---------------------------------------------------------------------------
# pods
# ---------------------------------------------------------------------------


@dataclass
class ContainerPort:
    host_ip: str = ""
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"

    @classmethod
    def from_obj(cls, o: dict) -> "ContainerPort":
        return cls(host_ip=o.get("hostIP", ""), host_port=int(o.get("hostPort", 0) or 0),
                   container_port=int(o.get("containerPort", 0) or 0),
                   protocol=o.get("protocol") or "TCP")

    def to_obj(self) -> dict:
        o: dict[str, Any] = {}
        if self.host_ip:
            o["hostIP"] = self.host_ip
        if self.host_port:
            o["hostPort"] = self.host_port
        if self.container_port:
            o["containerPort"] = self.container_port
        if self.protocol != "TCP":
            o["protocol"] = self.protocol
        return o


_COPY_ATOMIC = (str, int, float, bool, bytes, type(None), Quantity)


def _structural_copy(o):
    """Deep-copy a dataclass/list/dict graph, sharing atomic leaves.
    Quantity counts as atomic: its only writes are idempotent lazy memos."""
    if isinstance(o, _COPY_ATOMIC):
        return o
    if isinstance(o, list):
        return [_structural_copy(x) for x in o]
    if isinstance(o, dict):
        return {k: _structural_copy(v) for k, v in o.items()}
    if is_dataclass(o):
        new = object.__new__(type(o))
        d = new.__dict__
        for k, v in o.__dict__.items():
            d[k] = _structural_copy(v)
        return new
    return _copy_mod.deepcopy(o)


def _parse_resource_list(o: Optional[dict]) -> dict:
    return {k: parse_quantity(v) for k, v in (o or {}).items()}


def _resource_list_to_obj(rl: dict) -> dict:
    return {k: str(v) for k, v in rl.items()}


@dataclass
class Container:
    name: str = ""
    image: str = ""
    requests: dict = field(default_factory=dict)  # resource name -> Quantity
    limits: dict = field(default_factory=dict)
    ports: list = field(default_factory=list)

    @classmethod
    def from_obj(cls, o: dict) -> "Container":
        res = o.get("resources") or {}
        return cls(
            name=o.get("name", ""),
            image=o.get("image", ""),
            requests=_parse_resource_list(res.get("requests")),
            limits=_parse_resource_list(res.get("limits")),
            ports=[ContainerPort.from_obj(p) for p in o.get("ports") or []],
        )

    def to_obj(self) -> dict:
        o: dict[str, Any] = {}
        if self.name:
            o["name"] = self.name
        if self.image:
            o["image"] = self.image
        res: dict[str, Any] = {}
        if self.requests:
            res["requests"] = _resource_list_to_obj(self.requests)
        if self.limits:
            res["limits"] = _resource_list_to_obj(self.limits)
        if res:
            o["resources"] = res
        if self.ports:
            o["ports"] = [p.to_obj() for p in self.ports]
        return o


@dataclass
class Volume:
    """A pod volume. Only the sources the scheduler reads are typed
    (NoDiskConflict: GCE PD / AWS EBS / RBD / ISCSI, predicates.go:220-276;
    MaxPDVolumeCount filters + PVC references, predicates.go:361-460); the
    raw object is kept for round-trip."""

    name: str = ""
    raw: dict = field(default_factory=dict)

    @classmethod
    def from_obj(cls, o: dict) -> "Volume":
        return cls(name=o.get("name", ""), raw=dict(o))

    def to_obj(self) -> dict:
        return dict(self.raw)

    @property
    def gce_persistent_disk(self) -> Optional[dict]:
        return self.raw.get("gcePersistentDisk")

    @property
    def aws_elastic_block_store(self) -> Optional[dict]:
        return self.raw.get("awsElasticBlockStore")

    @property
    def rbd(self) -> Optional[dict]:
        return self.raw.get("rbd")

    @property
    def iscsi(self) -> Optional[dict]:
        return self.raw.get("iscsi")

    @property
    def azure_disk(self) -> Optional[dict]:
        return self.raw.get("azureDisk")

    @property
    def pvc_name(self) -> Optional[str]:
        """persistentVolumeClaim.claimName; None when not a PVC volume."""
        pvc = self.raw.get("persistentVolumeClaim")
        if pvc is None:
            return None
        return pvc.get("claimName", "")


@dataclass
class PodSpec:
    containers: list = field(default_factory=list)
    init_containers: list = field(default_factory=list)
    node_name: str = ""
    node_selector: Optional[dict] = None
    affinity: Optional[Affinity] = None
    tolerations: list = field(default_factory=list)
    scheduler_name: str = ""
    priority: Optional[int] = None
    host_network: bool = False
    volumes: list = field(default_factory=list)

    @classmethod
    def from_obj(cls, o: Optional[dict]) -> "PodSpec":
        o = o or {}
        return cls(
            containers=[Container.from_obj(c) for c in o.get("containers") or []],
            init_containers=[Container.from_obj(c) for c in o.get("initContainers") or []],
            node_name=o.get("nodeName", ""),
            node_selector=dict(o["nodeSelector"]) if o.get("nodeSelector") else None,
            affinity=Affinity.from_obj(o.get("affinity")),
            tolerations=[Toleration.from_obj(t) for t in o.get("tolerations") or []],
            scheduler_name=o.get("schedulerName", ""),
            priority=o.get("priority"),
            host_network=bool(o.get("hostNetwork", False)),
            volumes=[Volume.from_obj(v) for v in o.get("volumes") or []],
        )

    def to_obj(self) -> dict:
        o: dict[str, Any] = {"containers": [c.to_obj() for c in self.containers]}
        if self.init_containers:
            o["initContainers"] = [c.to_obj() for c in self.init_containers]
        if self.node_name:
            o["nodeName"] = self.node_name
        if self.node_selector is not None:
            o["nodeSelector"] = dict(self.node_selector)
        if self.affinity is not None:
            o["affinity"] = self.affinity.to_obj()
        if self.tolerations:
            o["tolerations"] = [t.to_obj() for t in self.tolerations]
        if self.scheduler_name:
            o["schedulerName"] = self.scheduler_name
        if self.priority is not None:
            o["priority"] = self.priority
        if self.host_network:
            o["hostNetwork"] = True
        if self.volumes:
            o["volumes"] = [v.to_obj() for v in self.volumes]
        return o


@dataclass
class PodCondition:
    type: str = ""
    status: str = ""
    reason: str = ""
    message: str = ""

    @classmethod
    def from_obj(cls, o: dict) -> "PodCondition":
        return cls(type=o.get("type", ""), status=o.get("status", ""),
                   reason=o.get("reason", ""), message=o.get("message", ""))

    def to_obj(self) -> dict:
        o = {"type": self.type, "status": self.status}
        if self.reason:
            o["reason"] = self.reason
        if self.message:
            o["message"] = self.message
        return o


@dataclass
class PodStatus:
    phase: str = ""
    conditions: list = field(default_factory=list)
    reason: str = ""
    message: str = ""
    nominated_node_name: str = ""

    @classmethod
    def from_obj(cls, o: Optional[dict]) -> "PodStatus":
        o = o or {}
        return cls(phase=o.get("phase", ""),
                   conditions=[PodCondition.from_obj(c) for c in o.get("conditions") or []],
                   reason=o.get("reason", ""), message=o.get("message", ""),
                   nominated_node_name=o.get("nominatedNodeName", ""))

    def to_obj(self) -> dict:
        o: dict[str, Any] = {}
        if self.phase:
            o["phase"] = self.phase
        if self.conditions:
            o["conditions"] = [c.to_obj() for c in self.conditions]
        if self.reason:
            o["reason"] = self.reason
        if self.message:
            o["message"] = self.message
        if self.nominated_node_name:
            o["nominatedNodeName"] = self.nominated_node_name
        return o


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    kind = "Pod"

    @classmethod
    def from_obj(cls, o: dict) -> "Pod":
        return cls(metadata=ObjectMeta.from_obj(o.get("metadata")),
                   spec=PodSpec.from_obj(o.get("spec")),
                   status=PodStatus.from_obj(o.get("status")))

    def to_obj(self) -> dict:
        return {"apiVersion": "v1", "kind": "Pod", "metadata": self.metadata.to_obj(),
                "spec": self.spec.to_obj(), "status": self.status.to_obj()}

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace or DEFAULT_NAMESPACE

    def key(self) -> str:
        """cache.MetaNamespaceKeyFunc."""
        return f"{self.namespace}/{self.metadata.name}"

    def copy(self) -> "Pod":
        """Independent deep copy. Structural (field-graph) rather than a
        to_obj/from_obj round-trip: the simulator's Bind seam copies every
        bound pod, and re-serializing + re-parsing quantities dominated the
        mirror cost of the preemption hybrid. Quantity leaves are immutable
        (lazy memo only) and shared; equality and scheduling behavior match
        the round-trip for any pod built through from_obj."""
        return _structural_copy(self)


# ---------------------------------------------------------------------------
# nodes
# ---------------------------------------------------------------------------


@dataclass
class NodeCondition:
    type: str = ""
    status: str = ""

    @classmethod
    def from_obj(cls, o: dict) -> "NodeCondition":
        return cls(type=o.get("type", ""), status=o.get("status", ""))

    def to_obj(self) -> dict:
        return {"type": self.type, "status": self.status}


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: list = field(default_factory=list)

    @classmethod
    def from_obj(cls, o: Optional[dict]) -> "NodeSpec":
        o = o or {}
        return cls(unschedulable=bool(o.get("unschedulable", False)),
                   taints=[Taint.from_obj(t) for t in o.get("taints") or []])

    def to_obj(self) -> dict:
        o: dict[str, Any] = {}
        if self.unschedulable:
            o["unschedulable"] = True
        if self.taints:
            o["taints"] = [t.to_obj() for t in self.taints]
        return o


@dataclass
class ContainerImage:
    names: list = field(default_factory=list)
    size_bytes: int = 0

    @classmethod
    def from_obj(cls, o: dict) -> "ContainerImage":
        return cls(names=list(o.get("names") or []), size_bytes=int(o.get("sizeBytes", 0) or 0))

    def to_obj(self) -> dict:
        return {"names": list(self.names), "sizeBytes": self.size_bytes}


@dataclass
class NodeStatus:
    capacity: dict = field(default_factory=dict)
    allocatable: dict = field(default_factory=dict)
    conditions: list = field(default_factory=list)
    images: list = field(default_factory=list)

    @classmethod
    def from_obj(cls, o: Optional[dict]) -> "NodeStatus":
        o = o or {}
        return cls(capacity=_parse_resource_list(o.get("capacity")),
                   allocatable=_parse_resource_list(o.get("allocatable")),
                   conditions=[NodeCondition.from_obj(c) for c in o.get("conditions") or []],
                   images=[ContainerImage.from_obj(i) for i in o.get("images") or []])

    def to_obj(self) -> dict:
        o: dict[str, Any] = {}
        if self.capacity:
            o["capacity"] = _resource_list_to_obj(self.capacity)
        if self.allocatable:
            o["allocatable"] = _resource_list_to_obj(self.allocatable)
        if self.conditions:
            o["conditions"] = [c.to_obj() for c in self.conditions]
        if self.images:
            o["images"] = [i.to_obj() for i in self.images]
        return o


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    kind = "Node"

    @classmethod
    def from_obj(cls, o: dict) -> "Node":
        return cls(metadata=ObjectMeta.from_obj(o.get("metadata")),
                   spec=NodeSpec.from_obj(o.get("spec")),
                   status=NodeStatus.from_obj(o.get("status")))

    def to_obj(self) -> dict:
        return {"apiVersion": "v1", "kind": "Node", "metadata": self.metadata.to_obj(),
                "spec": self.spec.to_obj(), "status": self.status.to_obj()}

    @property
    def name(self) -> str:
        return self.metadata.name

    def key(self) -> str:
        return self.metadata.name

    def copy(self) -> "Node":
        return Node.from_obj(self.to_obj())


# ---------------------------------------------------------------------------
# other resource kinds (modelled thinly; the simulator stores but rarely reads them)
# ---------------------------------------------------------------------------


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: dict = field(default_factory=dict)

    kind = "Service"

    @classmethod
    def from_obj(cls, o: dict) -> "Service":
        return cls(metadata=ObjectMeta.from_obj(o.get("metadata")),
                   selector=dict(_get(o, "spec", "selector", default={}) or {}))

    def to_obj(self) -> dict:
        return {"apiVersion": "v1", "kind": "Service", "metadata": self.metadata.to_obj(),
                "spec": {"selector": dict(self.selector)}}

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace or DEFAULT_NAMESPACE

    def key(self) -> str:
        return f"{self.namespace}/{self.metadata.name}"


# beta annotation override for StorageClassName (v1helper
# GetPersistentVolume(Claim)Class reads it before the spec field)
ANN_STORAGE_CLASS = "volume.beta.kubernetes.io/storage-class"
# alpha node-affinity annotation on PVs (volumehelper checkAlphaNodeAffinity)
ANN_ALPHA_NODE_AFFINITY = "volume.alpha.kubernetes.io/node-affinity"

VOLUME_BINDING_IMMEDIATE = "Immediate"
VOLUME_BINDING_WAIT = "WaitForFirstConsumer"


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    raw: dict = field(default_factory=dict)

    kind = "PersistentVolume"

    @classmethod
    def from_obj(cls, o: dict) -> "PersistentVolume":
        return cls(metadata=ObjectMeta.from_obj(o.get("metadata")), raw=dict(o))

    def to_obj(self) -> dict:
        o = dict(self.raw)
        o.setdefault("apiVersion", "v1")
        o["kind"] = "PersistentVolume"
        o["metadata"] = self.metadata.to_obj()
        return o

    @property
    def name(self) -> str:
        return self.metadata.name

    def key(self) -> str:
        return self.metadata.name

    def copy(self) -> "PersistentVolume":
        """Deep copy: raw holds nested spec dicts, and the binder's assume path
        mutates spec.claimRef — a shallow dict() would alias the original."""
        import copy as _copy

        return PersistentVolume(metadata=ObjectMeta.from_obj(self.metadata.to_obj()),
                                raw=_copy.deepcopy(self.raw))

    # --- typed spec accessors the scheduler reads ---

    @property
    def spec_raw(self) -> dict:
        return self.raw.get("spec") or {}

    @property
    def capacity_storage(self) -> int:
        """spec.capacity.storage in bytes (Quantity.Value semantics); memoized —
        it sits in the per-pod-per-node CheckVolumeBinding hot path."""
        v = self.__dict__.get("_capacity_storage")
        if v is None:
            qty = (self.spec_raw.get("capacity") or {}).get("storage")
            v = 0 if qty is None else parse_quantity(str(qty)).value()
            self.__dict__["_capacity_storage"] = v
        return v

    @property
    def claim_ref(self) -> Optional[dict]:
        return self.spec_raw.get("claimRef")

    @property
    def access_modes(self) -> list:
        return list(self.spec_raw.get("accessModes") or [])

    @property
    def volume_mode(self) -> str:
        return self.spec_raw.get("volumeMode") or "Filesystem"

    @property
    def storage_class_name(self) -> str:
        """v1helper.GetPersistentVolumeClass: beta annotation FIRST, then the
        spec field (helpers.go:398-405)."""
        if ANN_STORAGE_CLASS in self.metadata.annotations:
            return self.metadata.annotations[ANN_STORAGE_CLASS]
        return self.spec_raw.get("storageClassName") or ""

    @property
    def gce_persistent_disk(self) -> Optional[dict]:
        return self.spec_raw.get("gcePersistentDisk")

    @property
    def aws_elastic_block_store(self) -> Optional[dict]:
        return self.spec_raw.get("awsElasticBlockStore")

    @property
    def azure_disk(self) -> Optional[dict]:
        return self.spec_raw.get("azureDisk")

    def node_affinity_terms(self) -> Optional[list]:
        """Required node-affinity terms (ORed NodeSelectorTerm list) from
        spec.nodeAffinity.required, else the alpha annotation
        (volumeutil.CheckNodeAffinity reads both). None = unconstrained.
        Memoized — evaluated per pod per node by CheckVolumeBinding."""
        if "_node_affinity_terms" in self.__dict__:
            return self.__dict__["_node_affinity_terms"]
        na = self.spec_raw.get("nodeAffinity")
        req = (na or {}).get("required")
        if req is None:
            ann = self.metadata.annotations.get(ANN_ALPHA_NODE_AFFINITY)
            if ann:
                import json as _json

                affinity = _json.loads(ann)
                req = affinity.get("requiredDuringSchedulingIgnoredDuringExecution")
        terms = None if req is None else [
            NodeSelectorTerm.from_obj(t)
            for t in req.get("nodeSelectorTerms") or []]
        self.__dict__["_node_affinity_terms"] = terms
        return terms


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    raw: dict = field(default_factory=dict)

    kind = "PersistentVolumeClaim"

    @classmethod
    def from_obj(cls, o: dict) -> "PersistentVolumeClaim":
        return cls(metadata=ObjectMeta.from_obj(o.get("metadata")), raw=dict(o))

    def to_obj(self) -> dict:
        o = dict(self.raw)
        o.setdefault("apiVersion", "v1")
        o["kind"] = "PersistentVolumeClaim"
        o["metadata"] = self.metadata.to_obj()
        return o

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace or DEFAULT_NAMESPACE

    def key(self) -> str:
        return f"{self.namespace}/{self.metadata.name}"

    def copy(self) -> "PersistentVolumeClaim":
        return PersistentVolumeClaim.from_obj(self.to_obj())

    # --- typed spec accessors the scheduler reads ---

    @property
    def spec_raw(self) -> dict:
        return self.raw.get("spec") or {}

    @property
    def volume_name(self) -> str:
        return self.spec_raw.get("volumeName") or ""

    @property
    def access_modes(self) -> list:
        return list(self.spec_raw.get("accessModes") or [])

    @property
    def volume_mode(self) -> str:
        return self.spec_raw.get("volumeMode") or "Filesystem"

    @property
    def storage_class_name(self) -> str:
        """v1helper.GetPersistentVolumeClaimClass: beta annotation FIRST, then
        the spec field, which may be an explicit "" (helpers.go:409-420)."""
        if ANN_STORAGE_CLASS in self.metadata.annotations:
            return self.metadata.annotations[ANN_STORAGE_CLASS]
        sc = self.spec_raw.get("storageClassName")
        return sc if sc is not None else ""

    @property
    def request_storage(self) -> int:
        v = self.__dict__.get("_request_storage")
        if v is None:
            qty = ((self.spec_raw.get("resources") or {}).get("requests")
                   or {}).get("storage")
            v = 0 if qty is None else parse_quantity(str(qty)).value()
            self.__dict__["_request_storage"] = v
        return v

    def selector(self) -> Optional["LabelSelector"]:
        if "_selector" not in self.__dict__:
            self.__dict__["_selector"] = LabelSelector.from_obj(
                self.spec_raw.get("selector"))
        return self.__dict__["_selector"]


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    raw: dict = field(default_factory=dict)

    kind = "StorageClass"

    @classmethod
    def from_obj(cls, o: dict) -> "StorageClass":
        return cls(metadata=ObjectMeta.from_obj(o.get("metadata")), raw=dict(o))

    def to_obj(self) -> dict:
        o = dict(self.raw)
        o.setdefault("apiVersion", "storage.k8s.io/v1")
        o["kind"] = "StorageClass"
        o["metadata"] = self.metadata.to_obj()
        return o

    @property
    def name(self) -> str:
        return self.metadata.name

    def key(self) -> str:
        return self.metadata.name

    @property
    def volume_binding_mode(self) -> Optional[str]:
        """None when unset — shouldDelayBinding errors on a gate-on class with
        no mode (pv_controller.go:290-292)."""
        return self.raw.get("volumeBindingMode")


@dataclass
class PodDisruptionBudget:
    """Minimal policy/v1beta1 PDB: the scheduler reads namespace, selector, and
    status.disruptionsAllowed (preemption victim filtering,
    core/generic_scheduler.go filterPodsWithPDBViolation)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    disruptions_allowed: int = 0

    kind = "PodDisruptionBudget"

    @classmethod
    def from_obj(cls, o: dict) -> "PodDisruptionBudget":
        return cls(metadata=ObjectMeta.from_obj(o.get("metadata")),
                   selector=LabelSelector.from_obj(_get(o, "spec", "selector")),
                   disruptions_allowed=int(
                       _get(o, "status", "disruptionsAllowed", default=0) or 0))

    def to_obj(self) -> dict:
        o: dict[str, Any] = {"apiVersion": "policy/v1beta1",
                             "kind": "PodDisruptionBudget",
                             "metadata": self.metadata.to_obj(), "spec": {},
                             "status": {"disruptionsAllowed": self.disruptions_allowed}}
        if self.selector is not None:
            o["spec"]["selector"] = self.selector.to_obj()
        return o

    @property
    def namespace(self) -> str:
        return self.metadata.namespace or DEFAULT_NAMESPACE

    def key(self) -> str:
        return f"{self.namespace}/{self.metadata.name}"


_RESOURCE_OBJECT_TYPES = {
    ResourceType.PODS: Pod,
    ResourceType.PERSISTENT_VOLUMES: PersistentVolume,
    ResourceType.NODES: Node,
    ResourceType.SERVICES: Service,
    ResourceType.PERSISTENT_VOLUME_CLAIMS: PersistentVolumeClaim,
    ResourceType.STORAGE_CLASSES: StorageClass,
}


# ---------------------------------------------------------------------------
# SimulationPod (podspec schema)
# ---------------------------------------------------------------------------


@dataclass
class SimulationPod:
    """Reference: pkg/api/api.go:79-83 — {name, pod, num} podspec entries."""

    name: str = ""
    pod: Pod = field(default_factory=Pod)
    num: int = 1

    @classmethod
    def from_obj(cls, o: dict) -> "SimulationPod":
        return cls(name=o.get("name", ""), pod=Pod.from_obj(o.get("pod") or {}),
                   num=int(o.get("num", 1)))

    def to_obj(self) -> dict:
        return {"name": self.name, "pod": self.pod.to_obj(), "num": self.num}

#!/usr/bin/env python
"""Merge per-process flight-recorder traces into one Perfetto timeline
(ISSUE 20).

Each tpusim process (``tpusim stream --trace-out``, ``tpusim follow
--trace-out``, ``tpusim serve --trace-out``) writes its own Chrome
trace_event JSON with timestamps relative to ITS recorder epoch. This
tool joins them:

- **pid remap.** Every input file gets a distinct pid (its position in
  the argument list), with its process_name metadata preserved — two
  processes that both report os.getpid()==1234 stay distinct tracks.
- **clock alignment.** The replication hello handshake pins anchors in
  both files' ``otherData.anchors``: the follower stamps
  ``hello_tx_us`` (its reading when the hello left) and the leader pins
  ``peer_clk_us`` (that same reading, received) next to
  ``peer_clk_rx_us`` (the leader's own reading at receive). Aligning
  the follower means shifting its timeline by
  ``peer_clk_rx_us - hello_tx_us`` into the leader's clock domain —
  exact up to the one-way socket latency, which on a localhost pair is
  well under the span widths being read. Files with no anchors (the
  serve front door) are left unshifted relative to the FIRST input,
  which is therefore conventionally the leader.
- **flow joining needs no work**: flow events match on (cat, id) which
  are process-independent, so once the files share a document Perfetto
  renders the leader->follower ``wal:ship`` arrows and the serve
  enqueue/bucket arrows as one connected graph.

Usage:
    python tools/trace_merge.py leader.json follower.json serve.json \
        -o merged.json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional


def load_trace(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if "traceEvents" not in doc or not isinstance(doc["traceEvents"], list):
        raise ValueError(f"{path}: not a Chrome trace_event document "
                         "(no traceEvents list)")
    return doc


def shift_for(doc: Dict[str, Any], leader: Dict[str, Any]) -> float:
    """Microseconds to ADD to this document's timestamps to land it in
    the leader's clock domain; 0.0 when no handshake anchors pair up."""
    anchors = (doc.get("otherData") or {}).get("anchors") or {}
    leader_anchors = (leader.get("otherData") or {}).get("anchors") or {}
    tx = anchors.get("hello_tx_us")
    rx = leader_anchors.get("peer_clk_rx_us")
    peer = leader_anchors.get("peer_clk_us")
    if tx is None or rx is None or peer is None:
        return 0.0
    if abs(float(peer) - float(tx)) > 1e-3:
        # the leader heard a DIFFERENT hello than this file sent (a
        # reconnect, or a third process): the recorded peer reading is
        # authoritative for which send it pairs with
        tx = float(peer)
    return float(rx) - float(tx)


def merge(docs: List[Dict[str, Any]],
          names: Optional[List[str]] = None) -> Dict[str, Any]:
    """One merged Chrome trace: docs[0] is the reference clock domain."""
    events: List[Dict[str, Any]] = []
    leader = docs[0]
    for pid, doc in enumerate(docs, start=1):
        shift = shift_for(doc, leader) if doc is not leader else 0.0
        pname = (doc.get("otherData") or {}).get("process_name")
        for ev in doc["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) + shift, 3)
            if ev.get("ph") == "M" and ev.get("name") == "process_name" \
                    and names is not None and pid - 1 < len(names):
                ev = dict(ev, args={"name": names[pid - 1]})
            events.append(ev)
        if pname and not any(
                e.get("ph") == "M" and e.get("name") == "process_name"
                and e.get("pid") == pid for e in events):
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0, "ts": 0.0,
                           "args": {"name": pname}})
    return {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "otherData": {
            "merged_from": len(docs),
            "shifts_us": [0.0] + [round(shift_for(d, leader), 3)
                                  for d in docs[1:]],
        },
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Merge tpusim --trace-out files into one "
                    "Perfetto-loadable timeline (first file = reference "
                    "clock domain, conventionally the leader)")
    parser.add_argument("traces", nargs="+",
                        help="Chrome trace JSON files (leader first)")
    parser.add_argument("-o", "--out", required=True,
                        help="Merged output path")
    parser.add_argument("--name", action="append", default=None,
                        help="Override process name per input "
                             "(repeatable, positional)")
    args = parser.parse_args(argv)
    try:
        docs = [load_trace(p) for p in args.traces]
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"trace-merge: error: {exc}", file=sys.stderr)
        return 2
    merged = merge(docs, names=args.name)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(merged, f, sort_keys=True, separators=(",", ":"))
        f.write("\n")
    flows = sum(1 for e in merged["traceEvents"] if e.get("ph") == "s")
    print(f"trace-merge: {len(args.traces)} files -> {args.out} "
          f"({len(merged['traceEvents'])} events, {flows} flows, "
          f"shifts {merged['otherData']['shifts_us']} us)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

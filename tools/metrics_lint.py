#!/usr/bin/env python
"""Metrics-registry lint (ISSUE 13): naming + registration conventions.

Checks the in-process SchedulerMetrics registry, not grep output, so a
family only reachable through a helper still gets linted. Rules apply to
the `tpusim_*` namespace we own; the `scheduler_*` families reproduce the
reference's metric names verbatim and are grandfathered.

  - every family name registered exactly once
  - names are lowercase [a-z0-9_], no leading/trailing/double underscore
  - counter families end in `_total`
  - non-counter families do NOT end in `_total`
  - histogram families end in a unit suffix (_microseconds / _us /
    _seconds / _bytes) unless explicitly allowlisted as unitless
  - info-style gauges end in `_info`, and only they do
  - gauge families end in a unit suffix (_bytes / _ratio / _seconds /
    _microseconds / _us) unless allowlisted as a unitless count/level
    (ISSUE 14)
  - `_ratio`- and (non-histogram) `_bytes`-suffixed families must be
    gauges — a `_ratio` counter or `_bytes` counter is a modelling bug
  - labeled families may only use label names with a known-finite value
    set (_BOUNDED_LABELS); per-node/per-pod/per-signature labels on
    aggregate families are unbounded-cardinality and belong in the
    /analytics JSON body, not the exposition

Run standalone (`python tools/metrics_lint.py`; exit 1 on findings) or
through tests/test_metrics.py (tier-1).
"""

from __future__ import annotations

import os
import re
import sys
from typing import List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*[a-z0-9]$")
_HIST_UNIT_SUFFIXES = ("_microseconds", "_us", "_seconds", "_bytes")
# unitless-by-design histograms (counts per bucket, not a measured unit)
_UNITLESS_HISTOGRAMS = {"tpusim_serve_batch_occupancy", "tpusim_gang_size"}
_GAUGE_UNIT_SUFFIXES = ("_bytes", "_ratio", "_seconds", "_microseconds",
                        "_us")
# unitless-by-design gauges: dimensionless levels, counts, and rates
_UNITLESS_GAUGES = {
    "tpusim_breaker_state",
    "tpusim_serve_queue_depth",
    "tpusim_stream_pipeline_depth",
    "tpusim_stream_overlap_fraction",
    "tpusim_recovery_wal_records",
    "tpusim_slo_burn_rate",
    "tpusim_cluster_feasible_nodes",
    "tpusim_cluster_nodes",
    "tpusim_hbm_cache_entries",
    # ISSUE 16: mesh shape + per-shard node counts are dimensionless
    "tpusim_shard_count",
    "tpusim_shard_node_occupancy",
    # ISSUE 18: replication lag in records and the shipped sequence
    # cursor are dimensionless counts (the byte/time lags carry units)
    "tpusim_replication_lag_records",
    "tpusim_replication_last_shipped_seq",
    # ISSUE 19: the residency ledger's resident-twin count is dimensionless
    # (the per-tenant byte footprint carries units)
    "tpusim_tenant_resident_twins",
    # ISSUE 20: the /debug/trace ring's event count is dimensionless
    "tpusim_trace_ring_events",
}
# label names whose value sets are finite by construction; anything else
# (node names, pod names, plan signatures) is unbounded cardinality
# ("shard" is bounded by TPUSIM_SHARDS <= the device count)
# ("category" is bounded by the flight recorder's span-category set)
_BOUNDED_LABELS = {"route", "transition", "path", "reason", "kind",
                   "resource", "verdict", "component", "site", "tenant",
                   "shard", "category"}


def lint_registry(registry) -> List[str]:
    """All convention violations in a SchedulerMetrics instance."""
    from tpusim.framework.metrics import (
        Counter,
        Gauge,
        Histogram,
        InfoGauge,
        LabeledCounter,
        LabeledGauge,
        LabeledHistogram,
    )

    problems: List[str] = []
    seen = {}
    for metric in registry._all():
        name = metric.name
        if name in seen:
            problems.append(
                f"{name}: registered more than once "
                f"({type(seen[name]).__name__} and {type(metric).__name__})")
            continue
        seen[name] = metric
        if not name.startswith("tpusim_"):
            continue  # scheduler_* keeps the reference's verbatim names
        if not _NAME_RE.match(name) or "__" in name:
            problems.append(f"{name}: not lowercase [a-z0-9_] "
                            "(or has doubled/edge underscores)")
        is_counter = isinstance(metric, (Counter, LabeledCounter))
        if is_counter and not name.endswith("_total"):
            problems.append(f"{name}: counter families must end in _total")
        if not is_counter and name.endswith("_total"):
            problems.append(f"{name}: only counter families may end in "
                            "_total")
        if isinstance(metric, (Histogram, LabeledHistogram)) \
                and name not in _UNITLESS_HISTOGRAMS \
                and not name.endswith(_HIST_UNIT_SUFFIXES):
            problems.append(
                f"{name}: histogram families need a unit suffix "
                f"({'/'.join(_HIST_UNIT_SUFFIXES)}) or an allowlist entry "
                "in tools/metrics_lint.py")
        if isinstance(metric, InfoGauge) != name.endswith("_info"):
            problems.append(f"{name}: the _info suffix is reserved for "
                            "info-style gauges (and required on them)")
        is_gauge = isinstance(metric, (Gauge, LabeledGauge))
        if is_gauge and name not in _UNITLESS_GAUGES \
                and not name.endswith(_GAUGE_UNIT_SUFFIXES):
            problems.append(
                f"{name}: gauge families need a unit suffix "
                f"({'/'.join(_GAUGE_UNIT_SUFFIXES)}) or an allowlist "
                "entry in tools/metrics_lint.py")
        if name.endswith("_ratio") and not is_gauge:
            problems.append(f"{name}: _ratio families must be gauges")
        if name.endswith("_bytes") and not is_gauge \
                and not isinstance(metric, (Histogram, LabeledHistogram)):
            problems.append(f"{name}: _bytes families must be gauges "
                            "(or histograms)")
        label = getattr(metric, "label", None)
        if label is not None and label not in _BOUNDED_LABELS:
            problems.append(
                f"{name}: label {label!r} is not in the bounded-label "
                "allowlist — per-node/per-pod/per-signature breakdowns "
                "belong in the /analytics JSON body, not the metrics "
                "exposition (add finite-valued labels to _BOUNDED_LABELS)")
    return problems


def main() -> int:
    from tpusim.framework.metrics import SchedulerMetrics

    problems = lint_registry(SchedulerMetrics())
    for problem in problems:
        print(f"metrics-lint: {problem}", file=sys.stderr)
    if problems:
        return 1
    print("metrics-lint: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Stage-0 all-variants TPU smoke: one tiny-shape batch per kernel-variant
class, each checked for placement-hash parity against the XLA scan in the
same process.

The capture runbook (tools/tpu_capture.sh) runs this FIRST: even a
~2-minute healthy tunnel window then certifies that every Pallas
kernel-variant class — base scan, MostRequested scoring, host-ports,
disk-conflict, selector-spreading, volume-zone, inter-pod affinity,
max-PD volume counts, and the policy-residue classes (label-presence
rows + NodeLabel preference, ServiceAffinity first-pod locks,
ImageLocality, NoExecute-taint predicate, alwaysCheckAllPredicates
count-mode) — actually lowers through Mosaic and agrees with
the XLA scan bit-for-bit, plus that the preemption victim-selection
kernel (jaxe/preempt.py) byte-matches the host oracle and that the
streaming runtime's scatter-committed fast path (tpusim/stream)
byte-matches a fresh-compile reference without retracing once warm —
plus that a fully traced replicated fleet (leader -> follower WAL
shipping + a serve batch) exports one lint-clean Perfetto flow graph
without moving a single placement. Shapes are tiny
(<=8 nodes, <=24 pods) so the whole sweep compiles and runs in well
under a minute on a healthy TPU; off-TPU the Pallas kernels auto-select
interpreter mode, so the same script validates on CPU (slower).

Each variant prints one line:

    SMOKE <variant>: OK hash=<sha256[:16]> scheduled=<n>/<total> (<s>s)

and the script ends with `SMOKE COMPLETE: <n> variants, platform=<p>`
(exit 0) or `SMOKE FAILED: ...` (exit 1). TPUSIM_SMOKE_VARIANTS=a,b
restricts the sweep (debugging a single variant class).
"""

import hashlib
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# The sharded variant needs a >= 2 device mesh; a CPU host exposes one
# device unless told otherwise, and the flag only takes effect before
# jax initializes. Real accelerator hosts enumerate hardware devices and
# ignore it. Mirrors tests/conftest.py (which forces 8 for the suite).
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=2")

import numpy as np  # noqa: E402

from tpusim.jaxe import ensure_x64  # noqa: E402

ensure_x64()

from tpusim.api.snapshot import (  # noqa: E402
    ClusterSnapshot,
    make_node,
    make_pod,
    make_pod_volume,
    make_pv,
    make_pvc,
)
from tpusim.api.types import (  # noqa: E402
    LABEL_ZONE_FAILURE_DOMAIN,
    ContainerImage,
    ContainerPort,
    Service,
)
from tpusim.jaxe.fastscan import fast_scan, plan_fast  # noqa: E402
from tpusim.jaxe.kernels import (  # noqa: E402
    carry_init,
    config_for,
    pod_columns_to_device,
    schedule_scan,
    statics_to_device,
)
from tpusim.jaxe.state import NUM_FIXED_BITS, compile_cluster  # noqa: E402


def _service(name, selector):
    return Service.from_obj({"metadata": {"name": name,
                                          "namespace": "default"},
                             "spec": {"selector": selector}})


def _port_pod(name, port, **kw):
    p = make_pod(name, milli_cpu=100, **kw)
    p.spec.containers[0].ports = [ContainerPort.from_obj(
        {"containerPort": port, "hostPort": port})]
    return p


# --- one tiny workload per kernel-variant class -------------------------


def _base():
    """Group-free scan: taints, selectors, pins, preferred node affinity."""
    nodes = [make_node(f"n{i}", milli_cpu=(500, 1000, 2000)[i % 3],
                       memory=(1 + i % 3) * 1024**3, pods=(4, 8, 110)[i % 3],
                       labels={"zone": f"z{i % 3}"},
                       taints=[{"key": "dedicated", "value": "batch",
                                "effect": "NoSchedule"}] if i % 3 == 0
                       else None,
                       unschedulable=(i == 5)) for i in range(8)]
    seeded = [make_pod(f"r{i}", milli_cpu=300, memory=2**28,
                       node_name=f"n{i}", phase="Running") for i in range(4)]
    pods = []
    for i in range(24):
        kw = {}
        if i % 5 == 0:
            kw["tolerations"] = [{"key": "dedicated", "operator": "Equal",
                                  "value": "batch", "effect": "NoSchedule"}]
        if i % 4 == 0:
            kw["node_selector"] = {"zone": f"z{i % 4}"}  # z3 never matches
        if i % 9 == 0:
            kw["node_name"] = f"n{i % 10}"  # pins, one dangling
        pods.append(make_pod(f"p{i}", milli_cpu=(1 + i % 6) * 200,
                             memory=(1 + i % 4) * 2**27, **kw))
    return ClusterSnapshot(nodes=nodes, pods=seeded), pods


def _ports():
    nodes = [make_node(f"n{i}") for i in range(3)]
    seeded = _port_pod("seed", 8080, node_name="n0", phase="Running")
    pods = [_port_pod(f"p{i}", 8080) for i in range(5)] \
        + [_port_pod("other", 9090)]
    return ClusterSnapshot(nodes=nodes, pods=[seeded]), pods


def _disk():
    nodes = [make_node(f"n{i}") for i in range(2)]
    vol = [make_pod_volume("v", {"rbd": {"monitors": ["a"], "pool": "p",
                                         "image": "img"}})]
    pods = [make_pod(f"p{i}", milli_cpu=100, volumes=vol) for i in range(4)]
    return ClusterSnapshot(nodes=nodes), pods


def _spread():
    nodes = [make_node(f"n{i}", labels={
        LABEL_ZONE_FAILURE_DOMAIN: f"z{i % 2}"}) for i in range(4)]
    existing = [make_pod(f"e{i}", node_name=f"n{i % 2}", phase="Running",
                         labels={"app": "api"}) for i in range(3)]
    snap = ClusterSnapshot(nodes=nodes, pods=existing,
                           services=[_service("api", {"app": "api"})])
    return snap, [make_pod(f"p{i}", milli_cpu=10, labels={"app": "api"})
                  for i in range(8)]


def _vol_zone():
    nodes = [make_node(f"n{i}", labels={
        LABEL_ZONE_FAILURE_DOMAIN: f"z{i % 2}"}) for i in range(4)]
    pvs = [make_pv("pv-a", labels={LABEL_ZONE_FAILURE_DOMAIN: "z0"})]
    pvcs = [make_pvc("claim-a", volume_name="pv-a")]
    pods = [make_pod(f"p{i}", milli_cpu=10,
                     volumes=[make_pod_volume("v", pvc="claim-a")])
            for i in range(3)]
    return ClusterSnapshot(nodes=nodes, pvs=pvs, pvcs=pvcs), pods


def _interpod():
    nodes = [make_node(f"n{i}", milli_cpu=4000, memory=8 * 1024**3,
                       labels={"zone": f"z{i % 2}", "rack": f"r{i % 3}"})
             for i in range(6)]
    existing = [make_pod(f"e{i}", node_name=f"n{i}", phase="Running",
                         milli_cpu=100, labels={"app": ("a0", "a1")[i % 2]})
                for i in range(3)]
    pods = []
    for i in range(12):
        aff = None
        if i % 3 == 0:
            aff = {"podAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"labelSelector": {"matchLabels": {"app": "a0"}},
                     "topologyKey": "zone"}]}}
        elif i % 3 == 1:
            aff = {"podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"labelSelector": {"matchLabels": {"app": "a1"}},
                     "topologyKey": "rack"}]}}
        pods.append(make_pod(f"p{i}", milli_cpu=200, memory=2**27,
                             labels={"app": ("a0", "a1")[i % 2]},
                             affinity=aff))
    return ClusterSnapshot(nodes=nodes, pods=existing), pods


def _maxpd():
    # KUBE_MAX_PD_VOLS forced low so the volume-count limit actually fires
    os.environ["KUBE_MAX_PD_VOLS"] = "2"
    nodes = [make_node(f"n{i}", milli_cpu=64000, memory=64 * 1024**3,
                       pods=100) for i in range(3)]
    existing = [make_pod(
        f"e{i}", node_name=f"n{i % 3}", phase="Running", milli_cpu=100,
        volumes=[make_pod_volume(
            "v", {"awsElasticBlockStore": {"volumeID": f"ebs{i}"}})])
        for i in range(3)]
    pods = [make_pod(
        f"p{i}", milli_cpu=100, memory=2**26,
        volumes=[make_pod_volume(
            "v", {"awsElasticBlockStore": {"volumeID": f"ebs{i % 5}"}})])
        for i in range(10)]
    return ClusterSnapshot(nodes=nodes, pods=existing), pods


# --- policy-residue variant classes (ISSUE 4): one tiny workload per
# residue family the fused scan absorbed; builders return a third element
# (the policy-as-data dict) and run_pallas_variant compiles it like the
# backend does -------------------------------------------------------------


def _pol(preds, prios, **extra):
    d = {"kind": "Policy", "apiVersion": "v1",
         "predicates": preds, "priorities": prios}
    d.update(extra)
    return d


def _residue_nodes(n=6):
    nodes = []
    for i in range(n):
        labels = {"region": f"r{i % 2}", "zone": f"z{i % 3}"}
        if i % 3 != 2:
            labels["foo"] = "x"
        if i % 2 == 0:
            labels["bar"] = "y"
        node = make_node(f"n{i}", milli_cpu=(800, 1600, 3200)[i % 3],
                         memory=(2 + i % 3) * 2**30, labels=labels)
        if i % 2 == 1:
            node.status.images = [ContainerImage(
                names=[f"img-{i % 3}:v1"], size_bytes=400 * 1024**2)]
        nodes.append(node)
    return nodes


def _pol_labels():
    """Label-presence predicate rows + NodeLabel preference."""
    pods = [make_pod(f"p{i}", milli_cpu=(1 + i % 4) * 150, memory=2**27)
            for i in range(10)]
    return ClusterSnapshot(nodes=_residue_nodes()), pods, _pol(
        [{"name": "PodFitsResources"},
         {"name": "TestLabelsPresence",
          "argument": {"labelsPresence": {"labels": ["foo"],
                                          "presence": True}}}],
        [{"name": "LeastRequestedPriority", "weight": 1},
         {"name": "TestLabelPreference", "weight": 2,
          "argument": {"labelPreference": {"label": "bar",
                                           "presence": True}}}])


def _pol_service_affinity():
    """ServiceAffinity region locks: one service pre-bound by a running
    pod, one binding its first-pod lock inside the scan."""
    nodes = _residue_nodes()
    placed = [make_pod("seed", milli_cpu=100, memory=2**26, node_name="n0",
                       phase="Running", labels={"app": "api"})]
    snap = ClusterSnapshot(nodes=nodes, pods=placed,
                           services=[_service("api", {"app": "api"}),
                                     _service("web", {"app": "web"})])
    pods = [make_pod(f"p{i}", milli_cpu=150, memory=2**26,
                     labels={"app": ("api", "web")[i % 2]})
            for i in range(8)]
    return snap, pods, _pol(
        [{"name": "PodFitsResources"},
         {"name": "TestServiceAffinity",
          "argument": {"serviceAffinity": {"labels": ["region"]}}}],
        [{"name": "LeastRequestedPriority", "weight": 1}])


def _pol_image():
    """ImageLocality via the signature-table streaming path."""
    pods = []
    for i in range(9):
        p = make_pod(f"p{i}", milli_cpu=150, memory=2**26)
        if i % 2 == 0:
            p.spec.containers[0].image = f"img-{i % 3}:v1"
        pods.append(p)
    return ClusterSnapshot(nodes=_residue_nodes()), pods, _pol(
        [{"name": "PodFitsResources"}],
        [{"name": "ImageLocalityPriority", "weight": 2},
         {"name": "LeastRequestedPriority", "weight": 1}])


def _pol_noexec():
    """NoExecute-only taint predicate (policy-registered variant)."""
    nodes = [make_node(f"n{i}", milli_cpu=(800, 1600, 3200)[i % 3],
                       memory=(2 + i % 3) * 2**30,
                       labels={"zone": f"z{i % 3}"},
                       taints=[{"key": "evict", "value": "now",
                                "effect": "NoExecute"}] if i % 3 == 0
                       else None) for i in range(6)]
    pods = []
    for i in range(8):
        kw = {}
        if i % 2 == 0:
            kw["tolerations"] = [{"key": "evict", "operator": "Equal",
                                  "value": "now", "effect": "NoExecute"}]
        pods.append(make_pod(f"p{i}", milli_cpu=150, memory=2**26, **kw))
    return ClusterSnapshot(nodes=nodes), pods, _pol(
        [{"name": "PodFitsResources"},
         {"name": "PodToleratesNodeNoExecuteTaints"}],
        [{"name": "LeastRequestedPriority", "weight": 1}])


def _pol_count_mode():
    """alwaysCheckAllPredicates: per-stage failure bits stay live past the
    first miss (pods failing resources AND the presence row)."""
    snap, pods, _ = _pol_labels()
    pods = pods + [make_pod(f"big{i}", milli_cpu=50_000, memory=2**27)
                   for i in range(3)]
    return snap, pods, _pol(
        [{"name": "PodFitsResources"},
         {"name": "TestLabelsPresence",
          "argument": {"labelsPresence": {"labels": ["foo"],
                                          "presence": True}}}],
        [{"name": "LeastRequestedPriority", "weight": 1}],
        alwaysCheckAllPredicates=True)


PALLAS_VARIANTS = [
    # (name, workload builder, most_requested)
    ("base", _base, False),
    ("most_requested", _base, True),
    ("ports", _ports, False),
    ("disk", _disk, False),
    ("spread", _spread, False),
    ("vol_zone", _vol_zone, False),
    ("interpod", _interpod, False),
    ("maxpd", _maxpd, False),
    ("pol_labels", _pol_labels, False),
    ("pol_service_affinity", _pol_service_affinity, False),
    ("pol_image", _pol_image, False),
    ("pol_noexec", _pol_noexec, False),
    ("pol_count_mode", _pol_count_mode, False),
]


def run_pallas_variant(name, build, most_requested):
    """Pallas fast path vs the XLA scan, bit-for-bit, on one tiny batch."""
    built = build()
    snapshot, pods = built[:2]
    policy = built[2] if len(built) > 2 else None
    cp = ptabs = None
    if policy is not None:
        from dataclasses import replace as _dc_replace

        from tpusim.engine.policy import decode_policy
        from tpusim.engine.predicates import (
            POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED,
        )
        from tpusim.jaxe.policyc import build_policy_tables, compile_policy

        cp = compile_policy(decode_policy(policy))
        assert not cp.unsupported, (name, cp.unsupported)
        need_noexec = (cp.spec.pred_keys is not None
                       and POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED
                       in cp.spec.pred_keys)
        need_saa = bool(cp.spec.saa_weights) or cp.spec.sa_enabled
        compiled, cols = compile_cluster(snapshot, pods,
                                         need_noexec=need_noexec,
                                         need_saa=need_saa)
    else:
        compiled, cols = compile_cluster(snapshot, pods)
    assert not compiled.unsupported, (name, compiled.unsupported)
    config = config_for(
        [compiled], most_requested=most_requested,
        num_reason_bits=NUM_FIXED_BITS + len(compiled.scalar_names))
    if cp is not None:
        config = _dc_replace(config, policy=cp.spec)
        ptabs = build_policy_tables(cp, snapshot, pods, compiled, cols)
        if cp.saa_entries:
            config = _dc_replace(config, n_saa_doms=ptabs.n_saa_doms)
    plan, reason = plan_fast(config, compiled, cols, ptabs=ptabs)
    if plan is None:
        raise AssertionError(f"variant {name} ineligible for the fast "
                             f"path: {reason}")
    if cp is not None:
        from tpusim.jaxe.kernels import _tree_to_device, statics_to_host

        statics = _tree_to_device(statics_to_host(compiled)._replace(
            label_ok=ptabs.label_ok, label_prio=ptabs.label_prio,
            image_score=ptabs.image_score, saa_dom=ptabs.saa_dom,
            sa_pin=ptabs.sa_pin, sa_val=ptabs.sa_val))
        carry = carry_init(compiled)._replace(sa_lock=ptabs.sa_lock_init)
    else:
        statics = statics_to_device(compiled)
        carry = carry_init(compiled)
    _, choices, counts, advanced = schedule_scan(
        config, carry, statics, pod_columns_to_device(cols))
    f_choices, f_counts, f_adv = fast_scan(plan, chunk=16)
    choices, counts = np.asarray(choices), np.asarray(counts)
    if not np.array_equal(f_choices, choices):
        raise AssertionError(f"variant {name}: choices diverge from the "
                             f"XLA scan")
    w = f_counts.shape[1]
    if not np.array_equal(f_counts, counts[:, :w]):
        raise AssertionError(f"variant {name}: reason histograms diverge")
    if not np.array_equal(f_adv, np.asarray(advanced)):
        raise AssertionError(f"variant {name}: rr advancement diverges")
    h = hashlib.sha256(
        choices.tobytes() + counts.tobytes()).hexdigest()[:16]
    return h, int((choices >= 0).sum()), len(pods)


def _preempt_workload():
    """Arithmetic-reprieve class: packed low-priority residents, banded
    incoming pods — only PodFitsResources can flip, so the device
    victim-selection kernel handles every preemption."""
    nodes = [make_node(f"n{i}", milli_cpu=2000, memory=4 * 1024**3)
             for i in range(4)]
    residents = []
    for i in range(4):
        p = make_pod(f"fill{i}", milli_cpu=1800, memory=2**28,
                     node_name=f"n{i}", phase="Running")
        p.spec.priority = 0
        residents.append(p)
    pods = []
    for i in range(8):
        p = make_pod(f"p{i}", milli_cpu=600, memory=2**26)
        p.spec.priority = (0, 500, 1000)[i % 3]
        pods.append(p)
    return ClusterSnapshot(nodes=nodes, pods=residents), pods


def run_preempt_variant():
    """Device victim-selection kernel vs the host oracle on a tiny banded
    batch: same placements, same victims, and the device arm actually
    fired (an all-host run must not certify the kernel)."""
    from tpusim.jaxe.preempt import (
        PREEMPT_CLASS_STATS,
        reset_preempt_class_stats,
        run_with_preemption,
    )

    def sig(status):
        return ([(p.name, p.spec.node_name)
                 for p in status.successful_pods],
                sorted(p.name for p in status.preempted_pods),
                [p.name for p in status.failed_pods])

    snapshot, pods = _preempt_workload()
    reset_preempt_class_stats()
    os.environ.pop("TPUSIM_PREEMPT_DEVICE", None)  # AUTO: verify-then-trust
    dev = run_with_preemption([p.copy() for p in pods], snapshot)
    paths = dict(PREEMPT_CLASS_STATS)
    if not dev.preempted_pods:
        raise AssertionError("preempt workload evicted nothing; the "
                             "victim kernel was never exercised")
    if not (paths.get("device") or paths.get("device_verified")):
        raise AssertionError(f"victim selection never took the device "
                             f"arm: {paths}")
    os.environ["TPUSIM_PREEMPT_DEVICE"] = "0"
    try:
        host = run_with_preemption([p.copy() for p in pods], snapshot)
    finally:
        os.environ.pop("TPUSIM_PREEMPT_DEVICE", None)
    if sig(dev) != sig(host):
        raise AssertionError("device victim selection diverges from the "
                             "host oracle")
    h = hashlib.sha256(repr(sig(dev)).encode()).hexdigest()[:16]
    return h, len(dev.preempted_pods), paths


def run_chaos_breaker_variant():
    """Dispatch circuit breaker under scripted device faults: two injected
    exceptions trip it OPEN, the cooldown denial flips it HALF_OPEN, and a
    verified probe CLOSES it again — while every emitted batch stays
    byte-identical to the host pipeline (verify="all"). Certifies that a
    flaky accelerator degrades and RECOVERS instead of being benched for
    the life of the process."""
    from tpusim.backends import placement_hash
    from tpusim.chaos import DeviceFaultPlan
    from tpusim.jaxe.backend import JaxBackend, install_chaos, uninstall_chaos

    snapshot, pods = _base()
    backend = JaxBackend()
    expected = placement_hash(backend.schedule(pods, snapshot))
    breaker = install_chaos(DeviceFaultPlan(
        faults={0: "exception", 1: "exception"},
        failure_threshold=2, cooldown=1))
    try:
        for _ in range(4):  # fault, fault->open, denied->half_open, probe
            got = placement_hash(backend.schedule(pods, snapshot))
            if got != expected:
                raise AssertionError(
                    "placements diverged from the clean run under chaos")
    finally:
        uninstall_chaos()
    transitions = [t for t, _ in breaker.transitions]
    if transitions != ["open", "half_open", "close"]:
        raise AssertionError(f"breaker cycle incomplete: {transitions}")
    return expected[:16], transitions


def run_serve_fleet_variant():
    """Scenario fleet (tpusim/serve) on one tiny bucket: serve-path
    placements must hash-match per-scenario run_what_if — including a
    ghost-padded partial bucket — and an exact warm repeat must dispatch
    without tracing a single fresh program."""
    from tpusim.backends import placement_hash
    from tpusim.jaxe.whatif import compile_count, run_what_if
    from tpusim.serve import ScenarioFleet, WhatIfRequest

    base = _base()[0]
    scenarios = [(base, [make_pod(f"f{s}-p{i}", milli_cpu=(1 + i % 4) * 200,
                                  memory=(1 + (s + i) % 3) * 2**27)
                         for i in range(6 + s)])
                 for s in range(3)]
    fleet = ScenarioFleet(bucket_size=2, flush_after_s=60.0)
    load = lambda: [WhatIfRequest(pods=pods, snapshot=snap,  # noqa: E731
                                  cache_key=f"smoke-{i}")
                    for i, (snap, pods) in enumerate(scenarios)]
    # 3 requests / bucket 2: one full bucket + one ghost-padded partial
    responses = fleet.run(load())
    hashes = []
    for resp, (snap, pods) in zip(responses, scenarios):
        if not resp.ok:
            raise AssertionError(f"serve request failed: {resp.error}")
        got = placement_hash(resp.result.placements)
        [single] = run_what_if([(snap, pods)])
        want = placement_hash(single.placements)
        if got != want:
            raise AssertionError(
                f"serve placements diverge from run_what_if "
                f"(ghosts={resp.bucket_ghosts}): {got[:16]} != {want[:16]}")
        hashes.append(got)
    before = compile_count()
    warm = fleet.run(load())
    traced = compile_count() - before
    if traced:
        raise AssertionError(f"warm repeat traced {traced} program(s); "
                             "the warm-executable cache is broken")
    if not all(r.compile_cache_hit for r in warm):
        raise AssertionError("warm responses missing the compile_cache_hit "
                             "stamp")
    h = hashlib.sha256("".join(hashes).encode()).hexdigest()[:16]
    return h, len(responses), dict(fleet.executor.stats)


def run_stream_churn_variant():
    """Streaming runtime (tpusim/stream) under seeded churn: every cycle's
    placements — scatter-committed device-resident fast path and classified
    restages alike — must byte-match a fresh-compile reference arm, and a
    second warm session over the same shapes must dispatch without tracing
    a single fresh scan or scatter program (the pow2-bucket zero-retrace
    contract)."""
    from tpusim.jaxe.kernels import apply_delta_donated, schedule_scan_donated
    from tpusim.simulator import run_stream_simulation

    def cache_sizes():
        try:
            return (schedule_scan_donated._cache_size(),
                    apply_delta_donated._cache_size())
        except AttributeError:  # private jit API moved: skip the check
            return None

    out = run_stream_simulation(num_nodes=16, cycles=10, arrivals=16,
                                evict_fraction=0.25, node_flap_every=4,
                                seed=7, verify=True)
    if not out["verified"]:
        raise AssertionError(
            f"stream placements diverge from the full-restage reference on "
            f"{out['mismatched_cycles']} of {out['cycles']} cycles")
    stream_cycles = out["paths"].get("stream_scan", 0)
    if not stream_cycles:
        raise AssertionError(
            f"churn never took the O(delta) stream path: {out['paths']}")
    if not out["commits"]:
        raise AssertionError("no scatter commits dispatched")
    before = cache_sizes()
    warm = run_stream_simulation(num_nodes=16, cycles=4, arrivals=16,
                                 evict_fraction=0.25, seed=8)
    traced = None
    if before is not None:
        after = cache_sizes()
        traced = (after[0] - before[0], after[1] - before[1])
        if any(traced):
            raise AssertionError(
                f"warm session retraced (scan +{traced[0]}, scatter "
                f"+{traced[1]}); pow2 bucketing is broken")
    if warm["paths"].get("stream_scan", 0) != warm["cycles"] - 1:
        raise AssertionError(
            f"warm session left the stream path: {warm['paths']}")
    h = out["placement_chain"][:16]
    return h, out["scheduled"], out["decisions"], stream_cycles, traced


def run_stream_policy_variant():
    """Stream v2 (compiled-policy residency + pipelined dispatch) stage-0:
    a policy-built streaming session under node label/taint churn must (a)
    byte-match the fresh-compile reference every cycle while classifying
    only the cold start as a restage — churn lands as the O(delta) statics
    scatter, not a re-stage; (b) emit an identical placement chain from the
    pipelined double-buffered path; (c) leave every donated program cache
    untouched on a warm re-run (scan, delta scatter AND the policy-aware
    statics scatter)."""
    from tpusim.engine.policy import decode_policy
    from tpusim.jaxe.kernels import (
        apply_delta_donated,
        apply_statics_delta_donated,
        schedule_scan_donated,
    )
    from tpusim.simulator import run_stream_simulation

    policy = decode_policy(_pol(
        [{"name": "PodFitsResources"},
         {"name": "MatchNodeSelector"},
         {"name": "PodToleratesNodeTaints"},
         {"name": "TestServiceAffinity",
          "argument": {"serviceAffinity": {"labels": ["region"]}}},
         {"name": "TestLabelsPresence",
          "argument": {"labelsPresence": {"labels": ["foo"],
                                          "presence": True}}}],
        [{"name": "LeastRequestedPriority", "weight": 1},
         {"name": "zone-spread", "weight": 2,
          "argument": {"serviceAntiAffinity": {"label": "zone"}}},
         {"name": "bar-pref", "weight": 1,
          "argument": {"labelPreference": {"label": "bar",
                                           "presence": True}}}]))

    def cache_sizes():
        try:
            return (schedule_scan_donated._cache_size(),
                    apply_delta_donated._cache_size(),
                    apply_statics_delta_donated._cache_size())
        except AttributeError:  # private jit API moved: skip the check
            return None

    def run(**kw):
        return run_stream_simulation(num_nodes=16, cycles=10, arrivals=16,
                                     evict_fraction=0.25, label_churn=2,
                                     taint_churn=1, seed=7, policy=policy,
                                     **kw)

    out = run(verify=True)
    if not out["verified"]:
        raise AssertionError(
            f"policy-stream placements diverge from the full-restage "
            f"reference on {out['mismatched_cycles']} of "
            f"{out['cycles']} cycles")
    if out["restages"] != {"cold_start": 1}:
        raise AssertionError(
            f"label/taint churn restaged beyond the cold start: "
            f"{out['restages']} (paths {out['paths']}) — policy-table "
            f"residency is broken")
    piped = run(pipeline=True)
    if piped["placement_chain"] != out["placement_chain"]:
        raise AssertionError(
            "pipelined placement chain diverges from synchronous "
            f"({piped['placement_chain'][:16]} != "
            f"{out['placement_chain'][:16]})")
    pipelined_cycles = piped["paths"].get("pipelined", 0)
    if not pipelined_cycles:
        raise AssertionError(
            f"pipeline never engaged the async path: {piped['paths']}")
    before = cache_sizes()
    warm = run(pipeline=True)
    traced = None
    if before is not None:
        after = cache_sizes()
        traced = tuple(a - b for a, b in zip(after, before))
        if any(traced):
            raise AssertionError(
                f"warm policy session retraced (scan +{traced[0]}, delta "
                f"+{traced[1]}, statics +{traced[2]}); residency or "
                f"bucketing is broken")
    if warm["placement_chain"] != out["placement_chain"]:
        raise AssertionError("warm re-run chain diverges")
    h = out["placement_chain"][:16]
    return h, out["scheduled"], out["decisions"], pipelined_cycles, traced


def run_stream_recover_variant():
    """Crash recovery (tpusim/stream/persist) stage-0: a WAL-journaled
    streaming session killed mid-run by a scripted process crash must (a)
    recover to a fold chain byte-identical to the uninterrupted run's, (b)
    classify the recovery restage exactly once as "recovered", with zero
    replay invariant violations, and (c) resume WITHOUT retracing a single
    scan or scatter program — the recovered device picture re-enters the
    same pow2-bucketed executables the crashed run compiled."""
    import shutil
    import tempfile

    from tpusim.chaos.engine import ProcessCrash
    from tpusim.chaos.plan import ChurnEvent, FaultPlan
    from tpusim.jaxe.kernels import apply_delta_donated, schedule_scan_donated
    from tpusim.simulator import run_stream_simulation

    def cache_sizes():
        try:
            return (schedule_scan_donated._cache_size(),
                    apply_delta_donated._cache_size())
        except AttributeError:  # private jit API moved: skip the check
            return None

    def run(ckdir, **kw):
        return run_stream_simulation(num_nodes=16, cycles=10, arrivals=16,
                                     evict_fraction=0.25, node_flap_every=4,
                                     seed=7, checkpoint_dir=ckdir,
                                     checkpoint_every=2, **kw)

    base_dir = tempfile.mkdtemp(prefix="tpusim-smoke-ck-")
    ck_dir = tempfile.mkdtemp(prefix="tpusim-smoke-ck-")
    try:
        base = run(base_dir)
        plan = FaultPlan(seed=7, churn=[
            ChurnEvent(at=6, action="process_crash", target="emit")])
        try:
            run(ck_dir, chaos_plan=plan)
            raise AssertionError("scripted process crash never fired")
        except ProcessCrash:
            pass
        before = cache_sizes()
        out = run(ck_dir, recover=True)
        traced = None
        if before is not None:
            after = cache_sizes()
            traced = (after[0] - before[0], after[1] - before[1])
            if any(traced):
                raise AssertionError(
                    f"recovery retraced (scan +{traced[0]}, scatter "
                    f"+{traced[1]}); the restored device picture missed "
                    f"the warm executables")
        if out["fold_chain"] != base["fold_chain"]:
            raise AssertionError(
                f"recovered fold chain diverges from the uninterrupted "
                f"run ({out['fold_chain'][:16]} != "
                f"{base['fold_chain'][:16]})")
        if out["recovery_violations"]:
            raise AssertionError(
                f"WAL replay invariant violations: "
                f"{out['recovery_violations']}")
        if out["restages"].get("recovered") != 1:
            raise AssertionError(
                f"recovery restage misclassified: {out['restages']} "
                f"(want exactly one 'recovered')")
        h = out["fold_chain"][:16]
        return h, out["resume_cycle"], out["wal_records"], traced
    finally:
        shutil.rmtree(base_dir, ignore_errors=True)
        shutil.rmtree(ck_dir, ignore_errors=True)


def run_standby_variant():
    """Hot standby + failover (tpusim/stream/replicate) stage-0: a leader
    shipping its WAL live to an in-process FollowerTwin, killed mid-run by
    a scripted crash, must (a) promote the standby to a fold chain
    byte-identical to the crash-free run's, (b) replay only the unshipped
    tail (the replication lag), not the journal, with zero promotion
    violations, and (c) resume on the promoted twin WITHOUT retracing a
    single scan or scatter program — the follower's replayed device
    picture re-enters the same pow2-bucketed executables the leader (and
    the warm-up baseline) compiled."""
    import shutil
    import tempfile

    from tpusim.chaos.plan import kill_leader_campaign
    from tpusim.jaxe.kernels import apply_delta_donated, schedule_scan_donated
    from tpusim.simulator import run_replicated_stream, run_stream_simulation
    from tpusim.stream import CRASH_POINTS

    def cache_sizes():
        try:
            return (schedule_scan_donated._cache_size(),
                    apply_delta_donated._cache_size())
        except AttributeError:  # private jit API moved: skip the check
            return None

    kw = dict(num_nodes=16, cycles=10, arrivals=16, evict_fraction=0.25,
              node_flap_every=4, seed=7, checkpoint_every=2)
    base_dir = tempfile.mkdtemp(prefix="tpusim-smoke-repl-")
    rep_dir = tempfile.mkdtemp(prefix="tpusim-smoke-repl-")
    try:
        base = run_stream_simulation(checkpoint_dir=base_dir, **kw)
        plan = kill_leader_campaign(seed=7, cycles=kw["cycles"])[
            CRASH_POINTS.index("emit")]
        before = cache_sizes()
        out = run_replicated_stream(checkpoint_dir=rep_dir,
                                    chaos_plan=plan, **kw)
        traced = None
        if before is not None:
            after = cache_sizes()
            traced = (after[0] - before[0], after[1] - before[1])
            if any(traced):
                raise AssertionError(
                    f"promotion retraced (scan +{traced[0]}, scatter "
                    f"+{traced[1]}); the standby's replayed device "
                    f"picture missed the warm executables")
        if not out["crashed"] or not out["promoted"]:
            raise AssertionError(
                f"kill-the-leader never promoted: {out['crash_detail']}")
        if out["fold_chain"] != base["fold_chain"]:
            raise AssertionError(
                f"promoted chain diverges from the crash-free run "
                f"({out['fold_chain'][:16]} != {base['fold_chain'][:16]})")
        if out["promotion_violations"]:
            raise AssertionError(
                f"promotion violations: {out['promotion_violations']}")
        if out["divergence"]:
            raise AssertionError(
                f"follower diverged during replication: "
                f"{out['divergence']}")
        if not out["replayed_records"] < out["wal_records"]:
            raise AssertionError(
                f"promotion replayed the whole journal "
                f"({out['replayed_records']}/{out['wal_records']} "
                f"records) — the warm twin bought nothing")
        h = out["fold_chain"][:16]
        return (h, out["rto_s"] * 1e3, out["replayed_records"],
                out["wal_records"], traced)
    finally:
        shutil.rmtree(base_dir, ignore_errors=True)
        shutil.rmtree(rep_dir, ignore_errors=True)


def run_live_whatif_variant():
    """Live-twin what-if overlay (ISSUE 19) stage-0: an overlay query on a
    churn-warm device-resident twin must (a) answer placement-hash
    identical to the staged run_what_if oracle over the same logical
    state, (b) trace ZERO fresh programs across warm-shape repeats — the
    overlay rides the stream's pow2-bucketed scan + scatter programs —
    and (c) leave the churn run's fold chain byte-unchanged when queries
    interleave with live cycles (the copy-on-write rollback contract)."""
    import numpy as np

    from tpusim.api.snapshot import make_pod, synthetic_cluster
    from tpusim.backends import placement_hash
    from tpusim.jaxe.whatif import compile_count, run_what_if
    from tpusim.simulator import run_stream_simulation
    from tpusim.stream import ChurnLoadGen, StreamSession

    session = StreamSession(synthetic_cluster(16))
    gen = ChurnLoadGen(synthetic_cluster(16), seed=7, arrivals=16,
                       evict_fraction=0.25)
    for c in range(4):
        session.apply_events(gen.events(c))
        gen.note_bound(session.schedule(gen.batch()))
    rng = np.random.RandomState(19)
    qpods = [make_pod(f"smoke-q{i}",
                      milli_cpu=int(rng.randint(100, 1500)),
                      memory=int(rng.randint(2 ** 20, 2 ** 30)))
             for i in range(6)]
    placements = session.overlay_query(qpods)
    if placements is None:
        raise AssertionError("overlay refused on a warm resident twin")
    [oracle] = run_what_if([(session.inc.to_snapshot(), qpods)])
    h = placement_hash(placements)
    if h != placement_hash(oracle.placements):
        raise AssertionError(
            f"overlay hash {h[:16]} != staged run_what_if "
            f"{placement_hash(oracle.placements)[:16]} on the same state")
    traced_before = compile_count()
    for k in (6, 5, 3):   # all land in already-traced pow2 buckets
        if session.overlay_query(qpods[:k]) is None:
            raise AssertionError(f"warm overlay refused at {k} pods")
    retraces = compile_count() - traced_before
    if retraces:
        raise AssertionError(
            f"warm overlay queries traced {retraces} fresh programs; "
            "pow2 bucket reuse is broken")
    kw = dict(num_nodes=16, cycles=8, arrivals=16, evict_fraction=0.25,
              node_flap_every=4, seed=7)
    base = run_stream_simulation(**kw)
    live = run_stream_simulation(**kw, whatif_every=2, whatif_pods=6)
    if live["fold_chain"] != base["fold_chain"]:
        raise AssertionError(
            "interleaved overlay queries changed the churn fold chain: "
            f"{live['fold_chain'][:16]} vs {base['fold_chain'][:16]}")
    ov = live["overlay"]
    if ov["answered"] != ov["queries"]:
        raise AssertionError(f"overlay fell back under churn: {ov}")
    return h[:16], ov["answered"], retraces


def run_analytics_variant():
    """Cluster analytics plane (tpusim/obs/analytics) stage-0: with the
    post-scan reduction riding every dispatch, (a) on-device aggregates
    must equal a host-side numpy recomputation bit-for-bit for every
    captured sample across the jax-backend one-shot, the streaming runtime
    (sync AND pipelined), and the serve fleet; (b) placement hashes /
    chains must be byte-identical to an analytics-off run — the reduction
    is a separate dispatch over the scan's final carry, never a change to
    the scan program; (c) a pure-churn stream session must still classify
    only the cold start as a restage."""
    from tpusim.backends import placement_hash
    from tpusim.jaxe.backend import JaxBackend
    from tpusim.obs import analytics
    from tpusim.serve import ScenarioFleet, WhatIfRequest
    from tpusim.simulator import run_stream_simulation

    def stream(**kw):
        return run_stream_simulation(num_nodes=16, cycles=6, arrivals=16,
                                     evict_fraction=0.25, seed=7, **kw)

    snapshot, pods = _base()
    off_hash = placement_hash(JaxBackend().schedule(
        [p.copy() for p in pods], snapshot))
    off_stream = stream()

    # keep_inputs host-copies each reduction's input columns at capture
    # time (the carry buffers are donated into the next cycle), enabling
    # the device-vs-numpy replay below
    log = analytics.install(analytics.ClusterAnalytics(
        keep_inputs=True, sample_interval_s=0.0))
    try:
        on_hash = placement_hash(JaxBackend().schedule(
            [p.copy() for p in pods], snapshot))
        on_stream = stream()
        piped = stream(pipeline=True)
        serve_pods = [make_pod(f"an-p{i}", milli_cpu=200 * (1 + i % 3),
                               memory=(1 + i % 2) * 2**27)
                      for i in range(5)]
        fleet = ScenarioFleet(bucket_size=2, flush_after_s=60.0)
        [resp] = fleet.run([WhatIfRequest(pods=serve_pods, snapshot=snapshot,
                                          cache_key="analytics-smoke")])
        if not resp.ok:
            raise AssertionError(f"serve request failed: {resp.error}")
        mismatches = log.verify_against_host()
        if mismatches:
            raise AssertionError(
                "device aggregates diverge from the numpy recomputation: "
                + "; ".join(mismatches[:3]))
        sources = {s.source for s in log.samples()}
        if not {"backend", "stream", "serve"} <= sources:
            raise AssertionError(f"missing capture sources: {sorted(sources)}")
        n_samples = len(log.samples())
    finally:
        analytics.uninstall()

    if on_hash != off_hash:
        raise AssertionError(
            f"backend placement hash moved with analytics on "
            f"({on_hash[:16]} != {off_hash[:16]})")
    if on_stream["placement_chain"] != off_stream["placement_chain"]:
        raise AssertionError("stream placement chain moved with analytics on")
    if piped["placement_chain"] != off_stream["placement_chain"]:
        raise AssertionError(
            "pipelined placement chain moved with analytics on")
    if on_stream["restages"] != {"cold_start": 1}:
        raise AssertionError(
            f"analytics run restaged beyond the cold start: "
            f"{on_stream['restages']}")
    return off_hash[:16], n_samples, sorted(sources)


def run_gang_variant():
    """Gang admission (tpusim/gang) stage-0: (a) the host oracle and the
    batched kernel route must produce byte-identical placements for the
    same gang feed (TPUSIM_GANG_KERNEL=0 vs =1); (b) all-or-nothing — an
    oversized gang binds ZERO members and every member carries the SAME
    FitError message; (c) a gang-free feed's placement hash is untouched
    by the group driver's presence (annotation is the only trigger)."""
    from tpusim.backends import Placement, placement_hash
    from tpusim.gang.group import mark_gang
    from tpusim.simulator import run_simulation

    def cluster():
        nodes = [make_node(f"gn{i}", milli_cpu=4000,
                           labels={"topology.kubernetes.io/rack":
                                   f"rack-{i // 2}"})
                 for i in range(6)]
        return ClusterSnapshot(nodes=nodes, pods=[])

    def feed(gang=True):
        pods = [make_pod(f"gs{i}", milli_cpu=200) for i in range(4)]
        if gang:
            pods += [mark_gang(make_pod(f"gg-{j}", milli_cpu=800), "gg")
                     for j in range(4)]
        return pods

    def run_route(kernel_env):
        prev = os.environ.get("TPUSIM_GANG_KERNEL")
        os.environ["TPUSIM_GANG_KERNEL"] = kernel_env
        try:
            from tpusim.jaxe.backend import reset_fast_auto

            reset_fast_auto()
            st = run_simulation(feed(), cluster(), backend="jax")
        finally:
            if prev is None:
                os.environ.pop("TPUSIM_GANG_KERNEL", None)
            else:
                os.environ["TPUSIM_GANG_KERNEL"] = prev
        return placement_hash(
            [Placement(pod=p, node_name=p.spec.node_name)
             for p in sorted(st.successful_pods,
                             key=lambda p: p.metadata.name)]
            + [Placement(pod=p, reason="Unschedulable")
               for p in sorted(st.failed_pods,
                               key=lambda p: p.metadata.name)])

    host_hash = run_route("0")
    kernel_hash = run_route("1")
    if host_hash != kernel_hash:
        raise AssertionError(
            f"gang kernel route diverges from the host oracle "
            f"({kernel_hash[:16]} != {host_hash[:16]})")

    # all-or-nothing: 8 x 3900m on 6 x 4000m nodes cannot all fit
    big = [mark_gang(make_pod(f"big-{j}", milli_cpu=3900), "big")
           for j in range(8)]
    st = run_simulation(big, cluster(), backend="jax")
    if st.successful_pods:
        raise AssertionError(
            f"rejected gang left {len(st.successful_pods)} members bound")
    msgs = {p.status.conditions[-1].message for p in st.failed_pods}
    if len(st.failed_pods) != 8 or len(msgs) != 1:
        raise AssertionError(
            f"expected 8 members sharing one FitError, got "
            f"{len(st.failed_pods)} members / {len(msgs)} messages")

    # gang-free identity across backends (the annotation is the trigger)
    ref = run_simulation(feed(gang=False), cluster(), backend="reference")
    jx = run_simulation(feed(gang=False), cluster(), backend="jax")
    ref_bind = sorted((p.metadata.name, p.spec.node_name)
                      for p in ref.successful_pods)
    jx_bind = sorted((p.metadata.name, p.spec.node_name)
                     for p in jx.successful_pods)
    if ref_bind != jx_bind:
        raise AssertionError("gang-free feed diverges between backends")
    return host_hash[:16], len(feed()), len(msgs)


def run_sharded_variant():
    """Node-sharded twin (ISSUE 16) stage-0: the TPUSIM_SHARDS=2 mesh
    route must (a) byte-match the single-device placement hash for the
    same seeded feed — the verify-then-trust seam pins the (shards,
    config) signature on the first batch; (b) serve a warm second batch
    from the already-compiled shard_map program without tracing a fresh
    one (zero-retrace across batches). Returns None (skip) on hosts
    exposing a single device."""
    import jax

    if len(jax.devices()) < 2:
        return None
    from tpusim.backends import Placement, placement_hash
    from tpusim.jaxe.backend import _SHARD_AUTO, reset_fast_auto
    from tpusim.jaxe.kernels import _SHARDED_SCAN_PROGRAMS
    from tpusim.simulator import run_simulation

    def cluster():
        nodes = [make_node(f"sn{i}", milli_cpu=(1500, 2500, 4000)[i % 3],
                           memory=(2 << 30) + (i % 4) * (1 << 30),
                           labels={"zone": f"z{i % 2}",
                                   "topology.kubernetes.io/rack":
                                   f"rack-{i // 4}"})
                 for i in range(14)]
        return ClusterSnapshot(nodes=nodes, pods=[])

    def feed(tag="a"):
        pods = [make_pod(f"sp-{tag}-{i}", milli_cpu=150 + 70 * (i % 9),
                         memory=(192 << 20) * (1 + i % 3))
                for i in range(28)]
        # oversized tail: FitError text must survive the shard merge
        pods += [make_pod(f"sp-{tag}-big{j}", milli_cpu=9000)
                 for j in range(2)]
        return pods

    def run(shards, tag="a", reset=True):
        prev = os.environ.get("TPUSIM_SHARDS")
        os.environ["TPUSIM_SHARDS"] = str(shards)
        try:
            if reset:
                reset_fast_auto()
            st = run_simulation(feed(tag), cluster(), backend="jax")
        finally:
            if prev is None:
                os.environ.pop("TPUSIM_SHARDS", None)
            else:
                os.environ["TPUSIM_SHARDS"] = prev
        return placement_hash(
            [Placement(pod=p, node_name=p.spec.node_name)
             for p in sorted(st.successful_pods,
                             key=lambda p: p.metadata.name)]
            + [Placement(pod=p, reason="Unschedulable")
               for p in sorted(st.failed_pods,
                               key=lambda p: p.metadata.name)])

    base_hash = run(1)
    shard_hash = run(2)
    if shard_hash != base_hash:
        raise AssertionError(
            f"sharded route diverges from single-device "
            f"({shard_hash[:16]} != {base_hash[:16]})")
    if _SHARD_AUTO["disabled"] or not _SHARD_AUTO["verified_sigs"]:
        raise AssertionError(
            "sharded run never pinned a verified signature "
            f"(disabled={_SHARD_AUTO['disabled']})")

    # zero-retrace: a warm batch over the same shapes must reuse the
    # compiled shard_map program (the pinned sig skips re-verification)
    def program_traces():
        try:
            return sum(fn._cache_size()
                       for fn in _SHARDED_SCAN_PROGRAMS.values())
        except AttributeError:  # private jit API moved: skip the check
            return None

    before = program_traces()
    warm_hash = run(2, tag="b", reset=False)
    traced = None
    if before is not None:
        traced = program_traces() - before
        if traced:
            raise AssertionError(
                f"warm sharded batch retraced ({traced:+d} shard_map "
                "programs); the per-(config, mesh) cache is broken")
    warm_base = run(1, tag="b")
    if warm_hash != warm_base:
        raise AssertionError("warm sharded batch diverges from "
                             "single-device")
    return base_hash[:16], 2, traced


def run_traced_fleet_variant():
    """Fleet-wide distributed tracing (ISSUE 20) stage-0: a replicated
    leader -> follower stream run plus a traced serve batch, captured on
    one flight recorder, must (a) leave the fold chain byte-identical
    to an untraced run — the recorder is invisible to the decisions;
    (b) carry every WAL frame's context across the shipping socket:
    each flow start meets exactly one finish and the follower's replay
    spans are stamped with leader trace ids; (c) pin the hello-handshake
    clock anchors tools/trace_merge.py aligns multi-process captures
    on; and (d) export an artifact that tools/trace_lint.py certifies
    Perfetto-valid both as captured and after a trace_merge round-trip."""
    import importlib.util
    import json
    import shutil
    import tempfile

    from tpusim.obs import recorder as flight
    from tpusim.serve import ScenarioFleet, WhatIfRequest
    from tpusim.simulator import run_replicated_stream, \
        run_stream_simulation

    def load_tool(name):
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(os.path.dirname(__file__), f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    kw = dict(num_nodes=16, cycles=8, arrivals=16, evict_fraction=0.25,
              seed=9, checkpoint_every=2)
    base_dir = tempfile.mkdtemp(prefix="tpusim-smoke-trace-")
    rep_dir = tempfile.mkdtemp(prefix="tpusim-smoke-trace-")
    outer = flight.get_recorder()
    flight.uninstall()
    try:
        base = run_stream_simulation(checkpoint_dir=base_dir, **kw)
        rec = flight.install(
            flight.FlightRecorder(process_name="tpusim-smoke-fleet"))
        out = run_replicated_stream(checkpoint_dir=rep_dir, **kw)
        if out["fold_chain"] != base["fold_chain"]:
            raise AssertionError(
                f"tracing moved the fold chain ({out['fold_chain'][:16]} "
                f"!= {base['fold_chain'][:16]}); the recorder must be "
                "invisible to the decisions")
        if out["divergence"]:
            raise AssertionError(
                f"follower diverged under tracing: {out['divergence']}")
        snap, pods = _base()
        fleet = ScenarioFleet(bucket_size=2, flush_after_s=60.0)
        responses = fleet.run([WhatIfRequest(pods=pods, snapshot=snap)
                               for _ in range(2)])
        if not all(r.ok for r in responses):
            raise AssertionError("traced serve batch failed")
        flight.uninstall()

        s = [e for e in rec.events
             if e.get("ph") == "s" and e.get("cat") == "wal"]
        f = [e for e in rec.events
             if e.get("ph") == "f" and e.get("cat") == "wal"]
        if not s or {e["id"] for e in s} != {e["id"] for e in f}:
            raise AssertionError(
                f"wal flow graph disconnected ({len(s)} starts, "
                f"{len(f)} finishes)")
        applies = [e for e in rec.events
                   if e.get("name") == "replicate:apply"
                   and e.get("args", {}).get("trace_id")]
        leader_ids = {e["args"]["trace_id"] for e in s}
        if not applies or \
                not {e["args"]["trace_id"] for e in applies} <= leader_ids:
            raise AssertionError(
                "follower replay spans lost the leader's trace context")
        admits = [e for e in rec.events if e.get("name") == "serve:admit"
                  and e.get("args", {}).get("trace_id")]
        if not admits:
            raise AssertionError("serve admissions were not stamped with "
                                 "a trace context")
        for anchor in ("hello_tx_us", "peer_clk_us", "peer_clk_rx_us"):
            if anchor not in rec.anchors:
                raise AssertionError(
                    f"clock anchor {anchor} missing; trace_merge cannot "
                    "align this capture")
        doc = json.loads(rec.to_chrome_json())
        lint = load_tool("trace_lint")
        problems = lint.lint_trace(doc)
        merged = load_tool("trace_merge").merge([doc])
        problems += [f"post-merge: {p}" for p in lint.lint_trace(merged)]
        if problems:
            raise AssertionError(f"trace lint found: {problems[:3]}")
        return (out["fold_chain"][:16], len(doc["traceEvents"]), len(s),
                len(applies))
    finally:
        flight.uninstall()
        if outer is not None:
            flight.install(outer)
        shutil.rmtree(base_dir, ignore_errors=True)
        shutil.rmtree(rep_dir, ignore_errors=True)


def _write_smoke_trace(recorder):
    """Persist the sweep's flight-recorder trace; never fail the smoke."""
    path = os.environ.get("TPUSIM_SMOKE_TRACE") or os.path.join(
        os.path.dirname(__file__), "..", "bench_results", "smoke_trace.json")
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        recorder.write(path)
    except OSError as exc:
        print(f"SMOKE trace write failed: {exc}", flush=True)
        return
    print(f"SMOKE trace: {os.path.normpath(path)} "
          f"({len(recorder.events)} events)", flush=True)


def main() -> int:
    import jax

    from tpusim.obs import recorder as flight

    platform = jax.default_backend()
    only = [v for v in os.environ.get("TPUSIM_SMOKE_VARIANTS", "").split(",")
            if v]
    recorder = flight.install(flight.FlightRecorder())
    t0 = time.time()
    ran = 0
    try:
        for name, build, most in PALLAS_VARIANTS:
            if only and name not in only:
                continue
            t = time.time()
            vsp = flight.span("smoke_variant")
            vsp.set("variant", name)
            try:
                h, scheduled, total = run_pallas_variant(name, build, most)
            except Exception as exc:  # noqa: BLE001 — one line per failure
                vsp.set("parity", "FAILED")
                vsp.set("error", type(exc).__name__)
                vsp.end()
                print(f"SMOKE FAILED: {name}: {exc}", flush=True)
                return 1
            vsp.set("parity", "ok")
            vsp.set("hash", h)
            vsp.set("scheduled", f"{scheduled}/{total}")
            vsp.end()
            ran += 1
            print(f"SMOKE {name}: OK hash={h} scheduled={scheduled}/{total} "
                  f"({time.time() - t:.1f}s)", flush=True)
        if not only or "preempt_victim" in only:
            t = time.time()
            vsp = flight.span("smoke_variant")
            vsp.set("variant", "preempt_victim")
            try:
                h, n_victims, paths = run_preempt_variant()
            except Exception as exc:  # noqa: BLE001
                vsp.set("parity", "FAILED")
                vsp.set("error", type(exc).__name__)
                vsp.end()
                print(f"SMOKE FAILED: preempt_victim: {exc}", flush=True)
                return 1
            vsp.set("parity", "ok")
            vsp.set("hash", h)
            vsp.set("victims", n_victims)
            vsp.end()
            ran += 1
            print(f"SMOKE preempt_victim: OK hash={h} victims={n_victims} "
                  f"paths={paths} ({time.time() - t:.1f}s)", flush=True)
        if not only or "serve_fleet" in only:
            t = time.time()
            vsp = flight.span("smoke_variant")
            vsp.set("variant", "serve_fleet")
            try:
                h, n_req, stats = run_serve_fleet_variant()
            except Exception as exc:  # noqa: BLE001
                vsp.set("parity", "FAILED")
                vsp.set("error", type(exc).__name__)
                vsp.end()
                print(f"SMOKE FAILED: serve_fleet: {exc}", flush=True)
                return 1
            vsp.set("parity", "ok")
            vsp.set("hash", h)
            vsp.set("requests", n_req)
            vsp.end()
            ran += 1
            print(f"SMOKE serve_fleet: OK hash={h} requests={n_req} "
                  f"warm_hits={stats['warm_hits']} "
                  f"({time.time() - t:.1f}s)", flush=True)
        if not only or "chaos_breaker" in only:
            t = time.time()
            vsp = flight.span("smoke_variant")
            vsp.set("variant", "chaos_breaker")
            try:
                h, transitions = run_chaos_breaker_variant()
            except Exception as exc:  # noqa: BLE001
                vsp.set("parity", "FAILED")
                vsp.set("error", type(exc).__name__)
                vsp.end()
                print(f"SMOKE FAILED: chaos_breaker: {exc}", flush=True)
                return 1
            vsp.set("parity", "ok")
            vsp.set("hash", h)
            vsp.set("transitions", "->".join(transitions))
            vsp.end()
            ran += 1
            print(f"SMOKE chaos_breaker: OK hash={h} "
                  f"transitions={'->'.join(transitions)} "
                  f"({time.time() - t:.1f}s)", flush=True)
        if not only or "stream_churn" in only:
            t = time.time()
            vsp = flight.span("smoke_variant")
            vsp.set("variant", "stream_churn")
            try:
                h, scheduled, total, stream_cycles, traced = \
                    run_stream_churn_variant()
            except Exception as exc:  # noqa: BLE001
                vsp.set("parity", "FAILED")
                vsp.set("error", type(exc).__name__)
                vsp.end()
                print(f"SMOKE FAILED: stream_churn: {exc}", flush=True)
                return 1
            vsp.set("parity", "ok")
            vsp.set("hash", h)
            vsp.set("stream_cycles", stream_cycles)
            vsp.end()
            ran += 1
            retrace = ("skipped" if traced is None
                       else f"+{traced[0]}/+{traced[1]}")
            print(f"SMOKE stream_churn: OK hash={h} "
                  f"scheduled={scheduled}/{total} "
                  f"stream_cycles={stream_cycles} retrace={retrace} "
                  f"({time.time() - t:.1f}s)", flush=True)
        if not only or "stream_policy" in only:
            t = time.time()
            vsp = flight.span("smoke_variant")
            vsp.set("variant", "stream_policy")
            try:
                h, scheduled, total, pipelined_cycles, traced = \
                    run_stream_policy_variant()
            except Exception as exc:  # noqa: BLE001
                vsp.set("parity", "FAILED")
                vsp.set("error", type(exc).__name__)
                vsp.end()
                print(f"SMOKE FAILED: stream_policy: {exc}", flush=True)
                return 1
            vsp.set("parity", "ok")
            vsp.set("hash", h)
            vsp.set("pipelined_cycles", pipelined_cycles)
            vsp.end()
            ran += 1
            retrace = ("skipped" if traced is None
                       else f"+{traced[0]}/+{traced[1]}/+{traced[2]}")
            print(f"SMOKE stream_policy: OK hash={h} "
                  f"scheduled={scheduled}/{total} "
                  f"pipelined_cycles={pipelined_cycles} retrace={retrace} "
                  f"({time.time() - t:.1f}s)", flush=True)
        if not only or "stream_recover" in only:
            t = time.time()
            vsp = flight.span("smoke_variant")
            vsp.set("variant", "stream_recover")
            try:
                h, resume_cycle, wal_records, traced = \
                    run_stream_recover_variant()
            except Exception as exc:  # noqa: BLE001
                vsp.set("parity", "FAILED")
                vsp.set("error", type(exc).__name__)
                vsp.end()
                print(f"SMOKE FAILED: stream_recover: {exc}", flush=True)
                return 1
            vsp.set("parity", "ok")
            vsp.set("hash", h)
            vsp.set("resume_cycle", resume_cycle)
            vsp.end()
            ran += 1
            retrace = ("skipped" if traced is None
                       else f"+{traced[0]}/+{traced[1]}")
            print(f"SMOKE stream_recover: OK hash={h} "
                  f"resume_cycle={resume_cycle} wal_records={wal_records} "
                  f"retrace={retrace} ({time.time() - t:.1f}s)", flush=True)
        if not only or "standby" in only:
            t = time.time()
            vsp = flight.span("smoke_variant")
            vsp.set("variant", "standby")
            try:
                h, rto_ms, replayed, wal_records, traced = \
                    run_standby_variant()
            except Exception as exc:  # noqa: BLE001
                vsp.set("parity", "FAILED")
                vsp.set("error", type(exc).__name__)
                vsp.end()
                print(f"SMOKE FAILED: standby: {exc}", flush=True)
                return 1
            vsp.set("parity", "ok")
            vsp.set("hash", h)
            vsp.set("rto_ms", round(rto_ms, 2))
            vsp.end()
            ran += 1
            retrace = ("skipped" if traced is None
                       else f"+{traced[0]}/+{traced[1]}")
            print(f"SMOKE standby: OK hash={h} rto_ms={rto_ms:.1f} "
                  f"replayed={replayed}/{wal_records} retrace={retrace} "
                  f"({time.time() - t:.1f}s)", flush=True)
        if not only or "live_whatif" in only:
            t = time.time()
            vsp = flight.span("smoke_variant")
            vsp.set("variant", "live_whatif")
            try:
                h, answered, retraces = run_live_whatif_variant()
            except Exception as exc:  # noqa: BLE001
                vsp.set("parity", "FAILED")
                vsp.set("error", type(exc).__name__)
                vsp.end()
                print(f"SMOKE FAILED: live_whatif: {exc}", flush=True)
                return 1
            vsp.set("parity", "ok")
            vsp.set("hash", h)
            vsp.set("answered", answered)
            vsp.end()
            ran += 1
            print(f"SMOKE live_whatif: OK hash={h} answered={answered} "
                  f"retrace=+{retraces} ({time.time() - t:.1f}s)", flush=True)
        if not only or "analytics" in only:
            t = time.time()
            vsp = flight.span("smoke_variant")
            vsp.set("variant", "analytics")
            try:
                h, n_samples, sources = run_analytics_variant()
            except Exception as exc:  # noqa: BLE001
                vsp.set("parity", "FAILED")
                vsp.set("error", type(exc).__name__)
                vsp.end()
                print(f"SMOKE FAILED: analytics: {exc}", flush=True)
                return 1
            vsp.set("parity", "ok")
            vsp.set("hash", h)
            vsp.set("samples", n_samples)
            vsp.end()
            ran += 1
            print(f"SMOKE analytics: OK hash={h} samples={n_samples} "
                  f"sources={'+'.join(sources)} "
                  f"({time.time() - t:.1f}s)", flush=True)
        if not only or "gang" in only:
            t = time.time()
            vsp = flight.span("smoke_variant")
            vsp.set("variant", "gang")
            try:
                h, n_pods, n_msgs = run_gang_variant()
            except Exception as exc:  # noqa: BLE001
                vsp.set("parity", "FAILED")
                vsp.set("error", type(exc).__name__)
                vsp.end()
                print(f"SMOKE FAILED: gang: {exc}", flush=True)
                return 1
            vsp.set("parity", "ok")
            vsp.set("hash", h)
            vsp.end()
            ran += 1
            print(f"SMOKE gang: OK hash={h} pods={n_pods} "
                  f"shared_fit_msgs={n_msgs} "
                  f"({time.time() - t:.1f}s)", flush=True)
        if not only or "sharded" in only:
            t = time.time()
            vsp = flight.span("smoke_variant")
            vsp.set("variant", "sharded")
            try:
                out = run_sharded_variant()
            except Exception as exc:  # noqa: BLE001
                vsp.set("parity", "FAILED")
                vsp.set("error", type(exc).__name__)
                vsp.end()
                print(f"SMOKE FAILED: sharded: {exc}", flush=True)
                return 1
            if out is None:
                vsp.set("parity", "skipped")
                vsp.end()
                print("SMOKE sharded: SKIPPED (needs >= 2 devices)",
                      flush=True)
            else:
                h, n_shards, traced = out
                vsp.set("parity", "ok")
                vsp.set("hash", h)
                vsp.set("shards", n_shards)
                vsp.end()
                ran += 1
                retrace = "skipped" if traced is None else f"+{traced}"
                print(f"SMOKE sharded: OK hash={h} shards={n_shards} "
                      f"retrace={retrace} ({time.time() - t:.1f}s)",
                      flush=True)
        if not only or "traced_fleet" in only:
            t = time.time()
            vsp = flight.span("smoke_variant")
            vsp.set("variant", "traced_fleet")
            try:
                h, n_events, n_flows, n_applies = run_traced_fleet_variant()
            except Exception as exc:  # noqa: BLE001
                vsp.set("parity", "FAILED")
                vsp.set("error", type(exc).__name__)
                vsp.end()
                print(f"SMOKE FAILED: traced_fleet: {exc}", flush=True)
                return 1
            vsp.set("parity", "ok")
            vsp.set("hash", h)
            vsp.set("events", n_events)
            vsp.end()
            ran += 1
            print(f"SMOKE traced_fleet: OK hash={h} events={n_events} "
                  f"wal_flows={n_flows} replay_spans={n_applies} "
                  f"({time.time() - t:.1f}s)", flush=True)
    finally:
        flight.uninstall()
        _write_smoke_trace(recorder)
    print(f"SMOKE COMPLETE: {ran} variants, platform={platform} "
          f"({time.time() - t0:.1f}s)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

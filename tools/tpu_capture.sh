#!/usr/bin/env bash
# Round-4 TPU capture runbook: run the moment the axon tunnel heals.
# Sequential by design — ONE TPU client at a time; never kill -9 a child
# (bench.py's own watchdog stops children SIGINT-first).
#
# Produces, under bench_results/:
#   r4_tpu_ladder.jsonl   — configs 1-6 (incl. the preemption hybrid)
#   r4_tpu_fast.jsonl     — Pallas fastscan on configs 3-4 (TPUSIM_FAST=1);
#                           hash parity vs the XLA scan is checked by
#                           comparing placement_hash fields across the files
#   r4_tpu_phases.jsonl   — unroll + wavefront K sweeps and the phase split
#
# Each stage prints partial JSON lines as it goes, so a mid-run wedge still
# leaves the completed stages on disk.

set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results

run_stage() {
    # run_stage <name> <jsonl-out> <log-out> <command...>
    # The pipe lives INSIDE this function so its status (pipefail: the
    # command's own exit) is checked at function scope — an `exit` here
    # terminates the script, not a pipeline subshell.
    local name="$1" out="$2" log="$3"
    shift 3
    "$@" 2> >(tee "$log" >&2) | tee "$out"
    local st=$?
    if [ "$st" -ne 0 ]; then
        echo "== stage '$name' FAILED (exit $st); aborting — partial JSONL" \
             "is on disk; do not start another TPU client against a" \
             "possibly wedged tunnel ==" >&2
        exit 1
    fi
}

probe() {
    timeout 60 python -c "
import jax; d = jax.devices()
import jax.numpy as jnp
assert int(jnp.ones((8, 8)).sum()) == 64
print('PROBE OK:', d)" 2>&1 | tail -1
}

echo "== pre-flight probe =="
if ! probe | grep -q "PROBE OK"; then
    echo "tunnel not healthy; aborting (re-run when the probe passes)" >&2
    exit 1
fi

echo "== stage 1: full ladder (configs 1-6) =="
run_stage ladder bench_results/r4_tpu_ladder.jsonl \
    bench_results/r4_tpu_ladder.log python bench.py --ladder

echo "== stage 2: Pallas fastscan, configs 3-4 =="
run_stage fastscan bench_results/r4_tpu_fast.jsonl \
    bench_results/r4_tpu_fast.log \
    env TPUSIM_FAST=1 TPUSIM_BENCH_LADDER_CONFIGS=3,4 python bench.py --ladder

echo "== stage 3: config-5 warm-cache pair (criterion: 2nd fresh-process run <60s) =="
run_stage whatif1 bench_results/r4_tpu_whatif1.jsonl \
    bench_results/r4_tpu_whatif1.log \
    env TPUSIM_BENCH_LADDER_CONFIGS=5 TPUSIM_BENCH_TPU_AUTOLADDER=0 \
    python bench.py --ladder
t_start=$(date +%s)
run_stage whatif2 bench_results/r4_tpu_whatif2.jsonl \
    bench_results/r4_tpu_whatif2.log \
    env TPUSIM_BENCH_LADDER_CONFIGS=5 TPUSIM_BENCH_TPU_AUTOLADDER=0 \
    python bench.py --ladder
t_end=$(date +%s)
echo "== config-5 second-run wall: $((t_end - t_start))s (criterion <60s for the child's end-to-end; see [config 5] line in r4_tpu_whatif2.log) =="

echo "== stage 4: phase split + unroll/wavefront sweeps ==" 
run_stage phases bench_results/r4_tpu_phases.jsonl \
    bench_results/r4_tpu_phases.log python bench.py --phases

echo "== hash parity check (fastscan vs XLA scan) =="
if ! python - <<'EOF'
import json, re, sys

def hashes(path):
    out = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    # truncated trailing line from a mid-run wedge: keep the
                    # completed records
                    continue
                m = re.search(r"(config \d).*placement_hash=([0-9a-f]+)",
                              rec.get("metric", ""))
                if m:
                    out[m.group(1)] = m.group(2)
    except OSError:
        pass
    return out

ladder = hashes("bench_results/r4_tpu_ladder.jsonl")
fast = hashes("bench_results/r4_tpu_fast.jsonl")
ok = True
for cfg, h in fast.items():
    want = ladder.get(cfg)
    status = "MATCH" if h == want else f"MISMATCH (xla={want})"
    if h != want:
        ok = False
    print(f"{cfg}: fastscan={h} {status}")
if not fast:
    print("no fastscan hashes captured", file=sys.stderr)
    ok = False
sys.exit(0 if ok else 1)
EOF
then
    echo "== PARITY CHECK FAILED — do not record the fastscan rate ==" >&2
    exit 1
fi
echo "== capture complete; update BASELINE.md with the numbers above =="

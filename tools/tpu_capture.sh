#!/usr/bin/env bash
# Round-5 TPU capture runbook: run whenever the axon tunnel is healthy.
# Sequential by design — ONE TPU client at a time; never kill -9 a child
# (bench.py's own watchdog stops children SIGINT-first).
#
# IDEMPOTENT: each stage declares WHICH configs its artifact must hold on
# TPU and is skipped only when every one of them is present (a partial
# artifact from a mid-stage wedge re-runs); a stage FAILS (exit 1, so
# tools/tpu_watch.sh retries at the next healthy probe) when the run fell
# back to CPU or still left configs missing — a wedge/heal cycle therefore
# resumes exactly at the first incomplete TPU artifact.
#
# STAGE ORDER is priority order (round-5 VERDICT #1): the Pallas fastscan
# evidence comes FIRST — if the window allows nothing else, take that.
# Hash parity for the fastscan is checked against the freshest XLA-scan
# ladder records available (r5, falling back to r4: the ladder workloads
# are seed-deterministic and the XLA scan's placements are pinned by
# goldens — r2 and r4 produced identical platform=tpu hashes for configs
# 3 and 4, so cross-round comparison is sound).
#
# Produces, under bench_results/:
#   r5_tpu_fast.jsonl     — Pallas fastscan on configs 3-4 (TPUSIM_FAST=1)
#   r5_tpu_preempt.jsonl  — config 6, the preemption hybrid
#   r5_tpu_whatif1/2.jsonl — config-5 cold/warm compile-cache pair
#   r5_tpu_ladder.jsonl   — configs 1-5 XLA-scan ladder
#   r5_tpu_phases.jsonl   — unroll sweep and the phase split

set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results

stage_done() {
    # stage_done <file> <spec>: is the artifact TPU-complete?
    # spec "configs:3,4" = a platform=tpu record per config number;
    # spec "pallas:3,4"  = same, but ONLY records whose mode string is
    #                      "exact scan (pallas)" count — bench.py's
    #                      never-crash path relabels a Mosaic failure as a
    #                      plain XLA run, which must NOT satisfy the
    #                      fastscan stage (it would silently skip the
    #                      re-capture and make the parity check vacuous);
    # spec "phases"      = a platform=tpu record carrying the phase split
    python - "$1" "$2" <<'PYEOF'
import json, re, sys

path, spec = sys.argv[1], sys.argv[2]
need_pallas = spec.startswith("pallas:")
have = set()
phases_done = False
try:
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # truncated tail from a mid-run wedge
            metric = rec.get("metric", "")
            if "platform=tpu" not in metric:
                continue
            if need_pallas and "exact scan (pallas)" not in metric:
                continue  # XLA fallback relabel: not fastscan evidence
            # NOTE: a "partial" note still counts — children print a config
            # record only AFTER that config completes; the parent adds the
            # note when the stage was interrupted later
            m = re.search(r"config (\d)", metric)
            if m:
                have.add(m.group(1))
            if "phases" in rec:
                phases_done = True
except OSError:
    pass
if spec == "phases":
    sys.exit(0 if phases_done else 1)
want = set(spec.split(":", 1)[1].split(","))
sys.exit(0 if want <= have else 1)
PYEOF
}

run_stage() {
    # run_stage <name> <spec> <jsonl-out> <log-out> <command...>
    # Skips when the artifact already holds every expected TPU record;
    # aborts the script when the command fails OR the artifact is still
    # incomplete afterwards (CPU fallback / mid-stage wedge).
    local name="$1" spec="$2" out="$3" log="$4"
    shift 4
    if stage_done "$out" "$spec"; then
        echo "== stage '$name' already captured on TPU; skipping =="
        return 0
    fi
    "$@" 2> >(tee "$log" >&2) | tee "$out"
    local st=$?
    if [ "$st" -ne 0 ]; then
        echo "== stage '$name' FAILED (exit $st); aborting — partial JSONL" \
             "is on disk; do not start another TPU client against a" \
             "possibly wedged tunnel ==" >&2
        exit 1
    fi
    if ! stage_done "$out" "$spec"; then
        echo "== stage '$name' incomplete (CPU fallback or missing" \
             "configs); aborting so the watcher retries at the next" \
             "healthy probe ==" >&2
        exit 1
    fi
}

parity_check() {
    # fastscan-vs-XLA placement-hash parity, same-platform records only;
    # r5 ladder records win, r4 fills any config the r5 ladder lacks yet
    python - <<'EOF'
import json, re, sys

def hashes(path, need_pallas=False):
    out = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    # truncated trailing line from a mid-run wedge: keep the
                    # completed records
                    continue
                metric = rec.get("metric", "")
                if need_pallas and "exact scan (pallas)" not in metric:
                    continue  # XLA fallback relabel: comparing it to the
                    #           ladder would be XLA-vs-XLA, vacuously equal
                m = re.search(r"(config \d).*platform=(\w+).*"
                              r"placement_hash=([0-9a-f]+)", metric)
                if m:
                    # platform is part of the key: the CPU-fallback shapes
                    # are intentionally smaller, so cross-platform hashes
                    # differ by workload, not by placement divergence
                    out[(m.group(1), m.group(2))] = m.group(3)
    except OSError:
        pass
    return out

# cross-round fallback is sound: the ladder workloads are seeded and the
# XLA scan is golden-pinned (r2 == r4 hashes on configs 3-4, platform=tpu)
ladder = hashes("bench_results/r4_tpu_ladder.jsonl")
ladder.update(hashes("bench_results/r5_tpu_ladder.jsonl"))
fast = hashes("bench_results/r5_tpu_fast.jsonl", need_pallas=True)
ok = True
compared = 0
for key, h in sorted(fast.items()):
    want = ladder.get(key)
    if want is None:
        print(f"{key}: fastscan={h} (no same-platform ladder record; skipped)")
        continue
    compared += 1
    status = "MATCH" if h == want else f"MISMATCH (xla={want})"
    if h != want:
        ok = False
    print(f"{key}: fastscan={h} {status}")
if not compared:
    print("no comparable fastscan hashes captured", file=sys.stderr)
    ok = False
sys.exit(0 if ok else 1)
EOF
}

probe() {
    timeout 60 python -c "
import jax; d = jax.devices()
import jax.numpy as jnp
assert int(jnp.ones((8, 8)).sum()) == 64
print('PROBE OK:', d)" 2>&1 | tail -1
}

echo "== pre-flight probe =="
if ! probe | grep -q "PROBE OK"; then
    echo "tunnel not healthy; aborting (re-run when the probe passes)" >&2
    exit 1
fi
# the tunnel is provably healthy: drop any wedged-probe marker a previous
# stage left, or every bench child would skip its probe into CPU fallback
rm -f bench_results/.probe_wedged_at

smoke_done() {
    # the smoke certifies only when the whole sweep completed ON TPU —
    # an interpreter-mode (CPU) run proves nothing about Mosaic lowering
    grep -q "SMOKE COMPLETE: .* platform=tpu" \
        bench_results/r5_tpu_smoke.txt 2>/dev/null
}

echo "== stage 0: all-variants kernel smoke (tiny shapes, <60s on TPU) =="
if smoke_done; then
    echo "== stage 'smoke' already certified on TPU; skipping =="
else
    # one tiny batch per kernel-variant class (base/most-requested/ports/
    # disk/spread/vol-zone/interpod/maxpd + the preempt-victim kernel +
    # the scenario-fleet serve path + the streaming churn runtime + the
    # traced replicated fleet with its lint-clean trace export),
    # each hash-checked against the XLA scan in-process: even a ~2-minute
    # healthy window certifies Mosaic lowering of the whole surface
    if ! python tools/tpu_smoke.py \
            2> >(tee bench_results/r5_tpu_smoke.log >&2) \
            | tee bench_results/r5_tpu_smoke.txt; then
        echo "== stage 'smoke' FAILED — a kernel-variant class does not" \
             "lower or diverges from the XLA scan; aborting (the watcher" \
             "retries at the next healthy probe) ==" >&2
        exit 1
    fi
    if ! smoke_done; then
        echo "== smoke ran off-TPU (CPU fallback); aborting so the" \
             "watcher retries at the next healthy probe ==" >&2
        exit 1
    fi
fi

echo "== stage 1: Pallas fastscan, configs 3-4 (the round's #1 artifact) =="
run_stage fastscan pallas:3,4 bench_results/r5_tpu_fast.jsonl \
    bench_results/r5_tpu_fast.log \
    env TPUSIM_FAST=1 TPUSIM_BENCH_LADDER_CONFIGS=3,4 python bench.py --ladder

echo "== stage 1 parity (vs freshest XLA ladder records) =="
if parity_check; then
    rm -f bench_results/r5_parity_FAILED.txt
else
    # a MISMATCH is a Mosaic-vs-XLA numerics finding worth more than the
    # benchmark: preserve the artifacts and flag it loudly, but DON'T abort
    # — exiting here would dead-loop the watcher (the fastscan records
    # exist, so the stage skips and parity fails again) and starve every
    # later stage of its window. The final parity check governs exit code.
    parity_check > bench_results/r5_parity_FAILED.txt 2>&1 || true
    echo "== PARITY MISMATCH — preserved in r5_parity_FAILED.txt; the" \
         "fastscan rate is NOT trustworthy; continuing with later stages ==" >&2
fi

echo "== stage 2: preemption hybrid (config 6) =="
run_stage preempt configs:6 bench_results/r5_tpu_preempt.jsonl \
    bench_results/r5_tpu_preempt.log \
    env TPUSIM_BENCH_LADDER_CONFIGS=6 TPUSIM_BENCH_TPU_AUTOLADDER=0 \
    python bench.py --ladder

echo "== stage 3: config-5 warm-cache pair (criterion: 2nd fresh-process run <60s) =="
run_stage whatif1 configs:5 bench_results/r5_tpu_whatif1.jsonl \
    bench_results/r5_tpu_whatif1.log \
    env TPUSIM_BENCH_LADDER_CONFIGS=5 TPUSIM_BENCH_TPU_AUTOLADDER=0 \
    python bench.py --ladder
t_start=$(date +%s)
run_stage whatif2 configs:5 bench_results/r5_tpu_whatif2.jsonl \
    bench_results/r5_tpu_whatif2.log \
    env TPUSIM_BENCH_LADDER_CONFIGS=5 TPUSIM_BENCH_TPU_AUTOLADDER=0 \
    python bench.py --ladder
t_end=$(date +%s)
child_e2e=$(grep -o "what-if: [0-9.]*s end-to-end" \
    bench_results/r5_tpu_whatif2.log 2>/dev/null | tail -1 \
    | grep -o "[0-9.]*")
echo "== config-5 second-run wall: $((t_end - t_start))s; CHILD end-to-end" \
    "(the <60s warm-cache criterion — harness probe/spawn overhead is not" \
    "cache-warmness): ${child_e2e:-n/a}s; 0s wall = both runs were already" \
    "captured =="

echo "== stage 3b: scenario-fleet serving (config 8: scenarios/s, warm-cache + mesh curve) =="
run_stage serve configs:8 bench_results/r5_tpu_serve.jsonl \
    bench_results/r5_tpu_serve.log \
    env TPUSIM_BENCH_LADDER_CONFIGS=8 TPUSIM_BENCH_TPU_AUTOLADDER=0 \
    python bench.py --ladder

echo "== stage 3c: streaming runtime (config 9: O(delta) churn, stream-vs-restage) =="
run_stage stream configs:9 bench_results/r5_tpu_stream.jsonl \
    bench_results/r5_tpu_stream.log \
    env TPUSIM_BENCH_LADDER_CONFIGS=9 TPUSIM_BENCH_TPU_AUTOLADDER=0 \
    python bench.py --ladder

echo "== stage 3d: policy stream (config 10: residency churn + pipelined-vs-sync A/B) =="
run_stage policy_stream configs:10 bench_results/r5_tpu_policy_stream.jsonl \
    bench_results/r5_tpu_policy_stream.log \
    env TPUSIM_BENCH_LADDER_CONFIGS=10 TPUSIM_BENCH_TPU_AUTOLADDER=0 \
    python bench.py --ladder

echo "== stage 3e: crash recovery (config 11: replay-vs-interval curve + degraded serving) =="
run_stage recovery configs:11 bench_results/r5_tpu_recovery.jsonl \
    bench_results/r5_tpu_recovery.log \
    env TPUSIM_BENCH_LADDER_CONFIGS=11 TPUSIM_BENCH_TPU_AUTOLADDER=0 \
    python bench.py --ladder

echo "== stage 3f: gang admission (config 13: gang-cycle throughput + rack-spread A/B) =="
run_stage gang configs:13 bench_results/r5_tpu_gang.jsonl \
    bench_results/r5_tpu_gang.log \
    env TPUSIM_BENCH_LADDER_CONFIGS=13 TPUSIM_BENCH_TPU_AUTOLADDER=0 \
    python bench.py --ladder

echo "== stage 3g: sharded twin (config 14: pods/s vs shard count on the device mesh) =="
run_stage sharded configs:14 bench_results/r5_tpu_sharded.jsonl \
    bench_results/r5_tpu_sharded.log \
    env TPUSIM_BENCH_LADDER_CONFIGS=14 TPUSIM_BENCH_TPU_AUTOLADDER=0 \
    python bench.py --ladder

echo "== stage 3h: hot-standby failover (config 15: RTO-vs-cadence + replication-lag-vs-churn) =="
run_stage replication configs:15 bench_results/r5_tpu_replication.jsonl \
    bench_results/r5_tpu_replication.log \
    env TPUSIM_BENCH_LADDER_CONFIGS=15 TPUSIM_BENCH_TPU_AUTOLADDER=0 \
    python bench.py --ladder

echo "== stage 3i: live what-if serving (config 16: overlay-vs-staged curve + tenant round trip) =="
run_stage live_whatif configs:16 bench_results/r5_tpu_live_whatif.jsonl \
    bench_results/r5_tpu_live_whatif.log \
    env TPUSIM_BENCH_LADDER_CONFIGS=16 TPUSIM_BENCH_TPU_AUTOLADDER=0 \
    python bench.py --ladder

echo "== stage 4: full XLA ladder (configs 1-5; fresh same-round parity anchors) =="
run_stage ladder configs:1,2,3,4,5 bench_results/r5_tpu_ladder.jsonl \
    bench_results/r5_tpu_ladder.log \
    env TPUSIM_FAST=0 TPUSIM_BENCH_LADDER_CONFIGS=1,2,3,4,5 \
    python bench.py --ladder

echo "== stage 5: phase split + unroll sweep =="
run_stage phases phases bench_results/r5_tpu_phases.jsonl \
    bench_results/r5_tpu_phases.log python bench.py --phases

echo "== final hash parity check (now incl. same-round ladder records) =="
if ! parity_check; then
    echo "== PARITY CHECK FAILED — do not record the fastscan rate ==" >&2
    exit 1
fi
# the capture verified clean end-to-end: a stage-1 flag from comparing
# against r4-only anchors is superseded by the same-round check above
rm -f bench_results/r5_parity_FAILED.txt
echo "== capture complete; update BASELINE.md with the numbers above =="

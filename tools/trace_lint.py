#!/usr/bin/env python
"""Trace-artifact lint (ISSUE 20): Chrome trace_event validity.

Validates a ``--trace-out`` artifact (or a tools/trace_merge.py output)
the way Perfetto will read it, so a broken export fails in CI instead
of rendering as a silently-disconnected graph:

  - the document is Perfetto-loadable: a ``traceEvents`` list, known
    phase codes only (X / i / M / s / f), required fields per phase,
    non-negative ts and dur
  - per (pid, tid) the event stream is monotonic in the recorder's
    clock: events append at span END, so each event's emission time
    (ts+dur for X, ts otherwise) must be non-decreasing in file order,
    up to a small slack (--slack-us) for thread hand-off jitter — a
    violation beyond the slack means a clock went backwards or a merge
    shifted one process into another's past
  - every flow start ``s`` has a matching finish ``f`` on the same
    (cat, id) and vice versa (a dangling arrow means a hop lost its
    context), and every ``f`` carries ``bp: "e"``
  - optionally (--metrics), every exemplar trace id decorating a
    histogram exposition resolves to at least one event stamped with
    that trace_id — dashboards must be able to click through

Run standalone (``python tools/trace_lint.py trace.json [--metrics
metrics.prom]``; exit 1 on findings) or through tests/test_trace.py
(tier-1).
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Any, Dict, List

_KNOWN_PH = {"X", "i", "M", "s", "f"}
_EXEMPLAR_RE = re.compile(r'#\s*\{trace_id="([0-9a-f]+)"\}')


def lint_trace(doc: Dict[str, Any], slack_us: float = 5000.0) -> List[str]:
    """All validity violations in one Chrome trace document."""
    problems: List[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["document has no traceEvents list"]
    dtu = doc.get("displayTimeUnit", "ms")
    if dtu not in ("ms", "ns"):
        problems.append(f"displayTimeUnit {dtu!r} is not ms/ns")

    last_emit: Dict[tuple, float] = {}
    flow_s: Dict[tuple, int] = {}
    flow_f: Dict[tuple, int] = {}
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        name = ev.get("name", "?")
        where = f"event {i} ({name!r})"
        if ph not in _KNOWN_PH:
            problems.append(f"{where}: unknown phase {ph!r}")
            continue
        if "pid" not in ev:
            problems.append(f"{where}: no pid")
            continue
        if ph == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"{where}: bad ts {ts!r}")
            continue
        track = (ev["pid"], ev.get("tid"))
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: X event with bad dur {dur!r}")
                continue
            emit = ts + dur
        else:
            emit = ts
        prev = last_emit.get(track)
        if prev is not None and emit < prev - slack_us:
            problems.append(
                f"{where}: emission time {emit} jumps back "
                f"{round(prev - emit, 3)} us on pid/tid {track} — file "
                "order must follow the recorder clock (merge shift or "
                "clock regression)")
        last_emit[track] = max(emit, prev) if prev is not None else emit
        if ph == "s":
            flow_s[(ev.get("cat"), ev.get("id"))] = \
                flow_s.get((ev.get("cat"), ev.get("id")), 0) + 1
        elif ph == "f":
            if ev.get("bp") != "e":
                problems.append(f"{where}: flow finish without bp=e "
                                "(enclosing-slice binding)")
            flow_f[(ev.get("cat"), ev.get("id"))] = \
                flow_f.get((ev.get("cat"), ev.get("id")), 0) + 1
    for key in sorted(set(flow_s) - set(flow_f)):
        problems.append(f"flow {key[0]}:{key[1]}: start (s) without any "
                        "finish (f) — dangling arrow")
    for key in sorted(set(flow_f) - set(flow_s)):
        problems.append(f"flow {key[0]}:{key[1]}: finish (f) without a "
                        "start (s)")
    return problems


def lint_exemplars(doc: Dict[str, Any], metrics_text: str) -> List[str]:
    """Every exemplar trace id on the exposition resolves to at least
    one stamped event in the trace."""
    stamped = set()
    for ev in doc.get("traceEvents", []):
        tid = (ev.get("args") or {}).get("trace_id")
        if tid:
            stamped.add(tid)
    problems = []
    for trace_id in sorted(set(_EXEMPLAR_RE.findall(metrics_text))):
        if trace_id not in stamped:
            problems.append(f"exemplar trace_id {trace_id} on the metrics "
                            "exposition resolves to no event in the trace")
    return problems


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Validate a tpusim --trace-out artifact (Perfetto "
                    "loadability, per-track monotonicity, flow pairing, "
                    "exemplar resolution)")
    parser.add_argument("traces", nargs="+", help="Chrome trace JSON files")
    parser.add_argument("--metrics", default="",
                        help="A --metrics-out exposition: check its "
                             "exemplar trace ids resolve into the trace")
    parser.add_argument("--slack-us", type=float, default=5000.0,
                        help="Tolerated per-track backwards-jitter in "
                             "microseconds (thread hand-off races)")
    args = parser.parse_args(argv)
    rc = 0
    for path in args.traces:
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as exc:
            print(f"trace-lint: {path}: unreadable: {exc}", file=sys.stderr)
            rc = 1
            continue
        problems = lint_trace(doc, slack_us=args.slack_us)
        if args.metrics:
            with open(args.metrics, "r", encoding="utf-8") as f:
                problems += lint_exemplars(doc, f.read())
        for problem in problems:
            print(f"trace-lint: {path}: {problem}", file=sys.stderr)
        if problems:
            rc = 1
        else:
            n = len(doc.get("traceEvents", []))
            print(f"trace-lint: {path}: ok ({n} events)")
    return rc


if __name__ == "__main__":
    sys.exit(main())

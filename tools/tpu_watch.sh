#!/usr/bin/env bash
# Probe the axon tunnel every 10 min; on a healthy probe run the capture,
# exiting on success and resuming the watch after a mid-capture failure. Single TPU client by construction: the
# probe and the capture never overlap, and nothing else should touch the
# TPU while this runs (see bench_results/tpu_watch.log).
cd "$(dirname "$0")/.."
log=bench_results/tpu_watch.log
mkdir -p bench_results
# round-start PID check: a second watcher would mean two TPU clients
# racing the tunnel (probe vs capture), which is exactly the wedge this
# script exists to avoid — refuse to start while one is alive; a stale
# pidfile (dead pid) is reclaimed
pidfile=bench_results/tpu_watch.pid
if [ -f "$pidfile" ] && kill -0 "$(cat "$pidfile" 2>/dev/null)" 2>/dev/null; then
    echo "watcher already running (pid $(cat "$pidfile")); refusing to" \
         "start a second TPU client" >&2
    exit 1
fi
echo $$ > "$pidfile"
trap 'rm -f "$pidfile"' EXIT
echo "$(date -u +%H:%M:%S) watcher started (pid $$)" >> "$log"
while true; do
    if timeout 60 python -c "
import jax; jax.devices()
import jax.numpy as jnp
assert int(jnp.ones((8, 8)).sum()) == 64" >/dev/null 2>&1; then
        echo "$(date -u +%H:%M:%S) TUNNEL HEALED - starting capture" >> "$log"
        bash tools/tpu_capture.sh >> "$log" 2>&1
        rc=$?
        echo "$(date -u +%H:%M:%S) capture finished rc=$rc" >> "$log"
        if [ "$rc" -eq 0 ]; then
            exit 0
        fi
        # a mid-capture re-wedge leaves partial JSONL on disk; keep
        # watching and retry the capture at the next healthy probe
        echo "$(date -u +%H:%M:%S) capture failed; resuming watch" >> "$log"
    else
        echo "$(date -u +%H:%M:%S) probe failed" >> "$log"
    fi
    echo "$(date -u +%H:%M:%S) sleeping 600s" >> "$log"
    sleep 600
done

"""Benchmark: scheduled pods/sec, exact-scan jax backend vs the Python
reference loop (the stand-in for the Go loop — the reference publishes no
numbers and ships no buildable toolchain here; see BASELINE.md).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "pods/s", "vs_baseline": N}
vs_baseline = jax rate / reference-loop rate on the same workload shape (>1 is
faster). Details go to stderr. Never exits non-zero: on failure the JSON line
carries an "error" field instead.

Robustness model (the TPU tunnel here can wedge INSIDE backend init, with the
GIL held, at any attempt — including after a successful probe): the
measurement runs in a child process whose stderr is streamed through a stall
watchdog; no output for TPUSIM_BENCH_STALL_TIMEOUT seconds kills the child
and retries (bounded), then falls back to a CPU-sized run. The child prints a
JSON line after EACH completed stage (small → headline), so a later hang
still leaves the best completed result on stdout — the parent takes the last
JSON line, even from a killed child.

Workloads (BASELINE.md config ladder): the headline is config 3 — 100k mixed
Zipf-sized pods onto 5k heterogeneous nodes (taints/tolerations slice), exact
sequential semantics. `python bench.py --ladder` measures the full ladder
(20-pod quickstart; 1k uniform/100; 100k Zipf/5k; 1M/10k with
taints+affinity via the chunked donated scan; 50×20k batched what-if;
priority-band preemption; policy residue — label rows + ServiceAffinity +
ImageLocality on the 10k-node snapshot) and prints one JSON line per config
plus a summary line.

Before any measurement attempt the parent runs a PRE-FLIGHT PROBE: one tiny
device op in a subprocess under TPUSIM_BENCH_PROBE_TIMEOUT (40s). A wedged
tunnel therefore costs under a minute before a cleanly-labeled CPU fallback
("tpu_unavailable"), instead of burning the full retry ladder. Children are
never SIGKILLed while possibly inside a device op: SIGINT first, then
SIGTERM after a grace period, SIGKILL only as a last resort (a hard kill
mid-op has permanently wedged the tunnel before; see BASELINE.md).

Env knobs: TPUSIM_BENCH_PODS (default 100000), TPUSIM_BENCH_NODES (5000),
TPUSIM_BENCH_BASELINE_PODS (200),
TPUSIM_BENCH_STALL_TIMEOUT (240s), TPUSIM_BENCH_INIT_TIMEOUT (75s — stall
limit until the child reports its device list), TPUSIM_BENCH_PROBE_TIMEOUT
(40s), TPUSIM_BENCH_RUN_TIMEOUT (2400s),
TPUSIM_BENCH_RETRIES (2), TPUSIM_BENCH_CPU_PODS/_NODES (CPU-fallback shape),
TPUSIM_BENCH_CHUNK (131072; chunked-scan chunk length — the 100k headline runs as ONE dispatch, 1M runs 8 chunks of ~12s each, inside the stall watchdog), TPUSIM_SCAN_UNROLL,
TPUSIM_BENCH_LADDER_CONFIGS (ladder subset, e.g. "3,7"), TPUSIM_FAST=1
(Pallas fused-scan fast path for eligible group-free workloads; TPU only
unless TPUSIM_FAST_INTERPRET=1), TPUSIM_FAST_CHUNK (512),
TPUSIM_BENCH_DUAL_FAST=0 (disable the default-on TPU dual measurement that
emits a second "(pallas)" record with in-process hash parity per config).
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import re
import signal
import subprocess
import sys
import time
from typing import NamedTuple

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# idle-host envelope guard (VERDICT r5 Weak #3)
#
# A contended driver host can halve a median without any code regression
# (round 5: 6,232 pods/s under load vs 10,036 idle at the IDENTICAL
# placement hash). Every record is therefore compared against the last
# committed BENCH_r*.json record for the same (placement_hash, platform)
# whose load1 stamp looked idle; a >20% warm-median deviation is stamped
# into the record itself so the artifact trail carries the explanation.
# --------------------------------------------------------------------------

# load1 above this means the prior record itself ran contended and is no
# anchor; the committed idle records sit at 0.4-0.6
IDLE_LOAD1_MAX = float(os.environ.get("TPUSIM_BENCH_IDLE_LOAD1", "2.0"))


def _envelope_key(record: dict):
    """(placement_hash, platform) from a pods/s record's metric string, or
    None when the record carries no hash (hash equality is what pins 'same
    shape AND same placements' across rounds)."""
    if record.get("unit") != "pods/s":
        return None
    m = record.get("metric", "")
    ph = re.search(r"placement_hash=([0-9a-f]+)", m)
    pl = re.search(r"platform=(\w+)", m)
    if not ph or not pl:
        return None
    return ph.group(1), pl.group(1)


def _record_median_s(record: dict):
    """Comparable warm seconds: the warm_s median when the record has one,
    else the implied seconds-per-(value unit) — config-6 records are a
    single end-to-end run and ship no warm_s spread."""
    med = (record.get("warm_s") or {}).get("median")
    if med is not None:
        return float(med)
    value = record.get("value")
    if value:
        return 1.0 / float(value)
    return None


def load_idle_envelopes(bench_dir: str = None) -> dict:
    """(placement_hash, platform) -> (round_tag, warm_median_s) from the
    newest committed BENCH_r*.json whose record ran on an idle host
    (0 <= load1 <= IDLE_LOAD1_MAX) without an error flag."""
    if bench_dir is None:
        bench_dir = os.path.dirname(os.path.abspath(__file__))
    envelopes = {}
    for path in sorted(glob.glob(os.path.join(bench_dir, "BENCH_r*.json"))):
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        recs = doc.get("parsed")
        recs = [recs] if isinstance(recs, dict) else (recs or [])
        tag = re.search(r"(r\d+)", os.path.basename(path))
        tag = tag.group(1) if tag else os.path.basename(path)
        for rec in recs:
            if not isinstance(rec, dict) or rec.get("error"):
                continue
            key = _envelope_key(rec)
            med = _record_median_s(rec)
            load1 = rec.get("load1", -1.0)
            if key is None or med is None:
                continue
            if not 0 <= load1 <= IDLE_LOAD1_MAX:
                continue
            envelopes[key] = (tag, med)  # later rounds overwrite earlier
    return envelopes


_ENVELOPES = None


def stamp_envelope_deviation(result: dict, envelopes: dict = None) -> dict:
    """Stamp `envelope_deviation` (e.g. "+73% vs r04 idle") into `result`
    when its warm median deviates >20% from the last idle-host record for
    the same (placement_hash, platform). Mutates and returns `result`."""
    global _ENVELOPES
    if envelopes is None:
        if _ENVELOPES is None:
            _ENVELOPES = load_idle_envelopes()
        envelopes = _ENVELOPES
    key = _envelope_key(result)
    med = _record_median_s(result)
    if key is None or med is None or key not in envelopes:
        return result
    tag, idle_med = envelopes[key]
    dev = (med - idle_med) / idle_med
    if abs(dev) > 0.20:
        result["envelope_deviation"] = f"{dev:+.0%} vs {tag} idle"
    return result


# --------------------------------------------------------------------------
# workloads (BASELINE.md config ladder)
# --------------------------------------------------------------------------

def build_workload(num_pods: int, num_nodes: int, affinity: bool = False,
                   seed: int = 12345, priorities: bool = False):
    """Config-3 shape: heterogeneous nodes (taint slice, zone labels) + Zipf
    pods; affinity=True adds the config-4 node-affinity slice; priorities=True
    adds the config-6 priority bands (60% band 0, 30% band 500, 10% band
    1000 — saturation makes late high-priority pods preempt earlier ones)."""
    from tpusim.api.snapshot import ClusterSnapshot, make_node, make_pod

    rng = np.random.RandomState(seed)
    nodes = []
    for i in range(num_nodes):
        shape = i % 3
        milli_cpu = [4000, 8000, 16000][shape]
        memory = [8, 16, 32][shape] * 1024**3
        taints = None
        if i % 10 == 0:
            taints = [{"key": "dedicated", "value": "batch", "effect": "NoSchedule"}]
        nodes.append(make_node(f"node-{i}", milli_cpu=milli_cpu, memory=memory,
                               pods=110, labels={"zone": f"z{i % 4}"}, taints=taints))

    cpu_buckets = np.array([50, 100, 250, 500, 1000, 2000, 4000])
    mem_buckets = np.array([64, 128, 256, 512, 1024, 2048, 4096]) * 2**20
    weights = 1.0 / np.arange(1, len(cpu_buckets) + 1) ** 1.1
    weights /= weights.sum()
    cpu_idx = rng.choice(len(cpu_buckets), size=num_pods, p=weights)
    mem_idx = rng.choice(len(mem_buckets), size=num_pods, p=weights)
    tolerate = rng.rand(num_pods) < 0.1
    want_zone = rng.randint(0, 8, size=num_pods) if affinity else None

    pods = []
    for i in range(num_pods):
        kwargs = {}
        if tolerate[i]:
            kwargs["tolerations"] = [{"key": "dedicated", "operator": "Equal",
                                      "value": "batch", "effect": "NoSchedule"}]
        if affinity and want_zone[i] < 4:
            # config 4: half the pods pin a zone via required node affinity
            kwargs["affinity"] = {"nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [{"matchExpressions": [
                        {"key": "zone", "operator": "In",
                         "values": [f"z{want_zone[i]}"]}]}]}}}
        pod = make_pod(f"p-{i}", milli_cpu=int(cpu_buckets[cpu_idx[i]]),
                       memory=int(mem_buckets[mem_idx[i]]), **kwargs)
        if priorities:
            pod.spec.priority = int(rng.choice([0, 500, 1000],
                                               p=[0.6, 0.3, 0.1]))
        pods.append(pod)
    return ClusterSnapshot(nodes=nodes), pods


def uniform_workload(num_pods: int, num_nodes: int):
    from tpusim.api.snapshot import ClusterSnapshot, make_node, make_pod

    nodes = [make_node(f"node-{i}", milli_cpu=4000, memory=16 * 1024**3)
             for i in range(num_nodes)]
    pods = [make_pod(f"p-{i}", milli_cpu=1000, memory=1 * 2**30)
            for i in range(num_pods)]
    return ClusterSnapshot(nodes=nodes), pods


# Config-7 policy: every residue family the fused scan had to absorb —
# label-presence predicate rows (foo), ServiceAffinity over region (per
# -segment first-pod locks), NodeLabel preference (bar), SAA spreading over
# zone, and ImageLocality via the signature-table streaming path.
POLICY_RESIDUE = {
    "kind": "Policy", "apiVersion": "v1",
    "predicates": [
        {"name": "MatchNodeSelector"},
        {"name": "PodFitsResources"},
        {"name": "TestServiceAffinity",
         "argument": {"serviceAffinity": {"labels": ["region"]}}},
        {"name": "TestLabelsPresence",
         "argument": {"labelsPresence": {"labels": ["foo"],
                                         "presence": True}}},
    ],
    "priorities": [
        {"name": "LeastRequestedPriority", "weight": 1},
        {"name": "BalancedResourceAllocation", "weight": 1},
        {"name": "ImageLocalityPriority", "weight": 2},
        {"name": "TestServiceAntiAffinity", "weight": 3,
         "argument": {"serviceAntiAffinity": {"label": "zone"}}},
        {"name": "TestLabelPreference", "weight": 2,
         "argument": {"labelPreference": {"label": "bar",
                                          "presence": True}}},
    ],
}


def policy_residue_workload(num_pods: int, num_nodes: int, seed: int = 777):
    """Config-7 shape: config-3 Zipf resource pressure plus the label /
    service / image structure POLICY_RESIDUE reads — region (4 domains,
    ServiceAffinity), zone (6 domains, SAA spreading), foo on 2/3 of nodes
    (presence rows), bar on half (NodeLabel preference), an 8-image catalog
    on odd nodes (ImageLocality). Half the services are seeded with running
    pods (pre-bound region locks); the rest bind their first-pod lock
    inside the scan — the carry slots the fast path has to thread."""
    from tpusim.api.snapshot import ClusterSnapshot, make_node, make_pod
    from tpusim.api.types import ContainerImage, Service

    MB = 1024 * 1024
    rng = np.random.RandomState(seed)
    nodes = []
    for i in range(num_nodes):
        shape = i % 3
        labels = {"region": f"r{i % 4}", "zone": f"z{i % 6}"}
        if i % 3 != 2:
            labels["foo"] = "x"
        if i % 2 == 0:
            labels["bar"] = "y"
        node = make_node(f"node-{i}", milli_cpu=[4000, 8000, 16000][shape],
                         memory=[8, 16, 32][shape] * 1024**3, pods=110,
                         labels=labels)
        if i % 2 == 1:
            node.status.images = [
                ContainerImage(names=[f"img-{j}:v1"], size_bytes=400 * MB)
                for j in range(8) if (i + j) % 3 == 0]
        nodes.append(node)

    n_svc = 6
    services = [Service.from_obj({
        "metadata": {"name": f"svc{j}", "namespace": "default"},
        "spec": {"selector": {"app": f"app{j}"}}}) for j in range(n_svc)]
    placed = [make_pod(f"placed-{i}", milli_cpu=200, memory=128 * MB,
                       node_name=f"node-{i % num_nodes}", phase="Running",
                       labels={"app": f"app{i % (n_svc // 2)}"})
              for i in range(min(num_nodes, 64))]

    cpu_buckets = np.array([50, 100, 250, 500, 1000, 2000, 4000])
    mem_buckets = np.array([64, 128, 256, 512, 1024, 2048, 4096]) * 2**20
    weights = 1.0 / np.arange(1, len(cpu_buckets) + 1) ** 1.1
    weights /= weights.sum()
    cpu_idx = rng.choice(len(cpu_buckets), size=num_pods, p=weights)
    mem_idx = rng.choice(len(mem_buckets), size=num_pods, p=weights)
    pods = []
    for i in range(num_pods):
        kw = {}
        if i % 5 == 0:
            kw["node_selector"] = {"region": f"r{i % 4}"}
        pod = make_pod(f"p-{i}", milli_cpu=int(cpu_buckets[cpu_idx[i]]),
                       memory=int(mem_buckets[mem_idx[i]]),
                       labels={"app": f"app{i % n_svc}"} if i % 3 else None,
                       **kw)
        if i % 4 == 0:
            pod.spec.containers[0].image = f"img-{i % 8}:v1"
        pods.append(pod)
    return ClusterSnapshot(nodes=nodes, pods=placed, services=services), pods


# --------------------------------------------------------------------------
# child: the measurements (inside the watchdogged subprocess)
# --------------------------------------------------------------------------

def _prepare(snapshot, pods, provider_most_requested=False, to_device=True):
    """to_device=False keeps the pod columns host-side — the chunked scan
    uploads them chunk by chunk, so the full [P]-row PodX never lands in HBM
    at once (the point of the donated chunk loop)."""
    from tpusim.jaxe.kernels import (
        carry_init,
        config_for,
        pod_columns_to_device,
        pod_columns_to_host,
        statics_to_device,
    )
    from tpusim.jaxe.state import NUM_FIXED_BITS, compile_cluster

    t0 = time.perf_counter()
    compiled, cols = compile_cluster(snapshot, pods)
    log(f"  host compile (intern+tables): {time.perf_counter() - t0:.1f}s")
    config = config_for(
        [compiled], most_requested=provider_most_requested,
        num_reason_bits=NUM_FIXED_BITS + len(compiled.scalar_names))
    carry = carry_init(compiled)
    statics = statics_to_device(compiled)
    xs = (pod_columns_to_device(cols) if to_device
          else pod_columns_to_host(cols))
    return compiled, config, carry, statics, xs, cols


def _prepare_policy(snapshot, pods, policy, to_device=True):
    """Policy-aware _prepare: compile the policy-as-data, build the static
    residue tables once (label rows, NodeLabel priority, image signatures,
    SAA domains, ServiceAffinity pins — policyc.build_policy_tables), and
    graft them into the XLA statics plus the sa_lock carry, exactly as
    backend._schedule_on_device does. Returns the tables as a 7th element
    so plan_fast can prove fast-path eligibility for the same config."""
    from dataclasses import replace as _dc_replace

    from tpusim.engine.policy import decode_policy
    from tpusim.engine.predicates import (
        POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED,
    )
    from tpusim.jaxe.kernels import (
        _tree_to_device,
        carry_init,
        config_for,
        pod_columns_to_device,
        pod_columns_to_host,
        statics_to_host,
    )
    from tpusim.jaxe.policyc import build_policy_tables, compile_policy
    from tpusim.jaxe.state import NUM_FIXED_BITS, compile_cluster

    cp = compile_policy(decode_policy(policy))
    if cp.unsupported:
        raise ValueError(f"policy unsupported: {cp.unsupported}")
    need_noexec = (cp.spec.pred_keys is not None
                   and POD_TOLERATES_NODE_NO_EXECUTE_TAINTS_PRED
                   in cp.spec.pred_keys)
    need_saa = bool(cp.spec.saa_weights) or cp.spec.sa_enabled
    t0 = time.perf_counter()
    compiled, cols = compile_cluster(snapshot, pods, need_noexec=need_noexec,
                                     need_saa=need_saa)
    log(f"  host compile (intern+tables): {time.perf_counter() - t0:.1f}s")
    config = config_for(
        [compiled], most_requested=False,
        num_reason_bits=NUM_FIXED_BITS + len(compiled.scalar_names))
    config = _dc_replace(config, policy=cp.spec)
    # fills cols.img_id / cols.sa_self_id in place — must run before the
    # pod columns are shipped to the device
    ptabs = build_policy_tables(cp, snapshot, pods, compiled, cols)
    if cp.saa_entries:
        config = _dc_replace(config, n_saa_doms=ptabs.n_saa_doms)
    statics = _tree_to_device(statics_to_host(compiled)._replace(
        label_ok=ptabs.label_ok, label_prio=ptabs.label_prio,
        image_score=ptabs.image_score, saa_dom=ptabs.saa_dom,
        sa_pin=ptabs.sa_pin, sa_val=ptabs.sa_val))
    carry = carry_init(compiled)._replace(sa_lock=ptabs.sa_lock_init)
    xs = (pod_columns_to_device(cols) if to_device
          else pod_columns_to_host(cols))
    return compiled, config, carry, statics, xs, cols, ptabs


def _checksum(choices) -> int:
    """Placement checksum; fetching it as a host scalar provably forces the
    computation that produced `choices` (unlike block_until_ready on the
    axon runtime, which has been observed returning early)."""
    return int(np.sum(np.where(np.asarray(choices) >= 0,
                               np.asarray(choices), -1)))


def _run_once(config, carry, statics, xs, chunk: int):
    """One full scheduling pass; returns (choices np, checksum int, counts).

    Batches longer than `chunk` run through the double-buffered donated-carry
    chunked scan (bounded HBM churn, overlapped transfers, progress logging)."""
    from tpusim.jaxe.kernels import (
        schedule_scan,
        schedule_scan_chunked,
    )

    p = int(xs.req_cpu.shape[0])
    if chunk and p > chunk:
        t0 = time.perf_counter()

        def prog(ci, total, done):
            log(f"  chunk {ci}/{total}: {done}/{p} pods done "
                f"({time.perf_counter() - t0:.1f}s)")

        # xs holds host columns (measure_config keeps big batches on host)
        _, choices, counts, _ = schedule_scan_chunked(
            config, carry, statics, xs, chunk, progress=prog)
        return choices, _checksum(choices), counts
    else:
        _, choices, counts, _ = schedule_scan(config, carry, statics, xs)
    return np.asarray(choices), _checksum(choices), np.asarray(counts)


def _metrics_snapshot(reset: bool = False) -> dict:
    """Per-config snapshot of the framework metrics registry (ISSUE 2):
    every BENCH record embeds one so the trajectory files say which
    path (route/AUTO transitions/victim split) produced each number."""
    from tpusim.framework.metrics import register

    reg = register()
    snap = reg.snapshot()
    if reset:
        reg.reset()
    return snap


def measure_config(name: str, snapshot, pods, platform: str,
                   baseline_pods: int, chunk: int, timed_runs: int = 3,
                   policy=None):
    """Measure one ladder config; returns the result dict. `policy` (a
    policy-as-data dict) routes both the reference loop and the device scan
    through the compiled policy; fast-path eligibility for it is probed on
    every platform (planning is host-only) and stamped on the record."""
    from tpusim.backends import ReferenceBackend
    from tpusim.jaxe.kernels import carry_init

    num_pods, num_nodes = len(pods), len(snapshot.nodes)
    log(f"[{name}] {num_pods} pods x {num_nodes} nodes")
    _metrics_snapshot(reset=True)  # per-config registry window

    ref_rate = None
    mismatches = None
    sub = min(baseline_pods, num_pods)
    if sub:
        if policy is not None:
            from tpusim.engine.policy import decode_policy
            ref_backend = ReferenceBackend(policy=decode_policy(policy))
        else:
            ref_backend = ReferenceBackend()
        t0 = time.perf_counter()
        ref_placements = ref_backend.schedule(pods[:sub], snapshot)
        ref_elapsed = max(time.perf_counter() - t0, 1e-9)
        ref_rate = sub / ref_elapsed
        log(f"  reference loop: {sub} pods in {ref_elapsed:.1f}s "
            f"= {ref_rate:.1f} pods/s")

    use_chunks = bool(chunk) and num_pods > chunk
    ptabs = None
    if policy is not None:
        compiled, config, carry, statics, xs, cols, ptabs = _prepare_policy(
            snapshot, pods, policy, to_device=not use_chunks)
    else:
        compiled, config, carry, statics, xs, cols = _prepare(
            snapshot, pods, to_device=not use_chunks)
    if compiled.unsupported:
        return {"metric": f"{name} (unsupported: {compiled.unsupported})",
                "value": 0, "unit": "pods/s", "vs_baseline": 0}

    fast_probe = None
    if policy is not None:
        # eligibility evidence on every platform (host-only planning): the
        # measured pallas record itself needs a TPU (dual mode below)
        from tpusim.jaxe.fastscan import plan_fast as _probe_plan_fast

        fast_probe = _probe_plan_fast(config, compiled, cols, ptabs=ptabs)
        log("  policy fast-path: "
            + ("eligible" if fast_probe[0] is not None
               else f"ineligible ({fast_probe[1]})"))

    fast_plan = None
    fast_env = os.environ.get("TPUSIM_FAST")
    # dual mode (AUTO on TPU, VERDICT r4 item 5): measure the XLA scan AND
    # the Pallas fastscan in one child, emitting a second "(pallas)" record
    # with in-process hash parity — so a single driver-captured BENCH run
    # demonstrates the kernel without any builder-invoked stages
    dual_fast = (fast_env is None and platform == "tpu"
                 and os.environ.get("TPUSIM_BENCH_DUAL_FAST", "1") != "0")
    if fast_env == "1" or dual_fast:
        # one shared gate (env flag + interpreter override + tpu backend):
        # off-TPU the kernel would run in the Pallas interpreter, which is
        # meaningless as a benchmark
        from tpusim.jaxe.backend import _fast_path_enabled
        from tpusim.jaxe.fastscan import fast_scan, plan_fast

        if fast_env == "1" and not _fast_path_enabled()[0]:
            log("  TPUSIM_FAST requested but backend is not TPU; "
                "using the XLA scan (set TPUSIM_FAST_INTERPRET=1 to force "
                "the interpreter for correctness checks)")
        else:
            fast_plan, why = (fast_probe if fast_probe is not None
                              else plan_fast(config, compiled, cols))
            if fast_plan is None:
                log(f"  pallas fast path ineligible ({why}); "
                    "using the XLA scan")
            else:
                log("  pallas fast path eligible"
                    + (" (dual mode: XLA scan first, then pallas)"
                       if dual_fast else ""))
    if dual_fast:
        # the primary measurement below stays the XLA scan; the fastscan
        # runs after it via measure_fast_extra (skipped on checksum drift)
        dual_plan, fast_plan = fast_plan, None

    def one_pass(carry):
        nonlocal fast_plan
        if fast_plan is not None:
            t_start = time.perf_counter()

            def prog(ci, total, done):
                log(f"  fast chunk {ci}/{total}: {done}/{num_pods} pods "
                    f"({time.perf_counter() - t_start:.1f}s)")

            try:
                f_choices, f_counts, _adv = fast_scan(fast_plan,
                                                      progress=prog)
            except Exception as exc:
                # never crash the child mid-device-context (an abrupt exit
                # has wedged the axon tunnel before — BASELINE.md round-4
                # postmortem); degrade to the XLA scan and relabel the run
                log(f"  pallas fast path FAILED ({type(exc).__name__}: "
                    f"{exc}); falling back to the XLA scan")
                fast_plan = None
            else:
                return f_choices, _checksum(f_choices), f_counts
        return _run_once(config, carry, statics, xs, chunk)

    t0 = time.perf_counter()
    choices, checksum, counts = one_pass(carry)
    cold = time.perf_counter() - t0
    log(f"  device cold (incl XLA compile): {cold:.1f}s (checksum={checksum})")

    warm_times = []
    drift = False
    for _ in range(timed_runs):
        carry = carry_init(compiled)  # fresh carry (the donated one is gone)
        if ptabs is not None:
            carry = carry._replace(sa_lock=ptabs.sa_lock_init)
        t0 = time.perf_counter()
        choices, cs, counts = one_pass(carry)
        warm_times.append(time.perf_counter() - t0)
        if cs != checksum:
            drift = True
            log(f"  WARNING: checksum drift {checksum} -> {cs}")
    warm = float(np.median(warm_times))
    rate = num_pods / warm
    scheduled = int(np.sum(choices >= 0))
    phash = hashlib.sha256(choices.tobytes()).hexdigest()[:16]
    try:
        load1 = os.getloadavg()[0]
    except OSError:
        load1 = -1.0
    log(f"  device warm (median of {[f'{t:.3f}' for t in warm_times]}): "
        f"{num_pods} pods in {warm:.2f}s = {rate:.0f} pods/s "
        f"({scheduled} scheduled, {num_pods - scheduled} unschedulable) "
        f"placement_hash={phash} load1={load1:.1f}")

    if sub:
        names = compiled.statics.names
        mismatches = sum(
            1 for i in range(sub)
            if (names[choices[i]] if choices[i] >= 0 else "")
            != ref_placements[i].node_name)
        log(f"  parity check on first {sub} pods: {mismatches} mismatches")

    # the ladder drives the kernels directly (not backend.schedule), so the
    # route/dispatch families are fed here from the measured passes
    from tpusim.framework.metrics import register as _register_metrics

    _reg = _register_metrics()
    for t in [cold] + warm_times:
        _reg.backend_dispatch_latency.observe(t * 1e6)
    _reg.backend_route.inc(
        "fastscan" if fast_plan is not None
        else ("xla_chunked" if use_chunks else "xla_scan"),
        1 + len(warm_times))

    mode = "exact scan (pallas)" if fast_plan is not None else "exact scan"
    result = {
        "metric": f"scheduled pods/sec ({name}, {mode}, platform={platform}"
                  + (f", parity_mismatches={mismatches}" if mismatches is not None else "")
                  + f", placement_hash={phash})",
        "value": round(rate, 1),
        "unit": "pods/s",
        "vs_baseline": round(rate / ref_rate, 2) if ref_rate else 0,
        # variance envelope + host-load stamp (VERDICT r3 item 6): a shared
        # host can't distinguish a real regression from noise on a single
        # median — ship the spread and the load average with every record
        "warm_runs": len(warm_times),
        "warm_s": {"min": round(min(warm_times), 3),
                   "median": round(warm, 3),
                   "max": round(max(warm_times), 3)},
        "load1": round(load1, 2),
        "metrics": _metrics_snapshot(reset=True),
    }
    if fast_probe is not None:
        result["fast_eligible"] = fast_probe[0] is not None
        if fast_probe[0] is None:
            result["fast_ineligible_why"] = fast_probe[1]
    if drift:
        result["error"] = "checksum drift across timed runs; rate unreliable"

    if dual_fast and dual_plan is not None:
        if drift:
            # the XLA anchor is unstable: a parity verdict against it would
            # be meaningless, and an error-free "(pallas)" line could become
            # the ladder headline while the XLA record carries the drift flag
            log("  skipping the pallas dual measurement: the XLA runs "
                "drifted, so there is no stable parity anchor")
        else:
            extra = measure_fast_extra(name, dual_plan, platform, num_pods,
                                       timed_runs, phash, ref_rate, load1)
            if extra is not None:
                print(json.dumps(stamp_envelope_deviation(extra)), flush=True)
    return stamp_envelope_deviation(result)


def measure_fast_extra(name, plan, platform, num_pods, timed_runs,
                       xla_hash, ref_rate, load1):
    """Dual-mode second measurement (VERDICT r4 item 5): the Pallas fastscan
    on the workload just measured on the XLA scan, returned as its own
    record with in-process hash parity vs that run — so a single
    driver-captured BENCH proves the kernel with no builder-invoked stages.
    Returns None when the kernel fails (the XLA record already stands)."""
    from tpusim.jaxe.fastscan import fast_scan

    t_start = time.perf_counter()

    def fprog(ci, total, done):
        log(f"  fast chunk {ci}/{total}: {done}/{num_pods} pods "
            f"({time.perf_counter() - t_start:.1f}s)")

    try:
        t0 = time.perf_counter()
        with stage_heartbeat("  pallas cold run (Mosaic compile gives no "
                             "incremental progress)"):
            f_choices, _fc, _fa = fast_scan(plan, progress=fprog)
        log(f"  pallas cold (incl Mosaic compile): "
            f"{time.perf_counter() - t0:.1f}s")
        f_times = []
        for _ in range(timed_runs):
            t0 = time.perf_counter()
            f_choices, _fc, _fa = fast_scan(plan, progress=fprog)
            f_times.append(time.perf_counter() - t0)
    except Exception as exc:
        # never crash the child mid-device-context (a wedged tunnel costs
        # the whole window)
        log(f"  pallas dual measurement FAILED ({type(exc).__name__}: "
            f"{exc}); keeping the XLA record only")
        return None
    f_warm = float(np.median(f_times))
    f_rate = num_pods / f_warm
    f_hash = hashlib.sha256(np.asarray(f_choices).tobytes()).hexdigest()[:16]
    match = "match" if f_hash == xla_hash else "MISMATCH"
    log(f"  pallas warm (median of {[f'{t:.3f}' for t in f_times]}): "
        f"{f_rate:.0f} pods/s placement_hash={f_hash} "
        f"fast_parity={match} (xla={xla_hash})")
    extra = {
        "metric": f"scheduled pods/sec ({name}, exact scan (pallas), "
                  f"platform={platform}, fast_parity={match}, "
                  f"placement_hash={f_hash})",
        "value": round(f_rate, 1), "unit": "pods/s",
        "vs_baseline": round(f_rate / ref_rate, 2) if ref_rate else 0,
        "warm_runs": len(f_times),
        "warm_s": {"min": round(min(f_times), 3),
                   "median": round(f_warm, 3),
                   "max": round(max(f_times), 3)},
        "load1": round(load1, 2),
    }
    from tpusim.framework.metrics import register as _register_metrics

    _reg = _register_metrics()
    for t in f_times:
        _reg.backend_dispatch_latency.observe(t * 1e6)
    _reg.backend_route.inc("fastscan", len(f_times))
    extra["metrics"] = _metrics_snapshot(reset=True)
    if f_hash != xla_hash:
        extra["error"] = ("pallas placements diverge from the XLA "
                          "scan on this workload; rate untrustworthy")
    return extra


def _cpu_sized_workload() -> tuple:
    """CPU-shape knobs; explicit TPUSIM_BENCH_PODS/_NODES overrides win."""
    return (int(os.environ.get("TPUSIM_BENCH_CPU_PODS",
                               os.environ.get("TPUSIM_BENCH_PODS", 20_000))),
            int(os.environ.get("TPUSIM_BENCH_CPU_NODES",
                               os.environ.get("TPUSIM_BENCH_NODES", 2_000))))


def run_child(platform: str, ladder: bool, phases: bool = False) -> None:
    num_pods = int(os.environ.get("TPUSIM_BENCH_PODS", 100_000))
    num_nodes = int(os.environ.get("TPUSIM_BENCH_NODES", 5_000))
    if platform == "cpu":
        num_pods, num_nodes = _cpu_sized_workload()
    baseline_pods = int(os.environ.get("TPUSIM_BENCH_BASELINE_PODS", 200))
    chunk = int(os.environ.get("TPUSIM_BENCH_CHUNK", 131072))

    import jax

    if platform == "cpu":
        # The axon TPU plugin force-appends itself to jax_platforms, overriding
        # the JAX_PLATFORMS env var; pin via jax.config instead.
        jax.config.update("jax_platforms", "cpu")

    from tpusim.jaxe import ensure_x64

    ensure_x64()
    log("initializing backend...")
    devices = jax.devices()
    real_platform = devices[0].platform
    log(f"devices: {devices}")
    if platform != "cpu" and real_platform == "cpu":
        # the requested accelerator silently fell back to CPU (e.g. the axon
        # plugin failed init with a warning): use the CPU-sized workload
        log("default backend resolved to cpu; using the cpu-sized workload")
        num_pods, num_nodes = _cpu_sized_workload()

    if phases:
        run_phases(real_platform, chunk)
        return
    if ladder:
        run_ladder(real_platform, baseline_pods, chunk)
        return

    # stage 1: a small same-shape run — completes fast, leaves a valid JSON
    # line on stdout even if the full-size run later wedges
    small_snapshot, small_pods = build_workload(2_000, 500)
    small = measure_config("staged 2k Zipf pods, 500 nodes", small_snapshot,
                           small_pods, real_platform, baseline_pods,
                           chunk, timed_runs=1)
    small["note"] = "staged small run; full-size run follows"
    print(json.dumps(small), flush=True)

    # stage 2: the policy-residue config at the same full-size shape
    # (ISSUE 4): every driver capture carries fast-path eligibility
    # evidence for the policy features (plan-level on CPU; on TPU the dual
    # measurement also emits the measured "(pallas)" record). Runs before
    # the headline so the parent's last-JSON-line summary stays the
    # round-comparable headline config.
    psnap, ppods = policy_residue_workload(num_pods, num_nodes)
    pol = measure_config(
        f"{num_pods // 1000}k Zipf pods, {num_nodes} nodes, policy residue "
        "(labels+ServiceAffinity+ImageLocality)",
        psnap, ppods, real_platform, baseline_pods, chunk,
        policy=POLICY_RESIDUE)
    print(json.dumps(pol), flush=True)

    # final stage: the headline config — >=5 warm runs for a variance envelope
    snapshot, pods = build_workload(num_pods, num_nodes)
    result = measure_config(
        f"{num_pods // 1000}k Zipf pods, {num_nodes} heterogeneous nodes",
        snapshot, pods, real_platform, baseline_pods, chunk,
        timed_runs=int(os.environ.get("TPUSIM_BENCH_TIMED_RUNS", 5)))
    print(json.dumps(result), flush=True)


def _ladder_config_1(platform, baseline_pods, chunk) -> dict:
    """1. quickstart: etc/pod.yaml 20 pods vs synthetic nodes (falls back to
    the equivalent synthetic spec when the reference checkout is absent)."""
    from tpusim.api.podspec import expand_simulation_pods, parse_simulation_pods
    from tpusim.api.snapshot import synthetic_cluster

    quickstart = os.environ.get("TPUSIM_BENCH_QUICKSTART",
                                "/root/reference/etc/pod.yaml")
    try:
        with open(quickstart) as f:
            sim_pods = parse_simulation_pods(f.read())
        quick_pods = list(reversed(expand_simulation_pods(sim_pods)))
    except OSError:
        from tpusim.api.snapshot import make_pod

        log(f"  quickstart spec {quickstart!r} unavailable; using the "
            "equivalent synthetic 10 small + 10 oversized pods")
        quick_pods = ([make_pod(f"small-{i}", milli_cpu=100, memory=1024)
                       for i in range(10)]
                      + [make_pod(f"big-{i}", milli_cpu=100_000,
                                  memory=1024)
                         for i in range(10)])
    return measure_config(
        "config 1: quickstart 20 pods, 100 synthetic nodes",
        synthetic_cluster(100, milli_cpu=4000, memory=16 * 1024**3),
        quick_pods, platform, baseline_pods, chunk)


def _ladder_config_2(platform, baseline_pods, chunk) -> dict:
    """2. 1k uniform pods / 100 nodes."""
    snapshot, pods = uniform_workload(1_000, 100)
    return measure_config("config 2: 1k uniform pods, 100 nodes",
                          snapshot, pods, platform, baseline_pods, chunk)


def _ladder_config_3(platform, baseline_pods, chunk) -> dict:
    """3. 100k Zipf / 5k heterogeneous (the headline shape)."""
    snapshot, pods = build_workload(100_000, 5_000)
    return measure_config(
        "config 3: 100k Zipf pods, 5k heterogeneous nodes",
        snapshot, pods, platform, baseline_pods, chunk)


def _ladder_config_4(platform, baseline_pods, chunk) -> dict:
    """4. 1M pods / 10k nodes with taints+tolerations and node affinity
    (CPU fallback runs a scaled shape so the watchdog never fires)."""
    p4, n4 = (1_000_000, 10_000) if platform != "cpu" else (100_000, 2_000)
    snapshot, pods = build_workload(p4, n4, affinity=True)
    return measure_config(
        f"config 4: {p4 // 1000}k Zipf pods, {n4} nodes, "
        "taints+node-affinity",
        snapshot, pods, platform, baseline_pods, chunk,
        timed_runs=1)


def _ladder_config_5(platform, baseline_pods, chunk) -> dict:
    """5. multi-tenant what-if: 50 snapshots x 20k pods, one batched
    program. The single jitted vmap-of-scan program can sit in XLA compile
    for minutes with no observable progress, so a heartbeat thread keeps
    the parent's stall watchdog fed."""
    from tpusim.jaxe.whatif import run_what_if

    n_scen, p_scen, n_nodes5 = (50, 20_000, 1_000) if platform != "cpu" \
        else (8, 5_000, 500)
    scenarios = []
    t0 = time.perf_counter()
    for s in range(n_scen):
        snap, pods = build_workload(p_scen, n_nodes5, seed=1000 + s)
        scenarios.append((snap, pods))
    log(f"[config 5] built {n_scen}x{p_scen // 1000}k scenarios "
        f"in {time.perf_counter() - t0:.1f}s")
    # run_what_if compiles per invocation (the jitted program is built
    # inside), so every call pays host interning + XLA compile: the honest
    # metric is end-to-end including those costs
    t0 = time.perf_counter()
    with stage_heartbeat("[config 5] what-if still running (XLA compile "
                         "+ execution give no incremental progress)"):
        run_what_if(scenarios)
    e2e = time.perf_counter() - t0
    total = n_scen * p_scen
    log(f"[config 5] {n_scen}x{p_scen // 1000}k what-if: "
        f"{e2e:.1f}s end-to-end (incl. compile + host interning)")
    return {
        "metric": f"scheduled pods/sec (config 5: {n_scen}x"
                  f"{p_scen // 1000}k batched what-if, end-to-end incl. "
                  f"compile, platform={platform})",
        "value": round(total / e2e, 1), "unit": "pods/s",
        "vs_baseline": 0,
        "metrics": _metrics_snapshot(reset=True)}


def _ladder_config_7(platform, baseline_pods, chunk) -> dict:
    """7. policy residue (ISSUE 4): label rows + ServiceAffinity +
    ImageLocality on the 10k-node snapshot. Eligibility is probed on every
    platform; the measured "(pallas)" record lands via the dual
    measurement on TPU."""
    p7, n7 = ((200_000, 10_000) if platform != "cpu"
              else _cpu_sized_workload())
    snapshot, pods = policy_residue_workload(p7, n7)
    return measure_config(
        f"config 7: {p7 // 1000}k Zipf pods, {n7} nodes, policy residue "
        "(labels+ServiceAffinity+ImageLocality)",
        snapshot, pods, platform, baseline_pods, chunk,
        policy=POLICY_RESIDUE)


class LadderConfig(NamedTuple):
    """One ladder row: the SINGLE source for the config-number universe.
    The TPUSIM_BENCH_LADDER_CONFIGS bounds, the autoladder promotion
    subset (AUTOLADDER_DEFAULT_CONFIGS), and run_ladder's dispatch all
    derive from LADDER_CONFIGS — adding a config is one row + its runner,
    not three literal edits."""

    run: object            # (platform, baseline_pods, chunk) -> record dict
    autoladder: bool       # promoted into the default TPU capture?


# lambdas, not bare references: configs 6/8/9 call measure_* functions
# defined further down the module (late binding keeps the table up here
# with the ladder machinery it feeds)
LADDER_CONFIGS = {
    1: LadderConfig(_ladder_config_1, autoladder=False),
    2: LadderConfig(_ladder_config_2, autoladder=False),
    3: LadderConfig(_ladder_config_3, autoladder=True),
    4: LadderConfig(_ladder_config_4, autoladder=True),
    5: LadderConfig(_ladder_config_5, autoladder=True),
    6: LadderConfig(lambda p, b, c: measure_preemption(p, b),
                    autoladder=True),
    7: LadderConfig(_ladder_config_7, autoladder=True),
    8: LadderConfig(lambda p, b, c: measure_serve_fleet(p),
                    autoladder=True),
    9: LadderConfig(lambda p, b, c: measure_stream_churn(p),
                    autoladder=True),
    10: LadderConfig(lambda p, b, c: measure_policy_stream(p),
                     autoladder=True),
    11: LadderConfig(lambda p, b, c: measure_recovery(p),
                     autoladder=True),
    12: LadderConfig(lambda p, b, c: measure_analytics_overhead(p),
                     autoladder=True),
    13: LadderConfig(lambda p, b, c: measure_gang_ladder(p),
                     autoladder=True),
    14: LadderConfig(lambda p, b, c: measure_shard_scaling(p),
                     autoladder=True),
    15: LadderConfig(lambda p, b, c: measure_replication(p),
                     autoladder=True),
    16: LadderConfig(lambda p, b, c: measure_live_whatif(p),
                     autoladder=True),
}


def _ladder_configs() -> set:
    """Parse TPUSIM_BENCH_LADDER_CONFIGS (e.g. "3,5" to rerun a subset
    without repeating the whole ladder). Called in the PARENT before any
    child spawns: a typo'd knob must fail instantly, not burn the full
    retry ladder (each child pays backend init) producing "no JSON line"."""
    raw = os.environ.get("TPUSIM_BENCH_LADDER_CONFIGS",
                         ",".join(str(n) for n in LADDER_CONFIGS))
    try:
        wanted = {int(c) for c in raw.split(",") if c.strip()}
    except ValueError:
        wanted = set()
    if not wanted or not wanted <= set(LADDER_CONFIGS):
        raise SystemExit(
            f"TPUSIM_BENCH_LADDER_CONFIGS={raw!r}: need values in "
            f"{min(LADDER_CONFIGS)}-{max(LADDER_CONFIGS)}")
    return wanted


def run_ladder(platform: str, baseline_pods: int, chunk: int) -> None:
    """BASELINE.md ladder configs; one JSON line each."""
    wanted = _ladder_configs()
    for num, cfg in LADDER_CONFIGS.items():
        if num in wanted:
            print(json.dumps(cfg.run(platform, baseline_pods, chunk)),
                  flush=True)


def measure_serve_fleet(platform: str) -> dict:
    """Config 8: scenario-fleet serving throughput (tpusim/serve). One fixed
    cluster size, N what-if requests whose pod counts stay inside ONE shape
    class, so the cold pass traces exactly one program and every warm pass
    must ride the warm-executable cache (compile_cache_hit stamps the
    record; a warm trace is a regression). A second axis sweeps the
    ("scenario", "node") mesh sizes the host exposes — the mesh-scaling
    curve for the shard_map dispatch route."""
    import jax

    from tpusim.jaxe.whatif import compile_count
    from tpusim.serve import ScenarioFleet, WhatIfRequest

    n_req, p8, n8 = (64, 2_000, 200) if platform != "cpu" else (24, 400, 50)
    bucket = 8
    snapshot, pool = build_workload(p8, n8, seed=4242)
    # pod counts in (p8/2, p8]: same power-of-two budget => one shape class
    rng = np.random.RandomState(8)
    sizes = [int(rng.randint(p8 // 2 + 1, p8 + 1)) for _ in range(n_req)]

    def load():
        return [WhatIfRequest(pods=pool[:n], snapshot_ref="base",
                              cache_key=f"bench8-{i}-{n}")
                for i, n in enumerate(sizes)]

    def one_pass(fleet):
        t0 = time.perf_counter()
        responses = fleet.run(load())
        elapsed = time.perf_counter() - t0
        bad = [r for r in responses if not r.ok]
        if bad:
            raise RuntimeError(f"config 8: {len(bad)} requests failed: "
                               f"{bad[0].error}")
        return elapsed, responses

    fleet = ScenarioFleet(bucket_size=bucket, flush_after_s=0.05)
    fleet.register_snapshot("base", snapshot)
    with stage_heartbeat("[config 8] serve fleet cold pass (XLA compile "
                         "gives no incremental progress)"):
        cold_e2e, _ = one_pass(fleet)
    traces_before_warm = compile_count()
    warm_e2e, warm_responses = one_pass(fleet)
    warm_traces = compile_count() - traces_before_warm
    cache_hit = warm_traces == 0 and all(r.compile_cache_hit
                                         for r in warm_responses)
    log(f"[config 8] {n_req} requests, bucket {bucket}: cold "
        f"{n_req / cold_e2e:.1f}/s, warm {n_req / warm_e2e:.1f}/s, "
        f"warm traces {warm_traces}")

    mesh_curve = []
    n_dev = len(jax.devices())
    for m in (1, 2, 4, 8):
        if m > n_dev or bucket % m != 0:
            continue
        from tpusim.jaxe.sharding import make_scenario_mesh

        mfleet = ScenarioFleet(bucket_size=bucket, flush_after_s=0.05,
                               mesh=make_scenario_mesh(m))
        mfleet.register_snapshot("base", snapshot)
        with stage_heartbeat(f"[config 8] mesh {m}x1 cold pass"):
            m_cold, _ = one_pass(mfleet)
        m_warm, _ = one_pass(mfleet)
        mesh_curve.append({"mesh": f"{m}x1",
                           "cold_scenarios_per_s": round(n_req / m_cold, 1),
                           "scenarios_per_s": round(n_req / m_warm, 1)})
        log(f"[config 8] mesh {m}x1: warm {n_req / m_warm:.1f} scenarios/s")

    return {
        "metric": f"what-if scenarios/sec (config 8: serve fleet, {n_req} "
                  f"requests vs {n8} nodes, bucket {bucket}, warm pass, "
                  f"platform={platform})",
        "value": round(n_req / warm_e2e, 1), "unit": "scenarios/s",
        "vs_baseline": 0,
        "cold_scenarios_per_s": round(n_req / cold_e2e, 1),
        "compile_cache_hit": cache_hit,
        "warm_traces": warm_traces,
        "mesh_curve": mesh_curve,
        "fleet_stats": dict(fleet.executor.stats),
        "metrics": _metrics_snapshot(reset=True),
    }


class stage_heartbeat:
    """Logs '<label> (Ns elapsed)' every 60s until the block exits: any
    silent stage longer than TPUSIM_BENCH_STALL_TIMEOUT (240s) would
    otherwise be killed by the parent's stall watchdog — the round-4 TPU
    capture lost config 6 exactly this way (the 20k-pod hybrid run prints
    nothing while device dispatches and host preemptions alternate)."""

    def __init__(self, label: str):
        self.label = label

    def __enter__(self):
        import threading

        self._done = threading.Event()
        self._t0 = time.perf_counter()

        def beat():
            while not self._done.wait(60.0):
                log(f"{self.label} "
                    f"({time.perf_counter() - self._t0:.0f}s elapsed)")

        threading.Thread(target=beat, daemon=True).start()
        return self

    def __exit__(self, *exc):
        self._done.set()
        return False


def measure_preemption(platform: str, baseline_pods: int) -> dict:
    """Config 6: the host-device hybrid preemption path (jaxe/preempt.py) on
    a priority-banded, saturated config-4-style shape. Measures end-to-end
    pods/s (device scans + host Preempt re-dispatches) and placement parity
    vs the reference orchestrator on a subsample. Reference pipeline:
    core/generic_scheduler.go:205-262 driven from scheduler.go:449-455."""
    from tpusim.simulator import run_simulation

    # ~1.5x CPU oversubscription: late high-priority pods must preempt
    p6 = int(os.environ.get("TPUSIM_BENCH_PREEMPT_PODS",
                            20_000 if platform != "cpu" else 6_000))
    n6 = int(os.environ.get("TPUSIM_BENCH_PREEMPT_NODES",
                            1_000 if platform != "cpu" else 300))
    snapshot, pods = build_workload(p6, n6, affinity=True, priorities=True,
                                    seed=777)
    log(f"[config 6] {p6} priority-banded pods x {n6} nodes "
        "(--enable-pod-priority)")

    def outcome_map(status):
        placed = {p.name: p.spec.node_name for p in status.successful_pods}
        failed = {p.name for p in status.failed_pods}
        return placed, failed

    sub = min(baseline_pods, p6)
    mismatches = None
    if sub:
        # fresh copies per run: the orchestrator seams mutate fed pods in
        # place (Unschedulable conditions, nominated node names) and stale
        # status would contaminate the later runs' nominated-pods index
        t0 = time.perf_counter()
        ref_status = run_simulation([p.copy() for p in pods[:sub]], snapshot,
                                    backend="reference",
                                    enable_pod_priority=True)
        ref_elapsed = max(time.perf_counter() - t0, 1e-9)
        log(f"  reference orchestrator: {sub} pods in {ref_elapsed:.1f}s "
            f"= {sub / ref_elapsed:.1f} pods/s "
            f"({len(ref_status.preempted_pods)} preempted)")
        with stage_heartbeat("[config 6] parity run still going (first "
                             "preemption-path XLA compile)"):
            jax_sub = run_simulation([p.copy() for p in pods[:sub]], snapshot,
                                     backend="jax", enable_pod_priority=True)
        ref_placed, ref_failed = outcome_map(ref_status)
        jax_placed, jax_failed = outcome_map(jax_sub)
        mismatches = sum(
            1 for p in pods[:sub]
            if jax_placed.get(p.name) != ref_placed.get(p.name)
            or (p.name in jax_failed) != (p.name in ref_failed))
        log(f"  parity check on first {sub} pods: {mismatches} mismatches")

    from tpusim.jaxe.preempt import (
        PREEMPT_CLASS_STATS,
        reset_preempt_class_stats,
    )

    reset_preempt_class_stats()
    _metrics_snapshot(reset=True)  # registry window for the timed run only
    t0 = time.perf_counter()
    with stage_heartbeat("[config 6] hybrid still running"):
        status = run_simulation([p.copy() for p in pods], snapshot,
                                backend="jax", enable_pod_priority=True)
    e2e = max(time.perf_counter() - t0, 1e-9)
    # captured before the full-feed reference run below feeds the registry
    metrics_snap = _metrics_snapshot(reset=True)
    rate = p6 / e2e
    preempted = len(status.preempted_pods)
    victim_paths = dict(PREEMPT_CLASS_STATS)
    # outcome hash spanning placements AND the victim set — the config-6
    # analog of the scan's placement_hash, so the idle-envelope guard can
    # pin "same workload, same outcome" across rounds
    phash = hashlib.sha256(
        ("|".join(f"{p.name}:{p.spec.node_name}"
                  for p in status.successful_pods)
         + "#" + ",".join(sorted(p.name for p in status.preempted_pods))
         ).encode()).hexdigest()[:16]
    try:
        load1 = os.getloadavg()[0]
    except OSError:
        load1 = -1.0
    log(f"  hybrid end-to-end: {p6} pods in {e2e:.1f}s = {rate:.0f} pods/s "
        f"({len(status.successful_pods)} scheduled, "
        f"{len(status.failed_pods)} unschedulable, {preempted} preempted) "
        f"placement_hash={phash} load1={load1:.1f} "
        f"victim_paths={victim_paths}")

    # the honest 10x criterion needs the reference on the FULL feed at EQUAL
    # preemption counts (the parity subsample saturates nothing and preempts
    # 0 times, overstating the reference's rate); affordable on CPU shapes,
    # env-gated for the larger TPU shapes
    ref_full_limit = int(os.environ.get("TPUSIM_BENCH_PREEMPT_FULL_REF_MAX",
                                        8_000))
    vs_baseline = round(rate * ref_elapsed / sub, 2) if sub else 0
    ref_note = ""
    if p6 <= ref_full_limit:
        if sub == p6:
            # the parity subsample already covered the whole feed
            ref_full, ref_full_elapsed = ref_status, ref_elapsed
        else:
            t0 = time.perf_counter()
            with stage_heartbeat("[config 6] full-feed reference still "
                                 "running"):
                ref_full = run_simulation([p.copy() for p in pods], snapshot,
                                          backend="reference",
                                          enable_pod_priority=True)
            ref_full_elapsed = max(time.perf_counter() - t0, 1e-9)
        ref_rate = p6 / ref_full_elapsed
        log(f"  reference full feed: {p6} pods in {ref_full_elapsed:.1f}s "
            f"= {ref_rate:.0f} pods/s "
            f"({len(ref_full.preempted_pods)} preempted)")
        vs_baseline = round(rate / ref_rate, 2)
        ref_note = (f", ref_full={ref_rate:.0f}pods/s"
                    f"/{len(ref_full.preempted_pods)}preempted")
    return stamp_envelope_deviation({
        "metric": f"scheduled pods/sec (config 6: {p6 // 1000}k "
                  f"priority-banded pods, {n6} nodes, preemption hybrid, "
                  f"platform={platform}, preempted={preempted}"
                  + (f", parity_mismatches={mismatches}"
                     if mismatches is not None else "") + ref_note
                  + f", placement_hash={phash})",
        "value": round(rate, 1),
        "unit": "pods/s",
        "vs_baseline": vs_baseline,
        "load1": round(load1, 2),
        # victim-selection path split (device kernel vs host oracle) for the
        # arithmetic-reprieve offload — preempt.PREEMPT_CLASS_STATS
        "victim_paths": victim_paths,
        "metrics": metrics_snap,
    })


def _provenance_overhead(run_fn) -> dict:
    """A/B the decision-provenance capture cost at one representative
    shape (ISSUE 13 budgets <2%): the identical workload with and without
    an installed ProvenanceLog. The in-memory ring is the hot-path cost
    every capture site pays — one lock + one reference append per decoded
    batch; JSONL formatting is deferred to flush, outside the cycle loop."""
    from tpusim.obs import provenance

    off = run_fn()
    provenance.install(provenance.ProvenanceLog(capacity=4096))
    try:
        on = run_fn()
    finally:
        provenance.uninstall()
    delta = (off["decisions_per_s"] - on["decisions_per_s"]) \
        / max(off["decisions_per_s"], 1e-9)
    return {
        "off_decisions_per_s": round(off["decisions_per_s"], 1),
        "on_decisions_per_s": round(on["decisions_per_s"], 1),
        "overhead_fraction": round(delta, 4),
        "within_budget": delta < 0.02,
    }


def _tracing_overhead(run_fn) -> dict:
    """A/B the distributed-tracing cost (ISSUE 20 budgets <2%): the
    identical workload with and without an installed FlightRecorder —
    with tracing on, every cycle allocates a TraceContext, stamps every
    span/instant, and the latency observes carry exemplars. Also pins
    the zero-interference contract: the placement chain must be
    byte-identical across the two arms.

    The stamp is the MEDIAN delta over adjacent off/on pairs: a traced
    run records ~1e2 span stamps total, so the real cost is far below a
    percent, but on a contended host a lone sub-second pair swings by
    >10% either way. Pairing adjacent runs cancels slow drift, the
    median rejects excursions, and the artifact keeps every pair delta
    so a noisy-host stamp is diagnosable as such (the accelerator bench
    shapes run multi-second arms where the median resolves cleanly)."""
    from tpusim.obs import recorder as flight

    samples = []
    chain_identical = True
    for _ in range(7):
        off = run_fn()
        flight.install(flight.FlightRecorder(process_name="tpusim-bench"))
        try:
            on = run_fn()
        finally:
            flight.uninstall()
        chain_identical = chain_identical and \
            on["placement_chain"] == off["placement_chain"]
        samples.append((
            (off["decisions_per_s"] - on["decisions_per_s"])
            / max(off["decisions_per_s"], 1e-9),
            off["decisions_per_s"], on["decisions_per_s"]))
    deltas = sorted(s[0] for s in samples)
    delta, off_rate, on_rate = sorted(samples)[len(samples) // 2]
    return {
        "off_decisions_per_s": round(off_rate, 1),
        "on_decisions_per_s": round(on_rate, 1),
        "overhead_fraction": round(delta, 4),
        "pair_deltas": [round(d, 4) for d in deltas],
        "within_budget": delta < 0.02,
        "chain_identical": chain_identical,
    }


def measure_stream_churn(platform: str) -> dict:
    """Config 9: streaming-runtime churn (tpusim/stream). Three sweeps:

    - churn-rate curve at a fixed cluster size: sustained decisions/s and
      p99 cycle latency as the eviction fraction (the per-cycle delta
      volume) rises.
    - cluster-size curve at a FIXED delta rate, stream vs always-restage:
      the stream arm's warm steady-state cycle cost (p50; p99 absorbs the
      cold compile) should stay ~flat in node count — the O(delta) claim —
      while the restage arm's grows with the cluster.
    - the restage arm doubles as the controlled A/B for BASELINE.md's
      r02→r05 warm-CPU slide (11,410 → 6,232 pods/s on an unchanged
      placement hash): that slide is per-cycle full re-staging cost on a
      contended driver host, which the resident scatter path removes.
    """
    from tpusim.simulator import run_stream_simulation

    cycles, arrivals = (40, 64) if platform != "cpu" else (24, 64)
    sizes = (1_000, 4_000, 16_000) if platform != "cpu" else (200, 800, 3_200)
    mid = sizes[1]

    def warm_up(n, frac=0.25):
        # absorb in-process tracing before timing: the first run at a shape
        # traces the scan + scatter programs, and whichever arm ran first
        # would otherwise gift its compile to the other arm's jit cache,
        # skewing the stream-vs-restage decisions/s comparison
        run_stream_simulation(num_nodes=n, cycles=3, arrivals=arrivals,
                              evict_fraction=frac, seed=9)

    churn_curve = []
    for frac in (0.05, 0.25, 0.5):
        warm_up(mid, frac)
        out = run_stream_simulation(num_nodes=mid, cycles=cycles,
                                    arrivals=arrivals, evict_fraction=frac,
                                    seed=9)
        churn_curve.append({
            "evict_fraction": frac,
            "decisions_per_s": round(out["decisions_per_s"], 1),
            "p99_cycle_ms": round(out["p99_cycle_ms"], 2),
            "paths": out["paths"], "restages": out["restages"]})
        log(f"[config 9] evict {frac}: "
            f"{out['decisions_per_s']:.0f} decisions/s, "
            f"p99 {out['p99_cycle_ms']:.1f} ms")

    size_curve = []
    for n in sizes:
        warm_up(n)
        stream = run_stream_simulation(num_nodes=n, cycles=cycles,
                                       arrivals=arrivals,
                                       evict_fraction=0.25, seed=9)
        restage = run_stream_simulation(num_nodes=n, cycles=cycles,
                                        arrivals=arrivals,
                                        evict_fraction=0.25, seed=9,
                                        always_restage=True)
        size_curve.append({
            "nodes": n,
            "stream_p50_cycle_ms": round(stream["p50_cycle_ms"], 2),
            "restage_p50_cycle_ms": round(restage["p50_cycle_ms"], 2),
            # the per-cycle cost the resident scatter path removes: both
            # arms run the identical scan (O(N) compute), so the p50 gap is
            # the compile+re-staging term — O(delta) holding means this gap
            # stays ~flat as the cluster grows
            "staging_overhead_ms": round(
                restage["p50_cycle_ms"] - stream["p50_cycle_ms"], 2),
            "stream_decisions_per_s": round(stream["decisions_per_s"], 1),
            "restage_decisions_per_s": round(restage["decisions_per_s"], 1),
            "stream_vs_restage": round(
                stream["decisions_per_s"]
                / max(restage["decisions_per_s"], 1e-9), 2)})
        log(f"[config 9] {n} nodes: stream p50 "
            f"{stream['p50_cycle_ms']:.1f} ms vs restage "
            f"{restage['p50_cycle_ms']:.1f} ms "
            f"({size_curve[-1]['stream_vs_restage']}x)")

    warm_up(mid)
    provenance_overhead = _provenance_overhead(
        lambda: run_stream_simulation(num_nodes=mid, cycles=cycles,
                                      arrivals=arrivals, evict_fraction=0.25,
                                      seed=9))
    log(f"[config 9] provenance capture overhead: "
        f"{provenance_overhead['overhead_fraction'] * 100:.2f}% "
        f"(within_budget={provenance_overhead['within_budget']})")

    warm_up(mid)
    tracing_overhead = _tracing_overhead(
        lambda: run_stream_simulation(num_nodes=mid, cycles=cycles,
                                      arrivals=arrivals, evict_fraction=0.25,
                                      seed=9))
    log(f"[config 9] tracing overhead: "
        f"{tracing_overhead['overhead_fraction'] * 100:.2f}% "
        f"(within_budget={tracing_overhead['within_budget']}, "
        f"chain_identical={tracing_overhead['chain_identical']})")

    headline = size_curve[sizes.index(mid)]
    return {
        "metric": f"churn decisions/sec (config 9: streaming runtime, "
                  f"{mid} nodes, {arrivals} arrivals + 25% evictions per "
                  f"cycle, warm steady state, platform={platform})",
        "value": headline["stream_decisions_per_s"], "unit": "decisions/s",
        "vs_baseline": 0,
        "churn_curve": churn_curve,
        "size_curve": size_curve,
        # warm stream cycle cost growth across the size sweep (includes the
        # scan's own O(N) compute — on CPU that term dominates at the top
        # size; the restage arm's same ratio is the comparison)
        "o_delta_flatness": round(
            size_curve[-1]["stream_p50_cycle_ms"]
            / max(size_curve[0]["stream_p50_cycle_ms"], 1e-9), 2),
        "restage_flatness": round(
            size_curve[-1]["restage_p50_cycle_ms"]
            / max(size_curve[0]["restage_p50_cycle_ms"], 1e-9), 2),
        # growth of the staging term itself; ~1.0 = the O(delta) claim
        "staging_overhead_flatness": round(
            size_curve[-1]["staging_overhead_ms"]
            / max(size_curve[0]["staging_overhead_ms"], 1e-9), 2),
        "provenance_overhead": provenance_overhead,
        "tracing_overhead": tracing_overhead,
        "metrics": _metrics_snapshot(reset=True),
    }


# Config 10's inline policy: the residency workload needs label-selector,
# taint, service-(anti-)affinity and label-preference tables all live so
# the per-cycle statics scatter covers every policy-derived column family.
# Shapes mirror tests/compat_policies.json 1.0 without depending on the
# test tree from the bench child.
_POLICY_STREAM_DOC = {
    "apiVersion": "v1", "kind": "Policy",
    "predicates": [
        {"name": "MatchNodeSelector"},
        {"name": "PodFitsResources"},
        {"name": "PodToleratesNodeTaints"},
        {"name": "TestServiceAffinity",
         "argument": {"serviceAffinity": {"labels": ["region"]}}},
        {"name": "TestLabelsPresence",
         "argument": {"labelsPresence": {"labels": ["foo"],
                                         "presence": True}}},
    ],
    "priorities": [
        {"name": "LeastRequestedPriority", "weight": 1},
        {"name": "zone-spread", "weight": 2,
         "argument": {"serviceAntiAffinity": {"label": "zone"}}},
        {"name": "bar-pref", "weight": 1,
         "argument": {"labelPreference": {"label": "bar",
                                          "presence": True}}},
    ],
}


def measure_policy_stream(platform: str) -> dict:
    """Config 10: compiled-policy streaming (stream v2). Two sweeps:

    - churn curve at a fixed cluster size: node label/taint churn per
      cycle rises while restage counts must stay at the cold start only —
      the policy-table residency claim (churn lands as an O(delta)
      statics scatter, not a restage).
    - pipelined vs synchronous vs always-restage A/B across cluster
      sizes, per-shape warm-up, identical placement chains: the
      double-buffered async dispatch overlaps host decode/ingest with the
      device scan, so pipelined decisions/s should beat synchronous
      (acceptance: >= 1.2x at the mid size on an idle MULTI-CORE cpu —
      overlap needs a second host core to run the XLA scan while python
      decodes; on a 1-core host the structural ceiling is parity, and the
      record carries host_cpus so the artifact says which regime it is).
    """
    from tpusim.engine.policy import decode_policy
    from tpusim.simulator import run_stream_simulation

    policy = decode_policy(_POLICY_STREAM_DOC)
    cycles, arrivals = (40, 64) if platform != "cpu" else (24, 64)
    sizes = (1_000, 4_000, 16_000) if platform != "cpu" else (200, 800, 3_200)
    mid = sizes[1]

    def run(n, **kw):
        return run_stream_simulation(
            num_nodes=n, cycles=cycles, arrivals=arrivals,
            evict_fraction=0.25, seed=9, policy=policy,
            label_churn=2, taint_churn=1, **kw)

    def warm_up(n, **kw):
        # absorb in-process tracing before timing (see measure_stream_churn:
        # whichever arm runs a shape first would otherwise gift its compile
        # to the other arms' jit cache and skew the A/B). 10 cycles, not 3:
        # the delta-commit bucket sizes keep growing for the first several
        # cycles as the bound-pod pool fills, and a bucket first seen inside
        # the timed run costs a ~150 ms mid-run trace that dwarfs the
        # per-cycle signal
        run_stream_simulation(num_nodes=n, cycles=10, arrivals=arrivals,
                              evict_fraction=0.25, seed=9, policy=policy,
                              label_churn=2, taint_churn=1, **kw)

    churn_curve = []
    for label_churn, taint_churn in ((0, 0), (2, 1), (8, 4)):
        warm_up(mid)
        out = run_stream_simulation(
            num_nodes=mid, cycles=cycles, arrivals=arrivals,
            evict_fraction=0.25, seed=9, policy=policy,
            label_churn=label_churn, taint_churn=taint_churn)
        churn_curve.append({
            "label_churn": label_churn, "taint_churn": taint_churn,
            "decisions_per_s": round(out["decisions_per_s"], 1),
            "p99_cycle_ms": round(out["p99_cycle_ms"], 2),
            "paths": out["paths"], "restages": out["restages"],
            # the residency claim, checkable from the artifact: pure
            # label/taint churn must not restage beyond the cold start
            "cold_start_only": out["restages"] == {"cold_start": 1}})
        log(f"[config 10] churn {label_churn}+{taint_churn}: "
            f"{out['decisions_per_s']:.0f} decisions/s, "
            f"restages {out['restages']}")

    size_curve = []
    for n in sizes:
        arms = {}
        for arm, kw in (("sync", {}), ("pipelined", {"pipeline": True}),
                        ("restage", {"always_restage": True})):
            warm_up(n, **kw)
            arms[arm] = run(n, **kw)
        chains = {arm: out["placement_chain"] for arm, out in arms.items()}
        size_curve.append({
            "nodes": n,
            "sync_decisions_per_s": round(
                arms["sync"]["decisions_per_s"], 1),
            "pipelined_decisions_per_s": round(
                arms["pipelined"]["decisions_per_s"], 1),
            "restage_decisions_per_s": round(
                arms["restage"]["decisions_per_s"], 1),
            "pipelined_vs_sync": round(
                arms["pipelined"]["decisions_per_s"]
                / max(arms["sync"]["decisions_per_s"], 1e-9), 2),
            "sync_vs_restage": round(
                arms["sync"]["decisions_per_s"]
                / max(arms["restage"]["decisions_per_s"], 1e-9), 2),
            "pipelined_p50_cycle_ms": round(
                arms["pipelined"]["p50_cycle_ms"], 2),
            "sync_p50_cycle_ms": round(arms["sync"]["p50_cycle_ms"], 2),
            # exactness across all three arms — the pipeline reorders
            # work, never placements
            "chains_equal": len(set(chains.values())) == 1,
            "placement_chain": chains["sync"]})
        log(f"[config 10] {n} nodes: pipelined "
            f"{arms['pipelined']['decisions_per_s']:.0f} vs sync "
            f"{arms['sync']['decisions_per_s']:.0f} decisions/s "
            f"({size_curve[-1]['pipelined_vs_sync']}x, chains_equal="
            f"{size_curve[-1]['chains_equal']})")

    warm_up(mid)
    provenance_overhead = _provenance_overhead(lambda: run(mid))
    log(f"[config 10] provenance capture overhead: "
        f"{provenance_overhead['overhead_fraction'] * 100:.2f}% "
        f"(within_budget={provenance_overhead['within_budget']})")

    warm_up(mid)
    tracing_overhead = _tracing_overhead(lambda: run(mid))
    log(f"[config 10] tracing overhead: "
        f"{tracing_overhead['overhead_fraction'] * 100:.2f}% "
        f"(within_budget={tracing_overhead['within_budget']}, "
        f"chain_identical={tracing_overhead['chain_identical']})")

    headline = size_curve[sizes.index(mid)]
    return {
        "metric": f"pipelined policy-stream decisions/sec (config 10: "
                  f"compiled-policy residency + pipelined dispatch, "
                  f"{mid} nodes, {arrivals} arrivals + 25% evictions + "
                  f"label/taint churn per cycle, warm steady state, "
                  f"platform={platform})",
        "value": headline["pipelined_decisions_per_s"],
        "unit": "decisions/s",
        "vs_baseline": 0,
        "pipelined_vs_sync": headline["pipelined_vs_sync"],
        # the >=1.2x acceptance bar applies in the multi-core regime only
        "host_cpus": os.cpu_count(),
        "chains_equal": all(row["chains_equal"] for row in size_curve),
        "churn_curve": churn_curve,
        "size_curve": size_curve,
        "provenance_overhead": provenance_overhead,
        "tracing_overhead": tracing_overhead,
        "metrics": _metrics_snapshot(reset=True),
    }


def measure_recovery(platform: str) -> dict:
    """Config 11: crash recovery + degraded serving (ISSUE 12). Two parts:

    - recovery-time vs checkpoint-interval curve: a WAL-journaled stream
      run is killed by a scripted process crash at 3/4 of its cycles, then
      recovered; replay time and the recomputed-cycle count fall as the
      checkpoint interval tightens, while the recovered fold chain must
      stay byte-identical to the uninterrupted run's (the durability
      claim has a correctness bar, not just a latency one).
    - degraded-mode serve throughput: the scenario fleet under a
      permanent device-fault storm (breaker open, every bucket answered
      by the host reference fallback) vs the fault-free device path. The
      ratio is the cost of serving through an outage — the availability
      claim is that it degrades, not fails.
    """
    import shutil
    import tempfile

    from tpusim.chaos.engine import ChaosClock, ProcessCrash
    from tpusim.chaos.plan import ChurnEvent, DeviceFaultPlan, FaultPlan
    from tpusim.jaxe.backend import install_chaos, uninstall_chaos
    from tpusim.simulator import run_stream_simulation

    nodes, cycles, arrivals = ((2_000, 32, 64) if platform != "cpu"
                               else (400, 16, 32))
    crash_at = (cycles * 3) // 4

    def stream(ckdir, every, plan=None, recover=False):
        return run_stream_simulation(
            num_nodes=nodes, cycles=cycles, arrivals=arrivals,
            evict_fraction=0.25, seed=11, checkpoint_dir=ckdir,
            checkpoint_every=every, chaos_plan=plan, recover=recover)

    # the parity oracle: the same run, uninterrupted
    base_dir = tempfile.mkdtemp(prefix="tpusim-bench-ck-")
    try:
        base_chain = stream(base_dir, cycles + 1)["fold_chain"]
    finally:
        shutil.rmtree(base_dir, ignore_errors=True)

    crash_plan = FaultPlan(seed=11, churn=[
        ChurnEvent(at=crash_at, action="process_crash", target="emit")])
    recovery_curve = []
    for every in (1, 5, 20):
        ckdir = tempfile.mkdtemp(prefix="tpusim-bench-ck-")
        try:
            try:
                stream(ckdir, every, plan=crash_plan)
                raise RuntimeError("scripted crash did not fire")
            except ProcessCrash:
                pass
            t0 = time.perf_counter()
            out = stream(ckdir, every, recover=True)
            recover_s = time.perf_counter() - t0
            recovery_curve.append({
                "checkpoint_every": every,
                "replay_ms": round(out["replay_ms"], 2),
                "recover_total_s": round(recover_s, 3),
                "recomputed_cycles": len(out["recomputed_cycles"]),
                "resume_cycle": out["resume_cycle"],
                "wal_records": out["wal_records"],
                "violations": out["recovery_violations"],
                "chain_identical": out["fold_chain"] == base_chain})
            log(f"[config 11] checkpoint_every={every}: replay "
                f"{out['replay_ms']:.1f} ms, "
                f"{len(out['recomputed_cycles'])} cycles recomputed, "
                f"chain_identical={recovery_curve[-1]['chain_identical']}")
        finally:
            shutil.rmtree(ckdir, ignore_errors=True)

    # -- degraded-mode serving throughput --------------------------------
    from tpusim.api.snapshot import synthetic_cluster
    from tpusim.serve import ScenarioFleet, WhatIfRequest

    serve_nodes, requests = (64, 48) if platform != "cpu" else (16, 24)
    snapshot = synthetic_cluster(serve_nodes)
    pods = build_workload(8, serve_nodes)[1]

    def serve_pass():
        fleet = ScenarioFleet(bucket_size=4, clock=ChaosClock())
        fleet.register_snapshot("base", snapshot)
        reqs = [WhatIfRequest(pods=pods[:1 + i % 4], snapshot_ref="base",
                              cache_key=f"r{i}")
                for i in range(requests)]
        fleet.run(reqs)  # warm: absorb traces before timing
        t0 = time.perf_counter()
        responses = fleet.run([WhatIfRequest(
            pods=pods[:1 + i % 4], snapshot_ref="base", cache_key=f"r{i}")
            for i in range(requests)])
        elapsed = time.perf_counter() - t0
        fleet.stop()
        return responses, elapsed

    clean_responses, clean_s = serve_pass()
    install_chaos(DeviceFaultPlan(
        faults={i: "exception" for i in range(10_000)},
        failure_threshold=1, cooldown=1_000_000))
    try:
        storm_responses, storm_s = serve_pass()
    finally:
        uninstall_chaos()
    clean_rate = len(clean_responses) / max(clean_s, 1e-9)
    storm_rate = len(storm_responses) / max(storm_s, 1e-9)
    degraded = sum(1 for r in storm_responses if r.degraded)
    headline = recovery_curve[0]
    return {
        "metric": f"crash-recovery replay latency (config 11: WAL + "
                  f"checkpoint restore at checkpoint_every=1, {nodes} "
                  f"nodes, crash at cycle {crash_at}/{cycles}, "
                  f"platform={platform})",
        "value": headline["replay_ms"], "unit": "ms",
        "vs_baseline": 0,
        "recovery_curve": recovery_curve,
        "chains_identical": all(r["chain_identical"]
                                for r in recovery_curve),
        "serve_clean_rps": round(clean_rate, 1),
        "serve_degraded_rps": round(storm_rate, 1),
        "serve_degraded_vs_clean": round(
            storm_rate / max(clean_rate, 1e-9), 3),
        "serve_degraded_responses": degraded,
        "serve_all_answered": all(r.ok for r in storm_responses),
        "metrics": _metrics_snapshot(reset=True),
    }


def _analytics_overhead(run_fn) -> dict:
    """A/B the cluster-analytics capture cost at one representative shape
    (ISSUE 14 budgets <2%): the identical workload with and without an
    installed ClusterAnalytics. The hot-path cost every dispatch pays is
    one extra jitted reduction launch over the scan's final carry plus a
    lock + reference append; decode, ratio math, and JSONL formatting are
    all deferred off the cycle loop (scrape/snapshot time)."""
    from tpusim.obs import analytics

    # best-of-3 per arm: the workload's run-to-run jitter on a contended
    # CPU host is ~10%, an order of magnitude above the budget under test
    off = max(run_fn()["decisions_per_s"] for _ in range(3))
    analytics.install(analytics.ClusterAnalytics(capacity=512))
    try:
        run_fn()  # absorb the reduction's one-time trace+compile
        on = max(run_fn()["decisions_per_s"] for _ in range(3))
        sample = analytics.get().latest()
    finally:
        analytics.uninstall()
    delta = (off - on) / max(off, 1e-9)
    return {
        "off_decisions_per_s": round(off, 1),
        "on_decisions_per_s": round(on, 1),
        "overhead_fraction": round(delta, 4),
        "within_budget": delta < 0.02,
        "sample": sample,
    }


def measure_analytics_overhead(platform: str) -> dict:
    """Config 12: analytics-plane overhead on the config-9 stream churn
    workload. The contract under test is 'zero cost when disabled, <2%
    when enabled': the off arm is plain config-9 steady state, the on arm
    runs the identical seeded churn with the post-scan reduction capturing
    every cycle. Placement chains must match between the arms — the
    reduction never touches the scan program."""
    from tpusim.simulator import run_stream_simulation

    cycles, arrivals = (40, 64) if platform != "cpu" else (24, 64)
    nodes = 4_000 if platform != "cpu" else 800

    def run():
        return run_stream_simulation(num_nodes=nodes, cycles=cycles,
                                     arrivals=arrivals, evict_fraction=0.25,
                                     seed=9)

    run_stream_simulation(num_nodes=nodes, cycles=3, arrivals=arrivals,
                          evict_fraction=0.25, seed=9)  # absorb tracing
    overhead = _analytics_overhead(run)
    log(f"[config 12] analytics capture overhead: "
        f"{overhead['overhead_fraction'] * 100:.2f}% "
        f"(within_budget={overhead['within_budget']})")

    off_chain = run()["placement_chain"]
    from tpusim.obs import analytics
    analytics.install(analytics.ClusterAnalytics(capacity=512))
    try:
        on_chain = run()["placement_chain"]
    finally:
        analytics.uninstall()

    return {
        "metric": f"analytics-on churn decisions/sec (config 12: cluster "
                  f"analytics A/B on the config-9 stream workload, {nodes} "
                  f"nodes, {arrivals} arrivals + 25% evictions per cycle, "
                  f"platform={platform})",
        "value": overhead["on_decisions_per_s"], "unit": "decisions/s",
        "vs_baseline": 0,
        "analytics_overhead": {k: v for k, v in overhead.items()
                               if k != "sample"},
        "sample": overhead["sample"],
        "chains_identical": on_chain == off_chain,
        "metrics": _metrics_snapshot(reset=True),
    }


def measure_gang_ladder(platform: str) -> dict:
    """Config 13: gang admission (tpusim/gang). Two arms over one
    rack-labeled cluster: (a) steady-state throughput of the stream gang
    route (every cycle carries pod groups, so each decision pays the joint
    host-oracle/kernel solve); (b) a packing-quality A/B — the same gang
    feed placed by the group driver vs stripped of its annotations and
    placed per-pod, comparing racks-touched-per-gang (the cross-rack
    spread the rank-aware packer exists to minimize) and node packing."""
    from tpusim.api.snapshot import make_pod, synthetic_cluster
    from tpusim.gang.group import (
        GANG_MIN_AVAILABLE_ANNOTATION,
        GANG_NAME_ANNOTATION,
        gang_name,
        mark_gang,
    )
    from tpusim.simulator import run_simulation, run_stream_simulation

    nodes, cycles, arrivals = ((2_000, 30, 32) if platform != "cpu"
                               else (400, 16, 16))
    gang_size, gang_count = 8, 2

    def racked(n):
        snap = synthetic_cluster(n)
        for i, node in enumerate(snap.nodes):
            node.metadata.labels["topology.kubernetes.io/rack"] = \
                f"rack-{i // 16}"
        return snap

    # arm (a): stream throughput with gangs riding every cycle
    snap = racked(nodes)
    run_stream_simulation(snap, cycles=3, arrivals=arrivals,
                          gang_size=gang_size, gang_count=gang_count,
                          seed=13)  # absorb tracing
    out = run_stream_simulation(racked(nodes), cycles=cycles,
                                arrivals=arrivals, evict_fraction=0.25,
                                gang_size=gang_size, gang_count=gang_count,
                                seed=13)

    # arm (b): packing quality A/B on a one-shot multi-gang batch
    def gang_feed():
        pods = []
        for g in range(8):
            pods += [mark_gang(make_pod(f"b13-g{g}-{j}", milli_cpu=500),
                               f"b13-g{g}") for j in range(gang_size)]
        return pods

    def spread(status, by):
        groups = {}
        for p in status.successful_pods:
            name = by(p)
            if not name:
                continue
            idx = int(p.spec.node_name.split("-")[-1])
            groups.setdefault(name, set()).add(idx // 16)
        if not groups:
            return 0.0
        return sum(len(r) for r in groups.values()) / len(groups)

    ab_snap = racked(256 if platform != "cpu" else 128)
    grouped = run_simulation(gang_feed(), ab_snap, backend="jax")
    stripped = gang_feed()
    for p in stripped:
        p.metadata.annotations.pop(GANG_NAME_ANNOTATION, None)
        p.metadata.annotations.pop(GANG_MIN_AVAILABLE_ANNOTATION, None)
    solo = run_simulation(stripped, ab_snap, backend="jax")
    gang_spread = spread(grouped, gang_name)
    solo_spread = spread(solo, lambda p: p.metadata.name.rsplit("-", 1)[0])
    log(f"[config 13] racks/gang: grouped={gang_spread:.2f} "
        f"per-pod={solo_spread:.2f} "
        f"(stream {out['decisions_per_s']:.0f} dec/s, "
        f"paths={out['paths']})")

    return {
        "metric": f"gang-cycle churn decisions/sec (config 13: "
                  f"{gang_count}x{gang_size}-member pod groups + {arrivals} "
                  f"solo arrivals per cycle, {nodes} rack-labeled nodes, "
                  f"platform={platform})",
        "value": out["decisions_per_s"], "unit": "decisions/s",
        "vs_baseline": 0,
        "p50_cycle_ms": out["p50_cycle_ms"],
        "p99_cycle_ms": out["p99_cycle_ms"],
        "paths": out["paths"],
        "gangs_fed": out["load"]["gangs"],
        "racks_per_gang_grouped": gang_spread,
        "racks_per_gang_per_pod": solo_spread,
        "metrics": _metrics_snapshot(reset=True),
    }


def measure_shard_scaling(platform: str) -> dict:
    """Config 14 (ISSUE 16): pods/s vs shard count for the node-sharded
    backend route. One uniform batch through the FULL JaxBackend dispatch
    (compile → pad → stage → shard_map scan) at TPUSIM_SHARDS ∈ {1, 2, 4},
    each point stamped with its staging overhead (the shard:stage span:
    pad + NamedSharding placement, paid per batch). TPUSIM_SHARD_VERIFY=0
    for the curve — the verify replay runs the single-device scan beside
    every first sharded batch, which is the seam's cost, not the route's.
    TPUSIM_FAST=0 keeps the Pallas plan from absorbing the batch before
    the shard decision. On the CPU host the mesh is virtual devices
    sharing one socket, so the curve here measures partition overhead
    (expect <= 1.0x); the TPU capture stages the same curve on a real
    mesh where the per-shard O(N/k) evaluate actually parallelizes."""
    import jax

    from tpusim.backends import placement_hash
    from tpusim.jaxe.backend import JaxBackend, reset_fast_auto
    from tpusim.obs import recorder as flight

    num_nodes = 8_192 if platform != "cpu" else 512
    num_pods = num_nodes * 4  # exactly capacity: every pod places
    shard_counts = [k for k in (1, 2, 4) if k <= len(jax.devices())]
    timed_runs = 3
    overrides = {"TPUSIM_FAST": "0", "TPUSIM_SHARD_VERIFY": "0"}
    saved = {k: os.environ.get(k) for k in (*overrides, "TPUSIM_SHARDS")}
    os.environ.update(overrides)
    curve, hashes = [], set()
    try:
        for k in shard_counts:
            os.environ["TPUSIM_SHARDS"] = str(k)
            reset_fast_auto()
            snapshot, pods = uniform_workload(num_pods, num_nodes)
            backend = JaxBackend()
            hashes.add(placement_hash(backend.schedule(pods, snapshot)))
            samples, stage_us = [], []
            for _ in range(timed_runs):
                rec = flight.install(flight.FlightRecorder())
                t0 = time.perf_counter()
                backend.schedule(pods, snapshot)
                samples.append(time.perf_counter() - t0)
                flight.uninstall()
                stage_us.append(sum(ev["dur"] for ev in rec.events
                                    if ev["name"] == "shard:stage"))
            med = float(np.median(samples))
            curve.append({
                "shards": k,
                "pods_per_s": round(num_pods / med, 1),
                "median_s": round(med, 4),
                "stage_ms": round(float(np.median(stage_us)) / 1000, 3),
            })
            log(f"[config 14] shards={k}: "
                f"{curve[-1]['pods_per_s']:.0f} pods/s "
                f"(stage {curve[-1]['stage_ms']:.1f} ms)")
    finally:
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
        reset_fast_auto()
    if len(hashes) != 1:
        raise AssertionError(
            f"shard ladder produced {len(hashes)} distinct placement "
            "hashes; the route is not byte-stable across shard counts")
    base = curve[0]["pods_per_s"]
    return {
        "metric": f"sharded-twin throughput curve (config 14: {num_pods}"
                  f" uniform pods, {num_nodes} nodes, shards="
                  f"{shard_counts}, platform={platform})",
        "value": curve[-1]["pods_per_s"], "unit": "pods/s",
        "vs_baseline": 0,
        "shard_curve": curve,
        "speedup_vs_one_shard": round(curve[-1]["pods_per_s"] / base, 3),
        "metrics": _metrics_snapshot(reset=True),
    }


def measure_replication(platform: str) -> dict:
    """Config 15 (ISSUE 18): hot-standby failover economics. Two curves:

    - RTO vs checkpoint cadence: a replicated pair (leader + live
      FollowerTwin over the WAL-shipping socket) is killed at the emit
      boundary of a seeded mid-run cycle; the FailoverController
      promotes the follower and the churn load resumes on the twin.
      Promotion replays ONLY the unshipped tail, so the end-to-end RTO
      should stay flat as checkpoints thin out — cold recovery's replay
      (config 11) grows with the same interval, which is the standby's
      economic claim. Every point must land on the crash-free fold
      chain (the correctness bar rides along with the latency one).
    - replication lag vs churn: the shipping backlog the pair sustains
      (records unacked the instant the producer stops) and the shipped
      rate as the arrival rate doubles, on crash-free replicated runs
      whose drained chains must match on both sides.
    """
    import shutil
    import tempfile

    from tpusim.chaos.plan import CRASH_POINTS, kill_leader_campaign
    from tpusim.simulator import run_replicated_stream, run_stream_simulation

    nodes, cycles, arrivals = ((2_000, 32, 64) if platform != "cpu"
                               else (400, 16, 32))

    # the parity oracle: the same workload, uninterrupted + unreplicated
    base_dir = tempfile.mkdtemp(prefix="tpusim-bench-rep-")
    try:
        base_chain = run_stream_simulation(
            num_nodes=nodes, cycles=cycles, arrivals=arrivals,
            evict_fraction=0.25, seed=11, checkpoint_dir=base_dir,
            checkpoint_every=cycles + 1)["fold_chain"]
    finally:
        shutil.rmtree(base_dir, ignore_errors=True)

    campaign = kill_leader_campaign(seed=11, cycles=cycles)
    crash_plan = campaign[CRASH_POINTS.index("emit")]
    rto_curve = []
    for every in (1, 5, 20):
        ckdir = tempfile.mkdtemp(prefix="tpusim-bench-rep-")
        try:
            out = run_replicated_stream(
                num_nodes=nodes, cycles=cycles, arrivals=arrivals,
                evict_fraction=0.25, seed=11, chaos_plan=crash_plan,
                checkpoint_dir=ckdir, checkpoint_every=every)
            if not (out["crashed"] and out["promoted"]):
                raise RuntimeError(
                    f"config 15: scripted leader kill did not promote "
                    f"(crashed={out['crashed']} promoted={out['promoted']})")
            rto_curve.append({
                "checkpoint_every": every,
                "rto_ms": round(out["rto_s"] * 1e3, 2),
                "replayed_records": out["replayed_records"],
                "wal_records": out["wal_records"],
                "tail_fraction": round(
                    out["replayed_records"] / max(out["wal_records"], 1), 4),
                "resume_cycle": out["resume_cycle"],
                "lag_at_crash": out["lag_at_crash"],
                "violations": out["promotion_violations"],
                "chain_identical": out["fold_chain"] == base_chain})
            log(f"[config 15] checkpoint_every={every}: rto "
                f"{rto_curve[-1]['rto_ms']:.1f} ms, replayed "
                f"{out['replayed_records']}/{out['wal_records']} records, "
                f"chain_identical={rto_curve[-1]['chain_identical']}")
        finally:
            shutil.rmtree(ckdir, ignore_errors=True)

    lag_curve = []
    for arr in (arrivals // 2, arrivals, arrivals * 2):
        ckdir = tempfile.mkdtemp(prefix="tpusim-bench-rep-")
        try:
            t0 = time.perf_counter()
            out = run_replicated_stream(
                num_nodes=nodes, cycles=cycles, arrivals=arr,
                evict_fraction=0.25, seed=11,
                checkpoint_dir=ckdir, checkpoint_every=5)
            elapsed = time.perf_counter() - t0
            lag_curve.append({
                "arrivals_per_cycle": arr,
                "wal_records": out["wal_records"],
                "applied_records": out["applied_records"],
                "lag_at_loop_end": out["lag_at_loop_end"],
                "ship_records_per_s": round(
                    out["wal_records"] / max(elapsed, 1e-9), 1),
                "drained": out["drained"],
                "chain_identical": out["follower_chain_matches"]})
            log(f"[config 15] arrivals={arr}: lag_at_loop_end="
                f"{out['lag_at_loop_end']} of {out['wal_records']} records, "
                f"chain_match={out['follower_chain_matches']}")
        finally:
            shutil.rmtree(ckdir, ignore_errors=True)

    return {
        "metric": f"hot-standby failover RTO (config 15: leader killed at "
                  f"the emit boundary with a live follower attached, "
                  f"checkpoint_every=1, {nodes} nodes, {cycles} cycles, "
                  f"platform={platform})",
        "value": rto_curve[0]["rto_ms"], "unit": "ms",
        "vs_baseline": 0,
        "rto_curve": rto_curve,
        "lag_curve": lag_curve,
        "chains_identical": (
            all(r["chain_identical"] for r in rto_curve)
            and all(r["chain_identical"] for r in lag_curve)),
        "tail_only_replay": all(
            r["replayed_records"] < r["wal_records"] for r in rto_curve),
        "metrics": _metrics_snapshot(reset=True),
    }


def measure_live_whatif(platform: str) -> dict:
    """Config 16 (ISSUE 19): live-twin serving economics. Three curves:

    - overlay-vs-staged latency vs cluster size: answer the SAME what-if
      query against a churn-warm device-resident twin via (a) a
      copy-on-write overlay on the resident carry (mark -> scatter the
      scenario pods -> fused scan -> roll back) and (b) the staged
      run_what_if path, which re-stages the whole cluster per query.
      Staged cost grows with the cluster; the overlay rides the already
      resident arrays, so its warm latency should stay ~flat. Every
      point must be placement-hash identical across both paths, and the
      warm overlay repeats must trace ZERO new programs.
    - queries/s at fixed churn: overlay throughput interleaved with a
      live churn loop (whatif_every=1), plus proof that the interleaved
      queries leave the churn run's fold chain byte-unchanged.
    - tenant evict/restore round-trip (stream.tenancy): checkpoint
      eviction cost and the O(WAL-tail) restore, chain heads intact
      across the round trip.
    """
    import shutil
    import tempfile

    from tpusim.api.snapshot import make_pod, synthetic_cluster
    from tpusim.backends import placement_hash
    from tpusim.jaxe.whatif import compile_count, run_what_if
    from tpusim.simulator import run_stream_simulation
    from tpusim.stream import ChurnLoadGen, StreamSession

    sizes = ((200, 800, 3_200, 20_000) if platform != "cpu"
             else (100, 200, 800))
    warm_cycles, arrivals = 4, 32
    rng = np.random.RandomState(16)
    qpods = [make_pod(f"bench16-q{i}",
                      milli_cpu=int(rng.randint(100, 1500)),
                      memory=int(rng.randint(2 ** 20, 2 ** 30)))
             for i in range(8)]

    def warm_twin(n):
        session = StreamSession(synthetic_cluster(n))
        gen = ChurnLoadGen(synthetic_cluster(n), seed=16, arrivals=arrivals,
                           evict_fraction=0.25)
        for c in range(warm_cycles):
            session.apply_events(gen.events(c))
            gen.note_bound(session.schedule(gen.batch()))
        return session

    overlay_curve = []
    for n in sizes:
        session = warm_twin(n)
        first = session.overlay_query(qpods)   # absorb the overlay trace
        if first is None:
            raise RuntimeError(f"config 16: overlay refused at {n} nodes")
        traced_before = compile_count()
        reps = 5
        t0 = time.perf_counter()
        for _ in range(reps):
            placements = session.overlay_query(qpods)
        overlay_ms = (time.perf_counter() - t0) / reps * 1e3
        retraces = compile_count() - traced_before
        # staged comparison arm: full re-stage of the SAME logical state;
        # time the warm second call so both arms exclude their compile
        live_snap = session.inc.to_snapshot()
        run_what_if([(live_snap, qpods)])
        t0 = time.perf_counter()
        [staged] = run_what_if([(live_snap, qpods)])
        staged_ms = (time.perf_counter() - t0) * 1e3
        parity = placement_hash(placements) == \
            placement_hash(staged.placements)
        overlay_curve.append({
            "nodes": n,
            "overlay_ms": round(overlay_ms, 3),
            "staged_ms": round(staged_ms, 3),
            "staged_vs_overlay": round(staged_ms / max(overlay_ms, 1e-9), 2),
            "overlay_retraces": retraces,
            "parity": parity})
        log(f"[config 16] {n} nodes: overlay {overlay_ms:.2f} ms vs staged "
            f"{staged_ms:.2f} ms ({overlay_curve[-1]['staged_vs_overlay']}x),"
            f" retraces={retraces}, parity={parity}")

    # queries/s riding live churn + the chain-invariance proof
    mid = sizes[1]
    churn_kw = dict(num_nodes=mid, cycles=12, arrivals=arrivals,
                    evict_fraction=0.25, seed=16)
    run_stream_simulation(**churn_kw)               # warm the shapes
    base = run_stream_simulation(**churn_kw)
    live = run_stream_simulation(**churn_kw, whatif_every=1, whatif_pods=8)
    chain_unchanged = live["fold_chain"] == base["fold_chain"]
    ov = live["overlay"]
    qps = (ov["answered"]
           / max(live["elapsed_s"] - base["elapsed_s"], 1e-9))
    log(f"[config 16] {mid} nodes under churn: {ov['answered']} overlay "
        f"queries ({ov['fallbacks']} fallbacks), p50 "
        f"{ov['p50_query_ms']:.2f} ms, chain_unchanged={chain_unchanged}")

    # tenant evict/restore round trip under the residency ledger
    from tpusim.stream.tenancy import ResidencyBudget

    tdir = tempfile.mkdtemp(prefix="tpusim-bench-tenancy-")
    tenant_curve = []
    try:
        budget = ResidencyBudget(1 << 40)
        for name in ("a", "b"):
            s = budget.admit(name, synthetic_cluster(sizes[0]),
                             directory=os.path.join(tdir, name))
            gen = ChurnLoadGen(synthetic_cluster(sizes[0]), seed=16,
                               arrivals=arrivals, evict_fraction=0.25)
            for c in range(warm_cycles):
                s.apply_events(gen.events(c))
                gen.note_bound(s.schedule(gen.batch()))
        for name in ("a", "b"):
            chain_before = budget.chain(name)
            t0 = time.perf_counter()
            budget.evict(name)
            evict_ms = (time.perf_counter() - t0) * 1e3
            t0 = time.perf_counter()
            budget.restore(name)
            restore_ms = (time.perf_counter() - t0) * 1e3
            tenant_curve.append({
                "tenant": name,
                "evict_ms": round(evict_ms, 2),
                "restore_ms": round(restore_ms, 2),
                "chain_intact": budget.chain(name) == chain_before})
            log(f"[config 16] tenant {name}: evict {evict_ms:.1f} ms, "
                f"restore {restore_ms:.1f} ms, chain_intact="
                f"{tenant_curve[-1]['chain_intact']}")
    finally:
        shutil.rmtree(tdir, ignore_errors=True)

    return {
        "metric": f"live what-if overlay latency (config 16: warm overlay "
                  f"query on the device-resident twin, {sizes[-1]} nodes, "
                  f"8 scenario pods, platform={platform})",
        "value": overlay_curve[-1]["overlay_ms"], "unit": "ms",
        "vs_baseline": 0,
        "overlay_curve": overlay_curve,
        # warm overlay growth across the size sweep (the scan itself is
        # O(N) compute, so ~flat here means the staging term is gone, not
        # that the scan is free); the staged arm's own ratio rides along
        "overlay_flatness": round(
            overlay_curve[-1]["overlay_ms"]
            / max(overlay_curve[0]["overlay_ms"], 1e-9), 2),
        "staged_flatness": round(
            overlay_curve[-1]["staged_ms"]
            / max(overlay_curve[0]["staged_ms"], 1e-9), 2),
        "zero_retrace": all(
            r["overlay_retraces"] == 0 for r in overlay_curve),
        "queries_per_s_under_churn": round(qps, 1),
        "churn_overlay": ov,
        "tenant_curve": tenant_curve,
        "chains_identical": (
            chain_unchanged
            and all(r["parity"] for r in overlay_curve)
            and all(r["chain_intact"] for r in tenant_curve)),
        "metrics": _metrics_snapshot(reset=True),
    }


def run_phases(platform: str, chunk: int) -> None:
    """Per-phase time split + tuning sweeps (BASELINE.md 'per-phase time
    split'; VERDICT round-1 item 9).

    The production pipeline is ONE fused device program (filter→score→
    select→bind), so phases have no individually observable device time
    there; the split below times phase-isolated jitted programs over the same
    pods against a frozen snapshot (vmapped over pods): filter-only (score
    ops dead-code-eliminated by XLA), filter+score, +select, and the full
    step incl. the bind scatters. Also sweeps TPUSIM_SCAN_UNROLL for the
    exact scan."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from tpusim.jaxe.kernels import (
        _evaluate,
        _select,
        carry_init,
        schedule_scan,
    )

    # 5k pods keeps the [P, N] phase-program intermediates ~200MB (int64):
    # the 20k-pod shape wedged the axon tunnel mid-rep; the split is per-pod
    # normalized so the smaller pod axis costs nothing but noise
    num_pods = int(os.environ.get("TPUSIM_BENCH_PHASE_PODS", 5_000))
    num_nodes = int(os.environ.get("TPUSIM_BENCH_NODES", 5_000))
    if platform == "cpu":
        num_pods, num_nodes = 5_000, 1_000
    snapshot, pods = build_workload(num_pods, num_nodes)
    compiled, config, carry, statics, xs, _cols = _prepare(snapshot, pods)

    def timeit(fn, *args, reps=3, label=""):
        # per-stage logs keep the parent's stall watchdog fed: phase-program
        # XLA compiles at this shape run minutes each on the TPU tunnel
        if label:
            log(f"  [{label}] compiling...")
        t0 = time.perf_counter()
        out = fn(*args)           # compile
        jax.tree_util.tree_map(np.asarray, out)
        if label:
            log(f"  [{label}] compile+first run {time.perf_counter() - t0:.1f}s")
        times = []
        for r in range(reps):
            t0 = time.perf_counter()
            out = fn(*args)
            jax.tree_util.tree_map(np.asarray, out)  # force
            times.append(time.perf_counter() - t0)
            if label:
                log(f"  [{label}] rep {r + 1}/{reps}: {times[-1]:.3f}s")
        return float(np.median(times))

    # stage order: production-path tuning sweeps first, phase-isolated split
    # last — a mid-run tunnel wedge still leaves the tuning data (the parent
    # keeps the LAST JSON line printed, even from a killed child)
    summary = {
        "metric": f"per-phase split + tuning ({num_pods // 1000}k pods, "
                  f"{num_nodes} nodes, platform={platform})",
        "value": 0.0,
        "unit": "pods/s",
        "vs_baseline": 0,
        "metrics": _metrics_snapshot(reset=True),
    }

    # --- exact-scan unroll sweep ---
    unroll_results = {}
    for unroll in (1, 2, 4, 8):
        cfg_u = dataclasses.replace(config, scan_unroll=unroll)
        t = timeit(lambda cu=cfg_u: schedule_scan(cu, carry_init(compiled),
                                                  statics, xs)[1], reps=3,
                   label=f"unroll {unroll}")
        unroll_results[str(unroll)] = round(num_pods / t, 1)
        log(f"[unroll {unroll}] exact scan: {num_pods / t:.0f} pods/s")
    best_unroll = max(unroll_results, key=lambda k: unroll_results[k])
    summary.update(value=unroll_results[best_unroll],
                   exact_scan_unroll_pods_per_s=unroll_results,
                   best_unroll=int(best_unroll))
    print(json.dumps(summary), flush=True)

    # --- phase-isolated programs (vmapped over the pod axis, frozen carry) ---
    filter_fn = jax.jit(lambda c, s, x: jax.vmap(
        lambda xi: _evaluate(config, c, s, xi)[:2])(x))
    eval_fn = jax.jit(lambda c, s, x: jax.vmap(
        lambda xi: _evaluate(config, c, s, xi))(x))

    def select_stage(c, s, x):
        feasible, _, score, n_feasible, _aca = jax.vmap(
            lambda xi: _evaluate(config, c, s, xi))(x)
        rr = jnp.arange(feasible.shape[0], dtype=jnp.int64)
        return jax.vmap(_select)(feasible, score, n_feasible, rr)

    select_fn = jax.jit(select_stage)

    def full_stage(c, s, x):
        # filter+score+select plus the bind scatters (segment-sum by chosen
        # node) — the whole per-pod pipeline against the frozen carry
        feasible, _, score, n_feasible, _aca = jax.vmap(
            lambda xi: _evaluate(config, c, s, xi))(x)
        rr = jnp.arange(feasible.shape[0], dtype=jnp.int64)
        choices, founds = jax.vmap(_select)(feasible, score, n_feasible, rr)
        n = c.used_cpu.shape[0]
        gate = founds.astype(jnp.int64)
        seg = jnp.where(gate == 1, choices, n)

        def scatter(amounts, target):
            return target + jax.ops.segment_sum(
                amounts * gate, seg, num_segments=n + 1)[:n]

        return (scatter(x.req_cpu, c.used_cpu),
                scatter(x.req_mem, c.used_mem),
                scatter(x.nz_cpu, c.nonzero_cpu),
                scatter(x.nz_mem, c.nonzero_mem),
                scatter(jnp.ones_like(gate), c.pod_count), choices)

    full_fn = jax.jit(full_stage)

    t_filter = timeit(filter_fn, carry, statics, xs, label="filter")
    t_eval = timeit(eval_fn, carry, statics, xs, label="filter+score")
    t_select = timeit(select_fn, carry, statics, xs, label="+select")
    t_full = timeit(full_fn, carry, statics, xs, label="full step")
    phases = {
        "filter_us_per_pod": round(1e6 * t_filter / num_pods, 3),
        "score_us_per_pod": round(1e6 * max(t_eval - t_filter, 0.0) / num_pods, 3),
        "select_us_per_pod": round(1e6 * max(t_select - t_eval, 0.0) / num_pods, 3),
        "bind_us_per_pod": round(1e6 * max(t_full - t_select, 0.0) / num_pods, 3),
    }
    log(f"[phases] {num_pods} pods x {num_nodes} nodes (frozen snapshot): "
        f"filter {t_filter:.3f}s, +score {t_eval:.3f}s, "
        f"+select {t_select:.3f}s, full step {t_full:.3f}s")
    log(f"[phases] per-pod split: {phases}")
    summary["phases"] = phases
    print(json.dumps(summary), flush=True)


# --------------------------------------------------------------------------
# parent: watchdogged child with retries + CPU fallback
# --------------------------------------------------------------------------

def _graceful_stop(proc, reason: str) -> None:
    """Stop a child that may be inside a TPU device op. NEVER SIGKILL first:
    a hard kill mid-op has permanently wedged the axon tunnel for every later
    process (BASELINE.md). SIGINT lets the JAX runtime unwind; SIGTERM's
    kernel-side default disposition works even with the GIL held in C++;
    SIGKILL is the last resort for a truly unkillable child."""
    log(f"  stopping child ({reason}): SIGINT")
    try:
        proc.send_signal(signal.SIGINT)
    except OSError:
        return  # already gone
    try:
        proc.wait(timeout=15)
        return
    except subprocess.TimeoutExpired:
        pass
    log("  child ignored SIGINT; SIGTERM")
    proc.terminate()
    try:
        proc.wait(timeout=10)
        return
    except subprocess.TimeoutExpired:
        pass
    log("  child ignored SIGTERM; SIGKILL (last resort)")
    proc.kill()


_PROBE_WEDGE_CACHE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "bench_results",
    ".probe_wedged_at")


def preflight_probe(timeout: float):
    """One tiny device op in a throwaway subprocess; returns the resolved
    platform string, or None if the op didn't complete within `timeout`
    (wedged tunnel / hung backend init). Keeps the main attempts from ever
    touching a dead tunnel.

    A WEDGED verdict is cached on disk for TPUSIM_BENCH_PROBE_CACHE_TTL
    seconds (default 120): back-to-back invocations (the capture script's
    config-5 warm pair, the watcher's staged retries) then skip straight
    to the CPU fallback instead of each re-paying the full probe timeout.
    Only the negative verdict is cached — a healthy probe is fast and is
    always re-taken."""
    ttl = float(os.environ.get("TPUSIM_BENCH_PROBE_CACHE_TTL", 120))
    if ttl > 0:
        try:
            with open(_PROBE_WEDGE_CACHE) as f:
                age = time.time() - float(f.read().strip())
            if 0 <= age < ttl:
                log(f"probe skipped: tunnel was wedged {age:.0f}s ago "
                    f"(< {ttl:.0f}s TTL); assuming still wedged")
                return None
        except (OSError, ValueError):
            pass
    code = ("import jax, jax.numpy as jnp; d = jax.devices(); "
            "print('PROBE', d[0].platform, int(jnp.ones((8, 8)).sum()), "
            "flush=True)")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        _graceful_stop(proc, f"probe exceeded {timeout:.0f}s")
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        _note_probe_wedged()
        return None
    for line in (out or "").splitlines():
        parts = line.split()
        if len(parts) == 3 and parts[0] == "PROBE" and parts[2] == "64":
            try:
                os.unlink(_PROBE_WEDGE_CACHE)
            except OSError:
                pass
            return parts[1]
    # a fast non-timeout failure costs nothing to re-take: only the
    # timeout verdict (the expensive one the cache exists for) is cached
    return None


def _note_probe_wedged() -> None:
    try:
        os.makedirs(os.path.dirname(_PROBE_WEDGE_CACHE), exist_ok=True)
        with open(_PROBE_WEDGE_CACHE, "w") as f:
            f.write(str(time.time()))
    except OSError:
        pass


def run_watchdogged(cmd, stall_timeout: float, total_timeout: float,
                    init_timeout: float | None = None):
    """Run `cmd`, streaming its stderr; stop it if no output arrives for
    `stall_timeout` seconds or the total exceeds `total_timeout`. Until the
    child reports its device list ("devices:" line) the tighter
    `init_timeout` applies — backend-init wedges are the tunnel's known
    failure mode and deserve fast detection. Returns
    (json_lines_from_stdout, error | None) — partial results from a stopped
    child still count. Per-stream reader threads feed a queue so a child
    that wedges mid-line (or bursts multiple lines) can neither block the
    watchdog nor strand buffered output."""
    import queue
    import threading

    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True,
                            cwd=os.path.dirname(os.path.abspath(__file__)))
    q: queue.Queue = queue.Queue()

    def pump(stream, tag):
        for line in iter(stream.readline, ""):
            q.put((tag, line.rstrip("\n")))
        q.put((tag, None))

    threads = [threading.Thread(target=pump, args=(proc.stdout, "out"), daemon=True),
               threading.Thread(target=pump, args=(proc.stderr, "err"), daemon=True)]
    for t in threads:
        t.start()

    start = last_output = time.monotonic()
    json_lines = []
    error = None
    open_streams = 2
    init_done = False
    while open_streams:
        now = time.monotonic()
        limit = stall_timeout if init_done else (init_timeout or stall_timeout)
        if now - last_output > limit:
            phase = "stalled" if init_done else "backend-init stall"
            error = f"no output for {limit:.0f}s ({phase}); stopped"
            _graceful_stop(proc, error)
            break
        if now - start > total_timeout:
            error = f"exceeded total timeout {total_timeout:.0f}s; stopped"
            _graceful_stop(proc, error)
            break
        try:
            tag, line = q.get(timeout=5.0)
        except queue.Empty:
            continue
        if line is None:
            open_streams -= 1
            continue
        last_output = time.monotonic()
        if tag == "err" and line.startswith("devices:"):
            init_done = True
        if tag == "out":
            if line.strip().startswith("{"):
                try:
                    json_lines.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
        else:
            log(f"  [child] {line}")
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
    # drain anything the pumps captured before the kill
    while True:
        try:
            tag, line = q.get_nowait()
        except queue.Empty:
            break
        if tag == "out" and line and line.strip().startswith("{"):
            try:
                json_lines.append(json.loads(line))
            except json.JSONDecodeError:
                pass
        elif tag == "err" and line:
            log(f"  [child] {line}")
    if error is None and proc.returncode != 0:
        error = f"child exited rc={proc.returncode}"
    if json_lines and error is not None:
        last = json_lines[-1]
        last["note"] = (last.get("note", "") + "; " if last.get("note")
                        else "") + f"partial: {error}"
        return json_lines, None
    if json_lines:
        return json_lines, None
    return [], error or "child produced no JSON line"


# the ladder subset a healthy accelerator promotes the default run to
# (VERDICT r3 item 1: the north-star shapes) — derived from the registry,
# so a new LADDER_CONFIGS row opts into captures right there
AUTOLADDER_DEFAULT_CONFIGS = ",".join(
    str(n) for n, cfg in LADDER_CONFIGS.items() if cfg.autoladder)


def pick_headline(json_lines):
    """The ladder summary line quotes the headline config (3: 100k x 5k) —
    not the best rate, which a toy config would trivially win. An
    error-free pallas record for config 3 wins over the plain XLA record
    (it is the round-5 evidence the driver artifact exists to carry);
    anything else falls back to the last line."""
    return next(
        (r for r in json_lines
         if "config 3" in r.get("metric", "")
         and "(pallas)" in r.get("metric", "") and "error" not in r),
        next((r for r in json_lines
              if "config 3" in r.get("metric", "")
              and "(pallas)" not in r.get("metric", "")),
             json_lines[-1]))


def plan_attempts(probed, ladder: bool, phases: bool, retries: int):
    """(attempts, auto_ladder) for the watchdogged child runs.

    probed None (wedged tunnel) or "cpu" -> one CPU attempt. A healthy
    accelerator gets `retries` default-backend attempts plus a CPU fallback,
    and — unless the caller already asked for --ladder/--phases or set
    TPUSIM_BENCH_TPU_AUTOLADDER=0 — promotes the default invocation to the
    ladder HEADLINE configs (VERDICT r3 item 1): the driver-verified
    artifact then measures the north-star shapes (config 3: 100k x 5k;
    4: 1M x 10k; 5: what-if) instead of the small default. Only the
    "default" attempts run the promoted ladder; the CPU fallback keeps the
    plain default workload. No env writes (only the
    TPUSIM_BENCH_TPU_AUTOLADDER kill switch is read); the caller owns the
    TPUSIM_BENCH_LADDER_CONFIGS default (AUTOLADDER_DEFAULT_CONFIGS) and
    its validation."""
    if probed is None or probed == "cpu":
        # no accelerator (or its plugin failed init cleanly): no point in
        # default-backend attempts
        return [("cpu", 1)], False
    attempts = ([("default", a) for a in range(1, retries + 1)]
                + [("cpu", 1)])
    auto_ladder = (not ladder and not phases
                   and os.environ.get("TPUSIM_BENCH_TPU_AUTOLADDER", "1")
                   != "0")
    return attempts, auto_ladder


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        run_child(sys.argv[2] if len(sys.argv) > 2 else "default",
                  ladder="--ladder" in sys.argv,
                  phases="--phases" in sys.argv)
        return
    ladder = "--ladder" in sys.argv
    phases = "--phases" in sys.argv
    if ladder:
        _ladder_configs()  # validate the knob before spawning any child

    # persistent XLA compile cache for every child (config 5's per-process
    # ~2min compile becomes a one-time cost); TPUSIM_COMPILE_CACHE="" disables
    os.environ.setdefault(
        "TPUSIM_COMPILE_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))

    stall_timeout = float(os.environ.get("TPUSIM_BENCH_STALL_TIMEOUT", 240))
    init_timeout = float(os.environ.get("TPUSIM_BENCH_INIT_TIMEOUT", 75))
    probe_timeout = float(os.environ.get("TPUSIM_BENCH_PROBE_TIMEOUT", 40))
    run_timeout = float(os.environ.get("TPUSIM_BENCH_RUN_TIMEOUT", 2400))
    retries = int(os.environ.get("TPUSIM_BENCH_RETRIES", 2))

    errors: list[str] = []
    log(f"pre-flight probe (timeout {probe_timeout:.0f}s)...")
    t0 = time.monotonic()
    probed = preflight_probe(probe_timeout)
    if probed is None:
        errors.append(f"tpu_unavailable: pre-flight device op did not "
                      f"complete within {probe_timeout:.0f}s; CPU fallback")
        log(f"probe FAILED after {time.monotonic() - t0:.0f}s "
            "(wedged tunnel / hung backend init); skipping straight to CPU")
    else:
        log(f"probe OK: platform={probed} ({time.monotonic() - t0:.0f}s)")
    attempts, auto_ladder = plan_attempts(probed, ladder, phases, retries)
    if auto_ladder:
        os.environ.setdefault("TPUSIM_BENCH_LADDER_CONFIGS",
                              AUTOLADDER_DEFAULT_CONFIGS)
        _ladder_configs()  # validate (incl. any user override) before spawning
        log("TPU present: promoting default run to ladder configs "
            + os.environ["TPUSIM_BENCH_LADDER_CONFIGS"])
    for target, attempt in attempts:
        use_ladder = ladder or (auto_ladder and target == "default")
        log(f"benchmark on {target!r} (attempt {attempt}, "
            f"stall timeout {stall_timeout:.0f}s, total {run_timeout:.0f}s)")
        cmd = [sys.executable, os.path.abspath(__file__), "--child", target]
        if use_ladder:
            cmd.append("--ladder")
        if phases:
            cmd.append("--phases")
        json_lines, err = run_watchdogged(cmd, stall_timeout, run_timeout,
                                          init_timeout=init_timeout)
        if json_lines:
            if use_ladder:
                # one line per completed config, then the HEADLINE config
                # (3: 100k Zipf / 5k nodes) as the summary line — not the
                # best rate, which a toy config would trivially win
                for line in json_lines:
                    print(json.dumps(line), flush=True)
                headline = pick_headline(json_lines)
                summary = dict(headline)
                summary["metric"] = (f"ladder ({len(json_lines)} configs), "
                                     f"headline: " + summary["metric"])
                result = summary
            else:
                result = json_lines[-1]
            if errors:
                result["note"] = (result.get("note", "") + "; " if
                                  result.get("note") else "") + "; ".join(errors)
            print(json.dumps(result), flush=True)
            return
        errors.append(f"{target} attempt {attempt}: {err}")
        log(f"FAILED: {err}")
        if target == "default" and attempt < retries:
            backoff = 20.0 * attempt
            log(f"retrying in {backoff:.0f}s")
            time.sleep(backoff)

    print(json.dumps({
        "metric": "scheduled pods/sec (benchmark failed)",
        "value": 0,
        "unit": "pods/s",
        "vs_baseline": 0,
        "error": "; ".join(errors),
    }), flush=True)


if __name__ == "__main__":
    main()

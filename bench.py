"""Benchmark: scheduled pods/sec, exact-scan jax backend vs the Python
reference loop (the stand-in for the Go loop — the reference publishes no
numbers and ships no buildable toolchain here; see BASELINE.md).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "pods/s", "vs_baseline": N}
vs_baseline = jax rate / reference-loop rate on the same workload shape (>1 is
faster). Details go to stderr.

Workload: BASELINE.md config 3 — mixed Zipf-sized pods onto heterogeneous
nodes (with a taint/toleration slice), exact sequential semantics.

Env knobs: TPUSIM_BENCH_PODS (default 100000), TPUSIM_BENCH_NODES (5000),
TPUSIM_BENCH_BASELINE_PODS (200), TPUSIM_BENCH_BATCH (0 = exact scan).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_workload(num_pods: int, num_nodes: int):
    from tpusim.api.snapshot import ClusterSnapshot, make_node, make_pod

    rng = np.random.RandomState(12345)
    nodes = []
    for i in range(num_nodes):
        shape = i % 3
        milli_cpu = [4000, 8000, 16000][shape]
        memory = [8, 16, 32][shape] * 1024**3
        taints = None
        if i % 10 == 0:
            taints = [{"key": "dedicated", "value": "batch", "effect": "NoSchedule"}]
        nodes.append(make_node(f"node-{i}", milli_cpu=milli_cpu, memory=memory,
                               pods=110, labels={"zone": f"z{i % 4}"}, taints=taints))

    # Zipf-ish request sizes over discrete buckets
    cpu_buckets = np.array([50, 100, 250, 500, 1000, 2000, 4000])
    mem_buckets = np.array([64, 128, 256, 512, 1024, 2048, 4096]) * 2**20
    weights = 1.0 / np.arange(1, len(cpu_buckets) + 1) ** 1.1
    weights /= weights.sum()
    cpu_idx = rng.choice(len(cpu_buckets), size=num_pods, p=weights)
    mem_idx = rng.choice(len(mem_buckets), size=num_pods, p=weights)
    tolerate = rng.rand(num_pods) < 0.1

    pods = []
    for i in range(num_pods):
        kwargs = {}
        if tolerate[i]:
            kwargs["tolerations"] = [{"key": "dedicated", "operator": "Equal",
                                      "value": "batch", "effect": "NoSchedule"}]
        pods.append(make_pod(f"p-{i}", milli_cpu=int(cpu_buckets[cpu_idx[i]]),
                             memory=int(mem_buckets[mem_idx[i]]), **kwargs))
    return ClusterSnapshot(nodes=nodes), pods


def main() -> None:
    num_pods = int(os.environ.get("TPUSIM_BENCH_PODS", 100_000))
    num_nodes = int(os.environ.get("TPUSIM_BENCH_NODES", 5_000))
    baseline_pods = int(os.environ.get("TPUSIM_BENCH_BASELINE_PODS", 200))
    batch = int(os.environ.get("TPUSIM_BENCH_BATCH", 0))

    import jax

    from tpusim.backends import ReferenceBackend
    from tpusim.jaxe import ensure_x64
    from tpusim.jaxe.backend import _MOST_REQUESTED_PROVIDERS  # noqa: F401
    from tpusim.jaxe.kernels import (
        config_for,
        carry_init,
        pod_columns_to_device,
        schedule_scan,
        schedule_wavefront,
        statics_to_device,
    )
    from tpusim.jaxe.state import NUM_FIXED_BITS, compile_cluster

    ensure_x64()
    log(f"devices: {jax.devices()}")
    log(f"workload: {num_pods} pods x {num_nodes} nodes "
        f"({'exact scan' if batch == 0 else f'wavefront K={batch}'})")

    t0 = time.perf_counter()
    snapshot, pods = build_workload(num_pods, num_nodes)
    log(f"workload build: {time.perf_counter() - t0:.1f}s")

    # --- python reference-loop baseline on a subsample ---
    t0 = time.perf_counter()
    ref_placements = ReferenceBackend().schedule(pods[:baseline_pods], snapshot)
    ref_elapsed = time.perf_counter() - t0
    ref_rate = baseline_pods / ref_elapsed
    log(f"reference loop: {baseline_pods} pods in {ref_elapsed:.1f}s "
        f"= {ref_rate:.1f} pods/s "
        f"({sum(p.scheduled for p in ref_placements)} scheduled)")

    # --- jax backend ---
    t0 = time.perf_counter()
    compiled, cols = compile_cluster(snapshot, pods)
    log(f"host compile (intern+tables): {time.perf_counter() - t0:.1f}s")

    config = config_for(
        [compiled], most_requested=False,
        num_reason_bits=NUM_FIXED_BITS + len(compiled.scalar_names))
    carry = carry_init(compiled)
    statics = statics_to_device(compiled)
    xs = pod_columns_to_device(cols)

    def run():
        if batch > 0:
            _, choices, counts = schedule_wavefront(config, carry, statics, xs, batch)
        else:
            _, choices, counts = schedule_scan(config, carry, statics, xs)
        # NB: on the axon TPU runtime block_until_ready() returns before the
        # computation finishes; fetching the values is what actually blocks,
        # so time the full dispatch+fetch (which the simulator needs anyway).
        return np.asarray(choices)

    t0 = time.perf_counter()
    choices = run()
    cold = time.perf_counter() - t0
    log(f"device cold (incl XLA compile): {cold:.1f}s")

    # the first warm repeat right after compile can report a bogus ~0s on the
    # axon runtime; take the median of 3 timed runs
    warm_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        choices = run()
        warm_times.append(time.perf_counter() - t0)
    warm = float(np.median(warm_times))
    rate = num_pods / warm
    scheduled = int(np.sum(choices >= 0))
    log(f"device warm (median of {[f'{t:.3f}' for t in warm_times]}): "
        f"{num_pods} pods in {warm:.2f}s = {rate:.0f} pods/s "
        f"({scheduled} scheduled, {num_pods - scheduled} unschedulable)")

    # sanity: jax choices agree with the reference loop on the subsample
    names = compiled.statics.names
    mismatches = sum(
        1 for i in range(baseline_pods)
        if (names[choices[i]] if choices[i] >= 0 else "") != ref_placements[i].node_name)
    log(f"parity check on first {baseline_pods} pods: {mismatches} mismatches")

    mode = "exact scan" if batch == 0 else f"wavefront K={batch}"
    print(json.dumps({
        "metric": f"scheduled pods/sec ({num_pods // 1000}k Zipf pods, "
                  f"{num_nodes} heterogeneous nodes, {mode}, "
                  f"parity_mismatches={mismatches})",
        "value": round(rate, 1),
        "unit": "pods/s",
        "vs_baseline": round(rate / ref_rate, 2),
    }))


if __name__ == "__main__":
    main()

"""Benchmark: scheduled pods/sec, exact-scan jax backend vs the Python
reference loop (the stand-in for the Go loop — the reference publishes no
numbers and ships no buildable toolchain here; see BASELINE.md).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "pods/s", "vs_baseline": N}
vs_baseline = jax rate / reference-loop rate on the same workload shape (>1 is
faster). Details go to stderr. Never exits non-zero: on failure the JSON line
carries an "error" field instead (the TPU tunnel here can hang indefinitely
inside backend init, so all jax work runs in timeout-guarded subprocesses with
bounded retries and a CPU fallback).

Workload: BASELINE.md config 3 — mixed Zipf-sized pods onto heterogeneous
nodes (with a taint/toleration slice), exact sequential semantics.

Env knobs: TPUSIM_BENCH_PODS (default 100000), TPUSIM_BENCH_NODES (5000),
TPUSIM_BENCH_BASELINE_PODS (200), TPUSIM_BENCH_BATCH (0 = exact scan),
TPUSIM_BENCH_PROBE_TIMEOUT (150s), TPUSIM_BENCH_RUN_TIMEOUT (2400s),
TPUSIM_BENCH_CPU_PODS/_NODES (smaller shape used on the CPU fallback).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# workload
# --------------------------------------------------------------------------

def build_workload(num_pods: int, num_nodes: int):
    from tpusim.api.snapshot import ClusterSnapshot, make_node, make_pod

    rng = np.random.RandomState(12345)
    nodes = []
    for i in range(num_nodes):
        shape = i % 3
        milli_cpu = [4000, 8000, 16000][shape]
        memory = [8, 16, 32][shape] * 1024**3
        taints = None
        if i % 10 == 0:
            taints = [{"key": "dedicated", "value": "batch", "effect": "NoSchedule"}]
        nodes.append(make_node(f"node-{i}", milli_cpu=milli_cpu, memory=memory,
                               pods=110, labels={"zone": f"z{i % 4}"}, taints=taints))

    # Zipf-ish request sizes over discrete buckets
    cpu_buckets = np.array([50, 100, 250, 500, 1000, 2000, 4000])
    mem_buckets = np.array([64, 128, 256, 512, 1024, 2048, 4096]) * 2**20
    weights = 1.0 / np.arange(1, len(cpu_buckets) + 1) ** 1.1
    weights /= weights.sum()
    cpu_idx = rng.choice(len(cpu_buckets), size=num_pods, p=weights)
    mem_idx = rng.choice(len(mem_buckets), size=num_pods, p=weights)
    tolerate = rng.rand(num_pods) < 0.1

    pods = []
    for i in range(num_pods):
        kwargs = {}
        if tolerate[i]:
            kwargs["tolerations"] = [{"key": "dedicated", "operator": "Equal",
                                      "value": "batch", "effect": "NoSchedule"}]
        pods.append(make_pod(f"p-{i}", milli_cpu=int(cpu_buckets[cpu_idx[i]]),
                             memory=int(mem_buckets[mem_idx[i]]), **kwargs))
    return ClusterSnapshot(nodes=nodes), pods


# --------------------------------------------------------------------------
# child: the actual measurement (runs inside a timeout-guarded subprocess)
# --------------------------------------------------------------------------

def run_child(platform: str) -> None:
    num_pods = int(os.environ.get("TPUSIM_BENCH_PODS", 100_000))
    num_nodes = int(os.environ.get("TPUSIM_BENCH_NODES", 5_000))
    if platform == "cpu":
        # smaller default shape on the fallback so the run fits the timeout;
        # explicit env overrides win
        num_pods = int(os.environ.get("TPUSIM_BENCH_CPU_PODS",
                                      os.environ.get("TPUSIM_BENCH_PODS", 20_000)))
        num_nodes = int(os.environ.get("TPUSIM_BENCH_CPU_NODES",
                                       os.environ.get("TPUSIM_BENCH_NODES", 2_000)))
    baseline_pods = int(os.environ.get("TPUSIM_BENCH_BASELINE_PODS", 200))
    batch = int(os.environ.get("TPUSIM_BENCH_BATCH", 0))

    import jax

    if platform == "cpu":
        # The axon TPU plugin force-appends itself to jax_platforms, overriding
        # the JAX_PLATFORMS env var; pin via jax.config instead.
        jax.config.update("jax_platforms", "cpu")

    from tpusim.backends import ReferenceBackend
    from tpusim.jaxe import ensure_x64
    from tpusim.jaxe.kernels import (
        config_for,
        carry_init,
        pod_columns_to_device,
        schedule_scan,
        schedule_wavefront,
        statics_to_device,
    )
    from tpusim.jaxe.state import NUM_FIXED_BITS, compile_cluster

    ensure_x64()
    devices = jax.devices()
    real_platform = devices[0].platform
    log(f"devices: {devices}")
    log(f"workload: {num_pods} pods x {num_nodes} nodes "
        f"({'exact scan' if batch == 0 else f'wavefront K={batch}'})")

    t0 = time.perf_counter()
    snapshot, pods = build_workload(num_pods, num_nodes)
    log(f"workload build: {time.perf_counter() - t0:.1f}s")

    # --- python reference-loop baseline on a subsample ---
    t0 = time.perf_counter()
    ref_placements = ReferenceBackend().schedule(pods[:baseline_pods], snapshot)
    ref_elapsed = time.perf_counter() - t0
    ref_rate = baseline_pods / ref_elapsed
    log(f"reference loop: {baseline_pods} pods in {ref_elapsed:.1f}s "
        f"= {ref_rate:.1f} pods/s "
        f"({sum(p.scheduled for p in ref_placements)} scheduled)")

    # --- jax backend ---
    t0 = time.perf_counter()
    compiled, cols = compile_cluster(snapshot, pods)
    log(f"host compile (intern+tables): {time.perf_counter() - t0:.1f}s")

    config = config_for(
        [compiled], most_requested=False,
        num_reason_bits=NUM_FIXED_BITS + len(compiled.scalar_names))
    carry = carry_init(compiled)
    statics = statics_to_device(compiled)
    xs = pod_columns_to_device(cols)

    import jax.numpy as jnp

    def run():
        """One full scheduling pass; returns (choices ref, checksum int).

        The checksum is a device-side reduction over the decision vector,
        fetched as a host scalar: fetching it provably forces the whole
        computation (choices feeds the sum), unlike block_until_ready on
        the axon runtime, which has been observed returning early.
        """
        if batch > 0:
            _, choices, counts, _ = schedule_wavefront(config, carry, statics, xs, batch)
        else:
            _, choices, counts, _ = schedule_scan(config, carry, statics, xs)
        checksum = int(jnp.sum(jnp.where(choices >= 0, choices, -1)))
        return choices, checksum

    t0 = time.perf_counter()
    choices_dev, checksum = run()
    cold = time.perf_counter() - t0
    log(f"device cold (incl XLA compile): {cold:.1f}s (checksum={checksum})")

    # median of 3 timed runs; each run re-dispatches and fetches the checksum
    warm_times = []
    drift = False
    for _ in range(3):
        t0 = time.perf_counter()
        choices_dev, cs = run()
        warm_times.append(time.perf_counter() - t0)
        if cs != checksum:
            drift = True
            log(f"WARNING: checksum drift {checksum} -> {cs}")
    warm = float(np.median(warm_times))
    rate = num_pods / warm
    choices = np.asarray(choices_dev)
    scheduled = int(np.sum(choices >= 0))
    log(f"device warm (median of {[f'{t:.3f}' for t in warm_times]}): "
        f"{num_pods} pods in {warm:.2f}s = {rate:.0f} pods/s "
        f"({scheduled} scheduled, {num_pods - scheduled} unschedulable)")

    # sanity: jax choices agree with the reference loop on the subsample
    names = compiled.statics.names
    mismatches = sum(
        1 for i in range(baseline_pods)
        if (names[choices[i]] if choices[i] >= 0 else "") != ref_placements[i].node_name)
    log(f"parity check on first {baseline_pods} pods: {mismatches} mismatches")

    mode = "exact scan" if batch == 0 else f"wavefront K={batch}"
    result = {
        "metric": f"scheduled pods/sec ({num_pods // 1000}k Zipf pods, "
                  f"{num_nodes} heterogeneous nodes, {mode}, "
                  f"platform={real_platform}, "
                  f"parity_mismatches={mismatches})",
        "value": round(rate, 1),
        "unit": "pods/s",
        "vs_baseline": round(rate / ref_rate, 2),
    }
    if drift:
        # runtime-integrity failure: the rate may be measured on incomplete
        # execution — surface it in the artifact, not just stderr
        result["error"] = "checksum drift across timed runs; rate unreliable"
    print(json.dumps(result), flush=True)


# --------------------------------------------------------------------------
# parent: probe + orchestrate with timeouts, retries, and CPU fallback
# --------------------------------------------------------------------------

_PROBE_CODE = "import jax; d = jax.devices(); print(d[0].platform, flush=True)"


def probe_default_backend(timeout: float) -> str | None:
    """Try initializing the default jax backend in a subprocess.

    Returns the platform name on success, None on failure/timeout. Runs out
    of process because a hung TPU tunnel blocks jax.devices() indefinitely
    with the GIL held — no in-process timeout can recover from that.
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        log(f"probe: backend init timed out after {timeout:.0f}s")
        return None
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        log("probe: backend init failed: " + " | ".join(tail))
        return None
    platform = proc.stdout.strip().split()[-1] if proc.stdout.strip() else ""
    log(f"probe: default backend platform = {platform!r}")
    return platform or None


def run_bench_subprocess(platform: str, timeout: float):
    """Run the measurement child; returns (parsed_json | None, error | None)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--child", platform]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout,
                              cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired as e:
        for stream in (e.stderr, e.stdout):
            if stream:
                text = stream.decode() if isinstance(stream, bytes) else stream
                for line in text.strip().splitlines()[-10:]:
                    log(f"  [child] {line}")
        return None, f"bench run on {platform!r} timed out after {timeout:.0f}s"
    for line in (proc.stderr or "").strip().splitlines():
        log(f"  [child] {line}")
    if proc.returncode != 0:
        return None, f"bench run on {platform!r} exited rc={proc.returncode}"
    for line in reversed((proc.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    return None, f"bench run on {platform!r} produced no JSON line"


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        run_child(sys.argv[2] if len(sys.argv) > 2 else "default")
        return

    probe_timeout = float(os.environ.get("TPUSIM_BENCH_PROBE_TIMEOUT", 150))
    run_timeout = float(os.environ.get("TPUSIM_BENCH_RUN_TIMEOUT", 2400))
    retries = int(os.environ.get("TPUSIM_BENCH_PROBE_RETRIES", 3))

    errors: list[str] = []

    # 1) probe the default (TPU) backend with bounded retries
    platform = None
    for attempt in range(1, retries + 1):
        log(f"probe attempt {attempt}/{retries} (timeout {probe_timeout:.0f}s)")
        platform = probe_default_backend(probe_timeout)
        if platform:
            break
        if attempt < retries:
            backoff = 10.0 * attempt
            log(f"probe: retrying in {backoff:.0f}s")
            time.sleep(backoff)
    if not platform:
        errors.append(f"default backend unavailable after {retries} probes")
    elif platform == "cpu":
        # a "default" backend that is really the CPU (e.g. plugin init failed
        # with a warning-level fallback) must not run the TPU-sized workload
        errors.append("default backend probed as cpu; using cpu-sized workload")
        platform = None

    # 2) run the measurement on the probed backend, then fall back to CPU
    attempts = []
    if platform:
        attempts.append("default")
    attempts.append("cpu")
    for target in attempts:
        label = platform if target == "default" else "cpu"
        log(f"running benchmark on {label} (timeout {run_timeout:.0f}s)")
        result, err = run_bench_subprocess(target, run_timeout)
        if result is not None:
            if errors:
                result["note"] = "; ".join(errors)
            print(json.dumps(result), flush=True)
            return
        errors.append(err)
        log(f"FAILED: {err}")

    # 3) everything failed: still emit one valid JSON line, rc 0
    print(json.dumps({
        "metric": "scheduled pods/sec (benchmark failed)",
        "value": 0,
        "unit": "pods/s",
        "vs_baseline": 0,
        "error": "; ".join(errors),
    }), flush=True)


if __name__ == "__main__":
    main()

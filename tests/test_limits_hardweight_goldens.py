"""Final upstream golden tables: ResourceLimits
(resource_limits_test.go:100-140) and HardPodAffinitySymmetricWeight
(interpod_affinity_test.go:529-600).
"""

import pytest

from tpusim.api.snapshot import make_node
from tpusim.api.types import Node, Pod
from tpusim.engine import priorities as prios
from tpusim.engine.resources import NodeInfo, new_node_info_map


def limits_pod(*containers):
    return Pod.from_obj({
        "metadata": {"name": "p", "uid": "p"},
        "spec": {"containers": [
            {"name": f"c{i}", "resources": {"limits": dict(lim)}}
            for i, lim in enumerate(containers)]}})


def plain_node(name, milli_cpu, mem):
    alloc = {"pods": "110"}
    if milli_cpu:
        alloc["cpu"] = f"{milli_cpu}m"
    if mem:
        alloc["memory"] = str(mem)
    return Node.from_obj({
        "metadata": {"name": name},
        "status": {"capacity": dict(alloc), "allocatable": dict(alloc),
                   "conditions": [{"type": "Ready", "status": "True"}]}})


CPU_ONLY = limits_pod({"cpu": "1000m", "memory": "0"},
                      {"cpu": "2000m", "memory": "0"})
MEM_ONLY = limits_pod({"cpu": "0", "memory": "2000"},
                      {"cpu": "0", "memory": "3000"})
CPU_AND_MEM = limits_pod({"cpu": "1000m", "memory": "2000"},
                         {"cpu": "2000m", "memory": "3000"})

LIMITS_CASES = [
    ("pod does not specify its resource limits", limits_pod(),
     [("machine1", 4000, 10000), ("machine2", 4000, 0),
      ("machine3", 0, 10000), ("machine4", 0, 0)], [0, 0, 0, 0]),
    ("pod only specifies cpu limits", CPU_ONLY,
     [("machine1", 3000, 10000), ("machine2", 2000, 10000)], [1, 0]),
    ("pod only specifies mem limits", MEM_ONLY,
     [("machine1", 4000, 4000), ("machine2", 5000, 10000)], [0, 1]),
    ("pod specifies both cpu and mem limits", CPU_AND_MEM,
     [("machine1", 4000, 4000), ("machine2", 5000, 10000)], [1, 1]),
    ("node does not advertise its allocatables", CPU_AND_MEM,
     [("machine1", 0, 0)], [0]),
]


@pytest.mark.parametrize("name,pod,node_specs,expected",
                         LIMITS_CASES, ids=[c[0] for c in LIMITS_CASES])
def test_resource_limits_priority_golden(name, pod, node_specs, expected):
    scores = []
    for node_name, cpu, mem in node_specs:
        ni = NodeInfo()
        ni.set_node(plain_node(node_name, cpu, mem))
        scores.append(prios.resource_limits_priority_map(pod, None, ni).score)
    assert scores == expected, f"{name}: {scores} != {expected}"


HARD_AFFINITY = {"podAffinity": {
    "requiredDuringSchedulingIgnoredDuringExecution": [
        {"labelSelector": {"matchExpressions": [
            {"key": "service", "operator": "In", "values": ["S1"]}]},
         "topologyKey": "region"}]}}


def sym_pod(name, labels=None, affinity=None, node=""):
    obj = {"metadata": {"name": name, "uid": name, "namespace": "default",
                        "labels": labels or {}},
           "spec": {"containers": [{"name": "c"}]}, "status": {}}
    if affinity:
        obj["spec"]["affinity"] = affinity
    if node:
        obj["spec"]["nodeName"] = node
        obj["status"]["phase"] = "Running"
    return Pod.from_obj(obj)


@pytest.mark.parametrize("hard_weight,expected", [(1, [10, 10, 0]),
                                                  (0, [0, 0, 0])])
def test_hard_pod_affinity_symmetric_weight_golden(hard_weight, expected):
    pod = sym_pod("p", {"service": "S1"})
    existing = [sym_pod("e1", None, HARD_AFFINITY, node="machine1"),
                sym_pod("e2", None, HARD_AFFINITY, node="machine2")]
    nodes = [make_node("machine1", labels={"region": "China"}),
             make_node("machine2", labels={"region": "India"}),
             make_node("machine3", labels={"az": "az1"})]
    infos = new_node_info_map(nodes, existing)
    prio = prios.InterPodAffinityPriority(
        lambda n: infos.get(n), hard_pod_affinity_weight=hard_weight)
    scores = [hp.score for hp in prio.calculate(pod, infos, nodes)]
    assert scores == expected

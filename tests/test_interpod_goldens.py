"""TestInterPodAffinity golden table (predicates_test.go:2168-2780), run
through BOTH engines on the upstream single-node cluster: machine1 with
labels {region: r1, zone: z11}. Covers required pod affinity (operators,
ANDed expressions, namespaces, the self-match special case), own
anti-affinity, and existing pods' anti-affinity symmetry.
"""

import pytest

from tpusim.api.types import Node, Pod
from tpusim.api.snapshot import ClusterSnapshot
from tpusim.backends import ReferenceBackend
from tpusim.jaxe.backend import JaxBackend

POD_LABEL = {"service": "securityscan"}
POD_LABEL2 = {"security": "S1"}


def expr(key, op, *values):
    e = {"key": key, "operator": op}
    if values:
        e["values"] = list(values)
    return e


def term(exprs, topology_key="", namespaces=None):
    t = {"labelSelector": {"matchExpressions": list(exprs)}}
    if topology_key:
        t["topologyKey"] = topology_key
    if namespaces:
        t["namespaces"] = list(namespaces)
    return t


def ip_pod(name, labels=None, affinity=None, anti=None, node_name="",
           namespace="default"):
    aff = {}
    if affinity:
        aff["podAffinity"] = {
            "requiredDuringSchedulingIgnoredDuringExecution": affinity}
    if anti:
        aff["podAntiAffinity"] = {
            "requiredDuringSchedulingIgnoredDuringExecution": anti}
    obj = {
        "metadata": {"name": name, "uid": name, "namespace": namespace,
                     "labels": labels or {}},
        "spec": {"containers": [{"name": "c", "resources": {
            "requests": {"cpu": "10m"}}}]},
        "status": {},
    }
    if aff:
        obj["spec"]["affinity"] = aff
    if node_name:
        obj["spec"]["nodeName"] = node_name
        obj["status"]["phase"] = "Running"
    return Pod.from_obj(obj)


def machine1():
    return Node.from_obj({
        "metadata": {"name": "machine1",
                     "labels": {"region": "r1", "zone": "z11"}},
        "status": {
            "capacity": {"cpu": "4", "memory": "8Gi", "pods": "110"},
            "allocatable": {"cpu": "4", "memory": "8Gi", "pods": "110"},
            "conditions": [{"type": "Ready", "status": "True"}]}})


IN_SEC = [expr("service", "In", "securityscan", "value2")]
CASES = [
    ("no affinity rules, no existing pods",
     ip_pod("p"), [], True),
    ("required affinity In matches existing pod",
     ip_pod("p", POD_LABEL2, affinity=[term(IN_SEC, "region")]),
     [ip_pod("e", POD_LABEL, node_name="machine1")], True),
    ("required affinity NotIn matches existing pod",
     ip_pod("p", POD_LABEL2, affinity=[term(
         [expr("service", "NotIn", "securityscan3", "value3")], "region")]),
     [ip_pod("e", POD_LABEL, node_name="machine1")], True),
    ("different namespace does not satisfy",
     ip_pod("p", POD_LABEL2, affinity=[term(IN_SEC,
                                            namespaces=["DiffNameSpace"])]),
     [ip_pod("e", POD_LABEL, node_name="machine1", namespace="ns")], False),
    ("unmatching labelSelector",
     ip_pod("p", POD_LABEL, affinity=[term(
         [expr("service", "In", "antivirusscan", "value2")], "region")]),
     [ip_pod("e", POD_LABEL, node_name="machine1")], False),
    ("multiple required terms with different operators all match",
     ip_pod("p", POD_LABEL2, affinity=[
         term([expr("service", "Exists"),
               expr("wrongkey", "DoesNotExist")], "region"),
         term([expr("service", "In", "securityscan"),
               expr("service", "NotIn", "WrongValue")], "region")]),
     [ip_pod("e", POD_LABEL, node_name="machine1")], True),
    ("ANDed matchExpressions with one failing item",
     ip_pod("p", POD_LABEL2, affinity=[
         term([expr("service", "Exists"),
               expr("wrongkey", "DoesNotExist")], "region"),
         term([expr("service", "In", "securityscan2"),
               expr("service", "NotIn", "WrongValue")], "region")]),
     [ip_pod("e", POD_LABEL, node_name="machine1")], False),
    ("affinity and non-matching anti-affinity",
     ip_pod("p", POD_LABEL2, affinity=[term(IN_SEC, "region")],
            anti=[term([expr("service", "In", "antivirusscan", "value2")],
                       "node")]),
     [ip_pod("e", POD_LABEL, node_name="machine1")], True),
    ("anti-affinity symmetry that does not target the new pod",
     ip_pod("p", POD_LABEL2, affinity=[term(IN_SEC, "region")],
            anti=[term([expr("service", "In", "antivirusscan", "value2")],
                       "node")]),
     [ip_pod("e", POD_LABEL, node_name="machine1",
             anti=[term([expr("service", "In", "antivirusscan", "value2")],
                        "node")])], True),
    ("own anti-affinity matches the existing pod",
     ip_pod("p", POD_LABEL2, affinity=[term(IN_SEC, "region")],
            anti=[term(IN_SEC, "zone")]),
     [ip_pod("e", POD_LABEL, node_name="machine1")], False),
    ("existing pod's anti-affinity targets the new pod (symmetry)",
     ip_pod("p", POD_LABEL, affinity=[term(IN_SEC, "region")],
            anti=[term([expr("service", "In", "antivirusscan", "value2")],
                       "node")]),
     [ip_pod("e", POD_LABEL, node_name="machine1",
             anti=[term(IN_SEC, "zone")])], False),
    ("NotIn affinity vs own labels (no self-match rescue)",
     ip_pod("p", POD_LABEL, affinity=[term(
         [expr("service", "NotIn", "securityscan", "value2")], "region")]),
     [ip_pod("e", POD_LABEL, node_name="machine2")], False),
    ("existing anti-affinity respected when new pod has no constraints",
     ip_pod("p", POD_LABEL),
     [ip_pod("e", POD_LABEL, node_name="machine1",
             anti=[term(IN_SEC, "zone")])], False),
    ("existing anti-affinity NotIn does not target the new pod",
     ip_pod("p", POD_LABEL),
     [ip_pod("e", POD_LABEL, node_name="machine1",
             anti=[term([expr("service", "NotIn", "securityscan", "value2")],
                        "zone")])], True),
]


@pytest.mark.parametrize("name,pod,existing,fits",
                         CASES, ids=[c[0] for c in CASES])
def test_inter_pod_affinity_golden(name, pod, existing, fits):
    snapshot = ClusterSnapshot(nodes=[machine1()], pods=existing)
    for backend in (ReferenceBackend(), JaxBackend()):
        [placement] = backend.schedule([pod], snapshot)
        scheduled = placement.pod.spec.node_name == "machine1"
        assert scheduled == fits, (
            f"{name}: {type(backend).__name__} scheduled={scheduled}, "
            f"upstream expects fits={fits} ({placement.message})")
        if not fits:
            assert "pod affinity" in placement.message or \
                "anti-affinity" in placement.message, placement.message

"""Golden tables ported from the reference's scheduling-queue suite.

Reference: vendor/k8s.io/kubernetes/pkg/scheduler/core/scheduling_queue_test.go
(TestPriorityQueue_Add:93, _AddIfNotPresent:118,
_AddUnschedulableIfNotPresent:144, _Pop:170 (sequential — our Pop is
non-blocking by design, engine/queue.py docstring), _Update:187, _Delete:223,
_MoveAllToActiveQueue:243, _AssignedPodAdded:257, _WaitingPodsForNode:310,
TestUnschedulablePodsMap:327). Fixture pods mirror the file-scope vars at
:29-91 (hpp/ns1, mpp/ns2 nominated node1, up/ns1 unschedulable nominated
node1).
"""

from tpusim.api.snapshot import make_pod
from tpusim.api.types import PodCondition
from tpusim.engine.queue import PriorityQueue

LOW, MEDIUM, HIGH = 0, 500, 1000


def build(name, namespace, priority, nominated="", unschedulable=False,
          affinity=None, labels=None, node_name=""):
    p = make_pod(name, namespace=namespace, labels=labels,
                 affinity=affinity, node_name=node_name)
    p.spec.priority = priority
    if nominated:
        p.status.nominated_node_name = nominated
    if unschedulable:
        p.status.conditions.append(PodCondition(
            type="PodScheduled", status="False", reason="Unschedulable"))
    return p


def high_priority_pod():
    return build("hpp", "ns1", HIGH)


def high_pri_nominated_pod():
    return build("hpp", "ns1", HIGH, nominated="node1")


def med_priority_pod():
    return build("mpp", "ns2", MEDIUM, nominated="node1")


def unschedulable_pod():
    return build("up", "ns1", LOW, nominated="node1", unschedulable=True)


def nominated_names(q, node):
    return [p.metadata.name for p in q.waiting_pods_for_node(node)]


def test_priority_queue_add():
    """TestPriorityQueue_Add:93-116."""
    q = PriorityQueue()
    med, unsched, high = (med_priority_pod(), unschedulable_pod(),
                          high_priority_pod())
    q.add(med)
    q.add(unsched)
    q.add(high)
    assert nominated_names(q, "node1") == ["mpp", "up"]
    assert q.pop().metadata.name == "hpp"
    assert q.pop().metadata.name == "mpp"
    assert q.pop().metadata.name == "up"
    assert not q._nominated  # Pop removes nominated entries


def test_priority_queue_add_if_not_present():
    """TestPriorityQueue_AddIfNotPresent:118-142 (reaches into
    unschedulableQ.addOrUpdate exactly like the upstream test)."""
    q = PriorityQueue()
    hpn = high_pri_nominated_pod()
    q._unschedulable[hpn.key()] = hpn
    q.add_if_not_present(hpn)  # must not add anything
    med, unsched = med_priority_pod(), unschedulable_pod()
    q.add_if_not_present(med)
    q.add_if_not_present(unsched)
    assert nominated_names(q, "node1") == ["mpp", "up"]
    assert q.pop().metadata.name == "mpp"
    assert q.pop().metadata.name == "up"
    assert not q._nominated
    assert q._unschedulable[hpn.key()] is hpn


def test_priority_queue_add_unschedulable_if_not_present():
    """TestPriorityQueue_AddUnschedulableIfNotPresent:144-168: a pod without
    the Unschedulable condition goes to activeQ, one with it parks."""
    q = PriorityQueue()
    hpn = high_pri_nominated_pod()
    q.add(hpn)
    q.add_unschedulable_if_not_present(hpn)  # must not add anything
    med, unsched = med_priority_pod(), unschedulable_pod()
    q.add_unschedulable_if_not_present(med)    # no condition -> activeQ
    q.add_unschedulable_if_not_present(unsched)  # parks
    assert nominated_names(q, "node1") == ["hpp", "mpp", "up"]
    assert q.pop().metadata.name == "hpp"
    assert q.pop().metadata.name == "mpp"
    assert len(q._nominated) == 1
    assert q._unschedulable[unsched.key()] is unsched


def test_priority_queue_pop():
    """TestPriorityQueue_Pop:170-185 (sequential: non-blocking Pop)."""
    q = PriorityQueue()
    q.add(med_priority_pod())
    assert q.pop().metadata.name == "mpp"
    assert not q._nominated


def test_priority_queue_update():
    """TestPriorityQueue_Update:187-221."""
    q = PriorityQueue()
    high = high_priority_pod()
    q.update(None, high)
    assert high.key() in q._active_items
    assert not q._nominated
    # update the active pod, adding a nominated node name
    hpn = high_pri_nominated_pod()
    q.update(high, hpn)
    assert len(q._active_items) == 1
    assert len(q._nominated) == 1
    # updating an unschedulable pod in NO queue adds it to activeQ
    unsched = unschedulable_pod()
    q.update(unsched, unsched)
    assert unsched.key() in q._active_items
    # updating a pod already in activeQ keeps it there
    q.update(unsched, unsched)
    assert len(q._unschedulable) == 0
    assert unsched.key() in q._active_items
    assert q.pop().metadata.name == "hpp"


def test_priority_queue_delete():
    """TestPriorityQueue_Delete:223-241."""
    q = PriorityQueue()
    high, hpn = high_priority_pod(), high_pri_nominated_pod()
    q.update(high, hpn)
    unsched = unschedulable_pod()
    q.add(unsched)
    q.delete(hpn)
    assert unsched.key() in q._active_items
    assert hpn.key() not in q._active_items
    assert len(q._nominated) == 1  # only unschedulablePod's entry remains
    q.delete(unsched)
    assert not q._nominated


def test_priority_queue_move_all_to_active_queue():
    """TestPriorityQueue_MoveAllToActiveQueue:243-252."""
    q = PriorityQueue()
    q.add(med_priority_pod())
    unsched, high = unschedulable_pod(), high_priority_pod()
    q._unschedulable[unsched.key()] = unsched
    q._unschedulable[high.key()] = high
    q.move_all_to_active_queue()
    assert len(q._active_items) == 3


def test_priority_queue_assigned_pod_added():
    """TestPriorityQueue_AssignedPodAdded:257-308: a bound pod with labels
    matching a parked pod's required pod-affinity term moves that pod (and
    only that pod) to activeQ."""
    affinity_pod = build(
        "afp", "ns1", MEDIUM, nominated="node1", unschedulable=True,
        affinity={"podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"labelSelector": {"matchExpressions": [
                    {"key": "service", "operator": "In",
                     "values": ["securityscan", "value2"]}]},
                 "topologyKey": "region"}]}})
    label_pod = build("lbp", "ns1", LOW,
                      labels={"service": "securityscan"},
                      node_name="machine1")

    q = PriorityQueue()
    q.add(med_priority_pod())
    unsched = unschedulable_pod()
    q._unschedulable[unsched.key()] = unsched
    q._unschedulable[affinity_pod.key()] = affinity_pod
    q.assigned_pod_added(label_pod)
    assert affinity_pod.key() not in q._unschedulable
    assert affinity_pod.key() in q._active_items
    assert unsched.key() in q._unschedulable


def test_priority_queue_waiting_pods_for_node():
    """TestPriorityQueue_WaitingPodsForNode:310-325."""
    q = PriorityQueue()
    q.add(med_priority_pod())
    q.add(unschedulable_pod())
    q.add(high_priority_pod())
    assert q.pop().metadata.name == "hpp"
    assert nominated_names(q, "node1") == ["mpp", "up"]
    assert q.waiting_pods_for_node("node2") == []


def test_unschedulable_pods_map():
    """TestUnschedulablePodsMap:327-469: the parking map add/update/delete/
    clear table, driven through the queue's parking dict (keyed by the pod's
    full name — ours uses ns/name, identical uniqueness)."""
    def pod(name, ns, annotations=None, nominated=""):
        p = build(name, ns, LOW, nominated=nominated, unschedulable=True)
        if annotations:
            p.metadata.annotations = dict(annotations)
        return p

    pods = [pod("p0", "ns1", {"annot1": "val1"}, nominated="node1"),
            pod("p1", "ns1", {"annot": "val"}),
            pod("p2", "ns2", {"annot2": "val2", "annot3": "val3"},
                nominated="node3"),
            pod("p3", "ns4", nominated="node1")]
    updated = {0: pod("p0", "ns1", {"annot1": "patched"}, nominated="node1"),
               1: pod("p1", "ns1", {"annot": "patched"}),
               3: pod("p3", "ns4", nominated="node1")}

    cases = [
        # (add indices, update indices, delete indices, expected remaining)
        ([0, 1, 2, 3], [0], [0, 1], {"p2", "p3"}),
        ([0, 3], [3], [0, 3], set()),
        ([1, 2], [1], [2, 3], {"p1"}),
    ]
    for add_idx, upd_idx, del_idx, expect in cases:
        q = PriorityQueue()
        for i in add_idx:
            q._unschedulable[pods[i].key()] = pods[i]
        assert {p.metadata.name for p in q._unschedulable.values()} \
            == {pods[i].metadata.name for i in add_idx}
        for i in upd_idx:
            q._unschedulable[updated[i].key()] = updated[i]
            assert q._unschedulable[updated[i].key()] is updated[i]
        for i in del_idx:
            q.delete(pods[i])
        assert {p.metadata.name for p in q._unschedulable.values()} == expect
        q._unschedulable.clear()
        assert not q._unschedulable


# ---------------------------------------------------------------------------
# PodBackoff golden table
# Reference: vendor/.../pkg/scheduler/util/backoff_utils_test.go TestBackoff:34
# ---------------------------------------------------------------------------


def test_pod_backoff_golden():
    from tpusim.engine.util import PodBackoff

    class FakeClock:
        t = 0.0

        def __call__(self):
            return self.t

    clock = FakeClock()
    backoff = PodBackoff(default_duration=1.0, max_duration=60.0, clock=clock)
    steps = [
        ("default/foo", 1.0, 0.0),
        ("default/foo", 2.0, 0.0),
        ("default/foo", 4.0, 0.0),
        ("default/bar", 1.0, 120.0),
        # 'foo' has been gc'd here (idle > maxDuration)
        ("default/foo", 1.0, 0.0),
    ]
    for pod_id, expected, advance in steps:
        assert backoff.get_backoff_time(pod_id) == expected, pod_id
        clock.t += advance
        backoff.gc()
    backoff.get_entry("default/foo").backoff = 60.0
    assert backoff.get_backoff_time("default/foo") == 60.0
    # namespace split: same name, different namespace
    assert backoff.get_backoff_time("other/foo") == 1.0


def test_pod_backoff_try_backoff_and_wait():
    """TryBackoffAndWait analog (backoff_utils.go:63-70, non-sleeping): first
    call passes (entry created), immediate retry is rejected until the backoff
    window elapses."""
    from tpusim.engine.util import PodBackoff

    class FakeClock:
        t = 0.0

        def __call__(self):
            return self.t

    clock = FakeClock()
    backoff = PodBackoff(default_duration=1.0, max_duration=60.0, clock=clock)
    assert backoff.try_backoff_and_wait("default/p")
    backoff.get_backoff_time("default/p")  # record a failure: backoff 1 -> 2
    assert not backoff.try_backoff_and_wait("default/p")  # still inside window
    clock.t += 2.0
    assert backoff.try_backoff_and_wait("default/p")


def test_simulator_wires_assigned_pod_events_to_queue():
    """factory.go:607/630 parity: a bound pod's store event must trigger the
    queue's affinity-move (AssignedPodAdded/Updated), pulling a parked pod
    with a matching required pod-affinity term back to activeQ and raising
    receivedMoveRequest."""
    from tpusim.api.snapshot import make_node
    from tpusim.simulator import ClusterCapacity, SchedulerServerConfig

    affinity_pod = make_pod(
        "afp", milli_cpu=100,
        affinity={"podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"labelSelector": {"matchLabels": {"service": "securityscan"}},
                 "topologyKey": "kubernetes.io/hostname"}]}})
    affinity_pod.spec.priority = MEDIUM
    label_pod = make_pod("lbp", milli_cpu=100,
                         labels={"service": "securityscan"})
    label_pod.spec.priority = LOW

    cfg = SchedulerServerConfig(enable_pod_priority=True)
    # LIFO feed: the LAST entry pops first — affinity pod schedules first
    # (parks: no matching pod exists yet), then the label pod binds
    cc = ClusterCapacity(cfg, [label_pod, affinity_pod], [],
                         [make_node("n0", milli_cpu=2000)])
    cc.run()
    q = cc.scheduling_queue
    assert affinity_pod.key() not in q._unschedulable, \
        "bound-pod event did not move the parked affinity pod to activeQ"
    assert affinity_pod.key() in q._active_items
    assert q.received_move_request


def test_parking_survives_earlier_binds():
    """Regression (review finding): assigned-pod events raise
    receivedMoveRequest on every bind, and the simulator must mirror Pop()'s
    per-cycle reset — otherwise after the first bind no pod ever parks."""
    from tpusim.api.snapshot import make_node
    from tpusim.simulator import ClusterCapacity, SchedulerServerConfig

    small = make_pod("small", milli_cpu=100)
    big = make_pod("big", milli_cpu=100_000)  # can never fit
    cfg = SchedulerServerConfig(enable_pod_priority=True)
    # LIFO: small (last) pops first and binds; big then fails — and must PARK
    cc = ClusterCapacity(cfg, [big, small], [], [make_node("n0", milli_cpu=2000)])
    cc.run()
    q = cc.scheduling_queue
    assert big.key() in q._unschedulable, \
        "a stale move-request flag kept the failed pod out of the parking lot"
    assert big.key() not in q._active_items

"""Chaos engine unit coverage: the fault-plan schema, the dispatch circuit
breaker's full state machine, backoff under an injected clock, the bounded
watch buffer's "410 Gone" overflow, reflector reconvergence, and
equivalence-cache invalidation when churn deletes a node between attempts.

The end-to-end seeded campaigns live in test_chaos_fuzz.py; this module
pins each layer's mechanism in isolation.
"""

import json

import pytest

from tpusim.api.snapshot import make_node, make_pod, synthetic_cluster
from tpusim.api.types import Pod, ResourceType
from tpusim.chaos import (
    BreakerState,
    ChaosClock,
    ChaosEngine,
    ChurnEvent,
    CircuitBreaker,
    DeviceFaultPlan,
    DeviceInjector,
    FabricFaultPlan,
    FabricInjector,
    FaultPlan,
    InjectedDeviceError,
    load_plan,
    random_plan,
)
from tpusim.chaos.plan import PlanError
from tpusim.engine.util import PodBackoff
from tpusim.framework.events import WatchBuffer, WatchExpiredError
from tpusim.framework.metrics import register as register_metrics
from tpusim.framework.reflector import Reflector
from tpusim.framework.restclient import FakeRESTClient
from tpusim.framework.store import ResourceStore
from tpusim.simulator import (
    ClusterCapacity,
    SchedulerServerConfig,
    run_simulation,
)


def _pod(i, cpu=500, ns="default"):
    return make_pod(f"p{i}", milli_cpu=cpu, memory=1024**3, namespace=ns)


# ---------------------------------------------------------------------------
# fault-plan schema
# ---------------------------------------------------------------------------


def test_plan_json_round_trip(tmp_path):
    plan = FaultPlan(
        seed=42, max_retries=2,
        churn=[ChurnEvent(at=2, action="node_delete", target="node-1"),
               ChurnEvent(at=4, action="node_flap", target="node-0",
                          restore_after=2),
               ChurnEvent(at=5, action="pod_evict", target="default/web-1")],
        fabric=FabricFaultPlan(drop=[4], dup=[7], disconnect=[9]),
        device=DeviceFaultPlan(faults={0: "exception", 3: "corrupt_silent"},
                               failure_threshold=2, cooldown=1))
    path = tmp_path / "plan.json"
    path.write_text(plan.to_json())
    loaded = load_plan(str(path))
    assert loaded == plan
    # byte-stable: serialize(load(serialize(p))) == serialize(p)
    assert loaded.to_json() == plan.to_json()


def test_plan_empty_sections_omitted():
    obj = FaultPlan(seed=1).to_obj()
    assert set(obj) == {"seed", "max_retries"}


@pytest.mark.parametrize("mutate,match", [
    (lambda o: o.update(bogus=1), "unknown plan key"),
    (lambda o: o["churn"].append({"at": 0, "action": "node_melt",
                                  "target": "n"}), "unknown churn action"),
    (lambda o: o["churn"].append({"at": -1, "action": "node_delete",
                                  "target": "n"}), "negative boundary"),
    (lambda o: o["churn"].append({"at": 0, "action": "node_flap",
                                  "target": "n"}), "restore_after"),
    (lambda o: o.update(fabric={"drop": [1], "dup": [1]}), "both"),
    (lambda o: o.update(device={"faults": {"0": "segfault"}}),
     "unknown device fault"),
    (lambda o: o.update(device={"faults": {}, "failure_threshold": 0}),
     "failure_threshold"),
    (lambda o: o.update(device={"faults": {}, "verify": "never"}), "verify"),
])
def test_plan_validation_rejects(mutate, match):
    obj = FaultPlan(seed=0, churn=[]).to_obj()
    obj["churn"] = []
    mutate(obj)
    with pytest.raises(PlanError, match=match):
        FaultPlan.from_obj(obj)


def test_random_plan_deterministic_and_valid():
    nodes = [f"node-{i}" for i in range(6)]
    pods = [f"default/p{i}" for i in range(8)]
    a = random_plan(123, nodes, pods, attempts=8, device_dispatches=4)
    b = random_plan(123, nodes, pods, attempts=8, device_dispatches=4)
    assert a == b and a.to_json() == b.to_json()
    # keep_nodes: the first node is never churned
    assert all(ev.target != "node-0" for ev in a.churn
               if ev.action != "pod_evict")
    assert random_plan(124, nodes, pods, attempts=8) != a


# ---------------------------------------------------------------------------
# circuit breaker state machine
# ---------------------------------------------------------------------------


def test_breaker_full_cycle():
    brk = CircuitBreaker("device", failure_threshold=2, cooldown=2)
    assert brk.state is BreakerState.CLOSED and brk.allow()
    brk.record_failure("boom 1")
    assert brk.state is BreakerState.CLOSED  # below threshold
    brk.record_failure("boom 2")
    assert brk.state is BreakerState.OPEN
    # cooldown counted in DENIED dispatches, not wall time
    assert not brk.allow()
    assert not brk.allow()
    assert brk.state is BreakerState.HALF_OPEN
    assert brk.allow() and brk.probing
    brk.record_success()
    assert brk.state is BreakerState.CLOSED and not brk.probing
    assert [t for t, _ in brk.transitions] == ["open", "half_open", "close"]


def test_breaker_reopen_on_failed_probe():
    brk = CircuitBreaker("device", failure_threshold=1, cooldown=1)
    brk.record_failure("boom")
    assert not brk.allow()                      # denial 1 -> half-open
    assert brk.state is BreakerState.HALF_OPEN
    brk.record_failure("probe died")
    assert brk.state is BreakerState.OPEN
    assert [t for t, _ in brk.transitions] == ["open", "half_open", "reopen"]


def test_breaker_success_resets_failure_streak():
    brk = CircuitBreaker("device", failure_threshold=2, cooldown=1)
    brk.record_failure("a")
    brk.record_success()
    brk.record_failure("b")
    assert brk.state is BreakerState.CLOSED  # streak broke: 1, not 2


def test_breaker_transitions_reach_metrics():
    reg = register_metrics()
    before = dict(reg.breaker_transitions.values)
    brk = CircuitBreaker("device", failure_threshold=1, cooldown=1)
    brk.record_failure("boom")
    brk.allow()
    brk.record_success()
    for transition in ("open", "half_open", "close"):
        assert reg.breaker_transitions.values.get(transition, 0) \
            == before.get(transition, 0) + 1
    assert reg.breaker_state.value == 0.0  # closed again


# ---------------------------------------------------------------------------
# injectors
# ---------------------------------------------------------------------------


def test_device_injector_scripts_by_dispatch_index():
    inj = DeviceInjector({0: "exception", 2: "corrupt_silent"})
    with pytest.raises(InjectedDeviceError):
        inj.begin_dispatch()
    assert inj.begin_dispatch() is None
    assert inj.begin_dispatch() == "corrupt_silent"
    assert inj.injected == [(0, "exception"), (2, "corrupt_silent")]


def test_fabric_injector_classifies_by_global_index():
    inj = FabricInjector(drop={1}, dup={2}, disconnect={3})
    got = [inj.on_event("pods", "ADDED") for _ in range(5)]
    assert got == ["deliver", "drop", "dup", "disconnect", "deliver"]


# ---------------------------------------------------------------------------
# PodBackoff under an injected clock (satellite: injectable backoff clock)
# ---------------------------------------------------------------------------


def test_pod_backoff_injected_clock():
    clock = ChaosClock(start=100.0)
    backoff = PodBackoff(clock=clock)
    key = "default/p0"
    assert backoff.try_backoff_and_wait(key)      # first touch creates entry
    backoff.get_backoff_time(key)                 # failure: backoff doubles
    assert not backoff.try_backoff_and_wait(key)  # clock has not moved
    clock.advance(1.9)
    assert not backoff.try_backoff_and_wait(key)  # 1.9s < 2s backoff
    clock.advance(0.1)
    assert backoff.try_backoff_and_wait(key)      # exactly at expiry
    # deterministic doubling under the same clock: 2s -> 4s
    backoff.get_backoff_time(key)
    clock.advance(3.9)
    assert not backoff.try_backoff_and_wait(key)
    clock.advance(0.1)
    assert backoff.try_backoff_and_wait(key)


def test_pod_backoff_default_clock_unchanged():
    # the injectable-clock seam must not alter wall-clock behavior
    backoff = PodBackoff()
    assert backoff.try_backoff_and_wait("default/p0")
    backoff.get_backoff_time("default/p0")
    assert not backoff.try_backoff_and_wait("default/p0")


# ---------------------------------------------------------------------------
# bounded watch buffer: overflow == "410 Gone" (satellite: WatchBuffer)
# ---------------------------------------------------------------------------


def test_watch_buffer_overflow_raises_410():
    reg = register_metrics()
    before = reg.watch_overflow.values.get("pods", 0)
    buf = WatchBuffer(maxsize=3, resource="pods")
    for i in range(5):  # 2 past the window
        buf.emit("ADDED", make_pod(f"p{i}"))
    assert buf.closed
    with pytest.raises(WatchExpiredError) as exc:
        buf.read(timeout=0)
    assert exc.value.code == 410
    # the torn window is discarded — and every later read fails too
    with pytest.raises(WatchExpiredError):
        buf.read(timeout=0)
    assert reg.watch_overflow.values.get("pods", 0) == before + 1


def test_watch_buffer_disconnect_keeps_queued_frames():
    buf = WatchBuffer(maxsize=10, resource="pods")
    buf.emit("ADDED", make_pod("p0"))
    buf.close_with_error(WatchExpiredError("chaos: disconnect"))
    ev = buf.read(timeout=0)
    assert ev is not None and ev.object.name == "p0"
    with pytest.raises(WatchExpiredError):
        buf.read(timeout=0)


def test_watch_buffer_unbounded_never_overflows():
    buf = WatchBuffer(maxsize=0, resource="pods")
    for i in range(100):
        buf.emit("ADDED", make_pod(f"p{i}"))
    assert not buf.closed


# ---------------------------------------------------------------------------
# reflector: relist-on-410 reconvergence
# ---------------------------------------------------------------------------


def _fabric_fixture():
    store = ResourceStore()
    client = FakeRESTClient(store)
    return store, client


def test_reflector_reconverges_after_drop_and_disconnect():
    store, client = _fabric_fixture()
    events = []
    refl = Reflector(client, ResourceType.PODS,
                     handler=lambda t, o: events.append((t, o.key())))
    store.add(ResourceType.PODS, _pod(0))
    assert refl.sync() == 1
    client.fault_injector = FabricInjector(drop={1}, dup={2}, disconnect={4})
    store.add(ResourceType.PODS, _pod(1))   # 0: delivered
    store.add(ResourceType.PODS, _pod(2))   # 1: dropped
    store.add(ResourceType.PODS, _pod(3))   # 2: duplicated
    refl.sync()
    # the dropped frame silently diverged the bare mirror...
    assert "default/p2" not in refl.known
    store.delete(ResourceType.PODS, _pod(3))  # 3: delivered
    store.add(ResourceType.PODS, _pod(4))     # 4: disconnect (frame lost)
    refl.sync()
    # ...and the disconnect-triggered relist healed everything
    assert refl.relists == 1
    assert sorted(refl.known) == ["default/p0", "default/p1", "default/p2",
                                  "default/p4"]
    assert set(sorted(refl.known)) == {p.key() for p
                                       in store.list(ResourceType.PODS)}


def test_reflector_reconverges_after_overflow():
    store, client = _fabric_fixture()
    refl = Reflector(client, ResourceType.PODS)
    refl.sync()
    refl._buf.maxsize = 3  # shrink the live window to force the overflow
    for i in range(8):
        store.add(ResourceType.PODS, _pod(i))
    assert refl.sync() >= 8 - 3  # relist resynced whatever the tear lost
    assert refl.relists == 1
    assert len(refl.known) == 8


# ---------------------------------------------------------------------------
# churn through the store fabric (satellite: ecache invalidation)
# ---------------------------------------------------------------------------


def _chaos_cc(plan, num_nodes=3, num_pods=4, **config_kw):
    snap = synthetic_cluster(num_nodes)
    pods = [_pod(i) for i in range(num_pods)]
    engine = ChaosEngine(plan)
    cc = ClusterCapacity(SchedulerServerConfig(**config_kw), pods, [],
                         snap.nodes, chaos=engine)
    return cc, engine


def test_node_delete_invalidates_ecache_between_attempts():
    plan = FaultPlan(seed=0, churn=[
        ChurnEvent(at=1, action="node_delete", target="node-1")])
    cc, engine = _chaos_cc(plan, enable_equivalence_cache=True)
    ecache = cc.scheduler.equivalence_cache
    assert ecache is not None
    # attempt 1 cached predicate verdicts for node-1...
    ecache.update("node-1", "GeneralPredicates", 123, True, [])
    assert ecache.lookup("node-1", "GeneralPredicates", 123) == (True, [])
    engine.fire_boundary()   # boundary 0: nothing due
    engine.fire_boundary()   # boundary 1: node_delete -> DELETED via store
    # ...and the deletion rode the event fabric into whole-node invalidation
    assert ecache.lookup("node-1", "GeneralPredicates", 123) is None
    assert "node-1" not in cc.cache.nodes
    assert all(n.name != "node-1" for n in cc.nodes)
    assert engine.fired == [(1, "node_delete", "node-1")]


def test_node_delete_clears_nominations():
    plan = FaultPlan(seed=0, churn=[
        ChurnEvent(at=0, action="node_delete", target="node-1")])
    cc, engine = _chaos_cc(plan, enable_pod_priority=True)
    nominee = _pod(99)
    nominee.status.nominated_node_name = "node-1"
    cc.scheduling_queue.add_unschedulable_if_not_present(nominee)
    assert cc.scheduling_queue.waiting_pods_for_node("node-1")
    engine.fire_boundary()
    assert not cc.scheduling_queue.waiting_pods_for_node("node-1")
    assert nominee.status.nominated_node_name == ""


def test_pod_evict_requeues_fed_pod():
    plan = FaultPlan(seed=0, max_retries=2, churn=[
        ChurnEvent(at=3, action="pod_evict", target="default/p0")])
    snap = synthetic_cluster(2)
    status = run_simulation([_pod(i) for i in range(3)], snap,
                            backend="reference", chaos_plan=plan)
    assert status.chaos_violations == []
    # the evicted pod was re-fed and landed again
    assert status.chaos_summary["evicted"] == ["default/p0"]
    assert "default/p0" in {p.key() for p in status.successful_pods}


def test_node_flap_restores_and_reschedules():
    # one big pod only node-1 can hold after node-0 is cordoned; flap
    # node-1 away and back: the pod must park, then land on the restore
    plan = FaultPlan(seed=0, max_retries=3, churn=[
        ChurnEvent(at=0, action="node_flap", target="node-1",
                   restore_after=2)])
    nodes = [make_node("node-0", milli_cpu=1000), make_node("node-1")]
    pod = make_pod("big", milli_cpu=2000, memory=1024**3)
    from tpusim.api.snapshot import ClusterSnapshot

    status = run_simulation([pod], ClusterSnapshot(nodes=nodes),
                            backend="reference", chaos_plan=plan)
    assert status.chaos_violations == []
    assert [p.spec.node_name for p in status.successful_pods] == ["node-1"]
    summary = status.chaos_summary
    assert summary["churn_fired"] == 1
    assert summary["retries"].get("default/big", 0) >= 1

"""Differential tests: preemption on the jax backend (host-device hybrid,
tpusim/jaxe/preempt.py) vs the reference ClusterCapacity run.

Reference semantics under test: scheduler.go:449-455 (preempt on FitError) +
core/generic_scheduler.go:205-1000 (Preempt/selectNodesForPreemption/
selectVictimsOnNode/pickOneNodeForPreemption)."""

import random

from tpusim.api.snapshot import ClusterSnapshot, make_node, make_pod
from tpusim.simulator import run_simulation


def prio_pod(name, priority, milli_cpu=500, node_name="", labels=None,
             memory=0):
    p = make_pod(name, milli_cpu=milli_cpu, node_name=node_name, labels=labels,
                 memory=memory)
    p.spec.priority = priority
    if node_name:
        p.status.phase = "Running"
    return p


def status_sig(status):
    return {
        "success": [(p.name, p.spec.node_name) for p in status.successful_pods],
        "failed": [(p.name, p.status.conditions[-1].message if p.status.conditions else "")
                   for p in status.failed_pods],
        "preempted": sorted(p.name for p in status.preempted_pods),
        "stop": status.stop_reason,
    }


def assert_preempt_parity(pods, snapshot, provider="DefaultProvider"):
    ref = run_simulation(list(pods), snapshot, provider=provider,
                         backend="reference", enable_pod_priority=True)
    jax_status = run_simulation(list(pods), snapshot, provider=provider,
                                backend="jax", enable_pod_priority=True)
    assert status_sig(jax_status) == status_sig(ref)
    return jax_status


def test_jax_preemption_evicts_lower_priority_victim():
    node = make_node("n1", milli_cpu=1000, memory=16 * 1024**3)
    victim = prio_pod("victim", 1, milli_cpu=800, node_name="n1")
    high = prio_pod("high", 10, milli_cpu=800)
    snap = ClusterSnapshot(nodes=[node], pods=[victim])
    status = assert_preempt_parity([high], snap)
    assert [p.name for p in status.preempted_pods] == ["victim"]
    assert [p.name for p in status.successful_pods] == ["high"]


def test_jax_no_preemption_among_equal_priorities():
    node = make_node("n1", milli_cpu=1000, memory=16 * 1024**3)
    peer = prio_pod("peer", 10, milli_cpu=800, node_name="n1")
    pod = prio_pod("pod", 10, milli_cpu=800)
    snap = ClusterSnapshot(nodes=[node], pods=[peer])
    status = assert_preempt_parity([pod], snap)
    assert not status.preempted_pods
    assert [p.name for p in status.failed_pods] == ["pod"]


def test_jax_preemption_mid_batch_redispatch():
    """A preemption in the middle of the feed forces a re-dispatch; decisions
    before the preemptor must be kept, decisions after recomputed."""
    nodes = [make_node(f"n{i}", milli_cpu=2000, memory=16 * 1024**3)
             for i in range(3)]
    victims = [prio_pod(f"v{i}", 0, milli_cpu=1800, node_name=f"n{i}")
               for i in range(3)]
    # feed is LIFO: list order [first-fed last ... last-fed first]; build in
    # podspec order so 'small' pods schedule first, then the preemptor fires
    pods = [
        prio_pod("post", 0, milli_cpu=150),
        prio_pod("preemptor", 5, milli_cpu=1900),
        prio_pod("small-b", 0, milli_cpu=100),
        prio_pod("small-a", 0, milli_cpu=100),
    ]
    snap = ClusterSnapshot(nodes=nodes, pods=victims)
    status = assert_preempt_parity(pods, snap)
    assert len(status.preempted_pods) == 1
    assert any(p.name == "preemptor" for p in status.successful_pods)


def test_jax_preemption_cascade():
    """Several preemptors in one batch: each success invalidates later
    decisions, exercising repeated re-dispatch + bucket padding."""
    nodes = [make_node(f"n{i}", milli_cpu=1000, memory=16 * 1024**3)
             for i in range(4)]
    victims = [prio_pod(f"v{i}", i % 3, milli_cpu=900, node_name=f"n{i}")
               for i in range(4)]
    pods = [prio_pod(f"h{i}", 8, milli_cpu=900) for i in range(6)]
    snap = ClusterSnapshot(nodes=nodes, pods=victims)
    status = assert_preempt_parity(pods, snap)
    assert len(status.preempted_pods) == 4
    assert len(status.successful_pods) == 4
    assert len(status.failed_pods) == 2


def test_jax_preemption_respects_unresolvable_nodes():
    """Nodes failing on taints/selector are excluded from preemption
    (nodesWherePreemptionMightHelp, generic_scheduler.go:1050-1080)."""
    tainted = make_node("tainted", milli_cpu=4000, memory=16 * 1024**3,
                        taints=[{"key": "k", "value": "v",
                                 "effect": "NoSchedule"}])
    normal = make_node("normal", milli_cpu=1000, memory=16 * 1024**3)
    victim_t = prio_pod("vt", 0, milli_cpu=100, node_name="tainted")
    victim_n = prio_pod("vn", 0, milli_cpu=900, node_name="normal")
    pod = prio_pod("pod", 9, milli_cpu=900)
    snap = ClusterSnapshot(nodes=[tainted, normal], pods=[victim_t, victim_n])
    status = assert_preempt_parity([pod], snap)
    assert [p.name for p in status.preempted_pods] == ["vn"]
    assert status.successful_pods[0].spec.node_name == "normal"


def test_jax_preemption_random_differential():
    rng = random.Random(7)
    for trial in range(3):
        n_nodes = 6
        nodes = [make_node(f"n{i}", milli_cpu=rng.choice([1000, 2000, 3000]),
                           memory=16 * 1024**3) for i in range(n_nodes)]
        placed = []
        for i in range(10):
            placed.append(prio_pod(
                f"placed-{trial}-{i}", rng.randint(0, 5),
                milli_cpu=rng.choice([200, 500, 900]),
                node_name=f"n{rng.randrange(n_nodes)}"))
        pods = [prio_pod(f"new-{trial}-{i}", rng.randint(0, 10),
                         milli_cpu=rng.choice([300, 800, 1500, 2500]))
                for i in range(18)]
        snap = ClusterSnapshot(nodes=nodes, pods=placed)
        assert_preempt_parity(pods, snap)


def test_jax_preemption_no_nodes():
    pod = prio_pod("pod", 5, milli_cpu=100)
    snap = ClusterSnapshot(nodes=[], pods=[])
    status = assert_preempt_parity([pod], snap)
    assert [p.name for p in status.failed_pods] == ["pod"]


def test_jax_preemption_empty_feed():
    snap = ClusterSnapshot(nodes=[make_node("n1", milli_cpu=1000)], pods=[])
    status = assert_preempt_parity([], snap)
    assert status.stop_reason


def test_jax_preemption_chunk_sizing_invariant(monkeypatch):
    """The chunked dispatch loop: the carry flows across chunks, so ANY
    chunk sizing must produce the outcome of a single full dispatch
    (including the pow2 bucket padding after preemptions)."""
    import numpy as np

    rng = np.random.RandomState(11)
    nodes = [make_node(f"n{i}", milli_cpu=2000, memory=16 * 1024**3)
             for i in range(12)]
    placed = [prio_pod(f"placed-{i}", i % 3, milli_cpu=700,
                       node_name=f"n{i % 12}") for i in range(18)]
    pods = [prio_pod(f"new-{i}", int(rng.randint(0, 10)),
                     milli_cpu=int(rng.choice([400, 900, 1600])))
            for i in range(40)]
    snap = ClusterSnapshot(nodes=nodes, pods=placed)

    def run(chunk0, chunk_max):
        monkeypatch.setenv("TPUSIM_PREEMPT_CHUNK0", str(chunk0))
        monkeypatch.setenv("TPUSIM_PREEMPT_CHUNK_MAX", str(chunk_max))
        # fresh copies per run: the orchestrator seams mutate fed pods in
        # place (conditions, nominated node names)
        return run_simulation([p.copy() for p in pods], snap, backend="jax",
                              enable_pod_priority=True)

    small = run(8, 16)
    single = run(1 << 20, 1 << 20)
    assert status_sig(small) == status_sig(single)
    assert sorted(p.name for p in small.preempted_pods) == \
        sorted(p.name for p in single.preempted_pods)
    # the workload must actually exercise the preemption arm
    assert small.preempted_pods


def _node_mesh_or_skip():
    import jax
    import pytest

    from tpusim.jaxe.sharding import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8, snap=1)


def test_jax_preemption_node_sharded_mesh_matches_single_device():
    """The hybrid with the node axis sharded over the 8-way mesh (speculation
    chunks dispatch under `with mesh`, the carry re-arm after every preemption
    lands back on the mesh) must stay byte-identical to the single-device
    hybrid on a priority-banded saturated workload — including the device
    victim-selection arm, whose kernel runs unsharded off the host victim
    table. 10 nodes over 8 shards also exercises the node-axis padding."""
    import bench
    from tpusim.jaxe.preempt import run_with_preemption

    mesh = _node_mesh_or_skip()
    snap, pods = bench.build_workload(400, 10, priorities=True, seed=41)
    base = run_with_preemption([p.copy() for p in pods], snap)
    assert base.preempted_pods, "workload drifted: nothing preempted"
    sharded = run_with_preemption([p.copy() for p in pods], snap, mesh=mesh)
    assert status_sig(sharded) == status_sig(base)
    assert len(sharded.preempted_pods) == len(base.preempted_pods)


def test_jax_preemption_mesh_host_arm_parity(monkeypatch):
    """TPUSIM_PREEMPT_DEVICE=0 forces host victim selection; under the mesh
    the outcome must still match the single-device run with the device kernel
    on — the victim arm and the scan sharding are independent axes."""
    import bench
    from tpusim.jaxe.preempt import run_with_preemption

    mesh = _node_mesh_or_skip()
    snap, pods = bench.build_workload(400, 10, priorities=True, seed=43)
    monkeypatch.delenv("TPUSIM_PREEMPT_DEVICE", raising=False)
    base = run_with_preemption([p.copy() for p in pods], snap)
    assert base.preempted_pods
    monkeypatch.setenv("TPUSIM_PREEMPT_DEVICE", "0")
    sharded = run_with_preemption([p.copy() for p in pods], snap, mesh=mesh)
    assert status_sig(sharded) == status_sig(base)


def test_preempt_fast_path_engages_and_matches(monkeypatch):
    """Round-5: the preemption hybrid drives its speculation chunks through
    the Pallas kernel (interpreter here), re-arming the carry from
    refresh_dynamic after each preemption — placements byte-identical to
    the XLA hybrid at equal preemption counts."""
    import bench
    from tpusim.jaxe import fastscan
    from tpusim.jaxe.preempt import run_with_preemption

    snap, pods = bench.build_workload(600, 40, priorities=True, seed=17)

    monkeypatch.delenv("TPUSIM_FAST", raising=False)
    base = run_with_preemption(pods, snap)

    monkeypatch.setenv("TPUSIM_FAST", "1")
    monkeypatch.setenv("TPUSIM_FAST_INTERPRET", "1")
    calls = []
    real = fastscan.fast_scan

    def wrapped(*a, **kw):
        calls.append(kw.get("carry_in") is not None)
        return real(*a, **kw)

    monkeypatch.setattr(fastscan, "fast_scan", wrapped)
    fast = run_with_preemption(pods, snap)

    assert calls, "fast path did not engage"
    assert any(calls), "no chunk ran with an explicit carry (re-arm path)"

    def outcome(st):
        return ({p.metadata.name: p.spec.node_name
                 for p in st.successful_pods},
                sorted(p.metadata.name for p in st.failed_pods),
                sorted(p.metadata.name for p in st.preempted_pods))

    assert outcome(fast) == outcome(base)


def test_preempt_fast_verify_once_small_chunk0(monkeypatch):
    """A chunk0 below TPUSIM_FAST_VERIFY_MIN must verify ONLY the first
    chunk (later chunks run on a chained carry that no from-scratch replay
    matches) and must not spuriously disable the fast path."""
    import bench
    from tpusim.jaxe import backend, fastscan
    from tpusim.jaxe.preempt import run_with_preemption

    snap, pods = bench.build_workload(400, 30, priorities=True, seed=23)
    monkeypatch.delenv("TPUSIM_FAST", raising=False)
    base = run_with_preemption(pods, snap)

    monkeypatch.setenv("TPUSIM_FAST_INTERPRET", "1")
    monkeypatch.setenv("TPUSIM_PREEMPT_CHUNK0", "32")  # < min_pin (64)
    monkeypatch.setitem(backend._FAST_AUTO, "disabled", False)
    monkeypatch.setitem(backend._FAST_AUTO, "verified_sigs", set())
    # AUTO mode off-TPU never engages; force the gate while keeping
    # auto-mode verification on
    monkeypatch.setattr(backend, "_fast_path_enabled", lambda: (True, True))
    verifies = []
    real_verify = backend._auto_verify_and_pin

    def counting_verify(*a, **kw):
        verifies.append(1)
        return real_verify(*a, **kw)

    # run_with_preemption imports these names from backend at call time,
    # so patching the backend module covers the hybrid too
    monkeypatch.setattr(backend, "_auto_verify_and_pin", counting_verify)
    fast = run_with_preemption(pods, snap)
    assert len(verifies) == 1
    assert backend._FAST_AUTO["disabled"] is False
    assert {p.metadata.name: p.spec.node_name
            for p in fast.successful_pods} \
        == {p.metadata.name: p.spec.node_name for p in base.successful_pods}


def test_preempt_fast_path_with_interpod(monkeypatch):
    """Preemption + inter-pod anti-affinity together on the fast path: the
    post-victim re-arm must rebuild BOTH the presence and presence_dom
    carries (rearm_carry's interpod branch) and stay outcome-identical to
    the XLA hybrid."""
    import random

    from tpusim.api.snapshot import ClusterSnapshot, make_node, make_pod
    from tpusim.jaxe import fastscan
    from tpusim.jaxe.preempt import run_with_preemption

    rng = random.Random(31)
    nodes = [make_node(f"n{i}", milli_cpu=2000, memory=8 * 1024**3,
                       labels={"zone": f"z{i % 3}"}) for i in range(12)]
    low = []
    for i in range(20):
        p = make_pod(f"low{i}", milli_cpu=800, memory=2**28,
                     labels={"app": "lo"})
        p.spec.node_name = f"n{i % 12}"
        p.spec.priority = 0
        low.append(p)
    pods = []
    for i in range(60):
        kw = {"labels": {"app": f"a{rng.randrange(2)}"}}
        if rng.random() < 0.3:
            kw["affinity"] = {"podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"labelSelector":
                     {"matchLabels": {"app": kw["labels"]["app"]}},
                     "topologyKey": "zone"}]}}
        p = make_pod(f"p{i}", milli_cpu=rng.choice([400, 800]),
                     memory=2**28, **kw)
        p.spec.priority = int(rng.choice([0, 500, 1000]))
        pods.append(p)
    snap = ClusterSnapshot(nodes=nodes, pods=low)

    def outcome(st):
        return ({p.metadata.name: p.spec.node_name
                 for p in st.successful_pods},
                sorted(p.metadata.name for p in st.failed_pods),
                sorted(p.metadata.name for p in st.preempted_pods))

    monkeypatch.delenv("TPUSIM_FAST", raising=False)
    base = run_with_preemption(pods, snap)
    assert base.preempted_pods  # the shape must actually preempt

    monkeypatch.setenv("TPUSIM_FAST", "1")
    monkeypatch.setenv("TPUSIM_FAST_INTERPRET", "1")
    calls = []
    real = fastscan.fast_scan

    def wrapped(*a, **kw):
        calls.append(kw.get("carry_in") is not None)
        return real(*a, **kw)

    monkeypatch.setattr(fastscan, "fast_scan", wrapped)
    fast = run_with_preemption(pods, snap)
    assert calls and all(calls)
    assert outcome(fast) == outcome(base)

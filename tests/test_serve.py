"""Scenario-fleet serving tests (tpusim/serve).

Correctness bar: the serve path — admission, shape-class bucketing, ghost
padding, warm-executable reuse — must produce placements byte-identical
(placement hash) to per-scenario run_what_if. The batcher and queue are
tested host-side with injected clocks; warm repeats are proven by the
whatif compile counter (zero traces), not by timing.
"""

import jax
import numpy as np
import pytest

from tpusim.api.snapshot import ClusterSnapshot, make_node, make_pod
from tpusim.backends import placement_hash
from tpusim.framework.metrics import register
from tpusim.jaxe.whatif import compile_count, run_what_if
from tpusim.serve import (
    REJECT_INVALID,
    REJECT_QUEUE_FULL,
    REJECT_UNKNOWN_SNAPSHOT,
    AdmissionQueue,
    Bucket,
    PendingEntry,
    ScenarioFleet,
    ShapeClass,
    ShapeClassBatcher,
    WhatIfRequest,
    shape_class_for,
)
from tpusim.serve.request import _budget

needs_8_devices = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the virtual 8-device mesh")


def scenario(seed: int, num_nodes: int = 4, num_pods: int = 3):
    rng = np.random.RandomState(seed)
    nodes = [make_node(f"s{seed}-n{i}",
                       milli_cpu=int(rng.choice([2000, 4000, 8000])),
                       memory=int(rng.choice([4, 8])) * 1024**3)
             for i in range(num_nodes)]
    pods = [make_pod(f"s{seed}-p{i}",
                     milli_cpu=int(rng.randint(100, 1500)),
                     memory=int(rng.randint(2**20, 2**30)))
            for i in range(num_pods)]
    return ClusterSnapshot(nodes=nodes), pods


def singleton_hash(snap, pods):
    [result] = run_what_if([(snap, pods)])
    return placement_hash(result.placements)


# ---------------------------------------------------------------------------
# shape classes
# ---------------------------------------------------------------------------


class TestShapeClass:
    def test_budget_rounds_to_pow2_with_floor(self):
        assert [_budget(n) for n in (1, 3, 4, 5, 8, 9, 100)] == \
            [4, 4, 4, 8, 8, 16, 128]

    def test_same_class_across_sizes_within_budget(self):
        # 3 and 4 pods on 3 and 4 nodes land in one class (floor 4): one
        # bucket, one executable
        fleet = ScenarioFleet()
        classes = set()
        for num_nodes, num_pods in ((3, 3), (4, 4), (3, 4)):
            snap, pods = scenario(1, num_nodes, num_pods)
            staged, sc, _, _, _ = fleet.executor.stage(
                WhatIfRequest(pods=pods, snapshot=snap))
            classes.add(sc)
        assert len(classes) == 1
        (sc,) = classes
        assert sc.n_nodes == 4 and sc.n_pods == 4

    def test_deterministic(self):
        fleet = ScenarioFleet()
        snap, pods = scenario(2)
        req = lambda: WhatIfRequest(pods=list(pods), snapshot=snap)  # noqa: E731
        sc_a = fleet.executor.stage(req())[1]
        sc_b = fleet.executor.stage(req())[1]
        assert sc_a == sc_b and hash(sc_a) == hash(sc_b)


# ---------------------------------------------------------------------------
# batcher (host-only: fake staged entries, injected clock)
# ---------------------------------------------------------------------------


def _entry(shape_class, plan_sig="sig", at=0.0):
    return PendingEntry(request=WhatIfRequest(pods=[make_pod("x")]),
                        staged=None, future=None, admitted_at=at,
                        shape_class=shape_class, plan_sig=plan_sig)


class TestBatcher:
    SC_A = ShapeClass(n_nodes=4, n_pods=4, axes=())
    SC_B = ShapeClass(n_nodes=8, n_pods=4, axes=())

    def test_fills_bucket_in_arrival_order(self):
        batcher = ShapeClassBatcher(bucket_size=3, clock=lambda: 0.0)
        entries = [_entry(self.SC_A) for _ in range(3)]
        assert batcher.add(entries[0]) is None
        assert batcher.add(entries[1]) is None
        bucket = batcher.add(entries[2])
        assert bucket is not None and bucket.entries == entries
        assert bucket.ghosts == 0 and batcher.pending() == 0

    def test_distinct_keys_do_not_share_buckets(self):
        batcher = ShapeClassBatcher(bucket_size=2, clock=lambda: 0.0)
        assert batcher.add(_entry(self.SC_A)) is None
        assert batcher.add(_entry(self.SC_B)) is None
        assert batcher.add(_entry(self.SC_A, plan_sig="other")) is None
        assert batcher.pending() == 3  # three open buckets of one entry
        full = batcher.add(_entry(self.SC_A))
        assert full is not None and full.key == (self.SC_A, "sig")

    def test_deadline_flush_under_injected_clock(self):
        t = [0.0]
        batcher = ShapeClassBatcher(bucket_size=4, flush_after_s=0.5,
                                    clock=lambda: t[0])
        batcher.add(_entry(self.SC_A, at=0.0))
        t[0] = 0.2
        batcher.add(_entry(self.SC_A, at=0.2))
        assert batcher.due() == []  # oldest has waited 0.2 < 0.5
        assert batcher.next_deadline() == pytest.approx(0.5)
        t[0] = 0.49
        assert batcher.due() == []
        t[0] = 0.5  # the deadline is the oldest entry's, not the newest's
        [bucket] = batcher.due()
        assert len(bucket.entries) == 2 and bucket.ghosts == 2
        assert batcher.due() == [] and batcher.next_deadline() is None

    def test_flush_all_drains_everything(self):
        batcher = ShapeClassBatcher(bucket_size=4, clock=lambda: 0.0)
        batcher.add(_entry(self.SC_A))
        batcher.add(_entry(self.SC_B))
        buckets = batcher.flush_all()
        assert len(buckets) == 2 and batcher.pending() == 0


class TestAdmissionQueue:
    def test_bounded_put_pop(self):
        q = AdmissionQueue(maxsize=2)
        assert q.put("a") and q.put("b")
        assert not q.put("c")  # full: reject, never block
        assert q.pop() == "a" and q.pop() == "b" and q.pop() is None

    def test_close_rejects_new_but_drains_held(self):
        q = AdmissionQueue(maxsize=4)
        q.put("a")
        q.close()
        assert not q.put("b")
        assert q.closed and q.pop() == "a"

    def test_depth_gauge_tracks_transitions(self):
        q = AdmissionQueue(maxsize=4)
        q.put("a"), q.put("b")
        assert register().serve_queue_depth.value == 2
        q.pop()
        assert register().serve_queue_depth.value == 1


# ---------------------------------------------------------------------------
# the fleet end-to-end (device dispatch)
# ---------------------------------------------------------------------------


class TestFleet:
    def test_full_bucket_matches_run_what_if(self):
        scenarios = [scenario(10 + s) for s in range(2)]
        fleet = ScenarioFleet(bucket_size=2, flush_after_s=60.0)
        responses = fleet.run([WhatIfRequest(pods=p, snapshot=s)
                               for s, p in scenarios])
        for resp, (snap, pods) in zip(responses, scenarios):
            assert resp.ok, resp.error
            assert resp.bucket_real == 2 and resp.bucket_ghosts == 0
            assert placement_hash(resp.result.placements) == \
                singleton_hash(snap, pods)

    def test_ghost_padded_partial_bucket_matches_and_never_leaks(self):
        snap, pods = scenario(12)
        fleet = ScenarioFleet(bucket_size=2, flush_after_s=60.0)
        [resp] = fleet.run([WhatIfRequest(pods=pods, snapshot=snap)])
        assert resp.ok and resp.bucket_real == 1 and resp.bucket_ghosts == 1
        # one response per real request; its placements cover exactly the
        # request's pods (no ghost scenario, no pod-axis padding leaks out)
        assert [p.pod.name for p in resp.result.placements] == \
            [p.name for p in pods]
        assert placement_hash(resp.result.placements) == \
            singleton_hash(snap, pods)

    def test_warm_repeat_skips_recompilation(self):
        scenarios = [scenario(20 + s) for s in range(2)]
        fleet = ScenarioFleet(bucket_size=2, flush_after_s=60.0)
        load = lambda: [WhatIfRequest(pods=p, snapshot=s, cache_key=f"k{i}")  # noqa: E731
                        for i, (s, p) in enumerate(scenarios)]
        cold = fleet.run(load())
        assert all(r.ok for r in cold)
        before = compile_count()
        warm = fleet.run(load())
        assert compile_count() == before, \
            "warm repeat of an identical shape class must not trace"
        assert all(r.compile_cache_hit for r in warm)
        assert fleet.executor.stats["staged_hits"] >= 2  # cache_key reuse
        for a, b in zip(cold, warm):
            assert placement_hash(a.result.placements) == \
                placement_hash(b.result.placements)

    def test_snapshot_ref_and_rejections(self):
        snap, pods = scenario(30)
        fleet = ScenarioFleet(bucket_size=2, flush_after_s=60.0)
        fleet.register_snapshot("base", snap)
        ok, missing, no_pods, no_nodes = fleet.run([
            WhatIfRequest(pods=pods, snapshot_ref="base"),
            WhatIfRequest(pods=pods, snapshot_ref="nope"),
            WhatIfRequest(pods=[], snapshot_ref="base"),
            WhatIfRequest(pods=pods, snapshot=ClusterSnapshot(nodes=[])),
        ])
        assert ok.ok and placement_hash(ok.result.placements) == \
            singleton_hash(snap, pods)
        assert missing.rejected == REJECT_UNKNOWN_SNAPSHOT
        assert no_pods.rejected == REJECT_INVALID
        assert no_nodes.rejected == REJECT_INVALID
        assert "zero-node" in no_nodes.error
        assert register().serve_rejected.values[REJECT_INVALID] >= 2

    def test_queue_full_rejects_at_submit(self):
        snap, pods = scenario(31)
        fleet = ScenarioFleet(bucket_size=4, flush_after_s=60.0, max_queue=2)
        futures = [fleet.submit(WhatIfRequest(pods=pods, snapshot=snap))
                   for _ in range(3)]
        overflow = [f for f in futures if f.done()]
        assert len(overflow) == 1
        assert overflow[0].result().rejected == REJECT_QUEUE_FULL
        fleet.drain()
        accepted = [f.result() for f in futures if f.result().rejected is None]
        assert len(accepted) == 2 and all(r.ok for r in accepted)

    def test_deadline_flush_with_injected_clock(self):
        snap, pods = scenario(32)
        t = [0.0]
        fleet = ScenarioFleet(bucket_size=4, flush_after_s=0.5,
                              clock=lambda: t[0])
        future = fleet.submit(WhatIfRequest(pods=pods, snapshot=snap))
        fleet.pump()
        assert not future.done()  # waiting for siblings until the deadline
        t[0] = 0.49
        fleet.pump()
        assert not future.done()
        t[0] = 0.51
        fleet.pump()
        resp = future.result()
        assert resp.ok and resp.bucket_ghosts == 3
        assert placement_hash(resp.result.placements) == \
            singleton_hash(snap, pods)

    def test_serve_metric_families_exposed(self):
        snap, pods = scenario(33)
        ScenarioFleet(bucket_size=2, flush_after_s=60.0).run(
            [WhatIfRequest(pods=pods, snapshot=snap)])
        text = register().expose()
        for family in ("tpusim_serve_queue_depth",
                       "tpusim_serve_batch_occupancy",
                       "tpusim_serve_request_latency_microseconds",
                       "tpusim_serve_dispatch_total"):
            assert family in text, family

    @needs_8_devices
    def test_scenario_mesh_bucket_matches_run_what_if(self):
        from tpusim.jaxe.sharding import make_scenario_mesh

        mesh = make_scenario_mesh(8)
        with pytest.raises(ValueError, match="does not divide"):
            ScenarioFleet(bucket_size=6, mesh=mesh)
        fleet = ScenarioFleet(bucket_size=8, flush_after_s=60.0, mesh=mesh)
        scenarios = [scenario(40 + s) for s in range(3)]
        responses = fleet.run([WhatIfRequest(pods=p, snapshot=s)
                               for s, p in scenarios])
        # 3 real scenarios ghost-padded to the 8-shard bucket
        for resp, (snap, pods) in zip(responses, scenarios):
            assert resp.ok and resp.bucket_ghosts == 5
            assert placement_hash(resp.result.placements) == \
                singleton_hash(snap, pods)


# ---------------------------------------------------------------------------
# live-twin serving (ISSUE 19): resident-overlay dispatch + staged fallback
# ---------------------------------------------------------------------------


def _warm_twin(num_nodes=8, cycles=3, seed=11):
    from tpusim.api.snapshot import synthetic_cluster
    from tpusim.stream import ChurnLoadGen, StreamSession

    session = StreamSession(synthetic_cluster(num_nodes))
    gen = ChurnLoadGen(synthetic_cluster(num_nodes), seed=seed, arrivals=8,
                       evict_fraction=0.25)
    for c in range(cycles):
        session.apply_events(gen.events(c))
        gen.note_bound(session.schedule(gen.batch()))
    return session


class TestLiveTwin:
    def test_overlay_parity_and_warm_second_query(self):
        session = _warm_twin()
        fleet = ScenarioFleet(bucket_size=4, flush_after_s=60.0)
        fleet.attach_stream(session, ref="live")
        _, pods = scenario(41)
        want = singleton_hash(session.inc.to_snapshot(), pods)
        cold = fleet.submit(WhatIfRequest(pods=pods, snapshot_ref="live"))
        fleet.drain()
        resp = cold.result()
        assert resp.ok and not resp.compile_cache_hit
        assert placement_hash(resp.result.placements) == want
        warm = fleet.submit(WhatIfRequest(pods=pods, snapshot_ref="live"))
        fleet.drain()
        resp2 = warm.result()
        assert resp2.ok and resp2.compile_cache_hit
        assert placement_hash(resp2.result.placements) == want
        assert fleet.executor.stats["overlay_hits"] == 2
        assert fleet.executor.stats["overlay_fallbacks"] == 0

    def test_forced_restage_falls_back_to_staged_path(self):
        session = _warm_twin(seed=12)
        fleet = ScenarioFleet(bucket_size=1, flush_after_s=60.0)
        fleet.attach_stream(session, ref="live")
        session.force_restage("test_fallback")
        _, pods = scenario(42)
        want = singleton_hash(session.inc.to_snapshot(), pods)
        fut = fleet.submit(WhatIfRequest(pods=pods, snapshot_ref="live"))
        fleet.drain()
        resp = fut.result()
        # the staged path answered against the twin's SAME live host
        # picture — degraded service, identical placements
        assert resp.ok
        assert placement_hash(resp.result.placements) == want
        assert fleet.executor.stats["overlay_fallbacks"] >= 1
        assert fleet.executor.stats["overlay_hits"] == 0

    def test_plan_mismatch_routes_around_overlay(self):
        import json
        import pathlib

        from tpusim.engine.policy import decode_policy

        session = _warm_twin(seed=13)
        fleet = ScenarioFleet(bucket_size=1, flush_after_s=60.0)
        fleet.attach_stream(session, ref="live")
        pol = decode_policy(json.loads(
            (pathlib.Path(__file__).parent /
             "compat_policies.json").read_text())["1.0"])
        _, pods = scenario(43)
        fut = fleet.submit(WhatIfRequest(pods=pods, snapshot_ref="live",
                                         policy=pol))
        fleet.drain()
        resp = fut.result()
        assert resp.ok  # staged against the live picture, twin untouched
        assert fleet.executor.stats["overlay_hits"] == 0

    def test_detach_twin_restores_ref_lookup(self):
        session = _warm_twin(seed=14)
        fleet = ScenarioFleet(bucket_size=1, flush_after_s=60.0)
        fleet.attach_stream(session, ref="live")
        fleet.executor.detach_twin("live")
        _, pods = scenario(44)
        fut = fleet.submit(WhatIfRequest(pods=pods, snapshot_ref="live"))
        fleet.drain()
        assert fut.result().rejected == REJECT_UNKNOWN_SNAPSHOT

    def test_replica_answers_before_leader(self, tmp_path):
        from tpusim.api.snapshot import synthetic_cluster
        from tpusim.simulator import run_stream_simulation
        from tpusim.stream.replicate import FollowerTwin

        follower = FollowerTwin(synthetic_cluster(8))
        try:
            run_stream_simulation(num_nodes=8, cycles=4, arrivals=8,
                                  seed=15, evict_fraction=0.25,
                                  checkpoint_dir=str(tmp_path),
                                  checkpoint_every=2,
                                  replicate_to=follower.address)
            assert follower.diverged is None
            fleet = ScenarioFleet(bucket_size=1, flush_after_s=60.0)
            fleet.attach_stream(_warm_twin(seed=15), ref="live")
            fleet.attach_replica(follower, ref="live")
            _, pods = scenario(45)
            want = singleton_hash(follower.session.inc.to_snapshot(), pods)
            before = register().overlay_queries.values.get("follower", 0)
            fut = fleet.submit(WhatIfRequest(pods=pods,
                                             snapshot_ref="live"))
            fleet.drain()
            resp = fut.result()
            assert resp.ok
            assert placement_hash(resp.result.placements) == want
            assert register().overlay_queries.values.get(
                "follower", 0) == before + 1
        finally:
            follower.stop()

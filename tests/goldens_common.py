"""Shared fixtures for the schedulercache golden suites.

make_base_pod is the single port of cache_test.go's makeBasePod (used by both
tests/test_cache_goldens.py and tests/test_node_info_goldens.py): quantity
STRINGS, with empty cpu/memory meaning the key is ABSENT from requests — the
non-zero defaulting applies only to unset keys, never explicit zeros
(non_zero.go:36-54).
"""

from tpusim.api.quantity import parse_quantity
from tpusim.api.snapshot import make_pod
from tpusim.api.types import ContainerPort


def make_base_pod(name, cpu="", memory="", scalars=None, ports=(),
                  node_name="node"):
    pod = make_pod(name, node_name=node_name)
    requests = {}
    if cpu:
        requests["cpu"] = parse_quantity(cpu)
    if memory:
        requests["memory"] = parse_quantity(memory)
    for scalar_name, qty in (scalars or {}).items():
        requests[scalar_name] = parse_quantity(str(qty))
    pod.spec.containers[0].requests = requests
    pod.spec.containers[0].ports = [
        ContainerPort.from_obj({"hostIP": ip, "hostPort": hp,
                                "protocol": proto})
        for ip, hp, proto in ports]
    return pod

"""Wavefront (batched) mode tests: conservation properties + agreement with
exact mode where pods commute."""

import numpy as np

from tpusim.api.snapshot import ClusterSnapshot, make_node, make_pod, synthetic_cluster
from tpusim.backends import ReferenceBackend
from tpusim.jaxe.backend import JaxBackend


def test_wavefront_uniform_pods_counts_match_exact():
    # uniform pods commute: total scheduled count must equal the exact mode's
    snap = synthetic_cluster(8, milli_cpu=4000, memory=8 * 1024**3)
    pods = [make_pod(f"p{i}", milli_cpu=500, memory=512 * 2**20) for i in range(80)]
    exact = JaxBackend(fallback="error").schedule(pods, snap)
    wave = JaxBackend(fallback="error", batch_size=16).schedule(pods, snap)
    assert (sum(p.scheduled for p in exact) == sum(p.scheduled for p in wave)
            == 8 * 8)  # 4000/500 = 8 per node


def test_wavefront_spreads_within_wave():
    # all nodes tie: the rr bookkeeping must spread a wave across nodes, not
    # pile everything on node 0
    snap = synthetic_cluster(4, milli_cpu=4000, memory=16 * 1024**3)
    pods = [make_pod(f"p{i}", milli_cpu=1, memory=1) for i in range(4)]
    wave = JaxBackend(fallback="error", batch_size=4).schedule(pods, snap)
    assert len({p.node_name for p in wave}) == 4


def test_wavefront_respects_capacity_between_waves():
    # one node, capacity 2 pods per wave boundary: waves of 2 can never
    # overcommit because binds apply between waves
    snap = ClusterSnapshot(nodes=[make_node("n", milli_cpu=1000, memory=16 * 1024**3)])
    pods = [make_pod(f"p{i}", milli_cpu=400) for i in range(6)]
    wave = JaxBackend(fallback="error", batch_size=2).schedule(pods, snap)
    scheduled = [p for p in wave if p.scheduled]
    # 1000/400 = 2 fit exactly; wave 1 binds 2, wave 2+ see the node full...
    # except in-wave overcommit: wave 1's two pods both saw an empty node and
    # both fit (400+400 <= 1000), so 2 scheduled; wave 2 sees 800 used -> fails
    assert len(scheduled) == 2
    assert all("Insufficient cpu" in p.message for p in wave if not p.scheduled)


def test_wavefront_overcommit_is_bounded_by_wave():
    # the documented approximation: within one wave two pods can double-book a
    # node that fits only one — never more than one wave's worth
    snap = ClusterSnapshot(nodes=[make_node("n", milli_cpu=1000, memory=16 * 1024**3)])
    pods = [make_pod(f"p{i}", milli_cpu=600) for i in range(4)]
    wave = JaxBackend(fallback="error", batch_size=2).schedule(pods, snap)
    # both wave-1 pods pass the filter against the frozen empty node
    assert sum(p.scheduled for p in wave) == 2
    exact = JaxBackend(fallback="error").schedule(pods, snap)
    assert sum(p.scheduled for p in exact) == 1  # exact mode admits only one


def test_wavefront_batch_larger_than_pod_count():
    snap = synthetic_cluster(2, milli_cpu=4000, memory=8 * 1024**3)
    pods = [make_pod(f"p{i}", milli_cpu=100) for i in range(3)]
    wave = JaxBackend(fallback="error", batch_size=64).schedule(pods, snap)
    assert len(wave) == 3 and all(p.scheduled for p in wave)


def test_wavefront_failure_messages_match_reference_format():
    snap = ClusterSnapshot(nodes=[make_node("n", milli_cpu=100, memory=1024**3)])
    pods = [make_pod(f"p{i}", milli_cpu=5000) for i in range(3)]
    wave = JaxBackend(fallback="error", batch_size=2).schedule(pods, snap)
    ref = ReferenceBackend().schedule(pods, snap)
    assert [p.message for p in wave] == [p.message for p in ref]

"""Scheduler extender tests.

Reference behaviors pinned: core/extender.go:105-293 (Filter/Prioritize/Bind
wire protocol, nodeCacheCapable encoding, IsInterested managed-resource gate),
generic_scheduler.go:355-376 (filter failure append), :640-667 (prioritize
merge, errors ignored), :842-874 (preemption re-filter with victims removed),
factory.go:971-1000 (extender construction from policy, ignored resources).
"""

import pytest

from tpusim.api.snapshot import ClusterSnapshot, make_node, make_pod
from tpusim.engine.extender import ExtenderError, new_http_extender
from tpusim.engine.policy import (
    ExtenderConfig,
    ExtenderManagedResource,
    Policy,
    PredicatePolicy,
)
from tpusim.engine.providers import PluginFactoryArgs, create_from_config
from tpusim.engine.resources import NodeInfo
from tpusim.simulator import SchedulerServerConfig, new_cluster_capacity


def _nodes(n=3, **kwargs):
    return [make_node(f"n{i}", milli_cpu=4000, memory=2**33, **kwargs)
            for i in range(n)]


def _info_map(nodes, pods=()):
    infos = {}
    for node in nodes:
        info = NodeInfo()
        info.set_node(node)
        infos[node.name] = info
    for pod in pods:
        infos[pod.spec.node_name].add_pod(pod)
    return infos


class RecordingTransport:
    """In-process transport: records calls, replies from a handler map."""

    def __init__(self, handlers):
        self.handlers = handlers
        self.calls = []

    def __call__(self, verb, args):
        self.calls.append((verb, args))
        handler = self.handlers[verb]
        return handler(args) if callable(handler) else handler


class TestFilter:
    def test_filter_subsets_and_reports_failures(self):
        nodes = _nodes(3)
        transport = RecordingTransport({"filter": lambda args: {
            "nodes": {"items": [n for n in args["nodes"]["items"]
                                if n["metadata"]["name"] != "n1"]},
            "failedNodes": {"n1": "extender says no"},
        }})
        ext = new_http_extender(
            ExtenderConfig(url_prefix="http://e", filter_verb="filter"),
            transport=transport)
        filtered, failed = ext.filter(make_pod("p"), nodes, _info_map(nodes))
        assert [n.name for n in filtered] == ["n0", "n2"]
        assert failed == {"n1": "extender says no"}
        # wire shape: full node objects when not nodeCacheCapable
        verb, args = transport.calls[0]
        assert verb == "filter"
        assert args["nodeNames"] is None
        assert len(args["nodes"]["items"]) == 3

    def test_node_cache_capable_sends_names_only(self):
        nodes = _nodes(2)
        transport = RecordingTransport({"filter": lambda args: {
            "nodeNames": [args["nodeNames"][0]]}})
        ext = new_http_extender(
            ExtenderConfig(url_prefix="http://e", filter_verb="filter",
                           node_cache_capable=True),
            transport=transport)
        filtered, failed = ext.filter(make_pod("p"), nodes, _info_map(nodes))
        assert [n.name for n in filtered] == ["n0"]
        _, args = transport.calls[0]
        assert args["nodes"] is None
        assert args["nodeNames"] == ["n0", "n1"]

    def test_no_filter_verb_passthrough(self):
        nodes = _nodes(2)
        ext = new_http_extender(ExtenderConfig(url_prefix="http://e"),
                                transport=RecordingTransport({}))
        filtered, failed = ext.filter(make_pod("p"), nodes, _info_map(nodes))
        assert filtered == nodes and failed == {}

    def test_error_result_raises(self):
        nodes = _nodes(1)
        ext = new_http_extender(
            ExtenderConfig(url_prefix="http://e", filter_verb="filter"),
            transport=RecordingTransport({"filter": {"error": "boom"}}))
        with pytest.raises(ExtenderError, match="boom"):
            ext.filter(make_pod("p"), nodes, _info_map(nodes))


class TestPrioritizeBindInterest:
    def test_prioritize_returns_scores_and_weight(self):
        nodes = _nodes(2)
        ext = new_http_extender(
            ExtenderConfig(url_prefix="http://e", prioritize_verb="prioritize",
                           weight=3),
            transport=RecordingTransport({"prioritize": [
                {"host": "n0", "score": 5}, {"host": "n1", "score": 2}]}))
        scores, weight = ext.prioritize(make_pod("p"), nodes)
        assert weight == 3
        assert [(hp.host, hp.score) for hp in scores] == [("n0", 5), ("n1", 2)]

    def test_prioritize_without_verb_scores_zero(self):
        nodes = _nodes(2)
        ext = new_http_extender(ExtenderConfig(url_prefix="http://e"),
                                transport=RecordingTransport({}))
        scores, weight = ext.prioritize(make_pod("p"), nodes)
        assert weight == 0 and all(hp.score == 0 for hp in scores)

    def test_bind_sends_binding_args(self):
        transport = RecordingTransport({"bind": {}})
        ext = new_http_extender(
            ExtenderConfig(url_prefix="http://e", bind_verb="bind"),
            transport=transport)
        assert ext.is_binder()
        ext.bind(make_pod("p"), "n0")
        verb, args = transport.calls[0]
        assert verb == "bind"
        assert args["podName"] == "p" and args["node"] == "n0"

    def test_is_interested_managed_resources(self):
        config = ExtenderConfig(
            url_prefix="http://e", filter_verb="filter",
            managed_resources=[ExtenderManagedResource(name="example.com/foo")])
        ext = new_http_extender(config, transport=RecordingTransport({}))
        plain = make_pod("plain", milli_cpu=100)
        assert not ext.is_interested(plain)
        from tpusim.api.quantity import parse_quantity
        fancy = make_pod("fancy", milli_cpu=100)
        fancy.spec.containers[0].requests["example.com/foo"] = parse_quantity("1")
        assert ext.is_interested(fancy)
        # no managed resources → interested in everything
        ext_all = new_http_extender(ExtenderConfig(url_prefix="http://e"),
                                    transport=RecordingTransport({}))
        assert ext_all.is_interested(plain)


def _policy_with_extender(transport_handlers, **ext_kwargs):
    return Policy(
        predicates=[PredicatePolicy(name="PodFitsResources")],
        priorities=[],
        extender_configs=[ExtenderConfig(url_prefix="http://e", **ext_kwargs)])


class TestEngineIntegration:
    def test_extender_filter_in_scheduling(self):
        """The extender vetoes all but one node; its failure message appears in
        the FitError when everything is filtered out."""
        transport = RecordingTransport({"filter": lambda args: {
            "nodes": {"items": [n for n in args["nodes"]["items"]
                                if n["metadata"]["name"] == "n2"]},
            "failedNodes": {"n0": "gpu fragmentation", "n1": "gpu fragmentation"},
        }})
        policy = _policy_with_extender(None, filter_verb="filter")
        config = SchedulerServerConfig(policy=policy,
                                       extender_transport=transport)
        cc = new_cluster_capacity(config, [make_pod("p", milli_cpu=100, memory=1)],
                                  [], _nodes(3))
        cc.run()
        assert len(cc.status.successful_pods) == 1
        assert cc.status.successful_pods[0].spec.node_name == "n2"

    def test_extender_failure_reasons_in_report(self):
        transport = RecordingTransport({"filter": lambda args: {
            "nodes": {"items": []},
            "failedNodes": {n["metadata"]["name"]: "extender vetoed"
                            for n in args["nodes"]["items"]},
        }})
        policy = _policy_with_extender(None, filter_verb="filter")
        config = SchedulerServerConfig(policy=policy,
                                       extender_transport=transport)
        cc = new_cluster_capacity(config, [make_pod("p", milli_cpu=100, memory=1)],
                                  [], _nodes(2))
        cc.run()
        [failed] = cc.status.failed_pods
        msg = failed.status.conditions[0].message
        assert "extender vetoed" in msg

    def test_extender_prioritize_steers_choice(self):
        transport = RecordingTransport({"prioritize": lambda args: [
            {"host": name, "score": 10 if name == "n1" else 0}
            for name in (n["metadata"]["name"] for n in args["nodes"]["items"])]})
        policy = Policy(
            predicates=[PredicatePolicy(name="PodFitsResources")],
            priorities=[],
            extender_configs=[ExtenderConfig(url_prefix="http://e",
                                             prioritize_verb="prioritize",
                                             weight=2)])
        config = SchedulerServerConfig(policy=policy,
                                       extender_transport=transport)
        cc = new_cluster_capacity(config, [make_pod("p", milli_cpu=100, memory=1)],
                                  [], _nodes(3))
        cc.run()
        assert cc.status.successful_pods[0].spec.node_name == "n1"

    def test_prioritize_errors_ignored(self):
        def boom(args):
            raise ExtenderError("down")
        transport = RecordingTransport({"prioritize": boom})
        policy = Policy(
            predicates=[PredicatePolicy(name="PodFitsResources")],
            priorities=[],
            extender_configs=[ExtenderConfig(url_prefix="http://e",
                                             prioritize_verb="prioritize",
                                             weight=2)])
        config = SchedulerServerConfig(policy=policy,
                                       extender_transport=transport)
        cc = new_cluster_capacity(config, [make_pod("p", milli_cpu=100, memory=1)],
                                  [], _nodes(2))
        cc.run()
        assert len(cc.status.successful_pods) == 1  # scheduling still succeeds

    def test_filter_transport_error_fails_pod_not_run(self):
        """A filter transport failure marks the pod unschedulable; the
        simulation itself survives (generic_scheduler.go:360-363 error arm →
        scheduleOne → PodConditionUpdater)."""
        def boom(args):
            raise ExtenderError("connection refused")
        policy = _policy_with_extender(None, filter_verb="filter")
        config = SchedulerServerConfig(
            policy=policy, extender_transport=RecordingTransport({"filter": boom}))
        cc = new_cluster_capacity(
            config,
            [make_pod("p1", milli_cpu=100, memory=1),
             make_pod("p2", milli_cpu=100, memory=1)],
            [], _nodes(2))
        cc.run()
        assert len(cc.status.failed_pods) == 2
        assert "connection refused" in cc.status.failed_pods[0].status.conditions[0].message

    def test_prioritize_unknown_host_ignored(self):
        transport = RecordingTransport({"prioritize": lambda args: [
            {"host": "no-such-node", "score": 99}]})
        policy = Policy(
            predicates=[PredicatePolicy(name="PodFitsResources")],
            priorities=[],
            extender_configs=[ExtenderConfig(url_prefix="http://e",
                                             prioritize_verb="prioritize",
                                             weight=2)])
        config = SchedulerServerConfig(policy=policy,
                                       extender_transport=transport)
        cc = new_cluster_capacity(config, [make_pod("p", milli_cpu=100, memory=1)],
                                  [], _nodes(2))
        cc.run()
        assert len(cc.status.successful_pods) == 1

    def test_ignored_extended_resources_skip_fit_check(self):
        """A resource managed by an IgnoredByScheduler extender does not fail
        PodFitsResources even though no node allocates it
        (factory.go:984-988, predicates.go:754-761)."""
        policy = Policy(
            predicates=[PredicatePolicy(name="PodFitsResources")],
            priorities=[],
            extender_configs=[ExtenderConfig(
                url_prefix="http://e",
                managed_resources=[ExtenderManagedResource(
                    name="example.com/foo", ignored_by_scheduler=True)])])
        sched = create_from_config(policy, PluginFactoryArgs(),
                                   extender_transport=RecordingTransport({}))
        from tpusim.api.quantity import parse_quantity
        pod = make_pod("p", milli_cpu=100, memory=1)
        pod.spec.containers[0].requests["example.com/foo"] = parse_quantity("2")
        nodes = _nodes(1)
        fits, failed = sched.find_nodes_that_fit(pod, nodes, _info_map(nodes))
        assert [n.name for n in fits] == ["n0"]

    def test_preemption_extender_gate(self):
        nodes = _nodes(1)
        info_map = _info_map(nodes)
        vetoes = RecordingTransport({"filter": lambda args: {
            "nodes": {"items": []}, "failedNodes": {"n0": "no"}}})
        policy = _policy_with_extender(None, filter_verb="filter")
        sched = create_from_config(policy, PluginFactoryArgs(),
                                   extender_transport=vetoes)
        victim = make_pod("victim", milli_cpu=100, memory=1, node_name="n0",
                          phase="Running")
        info_map["n0"].add_pod(victim)
        ok = sched._node_passes_extenders_for_preemption(
            make_pod("p"), "n0", [victim], info_map)
        assert ok is False
        # and the victims really were removed for the extender's benefit
        _, args = vetoes.calls[0]
        assert args["nodes"]["items"][0]["metadata"]["name"] == "n0"
